"""Optimizer factory (parity: reference hydragnn/utils/optimizer.py:12-113).

All seven torch optimizers plus LAMB (the reference's DeepSpeed FusedLAMB)
mapped onto optax, wrapped in ``optax.inject_hyperparams`` so the learning
rate lives in the optimizer state and host-side schedulers (ReduceLROnPlateau)
can rewrite it between steps without retracing the jit'd train step.

The reference's ZeRO-1 ``ZeroRedundancyOptimizer`` wrapping is a sharding
choice here, not a different optimizer: when ``use_zero_redundancy`` is set,
the returned spec asks the parallel layer to shard optimizer state along the
data axis (see hydragnn_tpu/parallel/mesh.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerSpec:
    tx: optax.GradientTransformation
    learning_rate: float
    use_zero_redundancy: bool = False
    # config name of the optimizer ("" for hand-built specs) — the ZeRO
    # layer needs it to refuse non-elementwise optimizers, whose per-tensor
    # statistics (LAMB's trust ratio) would silently change under slicing
    name: str = ""


_FACTORIES = {
    "SGD": lambda lr: optax.inject_hyperparams(optax.sgd)(learning_rate=lr),
    "Adam": lambda lr: optax.inject_hyperparams(optax.adam)(learning_rate=lr),
    "Adadelta": lambda lr: optax.inject_hyperparams(optax.adadelta)(
        learning_rate=lr),
    "Adagrad": lambda lr: optax.inject_hyperparams(optax.adagrad)(
        learning_rate=lr),
    "Adamax": lambda lr: optax.inject_hyperparams(optax.adamax)(
        learning_rate=lr),
    "AdamW": lambda lr: optax.inject_hyperparams(optax.adamw)(learning_rate=lr),
    "RMSprop": lambda lr: optax.inject_hyperparams(optax.rmsprop)(
        learning_rate=lr),
    # DeepSpeed FusedLAMB parity (reference optimizer.py:31-40)
    "FusedLAMB": lambda lr: optax.inject_hyperparams(optax.lamb)(
        learning_rate=lr),
    "LAMB": lambda lr: optax.inject_hyperparams(optax.lamb)(learning_rate=lr),
}


def select_optimizer(opt_config: Dict[str, Any],
                     zero_stage: int = 0) -> OptimizerSpec:
    """Build from the Training.Optimizer config section.

    ``zero_stage`` is the run's CONFIG-DECLARED ZeRO stage
    (``zero_stage_from_training(training, env=False)`` — no HYDRAGNN_ZERO
    overlay): combining it — or the legacy ``use_zero_redundancy`` flag —
    with a non-elementwise optimizer raises here, at config time, instead
    of silently training with a trust ratio computed per SLICE rather
    than per tensor.  An env-FORCED stage over a LAMB config instead hits
    the trainer's warn-and-disable fallback (docs/SCALING.md)."""
    from hydragnn_tpu.parallel.zero import NON_ELEMENTWISE_OPTIMIZERS

    opt_type = opt_config.get("type", "AdamW")
    lr = float(opt_config.get("learning_rate", 1e-3))
    if opt_type not in _FACTORIES:
        raise NameError(f"The string {opt_type} does not name a valid optimizer")
    use_zero = bool(opt_config.get("use_zero_redundancy", False))
    if (use_zero or int(zero_stage) > 0) \
            and opt_type in NON_ELEMENTWISE_OPTIMIZERS:
        raise ValueError(
            f"ZeRO sharding is incompatible with {opt_type}: its per-tensor "
            "trust ratio changes under slice partitioning (see "
            "parallel/zero.py).  Use an elementwise optimizer (Adam/AdamW/"
            "SGD/...) or set zero_stage=0 / use_zero_redundancy=false.")
    return OptimizerSpec(
        tx=_FACTORIES[opt_type](lr),
        learning_rate=lr,
        use_zero_redundancy=use_zero,
        name=str(opt_type),
    )


def set_learning_rate(opt_state, lr: float):
    """Functionally rewrite the injected learning rate in an optimizer state."""
    import jax.numpy as jnp

    hp = dict(opt_state.hyperparams)
    old = jnp.asarray(hp["learning_rate"])
    hp["learning_rate"] = jnp.asarray(lr, dtype=old.dtype)
    return opt_state._replace(hyperparams=hp)


def get_learning_rate(opt_state) -> float:
    return float(opt_state.hyperparams["learning_rate"])
