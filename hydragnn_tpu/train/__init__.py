from hydragnn_tpu.train.optimizer import (
    OptimizerSpec,
    get_learning_rate,
    select_optimizer,
    set_learning_rate,
)
from hydragnn_tpu.train.trainer import (
    CheckpointTracker,
    EarlyStopping,
    ReduceLROnPlateau,
    TrainState,
    create_train_state,
    load_state,
    make_eval_step,
    make_train_step,
    save_state,
    test,
    train_validate_test,
)
