"""Training loop: jit'd step functions + host-side epoch driver.

TPU-native redesign of the reference train loop
(reference hydragnn/train/train_validate_test.py:53-664):

  - the hot path is ONE jit-compiled ``train_step`` (forward, weighted
    multi-task loss, optional energy-gradient force self-consistency term via
    ``jax.grad`` w.r.t. positions, backward, optimizer update) over padded
    static-shape batches — no per-batch head-index bookkeeping, no Python in
    the step;
  - data parallelism: batches arrive sharded along the mesh's data axis and
    gradients are averaged by XLA collectives inserted under jit (DDP parity,
    see hydragnn_tpu/parallel/mesh.py);
  - host-side control: ReduceLROnPlateau (factor 0.5 / patience 5 / min_lr
    1e-5, parity with reference run_training.py:94-96), EarlyStopping
    (utils/model.py:173-188), best-val Checkpoint with warmup
    (utils/model.py:191-224), TensorBoard scalars, SLURM time-based stop.
"""

from __future__ import annotations

import os
import pickle
import time

from hydragnn_tpu.utils.env import env_flag, env_int
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import Base, ModelConfig, multihead_loss
from hydragnn_tpu.train.optimizer import (
    OptimizerSpec,
    get_learning_rate,
    select_optimizer,
    set_learning_rate,
)


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def create_train_state(
    model: Base,
    example_batch: GraphBatch,
    opt_spec: OptimizerSpec,
    seed: int = 0,
) -> TrainState:
    variables = model.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        example_batch,
        train=False,
    )
    params = variables["params"]
    if getattr(model, "cfg", None) is not None and model.cfg.initial_bias is not None:
        from hydragnn_tpu.models.base import set_initial_bias

        params = set_initial_bias(params, model.cfg)
    batch_stats = variables.get("batch_stats", {})
    opt_state = opt_spec.tx.init(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
    )


def _force_head_indices(output_names: Optional[Sequence[str]]) -> Tuple[int, int]:
    """(energy_head, forces_head) or (-1, -1).  Parity with the reference's
    name-based detection (train_validate_test.py:433-438)."""
    if not output_names:
        return -1, -1
    e = [i for i, n in enumerate(output_names) if n == "total_energy"]
    f = [i for i, n in enumerate(output_names) if n == "atomic_forces"]
    assert len(e) <= 1, "multiple outputs are called total_energy"
    assert len(f) <= 1, "multiple outputs are called atomic_forces"
    if e and f:
        return e[0], f[0]
    return -1, -1


def _loss_and_metrics(
    model: Base,
    cfg: ModelConfig,
    params,
    batch_stats,
    g: GraphBatch,
    train: bool,
    energy_head: int = -1,
    forces_head: int = -1,
    dropout_rng: Optional[jax.Array] = None,
    dtype_policy: str = "f32",
):
    """Forward + weighted loss (+ self-consistency term); returns
    (loss, (per_head, new_batch_stats, outputs)).

    Mixed precision (``Architecture.mixed_precision`` -> cfg.compute_dtype
    "bfloat16", or the training policy ``dtype_policy="bf16"`` from
    ``Training.train_dtype_policy`` / HYDRAGNN_TRAIN_DTYPE — see
    docs/PERF.md PR-15): params and node/edge FEATURES are cast to bf16
    at THIS boundary — one choke point instead of threading dtype through
    every layer.  Deliberately kept f32: positions (bf16's 8-bit mantissa
    would quantize interatomic distances by ~0.1 A at catalyst-cell
    coordinate magnitudes, corrupting RBFs and the dE/dpos force term),
    the running batch statistics (an EMA accumulated through bf16 loses
    late-training drifts), the loss, and the gradients (transpose of the
    cast accumulates in f32).  Anything the f32 geometry touches promotes
    back to f32; the feature stack stays bf16.  Under the training policy
    the MASTER params (state.params), the optimizer state, and the loss /
    gradient accumulators all stay f32 — only this forward/backward
    computes in bf16.  ``dtype_policy`` is a Python-level branch: the
    default "f32" leaves the traced program byte-identical to a
    pre-policy build."""
    compute_dtype = (jnp.bfloat16 if (getattr(cfg, "compute_dtype", "float32")
                     == "bfloat16" or dtype_policy == "bf16") else None)
    if dtype_policy == "int8_edge":
        # int8 edge-MLP pilot: fake-quantize the edge-MLP kernels (int8
        # round-trip, straight-through grad) at this one boundary — the
        # rest of the step stays f32, master params/optimizer untouched
        from hydragnn_tpu.quant import fake_quant_edge_params

        params = fake_quant_edge_params(params)

    def _cast(tree, dtype):
        return jax.tree.map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    variables = {"params": params, "batch_stats": batch_stats}
    if compute_dtype is not None:
        variables = {"params": _cast(params, compute_dtype),
                     "batch_stats": batch_stats}
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None

    def apply_fn(gg):
        if compute_dtype is not None:
            gg = gg.replace(
                x=gg.x.astype(compute_dtype),
                edge_attr=(None if gg.edge_attr is None
                           else gg.edge_attr.astype(compute_dtype)))
        if train:
            out, mutated = model.apply(
                variables, gg, train=True, mutable=["batch_stats"], rngs=rngs)
            stats = mutated.get("batch_stats", batch_stats)
        else:
            out, stats = model.apply(variables, gg, train=False), batch_stats
        if compute_dtype is not None:
            out = [o.astype(jnp.float32) for o in out]
            stats = jax.tree.map(
                lambda s, o: s.astype(o.dtype), stats, batch_stats)
        return out, stats

    if energy_head >= 0 and forces_head >= 0:
        # Energy-gradient force self-consistency (reference
        # train_validate_test.py:478-488): forces are the negative gradient,
        # so the mismatch is |dE/dpos * scale + F_label| summed over real
        # nodes.  dE/dpos comes from the SAME forward that produces the head
        # outputs (one forward + one extra backward, matching the reference's
        # create_graph autograd.grad on the live graph) — not a second apply.

        def energy_of(pos):
            out, stats = apply_fn(g.replace(pos=pos))
            e = jnp.sum(out[energy_head] * g.graph_mask[:, None])
            return e, (out, stats)

        (_, (outputs, new_stats)), grads_energy = jax.value_and_grad(
            energy_of, has_aux=True)(g.pos)  # grads: [N, 3]
        total, per_head = multihead_loss(cfg, outputs, g)
        scale = g.extras.get("grad_energy_post_scaling_factor")
        if scale is not None:
            if scale.ndim == 1:
                scale = scale[:, None]
            grads_energy = grads_energy * scale
        f_label = g.labels[forces_head]
        mism = jnp.abs(
            grads_energy.reshape(f_label.shape) + f_label
        ) * g.node_mask[:, None]
        total = total + jnp.sum(mism)
    else:
        outputs, new_stats = apply_fn(g)
        total, per_head = multihead_loss(cfg, outputs, g)

    return total, (per_head, new_stats, outputs)


def tree_l2_norm(tree) -> jax.Array:
    """Global L2 norm of a pytree's leaves, accumulated in f32 (the in-jit
    grad/param/update norm metric — a tree-wide reduction is noise next to
    the step's matmuls, and under scan-chunking it rides the same
    executable, so it's effectively free)."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def step_telemetry_metrics(g: GraphBatch, grads, new_params,
                           updates) -> Dict[str, jax.Array]:
    """The in-jit telemetry extension of the step ``metrics`` dict: global
    grad/param/update norms plus real node/edge counts (the numerators of
    the host-side padding-waste accounting; the denominators are the static
    padded shapes the host already knows)."""
    return {
        "grad_norm": tree_l2_norm(grads),
        "param_norm": tree_l2_norm(new_params),
        "update_norm": tree_l2_norm(updates),
        "nodes_real": jnp.sum(g.node_mask),
        "edges_real": jnp.sum(g.edge_mask),
    }


def make_train_step(
    model: Base,
    cfg: ModelConfig,
    opt_spec: OptimizerSpec,
    output_names: Optional[Sequence[str]] = None,
    telemetry_metrics: bool = False,
    nonfinite_guard: bool = False,
    dtype_policy: str = "f32",
) -> Callable[[TrainState, GraphBatch], Tuple[TrainState, Dict[str, jax.Array]]]:
    """``telemetry_metrics=True`` adds the in-jit norm/count extension; the
    trainer passes the MetricsLogger's enable state.  Default OFF so direct
    builders (bench.py, tools/) time/cost-model the exact program a
    non-telemetry production run executes.

    ``nonfinite_guard=True`` (resilience/guards.py) checks loss + gradients
    for NaN/Inf inside the jit and suppresses the whole update (old params,
    old opt state, old batch stats) on a bad step, adding a ``skipped``
    metric.  Default OFF: the guard-off program is byte-identical to a
    pre-guard build.

    ``dtype_policy="bf16"`` runs the forward/backward in bf16 with f32
    master params, optimizer state, and accumulators (see
    _loss_and_metrics); the default "f32" is byte-identical to a
    pre-policy build."""
    energy_head, forces_head = _force_head_indices(output_names)

    def train_step(state: TrainState, g: GraphBatch):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0xD0), state.step)

        def loss_fn(params):
            return _loss_and_metrics(
                model, cfg, params, state.batch_stats, g, True,
                energy_head, forces_head, dropout_rng,
                dtype_policy=dtype_policy)

        (loss, (per_head, new_stats, _)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, new_opt_state = opt_spec.tx.update(
            grads, state.opt_state, state.params)
        from hydragnn_tpu.models.base import encoder_freeze_mask

        updates = encoder_freeze_mask(updates, cfg.freeze_conv)
        import optax

        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt_state,
        )
        metrics = {
            "loss": loss,
            "num_graphs": g.n_real_graphs,
            **{f"task_{i}": t for i, t in enumerate(per_head)},
        }
        if telemetry_metrics:
            metrics.update(
                step_telemetry_metrics(g, grads, new_params, updates))
        if nonfinite_guard:
            from hydragnn_tpu.resilience.guards import (
                apply_step_guard,
                nonfinite_flag,
            )

            bad = nonfinite_flag(loss, grads)
            new_state, metrics = apply_step_guard(
                bad, state, new_state, metrics)
        return new_state, metrics

    return train_step


# metric keys that are COUNTS over the dispatch (summed across the K
# scanned steps); every other scalar merges as a graph-weighted mean
# ("skipped" counts guard-suppressed steps within the dispatch)
_COUNT_METRIC_KEYS = ("num_graphs", "nodes_real", "edges_real", "skipped")


def merge_scanned_metrics(ms):
    """Graph-weighted merge of per-step metric stacks [K] from a scanned
    multi-step train step — same epoch-accumulation semantics as K separate
    dispatches (one definition shared by the local and mesh scan paths).
    Counts (graphs/nodes/edges consumed) sum over the K steps; losses and
    the telemetry norms merge graph-weighted."""
    ng = ms["num_graphs"]
    total = jnp.maximum(jnp.sum(ng), 1.0)
    merged = {}
    for k, v in ms.items():
        if k in _COUNT_METRIC_KEYS:
            merged[k] = jnp.sum(v)
        else:
            merged[k] = jnp.sum(v * ng) / total
    return merged


def _align_bucket_group(loader, factor: int) -> None:
    """Raise the underlying GraphDataLoader's ``bucket_group`` to a multiple
    of ``factor`` so batches later stacked together (DeviceStackLoader over
    local devices and/or scan steps) share one bucket PadSpec — np.stack
    over mismatched bucket shapes would raise mid-epoch."""
    if factor <= 1:
        return
    obj = loader
    while obj is not None and not hasattr(obj, "bucket_group"):
        obj = getattr(obj, "loader", None)
    if obj is not None:
        bg = max(1, int(obj.bucket_group))
        obj.bucket_group = factor * (-(-bg // factor))


def _auto_pipeline(train_loader, val_loader, test_loader, stack_factor=1):
    """Default-on fast-path selection for single-host runs (round-4
    VERDICT item 7): pick scan chunking K and device residency
    automatically when the explicit env knobs are unset, so the
    out-of-the-box `run_training` gets the measured-fast pipeline instead
    of requiring HYDRAGNN_STEPS_PER_DISPATCH/RESIDENT_DATASET tuning.

    Returns (auto_k, auto_resident).  Conservative by design:
    - only when every loader reports a length (peeking one batch costs one
      collate) and the run is single-process;
    - scan K only when the epoch has >= 8 dispatch units — a unit is
      ``stack_factor`` raw batches when the mesh path device-stacks them
      first — so K-stacking (drop_last) can never leave a zero-step epoch
      and trims at most a quarter of it (shuffling rotates what's dropped);
    - residency only for >= 32 batches (ResidentDeviceLoader freezes batch
      COMPOSITION after epoch 0 — harmless at scale, load-bearing for tiny
      CI runs) and when the staged train+val+test corpus fits the HBM
      budget (HYDRAGNN_RESIDENT_BUDGET_MB, default 6144).
    HYDRAGNN_AUTO_PIPELINE=0 disables both.
    """
    if os.environ.get("HYDRAGNN_AUTO_PIPELINE", "1") in ("", "0", "false",
                                                         "False"):
        return 1, False
    if jax.process_count() > 1:
        return 1, False
    try:
        n_train = len(train_loader)
        n_total = n_train + len(val_loader) + len(test_loader)
    except TypeError:
        return 1, False
    n_units = n_train // max(1, stack_factor)
    if n_units < 8:
        return 1, False
    # largest K <= 32 whose drop_last waste is <= 1/8 of the epoch
    auto_k = 1
    for k in range(min(32, n_units), 0, -1):
        if (n_units % k) * 8 <= n_units:
            auto_k = k
            break
    try:
        first = next(iter(train_loader))
    except StopIteration:
        return 1, False
    batch_bytes = sum(
        getattr(l, "nbytes", 0) for l in jax.tree_util.tree_leaves(first))
    # bucketed loaders: the peeked batch may come from the SMALLEST
    # bucket; scale to the worst-case spec so residency never turns on
    # from an underestimate and OOMs HBM during staging
    base = train_loader
    while base is not None and not hasattr(base, "pad_specs"):
        base = getattr(base, "loader", None)
    if base is not None and len(base.pad_specs) > 1:
        lo, hi = base.pad_specs[0], base.pad_specs[-1]
        batch_bytes *= max(
            hi.num_nodes / max(lo.num_nodes, 1),
            hi.num_edges / max(lo.num_edges, 1))
    budget = env_int("HYDRAGNN_RESIDENT_BUDGET_MB", 6144) * (1 << 20)
    auto_resident = (n_train >= 32 and batch_bytes * n_total <= budget)
    return auto_k, auto_resident


def make_scan_train_step(
    model: Base,
    cfg: ModelConfig,
    opt_spec: OptimizerSpec,
    output_names: Optional[Sequence[str]] = None,
    steps: int = 1,
    telemetry_metrics: bool = False,
    nonfinite_guard: bool = False,
    dtype_policy: str = "f32",
):
    """K sequential train steps inside one executable via ``lax.scan``.

    The input batch carries a leading [K, ...] axis of consecutive
    same-PadSpec batches (DeviceStackLoader).  Metrics come back
    graph-weighted over the K steps, so epoch accumulation in
    :func:`_run_epoch` sees the same semantics as K separate dispatches.
    Numerically identical to K sequential steps — only the host dispatch
    and argument-ingest latency are amortized (measured ~15 ms/dispatch on
    a tunneled v5e runtime; see docs/PERF.md).
    """
    from jax import lax

    base = make_train_step(model, cfg, opt_spec, output_names,
                           telemetry_metrics=telemetry_metrics,
                           nonfinite_guard=nonfinite_guard,
                           dtype_policy=dtype_policy)

    def scan_step(state: TrainState, g: GraphBatch):
        state, ms = lax.scan(base, state, g, length=steps)
        return state, merge_scanned_metrics(ms)

    return scan_step


def make_eval_step(
    model: Base, cfg: ModelConfig
) -> Callable[[TrainState, GraphBatch], Dict[str, Any]]:
    def eval_step(state: TrainState, g: GraphBatch):
        loss, (per_head, _, outputs) = _loss_and_metrics(
            model, cfg, state.params, state.batch_stats, g, False)
        return {
            "loss": loss,
            "num_graphs": g.n_real_graphs,
            "per_head": per_head,
            "outputs": outputs,
        }

    return eval_step


# ---------------------------------------------------------------------------
# Host-side control objects (parity: reference hydragnn/utils/model.py)
# ---------------------------------------------------------------------------


class ReduceLROnPlateau:
    """min-mode plateau scheduler (reference run_training.py:94-96 wiring of
    torch's scheduler: factor 0.5, patience 5, min_lr 1e-5)."""

    def __init__(self, factor: float = 0.5, patience: int = 5,
                 min_lr: float = 1e-5, threshold: float = 1e-4):
        self.factor = factor
        self.patience = patience
        self.min_lr = min_lr
        self.threshold = threshold
        self.best = float("inf")
        self.bad_epochs = 0

    def step(self, metric: float, lr: float) -> float:
        if metric < self.best * (1.0 - self.threshold):
            self.best = metric
            self.bad_epochs = 0
        else:
            self.bad_epochs += 1
        if self.bad_epochs > self.patience:
            self.bad_epochs = 0
            return max(lr * self.factor, self.min_lr)
        return lr

    def state_dict(self) -> Dict[str, float]:
        return {"best": self.best, "bad_epochs": self.bad_epochs}

    def load_state_dict(self, sd: Dict[str, float]) -> None:
        self.best = float(sd["best"])
        self.bad_epochs = int(sd["bad_epochs"])


class EarlyStopping:
    """Patience on validation loss (reference utils/model.py:173-188)."""

    def __init__(self, patience: int = 10, min_delta: float = 0.0):
        self.patience = patience
        self.min_delta = min_delta
        self.count = 0
        self.min_loss = float("inf")
        self.early_stop = False

    def __call__(self, val_loss: float) -> bool:
        if val_loss < self.min_loss:
            self.min_loss = val_loss
            self.count = 0
        elif val_loss > self.min_loss + self.min_delta:
            self.count += 1
            if self.count >= self.patience:
                self.early_stop = True
        return self.early_stop

    def state_dict(self) -> Dict[str, float]:
        return {"count": self.count, "min_loss": self.min_loss,
                "early_stop": self.early_stop}

    def load_state_dict(self, sd: Dict[str, float]) -> None:
        self.count = int(sd["count"])
        self.min_loss = float(sd["min_loss"])
        self.early_stop = bool(sd["early_stop"])


class CheckpointTracker:
    """Best-metric checkpointing with warmup (reference utils/model.py:191-224).

    Runs on EVERY rank: the metric is globally reduced, so the save decision
    is identical everywhere, and the transform may be a cross-process
    collective (ZeRO consolidation all_gather) that would deadlock behind a
    rank-0 gate.  Only rank 0 actually writes the file."""

    def __init__(self, name: str, warmup: int = 0, path: str = "./logs/",
                 rank: int = 0):
        self.name = name
        self.warmup = warmup
        self.path = path
        self.rank = rank
        self.count = 0
        self.best = float("inf")
        # e.g. ZeRO opt-state consolidation before serialization (reference
        # consolidate_state_dict before save, utils/model.py:61-62)
        self.transform = lambda s: s

    def __call__(self, state: TrainState, metric: float) -> bool:
        self.count += 1
        if self.count < self.warmup or metric >= self.best:
            return False
        self.best = metric
        save_state(self.transform(state), self.name, self.path, rank=self.rank)
        return True

    def state_dict(self) -> Dict[str, float]:
        return {"count": self.count, "best": self.best}

    def load_state_dict(self, sd: Dict[str, float]) -> None:
        self.count = int(sd["count"])
        self.best = float(sd["best"])


def save_state(state: TrainState, log_name: str, path: str = "./logs/",
               rank: int = 0) -> Optional[str]:
    """Rank-0 single-file checkpoint (reference utils/model.py:58-71 writes
    one .pk with model+optimizer state).  Written atomically (temp file +
    ``os.replace``): this is often the ONLY best-model checkpoint, and a
    crash mid-write must leave the previous good file intact."""
    if rank != 0:
        return None
    d = os.path.join(path, log_name)
    os.makedirs(d, exist_ok=True)
    fname = os.path.join(d, f"{log_name}.pk")
    payload = jax.device_get(
        {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
    )
    from hydragnn_tpu.resilience.ckpt_io import atomic_write_pickle

    atomic_write_pickle(fname, payload)
    return fname


def load_state(state: TrainState, log_name: str, path: str = "./logs/") -> TrainState:
    """Restore a saved checkpoint into an existing state skeleton."""
    fname = os.path.join(path, log_name, f"{log_name}.pk")
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    return TrainState(
        step=jnp.asarray(payload["step"]),
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=payload["opt_state"],
    )


# ---------------------------------------------------------------------------
# Epoch driver
# ---------------------------------------------------------------------------


def _traced_loader(loader, tr):
    """Yield ``loader``'s batches, recording each blocking ``next()`` as a
    ``train.data_wait`` span.  Only wrapped in when tracing is on — the
    default epoch loop iterates the raw loader untouched."""
    it = iter(loader)
    while True:
        t0 = time.perf_counter()
        try:
            g = next(it)
        except StopIteration:
            return
        tr.record_interval("train.data_wait", t0, time.perf_counter())
        yield g


def _traced_step(step_fn, tr):
    """Trace-mode train-step wrapper: splits each dispatch into an
    arg-ingest span (``train.h2d`` — the jit call's synchronous host->
    device transfer of the batch) and an on-device span (``train.step`` —
    compute + collectives; split the two with the ``comms`` probe's
    comm_pct).  The completion block is ONE added device sync per step:
    the flight recorder trades the zero-sync telemetry discipline for
    phase attribution, which is why tracing is opt-in."""

    def stepped(state, g):
        t0 = time.perf_counter()
        state, metrics = step_fn(state, g)
        t1 = time.perf_counter()
        jax.block_until_ready(metrics["loss"])
        t2 = time.perf_counter()
        tr.record_interval("train.h2d", t0, t1)
        tr.record_interval("train.step", t1, t2)
        return state, metrics

    return stepped


def _run_epoch(step_fn, state, loader, train: bool, profiler=None,
               steps_per_item: int = 1, telemetry=None, guard=None,
               preempt=None, chaos=None, skip_first: int = 0,
               consumed_base: int = 0):
    # ``consumed_base`` dispatch units were already skipped INSIDE the
    # loader (streaming fast-forward): the resume bundle's items_consumed
    # must still count them, but the iterator never yields them here.
    # Metrics accumulate as DEVICE scalars: no float() in the batch loop, so
    # steps dispatch back-to-back with no device->host sync (the reference
    # accumulates on device and reduces at epoch end,
    # train_validate_test.py:505-508).  No sync here either: the DEVICE
    # accumulator (total, tasks, n) — or None for an empty loader — is
    # returned for the caller to ``device_get`` together with the other
    # phases' (on a tunneled PJRT runtime each sync costs a ~100 ms round
    # trip, so train/val/test fetching separately added ~200 ms per
    # epoch); finalize the fetched value with :func:`_epoch_metrics`.
    total = None
    tasks = None
    n = None
    # flight recorder (opt-in, telemetry.trace): wrap the loader and step
    # so phase spans are recorded WITHOUT touching the default loop body —
    # tracing off leaves this function's hot path byte-identical
    tr = getattr(telemetry, "spans", None) if train else None
    if tr is not None:
        loader = _traced_loader(loader, tr)
        step_fn = _traced_step(step_fn, tr)
    # HYDRAGNN_MAX_NUM_BATCH caps TRAIN STEPS per epoch (reference
    # get_nbatch, train_validate_test.py:40-50 — used for weak-scaling
    # measurement).  With scan chunking each loader item carries
    # ``steps_per_item`` steps; dispatches stop before EXCEEDING the cap
    # (floor(nbatch/K) dispatches), so a K>1 run never does more optimizer
    # steps than the K=1 run it's compared against.
    nbatch = int(os.getenv("HYDRAGNN_MAX_NUM_BATCH", "0")) or None
    for ibatch, g in enumerate(loader):
        if nbatch is not None and (ibatch + 1) * steps_per_item > nbatch:
            break
        if ibatch < skip_first:
            # mid-run resume: these dispatch units were already executed by
            # the preempted run; set_epoch replayed the deterministic
            # shuffle, so skipping them continues the exact batch stream.
            # Preemption is still polled — a SIGTERM during a long replay
            # must re-save (at the SAME position: everything up to
            # skip_first was consumed by the previous run) instead of
            # burning the grace window.
            if train and preempt is not None and preempt.poll():
                preempt.consumed = consumed_base + skip_first
                break
            continue
        if train:
            if chaos is not None:
                g = chaos.on_train_dispatch(g)
            state, metrics = step_fn(state, g)
            if telemetry is not None:
                # zero-sync: device scalars + host timestamp are buffered;
                # the one fetch happens in telemetry.flush_steps at epoch end
                telemetry.on_step(metrics, g)
            if guard is not None:
                # buffers the device `skipped` flag; one device_get every
                # poll_every dispatches — raises NonFiniteTrainingError
                # after max_consecutive bad steps
                guard.on_step(metrics, g)
            n_tasks = sum(1 for k in metrics if k.startswith("task_"))
            per_head = [metrics[f"task_{i}"] for i in range(n_tasks)]
        else:
            metrics = step_fn(state, g)
            per_head = metrics["per_head"]
        ng = metrics["num_graphs"]
        loss_w = metrics["loss"] * ng
        ph = jnp.stack(per_head) * ng if per_head else jnp.zeros(0)
        if total is None:
            total, tasks, n = loss_w, ph, ng
        else:
            total, tasks, n = total + loss_w, tasks + ph, n + ng
        if profiler is not None:
            profiler.step()
        if train and preempt is not None:
            if chaos is not None and chaos.preempt_now():
                preempt.request()
            if preempt.poll():
                # stop at the batch boundary: the dispatched step's state is
                # complete; record the step-within-epoch for the bundle
                preempt.consumed = consumed_base + ibatch + 1
                break
    return state, (None if total is None else (total, tasks, n))


def _epoch_metrics(acc):
    """Finalize a fetched (total, tasks, n) accumulator to (loss, tasks)."""
    if acc is None:
        return 0.0, np.zeros(0)
    total, tasks, n = acc
    n = max(float(n), 1.0)
    return float(total) / n, np.asarray(tasks) / n


# bf16-train acceptance bound: relative drift of the step-0 loss and global
# gradient norm vs the f32 step on the SAME (state, batch).  5% is loose
# against bf16's ~0.4% unit roundoff because the drift compounds through
# the conv stack and the backward chain; a model that exceeds it at step 0
# (e.g. a loss balanced on cancellation) would not train faithfully in
# bf16, so the policy falls back to f32.  Module-level so tests can
# monkeypatch the bound to force both verdicts.
_TRAIN_DTYPE_TOL = 0.05


def _train_dtype_gate(model, cfg, state, opt_spec, output_names, batch,
                      policy="bf16"):
    """Golden-replay probe for a non-f32 ``Training.train_dtype_policy``
    ("bf16" or "int8_edge"): run ONE f32 train step and ONE policy train
    step on the same (state, first batch) — un-donated local jits, so
    neither touches the run's real state — and compare loss + grad-norm
    relative drift against :data:`_TRAIN_DTYPE_TOL`.  Returns
    (ok, drift_stats).

    Mirrors serving's golden-batch replay (quant/policy.py): the operator
    asked for a numerics change, so the change must prove itself against
    the f32 reference on real data before the run commits to it.  Costs
    two extra step compilations at step 0; the f32 probe's trace is the
    same program the fallback path would jit anyway."""
    f32_step = jax.jit(make_train_step(model, cfg, opt_spec, output_names,
                                       telemetry_metrics=True))
    bf_step = jax.jit(make_train_step(model, cfg, opt_spec, output_names,
                                      telemetry_metrics=True,
                                      dtype_policy=policy))
    _, m32 = jax.device_get(f32_step(state, batch))
    _, mbf = jax.device_get(bf_step(state, batch))
    ok, stats = True, {}
    for k in ("loss", "grad_norm"):
        ref, got = float(m32[k]), float(mbf[k])
        drift = abs(got - ref) / max(abs(ref), 1e-12)
        stats[k] = drift
        # `not <=` (rather than `>`): a NaN drift must reject too
        if not drift <= _TRAIN_DTYPE_TOL:
            ok = False
    return ok, stats


def train_validate_test(
    model: Base,
    cfg: ModelConfig,
    state: TrainState,
    opt_spec: OptimizerSpec,
    train_loader,
    val_loader,
    test_loader,
    config_nn: Dict[str, Any],
    log_name: str,
    verbosity: int = 0,
    writer=None,
    rank: Optional[int] = None,
    world_size: int = 1,
    logs_dir: str = "./logs/",
    use_mesh_dp: Optional[bool] = None,
    profile_config: Optional[Dict[str, Any]] = None,
    mesh=None,
    telemetry=None,
    resume_meta: Optional[Dict[str, Any]] = None,
) -> Tuple[TrainState, Dict[str, List[float]]]:
    """Epoch loop with LR plateau scheduling, early stopping, checkpointing.

    Parity with reference train_validate_test (train_validate_test.py:53-284):
    per-epoch train/val/test losses, scheduler.step(val), checkpoint(val) with
    warmup, optional early stop, metric reduction across ranks.

    When this process drives more than one accelerator (a TPU host's local
    chips), the loop automatically switches to the data-parallel mesh path:
    device-stacked batches through the shard_map step (DDP parity; see
    hydragnn_tpu/parallel/mesh.py).  ``use_mesh_dp`` forces the choice.
    """
    training = config_nn["Training"]
    num_epoch = int(training["num_epoch"])
    output_names = config_nn["Variables_of_interest"].get("output_names")
    # fault-tolerance knobs (resilience/config.py): read BEFORE the step
    # functions are built — the non-finite guard is a trace-time flag
    from hydragnn_tpu.resilience import Chaos, ResilienceConfig

    res_cfg = ResilienceConfig.from_training(training)
    chaos = Chaos.from_env(training.get("Chaos"))
    # ZeRO sharding request (Training.zero_stage + HYDRAGNN_ZERO env, plus
    # the legacy Optimizer.use_zero_redundancy flag) — resolved before the
    # step builders because the partition is a trace-time choice
    from hydragnn_tpu.parallel.zero import (
        NON_ELEMENTWISE_OPTIMIZERS,
        zero_stage_from_training,
    )

    zero_requested = zero_stage_from_training(training, opt_spec)
    zero_stage = zero_requested
    zero_fallback = None
    # graph sharding request (Training.graph_shard + HYDRAGNN_GRAPH_SHARD*
    # env): one giant graph split across the mesh (docs/SCALING.md §6) —
    # resolved before the step builders because the partition and the halo
    # exchange are trace-time choices
    from hydragnn_tpu.graph.partition import (
        HALO_SUPPORTED_MODELS,
        GraphShardConfig,
    )

    gs_cfg = GraphShardConfig.from_training(training)
    graph_shard = gs_cfg.backend
    if zero_requested and getattr(opt_spec, "name", "") \
            in NON_ELEMENTWISE_OPTIMIZERS:
        # env-forced ZeRO on a LAMB run: warn-and-disable rather than
        # changing numerics (config-declared combinations already raised in
        # select_optimizer)
        import warnings

        warnings.warn(
            f"ZeRO stage {zero_requested} requested but optimizer "
            f"{opt_spec.name} is not elementwise — training REPLICATED "
            "(per-tensor trust ratios would change under slicing)",
            stacklevel=2)
        zero_stage, zero_fallback = 0, "non_elementwise_optimizer"
    # an explicit (ensemble-branch) mesh means other branches run disjoint
    # programs concurrently — global host collectives (telemetry cross-rank
    # reduction) would interleave with theirs and deadlock; remember before
    # ``mesh`` is reassigned below
    explicit_mesh = mesh is not None

    if rank is None:
        # who writes artifacts for this log_name: with an explicit (branch)
        # mesh, the branch's lowest process is its leader — rank 0 within the
        # branch even when global process 0 is in another branch; otherwise
        # the global process index (0 for single-process runs).
        if mesh is not None:
            leader = min(d.process_index for d in mesh.devices.flat)
            rank = 0 if jax.process_index() == leader else 1
        else:
            rank = jax.process_index()

    # unified telemetry (hydragnn_tpu/telemetry): callers (run_training)
    # pass a configured MetricsLogger; direct trainer users get the env-knob
    # construction (HYDRAGNN_TELEMETRY=1 turns on the JSONL event log with
    # no config edit).  Built BEFORE the step functions: its enable state
    # decides whether the jitted steps carry the in-jit norm metrics.
    # Epoch records flow through it unconditionally — that's how the
    # TensorBoard scalars are written (TensorBoardSink).
    from hydragnn_tpu.telemetry import MetricsLogger

    if telemetry is None:
        telemetry = MetricsLogger.from_env(
            run_name=log_name,
            out_dir=os.path.join(logs_dir, log_name, "telemetry"),
            rank=rank, world_size=world_size,
            cross_rank=(not explicit_mesh and world_size > 1))

    n_local_devices = len(jax.local_devices())
    if mesh is not None:
        # an explicit (sub-)mesh may use a SUBSET of this process's
        # devices (ensemble branch, in-process elastic harness): stack as
        # many batches per dispatch as this process contributes to THAT
        # mesh, not as many devices as the process owns — the stacked
        # batch axis must equal the mesh's split extent
        _pidx = jax.process_index()
        n_local_devices = sum(
            1 for d in mesh.devices.flat if d.process_index == _pidx)
    n_proc = jax.process_count()
    if use_mesh_dp is None:
        # multi-process runs MUST take the global-mesh path even with one
        # device per process: the local-jit path would never synchronize
        # gradients and each rank would train a divergent model.  An explicit
        # ``mesh`` (e.g. a HostGroup ensemble-branch mesh) also forces it.
        use_mesh_dp = n_local_devices > 1 or n_proc > 1 or mesh is not None
    # fast-pipeline defaults (scan chunking + device residency) when the
    # explicit knobs are unset — see _auto_pipeline.  The mesh path stacks
    # n_local_devices batches per dispatch unit before any K-stacking.
    auto_k, auto_resident = 1, False
    if ("HYDRAGNN_STEPS_PER_DISPATCH" not in os.environ
            or "HYDRAGNN_RESIDENT_DATASET" not in os.environ):
        auto_k, auto_resident = _auto_pipeline(
            train_loader, val_loader, test_loader,
            stack_factor=n_local_devices if use_mesh_dp else 1)
    resident_on = (env_flag("HYDRAGNN_RESIDENT_DATASET")
                   if "HYDRAGNN_RESIDENT_DATASET" in os.environ
                   else auto_resident)
    # -- streaming data plane (data/stream/, docs/DATA.md) ------------------
    # load_data could not emit health events (no MetricsLogger yet); a
    # recorded fallback reason surfaces here, and an active stream loader
    # forces device residency OFF — caching every collated batch on device
    # would re-materialize the epoch the stream exists to avoid holding.
    from hydragnn_tpu.data.stream.config import (
        pop_fallback,
        pop_open_retries,
    )
    from hydragnn_tpu.data.stream.loader import (
        find_stream_loader,
        try_fast_forward,
    )

    for _ev in pop_open_retries():
        # store-open attempts that failed and were retried (bounded
        # backoff, resilience/ckpt_io.with_retries) before any fallback
        telemetry.health("stream_open_retry", **_ev)
    stream_fb = pop_fallback()
    if stream_fb:
        telemetry.health("stream_fallback", reason=stream_fb)
    stream_base = find_stream_loader(train_loader)
    if stream_base is not None:
        resident_on = False
        telemetry.health(
            "stream_open", n_samples=int(len(stream_base.indices)),
            window=int(stream_base.window), order=str(stream_base.order),
            batch_size=int(stream_base.batch_size),
            tail=bool(stream_base.tail_dir))
    # -- training dtype policy (docs/PERF.md PR-15) -------------------------
    # bf16 forward/backward with f32 master params/optimizer/accumulators.
    # Resolved BEFORE the step builders (a trace-time choice, like ZeRO and
    # graph sharding) and gated by a step-0 golden replay: an operator who
    # requested bf16 believes the numerics hold, so a drifting model must
    # fall back LOUDLY to f32 — bit-identical to an unrequested run.
    from hydragnn_tpu.quant import check_train_policy

    train_dtype = check_train_policy(
        str(training.get("train_dtype_policy", "f32") or "f32"))
    env_td = os.environ.get("HYDRAGNN_TRAIN_DTYPE", "").strip().lower()
    if env_td:
        train_dtype = check_train_policy(env_td)
    train_dtype_requested = train_dtype
    if train_dtype != "f32":
        import warnings

        req = train_dtype_requested
        resumed_td = (resume_meta or {}).get("pipeline", {}).get(
            "train_dtype")
        if resumed_td is not None:
            # crash/resume bit-parity: the preempted run's accept/reject
            # verdict is part of its traced program — reuse it verbatim
            # instead of re-probing (a probe on a different first batch
            # could flip the decision mid-run)
            train_dtype = check_train_policy(str(resumed_td))
        elif graph_shard != "off":
            warnings.warn(
                f"train_dtype_policy={req} requested with graph sharding "
                "— the halo/gspmd steps are not policy-threaded; training "
                "f32", stacklevel=2)
            telemetry.health("train_dtype_reject", requested=req,
                             reason="graph_shard")
            train_dtype = "f32"
        else:
            probe = next(iter(train_loader), None)
            if probe is None:
                warnings.warn(
                    f"train_dtype_policy={req} requested but the train "
                    "loader is empty — the acceptance probe cannot run; "
                    "training f32", stacklevel=2)
                telemetry.health("train_dtype_reject", requested=req,
                                 reason="empty_loader")
                train_dtype = "f32"
            else:
                td_ok, td_drift = _train_dtype_gate(
                    model, cfg, state, opt_spec, output_names, probe,
                    policy=req)
                if not td_ok:
                    warnings.warn(
                        f"train_dtype_policy={req} REJECTED by the step-0 "
                        f"golden replay (relative drift {td_drift} vs "
                        f"bound {_TRAIN_DTYPE_TOL}) — training f32",
                        stacklevel=2)
                    telemetry.health(
                        "train_dtype_reject", requested=req,
                        reason="golden_gate",
                        drift_loss=float(td_drift.get("loss", 0.0)),
                        drift_grad_norm=float(
                            td_drift.get("grad_norm", 0.0)),
                        tol=float(_TRAIN_DTYPE_TOL))
                    train_dtype = "f32"
    if use_mesh_dp:
        from hydragnn_tpu.parallel.mesh import (
            DeviceStackLoader,
            GlobalBatchLoader,
            make_dp_eval_step,
            make_dp_train_step,
            make_mesh,
            mesh_process_count,
        )

        if mesh is None:
            n_slices = int(os.environ.get("HYDRAGNN_NUM_SLICES", "0") or 0)
            if n_slices > 1:
                # multi-slice pod: 2-axis (dcn, ici) mesh; DP spans both
                from hydragnn_tpu.parallel.mesh import make_multislice_mesh

                mesh = make_multislice_mesh(num_slices=n_slices)
            else:
                mesh = make_mesh()  # global: every process's devices
        from hydragnn_tpu.parallel.mesh import mesh_dp_axes

        dp_axes = mesh_dp_axes(mesh)
        single_proc = mesh_process_count(mesh) == 1
        # -- graph-sharding gating (docs/SCALING.md §6): resolved BEFORE the
        # ZeRO placement because the gspmd baseline cannot compose with a
        # sharded state (its step is the local jit, no shard_map to slice
        # in), and every fallback must be LOUD — an operator who requested
        # graph sharding believes a giant graph fits
        gs_requested = graph_shard
        gs_fallback = None
        n_shards = int(mesh.devices.size)
        if graph_shard != "off":
            if not single_proc:
                gs_fallback = "multi_process"
            elif graph_shard == "halo" and len(mesh.axis_names) != 1:
                gs_fallback = "multi_axis_mesh"
            elif (graph_shard == "halo"
                    and cfg.model_type not in HALO_SUPPORTED_MODELS):
                gs_fallback = "unsupported_model"
            else:
                e_h, f_h = _force_head_indices(output_names)
                if graph_shard == "halo" and e_h >= 0 and f_h >= 0:
                    gs_fallback = "force_consistency"
            if gs_fallback is not None:
                import warnings

                warnings.warn(
                    f"graph sharding ({graph_shard}) requested but this run "
                    f"cannot use it ({gs_fallback}) — training with the "
                    "plain DP mesh path (the graph must fit one device)",
                    stacklevel=2)
                telemetry.health("graph_shard_fallback",
                                 requested=graph_shard, reason=gs_fallback)
                graph_shard = "off"
        if graph_shard == "gspmd" and zero_stage > 0:
            import warnings

            warnings.warn(
                "ZeRO cannot compose with the gspmd graph-shard baseline "
                "(its step is the local jit — no shard_map to slice the "
                "state in); training with REPLICATED state.  Use the halo "
                "backend for ZeRO + graph sharding.", stacklevel=2)
            zero_stage, zero_fallback = 0, "gspmd_graph_shard"
        # state placement through the ONE resume-composable entry point:
        # stage 0 replicates, stage >= 1 shards optimizer state — and
        # params at stage 2 — along the innermost mesh axis for the whole
        # run (reference ZeroRedundancyOptimizer, optimizer.py:43-103).
        # An elastic resume re-places a consolidated bundle with this
        # same call, so init and resume placement cannot drift apart.
        from hydragnn_tpu.parallel.zero import reshard_state

        state, zero_sh = reshard_state(state, mesh, stage=zero_stage)
        gs_stats = {}
        if graph_shard == "halo":
            # halo graph sharding: ONE graph (batch) split across the mesh —
            # loaders partition each batch into stacked HaloBatches, the
            # steps exchange halo rows (graph/partition.py, docs/SCALING.md
            # §6).  Scan chunking is not composed (the carrier is a
            # different pytree per topology bucket); K stays 1.
            from hydragnn_tpu.graph.partition import ShardedGraphLoader
            from hydragnn_tpu.parallel.mesh import (
                make_halo_eval_step,
                make_halo_train_step,
            )

            if env_int("HYDRAGNN_STEPS_PER_DISPATCH", 1) > 1:
                import warnings

                warnings.warn(
                    "HYDRAGNN_STEPS_PER_DISPATCH > 1 is not composed with "
                    "graph sharding; forcing K=1", stacklevel=2)
            steps_per_dispatch = 1
            hops = gs_cfg.hops or cfg.num_conv_layers
            if hops < cfg.num_conv_layers:
                # a halo shallower than the conv stack silently corrupts
                # boundary rows at the deeper layers — the exact
                # truncated-halo wrong answer graph_shard_halo_max refuses;
                # deeper than the stack is merely wasteful and allowed
                raise ValueError(
                    f"graph_shard_hops={hops} is shallower than the "
                    f"model's {cfg.num_conv_layers} conv layers — boundary "
                    "rows would train on silently wrong neighborhoods; "
                    "set it >= num_conv_layers or leave it 0 (auto)")
            head_types = list(cfg.output_type)
            gs_train = gs_val = gs_test = None
            if stream_base is not None:
                # disk-backed halo feed: shard gathers read straight off the
                # mmap store — the padded whole graph is never materialized
                from hydragnn_tpu.data.stream.halo import sharded_from_stream

                gs_train = sharded_from_stream(
                    train_loader, n_shards, gs_cfg, hops)
                gs_val = sharded_from_stream(
                    val_loader, n_shards, gs_cfg, hops)
                gs_test = sharded_from_stream(
                    test_loader, n_shards, gs_cfg, hops)
            if gs_train and gs_val and gs_test:
                train_loader, val_loader, test_loader = \
                    gs_train, gs_val, gs_test
            else:
                if stream_base is not None:
                    import warnings

                    warnings.warn(
                        "disk-backed halo feed needs batch_size=1 single-"
                        "host streaming loaders; composing the in-memory "
                        "partitioner over the stream instead (still "
                        "windowed, but each batch is padded host-side)",
                        stacklevel=2)
                train_loader = ShardedGraphLoader(
                    train_loader, n_shards, gs_cfg, hops, head_types)
                val_loader = ShardedGraphLoader(
                    val_loader, n_shards, gs_cfg, hops, head_types)
                test_loader = ShardedGraphLoader(
                    test_loader, n_shards, gs_cfg, hops, head_types)
            gs_stats = train_loader.peek_stats()
            train_step = make_halo_train_step(
                model, cfg, opt_spec, mesh, output_names, axis=dp_axes,
                zero_specs=zero_sh, telemetry_metrics=telemetry.enabled,
                nonfinite_guard=res_cfg.nonfinite_guard)
            eval_step = make_halo_eval_step(model, cfg, mesh, axis=dp_axes,
                                            zero=zero_sh)
        elif graph_shard == "gspmd":
            # correctness baseline: committed-sharded batches, GSPMD inserts
            # (full-array) collectives — no memory win, exact numerics
            # (parallel/graph_shard.py docstring)
            from hydragnn_tpu.parallel.graph_shard import (
                GspmdBatchLoader,
                make_gspmd_eval_step,
                make_gspmd_train_step,
            )

            steps_per_dispatch = 1
            train_loader = GspmdBatchLoader(train_loader, mesh)
            val_loader = GspmdBatchLoader(val_loader, mesh)
            test_loader = GspmdBatchLoader(test_loader, mesh)
            gs_stats = {"n_shards": n_shards}
            train_step = make_gspmd_train_step(
                model, cfg, opt_spec, mesh, output_names,
                telemetry_metrics=telemetry.enabled,
                nonfinite_guard=res_cfg.nonfinite_guard)
            eval_step = make_gspmd_eval_step(model, cfg, mesh)
        else:
            # scan chunking works on the multi-host path too: every process
            # assembles [K, d_local, ...] superbatches that GlobalBatchLoader
            # turns into [K, d_global, ...] (spec P(None, dp)) for the
            # scanned step — K steps of cross-host psum per dispatch,
            # amortizing the per-dispatch host latency that multi-host runs
            # otherwise pay per step (docs/SCALING.md "Dispatch overhead")
            steps_per_dispatch = max(
                1, env_int("HYDRAGNN_STEPS_PER_DISPATCH", auto_k))
            train_step = make_dp_train_step(
                model, cfg, opt_spec, mesh, output_names, axis=dp_axes,
                zero_specs=zero_sh, steps=steps_per_dispatch,
                telemetry_metrics=telemetry.enabled,
                nonfinite_guard=res_cfg.nonfinite_guard,
                dtype_policy=train_dtype)
            eval_step = make_dp_eval_step(model, cfg, mesh, axis=dp_axes,
                                          zero=zero_sh)
            _align_bucket_group(
                train_loader, n_local_devices * steps_per_dispatch)
            train_loader = DeviceStackLoader(
                train_loader, n_local_devices, drop_last=True)
            val_loader = DeviceStackLoader(
                val_loader, n_local_devices, drop_last=False)
            test_loader = DeviceStackLoader(
                test_loader, n_local_devices, drop_last=False)
            if steps_per_dispatch > 1:
                # second stack: [K, D, ...] superbatches for the scanned step
                train_loader = DeviceStackLoader(
                    train_loader, steps_per_dispatch, drop_last=True)
            if env_flag("HYDRAGNN_COMMS_PROBE") and single_proc:
                # opt-in comm-vs-compute attribution (docs/TELEMETRY.md
                # "Tracing"): A/B-time the annotated step vs a
                # collective-only replay on COPIES of the state, then fold
                # the split into the manifest `comms` block.  Single
                # process only — the replay is not a global collective
                # every rank could join.
                probe_b = next(iter(train_loader), None)
                if probe_b is not None:
                    from hydragnn_tpu.telemetry.comms import dp_comms_probe

                    telemetry.log_comms(dp_comms_probe(
                        model, cfg, opt_spec, mesh, state, probe_b,
                        output_names, zero_specs=zero_sh, axis=dp_axes,
                        steps=steps_per_dispatch))
        # per-device resident bytes under the chosen layout — the manifest
        # `sharding` block, so the ~1/N saving is a measured number; with
        # graph sharding active it also carries the partition stats
        # (cut-edge %, halo rows, imbalance, halo-buffer waste) teleview
        # renders
        from hydragnn_tpu.parallel.zero import sharding_report

        telemetry.log_sharding({
            "zero_stage_requested": zero_requested,
            **({"fallback": zero_fallback} if zero_fallback else {}),
            **sharding_report(state, zero_sh),
            **({"graph_shard": {
                "backend": graph_shard,
                "requested": gs_requested,
                **({"fallback": gs_fallback} if gs_fallback else {}),
                **gs_stats,
            }} if gs_requested != "off" else {}),
        })
        if graph_shard == "off" and not single_proc:
            train_loader = GlobalBatchLoader(
                train_loader, mesh, scan=steps_per_dispatch > 1)
            val_loader = GlobalBatchLoader(val_loader, mesh)
            test_loader = GlobalBatchLoader(test_loader, mesh)
        elif graph_shard != "gspmd":
            # single-process DP and halo-sharded batches alike are stacked
            # [D, ...] pytrees split along the mesh axis, so the prefetch /
            # device-resident wrappers apply to both; gspmd batches are
            # already committed-placed by GspmdBatchLoader
            from jax.sharding import NamedSharding, PartitionSpec as P

            # batch sharding: leading scan axis (if any) replicated, device
            # axis split over the mesh
            bspec = (P(None, dp_axes) if steps_per_dispatch > 1
                     else P(dp_axes))
            train_shard = NamedSharding(mesh, bspec)
            eval_shard = NamedSharding(mesh, P(dp_axes))
            if env_flag("HYDRAGNN_DEVICE_PREFETCH"):
                # async H2D of upcoming stacked batches while the current
                # step runs.  Opt-in: helps on locally-attached devices; on
                # a tunneled/remote runtime the background transfer contends
                # with dispatch and HURTS (docs/PERF.md).
                from hydragnn_tpu.data.prefetch import DevicePrefetcher

                train_loader = DevicePrefetcher(
                    train_loader, sharding=train_shard)
                val_loader = DevicePrefetcher(val_loader, sharding=eval_shard)
                test_loader = DevicePrefetcher(
                    test_loader, sharding=eval_shard)
            if resident_on:
                from hydragnn_tpu.data.prefetch import ResidentDeviceLoader

                train_loader = ResidentDeviceLoader(
                    train_loader, sharding=train_shard)
                val_loader = ResidentDeviceLoader(
                    val_loader, sharding=eval_shard)
                test_loader = ResidentDeviceLoader(
                    test_loader, sharding=eval_shard)
    else:
        zero_sh = None
        if graph_shard != "off":
            # graph sharding needs the mesh path (there is no axis to split
            # a graph across on the local-jit path) — warn-and-fall-back
            import warnings

            warnings.warn(
                f"graph sharding ({graph_shard}) requested but this run "
                "takes the single-device local-jit path — the graph must "
                "fit one device (sharding needs the mesh path: >1 local "
                "device, multi-process, or use_mesh_dp=True)", stacklevel=2)
            telemetry.health("graph_shard_fallback", requested=graph_shard,
                             reason="local_path")
            graph_shard = "off"
        if zero_stage > 0:
            # ZeRO needs the mesh path (there is no axis to shard along on
            # the local-jit path) — warn-and-fall-back, and record the
            # fallback so teleview can surface it loudly
            import warnings

            warnings.warn(
                f"ZeRO stage {zero_stage} requested but this run takes the "
                "single-device local-jit path — training REPLICATED "
                "(sharding needs the mesh path: >1 local device, "
                "multi-process, or use_mesh_dp=True)", stacklevel=2)
            zero_fallback = zero_fallback or "local_path"
            zero_stage = 0
        if zero_requested:
            telemetry.log_sharding({
                "zero_stage_requested": zero_requested,
                "fallback": zero_fallback,
                "zero_stage": 0, "axis": None, "axis_size": 1,
            })
        steps_per_dispatch = max(1, env_int("HYDRAGNN_STEPS_PER_DISPATCH", auto_k))
        if steps_per_dispatch > 1:
            # amortize per-step Python dispatch + arg-ingest latency by
            # scanning K train steps inside one executable (the batch
            # loader stacks K consecutive same-bucket batches)
            from hydragnn_tpu.parallel.mesh import DeviceStackLoader

            train_step = jax.jit(
                make_scan_train_step(model, cfg, opt_spec, output_names,
                                     steps_per_dispatch,
                                     telemetry_metrics=telemetry.enabled,
                                     nonfinite_guard=res_cfg.nonfinite_guard,
                                     dtype_policy=train_dtype),
                donate_argnums=0)
            _align_bucket_group(train_loader, steps_per_dispatch)
            train_loader = DeviceStackLoader(
                train_loader, steps_per_dispatch, drop_last=True)
        else:
            train_step = jax.jit(
                make_train_step(model, cfg, opt_spec, output_names,
                                telemetry_metrics=telemetry.enabled,
                                nonfinite_guard=res_cfg.nonfinite_guard,
                                dtype_policy=train_dtype),
                donate_argnums=0)
        if env_flag("HYDRAGNN_DEVICE_PREFETCH"):
            # async H2D of upcoming (stacked) batches — AFTER stacking, so
            # the staged device arrays are consumed directly by the step
            # instead of round-tripping through np.stack
            from hydragnn_tpu.data.prefetch import DevicePrefetcher

            train_loader = DevicePrefetcher(train_loader)
            val_loader = DevicePrefetcher(val_loader)
            test_loader = DevicePrefetcher(test_loader)
        if resident_on:
            # stage each (stacked) batch to HBM once, replay thereafter —
            # removes steady-state H2D transfer for datasets that fit
            from hydragnn_tpu.data.prefetch import ResidentDeviceLoader

            train_loader = ResidentDeviceLoader(train_loader)
            val_loader = ResidentDeviceLoader(val_loader)
            test_loader = ResidentDeviceLoader(test_loader)
        eval_step = jax.jit(make_eval_step(model, cfg))

    # the launched world shape as the elastic machinery defines it:
    # dp_extent is the number of batch shards per step — the extent the
    # stream split and the ZeRO padding actually depend on, not
    # world_size alone (resilience/elastic.py:world_block)
    dp_extent = int(mesh.devices.size) if use_mesh_dp else 1

    scheduler = ReduceLROnPlateau()
    earlystopper = None
    if training.get("EarlyStopping"):
        earlystopper = EarlyStopping(patience=training.get("patience", 10))
    # ZeRO-sharded optimizer state must be consolidated (all_gather over the
    # mesh — a collective EVERY process participates in) before any
    # serialization; one definition serves the pickle and orbax paths.
    consolidate = lambda s: s  # noqa: E731
    if use_mesh_dp and zero_sh is not None:
        from hydragnn_tpu.parallel.zero import consolidate_state

        consolidate = lambda s: consolidate_state(s, zero_sh, mesh)  # noqa: E731

    checkpointer = None
    if training.get("Checkpoint"):
        checkpointer = CheckpointTracker(
            log_name, warmup=training.get("checkpoint_warmup", 0),
            path=logs_dir, rank=rank)
        checkpointer.transform = consolidate

    # -- resilience wiring (docs/RESILIENCE.md) -----------------------------
    guard_monitor = None
    if res_cfg.nonfinite_guard:
        from hydragnn_tpu.resilience import NonFiniteGuardMonitor

        guard_monitor = NonFiniteGuardMonitor(
            max_consecutive=res_cfg.guard_max_consecutive,
            poll_every=res_cfg.guard_poll_every,
            steps_per_item=steps_per_dispatch,
            dump_path=os.path.join(logs_dir, log_name,
                                   "nonfinite_abort.json"),
            telemetry=telemetry)
    preempt = None
    if res_cfg.preemption:
        from hydragnn_tpu.resilience import PreemptionHandler

        # cross-rank agreement uses GLOBAL host collectives — an ensemble
        # branch (explicit sub-mesh) must not attempt them (same rule as
        # the telemetry cross-rank reduction)
        preempt = PreemptionHandler(
            sync_every=res_cfg.preempt_sync_every,
            cross_rank=(not explicit_mesh and world_size > 1)).install()
    # epoch-boundary elastic resize agreement (resilience/elastic.py) —
    # built only when something can arm a resize (the chaos knob today, a
    # capacity scheduler's drain hook tomorrow); None costs nothing
    from hydragnn_tpu.resilience import ElasticCoordinator

    elastic_coord = ElasticCoordinator.from_env(
        chaos=chaos, telemetry=telemetry, world_size=world_size,
        cross_rank=(not explicit_mesh and world_size > 1))

    # Orbax FULL-train-state checkpoint (step counter + params + batch stats
    # + opt state) every N epochs — beyond the reference's best-model pickle,
    # which restarts at epoch 0 (utils/model.py:58-103).  run_training's
    # ``continue`` path prefers this over the pickle when present.
    orbax_every = int(training.get("full_state_checkpoint", 0) or 0)
    orbax_dir = os.path.join(logs_dir, log_name, "orbax")

    from hydragnn_tpu.utils.print_utils import print_distributed
    from hydragnn_tpu.utils import tracer as tr
    from hydragnn_tpu.utils.profile import Profiler

    # per-batch wait/warmup/active trace schedule (reference wires
    # profiler.step() per train batch, train_validate_test.py:503)
    profiler = Profiler(profile_config, log_name, logs_dir)

    telemetry.attach_tensorboard(writer)
    telemetry.bind_step(train_step, state, steps_per_dispatch)

    history: Dict[str, Any] = {
        "train": [], "val": [], "test": [], "lr": [], "epoch_time": [],
        # the fast-pipeline configuration THIS run actually used — exact
        # provenance for bench/telemetry (re-deriving it afterwards can
        # disagree near the residency budget boundary)
        "pipeline": {"steps_per_dispatch": steps_per_dispatch,
                     "resident": bool(resident_on),
                     "zero_stage": zero_stage,
                     "graph_shard": graph_shard,
                     "train_dtype": train_dtype,
                     "train_dtype_requested": train_dtype_requested,
                     "auto_selected":
                         "HYDRAGNN_STEPS_PER_DISPATCH" not in os.environ}}
    lr = get_learning_rate(state.opt_state)

    # -- mid-run resume (resilience/resume.py + resilience/elastic.py) ------
    # the bundle's items_consumed counts dispatch units of the FINAL wrapped
    # train loader, so a same-shape resume must match the preempted run's
    # pipeline shape — a silent mismatch would re-run or skip real optimizer
    # steps.  A WORLD-shape mismatch routes through resolve_resume: strict
    # (default) refuses loudly naming both shapes, `epoch` admits the
    # resize at an epoch boundary (docs/RESILIENCE.md "Elastic training").
    from hydragnn_tpu.resilience.elastic import resolve_resume, world_block

    def _launched_world():
        try:
            units = int(len(train_loader)) or None
        except TypeError:
            units = None
        return world_block(
            world_size=world_size, n_local_devices=n_local_devices,
            dp_extent=dp_extent, zero_stage=zero_stage, epoch_units=units,
            plan_fingerprint=(stream_base.plan().fingerprint()
                              if stream_base is not None else None))

    start_epoch = 0
    skip_first = 0
    if resume_meta:
        decision = resolve_resume(
            resume_meta, policy=res_cfg.elastic_resume,
            launched=_launched_world(), telemetry=telemetry)
        rp = resume_meta.get("pipeline") or {}
        if not decision.elastic:
            # same-shape path: EXACTLY the pre-elastic validation, so an
            # unchanged-world resume stays bit-identical (the elastic
            # machinery is provably dormant here — tests/test_elastic.py)
            if rp and (int(rp.get("steps_per_dispatch", steps_per_dispatch))
                       != steps_per_dispatch
                       or bool(rp.get("use_mesh_dp", use_mesh_dp))
                       != bool(use_mesh_dp)
                       or str(rp.get("graph_shard", graph_shard))
                       != str(graph_shard)):
                raise ValueError(
                    f"resume bundle was saved with pipeline {rp} but this "
                    f"run built steps_per_dispatch={steps_per_dispatch}, "
                    f"use_mesh_dp={use_mesh_dp}; resume with the same "
                    "pipeline knobs (HYDRAGNN_STEPS_PER_DISPATCH etc.) for "
                    "an exact continuation")
        else:
            # admitted resize: the position is epoch-granular (or an exact
            # unit conversion), so steps_per_dispatch / use_mesh_dp may
            # differ freely — but graph_shard changes what a dispatch unit
            # CONTAINS, so the stream is not comparable across backends
            if str(rp.get("graph_shard", graph_shard)) != str(graph_shard):
                raise ValueError(
                    "elastic resume: bundle was saved with graph_shard="
                    f"{rp.get('graph_shard')!r} but this run built "
                    f"{graph_shard!r}; the dispatch-unit stream is not "
                    "comparable across graph-shard backends")
            saved_ws = int(decision.saved.get("world_size", 1))
            telemetry.health(
                "elastic_resize", saved_world=saved_ws,
                world_size=world_size, epoch=decision.start_epoch,
                rounded=bool(decision.rounded), reason=decision.reason)
            telemetry.health(
                "elastic_admit", epoch=decision.start_epoch,
                items=decision.skip_first, saved_world=saved_ws,
                world_size=world_size, zero_stage=zero_stage,
                reason=decision.reason)
            if decision.rounded:
                import warnings

                warnings.warn(
                    "elastic resume rounded a mid-epoch position (epoch "
                    f"{int(resume_meta.get('epoch', 0))}, "
                    f"{int(resume_meta.get('items_consumed', 0))} unit(s) "
                    "consumed) up to the epoch "
                    f"{decision.start_epoch} boundary — the remainder of "
                    "the saved epoch is not replayed", stacklevel=2)
        start_epoch = decision.start_epoch
        skip_first = decision.skip_first
        if resume_meta.get("scheduler"):
            scheduler.load_state_dict(resume_meta["scheduler"])
        if earlystopper is not None and resume_meta.get("earlystop"):
            earlystopper.load_state_dict(resume_meta["earlystop"])
        if checkpointer is not None and resume_meta.get("checkpointer"):
            checkpointer.load_state_dict(resume_meta["checkpointer"])
        for k, v in (resume_meta.get("history") or {}).items():
            if k in history and isinstance(v, list):
                history[k] = list(v)
        lr = float(resume_meta.get("lr", lr))
        telemetry.resume_counts(int(resume_meta.get("saved_step", 0)))
        telemetry.health("resume_from", epoch=start_epoch,
                         items=skip_first,
                         step=resume_meta.get("saved_step"))

    def _save_resume(epoch_i: int, items: int, reason: str) -> bool:
        """Write the resume bundle (state + host control state); every
        rank enters (the consolidate transform and orbax save are
        collectives), rank 0 writes the meta."""
        from hydragnn_tpu.resilience import resume_dir, save_resume_bundle

        meta = {
            "epoch": epoch_i,
            "items_consumed": items,
            "scheduler": scheduler.state_dict(),
            "earlystop": (earlystopper.state_dict()
                          if earlystopper is not None else None),
            "checkpointer": (checkpointer.state_dict()
                             if checkpointer is not None else None),
            "history": {k: history[k]
                        for k in ("train", "val", "test", "lr",
                                  "epoch_time")},
            "lr": lr,
            "pipeline": {"steps_per_dispatch": steps_per_dispatch,
                         "resident": bool(resident_on),
                         "use_mesh_dp": bool(use_mesh_dp),
                         # the bundle's state is CONSOLIDATED (stage-
                         # agnostic) and the graph partition is DATA
                         # sharding only — a resume may re-shard the state
                         # under any stage exactly, but the batch stream
                         # position counts dispatch units of THIS loader
                         # stack, so graph_shard must match
                         "zero_stage": zero_stage,
                         "graph_shard": graph_shard,
                         # accept/reject verdict, not the request: a
                         # resumed run reuses it verbatim (no re-probe) so
                         # the continuation traces the SAME program
                         "train_dtype": train_dtype,
                         "n_local_devices": n_local_devices},
            "world_size": world_size,
            # the launched world shape + stream-plan identity: what a
            # resume at a DIFFERENT shape validates against and converts
            # the saved position with (resilience/elastic.py)
            "world": _launched_world(),
        }
        ok = save_resume_bundle(
            consolidate(state), meta, resume_dir(logs_dir, log_name),
            rank=rank, retries=res_cfg.ckpt_retries,
            backoff=res_cfg.ckpt_backoff, telemetry=telemetry,
            chaos=chaos, reason=reason,
            cross_rank=(not explicit_mesh and world_size > 1))
        telemetry.health(
            "walltime_save" if reason == "walltime" else "preempt_save",
            epoch=epoch_i, items=items, ok=ok,
            step=int(jax.device_get(state.step)))
        return ok

    try:
        for epoch in range(start_epoch, num_epoch):
            t0 = time.time()
            telemetry.begin_epoch(epoch)
            train_loader.set_epoch(epoch)
            if stream_base is not None and stream_base.tail_grew:
                old_n, new_n = stream_base.tail_grew
                stream_base.tail_grew = None
                telemetry.health("stream_tail_grow", old=int(old_n),
                                 new=int(new_n))
            # mid-epoch resume: a streaming loader skips the already-
            # consumed units inside its plan (never decoding them); other
            # loaders fall back to _run_epoch's iterate-and-discard
            sf = skip_first if epoch == start_epoch else 0
            ff_base = 0
            if sf and try_fast_forward(train_loader, sf):
                ff_base, sf = sf, 0
            # train/val/test all DISPATCH without a device->host sync; ONE
            # combined device_get drains the queue per epoch (each separate
            # sync costs a full tunnel round trip, ~100 ms on remote PJRT —
            # three of them made the out-of-the-box epoch 37% slower).  The
            # tr regions therefore time dispatch, not execution; the fetch
            # region carries the wait.
            tr.start("train")
            state, train_acc = _run_epoch(
                train_step, state, train_loader, True, profiler=profiler,
                steps_per_item=steps_per_dispatch,
                telemetry=telemetry if telemetry.enabled else None,
                guard=guard_monitor, preempt=preempt, chaos=chaos,
                skip_first=sf, consumed_base=ff_base)
            tr.stop("train")
            if epoch == start_epoch:
                # model dispatch sites recorded any fell-off-the-fast-path
                # reasons at trace time (telemetry/pipeline.py); the first
                # epoch's dispatch is done, so surface them as health
                # events an operator (and teleview) will actually see
                from hydragnn_tpu.telemetry import pipeline as _pipe

                for fb in _pipe.pop_fallbacks("fused"):
                    telemetry.health("fused_fallback", **fb)
                    if fb.get("arch") == "EGNN":
                        # per-arch kind kept as an alias for one release
                        # (dashboards keyed on it migrate to
                        # fused_fallback + arch field)
                        legacy = {k: v for k, v in fb.items()
                                  if k != "arch"}
                        telemetry.health("egcl_fallback", **legacy)
            if preempt is not None and preempt.stop_requested:
                # preemption agreed mid-epoch: bundle the exact position
                # (epoch + items consumed) and stop; `continue` resumes here
                telemetry.flush_steps()
                _save_resume(epoch, preempt.consumed, reason="preempt")
                history["preempted"] = True
                print_distributed(
                    verbosity,
                    f"Preempted at epoch {epoch} after {preempt.consumed} "
                    "train dispatch(es); resume bundle saved")
                break
            if guard_monitor is not None:
                # drain buffered skip flags before val/test; raises
                # NonFiniteTrainingError past the consecutive-bad threshold
                guard_monitor.flush()
            # HYDRAGNN_VALTEST=0 skips the val/test epochs (reference knob)
            valtest = bool(int(os.getenv("HYDRAGNN_VALTEST", "1")))
            val_acc = test_acc = None
            if valtest:
                tr.start("validate")
                _, val_acc = _run_epoch(eval_step, state, val_loader, False)
                tr.stop("validate")
                tr.start("test")
                _, test_acc = _run_epoch(eval_step, state, test_loader, False)
                tr.stop("test")
            tr.start("metrics_fetch")
            train_acc, val_acc, test_acc = jax.device_get(
                (train_acc, val_acc, test_acc))
            # drain the buffered per-step telemetry in the same sync window
            # (one device_get of tiny scalars; no-op when disabled)
            telemetry.flush_steps()
            tr.stop("metrics_fetch")
            train_loss, train_tasks = _epoch_metrics(train_acc)
            if valtest:
                val_loss, _ = _epoch_metrics(val_acc)
                test_loss, _ = _epoch_metrics(test_acc)
            else:
                val_loss = test_loss = train_loss

            if world_size > 1 and not use_mesh_dp:
                # local-jit fallback only: the global-mesh step already psums
                # losses across every process's devices inside the jit.
                from hydragnn_tpu.parallel.comm import host_allreduce
                reduced = host_allreduce(
                    np.asarray([train_loss, val_loss, test_loss]), op="sum")
                train_loss, val_loss, test_loss = (reduced / world_size).tolist()

            new_lr = scheduler.step(val_loss, lr)
            if new_lr != lr:
                lr = new_lr
                state = state.replace(
                    opt_state=set_learning_rate(state.opt_state, lr))

            history["train"].append(train_loss)
            history["val"].append(val_loss)
            history["test"].append(test_loss)
            history["lr"].append(lr)
            # wall time per epoch (train + val/test + host bookkeeping): the
            # sustained-throughput evidence bench.py reports comes from here
            history["epoch_time"].append(time.time() - t0)

            # one epoch record through the telemetry spine: the TensorBoard
            # scalars ride TensorBoardSink (same tags as the old inline
            # add_scalar calls), JSONL/CSV/stdout sinks get the full record,
            # and cross-rank min/max/avg of the timing metrics reduce here
            epoch_scalars = {
                "train_loss": train_loss,
                "val_loss": val_loss,
                "test_loss": test_loss,
                "lr": lr,
                "epoch_time_s": history["epoch_time"][-1],
                "train_tasks": [float(t) for t in train_tasks],
            }
            # epoch-level throughput (the fetched accumulator's graph count
            # over the epoch wall clock) — the metric the cross-rank
            # min/max/avg reduction compares across hosts.  ALWAYS present
            # (0.0 for an empty epoch): the reduction's key list must be
            # identical on every rank or the collective shapes mismatch.
            epoch_scalars["graphs_per_s"] = (
                float(train_acc[2]) / history["epoch_time"][-1]
                if train_acc is not None and history["epoch_time"][-1] > 0
                else 0.0)
            telemetry.log_epoch(epoch, epoch_scalars,
                                train_loader=train_loader)

            print_distributed(
                verbosity,
                f"Epoch: {epoch:4d}, train loss: {train_loss:.8f}, "
                f"val loss: {val_loss:.8f}, test loss: {test_loss:.8f}, "
                f"lr: {lr:.2e}  ({time.time() - t0:.2f}s)",
            )

            if checkpointer is not None:
                checkpointer(state, val_loss)
            if orbax_every and (epoch + 1) % orbax_every == 0:
                # EVERY process calls this: the ZeRO consolidation jit and
                # orbax's CheckpointManager are both cross-process collectives —
                # a rank-0 gate would deadlock multi-host runs.  Retried with
                # backoff; a persistently failing filesystem warns and the
                # run KEEPS TRAINING (a periodic checkpoint is not worth the
                # run) — resilience/ckpt_io.py.
                from hydragnn_tpu.resilience.ckpt_io import with_retries
                from hydragnn_tpu.utils.checkpoint import save_checkpoint

                consolidated = consolidate(state)
                with_retries(
                    lambda: save_checkpoint(consolidated, orbax_dir),
                    retries=res_cfg.ckpt_retries,
                    backoff=res_cfg.ckpt_backoff,
                    what="periodic full-state checkpoint",
                    telemetry=telemetry, chaos=chaos, on_fail="warn",
                    cross_rank=(not explicit_mesh and world_size > 1))
            if earlystopper is not None and earlystopper(val_loss):
                print_distributed(verbosity, f"Early stopping at epoch {epoch}")
                break
            # SLURM walltime graceful stop (reference train_validate_test.py:229-235)
            # — now resumable: the full resume bundle is saved before
            # breaking, so `continue` picks up at epoch+1 instead of losing
            # everything since the last full_state_checkpoint epoch
            if os.getenv("SLURM_JOB_ID"):
                from hydragnn_tpu.utils.slurm import check_remaining

                if not check_remaining(time.time() - t0):
                    print_distributed(
                        verbosity,
                        f"Stopping at epoch {epoch}: insufficient SLURM walltime")
                    _save_resume(epoch + 1, 0, reason="walltime")
                    history["preempted"] = True
                    break
            # a signal delivered during val/test (or missed by the final
            # mid-train sync point) is caught at the epoch boundary; every
            # rank forces the agreement collective here, keeping it symmetric
            if preempt is not None and preempt.poll(force=True):
                _save_resume(epoch + 1, 0, reason="preempt")
                history["preempted"] = True
                print_distributed(
                    verbosity,
                    f"Preempted at end of epoch {epoch}; resume bundle saved")
                break
            # agreed elastic resize: the position is the single integer
            # epoch+1 — exactly what a different-shape relaunch can admit
            # — so save the boundary bundle and exit through the same
            # path a preemption takes; retiring hosts never relaunch,
            # the survivors/joiners `continue` at the new world size
            if elastic_coord is not None:
                resize = elastic_coord.poll(epoch)
                if resize is not None:
                    _save_resume(epoch + 1, 0, reason="elastic")
                    history["preempted"] = True
                    history["elastic"] = resize
                    print_distributed(
                        verbosity,
                        f"Elastic resize agreed at end of epoch {epoch}: "
                        f"world {resize['world_size']} -> "
                        f"{resize['target_world_size']}; resume bundle "
                        "saved")
                    break

    finally:
        # teardown runs on EVERY exit path — a crash mid-epoch must
        # still stop an active trace, write the (partial-history)
        # manifest, close the sinks and unlatch the module-global
        # pipeline counters, or the next run in this process (HPO
        # trial, test) inherits stale telemetry state
        if preempt is not None:
            preempt.uninstall()
        # release this run's cached orbax managers (background threads +
        # handles) — an HPO loop's trials use fresh directories and would
        # otherwise pin one manager per directory for the process lifetime
        from hydragnn_tpu.resilience import resume as _resume
        from hydragnn_tpu.utils.checkpoint import close_manager

        close_manager(orbax_dir)
        close_manager(os.path.join(
            _resume.resume_dir(logs_dir, log_name), _resume.STATE_DIRNAME))
        profiler.disable()
        timer = tr.get("timer")
        telemetry.finalize(
            history, timers=timer.summary() if timer is not None else None)
    if use_mesh_dp and zero_sh is not None:
        # hand back a fully-replicated, unpadded state: callers (final
        # save_state, run_prediction, tests) are stage-agnostic
        state = consolidate(state)
    return state, history


def test(
    eval_step,
    state: TrainState,
    loader,
    num_heads: int,
    reduce_ranks: bool = True,
    world_size: int = 1,
    *,
    output_types: Sequence[str],
) -> Tuple[float, np.ndarray, List[np.ndarray], List[np.ndarray]]:
    """Full-dataset evaluation returning (error, per-task error, true, pred)
    per head with padding stripped (parity: reference test(),
    train_validate_test.py:565-664)."""
    total = 0.0
    n = 0.0
    tasks = np.zeros(num_heads)
    true_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]
    pred_values: List[List[np.ndarray]] = [[] for _ in range(num_heads)]
    dump_file = None
    if int(os.getenv("HYDRAGNN_DUMP_TESTDATA", "0")):
        # per-rank raw test dump (reference train_validate_test.py:580-623)
        from hydragnn_tpu.parallel.comm import process_index

        dump_file = open(f"testdata_rank{process_index()}.pickle", "wb")
    for g in loader:
        m = eval_step(state, g)
        ng = float(m["num_graphs"])
        total += float(m["loss"]) * ng
        tasks += np.asarray([float(t) for t in m["per_head"]]) * ng
        n += ng
        outputs = m["outputs"]
        gm = np.asarray(g.graph_mask) > 0
        nm = np.asarray(g.node_mask) > 0
        if hasattr(g, "send_idx") and gm.ndim == 2:
            # halo-sharded batch (graph/partition.py:HaloBatch): graph
            # arrays are REPLICATED per shard and stacked [D, G] — without
            # this, every real graph's label/prediction is collected D
            # times.  Node rows need no dedup: node_mask marks each real
            # node on exactly its owner shard.
            gm[1:] = False
        for ih in range(num_heads):
            out = np.asarray(outputs[ih])
            lab = np.asarray(g.labels[ih])
            # per-head type is required: shape inference is ambiguous when
            # padded node count equals padded graph count
            mask = gm if output_types[ih] == "graph" else nm
            true_values[ih].append(lab[mask])
            # gaussian_nll heads emit [mean, log_sigma] at 2x the label
            # width — the prediction is the mean block
            pred_values[ih].append(out[mask][:, : lab.shape[-1]])
        if dump_file is not None:
            pickle.dump(
                {f"head{ih}": {"true": true_values[ih][-1],
                               "pred": pred_values[ih][-1]}
                 for ih in range(num_heads)},
                dump_file)
    if dump_file is not None:
        dump_file.close()
    n = max(n, 1.0)
    error = total / n
    tasks = tasks / n
    true_cat = [np.concatenate(v, axis=0) for v in true_values]
    pred_cat = [np.concatenate(v, axis=0) for v in pred_values]
    if reduce_ranks and world_size > 1:
        from hydragnn_tpu.parallel.comm import (
            host_allgather_variable,
            host_allreduce,
        )

        error = float(host_allreduce(np.asarray([error]), "sum")[0]) / world_size
        tasks = host_allreduce(tasks, "sum") / world_size
        # per-host sample counts differ: padded variable-size gather
        # (parity: reference gather_tensor_ranks, train_validate_test.py:381-419)
        true_cat = [host_allgather_variable(t) for t in true_cat]
        pred_cat = [host_allgather_variable(p) for p in pred_cat]
    return error, tasks, true_cat, pred_cat
