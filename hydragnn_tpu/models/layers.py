"""Shared neural building blocks: activations, losses, MLPs, masked BatchNorm.

Parity targets:
  - activation selector        -> reference hydragnn/utils/model.py:30-44
  - loss selector              -> reference hydragnn/utils/model.py:47-55
  - PyG BatchNorm under padding-> :class:`MaskedBatchNorm` (masked statistics;
    with jit + sharding the batch statistics are computed over the *global*
    sharded batch, which natively gives SyncBatchNorm semantics, reference
    hydragnn/utils/distributed.py:238-239)
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import os

import jax
import jax.numpy as jnp
import flax.linen as nn


class PReLU(nn.Module):
    """Learnable leaky-ReLU (torch.nn.PReLU parity: single shared slope 0.25)."""

    @nn.compact
    def __call__(self, x):
        alpha = self.param("alpha", lambda key: jnp.asarray(0.25, jnp.float32))
        return jnp.where(x >= 0, x, alpha * x)


def activation_module(name: str):
    """Activation by config name (reference hydragnn/utils/model.py:30-44)."""
    fns = {
        "relu": nn.relu,
        "selu": nn.selu,
        "elu": nn.elu,
        "lrelu_01": lambda x: nn.leaky_relu(x, 0.1),
        "lrelu_025": lambda x: nn.leaky_relu(x, 0.25),
        "lrelu_05": lambda x: nn.leaky_relu(x, 0.5),
    }
    if name == "prelu":
        return PReLU()
    if name not in fns:
        raise ValueError(f"Unknown activation function: {name}")
    return fns[name]


def loss_function(name: str) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """Masked, mean-reduced loss (reference hydragnn/utils/model.py:47-55).

    Signature: (pred, target, mask) -> scalar.  ``mask`` broadcasts along the
    leading axis; the mean runs over valid elements only, so padded rows
    reproduce the reference's unpadded loss exactly.
    """

    def _masked_mean(err, mask):
        # shard-aware (graph/partition.py): under a halo-sharding trace the
        # valid rows are split across shards — psum numerator and count so
        # every shard computes the exact GLOBAL masked mean (identity
        # outside a halo trace)
        from hydragnn_tpu.graph.partition import halo_psum

        m = mask.reshape(mask.shape + (1,) * (err.ndim - mask.ndim))
        denom = jnp.maximum(
            halo_psum(jnp.sum(m)) * err.shape[-1], 1.0)
        return halo_psum(jnp.sum(err * m)) / denom

    if name == "mse":
        return lambda p, t, m: _masked_mean((p - t) ** 2, m)
    if name == "mae":
        return lambda p, t, m: _masked_mean(jnp.abs(p - t), m)
    if name == "smooth_l1":

        def _sl1(p, t, m):
            d = jnp.abs(p - t)
            return _masked_mean(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5), m)

        return _sl1
    if name == "rmse":
        return lambda p, t, m: jnp.sqrt(_masked_mean((p - t) ** 2, m) + 1e-16)
    raise ValueError(f"Unknown loss function: {name}")


def symmetric_uniform_init(bound: float):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


# torch.nn.Linear default init: kaiming_uniform(a=sqrt(5)) on the kernel
# (== uniform(+-sqrt(1/fan_in))) and uniform(+-1/sqrt(fan_in)) bias.  The
# reference relies on this spread-out init; zero-init biases make narrow ReLU
# heads collapse to constants on some seeds.
torch_kernel_init = nn.initializers.variance_scaling(
    1.0 / 3.0, "fan_in", "uniform")


class TDense(nn.Module):
    """Dense layer with torch.nn.Linear's default initialization."""

    features: int

    @nn.compact
    def __call__(self, x):
        import math

        fan_in = x.shape[-1]
        bound = 1.0 / math.sqrt(fan_in)
        kernel = self.param(
            "kernel", torch_kernel_init, (fan_in, self.features))
        bias = self.param(
            "bias", symmetric_uniform_init(bound), (self.features,))
        return x @ kernel + bias


class MLP(nn.Module):
    """Dense stack: hidden layers with activation, linear output layer."""

    features: Sequence[int]
    activation: str = "relu"
    final_activation: bool = False

    @nn.compact
    def __call__(self, x):
        act = activation_module(self.activation)
        for i, f in enumerate(self.features):
            x = TDense(f, name=f"dense_{i}")(x)
            if i < len(self.features) - 1 or self.final_activation:
                x = act(x)
        return x


class MaskedBatchNorm(nn.Module):
    """BatchNorm over valid (masked) rows with running statistics.

    Equivalent to PyG ``BatchNorm`` (torch momentum 0.1, eps 1e-5) but exact
    under padded static-shape batching: padded rows contribute nothing to the
    batch statistics.  Under jit with a data-sharded batch the reductions are
    global across devices — i.e. cross-replica (Sync) BatchNorm for free.
    """

    features: int
    momentum: float = 0.1  # torch convention: new = (1-m)*old + m*batch
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x, mask, use_running_average: bool = False):
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        scale = self.param("scale", nn.initializers.ones, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))

        # experimental recipe knob (wide-GAT eval-divergence studies,
        # docs/PERF.md): override the running-stats momentum without
        # touching the checkpointed module tree
        momentum = float(
            os.environ.get("HYDRAGNN_BN_MOMENTUM") or self.momentum)

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # shard-aware statistics: under a halo-sharding trace
            # (graph/partition.py:halo_context) the masked rows are split
            # across shards — psum the partial sums/counts so every shard
            # normalizes with the exact GLOBAL batch statistics (the same
            # SyncBatchNorm semantics the GSPMD path gets implicitly, and
            # the property that keeps a halo copy bit-consistent with its
            # owner row).  Identity outside a halo trace.
            from hydragnn_tpu.graph.partition import halo_psum

            m = mask.astype(x.dtype)[:, None]
            count = jnp.maximum(halo_psum(jnp.sum(m)), 1.0)
            mean = halo_psum(jnp.sum(x * m, axis=0)) / count
            var = halo_psum(jnp.sum(((x - mean) ** 2) * m, axis=0)) / count
            if not self.is_initializing():
                # torch tracks the *unbiased* variance in running stats
                unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
                ra_mean.value = (
                    1.0 - momentum
                ) * ra_mean.value + momentum * mean
                ra_var.value = (
                    1.0 - momentum
                ) * ra_var.value + momentum * unbiased
        return scale * (x - mean) * jax.lax.rsqrt(var + self.eps) + bias


def shifted_softplus(x):
    """softplus(x) - log(2): SchNet's activation (PyG ShiftedSoftplus)."""
    return jax.nn.softplus(x) - jnp.log(2.0)


class DenseParams(nn.Module):
    """Parameters of an ``nn.Dense`` WITHOUT its matmul: same names
    (kernel/bias), same default inits, same param tree — so the fused
    edge-block paths (ops/fused_block.py specs: SchNet's cfconv,
    DimeNet's triplet interaction, EGNN's interaction block, CGCNN's
    gated sum) and the composed paths share checkpoints.
    ``kernel_init`` overrides for layers whose nn.Dense twin uses a
    non-default init (EGNN's coord gate)."""

    in_dim: int
    features: int
    use_bias: bool = True
    kernel_init: object = None

    @nn.compact
    def __call__(self):
        init = self.kernel_init or nn.linear.default_kernel_init
        k = self.param("kernel", init, (self.in_dim, self.features))
        if not self.use_bias:
            return k, None
        b = self.param("bias", nn.initializers.zeros_init(),
                       (self.features,))
        return k, b


def edge_geometry(pos, src, dst):
    """The ONE per-edge geometry definition shared by the composed paths
    and the fused kernels (EGNN's interaction block, SchNet's coord
    branch, the builder's geo-lane packing): normalized difference
    vector and squared distance.  eps inside the sqrt: padding
    self-edges have radial == 0 exactly, where sqrt's gradient is inf —
    this path must stay differentiable for the energy-gradient force
    loss (jax.grad wrt pos)."""
    diff = pos[src] - pos[dst]
    radial = jnp.sum(diff * diff, axis=-1, keepdims=True)
    diff = diff / (jnp.sqrt(radial + 1e-12) + 1.0)  # norm_diff=True
    return diff, radial
