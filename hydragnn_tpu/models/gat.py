"""GAT stack (parity: reference hydragnn/models/GATStack.py).

GATv2 attention with heads=6, negative_slope=0.05, attention dropout 0.25,
and self-loops (reference GATStack.py:91-100).  All-but-last encoder layers
concatenate heads (features = hidden_dim * heads); the final layer averages
them (GATStack.py:35-46) — the stack overrides the encoder/BN dim bookkeeping
accordingly.

The padded-edge problem GATv2 poses on TPU is the softmax: attention is
normalized per receiving node over its incident edges *plus* its self-loop.
We compute a numerically-stable segment softmax over the static edge array
with masks, handling the self-loop term analytically (no edge-array resize).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


def _fused_gat_enabled() -> bool:
    """One-pass Pallas attention gate: HYDRAGNN_GAT_FUSED overrides, else
    it follows the fused aggregation backend selection."""
    import os

    v = os.environ.get("HYDRAGNN_GAT_FUSED")
    if v is not None:
        return v not in ("", "0", "false", "False")
    from hydragnn_tpu.ops.aggregate import aggr_backend

    return aggr_backend() == "fused"


class GATv2Conv(nn.Module):
    out_dim: int  # per-head output dim
    heads: int
    negative_slope: float
    concat: bool
    dropout: float = 0.25

    @nn.compact
    def __call__(self, x, pos, g, train):
        n = x.shape[0]
        h, f = self.heads, self.out_dim
        src, dst = g.senders, g.receivers

        # keep node features FLAT [N, h*f]: every gather/scatter below runs
        # on 2D operands (3D scatters lowered catastrophically on TPU —
        # the r03 arch sweep measured 134 ms/step before this layout)
        xl = nn.Dense(h * f, name="lin_l")(x)  # source transform
        xr = nn.Dense(h * f, name="lin_r")(x)  # target transform
        att = self.param("att", nn.initializers.lecun_normal(), (1, h, f))

        def logits(s, t):
            z = nn.leaky_relu(s + t, self.negative_slope)
            return jnp.sum(z.reshape(-1, h, f) * att, axis=-1)  # [., h]

        b_edge, b_self = self._dropout_bits(
            train, g.senders.shape[0], n, x.dtype)

        perm = g.extras.get("edge_perm_sender") if g.extras else None
        # width gate is PER HEAD: wider hf = h*f tiles over balanced head
        # groups inside gat_edge_attention_tiled (attention is head-
        # independent), so only a single over-wide head forces the
        # composed path.  Queried live from ops/gat_mp (the module that
        # owns FUSED_HF_LIMIT) so gate and tiling can never diverge.
        from hydragnn_tpu.ops.gat_mp import fused_head_width_ok

        fused = (perm is not None and _fused_gat_enabled()
                 and fused_head_width_ok(f))
        from hydragnn_tpu.telemetry.pipeline import count_fused_choice

        count_fused_choice("gat_attn", fused)
        if fused:
            out = self._fused_attention(xl, xr, att, logits, g, perm,
                                        b_edge, b_self)
        else:
            out = self._composed_attention(xl, xr, logits, g,
                                           b_edge, b_self)

        if self.concat:
            out = out.reshape(n, h * f)
            bias = self.param("bias", nn.initializers.zeros, (h * f,))
        else:
            out = jnp.mean(out, axis=1)
            bias = self.param("bias", nn.initializers.zeros, (f,))
        return out + bias, pos

    def _dropout_bits(self, train, e_count, n, dtype):
        """Attention-dropout bits/keep for edges and self-loops (None when
        inactive) — ONE definition serving both attention paths."""
        h = self.heads
        if not (train and self.dropout > 0):
            return None, None
        rng = self.make_rng("dropout")
        keep = 1.0 - self.dropout
        k1, k2 = jax.random.split(rng)
        b_edge = (jax.random.bernoulli(k1, keep, (e_count, h))
                  .astype(dtype) / keep)
        b_self = (jax.random.bernoulli(k2, keep, (n, h))
                  .astype(dtype) / keep)
        return b_edge, b_self

    def _composed_attention(self, xl, xr, logits, g, b_edge, b_self):
        """Segment-op attention path: separate logits gathers, segment
        softmax, fused-or-XLA aggregation.  Returns [N, h, f]."""
        n = xl.shape[0]
        h, f = self.heads, self.out_dim
        dst = g.receivers

        # gathers whose backward rides the dense sorted scatter instead of
        # XLA's scatter-add (marker-gated; plain gather otherwise)
        e_edge = logits(segment.gather_sender(xl, g),
                        segment.gather_receiver_sorted(xr, g))  # [E, h]
        e_self = logits(xl, xr)  # [N, h] self-loop logit per node

        # softmax over {incident edges} U {self loop}, masked on padded
        # edges.  The max subtraction is for numerical stability only —
        # softmax is shift-invariant, so stop_gradient kills its (sort-
        # heavy) backward without changing any derivative.
        neg = -1e9
        e_edge = jnp.where(g.edge_mask[:, None] > 0, e_edge, neg)
        # plain XLA segment_max measured FASTER than both a dense-schedule
        # Pallas max kernel (in-kernel row loop too serial: 6.5k g/s) and a
        # segmented associative-scan max (compile blowup) — 9.3k g/s on the
        # v5e sweep config; see docs/PERF.md "measured and rejected"
        seg_max = segment.segment_max(e_edge, dst, n)
        deg = segment.degree(dst, n, g.edge_mask)
        seg_max = jnp.where(deg[:, None] > 0, seg_max, e_self)
        seg_max = jax.lax.stop_gradient(jnp.maximum(seg_max, e_self))
        exp_edge = jnp.exp(e_edge - seg_max[dst]) * g.edge_mask[:, None]
        exp_self = jnp.exp(e_self - seg_max)
        denom = segment.scatter_segment(exp_edge, g) + exp_self
        alpha_edge = exp_edge / jnp.maximum(denom, 1e-16)[dst]
        alpha_self = exp_self / jnp.maximum(denom, 1e-16)

        if b_edge is not None:
            alpha_edge = alpha_edge * b_edge
            alpha_self = alpha_self * b_self

        # out[n] = sum_e alpha[e] * xl[src[e]] — the gather-multiply-
        # segment-sum core; per-head alpha broadcast across the head's f
        # features keeps it one flat [E, h*f] weight (rides the fused
        # Pallas kernel when the batch carries the collate marker)
        w_alpha = jnp.repeat(alpha_edge, f, axis=1)  # [E, h*f]
        out = segment.gather_mul_segment(xl, w_alpha, g)
        return out.reshape(n, h, f) + alpha_self[:, :, None] * xl.reshape(
            n, h, f)

    def _fused_attention(self, xl, xr, att, logits, g, perm, b_edge,
                         b_self):
        """One-pass Pallas edge attention (ops/gat_mp.py) + the self-loop
        merged here in plain jnp.  Numerically the same softmax over
        {incident edges} U {self} as the composed path; the max shifts are
        stop_gradient'd (shift invariance) exactly as there.  Returns
        [N, h, f] in the compute dtype.  Above FUSED_HF_LIMIT the call
        tiles over balanced head groups (ops/gat_mp.py) — same math, one
        kernel launch per group."""
        from hydragnn_tpu.ops.gat_mp import gat_edge_attention_tiled

        n = xl.shape[0]
        h, f = self.heads, self.out_dim

        # block-diagonal logit matrix (autodiff carries datt_mat -> att)
        rows = jnp.arange(h * f)
        att_mat = jnp.zeros((h * f, h), xl.dtype).at[rows, rows // f].set(
            att.reshape(-1))

        e_count = g.senders.shape[0]
        if b_edge is None:
            b_edge = jnp.ones((e_count, h), jnp.float32)
            b_self = jnp.ones((n, h), jnp.float32)

        acc, m, d = gat_edge_attention_tiled(
            xl, xr, att_mat, g.senders, g.receivers, perm,
            g.edge_mask, b_edge, (self.negative_slope, f))
        m = jax.lax.stop_gradient(m)

        e_self = logits(xl, xr)                       # [N, h]
        m_t = jax.lax.stop_gradient(jnp.maximum(m, e_self))
        r_e = jnp.exp(m - m_t)
        r_s = jnp.exp(e_self - m_t)
        d_t = jnp.maximum(d * r_e + r_s, 1e-16)

        def expand(v):
            return jnp.repeat(v, f, axis=1)           # [N, h] -> [N, h*f]

        num = acc * expand(r_e) + expand(b_self * r_s) * xl
        # the kernel accumulates in f32; rejoin the compute-dtype pipeline
        out = (num / expand(d_t)).astype(xl.dtype)
        return out.reshape(n, h, f)


class GATStack(Base):
    def encoder_dims(self) -> List[Tuple[int, int, int]]:
        # hidden layers concat heads -> hidden_dim*heads features; final
        # layer averages heads -> hidden_dim (reference GATStack.py:35-46)
        c = self.cfg
        h = c.gat_heads
        dims = [(c.input_dim, c.hidden_dim, c.hidden_dim * h)]
        for _ in range(c.num_conv_layers - 2):
            dims.append((c.hidden_dim * h, c.hidden_dim, c.hidden_dim * h))
        dims.append((c.hidden_dim * h, c.hidden_dim, c.hidden_dim))
        return dims

    def node_conv_dims(self, head_dim):
        # reference GATStack.py:48-89: hidden node convs concat heads
        c = self.cfg
        h = c.gat_heads
        hdn = list(c.node_head.dim_headlayers)
        hidden = [(c.hidden_dim, hdn[0], hdn[0] * h)]
        for i in range(c.node_head.num_headlayers - 1):
            hidden.append((hdn[i] * h, hdn[i + 1], hdn[i + 1] * h))
        out = (hdn[-1] * h, head_dim, head_dim)
        return hidden, out

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        return GATv2Conv(
            out_dim,
            heads=c.gat_heads,
            negative_slope=c.gat_negative_slope,
            concat=not last_layer,
            dropout=c.dropout,
            name=name,
        )
