"""GIN stack (parity: reference hydragnn/models/GINStack.py).

GINConv with a 2-layer MLP and a trainable eps initialized to 100.0
(reference GINStack.py:26-34): out_i = MLP((1 + eps) x_i + sum_{j->i} x_j).
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class GINConv(nn.Module):
    out_dim: int
    eps_init: float = 100.0

    @nn.compact
    def __call__(self, x, pos, g, train):
        eps = self.param("eps", lambda key: jnp.asarray(self.eps_init, jnp.float32))
        agg = segment.gather_segment(x, g)
        h = (1.0 + eps) * x + agg
        h = nn.Dense(self.out_dim, name="mlp_0")(h)
        h = nn.relu(h)
        h = nn.Dense(self.out_dim, name="mlp_1")(h)
        return h, pos


class GINStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        return GINConv(out_dim, name=name)
