"""Model factory (parity: reference hydragnn/models/create.py:31-307).

Dispatches on ``model_type`` to the 9 conv stacks and initializes parameters
with a fixed seed (the reference seeds torch with 0; create.py:105).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import Base, ModelConfig
from hydragnn_tpu.models.sage import SAGEStack
from hydragnn_tpu.models.gin import GINStack
from hydragnn_tpu.models.gat import GATStack
from hydragnn_tpu.models.mfc import MFCStack
from hydragnn_tpu.models.pna import PNAStack
from hydragnn_tpu.models.cgcnn import CGCNNStack
from hydragnn_tpu.models.schnet import SCFStack
from hydragnn_tpu.models.egnn import EGCLStack
from hydragnn_tpu.models.dimenet import DIMEStack

_STACKS = {
    "SAGE": SAGEStack,
    "GIN": GINStack,
    "GAT": GATStack,
    "MFC": MFCStack,
    "PNA": PNAStack,
    "CGCNN": CGCNNStack,
    "SchNet": SCFStack,
    "DimeNet": DIMEStack,
    "EGNN": EGCLStack,
}

# THE canonical arch list: bench.py's per-arch sweep and the fused-vs-
# scatter parity tests (tests/test_fused_block.py) both derive from it, so
# a newly registered stack cannot miss bench or parity coverage.
ALL_ARCHS = tuple(_STACKS)


def create_model_config(config: Dict[str, Any]) -> Base:
    """Build the (uninitialized) flax module from a finalized config dict."""
    cfg = ModelConfig.from_config(config["NeuralNetwork"])
    return create_model(cfg)


def create_model(cfg: ModelConfig) -> Base:
    if cfg.model_type not in _STACKS:
        raise ValueError(f"Unknown model_type: {cfg.model_type}")
    if (cfg.model_type == "GAT" and cfg.dropout > 0
            and cfg.hidden_dim * cfg.gat_heads >= 256):
        import warnings

        # measured pathology (tools/gat_pathology.py, docs/PERF.md round
        # 5): at this width, attention dropout makes the BN running
        # statistics track a train-time distribution that mismatches
        # eval mode — train loss converges while EVAL error grows past
        # predict-the-mean, in BOTH this framework and the torch
        # reference (ACCURACY_r04/r05).  Dropout 0 measured test MAE
        # 0.40 vs 2.46 (flagship Morse-QM9 protocol, lr 1e-3).
        warnings.warn(
            f"GAT with attention dropout {cfg.dropout} at width "
            f"{cfg.hidden_dim}x{cfg.gat_heads} heads diverges in eval "
            "mode (BN running-stats mismatch; see docs/PERF.md round 5)."
            ' Set "Architecture": {"dropout": 0.0} — measured test MAE '
            "0.40 vs 2.46 on the flagship protocol.",
            stacklevel=2)
    if cfg.model_type == "PNA":
        assert cfg.pna_avg_deg_log is not None, "PNA requires degree input."
    if cfg.model_type == "MFC":
        assert cfg.max_degree is not None, "MFC requires max_neighbours input."
    if cfg.model_type == "SchNet":
        assert cfg.num_gaussians is not None, "SchNet requires num_gaussians input."
        assert cfg.num_filters is not None, "SchNet requires num_filters input."
        assert cfg.radius is not None, "SchNet requires radius input."
    if cfg.model_type == "DimeNet":
        for key in (
            "basis_emb_size",
            "envelope_exponent",
            "int_emb_size",
            "out_emb_size",
            "num_after_skip",
            "num_before_skip",
            "num_radial",
            "num_spherical",
            "radius",
        ):
            assert getattr(cfg, key) is not None, f"DimeNet requires {key} input."
    if cfg.model_type == "CGCNN" and cfg.node_head is not None:
        if cfg.node_head.type == "conv" and "node" in cfg.output_type:
            raise ValueError(
                '"conv" node decoder is not supported for CGCNN '
                "(reference CGCNNStack.py:66-89)."
            )
    return _STACKS[cfg.model_type](cfg=cfg)


def init_model(
    model: Base, example_batch: GraphBatch, seed: int = 0
) -> Dict[str, Any]:
    """Initialize variables ({'params', 'batch_stats'}) with a fixed seed."""
    rngs = {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)}
    return model.init(rngs, example_batch, train=False)
