"""CGCNN stack (parity: reference hydragnn/models/CGCNNStack.py).

CGConv with additive aggregation: for z_ij = [x_i, x_j, e_ij],
out_i = x_i + sum_{j->i} sigmoid(W_f z_ij) * softplus(W_s z_ij).
CGConv preserves feature dimension, so the stack forces
hidden_dim = input_dim (reference CGCNNStack.py:30-40), and conv-type node
heads are rejected (CGCNNStack.py:66-89 — enforced in ModelConfig.from_config
via the create-time validation in models/create.py).

The whole gated sum (both gathers -> gate MLP pair -> sigmoid*softplus ->
segment sum) dispatches to ONE Pallas pass (ops/cgcnn_mp.py) when the
batch carries the sender-sort marker and the widths fit the kernel's
tile limits; the composed XLA path below is the bit-tested fallback.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base
from hydragnn_tpu.models.layers import DenseParams
from hydragnn_tpu.ops.fused_block import note_fallback


def _cgcnn_pipeline_enabled(dim: int, edge_dim: int) -> bool:
    """Fused gated-sum gate (ops/cgcnn_mp.py): structural tile limits
    only — like EGNN's interaction block there is NO width floor,
    because the win is eliminating the [E, 2F+A] concat and both [E, F]
    gate/core streams plus the scatter pass, which dominates at
    CGCNN's stream-bound widths.  Env override HYDRAGNN_CGCNN_FUSED=1/0
    forces it either way (subject to the structural limits)."""
    from hydragnn_tpu.ops.cgcnn_mp import CGCNN_F_LIMIT, CGCNN_GEO_LIMIT

    if dim > CGCNN_F_LIMIT or edge_dim > CGCNN_GEO_LIMIT:
        return False
    v = os.environ.get("HYDRAGNN_CGCNN_FUSED")
    if v is not None:
        return v.strip().lower() not in ("0", "false", "off", "no", "")
    return True


def _cgcnn_fused_wanted() -> bool:
    if os.environ.get("HYDRAGNN_AGGR_BACKEND", "").strip().lower() \
            == "fused":
        return True
    v = os.environ.get("HYDRAGNN_CGCNN_FUSED")
    return v is not None and v.strip().lower() not in (
        "0", "false", "off", "no", "")


class CGConv(nn.Module):
    dim: int  # feature dim, preserved
    edge_dim: int = 0

    @nn.compact
    def __call__(self, x, pos, g, train):
        use_ea = bool(self.edge_dim) and g.edge_attr is not None
        a = g.edge_attr.shape[-1] if use_ea else 0

        # gate params are declared matmul-free so the fused block can
        # consume them raw; the composed path applies them exactly as
        # the nn.Dense layers they replace (identical names/inits —
        # checkpoints are path-independent).  Input width comes from the
        # ACTUAL x (nn.Dense sized lazily the same way; self.dim only
        # fixes the output width)
        zin = 2 * x.shape[-1] + a
        kf, bf = DenseParams(zin, self.dim, name="lin_f")()
        ks, bs = DenseParams(zin, self.dim, name="lin_s")()

        perm = g.extras.get("edge_perm_sender") if g.extras else None
        fused = (perm is not None
                 and _cgcnn_pipeline_enabled(self.dim, a))
        segment._count("cgcnn", fused)
        if not fused and _cgcnn_fused_wanted():
            note_fallback(
                "CGCNN",
                reason="no_sender_perm" if perm is None else "width_gate",
                dim=int(self.dim), edge_dim=int(a))

        if fused:
            from hydragnn_tpu.ops.cgcnn_mp import cgcnn_gated_block

            em = g.edge_mask.astype(jnp.int32)
            agg = cgcnn_gated_block(
                x, g.edge_attr if use_ea else None, em, kf, bf, ks, bs,
                g.senders, g.receivers, perm)
        else:
            # dense-backward gathers (marker-gated): 55.4k -> 68.1k
            # graphs/s vs same-session baseline on the v5e sweep (the
            # concat's scatter-add backward was the remaining XLA
            # scatter here)
            parts = [segment.gather_receiver_sorted(x, g),
                     segment.gather_sender(x, g)]
            if use_ea:
                parts.append(g.edge_attr)
            z = jnp.concatenate(parts, axis=-1)
            gate = jax.nn.sigmoid(z @ kf + bf)
            core = jax.nn.softplus(z @ ks + bs)
            # fused multi-moment scatter (sum moment only) when the
            # batch carries the collate marker
            # (HYDRAGNN_AGGR_BACKEND=fused), else masked segment_sum —
            # one dispatcher with the PNA-class archs
            agg = segment.poly_scatter_segment(
                gate * core, g, ("sum",))["sum"]
        return x + agg, pos


class CGCNNStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        return CGConv(dim=in_dim, edge_dim=self.cfg.edge_dim or 0, name=name)
