"""CGCNN stack (parity: reference hydragnn/models/CGCNNStack.py).

CGConv with additive aggregation: for z_ij = [x_i, x_j, e_ij],
out_i = x_i + sum_{j->i} sigmoid(W_f z_ij) * softplus(W_s z_ij).
CGConv preserves feature dimension, so the stack forces
hidden_dim = input_dim (reference CGCNNStack.py:30-40), and conv-type node
heads are rejected (CGCNNStack.py:66-89 — enforced in ModelConfig.from_config
via the create-time validation in models/create.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class CGConv(nn.Module):
    dim: int  # feature dim, preserved
    edge_dim: int = 0

    @nn.compact
    def __call__(self, x, pos, g, train):
        # dense-backward gathers (marker-gated): 55.4k -> 68.1k graphs/s
        # vs same-session baseline on the v5e sweep (the concat's
        # scatter-add backward was the remaining XLA scatter here)
        parts = [segment.gather_receiver_sorted(x, g),
                 segment.gather_sender(x, g)]
        if self.edge_dim and g.edge_attr is not None:
            parts.append(g.edge_attr)
        z = jnp.concatenate(parts, axis=-1)
        gate = jax.nn.sigmoid(nn.Dense(self.dim, name="lin_f")(z))
        core = jax.nn.softplus(nn.Dense(self.dim, name="lin_s")(z))
        # fused multi-moment scatter (sum moment only) when the batch
        # carries the collate marker (HYDRAGNN_AGGR_BACKEND=fused), else
        # masked segment_sum — one dispatcher with the PNA-class archs
        agg = segment.poly_scatter_segment(
            gate * core, g, ("sum",))["sum"]
        return x + agg, pos


class CGCNNStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        return CGConv(dim=in_dim, edge_dim=self.cfg.edge_dim or 0, name=name)
