"""Shared multi-headed GNN skeleton (flax.linen).

TPU-native re-design of the reference's ``Base`` (reference
hydragnn/models/Base.py:24-426): a stack of interchangeable message-passing
convolutions + masked BatchNorm feature layers, masked global mean pooling,
and N decoder heads (graph-level MLP heads behind a shared MLP trunk;
node-level MLP / per-node-MLP / conv-stack heads).

Differences by design (TPU-first):
  - operates on padded static-shape :class:`GraphBatch` with masks, so one
    compiled XLA program serves every batch;
  - batch statistics in :class:`MaskedBatchNorm` are computed over the global
    (sharded) batch under jit — cross-replica SyncBatchNorm for free;
  - the multi-head label layout is static (see graph/batch.py), so the loss
    is a plain masked mean per head, with task weights normalized to sum 1
    (parity with reference Base.loss_hpweighted, Base.py:343-360).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.layers import (
    MLP,
    MaskedBatchNorm,
    activation_module,
    loss_function,
)


def _validated_compute_dtype(arch) -> str:
    """"bfloat16" via ``mixed_precision: true`` or an explicit
    ``compute_dtype``; anything unrecognized raises instead of silently
    training in f32 while the user believes bf16 is on."""
    dt = ("bfloat16" if arch.get("mixed_precision")
          else arch.get("compute_dtype", "float32"))
    if dt not in ("float32", "bfloat16"):
        raise ValueError(
            f"compute_dtype must be 'float32' or 'bfloat16', got {dt!r}")
    return dt


@dataclasses.dataclass(frozen=True)
class GraphHeadCfg:
    num_sharedlayers: int
    dim_sharedlayers: int
    num_headlayers: int
    dim_headlayers: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class NodeHeadCfg:
    num_headlayers: int
    dim_headlayers: Tuple[int, ...]
    type: str = "mlp"  # "mlp" | "mlp_per_node" | "conv"


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Static (hashable) model hyper-parameters.

    Mirrors the argument list of the reference factory
    (hydragnn/models/create.py:71-102) as one frozen dataclass.
    """

    model_type: str
    input_dim: int
    hidden_dim: int
    output_dim: Tuple[int, ...]
    output_type: Tuple[str, ...]
    graph_head: Optional[GraphHeadCfg]
    node_head: Optional[NodeHeadCfg]
    activation: str = "relu"
    loss_fn: str = "mse"
    task_weights: Tuple[float, ...] = ()
    equivariance: bool = False
    num_conv_layers: int = 2
    num_nodes: Optional[int] = None
    edge_dim: Optional[int] = None
    dropout: float = 0.25
    freeze_conv: bool = False
    initial_bias: Optional[float] = None
    # "bfloat16" = mixed precision: f32 params/grads/loss, bf16 compute
    # (cast at the train-step boundary, hydragnn_tpu/train/trainer.py)
    compute_dtype: str = "float32"
    # --- architecture-specific knobs ---
    pna_avg_deg_log: Optional[float] = None
    pna_avg_deg_lin: Optional[float] = None
    gat_heads: int = 6
    gat_negative_slope: float = 0.05
    max_degree: Optional[int] = None
    max_neighbours: Optional[int] = None
    num_gaussians: Optional[int] = None
    num_filters: Optional[int] = None
    radius: Optional[float] = None
    envelope_exponent: Optional[int] = None
    num_before_skip: Optional[int] = None
    num_after_skip: Optional[int] = None
    num_radial: Optional[int] = None
    num_spherical: Optional[int] = None
    basis_emb_size: Optional[int] = None
    int_emb_size: Optional[int] = None
    out_emb_size: Optional[int] = None

    def __post_init__(self):
        # validate HERE so every construction path (from_config, direct
        # dataclass use, dataclasses.replace, env knobs) is covered — the
        # trainer maps anything != "bfloat16" to f32 without error, so an
        # unvalidated typo like "bf16" would silently train in f32
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "compute_dtype must be 'float32' or 'bfloat16', "
                f"got {self.compute_dtype!r}")

    @property
    def use_edge_attr(self) -> bool:
        return self.edge_dim is not None and self.edge_dim > 0

    @property
    def num_heads(self) -> int:
        return len(self.output_dim)

    @property
    def norm_task_weights(self) -> Tuple[float, ...]:
        s = sum(abs(w) for w in self.task_weights)
        return tuple(w / s for w in self.task_weights)

    @staticmethod
    def from_config(config: Dict[str, Any]) -> "ModelConfig":
        """Build from a finalized reference-schema JSON config dict
        (accepts the full config or its NeuralNetwork section)."""
        if "NeuralNetwork" in config:
            config = config["NeuralNetwork"]
        arch = config["Architecture"]
        training = config["Training"]
        heads_cfg = arch.get("output_heads", {})
        graph_head = None
        if "graph" in heads_cfg:
            g = heads_cfg["graph"]
            graph_head = GraphHeadCfg(
                num_sharedlayers=g["num_sharedlayers"],
                dim_sharedlayers=g["dim_sharedlayers"],
                num_headlayers=g["num_headlayers"],
                dim_headlayers=tuple(g["dim_headlayers"]),
            )
        node_head = None
        if "node" in heads_cfg:
            n = heads_cfg["node"]
            node_head = NodeHeadCfg(
                num_headlayers=n["num_headlayers"],
                dim_headlayers=tuple(n["dim_headlayers"]),
                type=n.get("type", "mlp"),
            )
        pna_deg = arch.get("pna_deg")
        avg_log = avg_lin = None
        if pna_deg is not None:
            hist = np.asarray(pna_deg, dtype=np.float64)
            bins = np.arange(len(hist), dtype=np.float64)
            total = max(hist.sum(), 1.0)
            avg_log = float((np.log(bins + 1) * hist).sum() / total)
            avg_lin = float((bins * hist).sum() / total)
        hidden_dim = arch["hidden_dim"]
        if arch["model_type"] == "CGCNN":
            # CGConv preserves feature dims (reference CGCNNStack.py:30-40)
            hidden_dim = arch["input_dim"]
        return ModelConfig(
            model_type=arch["model_type"],
            input_dim=arch["input_dim"],
            hidden_dim=hidden_dim,
            output_dim=tuple(arch["output_dim"]),
            output_type=tuple(arch["output_type"]),
            graph_head=graph_head,
            node_head=node_head,
            activation=arch.get("activation_function", "relu"),
            loss_fn=training.get("loss_function_type", "mse"),
            task_weights=tuple(float(w) for w in arch["task_weights"]),
            equivariance=bool(arch.get("equivariance", False)),
            num_conv_layers=arch["num_conv_layers"],
            num_nodes=arch.get("num_nodes"),
            edge_dim=arch.get("edge_dim"),
            freeze_conv=bool(arch.get("freeze_conv_layers", False)),
            initial_bias=arch.get("initial_bias"),
            compute_dtype=_validated_compute_dtype(arch),
            pna_avg_deg_log=avg_log,
            pna_avg_deg_lin=avg_lin,
            max_degree=arch.get("max_neighbours"),
            max_neighbours=arch.get("max_neighbours"),
            num_gaussians=arch.get("num_gaussians"),
            num_filters=arch.get("num_filters"),
            radius=arch.get("radius"),
            envelope_exponent=arch.get("envelope_exponent"),
            num_before_skip=arch.get("num_before_skip"),
            num_after_skip=arch.get("num_after_skip"),
            num_radial=arch.get("num_radial"),
            num_spherical=arch.get("num_spherical"),
            basis_emb_size=arch.get("basis_emb_size"),
            int_emb_size=arch.get("int_emb_size"),
            out_emb_size=arch.get("out_emb_size"),
            # extension over the reference schema (its Base hardcodes
            # dropout=0.25 with a FIXME about config exposure,
            # reference Base.py:40): Architecture.dropout overrides the
            # GAT attention-dropout rate.  Setting 0.0 is the measured
            # recipe for the wide-GAT eval divergence — docs/PERF.md
            # round 5, test MAE 0.40 vs 2.46 at the flagship protocol.
            dropout=float(arch.get("dropout", 0.25)),
        )


class MLPNode(nn.Module):
    """Node-level MLP head: one shared MLP, or one MLP per node index
    (reference hydragnn/models/Base.py:366-426)."""

    hidden_dims: Tuple[int, ...]
    output_dim: int
    activation: str
    per_node: bool = False
    num_nodes: Optional[int] = None

    @nn.compact
    def __call__(self, x, node_gid):
        if not self.per_node:
            return MLP(
                tuple(self.hidden_dims) + (self.output_dim,),
                activation=self.activation,
            )(x)
        assert self.num_nodes is not None, "num_nodes required for mlp_per_node"
        act = activation_module(self.activation)
        # Per-node parameter banks: [num_nodes, in, out] selected by the
        # node's index within its (fixed-size) graph.
        n = x.shape[0]
        local_idx = jnp.arange(n, dtype=jnp.int32) - node_gid * self.num_nodes
        local_idx = jnp.clip(local_idx, 0, self.num_nodes - 1)
        dims = (x.shape[-1],) + tuple(self.hidden_dims) + (self.output_dim,)
        h = x
        for i in range(len(dims) - 1):
            w = self.param(
                f"w_{i}",
                nn.initializers.lecun_normal(),
                (self.num_nodes, dims[i], dims[i + 1]),
            )
            b = self.param(
                f"b_{i}", nn.initializers.zeros, (self.num_nodes, dims[i + 1])
            )
            h = jnp.einsum("ni,nio->no", h, jnp.take(w, local_idx, axis=0))
            h = h + jnp.take(b, local_idx, axis=0)
            if i < len(dims) - 2:
                h = act(h)
        return h


class Base(nn.Module):
    """Shared skeleton; subclasses provide ``make_conv`` (+ dim overrides)."""

    cfg: ModelConfig

    # Subclasses flip this off when the reference uses Identity feature
    # layers instead of BatchNorm (SchNet, EGNN; SCFStack.py:63, EGCLStack.py:41).
    has_batchnorm: bool = True

    def make_conv(self, name: str, in_dim: int, out_dim: int, last_layer: bool):
        raise NotImplementedError

    def encoder_dims(self) -> List[Tuple[int, int, int]]:
        """Per-encoder-layer (in_dim, out_dim, bn_features)."""
        c = self.cfg
        dims = [(c.input_dim, c.hidden_dim, c.hidden_dim)]
        for _ in range(c.num_conv_layers - 1):
            dims.append((c.hidden_dim, c.hidden_dim, c.hidden_dim))
        return dims

    def node_conv_dims(self, head_dim: int) -> Tuple[List[Tuple[int, int, int]], Tuple[int, int, int]]:
        """Hidden conv dims + output conv dims for conv-type node heads
        (reference Base._init_node_conv, Base.py:141-199)."""
        c = self.cfg
        hdn = list(c.node_head.dim_headlayers)
        hidden = [(c.hidden_dim, hdn[0], hdn[0])]
        for i in range(c.node_head.num_headlayers - 1):
            hidden.append((hdn[i], hdn[i + 1], hdn[i + 1]))
        out = (hdn[-1], head_dim, head_dim)
        return hidden, out

    def encoder_out_dim(self) -> int:
        return self.cfg.hidden_dim

    @nn.compact
    def __call__(self, g: GraphBatch, train: bool = True):
        c = self.cfg
        act = activation_module(c.activation)
        num_graphs = g.num_graphs

        # --- encoder: conv stack + feature layers ---
        x, pos = g.x, g.pos
        enc_dims = self.encoder_dims()
        n_layers = len(enc_dims)
        for i, (din, dout, bnf) in enumerate(enc_dims):
            last = i == n_layers - 1
            conv = self.make_conv(f"encoder_conv_{i}", din, dout, last)
            x, pos = conv(x, pos, g, train)
            if self.has_batchnorm:
                x = MaskedBatchNorm(bnf, name=f"encoder_bn_{i}")(
                    x, g.node_mask, use_running_average=not train
                )
            x = act(x)

        # --- decoder: masked mean pool + heads ---
        x_graph = segment.masked_mean_pool(
            x, g.node_gid, num_graphs, g.node_mask,
            sorted_hint=bool(g.extras and "edge_perm_sender" in g.extras))

        graph_shared = None
        if c.graph_head is not None:
            gh = c.graph_head
            graph_shared = MLP(
                (gh.dim_sharedlayers,) * gh.num_sharedlayers,
                activation=c.activation,
                final_activation=True,
                name="graph_shared",
            )

        # Conv-type node heads share their hidden conv stack across heads
        # (reference appends the same modules to every head; Base.py:258-266).
        node_conv_hidden = None
        if (
            c.node_head is not None
            and c.node_head.type == "conv"
            and "node" in c.output_type
        ):
            hidden_dims, _ = self.node_conv_dims(0)
            node_conv_hidden = [
                (
                    self.make_conv(f"node_conv_hidden_{j}", din, dout, False),
                    MaskedBatchNorm(bnf, name=f"node_conv_hidden_bn_{j}"),
                )
                for j, (din, dout, bnf) in enumerate(hidden_dims)
            ]

        outputs = []
        for ihead, (head_dim, head_type) in enumerate(zip(c.output_dim, c.output_type)):
            if head_type == "graph":
                gh = c.graph_head
                z = graph_shared(x_graph)
                z = MLP(
                    tuple(gh.dim_headlayers) + (head_dim,),
                    activation=c.activation,
                    name=f"head_{ihead}",
                )(z)
                outputs.append(z)
            elif head_type == "node":
                nh = c.node_head
                if nh.type in ("mlp", "mlp_per_node"):
                    z = MLPNode(
                        hidden_dims=nh.dim_headlayers,
                        output_dim=head_dim,
                        activation=c.activation,
                        per_node=nh.type == "mlp_per_node",
                        num_nodes=c.num_nodes,
                        name=f"head_{ihead}",
                    )(x, g.node_gid)
                elif nh.type == "conv":
                    _, (odin, odout, obnf) = self.node_conv_dims(head_dim)
                    z, zpos = x, pos
                    for conv, bn in node_conv_hidden:
                        z, zpos = conv(z, zpos, g, train)
                        z = act(bn(z, g.node_mask, use_running_average=not train))
                    out_conv = self.make_conv(f"head_{ihead}_out_conv", odin, odout, True)
                    z, zpos = out_conv(z, zpos, g, train)
                    z = act(
                        MaskedBatchNorm(obnf, name=f"head_{ihead}_out_bn")(
                            z, g.node_mask, use_running_average=not train
                        )
                    )
                else:
                    raise ValueError(f"Unknown node head type: {nh.type}")
                outputs.append(z)
            else:
                raise ValueError(f"Unknown head type: {head_type}")
        return tuple(outputs)


def multihead_loss_nll(
    cfg: ModelConfig,
    outputs: Sequence[jax.Array],
    g: GraphBatch,
) -> Tuple[jax.Array, List[jax.Array]]:
    """Gaussian NLL multi-task loss for UQ heads (parity with the reference's
    disabled stub Base.loss_nll, Base.py:322-341: each head emits [mean,
    log_sigma] pairs; loss = 0.5*log(2*pi*sigma^2) + (x-mu)^2/(2*sigma^2))."""
    weights = cfg.norm_task_weights
    total = 0.0
    per_head = []
    for ihead, (out, head_type) in enumerate(zip(outputs, cfg.output_type)):
        label = g.labels[ihead]
        mask = g.graph_mask if head_type == "graph" else g.node_mask
        dim = label.shape[-1]
        mean, log_sigma = out[..., :dim], out[..., dim : 2 * dim]
        # clamp log_sigma so padded rows cannot produce inf/NaN through exp
        log_sigma = jnp.clip(log_sigma, -15.0, 15.0)
        var = jnp.exp(2.0 * log_sigma)
        nll = 0.5 * jnp.log(2.0 * jnp.pi * var) + (label - mean) ** 2 / (
            2.0 * var)
        m = mask.reshape(mask.shape + (1,) * (nll.ndim - mask.ndim))
        nll = jnp.where(m > 0, nll, 0.0)
        # shard-aware like loss_function's masked mean (graph/partition.py)
        from hydragnn_tpu.graph.partition import halo_psum

        head_loss = halo_psum(jnp.sum(nll)) / jnp.maximum(
            halo_psum(jnp.sum(m)) * dim, 1.0)
        per_head.append(head_loss)
        total = total + weights[ihead] * head_loss
    return total, per_head


def set_initial_bias(params, cfg: ModelConfig):
    """Set the output-layer bias of every graph head to ``cfg.initial_bias``
    (parity: reference Base.initial_bias for UQ, Base.py:134-139)."""
    import flax

    if cfg.initial_bias is None:
        return params
    flat = flax.traverse_util.flatten_dict(params)
    # last dense index per head module
    last_dense: Dict[str, int] = {}
    for path in flat:
        if len(path) >= 2 and str(path[0]).startswith("head_") and str(
                path[1]).startswith("dense_"):
            idx = int(str(path[1]).split("_")[1])
            last_dense[path[0]] = max(last_dense.get(path[0], -1), idx)
    for path in list(flat):
        if (len(path) >= 3 and str(path[0]).startswith("head_")
                and str(path[1]) == f"dense_{last_dense.get(path[0], -1)}"
                and path[2] == "bias"):
            flat[path] = jnp.full_like(flat[path], cfg.initial_bias)
    return flax.traverse_util.unflatten_dict(flat)


def encoder_freeze_mask(updates, frozen: bool):
    """Zero updates for encoder conv/bn params (parity: reference
    Base.freeze_conv, Base.py:128-132 — frozen conv layers receive no
    gradient updates and no weight decay)."""
    if not frozen:
        return updates
    import jax.tree_util as jtu

    def zero_enc(path, u):
        top = str(getattr(path[0], "key", path[0]))
        if top.startswith("encoder_"):
            return jnp.zeros_like(u)
        return u

    return jtu.tree_map_with_path(zero_enc, updates)


def print_model(model: "Base", params, verbosity: int = 0) -> int:
    """Parameter-count summary (reference utils/model.py:157-165)."""
    import numpy as np

    from hydragnn_tpu.utils.print_utils import print_distributed

    leaves = jax.tree.leaves(params)
    total = int(sum(np.prod(l.shape) for l in leaves))
    print_distributed(
        verbosity,
        f"{type(model).__name__}: {len(leaves)} parameter arrays, "
        f"{total} parameters")
    return total


def multihead_loss(
    cfg: ModelConfig,
    outputs: Sequence[jax.Array],
    g: GraphBatch,
) -> Tuple[jax.Array, List[jax.Array]]:
    """Weighted multi-task loss over padded batches.

    Parity with reference Base.loss_hpweighted (Base.py:343-360): per-head
    loss via the configured loss function, total = sum of per-head losses
    times normalized task weights.  ``loss_function_type: "gaussian_nll"``
    selects the UQ loss (heads emit [mean, log_sigma] at 2x the label dim;
    pair with ``Architecture.initial_bias`` — parity-plus over the
    reference's disabled stub, Base.py:322-341).
    """
    if cfg.loss_fn == "gaussian_nll":
        return multihead_loss_nll(cfg, outputs, g)
    loss_fn = loss_function(cfg.loss_fn)
    weights = cfg.norm_task_weights
    total = 0.0
    per_head = []
    for ihead, (out, head_type) in enumerate(zip(outputs, cfg.output_type)):
        label = g.labels[ihead]
        mask = g.graph_mask if head_type == "graph" else g.node_mask
        head_loss = loss_fn(out, label, mask)
        per_head.append(head_loss)
        total = total + weights[ihead] * head_loss
    return total, per_head
