"""SchNet stack (parity: reference hydragnn/models/SCFStack.py).

Continuous-filter convolution: Gaussian-smeared edge distances feed a filter
MLP (shifted-softplus) with a cosine cutoff envelope; messages are
filter-modulated linear node features, sum-aggregated.  An optional
E(3)-equivariant position-update branch (coord MLP on the filter values,
mean-aggregated displacement) runs on all but the last layer
(reference SCFStack.py:143-223).

Edge distances are recomputed from current positions each layer — the edge
*topology* is fixed host-side (static shapes), which matches the reference's
RadiusInteractionGraph behavior as long as positions move within the cutoff.
No BatchNorm feature layers (reference uses Identity; SCFStack.py:63).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base
from hydragnn_tpu.models.layers import shifted_softplus


def gaussian_smearing(dist, radius, num_gaussians):
    """PyG GaussianSmearing(0, radius, num_gaussians) parity."""
    offsets = jnp.linspace(0.0, radius, num_gaussians)
    coeff = -0.5 / (offsets[1] - offsets[0]) ** 2
    return jnp.exp(coeff * (dist[:, None] - offsets[None, :]) ** 2)


class SCFConv(nn.Module):
    out_dim: int
    num_gaussians: int
    num_filters: int
    cutoff: float
    equivariant: bool
    use_edge_attr: bool

    @nn.compact
    def __call__(self, x, pos, g, train):
        n = x.shape[0]
        src, dst = g.senders, g.receivers

        if self.use_edge_attr and g.edge_attr is not None:
            w = jnp.linalg.norm(g.edge_attr, axis=-1)
        else:
            d = pos[src] - pos[dst]
            # eps inside the sqrt keeps the gradient finite on padding
            # self-edges (distance exactly 0) for jax.grad wrt positions
            w = jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-12)
        rbf = gaussian_smearing(w, self.cutoff, self.num_gaussians)

        # cosine envelope, hard-zeroed beyond the cutoff (edge topology is
        # static, so drifted positions must not re-enter with full weight)
        cut = 0.5 * (jnp.cos(w * jnp.pi / self.cutoff) + 1.0)
        cut = jnp.where(w <= self.cutoff, cut, 0.0)
        filt = nn.Dense(self.num_filters, name="filter_0")(rbf)
        filt = shifted_softplus(filt)
        filt = nn.Dense(self.num_filters, name="filter_1")(filt)
        filt = filt * cut[:, None] * g.edge_mask[:, None]

        # xavier-uniform init on lin1/lin2, zero bias — parity with reference
        # CFConv.reset_parameters (SCFStack.py:185-188)
        h = nn.Dense(self.num_filters, use_bias=False,
                     kernel_init=nn.initializers.xavier_uniform(),
                     name="lin1")(x)

        if self.equivariant:
            diff = pos[src] - pos[dst]
            radial = jnp.sum(diff * diff, axis=-1, keepdims=True)
            diff = diff / (jnp.sqrt(radial + 1e-12) + 1.0)
            cmlp = nn.Dense(self.num_filters, name="coord_mlp_0")(filt)
            cmlp = nn.relu(cmlp)
            cmlp = nn.Dense(
                1,
                use_bias=False,
                # torch xavier_uniform_(gain=g) has std g*sqrt(2/fan_avg*... )
                # => variance_scaling needs scale = g^2 (reference
                # SCFStack.py:162-163, gain 0.001)
                kernel_init=nn.initializers.variance_scaling(
                    1e-6, "fan_avg", "uniform"
                ),
                name="coord_mlp_1",
            )(cmlp)
            trans = jnp.clip(diff * cmlp, -100.0, 100.0)
            # aggregated at the edge source, matching reference CFConv
            # coord_model (SCFStack.py:173-181)
            pos = pos + segment.segment_mean(trans, src, n, g.edge_mask)

        # lowers to the fused gather-multiply-aggregate Pallas kernel under
        # HYDRAGNN_AGGR_BACKEND=fused (ops/fused_mp.py; measured numbers in
        # docs/PERF.md)
        agg = segment.gather_mul_segment(h, filt, g)
        out = nn.Dense(self.out_dim,
                       kernel_init=nn.initializers.xavier_uniform(),
                       name="lin2")(agg)
        return out, pos


class SCFStack(Base):
    has_batchnorm: bool = False

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        assert c.num_gaussians is not None and c.num_filters is not None
        assert c.radius is not None, "SchNet requires radius input."
        return SCFConv(
            out_dim,
            num_gaussians=c.num_gaussians,
            num_filters=c.num_filters,
            cutoff=c.radius,
            equivariant=c.equivariance and not last_layer,
            use_edge_attr=c.use_edge_attr,
            name=name,
        )
