"""SchNet stack (parity: reference hydragnn/models/SCFStack.py).

Continuous-filter convolution: Gaussian-smeared edge distances feed a filter
MLP (shifted-softplus) with a cosine cutoff envelope; messages are
filter-modulated linear node features, sum-aggregated.  An optional
E(3)-equivariant position-update branch (coord MLP on the filter values,
mean-aggregated displacement) runs on all but the last layer
(reference SCFStack.py:143-223).

Edge distances are recomputed from current positions each layer — the edge
*topology* is fixed host-side (static shapes), which matches the reference's
RadiusInteractionGraph behavior as long as positions move within the cutoff.
No BatchNorm feature layers (reference uses Identity; SCFStack.py:63).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base
from hydragnn_tpu.models.layers import (
    DenseParams, edge_geometry, shifted_softplus)

# historical import location (DenseParams now lives in models/layers.py)
_DenseParams = DenseParams


def gaussian_smearing(dist, radius, num_gaussians):
    """PyG GaussianSmearing(0, radius, num_gaussians) parity."""
    offsets = jnp.linspace(0.0, radius, num_gaussians)
    coeff = -0.5 / (offsets[1] - offsets[0]) ** 2
    return jnp.exp(coeff * (dist[:, None] - offsets[None, :]) ** 2)


def _scf_pipeline_enabled(num_filters: int, num_gaussians: int) -> bool:
    """Fused CFConv edge pipeline gate (ops/scf_mp.py): structural limits
    (basis fits the padded lane count, width fits VMEM) plus a width
    floor — the in-kernel filter MLP re-evaluates E*F^2 in both backward
    passes, which only pays off where the composed path is stream-bound
    (measured BOTH sides of the crossover on the v5e: h64 f32 7.62 ->
    8.19 ms = pipeline loses; h512/h1024 bf16 +27% = pipeline wins —
    docs/PERF.md round 4).  Env override HYDRAGNN_SCF_FUSED=1/0 forces
    it either way.

    Numerics note (bf16 models): the pipeline evaluates the filter MLP
    and its backward matmuls — including the dW0/dW1 weight grads and
    drbf, which feed distance/position grads — with bf16 operands (f32
    accumulation), whereas the composed path's filter chain runs in f32
    (f32 params x f32 rbf).  Crossing the F >= 256 default therefore
    changes filter numerics beyond the stream dtype; drift is pinned to
    <4% of grad scale by tests/test_scf_fused.py::
    test_bf16_gradients_within_tolerance.  A/B against the composed path
    with HYDRAGNN_SCF_FUSED=0 if exact f32 filters are needed."""
    from hydragnn_tpu.ops.scf_mp import SCF_F_LIMIT

    if num_gaussians > 127 or num_filters > SCF_F_LIMIT:
        return False
    v = os.environ.get("HYDRAGNN_SCF_FUSED")
    if v is not None:
        return v.strip().lower() not in ("0", "false", "off", "no", "")
    return num_filters >= 256


class SCFConv(nn.Module):
    out_dim: int
    num_gaussians: int
    num_filters: int
    cutoff: float
    equivariant: bool
    use_edge_attr: bool

    @nn.compact
    def __call__(self, x, pos, g, train):
        n = x.shape[0]
        src, dst = g.senders, g.receivers

        if self.use_edge_attr and g.edge_attr is not None:
            w = jnp.linalg.norm(g.edge_attr, axis=-1)
        else:
            d = pos[src] - pos[dst]
            # eps inside the sqrt keeps the gradient finite on padding
            # self-edges (distance exactly 0) for jax.grad wrt positions
            w = jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-12)
        rbf = gaussian_smearing(w, self.cutoff, self.num_gaussians)

        # cosine envelope, hard-zeroed beyond the cutoff (edge topology is
        # static, so drifted positions must not re-enter with full weight)
        cut = 0.5 * (jnp.cos(w * jnp.pi / self.cutoff) + 1.0)
        cut = jnp.where(w <= self.cutoff, cut, 0.0)

        # filter params are declared matmul-free so the fused edge
        # pipeline below can consume them raw; the composed path applies
        # them exactly as the nn.Dense layers they replace (identical
        # names/inits — checkpoints are path-independent)
        k0, b0 = DenseParams(self.num_gaussians, self.num_filters,
                             name="filter_0")()
        k1, b1 = DenseParams(self.num_filters, self.num_filters,
                             name="filter_1")()
        perm = g.extras.get("edge_perm_sender") if g.extras else None
        fused_pipeline = (
            perm is not None and not self.equivariant
            and _scf_pipeline_enabled(self.num_filters, self.num_gaussians))

        filt = None
        if not fused_pipeline:
            filt = shifted_softplus(rbf @ k0 + b0) @ k1 + b1
            filt = filt * cut[:, None] * g.edge_mask[:, None]

        # xavier-uniform init on lin1/lin2, zero bias — parity with reference
        # CFConv.reset_parameters (SCFStack.py:185-188)
        h = nn.Dense(self.num_filters, use_bias=False,
                     kernel_init=nn.initializers.xavier_uniform(),
                     name="lin1")(x)

        if self.equivariant:
            diff, _ = edge_geometry(pos, src, dst)
            cmlp = nn.Dense(self.num_filters, name="coord_mlp_0")(filt)
            cmlp = nn.relu(cmlp)
            cmlp = nn.Dense(
                1,
                use_bias=False,
                # torch xavier_uniform_(gain=g) has std g*sqrt(2/fan_avg*... )
                # => variance_scaling needs scale = g^2 (reference
                # SCFStack.py:162-163, gain 0.001)
                kernel_init=nn.initializers.variance_scaling(
                    1e-6, "fan_avg", "uniform"
                ),
                name="coord_mlp_1",
            )(cmlp)
            trans = jnp.clip(diff * cmlp, -100.0, 100.0)
            # aggregated at the edge source, matching reference CFConv
            # coord_model (SCFStack.py:173-181)
            pos = pos + segment.segment_mean(trans, src, n, g.edge_mask)

        if fused_pipeline:
            # whole-edge-pipeline Pallas kernel (ops/scf_mp.py): filter MLP
            # + gather + multiply + segment-sum with no [E, F] HBM streams
            from hydragnn_tpu.ops.scf_mp import scf_edge_pipeline

            cm = cut * g.edge_mask
            # em: schedule-skip validity (kernel never visits masked-edge
            # blocks — ~half the edge slots at flagship padding ratios)
            em = g.edge_mask.astype(jnp.int32)
            agg = scf_edge_pipeline(h, rbf, cm, em, k0, b0, k1, b1,
                                    g.senders, g.receivers, perm)
        else:
            # lowers to the fused gather-multiply-aggregate Pallas kernel
            # under HYDRAGNN_AGGR_BACKEND=fused (ops/fused_mp.py; measured
            # numbers in docs/PERF.md)
            agg = segment.gather_mul_segment(h, filt, g)
        out = nn.Dense(self.out_dim,
                       kernel_init=nn.initializers.xavier_uniform(),
                       name="lin2")(agg)
        return out, pos


class SCFStack(Base):
    has_batchnorm: bool = False

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        assert c.num_gaussians is not None and c.num_filters is not None
        assert c.radius is not None, "SchNet requires radius input."
        return SCFConv(
            out_dim,
            num_gaussians=c.num_gaussians,
            num_filters=c.num_filters,
            cutoff=c.radius,
            equivariant=c.equivariance and not last_layer,
            use_edge_attr=c.use_edge_attr,
            name=name,
        )
