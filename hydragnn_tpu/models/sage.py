"""GraphSAGE stack (parity: reference hydragnn/models/SAGEStack.py).

SAGEConv semantics: out_i = W_self x_i + W_neigh mean_{j->i}(x_j).
Expressed TPU-natively as a gather + masked segment mean + two dense layers
(both lower to MXU matmuls under XLA).
"""

from __future__ import annotations

import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class SAGEConv(nn.Module):
    out_dim: int

    @nn.compact
    def __call__(self, x, pos, g, train):
        # masked neighbor mean: sum AND count from ONE fused multi-moment
        # pass under HYDRAGNN_AGGR_BACKEND=fused (ops/poly_mp.py) — the
        # separate degree scatter folds into the aggregation kernel;
        # _mean_divide = THE empty-segment convention (max(cnt, 1))
        res = segment.poly_gather_segment(x, g, ("sum", "cnt"))
        neigh = segment._mean_divide(res["sum"], res["cnt"])
        out = nn.Dense(self.out_dim, name="lin_self")(x) + nn.Dense(
            self.out_dim, use_bias=False, name="lin_neigh"
        )(neigh)
        return out, pos


class SAGEStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        return SAGEConv(out_dim, name=name)
