"""EGNN stack (parity: reference hydragnn/models/EGCLStack.py).

E(n)-equivariant graph convolution layer: edge MLP on
[h_src, h_dst, ||dx||^2, edge_attr]; equivariant coordinate update from a
scalar gate on the edge features (tanh-bounded, clamped, mean-aggregated);
node MLP on [h, sum of incident messages].  The coordinate branch runs on
all but the last layer (reference EGCLStack.py:36-46); aggregation happens
at the edge *source* as in the reference (EGCLStack.py:194,210).
No BatchNorm feature layers (reference uses Identity; EGCLStack.py:41).

The whole interaction block (gather -> edge MLP -> coord gate -> both
scatters) dispatches to ONE Pallas pass (ops/egcl_mp.py) when the batch
carries the sender-sort marker and the widths fit the kernel's tile
limits; the composed XLA path below is the bit-tested fallback.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base
from hydragnn_tpu.models.layers import DenseParams, edge_geometry
from hydragnn_tpu.ops.fused_block import note_fallback


def _egcl_pipeline_enabled(features: int, hidden: int, geo_dim: int) -> bool:
    """Fused EGCL interaction-block gate (ops/egcl_mp.py): structural
    tile limits only — unlike SchNet's cfconv there is NO width floor,
    because the win here is eliminating the [E, *] streams (concat, two
    MLP activations, gate, translations) plus BOTH scatter passes, which
    dominates even at EGNN's mainline hidden width 64 where the step is
    gather/scatter-bound rather than matmul-bound.  Env override
    HYDRAGNN_EGCL_FUSED=1/0 forces it either way (subject to the
    structural limits — the kernel cannot run beyond them)."""
    from hydragnn_tpu.ops.egcl_mp import (
        EGCL_F_LIMIT, EGCL_GEO_LIMIT, EGCL_H_LIMIT)

    if features > EGCL_F_LIMIT or hidden > EGCL_H_LIMIT \
            or geo_dim > EGCL_GEO_LIMIT:
        return False
    v = os.environ.get("HYDRAGNN_EGCL_FUSED")
    if v is not None:
        return v.strip().lower() not in ("0", "false", "off", "no", "")
    return True


def _egcl_fused_wanted() -> bool:
    """Did the operator ask for the fused data layout?  Either knob
    counts: the global aggregation backend or the EGCL-specific force."""
    if os.environ.get("HYDRAGNN_AGGR_BACKEND", "").strip().lower() \
            == "fused":
        return True
    v = os.environ.get("HYDRAGNN_EGCL_FUSED")
    return v is not None and v.strip().lower() not in (
        "0", "false", "off", "no", "")


class EGCL(nn.Module):
    out_dim: int
    hidden_dim: int
    edge_dim: int
    equivariant: bool

    @nn.compact
    def __call__(self, x, pos, g, train):
        n = x.shape[0]
        src, dst = g.senders, g.receivers

        # shared per-edge geometry, computed ONCE (the coord branch used
        # to recompute diff/radial on the fallback route)
        diff, radial = edge_geometry(pos, src, dst)
        use_ea = bool(self.edge_dim) and g.edge_attr is not None
        geo_dim = 4 + (g.edge_attr.shape[-1] if use_ea else 0)

        # edge/coord MLP params are declared matmul-free so the fused
        # block can consume them raw; the composed path applies them
        # exactly as the nn.Dense layers they replace (identical
        # names/inits — checkpoints are path-independent)
        in_dim = 2 * x.shape[-1] + geo_dim - 3
        k0, b0 = DenseParams(in_dim, self.hidden_dim,
                             name="edge_mlp_0")()
        k1, b1 = DenseParams(self.hidden_dim, self.hidden_dim,
                             name="edge_mlp_1")()
        kc0 = bc0 = kc1 = None
        if self.equivariant:
            kc0, bc0 = DenseParams(self.hidden_dim, self.hidden_dim,
                                   name="coord_mlp_0")()
            kc1, _ = DenseParams(
                self.hidden_dim, 1, use_bias=False,
                kernel_init=nn.initializers.variance_scaling(
                    0.001, "fan_avg", "uniform"),
                name="coord_mlp_1")()

        perm = g.extras.get("edge_perm_sender") if g.extras else None
        fused = (perm is not None
                 and _egcl_pipeline_enabled(x.shape[-1], self.hidden_dim,
                                            geo_dim))
        segment._count("egcl", fused)
        if not fused and _egcl_fused_wanted():
            # models hold no MetricsLogger — record the reason here (trace
            # time, deduped) for the trainer to surface as a unified
            # `fused_fallback` health event after the first epoch
            note_fallback(
                "EGNN",
                reason="no_sender_perm" if perm is None else "width_gate",
                features=int(x.shape[-1]), hidden=int(self.hidden_dim),
                geo_dim=int(geo_dim))

        if fused:
            from hydragnn_tpu.ops.egcl_mp import egcl_block

            geo = jnp.concatenate(
                [diff, radial] + ([g.edge_attr] if use_ea else []),
                axis=-1)
            em = g.edge_mask.astype(jnp.int32)
            agg, psum = egcl_block(
                self.equivariant, x, geo, em, k0, b0, k1, b1,
                kc0, bc0, kc1, src, dst, perm)
            if self.equivariant:
                cnt = segment.segment_count(src, n, g.edge_mask)
                pos = pos + segment._mean_divide(psum[:, :3], cnt)
        else:
            # gathers whose backward rides the dense sorted scatter
            # (marker-gated; measured +9% end-to-end on the v5e sweep)
            parts = [segment.gather_sender(x, g),
                     segment.gather_receiver_sorted(x, g), radial]
            if use_ea:
                parts.append(g.edge_attr)
            m = jnp.concatenate(parts, axis=-1)
            m = nn.relu(m @ k0 + b0)
            m = nn.relu(m @ k1 + b1)
            m = m * g.edge_mask[:, None]

            if self.equivariant:
                c = nn.relu(m @ kc0 + bc0)
                c = jnp.tanh(c @ kc1)  # tanh=True in reference E_GCL
                trans = jnp.clip(diff * c, -100.0, 100.0)
                # sender-side aggregation matching the reference; the
                # fused path scatters the same translation sum in-kernel
                pos = pos + segment.segment_mean(trans, src, n,
                                                 g.edge_mask)

            agg = segment.segment_sum(m, src, n, g.edge_mask)

        h = jnp.concatenate([x, agg], axis=-1)
        h = nn.Dense(self.hidden_dim, name="node_mlp_0")(h)
        h = nn.relu(h)
        h = nn.Dense(self.out_dim, name="node_mlp_1")(h)
        return h, pos


class EGCLStack(Base):
    has_batchnorm: bool = False

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        return EGCL(
            out_dim,
            hidden_dim=c.hidden_dim,
            edge_dim=c.edge_dim or 0,
            equivariant=c.equivariance and not last_layer,
            name=name,
        )
