"""EGNN stack (parity: reference hydragnn/models/EGCLStack.py).

E(n)-equivariant graph convolution layer: edge MLP on
[h_src, h_dst, ||dx||^2, edge_attr]; equivariant coordinate update from a
scalar gate on the edge features (tanh-bounded, clamped, mean-aggregated);
node MLP on [h, sum of incident messages].  The coordinate branch runs on
all but the last layer (reference EGCLStack.py:36-46); aggregation happens
at the edge *source* as in the reference (EGCLStack.py:194,210).
No BatchNorm feature layers (reference uses Identity; EGCLStack.py:41).
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class EGCL(nn.Module):
    out_dim: int
    hidden_dim: int
    edge_dim: int
    equivariant: bool

    @nn.compact
    def __call__(self, x, pos, g, train):
        n = x.shape[0]
        src, dst = g.senders, g.receivers

        diff = pos[src] - pos[dst]
        radial = jnp.sum(diff * diff, axis=-1, keepdims=True)
        # eps inside the sqrt: padding self-edges have radial == 0 exactly,
        # where sqrt's gradient is inf — this path must stay differentiable
        # for the energy-gradient force loss (jax.grad wrt pos).
        diff = diff / (jnp.sqrt(radial + 1e-12) + 1.0)  # norm_diff=True

        # gathers whose backward rides the dense sorted scatter
        # (marker-gated; measured +9% end-to-end on the v5e sweep)
        parts = [segment.gather_sender(x, g),
                 segment.gather_receiver_sorted(x, g), radial]
        if self.edge_dim and g.edge_attr is not None:
            parts.append(g.edge_attr)
        m = jnp.concatenate(parts, axis=-1)
        m = nn.Dense(self.hidden_dim, name="edge_mlp_0")(m)
        m = nn.relu(m)
        m = nn.Dense(self.hidden_dim, name="edge_mlp_1")(m)
        m = nn.relu(m)
        m = m * g.edge_mask[:, None]

        if self.equivariant:
            c = nn.Dense(self.hidden_dim, name="coord_mlp_0")(m)
            c = nn.relu(c)
            c = nn.Dense(
                1,
                use_bias=False,
                kernel_init=nn.initializers.variance_scaling(
                    0.001, "fan_avg", "uniform"
                ),
                name="coord_mlp_1",
            )(c)
            c = jnp.tanh(c)  # tanh=True in reference E_GCL
            trans = jnp.clip(diff * c, -100.0, 100.0)
            # sender-side aggregation: the XLA masked segment ops beat
            # the sender-permuted dense kernel here (measured 43.9k vs
            # 37.5k graphs/s on the v5e sweep config — the [E] perm
            # gather outweighs the scatter win at EGNN's message width)
            pos = pos + segment.segment_mean(trans, src, n, g.edge_mask)

        agg = segment.segment_sum(m, src, n, g.edge_mask)
        h = jnp.concatenate([x, agg], axis=-1)
        h = nn.Dense(self.hidden_dim, name="node_mlp_0")(h)
        h = nn.relu(h)
        h = nn.Dense(self.out_dim, name="node_mlp_1")(h)
        return h, pos


class EGCLStack(Base):
    has_batchnorm: bool = False

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        return EGCL(
            out_dim,
            hidden_dim=c.hidden_dim,
            edge_dim=c.edge_dim or 0,
            equivariant=c.equivariance and not last_layer,
            name=name,
        )
