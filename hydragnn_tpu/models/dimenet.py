"""DimeNet++ stack (parity: reference hydragnn/models/DIMEStack.py).

Directional message passing on *edge* features with triplet (k->j->i)
interactions.  The reference builds ragged triplet indices per batch with
torch_sparse SparseTensor (DIMEStack.py:158-182); here the triplet table is
precomputed host-side by the batcher into padded static arrays
(:func:`build_triplets` / :func:`add_dimenet_extras`), and distances/angles
are recomputed on device from positions (keeping ``jax.grad`` w.r.t.
positions intact for force losses).

The Bessel radial basis and the spherical (Legendre x spherical-Bessel)
basis are evaluated in pure JAX; spherical-Bessel zeros are found host-side
with scipy at module-construction time and cached.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.models.base import Base


# ---------------------------------------------------------------------------
# host-side: triplet construction + spherical-Bessel zeros
# ---------------------------------------------------------------------------


def build_triplets(edge_index: np.ndarray, num_nodes: int):
    """Triplet table (k->j->i) from an edge list (parity with reference
    triplets(), DIMEStack.py:158-182).

    For every pair of edges (k->j) and (j->i) with k != i, emits node indices
    (idx_i, idx_j, idx_k) and the two edge ids (idx_kj, idx_ji), with idx_ji
    nondecreasing (the dense sorted-scatter in InteractionPPBlock relies on
    this; enforced by :func:`add_dimenet_extras`).

    Fully vectorized (numpy): group incoming edge ids by destination node,
    then expand each edge (j->i) against the incoming-edge group of j via
    repeat + cumsum arithmetic — no per-edge Python loop (round-2 VERDICT
    flagged the loop builder as the DimeNet input bottleneck).
    """
    src = np.asarray(edge_index[0], np.int64)
    dst = np.asarray(edge_index[1], np.int64)  # j->i: src=j, dst=i
    e = src.shape[0]
    if e == 0:
        return tuple(np.zeros((0,), np.int32) for _ in range(5))
    # incoming edge ids per node, grouped: stable argsort of dst keeps edge
    # ids increasing within each group (matches the loop builder's order)
    order = np.argsort(dst, kind="stable")
    counts = np.bincount(dst, minlength=num_nodes)
    ptr = np.zeros(num_nodes + 1, np.int64)
    np.cumsum(counts, out=ptr[1:])
    # edge eid (j->i) pairs with every incoming edge of j
    num = counts[src]  # candidates per edge
    ji = np.repeat(np.arange(e, dtype=np.int64), num)
    ends = np.cumsum(num)
    within = np.arange(int(ends[-1]), dtype=np.int64) - np.repeat(
        ends - num, num)
    kj = order[ptr[src[ji]] + within]
    keep = src[kj] != dst[ji]  # drop k == i backtracking triplets
    ji, kj = ji[keep], kj[keep]
    return tuple(
        a.astype(np.int32) for a in (dst[ji], src[ji], src[kj], kj, ji)
    )


class DnTriGate:
    """Per-dataset/loader gate for the factored-basis fused-triplet path.

    Marker PRESENCE ("dn_tri_ok") is the static gate the model reads, so it
    must be CONSISTENT across every batch of a run: DeviceStackLoader
    np.stacks consecutive batches' extras trees, and a per-batch decision
    that flips mid-epoch produces mismatched trees (the ADVICE
    marker-instability item).  Two modes:

    - static (``max_edges_per_graph`` given): the decision is made ONCE from
      the dataset-wide bound.  A graph's real edges are contiguous in
      edge-id space (collate invariant), so a graph with at most L edges
      spans at most ceil((L-1)/_NODE_BLOCK) edge blocks at worst alignment —
      no per-batch measurement at all.
    - sticky (no bound — one-shot callers): per-batch measurement, but the
      first over-span batch disables the marker for the REST OF THE RUN
      (clean whole-run fallback instead of a mid-run tree flip; batches
      already emitted keep their marker, so prefer the static mode for any
      multi-batch pipeline).
    """

    def __init__(self, max_edges_per_graph=None):
        from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK

        self.static = max_edges_per_graph is not None
        if self.static:
            L = max(int(max_edges_per_graph), 1)
            self.span_bound = -(-(L - 1) // _NODE_BLOCK)
            self.ok = self.span_bound <= 2
        else:
            self.span_bound = None
            self.ok = True

    def allow(self, measure_span) -> bool:
        """``measure_span`` is a thunk (only called when a measurement is
        actually needed — the static mode never pays it)."""
        if self.static or not self.ok:
            return self.ok
        if measure_span() > 2:
            self.ok = False  # sticky: whole-run fallback from here on
            # one-shot: surfaces as the unified `fused_fallback` health
            # event ({arch, reason}) after the first epoch's dispatch
            from hydragnn_tpu.ops.fused_block import note_fallback

            note_fallback("DimeNet", reason="edge_span")
        return self.ok


def add_dimenet_extras(batch, max_triplets: int, tri_gate=None):
    """Post-collate hook: attach padded triplet arrays to a numpy GraphBatch.

    Padded triplets point at the trailing padded node/edge and carry mask 0.
    ``tri_gate`` (a :class:`DnTriGate`) decides the fused-triplet marker
    once per dataset/loader; omitted, a transient per-batch gate preserves
    the one-shot-caller behavior.
    """
    n, e = batch.x.shape[0], batch.senders.shape[0]
    ei = np.stack([np.asarray(batch.senders), np.asarray(batch.receivers)])
    # only real edges participate
    real = np.asarray(batch.edge_mask) > 0
    ei_real = ei[:, real]
    real_ids = np.nonzero(real)[0].astype(np.int32)
    ti, tj, tk, tkj, tji = build_triplets(ei_real, n)
    t = ti.shape[0]
    if t > max_triplets:
        raise ValueError(f"batch has {t} triplets > max_triplets={max_triplets}")

    def _pad(arr, fill):
        out = np.full((max_triplets,), fill, np.int32)
        out[:t] = arr
        return out

    # the dense sorted scatter over idx_ji (InteractionPPBlock) requires a
    # nondecreasing segment id sequence — enforce the invariant where it is
    # created so a future builder change cannot silently corrupt the scatter
    # (real_ids is increasing, so the mapped ids inherit tji's order, and
    # the e-1 padding fill keeps the full padded array nondecreasing too)
    if t and not np.all(np.diff(tji) >= 0):
        raise AssertionError("build_triplets produced non-sorted idx_ji")

    extras = dict(batch.extras)
    extras["dn_idx_i"] = _pad(ti, n - 1)
    extras["dn_idx_j"] = _pad(tj, n - 1)
    extras["dn_idx_k"] = _pad(tk, n - 1)
    idx_kj = _pad(real_ids[tkj] if t else tkj, e - 1)
    extras["dn_idx_kj"] = idx_kj
    extras["dn_idx_ji"] = _pad(real_ids[tji] if t else tji, e - 1)
    # stable argsort of idx_kj: lets the triplet-side gathers
    # (x_kj[idx_kj], rbf[idx_kj]) ride the dense sorted-scatter kernel in
    # their BACKWARD (otherwise XLA scatter-adds 188k unsorted rows per
    # layer — measured as the dominant cost of the DimeNet step)
    extras["dn_perm_kj"] = np.argsort(idx_kj, kind="stable").astype(np.int32)
    mask = np.zeros((max_triplets,), np.float32)
    mask[:t] = 1.0
    extras["dn_triplet_mask"] = mask

    # fused-triplet window marker: the interaction's triplet contraction is
    # message passing in EDGE space (x_kj[idx_kj] * sbf scattered over
    # idx_ji) and can ride the W-window fused kernel when every graph's
    # edge-id span fits the window.  Encoded in the marker array's SHAPE
    # (static under jit): shape[0] == window.  Gated like collate's
    # edge_perm_sender: only under the fused backend.
    from hydragnn_tpu.ops.aggregate import aggr_backend

    # OPT-IN (HYDRAGNN_DIMENET_FUSED_TRI=1): measured SLOWER than the XLA
    # composed path on the v5e sweep config (61.9 vs 56.9 ms/step; larger
    # block variants 60.4-61.0) — the T->E schedule's output-block count
    # (E/128 blocks for only ~2.3 triplets/edge) pays more per-step
    # overhead than the fused gather+scatter saves.  Kept as a tested
    # capability for shapes with denser triplet fan-in.
    from hydragnn_tpu.utils.env import env_flag

    if aggr_backend() == "fused":
        from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK

        def measure_span() -> int:
            # max edge-block span of any graph in THIS batch (a triplet-free
            # batch trivially fits any window)
            if not t:
                return 0
            gid_of_edge = np.asarray(batch.node_gid)[
                np.asarray(batch.receivers)[real]].astype(np.int64)
            blocks = (real_ids // _NODE_BLOCK).astype(np.int64)
            ng = int(gid_of_edge.max()) + 1
            lo = np.full(ng, np.iinfo(np.int64).max)
            hi = np.full(ng, -1)
            np.minimum.at(lo, gid_of_edge, blocks)
            np.maximum.at(hi, gid_of_edge, blocks)
            occ = hi >= 0
            return int((hi[occ] - lo[occ]).max()) if occ.any() else 0

        # factored-basis triplet kernel marker (ops/dn_tri.py, default-on
        # when applicable): every graph's edge-id span fits the 5-block
        # window.  The decision comes from the DnTriGate — static per
        # dataset when the caller provides the max-edges-per-graph bound
        # (loaders do: load_data.py), so every batch of a run carries the
        # same extras tree; a span this close to the window limit means the
        # kernel is inapplicable anyway — molecular batches sit far below.
        if tri_gate is None:
            tri_gate = DnTriGate()  # transient: per-batch (one-shot callers)
        if not env_flag("HYDRAGNN_DN_TRI_OFF") and tri_gate.allow(
                measure_span):
            extras["dn_tri_ok"] = np.zeros((1,), np.float32)
        if env_flag("HYDRAGNN_DIMENET_FUSED_TRI"):
            # legacy opt-in T->E fused path (measured slower; kept as a
            # tested capability) — the user opted in, so a batch whose
            # graphs exceed the window is an error, not a fallback
            span = measure_span()
            if span > 2:
                raise ValueError(
                    f"HYDRAGNN_DIMENET_FUSED_TRI: a graph spans {span} "
                    f"edge blocks (> 2); the 5-block window cannot cover "
                    f"it — unset the knob for this dataset")
            extras["dn_tri_window"] = np.zeros((5,), np.float32)
    return batch.replace(extras=extras)


def count_triplets(edge_index: np.ndarray, num_nodes: int) -> int:
    """Number of (k->j->i, k != i) triplets for sizing the static pad."""
    src, dst = edge_index[0], edge_index[1]
    in_deg = np.bincount(dst, minlength=num_nodes)
    # per edge j->i: one triplet per incoming edge of j, minus (i->j) if present
    total = int(in_deg[src].sum())
    pair = set(zip(src.tolist(), dst.tolist()))
    reverse = sum(1 for s, d in pair if (d, s) in pair)
    return total - reverse


@functools.lru_cache(maxsize=8)
def spherical_bessel_zeros(num_spherical: int, num_radial: int) -> np.ndarray:
    """First ``num_radial`` positive zeros of j_l, l = 0..num_spherical-1."""
    from scipy.optimize import brentq
    from scipy.special import spherical_jn

    zeros = np.zeros((num_spherical, num_radial))
    # j_0 zeros are n*pi; bracket higher-l zeros between consecutive j_{l-1} zeros
    grid = np.arange(1, num_radial + num_spherical + 2) * np.pi
    prev = grid.astype(np.float64)  # zeros of j_0
    zeros[0] = prev[:num_radial]
    for l in range(1, num_spherical):
        cur = []
        for a, b in zip(prev[:-1], prev[1:]):
            cur.append(brentq(lambda x: spherical_jn(l, x), a, b))
        prev = np.asarray(cur)
        zeros[l] = prev[:num_radial]
    return zeros


@functools.lru_cache(maxsize=8)
def sbf_normalizer(num_spherical: int, num_radial: int) -> np.ndarray:
    """DimeNet normalization sqrt(2) / |j_{l+1}(z_ln)| per (l, n)."""
    from scipy.special import spherical_jn

    z = spherical_bessel_zeros(num_spherical, num_radial)
    norm = np.zeros_like(z)
    for l in range(num_spherical):
        norm[l] = math.sqrt(2.0) / np.abs(spherical_jn(l + 1, z[l]))
    return norm


# ---------------------------------------------------------------------------
# device-side basis functions
# ---------------------------------------------------------------------------


def envelope(x, exponent: int):
    """DimeNet polynomial envelope u(x) with u(1)=u'(1)=u''(1)=0."""
    p = exponent + 1
    a = -(p + 1) * (p + 2) / 2.0
    b = p * (p + 2.0)
    c = -p * (p + 1) / 2.0
    xs = jnp.maximum(x, 1e-7)
    val = 1.0 / xs + a * xs ** (p - 1) + b * xs**p + c * xs ** (p + 1)
    return jnp.where(x < 1.0, val, 0.0)


def _spherical_jl(l_max: int, x):
    """j_0..j_lmax via upward recurrence with a small-x Taylor guard."""
    xs = jnp.maximum(x, 1e-7)
    out = []
    j0 = jnp.sin(xs) / xs
    out.append(j0)
    if l_max >= 1:
        j1 = jnp.sin(xs) / xs**2 - jnp.cos(xs) / xs
        out.append(j1)
        for l in range(1, l_max):
            out.append((2 * l + 1) / xs * out[l] - out[l - 1])
    # small-x: j_l(x) ~ x^l / (2l+1)!! * (1 - x^2/(2(2l+3)) + x^4/(8(2l+3)(2l+5)))
    small = x < 0.5
    res = []
    dfact = 1.0
    for l in range(l_max + 1):
        if l > 0:
            dfact *= 2 * l + 1
        taylor = (
            x**l
            / dfact
            * (1.0 - x**2 / (2.0 * (2 * l + 3)) + x**4 / (8.0 * (2 * l + 3) * (2 * l + 5)))
        )
        res.append(jnp.where(small, taylor, out[l]))
    return res


def _legendre(l_max: int, c):
    """P_0..P_lmax(c) via the stable three-term recurrence."""
    out = [jnp.ones_like(c)]
    if l_max >= 1:
        out.append(c)
        for l in range(1, l_max):
            out.append(((2 * l + 1) * c * out[l] - l * out[l - 1]) / (l + 1))
    return out


class BesselBasis(nn.Module):
    """Radial Bessel basis with trainable frequencies (PyG BesselBasisLayer)."""

    num_radial: int
    cutoff: float
    envelope_exponent: int

    @nn.compact
    def __call__(self, dist):
        freq = self.param(
            "freq",
            lambda key: jnp.arange(1, self.num_radial + 1, dtype=jnp.float32) * jnp.pi,
        )
        d = dist[:, None] / self.cutoff
        return envelope(d, self.envelope_exponent) * jnp.sin(freq * d)


def radial_sbf(dist_norm, num_spherical: int, num_radial: int,
               envelope_exponent: int):
    """Per-EDGE radial part of the spherical basis: [E, S, R] with
    norm * j_l(z_lr * d) * envelope(d) at slot (l, r)."""
    zeros = jnp.asarray(
        spherical_bessel_zeros(num_spherical, num_radial), jnp.float32
    )  # [S, R]
    norms = jnp.asarray(sbf_normalizer(num_spherical, num_radial), jnp.float32)

    x = dist_norm[:, None, None] * zeros[None, :, :]  # [E, S, R]
    jls = _spherical_jl(num_spherical - 1, x.reshape(-1))  # list of [E*S*R]
    e = dist_norm.shape[0]
    # slot l needs only order l: slice the diagonal directly instead of
    # stacking all orders into [E, S, S, R] and einsum-selecting (the
    # round-3 code's 7x-materialized intermediate)
    rbf = jnp.stack(
        [jls[l].reshape(e, num_spherical, num_radial)[:, l, :]
         for l in range(num_spherical)],
        axis=1)  # [E, S, R]
    rbf = rbf * norms[None, :, :]
    return rbf * envelope(dist_norm[:, None, None], envelope_exponent)


def angular_cbf(angle, num_spherical: int):
    """Per-TRIPLET angular part: [T, S] real-spherical-harmonic Legendre."""
    cos_a = jnp.cos(angle)
    pl = _legendre(num_spherical - 1, cos_a)
    return jnp.stack(
        [
            math.sqrt((2 * l + 1) / (4 * math.pi)) * pl[l]
            for l in range(num_spherical)
        ],
        axis=1,
    )


def spherical_basis_factors(dist_norm, angle, num_spherical: int,
                            num_radial: int, envelope_exponent: int):
    """The spherical basis FACTORED: sbf[t] = radial[idx_kj[t]] *
    expand(cbf[t]) with radial EDGE-space [E, S*R] and cbf TRIPLET-space
    [T, S] (the fused triplet kernel lane-expands the angular columns
    over their radial slots in-VMEM — the [T, S*R] stream never
    exists)."""
    radial = radial_sbf(
        dist_norm, num_spherical, num_radial, envelope_exponent)
    radial2 = radial.reshape(dist_norm.shape[0],
                             num_spherical * num_radial)
    cbf = angular_cbf(angle, num_spherical)       # [T, S]
    return radial2, cbf


def spherical_basis(
    dist_norm, angle, idx_kj, num_spherical: int, num_radial: int,
    envelope_exponent: int, perm_kj=None
):
    """[T, num_spherical*num_radial] spherical basis per triplet.

    ``perm_kj`` (host-precomputed stable argsort of ``idx_kj``) routes the
    edge->triplet gather's backward through the dense sorted scatter.
    """
    rbf2, cbf = spherical_basis_factors(
        dist_norm, angle, num_spherical, num_radial, envelope_exponent)
    if perm_kj is not None:
        rbf_t = segment.gather_perm(rbf2, idx_kj, perm_kj)
    else:
        rbf_t = rbf2[idx_kj]
    out = rbf_t.reshape(-1, num_spherical, num_radial) * cbf[:, :, None]
    return out.reshape(-1, num_spherical * num_radial)


# ---------------------------------------------------------------------------
# network blocks (PyG DimeNet++ block structure)
# ---------------------------------------------------------------------------

_silu = jax.nn.silu


class ResidualLayer(nn.Module):
    dim: int

    @nn.compact
    def __call__(self, x):
        h = _silu(nn.Dense(self.dim, name="lin1")(x))
        h = _silu(nn.Dense(self.dim, name="lin2")(h))
        return x + h


class _ResidualParams(nn.Module):
    """Parameters of a ResidualLayer WITHOUT its matmuls (same names
    lin1/lin2 with kernel/bias, same inits) — the fused row-MLP tail
    consumes them raw while checkpoints stay path-independent."""

    dim: int

    @nn.compact
    def __call__(self):
        from hydragnn_tpu.models.layers import DenseParams as _DenseParams

        k1, b1 = _DenseParams(self.dim, self.dim, name="lin1")()
        k2, b2 = _DenseParams(self.dim, self.dim, name="lin2")()
        return (k1, b1, k2, b2)


class InteractionPPBlock(nn.Module):
    hidden: int
    int_emb_size: int
    basis_emb_size: int
    num_before_skip: int
    num_after_skip: int
    sorted_hint: bool = False  # idx_ji is nondecreasing (builder order)
    tri_window: int = 0  # >0: fused edge-space kernel window (collate-vouched)
    tri_kernel: bool = False  # fused factored-basis kernel (ops/dn_tri.py)
    tri_builder: bool = False  # builder-backed wide-dim path (ops/dn_tri.py)
    num_radial: int = 6  # static R for the kernel's lane expansion

    @nn.compact
    def __call__(self, x_edge, rbf, sbf, idx_kj, idx_ji, triplet_mask,
                 perm_kj=None, radial=None, cbf_exp=None):
        e = x_edge.shape[0]
        # 0/1 mask: exact in any dtype; keeps the [T, *] streams in the
        # compute dtype instead of promoting them back to f32
        triplet_mask = triplet_mask.astype(x_edge.dtype)
        x_ji = _silu(nn.Dense(self.hidden, name="lin_ji")(x_edge))
        x_kj = _silu(nn.Dense(self.hidden, name="lin_kj")(x_edge))

        rbf_emb = nn.Dense(self.basis_emb_size, use_bias=False, name="lin_rbf1")(rbf)
        rbf_emb = nn.Dense(self.hidden, use_bias=False, name="lin_rbf2")(rbf_emb)
        x_kj = x_kj * rbf_emb
        x_kj = _silu(nn.Dense(self.int_emb_size, use_bias=False, name="lin_down")(x_kj))

        if self.tri_kernel:
            # factored-basis fused pass (ops/dn_tri.py): the sbf-embedding
            # MLP, the x_kj gather and the ji-scatter all run in VMEM —
            # the only [T, *] HBM streams left are cbf_exp and the index
            # tables.  Matmul-free param declarations keep the tree
            # identical to the nn.Dense layers they replace (checkpoint
            # path-independence, as in models/schnet._DenseParams).
            from hydragnn_tpu.models.layers import DenseParams as _DenseParams
            from hydragnn_tpu.ops.dn_tri import dimenet_triplet_mp

            sr = radial.shape[1]
            k1, _ = _DenseParams(sr, self.basis_emb_size, use_bias=False,
                                 name="lin_sbf1")()
            k2, _ = _DenseParams(self.basis_emb_size, self.int_emb_size,
                                 use_bias=False, name="lin_sbf2")()
            x_kj = dimenet_triplet_mp(
                radial.astype(x_edge.dtype), x_kj,
                cbf_exp.astype(x_edge.dtype), k1, k2, idx_kj, idx_ji,
                triplet_mask.astype(jnp.int32), perm_kj,
                self.num_radial)

            from hydragnn_tpu.utils.env import env_flag

            if (not env_flag("HYDRAGNN_DN_ROW_MLP_OFF")
                    and self.hidden <= 128 and self.int_emb_size <= 128):
                # fused row-local tail (ops/row_mlp.py): lin_up + skip
                # structure in one Pallas pass — the ~10 narrow [E, H]
                # Dense boundary streams collapse to 3 inputs + 1 output.
                # Matmul-free param declarations mirror the nn.Dense /
                # ResidualLayer tree (checkpoint path-independence).
                from hydragnn_tpu.ops.row_mlp import dimenet_post_mlp

                wb = list(_DenseParams(self.int_emb_size, self.hidden,
                                       use_bias=False, name="lin_up")())
                for i in range(self.num_before_skip):
                    wb += list(_ResidualParams(
                        self.hidden, name=f"before_skip_{i}")())
                wb += list(_DenseParams(self.hidden, self.hidden,
                                        name="lin")())
                for i in range(self.num_after_skip):
                    wb += list(_ResidualParams(
                        self.hidden, name=f"after_skip_{i}")())
                return dimenet_post_mlp(
                    x_kj, x_ji, x_edge, self.num_before_skip,
                    self.num_after_skip, *wb)
        elif self.tri_builder:
            # builder-backed fused path where the factored-basis gate
            # rejects on dims (S*R or the embedding sizes exceed its 64-
            # lane packing but still fit one 128-lane tile): the chain
            # fuses lin_sbf1/lin_sbf2 with the gather-multiply-scatter,
            # so the [T, D] embedding never hits HBM.  Matmul-free param
            # declarations keep the tree identical to the nn.Dense
            # layers (checkpoint path-independence).
            from hydragnn_tpu.models.layers import DenseParams
            from hydragnn_tpu.ops.dn_tri import dimenet_tri_builder

            k1, _ = DenseParams(sbf.shape[-1], self.basis_emb_size,
                                use_bias=False, name="lin_sbf1")()
            k2, _ = DenseParams(self.basis_emb_size, self.int_emb_size,
                                use_bias=False, name="lin_sbf2")()
            x_kj = dimenet_tri_builder(
                x_kj, sbf, triplet_mask.astype(jnp.int32), k1, k2,
                idx_kj, idx_ji, perm_kj)
        elif self.tri_window:
            sbf_emb = nn.Dense(self.basis_emb_size, use_bias=False, name="lin_sbf1")(sbf)
            sbf_emb = nn.Dense(self.int_emb_size, use_bias=False, name="lin_sbf2")(sbf_emb)
            # the triplet contraction IS message passing in EDGE space:
            # out[e'] = sum_{t: ji(t)=e'} x_kj[kj(t)] * sbf_emb[t] — one
            # fused W-window pass (fwd AND its dx backward via perm_kj)
            # instead of gather + [T, D] materialization + sorted scatter
            from hydragnn_tpu.ops.fused_mp import gather_mul_segment_sum

            x_kj = gather_mul_segment_sum(
                x_kj, sbf_emb * triplet_mask[:, None], idx_kj, idx_ji,
                perm_kj, self.tri_window)
        else:
            sbf_emb = nn.Dense(self.basis_emb_size, use_bias=False, name="lin_sbf1")(sbf)
            sbf_emb = nn.Dense(self.int_emb_size, use_bias=False, name="lin_sbf2")(sbf_emb)
            # NOTE: this gather deliberately does NOT use gather_perm — its
            # backward (scatter-add over idx_kj) fuses into the surrounding
            # elementwise cotangent under XLA, and routing it through the
            # dense sorted scatter (which needs an extra g[perm] gather
            # first) was measured 12 ms/step SLOWER on the v5e sweep
            # config.  The rbf->triplet gather in spherical_basis keeps the
            # perm: its backward only runs under pos-grad (force training),
            # where the dense path halves the cost (tools/profile_dimenet*.py).
            msg = x_kj[idx_kj] * sbf_emb * triplet_mask[:, None]
            # build_triplets emits idx_ji in nondecreasing order (outer
            # loop over edge ids) — the dense-schedule sorted scatter
            # applies; passing the mask also schedule-skips padded-triplet
            # blocks (add_dimenet_extras parks them zero-valued at the
            # tail)
            x_kj = segment.sorted_segment_sum(
                msg, idx_ji, e, triplet_mask, sorted_hint=self.sorted_hint)
        x_kj = _silu(nn.Dense(self.hidden, use_bias=False, name="lin_up")(x_kj))

        h = x_ji + x_kj
        for i in range(self.num_before_skip):
            h = ResidualLayer(self.hidden, name=f"before_skip_{i}")(h)
        h = _silu(nn.Dense(self.hidden, name="lin")(h)) + x_edge
        for i in range(self.num_after_skip):
            h = ResidualLayer(self.hidden, name=f"after_skip_{i}")(h)
        return h


class OutputPPBlock(nn.Module):
    hidden: int
    out_emb_size: int
    out_dim: int
    num_layers: int = 1

    sorted_hint: bool = False  # receivers are nondecreasing (collate)

    @nn.compact
    def __call__(self, x_edge, rbf, receivers, num_nodes, edge_mask):
        g = nn.Dense(self.hidden, use_bias=False, name="lin_rbf")(rbf)
        x = g * x_edge
        x = segment.sorted_segment_sum(
            x, receivers, num_nodes, edge_mask, sorted_hint=self.sorted_hint)
        x = nn.Dense(self.out_emb_size, use_bias=False, name="lin_up")(x)
        for i in range(self.num_layers):
            x = _silu(nn.Dense(self.out_emb_size, name=f"lin_{i}")(x))
        return nn.Dense(self.out_dim, use_bias=False, name="lin_out")(x)


class DimeNetConv(nn.Module):
    """One DIMEStack 'conv': lin -> embed -> interaction -> output
    (reference get_conv, DIMEStack.py:79-116)."""

    in_dim: int
    out_dim: int
    num_radial: int
    num_spherical: int
    basis_emb_size: int
    int_emb_size: int
    out_emb_size: int
    num_before_skip: int
    num_after_skip: int
    envelope_exponent: int
    cutoff: float

    @nn.compact
    def __call__(self, x, pos, g: GraphBatch, train):
        hidden = self.out_dim if self.in_dim == 1 else self.in_dim
        assert hidden > 1, "DimeNet requires more than one hidden dimension."
        n = x.shape[0]
        src, dst = g.senders, g.receivers
        ex = g.extras
        idx_i, idx_j, idx_k = ex["dn_idx_i"], ex["dn_idx_j"], ex["dn_idx_k"]
        idx_kj, idx_ji = ex["dn_idx_kj"], ex["dn_idx_ji"]
        tmask = ex["dn_triplet_mask"]
        perm_kj = ex.get("dn_perm_kj")

        dist = jnp.sqrt(
            jnp.sum((pos[dst] - pos[src]) ** 2, axis=-1) + 1e-14
        )
        dist = jnp.where(g.edge_mask > 0, dist, self.cutoff)  # keep padding finite

        pos_i = pos[idx_i]
        v_ji = pos[idx_j] - pos_i
        v_ki = pos[idx_k] - pos_i
        a = jnp.sum(v_ji * v_ki, axis=-1)
        b = jnp.linalg.norm(jnp.cross(v_ji, v_ki) + 1e-14, axis=-1)
        angle = jnp.arctan2(b, a)

        rbf = BesselBasis(
            self.num_radial, self.cutoff, self.envelope_exponent, name="rbf"
        )(dist)
        # factored-basis fused triplet kernel gate: collate vouches the
        # window invariant ("dn_tri_ok"), the dims must fit the padded
        # lanes, and the sort invariants must hold (sorted_hint/perm)
        sr = self.num_spherical * self.num_radial
        tri_w = ex.get("dn_tri_window")
        tri_kernel = (
            ex.get("dn_tri_ok") is not None and perm_kj is not None
            and self.num_spherical <= 8 and sr <= 64
            and self.int_emb_size <= 64 and self.basis_emb_size <= 64
            # an explicit HYDRAGNN_DIMENET_FUSED_TRI opt-in wins: the
            # legacy T->E path stays reachable (and testable)
            and tri_w is None)
        # wide dims beyond the factored kernel's packing fall to the
        # builder-backed fused path (ops/dn_tri.dimenet_tri_builder) —
        # same window invariant, full-sbf geometry stream
        from hydragnn_tpu.ops.dn_tri import TRI_EMB_LIMIT, TRI_SBF_LIMIT

        tri_builder = (
            not tri_kernel and tri_w is None
            and ex.get("dn_tri_ok") is not None and perm_kj is not None
            and sr <= TRI_SBF_LIMIT
            and self.basis_emb_size <= TRI_EMB_LIMIT
            and self.int_emb_size <= TRI_EMB_LIMIT)
        if (ex.get("dn_tri_ok") is not None and perm_kj is not None
                and not (tri_kernel or tri_builder)):
            from hydragnn_tpu.ops.fused_block import note_fallback

            note_fallback("DimeNet", reason="width_gate",
                          sr=int(sr), int_emb=int(self.int_emb_size),
                          basis_emb=int(self.basis_emb_size))
        radial2 = cbf_exp = None
        if tri_kernel:
            radial2, cbf_exp = spherical_basis_factors(
                dist / self.cutoff, angle, self.num_spherical,
                self.num_radial, self.envelope_exponent)
            sbf = None
        else:
            sbf = spherical_basis(
                dist / self.cutoff,
                angle,
                idx_kj,
                self.num_spherical,
                self.num_radial,
                self.envelope_exponent,
                perm_kj=perm_kj,
            )
        # Mixed precision: the Bessel/Legendre recurrences are evaluated in
        # f32 (pos/dist/angle stay f32 for force grads and recurrence
        # stability), but the [T, S*R] / [E, R] basis STREAMS are cast to
        # the compute dtype here so the whole triplet-space chain — the
        # step's dominant HBM traffic (round-4 attribution: 9.4 GB/step of
        # [T, *] f32 streams at gather/scatter bandwidth) — runs in bf16
        # when the model does.  x carries the trainer's compute dtype;
        # under f32 training these casts are no-ops.
        rbf = rbf.astype(x.dtype)
        if sbf is not None:
            sbf = sbf.astype(x.dtype)

        h = nn.Dense(hidden, name="lin_in")(x)
        # embedding block (no atomic embedding; reference HydraEmbeddingBlock)
        rbf_e = _silu(nn.Dense(hidden, name="emb_lin_rbf")(rbf))
        x_edge = _silu(
            nn.Dense(hidden, name="emb_lin")(
                jnp.concatenate([h[dst], h[src], rbf_e], axis=-1)
            )
        )
        sorted_hint = bool(g.extras and "edge_perm_sender" in g.extras)
        # window encoded in the marker array's SHAPE (static under jit)
        tri_window = int(tri_w.shape[0]) if tri_w is not None else 0
        x_edge = InteractionPPBlock(
            hidden,
            self.int_emb_size,
            self.basis_emb_size,
            self.num_before_skip,
            self.num_after_skip,
            sorted_hint=sorted_hint,
            tri_window=tri_window,
            tri_kernel=tri_kernel,
            tri_builder=tri_builder,
            num_radial=self.num_radial,
            name="interaction",
        )(x_edge, rbf, sbf, idx_kj, idx_ji, tmask, perm_kj=perm_kj,
          radial=radial2, cbf_exp=cbf_exp)
        out = OutputPPBlock(
            hidden, self.out_emb_size, self.out_dim, num_layers=1,
            sorted_hint=sorted_hint, name="output"
        )(x_edge, rbf, dst, n, g.edge_mask)
        return out, pos


class DIMEStack(Base):
    has_batchnorm: bool = False

    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        # HYDRAGNN_DIMENET_REMAT=1 rematerializes each conv in the
        # backward.  Measured and REJECTED as a default on the v5e sweep
        # config (92.0 vs 65.0 ms/step): although the step moves ~9.4 GB
        # of residuals (round-4 attribution), remat re-evaluates the
        # spherical basis inside every layer's backward — losing the
        # cross-layer CSE that normally computes it once — and the
        # recompute costs more than the saved HBM round-trips.  Kept as an
        # opt-in for memory-limited configs (wide OC20-scale batches).
        from hydragnn_tpu.utils.env import env_flag

        cls = DimeNetConv
        if env_flag("HYDRAGNN_DIMENET_REMAT"):
            cls = nn.remat(DimeNetConv, static_argnums=(3,))
        return cls(
            in_dim=in_dim,
            out_dim=out_dim,
            num_radial=c.num_radial,
            num_spherical=c.num_spherical,
            basis_emb_size=c.basis_emb_size,
            int_emb_size=c.int_emb_size,
            out_emb_size=c.out_emb_size,
            num_before_skip=c.num_before_skip,
            num_after_skip=c.num_after_skip,
            envelope_exponent=c.envelope_exponent,
            cutoff=c.radius,
            name=name,
        )
