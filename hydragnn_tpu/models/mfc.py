"""MFC stack (parity: reference hydragnn/models/MFCStack.py).

MFConv (molecular fingerprint conv): degree-dependent weight matrices —
out_i = W_root[d_i] x_i + W[d_i] sum_{j->i} x_j, where d_i is the in-degree
clamped to ``max_degree``.  The per-node weight selection is a gather over a
[max_degree+1, in, out] parameter bank followed by a batched matmul.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class MFConv(nn.Module):
    out_dim: int
    max_degree: int  # degree-table clip bound (weight-bank size)

    @nn.compact
    def __call__(self, x, pos, g, train):
        n, in_dim = x.shape
        d = self.max_degree + 1
        w_root = self.param(
            "w_root", nn.initializers.lecun_normal(), (d, in_dim, self.out_dim)
        )
        w_neigh = self.param(
            "w_neigh", nn.initializers.lecun_normal(), (d, in_dim, self.out_dim)
        )
        bias = self.param("bias", nn.initializers.zeros, (d, self.out_dim))

        # neighbor sum AND degree from ONE fused multi-moment pass when
        # the batch carries the collate marker (ops/poly_mp.py) — the
        # separate degree scatter folds into the aggregation kernel
        res = segment.poly_gather_segment(x, g, ("sum", "cnt"))
        deg = jnp.clip(res["cnt"].astype(jnp.int32), 0, self.max_degree)
        agg = res["sum"]

        # One wide MXU matmul against ALL degree banks + a row select,
        # instead of gathering a per-node [N, in, out] weight tensor
        # (~167 MB/layer at bench shapes) into a batched einsum — measured
        # 2.6x end-to-end on the v5e (21.0k -> 55.5k graphs/s).  Identical
        # math: selecting the deg-th output equals using the deg-th bank.
        hr = (x @ w_root.transpose(1, 0, 2).reshape(in_dim, -1)
              ).reshape(n, d, self.out_dim)
        hn = (agg @ w_neigh.transpose(1, 0, 2).reshape(in_dim, -1)
              ).reshape(n, d, self.out_dim)
        out = jnp.take_along_axis(hr + hn, deg[:, None, None], axis=1)[:, 0]
        return out + jnp.take(bias, deg, axis=0), pos


class MFCStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        assert self.cfg.max_degree is not None, "MFC requires max_neighbours."
        return MFConv(out_dim, max_degree=self.cfg.max_degree, name=name)
