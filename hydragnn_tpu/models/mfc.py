"""MFC stack (parity: reference hydragnn/models/MFCStack.py).

MFConv (molecular fingerprint conv): degree-dependent weight matrices —
out_i = W_root[d_i] x_i + W[d_i] sum_{j->i} x_j, where d_i is the in-degree
clamped to ``max_degree``.  The per-node weight selection is a gather over a
[max_degree+1, in, out] parameter bank followed by a batched matmul.
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class MFConv(nn.Module):
    out_dim: int
    max_degree: int  # degree-table clip bound (weight-bank size)

    @nn.compact
    def __call__(self, x, pos, g, train):
        n, in_dim = x.shape
        d = self.max_degree + 1
        w_root = self.param(
            "w_root", nn.initializers.lecun_normal(), (d, in_dim, self.out_dim)
        )
        w_neigh = self.param(
            "w_neigh", nn.initializers.lecun_normal(), (d, in_dim, self.out_dim)
        )
        bias = self.param("bias", nn.initializers.zeros, (d, self.out_dim))

        deg = segment.degree(g.receivers, n, g.edge_mask).astype(jnp.int32)
        deg = jnp.clip(deg, 0, self.max_degree)
        agg = segment.gather_segment(x, g)

        out = jnp.einsum("ni,nio->no", x, jnp.take(w_root, deg, axis=0))
        out = out + jnp.einsum("ni,nio->no", agg, jnp.take(w_neigh, deg, axis=0))
        out = out + jnp.take(bias, deg, axis=0)
        return out, pos


class MFCStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        assert self.cfg.max_degree is not None, "MFC requires max_neighbours."
        return MFConv(out_dim, max_degree=self.cfg.max_degree, name=name)
