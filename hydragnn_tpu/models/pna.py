"""PNA stack (parity: reference hydragnn/models/PNAStack.py).

Principal Neighbourhood Aggregation with aggregators [mean, min, max, std]
and scalers [identity, amplification, attenuation, linear]
(reference PNAStack.py:28-34; towers=1, pre_layers=1, post_layers=1,
divide_input=False as in PyG PNAConv).  The degree-scaler averages
(avg log-degree / avg degree) are computed from the training-set degree
histogram collected by the data layer (parity with gather_deg,
reference hydragnn/preprocess/utils.py:177-195).
"""

from __future__ import annotations

import jax.numpy as jnp
import flax.linen as nn

from hydragnn_tpu.graph import segment
from hydragnn_tpu.models.base import Base


class PNAConv(nn.Module):
    out_dim: int
    in_dim: int
    avg_deg_log: float
    avg_deg_lin: float
    edge_dim: int = 0

    @nn.compact
    def __call__(self, x, pos, g, train):
        f = self.in_dim

        # gathers whose backward rides the dense sorted scatter instead of
        # XLA's scatter-add (marker-gated; plain gathers otherwise)
        h_src = segment.gather_sender(x, g)
        h_dst = segment.gather_receiver_sorted(x, g)
        if self.edge_dim:
            e = nn.Dense(f, name="edge_encoder")(g.edge_attr)
            z = jnp.concatenate([h_dst, h_src, e], axis=-1)
        else:
            z = jnp.concatenate([h_dst, h_src], axis=-1)
        msg = nn.Dense(f, name="pre_nn")(z)  # pre_layers=1

        # ALL FOUR aggregators (mean/std via a sum + sum-of-squares pair,
        # min/max via a running max of [msg, -msg]) plus the degree come
        # out of ONE fused multi-moment pass when the batch carries the
        # collate marker (ops/poly_mp.py) — composed, they cost two
        # scatter-sums, a double-width segment_max that XLA lowers to a
        # long sort pipeline, and a separate degree scatter.  Numerics
        # are the segment_mean/segment_std conventions (max(deg,1)
        # divide, eps 1e-5); min(x) = -max(-x), same values and grads.
        res = segment.poly_scatter_segment(
            msg, g, ("sum", "sq", "mx", "mn", "cnt"))
        deg = jnp.maximum(res["cnt"], 1.0)[:, None]
        mean = res["sum"] / deg
        sq_mean = res["sq"] / deg
        std = jnp.sqrt(jnp.maximum(sq_mean - mean * mean, 0.0) + 1e-5)
        agg = jnp.concatenate(
            [mean, res["mn"], res["mx"], std], axis=-1)  # [N, 4F]

        log_deg = jnp.log(deg + 1.0)
        scaled = jnp.concatenate(
            [
                agg,
                agg * (log_deg / self.avg_deg_log),
                agg * (self.avg_deg_log / log_deg),
                agg * (deg / jnp.maximum(self.avg_deg_lin, 1e-8)),
            ],
            axis=-1,
        )  # [N, 16F]

        out = jnp.concatenate([x, scaled], axis=-1)
        out = nn.Dense(self.out_dim, name="post_nn")(out)  # post_layers=1
        out = nn.Dense(self.out_dim, name="lin_out")(out)
        return out, pos


class PNAStack(Base):
    def make_conv(self, name, in_dim, out_dim, last_layer):
        c = self.cfg
        assert c.pna_avg_deg_log is not None, "PNA requires degree input."
        return PNAConv(
            out_dim,
            in_dim=in_dim,
            avg_deg_log=max(c.pna_avg_deg_log, 1e-8),
            avg_deg_lin=c.pna_avg_deg_lin,
            edge_dim=c.edge_dim or 0,
            name=name,
        )
