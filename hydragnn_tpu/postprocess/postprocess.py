"""Output denormalization (parity: reference hydragnn/postprocess/postprocess.py:13-54)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def output_denormalize(y_minmax: Sequence[Sequence[float]], true_values, predicted_values):
    """Inverse the min-max normalization on per-head true/pred arrays."""
    for ihead in range(len(true_values)):
        ymin, ymax = float(y_minmax[ihead][0]), float(y_minmax[ihead][1])
        true_values[ihead] = np.asarray(true_values[ihead]) * (ymax - ymin) + ymin
        predicted_values[ihead] = (
            np.asarray(predicted_values[ihead]) * (ymax - ymin) + ymin
        )
    return true_values, predicted_values


def unscale_features_by_num_nodes(values: np.ndarray, num_nodes: np.ndarray) -> np.ndarray:
    """Undo per-num-nodes feature scaling (reference postprocess.py:29-54)."""
    return np.asarray(values) * np.asarray(num_nodes).reshape(-1, 1)
