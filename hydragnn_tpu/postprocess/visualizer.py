"""Result visualization (parity: reference hydragnn/postprocess/visualizer.py).

Matplotlib plots of training results: per-head parity scatter plots, error
PDFs and conditional means, loss history, node-count histogram.  All methods
render to PNG under ``logs/<name>/`` on rank 0.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        node_feature: Optional[Sequence] = None,
        num_heads: int = 1,
        head_dims: Optional[Sequence[int]] = None,
        logs_dir: str = "./logs/",
    ):
        self.log_name = model_with_config_name
        self.outdir = os.path.join(logs_dir, model_with_config_name)
        os.makedirs(self.outdir, exist_ok=True)
        self.num_heads = num_heads
        self.head_dims = list(head_dims or [1] * num_heads)

    # -- scatter / parity plots (reference visualizer.py:692-720) ----------
    def create_scatter_plots(
        self,
        true_values: Sequence[np.ndarray],
        predicted_values: Sequence[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> None:
        plt = _plt()
        n = len(true_values)
        fig, axs = plt.subplots(1, n, figsize=(5 * n, 4.5), squeeze=False)
        for ih in range(n):
            t = np.asarray(true_values[ih]).reshape(-1)
            p = np.asarray(predicted_values[ih]).reshape(-1)
            ax = axs[0][ih]
            ax.scatter(t, p, s=6, edgecolor="b", facecolor="none")
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = output_names[ih] if output_names else f"head{ih}"
            ax.set_title(f"{name}  MAE={np.abs(t - p).mean():.4f}")
            ax.set_xlabel("true")
            ax.set_ylabel("predicted")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, f"scatter{suffix}.png"))
        plt.close(fig)

    # -- error statistics (reference "global analysis", visualizer.py:134+) -
    def create_error_histograms(
        self,
        true_values: Sequence[np.ndarray],
        predicted_values: Sequence[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        plt = _plt()
        n = len(true_values)
        fig, axs = plt.subplots(1, n, figsize=(5 * n, 4), squeeze=False)
        for ih in range(n):
            err = (np.asarray(predicted_values[ih]) -
                   np.asarray(true_values[ih])).reshape(-1)
            ax = axs[0][ih]
            ax.hist(err, bins=40, color="b", alpha=0.6, density=True)
            name = output_names[ih] if output_names else f"head{ih}"
            ax.set_title(f"{name} error PDF")
            ax.set_xlabel("pred - true")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "error_pdf.png"))
        plt.close(fig)

    # -- loss history (reference visualizer.py:629-690) --------------------
    def plot_history(self, history: Dict[str, List[float]]) -> None:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(6, 4.5))
        for split in ("train", "val", "test"):
            if split in history and history[split]:
                ax.semilogy(history[split], label=split)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "history.png"))
        plt.close(fig)

    # -- dataset statistics (reference visualizer.py:734+) -----------------
    def num_nodes_plot(self, node_counts: Sequence[int]) -> None:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(np.asarray(node_counts), bins=20, color="b", alpha=0.7)
        ax.set_xlabel("nodes per graph")
        ax.set_ylabel("count")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "num_nodes.png"))
        plt.close(fig)
