"""Result visualization (parity: reference hydragnn/postprocess/visualizer.py).

Matplotlib plots of training results: per-head parity scatter plots, error
PDFs and conditional means, loss history, node-count histogram.  All methods
render to PNG under ``logs/<name>/`` on rank 0.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


class Visualizer:
    def __init__(
        self,
        model_with_config_name: str,
        node_feature: Optional[Sequence] = None,
        num_heads: int = 1,
        head_dims: Optional[Sequence[int]] = None,
        logs_dir: str = "./logs/",
    ):
        self.log_name = model_with_config_name
        self.outdir = os.path.join(logs_dir, model_with_config_name)
        os.makedirs(self.outdir, exist_ok=True)
        self.num_heads = num_heads
        self.head_dims = list(head_dims or [1] * num_heads)

    # -- scatter / parity plots (reference visualizer.py:692-720) ----------
    def create_scatter_plots(
        self,
        true_values: Sequence[np.ndarray],
        predicted_values: Sequence[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
        iepoch: Optional[int] = None,
    ) -> None:
        plt = _plt()
        n = len(true_values)
        fig, axs = plt.subplots(1, n, figsize=(5 * n, 4.5), squeeze=False)
        for ih in range(n):
            t = np.asarray(true_values[ih]).reshape(-1)
            p = np.asarray(predicted_values[ih]).reshape(-1)
            ax = axs[0][ih]
            ax.scatter(t, p, s=6, edgecolor="b", facecolor="none")
            lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            name = output_names[ih] if output_names else f"head{ih}"
            ax.set_title(f"{name}  MAE={np.abs(t - p).mean():.4f}")
            ax.set_xlabel("true")
            ax.set_ylabel("predicted")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, f"scatter{suffix}.png"))
        plt.close(fig)

    # -- error statistics (reference "global analysis", visualizer.py:134+) -
    def create_error_histograms(
        self,
        true_values: Sequence[np.ndarray],
        predicted_values: Sequence[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        plt = _plt()
        n = len(true_values)
        fig, axs = plt.subplots(1, n, figsize=(5 * n, 4), squeeze=False)
        for ih in range(n):
            err = (np.asarray(predicted_values[ih]) -
                   np.asarray(true_values[ih])).reshape(-1)
            ax = axs[0][ih]
            ax.hist(err, bins=40, color="b", alpha=0.6, density=True)
            name = output_names[ih] if output_names else f"head{ih}"
            ax.set_title(f"{name} error PDF")
            ax.set_xlabel("pred - true")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "error_pdf.png"))
        plt.close(fig)

    # -- global analysis (reference visualizer.py:134-279) -----------------
    @staticmethod
    def _err_condmean(true_values: np.ndarray, predicted_values: np.ndarray,
                      nbins: int = 20):
        """Mean absolute error conditioned on the true value (binned)."""
        t = np.asarray(true_values).reshape(-1)
        p = np.asarray(predicted_values).reshape(-1)
        err = np.abs(p - t)
        edges = np.linspace(t.min(), t.max() + 1e-12, nbins + 1)
        which = np.clip(np.digitize(t, edges) - 1, 0, nbins - 1)
        centers, means = [], []
        for b in range(nbins):
            m = which == b
            if m.any():
                centers.append(0.5 * (edges[b] + edges[b + 1]))
                means.append(err[m].mean())
        return np.asarray(centers), np.asarray(means)

    def create_plot_global_analysis(
        self,
        varname: str,
        true_values,
        predicted_values,
        save_plot: bool = True,
    ) -> None:
        """Scatter + conditional-mean-error + error-PDF panel for one head
        (reference create_plot_global_analysis, visualizer.py:134-279).

        Scalar heads get one 1x3 row; vector heads get two rows analysing the
        vector LENGTH and the component SUM per sample (the reference's
        vlen/vsum panels)."""
        plt = _plt()
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        if t.ndim == 1:
            t, p = t[:, None], p[:, None]
        dim = t.shape[1]

        def _row(axs, tv, pv, label):
            ax = axs[0]
            ax.scatter(tv, pv, s=6, edgecolor="b", facecolor="none")
            lo = float(min(tv.min(), pv.min()))
            hi = float(max(tv.max(), pv.max()))
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            ax.set_title(f"{label}")
            ax.set_xlabel("True")
            ax.set_ylabel("Predicted")
            ax = axs[1]
            xs, em = self._err_condmean(tv, pv)
            ax.plot(xs, em, "ro")
            ax.set_title("Conditional mean abs. error")
            ax.set_xlabel("True")
            ax.set_ylabel("abs. error")
            ax = axs[2]
            hist1d, edges = np.histogram(pv - tv, bins=40, density=True)
            ax.plot(0.5 * (edges[:-1] + edges[1:]), hist1d, "ro")
            ax.set_title(f"{label}: error PDF")
            ax.set_xlabel("Error")
            ax.set_ylabel("PDF")

        if dim == 1:
            fig, axs = plt.subplots(1, 3, figsize=(15, 4.5))
            _row(axs, t.reshape(-1), p.reshape(-1), f"{varname}")
        else:
            fig, axs = plt.subplots(2, 3, figsize=(15, 9))
            tl = np.linalg.norm(t, axis=1)
            pl = np.linalg.norm(p, axis=1)
            _row(axs[0], tl, pl, f"{varname} |v|")
            _row(axs[1], t.sum(axis=1), p.sum(axis=1), f"{varname} sum")
        fig.tight_layout()
        if save_plot:
            fig.savefig(os.path.join(
                self.outdir, f"global_analysis_{varname}.png"))
        plt.close(fig)

    def create_parity_plot_vector(
        self,
        varname: str,
        true_values,
        predicted_values,
        head_dim: int,
        iepoch: Optional[int] = None,
        save_plot: bool = True,
    ) -> None:
        """Per-component parity grid for a vector head (reference
        create_parity_plot_vector, visualizer.py:467-613)."""
        import math

        plt = _plt()
        t = np.asarray(true_values).reshape(-1, head_dim)
        p = np.asarray(predicted_values).reshape(-1, head_dim)
        nrow = max(int(math.floor(math.sqrt(head_dim))), 1)
        ncol = int(math.ceil(head_dim / nrow))
        markers = ["o", "s", "d"]
        fig, axs = plt.subplots(
            nrow, ncol, figsize=(ncol * 4, nrow * 4), squeeze=False)
        flat = axs.flatten()
        for ic in range(head_dim):
            ax = flat[ic]
            ax.scatter(t[:, ic], p[:, ic], s=6, c="b",
                       marker=markers[ic % len(markers)])
            lo = float(min(t[:, ic].min(), p[:, ic].min()))
            hi = float(max(t[:, ic].max(), p[:, ic].max()))
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            ax.set_title(f"comp:{ic}")
        for ie in range(head_dim, flat.size):
            flat[ie].axis("off")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        if save_plot:
            fig.savefig(os.path.join(
                self.outdir, f"parity_vector_{varname}{suffix}.png"))
        plt.close(fig)

    def create_parity_plot_and_error_histogram_scalar(
        self,
        varname: str,
        true_values,
        predicted_values,
        iepoch: Optional[int] = None,
        save_plot: bool = True,
    ) -> None:
        """Side-by-side parity scatter + error-PDF for one scalar head
        (reference create_parity_plot_and_error_histogram_scalar,
        visualizer.py:281-386)."""
        plt = _plt()
        t = np.asarray(true_values).reshape(-1)
        p = np.asarray(predicted_values).reshape(-1)
        fig, axs = plt.subplots(1, 2, figsize=(12, 6))
        ax = axs[0]
        ax.scatter(t, p, s=6, edgecolor="b", facecolor="none")
        lo, hi = float(min(t.min(), p.min())), float(max(t.max(), p.max()))
        ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
        ax.set_title(varname)
        ax.set_xlabel("True")
        ax.set_ylabel("Predicted")
        ax = axs[1]
        hist1d, edges = np.histogram(p - t, bins=40, density=True)
        ax.plot(0.5 * (edges[:-1] + edges[1:]), hist1d, "ro")
        ax.set_title(f"{varname}: error PDF")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        if save_plot:
            fig.savefig(os.path.join(
                self.outdir, f"parity_errpdf_{varname}{suffix}.png"))
        plt.close(fig)

    def create_error_histogram_per_node(
        self,
        varname: str,
        true_values,
        predicted_values,
        iepoch: Optional[int] = None,
        save_plot: bool = True,
    ) -> None:
        """Per-node-position error PDFs for node-level outputs on
        FIXED-SIZE graphs ([num_samples, num_nodes] layout; reference
        create_error_histogram_per_node, visualizer.py:387-466).  Scalar
        per-graph outputs (one column) are skipped like the reference."""
        import math

        plt = _plt()
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        if t.ndim == 1 or t.shape[1] == 1:
            return
        n_nodes = t.shape[1]
        nrow = max(int(math.floor(math.sqrt(n_nodes))), 1)
        ncol = int(math.ceil(n_nodes / nrow))
        fig, axs = plt.subplots(
            nrow, ncol, figsize=(ncol * 3.5, nrow * 3.2), squeeze=False)
        flat = axs.flatten()
        for inode in range(n_nodes):
            err = p[:, inode] - t[:, inode]
            hist1d, edges = np.histogram(err, bins=40, density=True)
            ax = flat[inode]
            ax.plot(0.5 * (edges[:-1] + edges[1:]), hist1d, "ro")
            ax.set_title(f"node {inode}")
        for ie in range(n_nodes, flat.size):
            flat[ie].axis("off")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        if save_plot:
            fig.savefig(os.path.join(
                self.outdir, f"errpdf_per_node_{varname}{suffix}.png"))
        plt.close(fig)

    def create_parity_plot_per_node_vector(
        self,
        varname: str,
        true_values,
        predicted_values,
        iepoch: Optional[int] = None,
        save_plot: bool = True,
    ) -> None:
        """Per-node parity grid for 3-vector node outputs on FIXED-SIZE
        graphs ([num_samples, num_nodes*3] layout; reference
        create_parity_plot_per_node_vector, visualizer.py:519-613):
        one panel per node, the three vector components overplotted with
        distinct markers."""
        import math

        plt = _plt()
        t = np.asarray(true_values)
        p = np.asarray(predicted_values)
        t = t.reshape(t.shape[0], -1, 3)
        p = p.reshape(p.shape[0], -1, 3)
        n_nodes = t.shape[1]
        markers = ["o", "s", "d"]
        nrow = max(int(math.floor(math.sqrt(n_nodes))), 1)
        ncol = int(math.ceil(n_nodes / nrow))
        fig, axs = plt.subplots(
            nrow, ncol, figsize=(ncol * 3, nrow * 3), squeeze=False)
        flat = axs.flatten()
        for inode in range(n_nodes):
            ax = flat[inode]
            for ic in range(3):
                ax.scatter(t[:, inode, ic], p[:, inode, ic], s=6, c="b",
                           marker=markers[ic])
            lo = float(min(t[:, inode].min(), p[:, inode].min()))
            hi = float(max(t[:, inode].max(), p[:, inode].max()))
            ax.plot([lo, hi], [lo, hi], "r--", linewidth=1)
            ax.set_title(f"node {inode}")
        for ie in range(n_nodes, flat.size):
            flat[ie].axis("off")
        suffix = f"_epoch{iepoch}" if iepoch is not None else ""
        fig.tight_layout()
        if save_plot:
            fig.savefig(os.path.join(
                self.outdir, f"parity_per_node_{varname}{suffix}.png"))
        plt.close(fig)

    def create_plot_global(
        self,
        true_values: Sequence[np.ndarray],
        predicted_values: Sequence[np.ndarray],
        output_names: Optional[Sequence[str]] = None,
    ) -> None:
        """Global analysis (scatter/condmean/error-PDF) for every head
        (reference create_plot_global, visualizer.py:722-733)."""
        for ih in range(len(true_values)):
            name = output_names[ih] if output_names else f"head{ih}"
            self.create_plot_global_analysis(
                name, true_values[ih], predicted_values[ih], save_plot=True)

    # -- loss history (reference visualizer.py:629-690) --------------------
    def plot_history(self, history: Dict[str, List[float]]) -> None:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(6, 4.5))
        for split in ("train", "val", "test"):
            if split in history and history[split]:
                ax.semilogy(history[split], label=split)
        ax.set_xlabel("epoch")
        ax.set_ylabel("loss")
        ax.legend()
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "history.png"))
        plt.close(fig)

    # -- dataset statistics (reference visualizer.py:734+) -----------------
    def num_nodes_plot(self, node_counts: Sequence[int]) -> None:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(5, 4))
        ax.hist(np.asarray(node_counts), bins=20, color="b", alpha=0.7)
        ax.set_xlabel("nodes per graph")
        ax.set_ylabel("count")
        fig.tight_layout()
        fig.savefig(os.path.join(self.outdir, "num_nodes.png"))
        plt.close(fig)
