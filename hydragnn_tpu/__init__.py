"""hydragnn_tpu: TPU-native multi-headed GNN training framework.

A ground-up JAX/XLA/pjit re-design with the capabilities of ORNL's HydraGNN
(config-driven multi-task GNN training for atomistic science).  See SURVEY.md
for the reference blueprint and the per-module docstrings for parity notes.
"""

from hydragnn_tpu import graph, config, models, data, train, utils, parallel, postprocess
from hydragnn_tpu.run_training import run_training
from hydragnn_tpu.run_prediction import run_prediction

__version__ = "0.1.0"
