"""hydragnn_tpu: TPU-native multi-headed GNN training framework.

A ground-up JAX/XLA/pjit re-design with the capabilities of ORNL's HydraGNN
(config-driven multi-task GNN training for atomistic science).  See SURVEY.md
for the reference blueprint and the per-module docstrings for parity notes.
"""

from hydragnn_tpu import graph, config, models

__version__ = "0.1.0"
