"""Robustness-idiom rules: exception hygiene and atomic sidecar writes.

- ROB001: a broad ``except Exception`` that neither re-raises, nor logs,
  nor emits a health event, nor USES the caught exception value swallows
  the failure silently — the class of bug that turns a checkpoint-write
  error into a run that "succeeded" with no checkpoint (the PR-7
  save_checkpoint silent-False bug).
- ROB002: ``open(path, "w")`` + ``json.dump``/``pickle.dump`` without
  the tmp+``os.replace`` idiom leaves a torn file when the process dies
  mid-write — the PR-3 best-model-pickle bug.  resilience/ckpt_io.py
  has the atomic writer; use it.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..astutil import base_name, call_name, const_str
from ..core import Finding, Rule, Severity, register

_BROAD = {"Exception", "BaseException"}
_LOGGING_ATTRS = {"warning", "warn", "error", "exception", "info",
                  "debug", "critical", "log", "health", "print_exc",
                  "fail", "set_exception"}
_LOGGING_NAMES = {"print"}


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:  # bare except
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Attribute) and t.attr in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _handler_handles(h: ast.ExceptHandler) -> bool:
    """True when the handler re-raises, logs, emits health, or uses the
    caught exception value (propagating the reason somewhere)."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOGGING_ATTRS:
                return True
            if isinstance(fn, ast.Name) and fn.id in _LOGGING_NAMES:
                return True
    if h.name:
        for node in ast.walk(h):
            if (isinstance(node, ast.Name) and node.id == h.name
                    and isinstance(node.ctx, ast.Load)):
                return True
    return False


@register
class SwallowedException(Rule):
    id = "ROB001"
    name = "swallowed-broad-except"
    severity = Severity.WARN
    doc = ("broad `except Exception` must re-raise, log, emit a health "
           "event, or use the error — never swallow silently")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if _handler_handles(node):
                continue
            out.append(self.finding(
                ctx, node,
                "broad except swallows the error silently — narrow the "
                "exception type, log/emit a health event, or annotate "
                "with `# graftlint: disable=ROB001 (reason)`"))
        return out


def _open_write_target(call: ast.Call) -> Optional[ast.AST]:
    """The path argument when ``call`` is ``open(path, "w"/"wb"/...)``."""
    if base_name(call_name(call)) != "open" or not call.args:
        return None
    mode = ""
    if len(call.args) >= 2:
        mode = const_str(call.args[1]) or ""
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value) or ""
    if "w" not in mode:
        return None
    return call.args[0]


def _expr_is_tmpish(node: ast.AST, src_segment: str) -> bool:
    low = src_segment.lower()
    return "tmp" in low or "temp" in low or "partial" in low


@register
class NonAtomicSidecarWrite(Rule):
    id = "ROB002"
    name = "non-atomic-sidecar-write"
    severity = Severity.WARN
    doc = ("json/pickle sidecars must be written tmp+os.replace "
           "(resilience/ckpt_io.py has the atomic writer)")

    def check_file(self, ctx) -> Iterable[Finding]:
        from ..astutil import build_parents, enclosing_function

        out: List[Finding] = []
        parents = build_parents(ctx.tree)
        for w in ast.walk(ctx.tree):
            if not isinstance(w, (ast.With, ast.AsyncWith)):
                continue
            for item in w.items:
                e = item.context_expr
                if not isinstance(e, ast.Call):
                    continue
                target = _open_write_target(e)
                if target is None:
                    continue
                seg = ast.get_source_segment(ctx.src, target) or ""
                if _expr_is_tmpish(target, seg):
                    continue
                dumps = [c for c in ast.walk(w)
                         if isinstance(c, ast.Call)
                         and call_name(c) in ("json.dump", "pickle.dump")]
                if not dumps:
                    continue
                # the atomic idiom: os.replace anywhere in the enclosing
                # function (the dump goes to a tmp we failed to name-spot,
                # or the function renames after the with-block)
                scope = enclosing_function(w, parents) or ctx.tree
                if any(isinstance(n, ast.Call)
                       and call_name(n) in ("os.replace", "os.rename")
                       for n in ast.walk(scope)):
                    continue
                out.append(self.finding(
                    ctx, w,
                    "non-atomic sidecar write: open(..., 'w') + dump "
                    "without tmp+os.replace — a crash mid-write tears "
                    "the file"))
        return out
