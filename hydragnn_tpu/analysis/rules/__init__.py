"""Rule modules — importing each registers its rules (see core.register)."""
