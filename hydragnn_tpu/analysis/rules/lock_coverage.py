"""Lock-coverage rule: shared mutable state in lock-owning classes.

The MetricsLogger/batcher/fleet bug class (PRs 4, 5, 6, 8 each paid a
review-hardening pass for one): a class owns a ``threading.Lock`` because
a second thread reaches it, but one write site to a shared attribute
slips in outside ``with self._lock`` — a torn counter under load, or a
lost update that only reproduces at fleet rates.

Rule: in any class that owns a Lock/RLock/Condition attribute, an
instance attribute WRITTEN from two or more methods (``__init__`` and
friends exempt — single-threaded construction) must only be mutated
under a ``with self.<lock>`` block.

Escape hatches, in preference order: (1) actually take the lock; (2) a
method named ``*_locked`` or whose docstring contains "caller holds" /
"lock held" / "under the lock" is treated as externally guarded; (3) a
``# graftlint: disable=LCK001 (reason)`` suppression for provably-benign
cases (e.g. monotonic flag set before the thread starts).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..astutil import base_name, build_parents, call_name
from ..core import Finding, Rule, Severity, register

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_EXEMPT_METHODS = {"__init__", "__new__", "__post_init__", "__repr__",
                   "__del__"}
_GUARD_DOC_MARKERS = ("caller holds", "lock held", "under the lock",
                      "holding the lock")


def _method_is_externally_guarded(m: ast.AST) -> bool:
    if m.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(m) or ""
    low = doc.lower()
    return any(marker in low for marker in _GUARD_DOC_MARKERS)


def _self_attr_writes(m: ast.AST) -> List[ast.Attribute]:
    """Attribute targets ``self.X`` written anywhere in a method
    (Assign/AugAssign/AnnAssign, tuple unpacking included)."""
    out: List[ast.Attribute] = []

    def targets_of(node):
        if isinstance(node, ast.Assign):
            return node.targets
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        return []

    for node in ast.walk(m):
        for tgt in targets_of(node):
            for t in ast.walk(tgt):
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and isinstance(t.ctx, (ast.Store,))):
                    out.append(t)
    return out


def _is_under_lock(node: ast.AST, parents, lock_attrs: Set[str]) -> bool:
    q = node
    while q in parents:
        q = parents[q]
        if isinstance(q, (ast.With, ast.AsyncWith)):
            for item in q.items:
                e = item.context_expr
                # `with self._lock:` — and `with self._cv:` etc.
                if (isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr in lock_attrs):
                    return True
        if isinstance(q, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return False


@register
class UnguardedSharedWrite(Rule):
    id = "LCK001"
    name = "unguarded-shared-write"
    severity = Severity.ERROR
    doc = ("in a lock-owning class, attributes written from >=2 methods "
           "must be mutated under `with self.<lock>`")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        parents = build_parents(ctx.tree)
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            lock_attrs: Set[str] = set()
            for m in methods:
                for node in ast.walk(m):
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and base_name(call_name(node.value))
                            in _LOCK_CTORS):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                lock_attrs.add(t.attr)
            if not lock_attrs:
                continue

            # attr -> {method name -> [write nodes]} over non-exempt,
            # non-externally-guarded methods
            writes: Dict[str, Dict[str, List[ast.Attribute]]] = {}
            for m in methods:
                if m.name in _EXEMPT_METHODS:
                    continue
                for t in _self_attr_writes(m):
                    if t.attr in lock_attrs:
                        continue
                    writes.setdefault(t.attr, {}).setdefault(
                        m.name, []).append(t)

            for attr, by_method in sorted(writes.items()):
                if len(by_method) < 2:
                    continue
                for mname, nodes in sorted(by_method.items()):
                    method = next(m for m in methods if m.name == mname)
                    if _method_is_externally_guarded(method):
                        continue
                    for node in nodes:
                        if not _is_under_lock(node, parents, lock_attrs):
                            out.append(self.finding(
                                ctx, node,
                                f"`self.{attr}` is written from "
                                f"{len(by_method)} methods of lock-owning "
                                f"class `{cls.name}` but this write in "
                                f"`{mname}` is outside `with self."
                                f"{'/'.join(sorted(lock_attrs))}`"))
        return out
