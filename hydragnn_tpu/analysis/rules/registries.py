"""Cross-artifact registry rules: env knobs, health kinds, config keys.

The drift these catch accumulated over eight PRs: 80+ ``HYDRAGNN_*``
knobs spread across five config layers with no single inventory, health
event kinds added in code but never documented (or documented and then
renamed), and finalize-written config keys nobody validates on read.
The registries (`analysis/registry.py`) are the declared truth; these
rules pin code and docs to them from both directions.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..astutil import const_str
from ..core import Finding, Rule, Severity, register
from ..registry import HEALTH_KINDS, KNOBS, SPAN_NAMES, emit_knob_docs

_KNOB_RE = re.compile(r"HYDRAGNN_[A-Z0-9_]+")


def _knob_mentions(text: str) -> Set[str]:
    """Complete knob names in a string — a match ending in ``_`` is a
    prefix construction (``"HYDRAGNN_SERVE_" + name``), not a knob."""
    return {m for m in _KNOB_RE.findall(text) if not m.endswith("_")}


def _string_constants(tree: ast.AST):
    for node in ast.walk(tree):
        s = const_str(node)
        if s is not None:
            yield node, s
        elif isinstance(node, ast.JoinedStr):
            for v in node.values:
                sv = const_str(v)
                if sv is not None:
                    yield node, sv


@register
class UndeclaredEnvKnob(Rule):
    id = "REG001"
    name = "undeclared-env-knob"
    severity = Severity.ERROR
    doc = ("every HYDRAGNN_* name in code must be declared in the knob "
           "registry (analysis/registry.py)")

    def check_file(self, ctx) -> Iterable[Finding]:
        if ctx.rel.endswith("analysis/registry.py"):
            return []
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for node, s in _string_constants(ctx.tree):
            for name in sorted(_knob_mentions(s)):
                if name in KNOBS:
                    continue
                key = (node.lineno, name)
                if key in seen:
                    continue
                seen.add(key)
                out.append(self.finding(
                    ctx, node,
                    f"env knob `{name}` is not declared in the knob "
                    f"registry (hydragnn_tpu/analysis/registry.py) — "
                    f"declare it (name/config/default/module/effect), "
                    f"then `tools/graftlint.py --emit-docs`"))
        return out


@register
class KnobRegistryDrift(Rule):
    id = "REG002"
    name = "knob-registry-drift"
    severity = Severity.WARN
    doc = ("every declared knob must still be read somewhere, and "
           "docs/KNOBS.md must match the generated registry table")

    def check_project(self, project) -> Iterable[Finding]:
        out: List[Finding] = []
        reg_ctx = next((f for f in project.files
                        if f.rel.endswith("analysis/registry.py")), None)

        def reg_line(name: str) -> int:
            if reg_ctx is None:
                return 1
            for i, line in enumerate(reg_ctx.lines, start=1):
                if f'"{name}"' in line:
                    return i
            return 1

        used: Set[str] = set()
        for f in project.files:
            # the registry's own declarations don't count as use — every
            # declared knob trivially appears there (REG001 excludes the
            # file for the same reason)
            if f.rel.endswith("analysis/registry.py"):
                continue
            used |= _knob_mentions(f.src)
        for name in sorted(KNOBS):
            if name not in used and reg_ctx is not None:
                out.append(self.finding(
                    reg_ctx, reg_line(name),
                    f"declared knob `{name}` is never mentioned in code "
                    f"— delete the registry entry (and its doc row) or "
                    f"wire the knob up"))

        docs = project.read_text("docs/KNOBS.md")
        if reg_ctx is not None and docs != emit_knob_docs():
            out.append(self.finding(
                reg_ctx, 1,
                "docs/KNOBS.md is missing or stale — regenerate with "
                "`python tools/graftlint.py --emit-docs`"))
        return out


def _health_kind_literals(call: ast.Call) -> Optional[List[str]]:
    """Kind literal(s) of a ``health(...)`` call: a string constant, or
    a conditional expression whose branches are both string constants.
    None = dynamic."""
    if not call.args:
        return None
    a = call.args[0]
    s = const_str(a)
    if s is not None:
        return [s]
    if isinstance(a, ast.IfExp):
        b, c = const_str(a.body), const_str(a.orelse)
        if b is not None and c is not None:
            return [b, c]
    return None


def _iter_health_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name == "health" and node.args:
            yield node


@register
class UndeclaredHealthKind(Rule):
    id = "REG003"
    name = "undeclared-health-kind"
    severity = Severity.ERROR
    doc = ("every health(kind=...) literal must be declared in the "
           "health-kind registry; dynamic kinds need a suppression")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for call in _iter_health_calls(ctx.tree):
            kinds = _health_kind_literals(call)
            if kinds is None:
                out.append(self.finding(
                    ctx, call,
                    "health() called with a non-literal kind — the "
                    "registry rule cannot see it; pass literal kinds "
                    "(an IfExp of two literals is fine) or suppress "
                    "with a reason"))
                continue
            for kind in kinds:
                if kind not in HEALTH_KINDS:
                    out.append(self.finding(
                        ctx, call,
                        f"health kind `{kind}` is not declared in the "
                        f"health-kind registry (analysis/registry.py) — "
                        f"declare it and document it in "
                        f"docs/TELEMETRY.md"))
        return out


@register
class HealthKindDrift(Rule):
    id = "REG004"
    name = "health-kind-drift"
    severity = Severity.WARN
    doc = ("every declared health kind must be emitted somewhere in "
           "hydragnn_tpu/ and documented in docs/TELEMETRY.md")

    def check_project(self, project) -> Iterable[Finding]:
        out: List[Finding] = []
        reg_ctx = next((f for f in project.files
                        if f.rel.endswith("analysis/registry.py")), None)
        if reg_ctx is None:
            return []

        def reg_line(name: str) -> int:
            for i, line in enumerate(reg_ctx.lines, start=1):
                if f'_h("{name}"' in line:
                    return i
            return 1

        emitted: Set[str] = set()
        for f in project.files:
            if not f.rel.startswith("hydragnn_tpu/"):
                continue
            for call in _iter_health_calls(f.tree):
                emitted |= set(_health_kind_literals(call) or ())

        docs = project.read_text("docs/TELEMETRY.md") or ""
        for kind in sorted(HEALTH_KINDS):
            if kind not in emitted:
                out.append(self.finding(
                    reg_ctx, reg_line(kind),
                    f"declared health kind `{kind}` is never emitted — "
                    f"dead schema; delete it from the registry and "
                    f"docs/TELEMETRY.md"))
            if f"`{kind}`" not in docs:
                out.append(self.finding(
                    reg_ctx, reg_line(kind),
                    f"declared health kind `{kind}` is not documented "
                    f"in docs/TELEMETRY.md"))
        return out


# trace-API entry points whose first positional arg is a span name.
# ``span`` is deliberately held to a literal-only check (re.Match.span(1)
# and other unrelated ``.span()`` spellings must not trip the rule);
# ``record_interval``/``comm_region`` are unambiguous and also fail on
# dynamic names the registry cannot see.
_SPAN_CALL_NAMES = ("span", "record_interval", "comm_region")
_SPAN_STRICT_NAMES = ("record_interval", "comm_region")


def _iter_span_calls(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name in _SPAN_CALL_NAMES and node.args:
            yield name, node


@register
class UndeclaredSpanName(Rule):
    id = "REG006"
    name = "undeclared-span-name"
    severity = Severity.ERROR
    doc = ("every span-name literal passed to the trace API (span/"
           "record_interval/comm_region) must be declared in the "
           "span-name registry (analysis/registry.py)")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for fname, call in _iter_span_calls(ctx.tree):
            s = const_str(call.args[0])
            if s is None:
                if fname in _SPAN_STRICT_NAMES:
                    out.append(self.finding(
                        ctx, call,
                        f"{fname}() called with a non-literal span name "
                        f"— the registry rule cannot see it; pass a "
                        f"literal declared in SPAN_NAMES or suppress "
                        f"with a reason"))
                continue
            if s not in SPAN_NAMES:
                out.append(self.finding(
                    ctx, call,
                    f"span name `{s}` is not declared in the span-name "
                    f"registry (hydragnn_tpu/analysis/registry.py) — "
                    f"declare it (name/module/desc) and document it in "
                    f"docs/TELEMETRY.md"))
        return out


# (writer file, writer function, reader file, reader function) pairs for
# the finalize-written config sections.  Writers return a dict literal;
# readers consume keys via `<x>.get("key", ...)` — both key sets must
# match or a finalize-written key is never validated on read (or a read
# key silently has no written-back default).
CONFIG_KEY_SPECS = [
    ("hydragnn_tpu/serve/config.py", "serving_defaults",
     "hydragnn_tpu/serve/config.py", "from_section"),
    ("hydragnn_tpu/resilience/config.py", "resilience_training_defaults",
     "hydragnn_tpu/resilience/config.py", "from_training"),
    ("hydragnn_tpu/config/config.py", "_telemetry_defaults",
     "hydragnn_tpu/telemetry/logger.py", "from_section"),
]


def _function_def(tree: ast.AST, name: str) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _dict_literal_keys(fn: ast.AST) -> Optional[Set[str]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(
                node.value, ast.Dict):
            keys = set()
            for k in node.value.keys:
                s = const_str(k)
                if s is None:
                    return None  # computed keys: not statically checkable
                keys.add(s)
            return keys
    return None


def _get_call_keys(fn: ast.AST) -> Set[str]:
    keys: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args):
            s = const_str(node.args[0])
            # env reads (`os.environ.get("HYDRAGNN_...")`) ride the same
            # .get spelling but are REG001/REG002's territory
            if s is not None and not s.startswith("HYDRAGNN_"):
                keys.add(s)
    return keys


@register
class ConfigKeyDrift(Rule):
    id = "REG005"
    name = "config-key-drift"
    severity = Severity.ERROR
    doc = ("finalize-written config defaults and their readers must "
           "agree key-for-key (every written key validated on read)")

    def check_project(self, project) -> Iterable[Finding]:
        out: List[Finding] = []
        specs = list(CONFIG_KEY_SPECS)
        # fixture support, EXPLICITLY scoped: only files named
        # `reg005_*.py` (this rule's own fixture corpus) self-pair their
        # `*_defaults` writer with their `from_*` reader — a real module
        # that merely happens to define both shapes is never guessed at
        for f in project.files:
            if not os.path.basename(f.rel).startswith("reg005_"):
                continue
            writer = next(
                (n.name for n in ast.walk(f.tree)
                 if isinstance(n, ast.FunctionDef)
                 and n.name.endswith("_defaults")
                 and _dict_literal_keys(n) is not None), None)
            reader = next(
                (n.name for n in ast.walk(f.tree)
                 if isinstance(n, ast.FunctionDef)
                 and n.name.startswith("from_")), None)
            if writer and reader:
                specs.append((f.rel, writer, f.rel, reader))

        for wfile, wfunc, rfile, rfunc in specs:
            wctx = project.by_rel.get(wfile)
            rctx = project.by_rel.get(rfile)
            if wctx is None or rctx is None:
                continue  # partial scans (e.g. --diff on one file)
            wfn = _function_def(wctx.tree, wfunc)
            rfn = _function_def(rctx.tree, rfunc)
            if wfn is None or rfn is None:
                continue
            written = _dict_literal_keys(wfn)
            if written is None:
                continue
            read = _get_call_keys(rfn)
            for key in sorted(written - read):
                out.append(self.finding(
                    wctx, wfn,
                    f"config key `{key}` is written by {wfunc}() but "
                    f"never read/validated by {rfile}:{rfunc}()"))
            for key in sorted(read - written):
                out.append(self.finding(
                    rctx, rfn,
                    f"config key `{key}` is read by {rfunc}() but not "
                    f"written back as a default by {wfile}:{wfunc}()"))
        return out
