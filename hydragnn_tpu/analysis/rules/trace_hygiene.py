"""Trace-hygiene rules: invariants of code that runs under jax tracing.

The bug classes these encode (docs/ANALYSIS.md has the history):

- TRC001: a host-sync call (``.item()``, ``np.asarray``, ``time.time``)
  inside a traced function either fails at trace time or silently bakes
  a trace-time constant into the compiled program.
- TRC002: a Python ``if``/``while`` on a traced argument raises a
  ConcretizationTypeError at trace time — or, with weak typing, forces
  an early concretization sync.
- TRC003: constructing ``jax.jit``/``shard_map`` wrappers inside a loop
  re-traces per iteration — the PR-7 consolidate bug: a fresh jit
  wrapper on the checkpoint path re-traced every leaf on every save,
  inside the SIGTERM grace window.
- TRC004: an argument donated via ``donate_argnums`` is DELETED by the
  call; reading it afterwards fails (or silently reads garbage on some
  backends).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..astutil import (base_name, build_parents, call_name, const_str,
                       walk_skip_nested_functions)
from ..core import Finding, Rule, Severity, register

# callables whose function-valued arguments are traced
JIT_WRAPPERS = {"jit", "pjit", "pmap"}
TRACING_CALLERS = JIT_WRAPPERS | {
    "shard_map", "_shard_map", "pallas_call", "scan", "fori_loop",
    "while_loop", "cond", "switch", "vmap", "grad", "value_and_grad",
    "remat", "checkpoint", "custom_vjp",
}

HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "jax.device_get", "device_get",
}


def _decorator_traces(dec: ast.AST) -> bool:
    """True when a decorator marks the function as traced
    (``@jax.jit``, ``@partial(jax.jit, ...)``, ``@_shard_map(...)``)."""
    if isinstance(dec, ast.Call):
        name = base_name(call_name(dec))
        if name in TRACING_CALLERS:
            return True
        if name == "partial" and dec.args:
            return base_name(dotted_or_none(dec.args[0])) in TRACING_CALLERS
        return False
    return base_name(dotted_or_none(dec)) in TRACING_CALLERS


def dotted_or_none(node: ast.AST) -> Optional[str]:
    from ..astutil import dotted

    return dotted(node)


def _static_names(call_or_dec: Optional[ast.Call],
                  fn: ast.AST) -> Set[str]:
    """Parameter names excluded from tracing: static_argnums/argnames
    (when constant) plus the conventional self/cls."""
    out = {"self", "cls"}
    if call_or_dec is None:
        return out
    posnames = [a.arg for a in getattr(fn.args, "posonlyargs", [])] + \
        [a.arg for a in fn.args.args]
    for kw in call_or_dec.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        vals = []
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = [getattr(e, "value", None) for e in kw.value.elts]
        elif isinstance(kw.value, ast.Constant):
            vals = [kw.value.value]
        for v in vals:
            if isinstance(v, int) and 0 <= v < len(posnames):
                out.add(posnames[v])
            elif isinstance(v, str):
                out.add(v)
    return out


def _collect_traced_functions(tree: ast.AST) -> List[Tuple[ast.AST, Set[str]]]:
    """Find (function node, traced param names) pairs: decorated defs,
    defs/lambdas passed to tracing callers."""
    defs_by_name: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)

    traced: Dict[ast.AST, Set[str]] = {}

    def add(fn: ast.AST, statics: Set[str]) -> None:
        params = [a.arg for a in getattr(fn.args, "posonlyargs", [])] + \
            [a.arg for a in fn.args.args] + \
            [a.arg for a in fn.args.kwonlyargs]
        names = {p for p in params if p not in statics}
        if fn in traced:
            traced[fn] &= names  # keep the intersection when marked twice
        else:
            traced[fn] = names

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _decorator_traces(dec):
                    call = dec if isinstance(dec, ast.Call) else None
                    add(node, _static_names(call, node))
        elif isinstance(node, ast.Call):
            if base_name(call_name(node)) not in TRACING_CALLERS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    add(arg, _static_names(node, arg))
                elif isinstance(arg, ast.Name) and arg.id in defs_by_name:
                    fn = defs_by_name[arg.id]
                    add(fn, _static_names(node, fn))
    return list(traced.items())


@register
class HostSyncInTracedFunction(Rule):
    id = "TRC001"
    name = "host-sync-in-traced-fn"
    severity = Severity.ERROR
    doc = ("no host-sync calls (.item()/np.asarray/time.time/device_get) "
           "inside functions that run under jax tracing")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn, _params in _collect_traced_functions(ctx.tree):
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    if name in HOST_SYNC_CALLS or (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr in HOST_SYNC_ATTRS
                            and not node.args):
                        label = name or f".{node.func.attr}()"
                        out.append(self.finding(
                            ctx, node,
                            f"host-sync call `{label}` inside traced "
                            f"function `{getattr(fn, 'name', '<lambda>')}` "
                            f"— hoist it out of the traced region"))
        return out


def _dynamic_param_refs(test: ast.AST, params: Set[str]) -> List[ast.Name]:
    """Name nodes in a condition that reference traced params in a way
    that concretizes them.  Static accesses (``x.shape``/``x.ndim``/
    ``x.dtype``, ``len(x)``, ``isinstance(x, ...)``, ``x is None``,
    membership tests) are excluded."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
            for op in test.ops):
        return []
    parents = build_parents(test)
    refs = []
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in params):
            continue
        p = parents.get(node)
        if isinstance(p, ast.Attribute) and p.attr in (
                "shape", "ndim", "dtype", "size", "aval", "sharding"):
            continue
        skip = False
        q = node
        while q in parents:
            q = parents[q]
            if isinstance(q, ast.Call) and base_name(call_name(q)) in (
                    "isinstance", "len", "hasattr", "getattr", "callable",
                    "type"):
                skip = True
                break
            if isinstance(q, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                    for op in q.ops):
                skip = True
                break
        if not skip:
            refs.append(node)
    return refs


@register
class BranchOnTracedArgument(Rule):
    id = "TRC002"
    name = "python-branch-on-traced-arg"
    severity = Severity.ERROR
    doc = ("no Python if/while on traced arguments inside traced "
           "functions — use lax.cond/jnp.where")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for fn, params in _collect_traced_functions(ctx.tree):
            if not params:
                continue
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.If, ast.While)):
                        test = node.test
                    elif isinstance(node, ast.IfExp):
                        test = node.test
                    else:
                        continue
                    refs = _dynamic_param_refs(test, params)
                    if refs:
                        names = ", ".join(sorted({r.id for r in refs}))
                        out.append(self.finding(
                            ctx, node,
                            f"Python branch on traced argument(s) "
                            f"`{names}` inside traced function "
                            f"`{getattr(fn, 'name', '<lambda>')}` — "
                            f"use lax.cond/jnp.where or mark the "
                            f"argument static"))
        return out


@register
class JitConstructionInLoop(Rule):
    id = "TRC003"
    name = "jit-construction-in-loop"
    severity = Severity.ERROR
    doc = ("no jax.jit/shard_map/pallas_call wrapper construction inside "
           "a loop — each iteration re-traces (cache the wrapper)")

    _CTORS = {"jit", "pjit", "pmap", "shard_map", "_shard_map",
              "pallas_call"}

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for node in walk_skip_nested_functions(loop):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                bn = base_name(name)
                hit = bn in self._CTORS or (
                    bn == "partial" and node.args
                    and base_name(dotted_or_none(node.args[0]))
                    in self._CTORS)
                if hit:
                    out.append(self.finding(
                        ctx, node,
                        f"`{name}(...)` constructed inside a loop — the "
                        f"wrapper's trace cache is thrown away every "
                        f"iteration (hoist/cache it; the PR-7 "
                        f"re-trace-every-save bug)"))
        return out


@register
class DonatedArgumentReused(Rule):
    id = "TRC004"
    name = "donated-arg-reused"
    severity = Severity.ERROR
    doc = ("an argument donated to a jitted call must not be read after "
           "the call — donation deletes its buffer")

    def check_file(self, ctx) -> Iterable[Finding]:
        out: List[Finding] = []
        for scope in ast.walk(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Module)):
                continue
            # ONLY this scope's own statements: a nested/sibling function
            # body is its own scope (merging them would cross-match
            # same-named variables between unrelated functions)
            nodes = []
            for s in scope.body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                nodes.append(s)
                nodes.extend(walk_skip_nested_functions(s))
            donating: Dict[str, List[int]] = {}
            for stmt in nodes:
                if not isinstance(stmt, ast.Assign):
                    continue
                v = stmt.value
                if not isinstance(v, ast.Call):
                    continue
                donate = [kw for kw in v.keywords
                          if kw.arg in ("donate_argnums",
                                        "donate_argnames")]
                if not donate or base_name(call_name(v)) not in (
                        "jit", "pjit", "pmap"):
                    continue
                nums: List[int] = []
                kv = donate[0].value
                if isinstance(kv, ast.Constant) and isinstance(
                        kv.value, int):
                    nums = [kv.value]
                elif isinstance(kv, (ast.Tuple, ast.List)):
                    nums = [e.value for e in kv.elts
                            if isinstance(e, ast.Constant)
                            and isinstance(e.value, int)]
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and nums:
                        donating[tgt.id] = nums
            if donating:
                out.extend(self._check_scope(ctx, nodes, donating))
        return out

    def _check_scope(self, ctx, nodes, donating) -> List[Finding]:
        out: List[Finding] = []
        # (lineno, donated-name) for every donating call site
        donated_at: List[Tuple[int, str]] = []
        for node in nodes:
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name) and node.func.id in donating:
                for pos in donating[node.func.id]:
                    if pos < len(node.args) and isinstance(
                            node.args[pos], ast.Name):
                        donated_at.append(
                            (node.lineno, node.args[pos].id))
        for call_line, var in donated_at:
            restored = [n.lineno for n in nodes
                        if isinstance(n, (ast.Assign, ast.AugAssign))
                        and any(isinstance(t, ast.Name) and t.id == var
                                for t in (n.targets if isinstance(
                                    n, ast.Assign) else [n.target]))]
            for node in nodes:
                if (isinstance(node, ast.Name) and node.id == var
                        and isinstance(node.ctx, ast.Load)
                        and node.lineno > call_line
                        and not any(call_line <= r <= node.lineno
                                    for r in restored)):
                    out.append(self.finding(
                        ctx, node,
                        f"`{var}` was donated to a jitted call at line "
                        f"{call_line} and read again here — its buffer "
                        f"is deleted by donation"))
                    break  # one finding per donated call is enough
        return out
