"""Run rules over a project; apply suppressions, baseline, diff scoping.

Exit-code contract (enforced by tools/graftlint.py): 0 = no unsuppressed
findings, 1 = findings, 2 = usage/internal error.  The baseline file
grandfathers provably-benign findings; every entry needs a one-line
``justification`` (tests/test_lint.py asserts that) so "baseline it"
never becomes "ignore it silently".
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Set

from .core import Finding, Rule, all_rules, is_suppressed, normalize_code
from .project import Project


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    code: str  # normalized source line
    justification: str = ""

    def matches(self, f: Finding) -> bool:
        return (self.rule == f.rule and self.path == f.path
                and self.code == normalize_code(f.code))


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # unsuppressed, unbaselined — these fail
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[BaselineEntry]
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings


def load_baseline(path: str) -> List[BaselineEntry]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return []
    return [BaselineEntry(rule=e["rule"], path=e["path"],
                          code=normalize_code(e.get("code", "")),
                          justification=e.get("justification", ""))
            for e in data.get("entries", [])]


def write_baseline(path: str, findings: Sequence[Finding],
                   keep: Sequence[BaselineEntry] = ()) -> None:
    """Write current findings as baseline entries.  Existing justified
    entries are kept verbatim ONLY while they still match a finding —
    stale entries are shed here, so ``--write-baseline`` is the
    documented remedy for a stale-baseline gate failure."""
    entries = []
    seen = set()
    for e in keep:
        if not any(e.matches(f) for f in findings):
            continue  # stale: the finding is gone
        key = (e.rule, e.path, e.code)
        if key not in seen:
            seen.add(key)
            entries.append(dataclasses.asdict(e))
    for f in findings:
        key = (f.rule, f.path, normalize_code(f.code))
        if key in seen:
            continue
        seen.add(key)
        entries.append({"rule": f.rule, "path": f.path,
                        "code": normalize_code(f.code),
                        "justification": "TODO: justify or fix"})
    entries.sort(key=lambda e: (e["path"], e["rule"], e["code"]))
    # the atomic idiom ROB002 demands of everyone else (stdlib-only
    # spelling: this package must not import hydragnn_tpu.resilience)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def changed_lines_from_git(root: str, ref: str) -> Dict[str, Set[int]]:
    """Map repo-relative path -> changed line numbers vs ``ref``
    (``git diff -U0``); used by ``--diff`` to scope findings to the PR."""
    out = subprocess.run(
        ["git", "diff", "-U0", ref, "--", "*.py"],
        cwd=root, capture_output=True, text=True, check=True).stdout
    changed: Dict[str, Set[int]] = {}
    cur: Optional[str] = None
    for line in out.splitlines():
        if line.startswith("+++ b/"):
            cur = line[6:]
            changed.setdefault(cur, set())
        elif line.startswith("@@") and cur is not None:
            # @@ -a,b +c,d @@
            plus = line.split("+", 1)[1].split(" ", 1)[0]
            start, _, count = plus.partition(",")
            n = int(count) if count else 1
            changed[cur].update(range(int(start), int(start) + max(n, 1)))
    return changed


def run_project(project: Project,
                rules: Optional[Sequence[Rule]] = None,
                baseline: Sequence[BaselineEntry] = (),
                changed: Optional[Dict[str, Set[int]]] = None) -> LintResult:
    rules = list(rules) if rules is not None else all_rules()
    raw: List[Finding] = []
    for ctx in project.files:
        for rule in rules:
            for f in rule.check_file(ctx):
                raw.append(f)
    for rule in rules:
        for f in rule.check_project(project):
            raw.append(f)
    raw.sort(key=lambda f: (f.path, f.line, f.rule))

    suppressed: List[Finding] = []
    kept: List[Finding] = []
    for f in raw:
        ctx = project.by_rel.get(f.path)
        if ctx is not None and is_suppressed(
                f, ctx.suppressed_lines, ctx.suppressed_file):
            suppressed.append(f)
        else:
            kept.append(f)

    baselined: List[Finding] = []
    matched: Set[int] = set()
    final: List[Finding] = []
    for f in kept:
        hit = None
        for i, e in enumerate(baseline):
            if e.matches(f):
                hit = i
                break
        if hit is not None:
            matched.add(hit)
            baselined.append(f)
        else:
            final.append(f)
    # an entry is stale only when its file was actually scanned — a
    # subset run (one path, --diff) must not condemn out-of-scope entries
    stale = [e for i, e in enumerate(baseline)
             if i not in matched and e.path in project.by_rel]

    if changed is not None:
        final = [f for f in final
                 if f.line in changed.get(f.path, set())]

    return LintResult(findings=final, suppressed=suppressed,
                      baselined=baselined, stale_baseline=stale,
                      files_scanned=len(project.files))
