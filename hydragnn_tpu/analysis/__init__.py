"""graftlint: project-invariant static analysis (stdlib ``ast`` only).

Every rule here encodes an invariant this repo has already paid for in a
review-hardening pass — uncached jit wrappers re-traced on the save path
(PR 7), shared mutable state written outside its lock in the threaded
serve layer (PRs 4/5/6/8), silently-swallowed exceptions, and drift
between code and its contracts (env knobs vs config/docs, health-event
kinds vs docs/TELEMETRY.md).  docs/ANALYSIS.md is the rule catalog;
``tools/graftlint.py`` is the CLI; ``tests/test_lint.py`` is the tier-1
gate (zero unsuppressed findings over hydragnn_tpu/, tools/, tests/).

IMPORTANT: this package must stay importable WITHOUT jax/flax/numpy —
the CLI loads it standalone (importlib spec, bypassing the heavyweight
``hydragnn_tpu.__init__``) so a lint pass costs milliseconds, not a jax
import.  Use only stdlib modules and RELATIVE imports here.
"""

from .core import (  # noqa: F401
    Finding,
    Rule,
    Severity,
    all_rules,
    get_rule,
    register,
)
from .project import FileCtx, Project, collect_project  # noqa: F401
from .runner import (  # noqa: F401
    LintResult,
    load_baseline,
    run_project,
    write_baseline,
)
from .registry import HEALTH_KINDS, KNOBS, emit_knob_docs  # noqa: F401

# importing the rule modules registers every rule
from .rules import lock_coverage  # noqa: F401
from .rules import registries  # noqa: F401
from .rules import robustness  # noqa: F401
from .rules import trace_hygiene  # noqa: F401
