"""File collection and parsing: the project model rules run against."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set

from .core import parse_suppressions

# Directories never scanned.  `fixtures` holds the rule test corpus —
# files that VIOLATE invariants on purpose.
EXCLUDE_DIRS = {"__pycache__", "fixtures", ".git", "node_modules"}


@dataclasses.dataclass
class FileCtx:
    path: str  # absolute
    rel: str  # repo-relative, posix
    src: str
    lines: List[str]
    tree: ast.AST
    suppressed_lines: Dict[int, Set[str]]
    suppressed_file: Set[str]


class Project:
    """Parsed files plus access to non-Python artifacts (docs)."""

    def __init__(self, root: str, files: List[FileCtx]):
        self.root = root
        self.files = files
        self.by_rel = {f.rel: f for f in files}

    def read_text(self, rel: str) -> Optional[str]:
        p = os.path.join(self.root, rel)
        if not os.path.exists(p):
            return None
        with open(p, encoding="utf-8") as fh:
            return fh.read()


def parse_file(path: str, root: str) -> FileCtx:
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)  # SyntaxError propagates
    per_line, file_wide = parse_suppressions(lines)
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return FileCtx(path=path, rel=rel, src=src, lines=lines, tree=tree,
                   suppressed_lines=per_line, suppressed_file=file_wide)


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.abspath(os.path.join(dirpath, fn)))
    return out


def collect_project(root: str, paths: Sequence[str]) -> Project:
    files = [parse_file(p, root) for p in iter_python_files(paths)]
    return Project(root=os.path.abspath(root), files=files)
