"""The cross-artifact registries: env knobs and health-event kinds.

These are the single declared sources the registry rules check code and
docs against:

- every ``HYDRAGNN_*`` string in code must name a knob declared in
  :data:`KNOBS` (rule REG001), every declared knob must still be read
  somewhere and appear in docs/KNOBS.md (REG002), and docs/KNOBS.md is
  GENERATED from this table (``tools/graftlint.py --emit-docs``) so it
  cannot drift;
- every literal ``MetricsLogger.health(kind=...)`` emitted by the
  package must name a kind declared in :data:`HEALTH_KINDS` (REG003),
  and every declared kind must be emitted somewhere and documented in
  docs/TELEMETRY.md (REG004);
- every literal span name passed to the trace API (``span``,
  ``record_interval``, ``comm_region`` calls) must name a span declared
  in :data:`SPAN_NAMES` (REG006) — the flight recorder's waterfall and
  percentile views group by these names, so an undeclared ad-hoc name is
  a span nobody's dashboards will ever aggregate.

Adding a knob, health kind, or span therefore means: declare it here,
use it, document it — the lint gate fails on any one of the three
missing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str  # HYDRAGNN_* spelling
    config: str  # config-file spelling ("" = env-only knob)
    default: str  # effective default, as documented
    module: str  # owning module (repo-relative)
    desc: str  # one-line effect


def _k(name, config, default, module, desc):
    return Knob(name=name, config=config, default=default, module=module,
                desc=desc)


_KNOB_LIST = [
    # -- data pipeline ----------------------------------------------------
    _k("HYDRAGNN_NUM_WORKERS", "", "2",
       "hydragnn_tpu/data/prefetch.py",
       "prefetch worker thread count (dataloader auto-pipeline may set it)"),
    _k("HYDRAGNN_COLLATE_PROCS", "", "4",
       "hydragnn_tpu/data/prefetch.py",
       "collate process-pool size (0 = in-thread collation)"),
    _k("HYDRAGNN_COLLATE_SHM", "", "1",
       "hydragnn_tpu/data/prefetch.py",
       "ship collated batches via shared memory (0 = pickle over pipe)"),
    _k("HYDRAGNN_AFFINITY", "", "0",
       "hydragnn_tpu/data/prefetch.py",
       "pin prefetch/collate workers to CPU cores"),
    _k("HYDRAGNN_AFFINITY_WIDTH", "", "2",
       "hydragnn_tpu/data/prefetch.py",
       "cores per pinned worker"),
    _k("HYDRAGNN_AFFINITY_OFFSET", "", "0",
       "hydragnn_tpu/data/prefetch.py",
       "first core index for worker pinning"),
    _k("HYDRAGNN_NUM_BUCKETS", "", "0 (auto)",
       "hydragnn_tpu/data/dataloader.py",
       "PadSpec bucket-ladder size for the training loader"),
    _k("HYDRAGNN_USE_VARIABLE_GRAPH_SIZE", "", "0",
       "hydragnn_tpu/data/dataloader.py",
       "legacy spelling: 4-bucket ladder for variable-size datasets"),
    _k("HYDRAGNN_RESIDENT_DATASET", "", "auto",
       "hydragnn_tpu/train/trainer.py",
       "keep collated batches device-resident across epochs"),
    _k("HYDRAGNN_RESIDENT_BUDGET_MB", "", "6144",
       "hydragnn_tpu/train/trainer.py",
       "HBM budget the auto-pipeline sizes the resident set against"),
    _k("HYDRAGNN_DEVICE_PREFETCH", "", "0",
       "hydragnn_tpu/train/trainer.py",
       "overlap H2D transfer one batch ahead"),
    # -- streaming data plane (data/stream/) ------------------------------
    _k("HYDRAGNN_STREAM", "Dataset.stream", "0",
       "hydragnn_tpu/data/stream/config.py",
       "stream gpack samples with bounded residency instead of loading "
       "the dataset in memory"),
    _k("HYDRAGNN_STREAM_PATH", "Dataset.stream_path", "",
       "hydragnn_tpu/data/stream/config.py",
       "gpack store path the streaming loader reads from"),
    _k("HYDRAGNN_STREAM_WINDOW", "Dataset.stream_window", "1024",
       "hydragnn_tpu/data/stream/config.py",
       "decoded-sample residency window W (peak ~ W + batch_size samples)"),
    _k("HYDRAGNN_STREAM_ORDER", "Dataset.stream_order", "global",
       "hydragnn_tpu/data/stream/config.py",
       "epoch order: global (bit-parity with in-memory) | sequential | "
       "block (locality shuffle)"),
    _k("HYDRAGNN_STREAM_BLOCK", "Dataset.stream_block", "2048",
       "hydragnn_tpu/data/stream/config.py",
       "block size for stream_order=block"),
    _k("HYDRAGNN_STREAM_TAIL", "Dataset.stream_tail", "",
       "hydragnn_tpu/data/stream/config.py",
       "ingest dir to tail: re-reads the manifest each epoch and trains "
       "on newly sealed segments (implies stream)"),
    _k("HYDRAGNN_STREAM_OPEN_RETRIES", "Dataset.stream_open_retries", "2",
       "hydragnn_tpu/data/stream/config.py",
       "store-open retry attempts (bounded backoff) before the "
       "in-memory fallback"),
    # -- trainer / pipeline ----------------------------------------------
    _k("HYDRAGNN_AUTO_PIPELINE", "", "1",
       "hydragnn_tpu/train/trainer.py",
       "derive pipeline knobs (scan K, resident, workers) automatically"),
    _k("HYDRAGNN_STEPS_PER_DISPATCH", "", "auto",
       "hydragnn_tpu/train/trainer.py",
       "optimizer steps folded into one scanned dispatch"),
    _k("HYDRAGNN_MAX_NUM_BATCH", "", "0 (all)",
       "hydragnn_tpu/train/trainer.py",
       "truncate each epoch to N batches (smoke runs)"),
    _k("HYDRAGNN_VALTEST", "", "1",
       "hydragnn_tpu/train/trainer.py",
       "run the val/test phases each epoch (0 = train only)"),
    _k("HYDRAGNN_DUMP_TESTDATA", "", "0",
       "hydragnn_tpu/train/trainer.py",
       "dump per-sample test predictions for postprocessing"),
    _k("HYDRAGNN_NUM_SLICES", "", "0",
       "hydragnn_tpu/train/trainer.py",
       "force a (dcn, ici) multi-slice mesh shape"),
    _k("HYDRAGNN_BN_MOMENTUM", "", "model default",
       "hydragnn_tpu/models/layers.py",
       "BatchNorm momentum override"),
    _k("HYDRAGNN_TRAIN_DTYPE", "Training.train_dtype_policy", "f32",
       "hydragnn_tpu/train/trainer.py",
       "train-step compute dtype: f32 | bf16 (f32 master state; "
       "step-0 golden gate, loud f32 fallback)"),
    # -- parallel / distributed ------------------------------------------
    _k("HYDRAGNN_MASTER_ADDR", "", "127.0.0.1",
       "hydragnn_tpu/parallel/mesh.py",
       "jax.distributed coordinator address"),
    _k("HYDRAGNN_MASTER_PORT", "", "8889",
       "hydragnn_tpu/parallel/mesh.py",
       "jax.distributed coordinator port"),
    _k("HYDRAGNN_ZERO", "Training.zero_stage", "0",
       "hydragnn_tpu/parallel/zero.py",
       "ZeRO stage (0|1|2); env wins over the config stage"),
    _k("HYDRAGNN_GRAPH_SHARD", "Training.graph_shard", "off",
       "hydragnn_tpu/graph/partition.py",
       "graph-sharding backend: off | halo (production) | gspmd (baseline)"),
    _k("HYDRAGNN_GRAPH_SHARD_METHOD", "Training.graph_shard_method", "sfc",
       "hydragnn_tpu/graph/partition.py",
       "partition node order: sfc (Morton) | bfs | block"),
    _k("HYDRAGNN_GRAPH_SHARD_HOPS", "Training.graph_shard_hops",
       "0 (num_conv_layers)", "hydragnn_tpu/graph/partition.py",
       "halo depth in hops (0 = the model's conv depth)"),
    _k("HYDRAGNN_GRAPH_SHARD_HALO_MAX", "Training.graph_shard_halo_max",
       "0 (auto bucket)", "hydragnn_tpu/graph/partition.py",
       "per-peer halo row cap; exceeding it raises (never truncates)"),
    # -- kernels / fused-path gates --------------------------------------
    _k("HYDRAGNN_AGGR_BACKEND", "", "scatter",
       "hydragnn_tpu/ops/aggregate.py",
       "aggregation backend: fused (Pallas) | scatter (XLA)"),
    _k("HYDRAGNN_SCF_FUSED", "", "auto",
       "hydragnn_tpu/models/schnet.py",
       "SchNet fused CFConv pipeline gate"),
    _k("HYDRAGNN_SCF_BE_R", "", "auto",
       "hydragnn_tpu/ops/scf_mp.py",
       "fused-CFConv edge-block residency override"),
    _k("HYDRAGNN_GAT_FUSED", "", "auto",
       "hydragnn_tpu/models/gat.py",
       "GAT fused edge-attention gate"),
    _k("HYDRAGNN_EGCL_FUSED", "", "auto",
       "hydragnn_tpu/models/egnn.py",
       "EGNN fused EGCL interaction-block gate (1/0 forces, subject "
       "to the kernel's structural width limits)"),
    _k("HYDRAGNN_CGCNN_FUSED", "", "auto",
       "hydragnn_tpu/models/cgcnn.py",
       "CGCNN fused gated-sum block gate (1/0 forces, subject to the "
       "kernel's structural width limits)"),
    _k("HYDRAGNN_DN_TRI_OFF", "", "0",
       "hydragnn_tpu/models/dimenet.py",
       "disable the DimeNet fused-triplet kernel"),
    _k("HYDRAGNN_DIMENET_FUSED_TRI", "", "0",
       "hydragnn_tpu/models/dimenet.py",
       "force the fused-triplet kernel past the dataset-bound gate"),
    _k("HYDRAGNN_DN_ROW_MLP_OFF", "", "0",
       "hydragnn_tpu/models/dimenet.py",
       "disable the fused residual-MLP tail"),
    _k("HYDRAGNN_DIMENET_REMAT", "", "0",
       "hydragnn_tpu/models/dimenet.py",
       "remat DimeNet interaction blocks"),
    # -- telemetry --------------------------------------------------------
    _k("HYDRAGNN_TELEMETRY", "Telemetry.enable", "0",
       "hydragnn_tpu/telemetry/logger.py",
       "enable the telemetry subsystem"),
    _k("HYDRAGNN_TELEMETRY_SINKS", "Telemetry.sinks", "jsonl,stdout",
       "hydragnn_tpu/telemetry/logger.py",
       "comma list of sinks (jsonl,csv,stdout,tensorboard)"),
    _k("HYDRAGNN_TELEMETRY_DIR", "Telemetry.dir",
       "logs/<run>/telemetry", "hydragnn_tpu/telemetry/logger.py",
       "telemetry output directory"),
    _k("HYDRAGNN_TELEMETRY_HEARTBEAT", "Telemetry.heartbeat", "50",
       "hydragnn_tpu/telemetry/logger.py",
       "stdout heartbeat cadence (steps)"),
    _k("HYDRAGNN_TELEMETRY_SYNC", "Telemetry.sync_steps", "0",
       "hydragnn_tpu/telemetry/logger.py",
       "block per step for true device step times"),
    _k("HYDRAGNN_PEAK_FLOPS", "", "197e12 (v5e bf16)",
       "hydragnn_tpu/telemetry/flops.py",
       "MFU peak-flops basis override"),
    _k("HYDRAGNN_TRACE", "Telemetry.trace", "0",
       "hydragnn_tpu/telemetry/trace.py",
       "flight recorder: record request/train-phase spans (JSONL "
       "event=span; adds one device sync per traced train step)"),
    _k("HYDRAGNN_TRACE_RING", "Telemetry.trace_ring", "512",
       "hydragnn_tpu/telemetry/trace.py",
       "in-memory span ring capacity (JSONL stream is unbounded)"),
    _k("HYDRAGNN_COMMS_PROBE", "", "0",
       "hydragnn_tpu/telemetry/comms.py",
       "A/B comm-vs-compute probe at train start (mesh DP path); split "
       "lands in the manifest `comms` block"),
    _k("HYDRAGNN_SLO_P99_MS", "", "0 (off)",
       "hydragnn_tpu/telemetry/slo.py",
       "serving SLO: p99 latency target the burn-rate monitor checks"),
    _k("HYDRAGNN_SLO_SHED_BUDGET", "", "0.05",
       "hydragnn_tpu/telemetry/slo.py",
       "serving SLO: tolerated shed/error ratio (fraction of requests)"),
    _k("HYDRAGNN_SLO_WINDOW_S", "", "60",
       "hydragnn_tpu/telemetry/slo.py",
       "burn-rate monitor sliding-window length"),
    _k("HYDRAGNN_SLO_BURN", "", "2.0",
       "hydragnn_tpu/telemetry/slo.py",
       "burn-rate multiple of the shed budget that fires `slo_burn`"),
    # -- profiler (utils/profile.py env overlay) --------------------------
    _k("HYDRAGNN_PROFILE", "Profile.enable", "0",
       "hydragnn_tpu/utils/profile.py",
       "capture a jax.profiler device trace on the step schedule"),
    _k("HYDRAGNN_PROFILE_WAIT", "Profile.wait", "5",
       "hydragnn_tpu/utils/profile.py",
       "profiler schedule: steps to skip before warmup"),
    _k("HYDRAGNN_PROFILE_WARMUP", "Profile.warmup", "3",
       "hydragnn_tpu/utils/profile.py",
       "profiler schedule: warmup steps before the trace starts"),
    _k("HYDRAGNN_PROFILE_ACTIVE", "Profile.active", "3",
       "hydragnn_tpu/utils/profile.py",
       "profiler schedule: traced steps"),
    _k("HYDRAGNN_PROFILE_DIR", "Profile.trace_dir",
       "logs/<run>/trace", "hydragnn_tpu/utils/profile.py",
       "device-trace output directory"),
    # -- resilience (Training section) -----------------------------------
    _k("HYDRAGNN_NONFINITE_GUARD", "Training.nonfinite_guard", "0",
       "hydragnn_tpu/resilience/config.py",
       "in-jit non-finite step guard (skip bad steps)"),
    _k("HYDRAGNN_GUARD_MAX_BAD", "Training.guard_max_consecutive", "5",
       "hydragnn_tpu/resilience/config.py",
       "consecutive skipped steps before NonFiniteTrainingError"),
    _k("HYDRAGNN_GUARD_POLL", "Training.guard_poll_every", "8",
       "hydragnn_tpu/resilience/config.py",
       "guard-monitor poll cadence (batches)"),
    _k("HYDRAGNN_PREEMPT", "Training.preemption", "1",
       "hydragnn_tpu/resilience/config.py",
       "SIGTERM/SIGINT preemption-aware checkpointing"),
    _k("HYDRAGNN_PREEMPT_SYNC", "Training.preempt_sync_every", "8",
       "hydragnn_tpu/resilience/config.py",
       "multi-host preemption-agreement cadence (polls)"),
    _k("HYDRAGNN_CKPT_RETRIES", "Training.ckpt_retries", "3",
       "hydragnn_tpu/resilience/config.py",
       "checkpoint-write retry attempts"),
    _k("HYDRAGNN_CKPT_BACKOFF", "Training.ckpt_backoff", "0.5",
       "hydragnn_tpu/resilience/config.py",
       "checkpoint retry backoff (seconds, doubling)"),
    _k("HYDRAGNN_ELASTIC_RESUME", "Training.elastic_resume", "strict",
       "hydragnn_tpu/resilience/elastic.py",
       "world-shape-mismatch resume policy: strict refuses loudly, "
       "epoch admits the resize at an epoch boundary"),
    # -- chaos (test-only fault injection) -------------------------------
    _k("HYDRAGNN_CHAOS_NAN_STEP", "Training.Chaos.nan_step", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "inject NaN loss at step spec k|k1,k2|k+"),
    _k("HYDRAGNN_CHAOS_PREEMPT_STEP", "Training.Chaos.preempt_step", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "inject a preemption signal at step k"),
    _k("HYDRAGNN_CHAOS_CKPT_FAILS", "Training.Chaos.ckpt_fails", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "fail the first N checkpoint writes"),
    _k("HYDRAGNN_CHAOS_ELASTIC", "Training.Chaos.elastic", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "force an elastic resize of ±k hosts at an epoch boundary "
       "(epoch:±k | e:±k)"),
    _k("HYDRAGNN_CHAOS_SERVE_PREDICT_MS", "Serving.Chaos.predict_ms",
       "off", "hydragnn_tpu/resilience/chaos.py",
       "inject predict latency (ms|ms@k+)"),
    _k("HYDRAGNN_CHAOS_SERVE_FAIL_STEP", "Serving.Chaos.fail_step", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "fail predict flushes at flush spec k|k1,k2|k+"),
    _k("HYDRAGNN_CHAOS_SERVE_RELOAD_CORRUPT",
       "Serving.Chaos.reload_corrupt", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "NaN-corrupt the next N reload candidates"),
    _k("HYDRAGNN_CHAOS_REPLICA_KILL", "Serving.FleetChaos.kill", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "kill replica at probe tick spec tick[:replica]|tick+"),
    _k("HYDRAGNN_CHAOS_REPLICA_HANG", "Serving.FleetChaos.hang", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "wedge a replica's predict at probe tick spec"),
    _k("HYDRAGNN_CHAOS_REPLICA_FLAP", "Serving.FleetChaos.flap", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "kill the target at EVERY armed tick (crash loop)"),
    _k("HYDRAGNN_CHAOS_TENANT_HOT", "Serving.FleetChaos.tenant_hot", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "mark a tenant hot at probe tick spec tick[:tenant]|tick+ "
       "(router sheds it 429)"),
    _k("HYDRAGNN_CHAOS_SCALE_FAIL", "Serving.FleetChaos.scale_fail", "off",
       "hydragnn_tpu/resilience/chaos.py",
       "make the next scale-up's fresh replica die at probe tick spec"),
    # -- serving ----------------------------------------------------------
    _k("HYDRAGNN_SERVE_BUCKETS", "Serving.buckets", "1,4,16",
       "hydragnn_tpu/serve/config.py",
       "batch-capacity bucket ladder (comma list, ascending)"),
    _k("HYDRAGNN_SERVE_MAX_NODES", "Serving.max_nodes_per_graph", "0",
       "hydragnn_tpu/serve/config.py",
       "per-graph worst-case nodes (sizes bucket PadSpecs)"),
    _k("HYDRAGNN_SERVE_MAX_EDGES", "Serving.max_edges_per_graph", "0",
       "hydragnn_tpu/serve/config.py",
       "per-graph worst-case edges (sizes bucket PadSpecs)"),
    _k("HYDRAGNN_SERVE_EDGE_NORM", "Serving.edge_length_norm", "0.0",
       "hydragnn_tpu/serve/config.py",
       "edge-length normalization constant (training provenance)"),
    _k("HYDRAGNN_SERVE_MAX_WAIT_MS", "Serving.max_wait_ms", "20",
       "hydragnn_tpu/serve/config.py",
       "micro-batcher deadline-flush budget"),
    _k("HYDRAGNN_SERVE_QUEUE", "Serving.max_queue", "1024",
       "hydragnn_tpu/serve/config.py",
       "bounded request-queue capacity"),
    _k("HYDRAGNN_SERVE_HOST", "Serving.host", "127.0.0.1",
       "hydragnn_tpu/serve/config.py", "HTTP bind host"),
    _k("HYDRAGNN_SERVE_PORT", "Serving.port", "8808",
       "hydragnn_tpu/serve/config.py", "HTTP bind port (0 = ephemeral)"),
    _k("HYDRAGNN_SERVE_DRAIN_S", "Serving.drain_timeout_s", "10",
       "hydragnn_tpu/serve/config.py",
       "graceful-shutdown queue-drain budget"),
    _k("HYDRAGNN_SERVE_DEADLINE_MS", "Serving.request_deadline_ms",
       "10000", "hydragnn_tpu/serve/config.py",
       "default per-request deadline (queue wait + service)"),
    _k("HYDRAGNN_SERVE_PREDICT_TIMEOUT_S", "Serving.predict_timeout_s",
       "30", "hydragnn_tpu/serve/config.py",
       "predict watchdog (flush exceeding it fails, 504)"),
    _k("HYDRAGNN_SERVE_BREAKER_THRESHOLD", "Serving.breaker_threshold",
       "5", "hydragnn_tpu/serve/config.py",
       "consecutive flush failures that trip the breaker (0 = off)"),
    _k("HYDRAGNN_SERVE_BREAKER_COOLDOWN_S", "Serving.breaker_cooldown_s",
       "5", "hydragnn_tpu/serve/config.py",
       "breaker open -> half-open probe delay"),
    _k("HYDRAGNN_SERVE_RELOAD_WATCH", "Serving.reload_watch_path", "",
       "hydragnn_tpu/serve/config.py",
       "checkpoint file to hot-reload on mtime change"),
    _k("HYDRAGNN_SERVE_RELOAD_WATCH_S", "Serving.reload_watch_s", "0",
       "hydragnn_tpu/serve/config.py",
       "reload-watch poll interval (0 = off)"),
    _k("HYDRAGNN_SERVE_RELOAD_ROOT", "Serving.reload_root", "",
       "hydragnn_tpu/serve/config.py",
       "allowlisted checkpoint dir for non-loopback POST /reload"),
    _k("HYDRAGNN_SERVE_QUANT_POLICY", "Serving.quant_policy", "f32",
       "hydragnn_tpu/serve/config.py",
       "inference dtype policy: f32 | bf16 | int8"),
    _k("HYDRAGNN_SERVE_QUANT_TOL", "Serving.quant_tolerance", "0.05",
       "hydragnn_tpu/serve/config.py",
       "max golden-batch drift a quant policy may introduce"),
    _k("HYDRAGNN_SERVE_FLEET", "Serving.fleet_replicas", "0",
       "hydragnn_tpu/serve/config.py",
       "replica count behind the failover router (0 = single server)"),
    _k("HYDRAGNN_SERVE_FLEET_INPROCESS", "Serving.fleet_inprocess", "0",
       "hydragnn_tpu/serve/config.py",
       "thread replicas in-process (shared compile cache)"),
    _k("HYDRAGNN_SERVE_FLEET_PROBE_S", "Serving.fleet_probe_s", "1",
       "hydragnn_tpu/serve/config.py",
       "supervisor health-probe interval"),
    _k("HYDRAGNN_SERVE_FLEET_BACKOFF_S",
       "Serving.fleet_restart_backoff_s", "1",
       "hydragnn_tpu/serve/config.py",
       "replica restart backoff base (doubles per restart)"),
    _k("HYDRAGNN_SERVE_FLEET_BACKOFF_MAX_S",
       "Serving.fleet_restart_backoff_max_s", "30",
       "hydragnn_tpu/serve/config.py", "replica restart backoff cap"),
    _k("HYDRAGNN_SERVE_FLEET_MAX_RESTARTS", "Serving.fleet_max_restarts",
       "5", "hydragnn_tpu/serve/config.py",
       "restart-storm cap per window (exceeded -> FAILED)"),
    _k("HYDRAGNN_SERVE_FLEET_RESTART_WINDOW_S",
       "Serving.fleet_restart_window_s", "300",
       "hydragnn_tpu/serve/config.py", "restart-storm window"),
    _k("HYDRAGNN_SERVE_FLEET_DRAIN_S", "Serving.fleet_drain_timeout_s",
       "10", "hydragnn_tpu/serve/config.py",
       "drain-and-replace in-flight budget"),
    _k("HYDRAGNN_SERVE_FLEET_STARTUP_S",
       "Serving.fleet_startup_timeout_s", "300",
       "hydragnn_tpu/serve/config.py",
       "subprocess replica first-/healthz budget"),
    _k("HYDRAGNN_SERVE_FLEET_QUORUM", "Serving.fleet_quorum",
       "0 (majority)", "hydragnn_tpu/serve/config.py",
       "live replicas below this -> fleet_degraded"),
    _k("HYDRAGNN_SERVE_FLEET_MIN", "Serving.fleet_min_replicas", "1",
       "hydragnn_tpu/serve/config.py",
       "autoscaler floor: scale-down never goes below this"),
    _k("HYDRAGNN_SERVE_FLEET_MAX", "Serving.fleet_max_replicas", "0",
       "hydragnn_tpu/serve/config.py",
       "autoscaler ceiling (0 = closed-loop autoscaling off)"),
    _k("HYDRAGNN_SERVE_AUTOSCALE_UP_FRAC", "Serving.autoscale_up_frac",
       "0.5", "hydragnn_tpu/serve/config.py",
       "scale up when est queue wait exceeds this fraction of the "
       "request deadline"),
    _k("HYDRAGNN_SERVE_AUTOSCALE_UP_TICKS", "Serving.autoscale_up_ticks",
       "3", "hydragnn_tpu/serve/config.py",
       "consecutive hot probe ticks before a scale-up (hysteresis)"),
    _k("HYDRAGNN_SERVE_AUTOSCALE_QUIET_S", "Serving.autoscale_quiet_s",
       "60", "hydragnn_tpu/serve/config.py",
       "sustained empty-queue window before a zero-drop scale-down"),
    _k("HYDRAGNN_SERVE_AUTOSCALE_COOLDOWN_S",
       "Serving.autoscale_cooldown_s", "30",
       "hydragnn_tpu/serve/config.py",
       "minimum spacing between scale decisions"),
    _k("HYDRAGNN_SERVE_MAX_TENANTS", "Serving.max_tenants", "4",
       "hydragnn_tpu/serve/config.py",
       "resident tenant engines per replica incl. default (LRU beyond)"),
    _k("HYDRAGNN_SERVE_TENANT_BUDGET_FRAC", "Serving.tenant_budget_frac",
       "0", "hydragnn_tpu/serve/config.py",
       "per-tenant outstanding cap as a fraction of fleet drain "
       "capacity (0 = budgets off)"),
    _k("HYDRAGNN_SERVE_MAX_EXECUTABLES", "Serving.max_resident_executables",
       "0", "hydragnn_tpu/serve/config.py",
       "engine AOT-executable LRU cap (0 = unbounded)"),
    # -- misc -------------------------------------------------------------
    _k("HYDRAGNN_SYSTEM", "", "",
       "hydragnn_tpu/hpo.py",
       "HPC system name for HPO launch templates"),
    _k("HYDRAGNN_TEST_SCRATCH", "", "/tmp/hydragnn_tpu_tests",
       "tests/conftest.py", "test scratch directory"),
]

KNOBS: Dict[str, Knob] = {k.name: k for k in _KNOB_LIST}


@dataclasses.dataclass(frozen=True)
class HealthKind:
    name: str
    module: str  # emitting module (repo-relative)
    desc: str


def _h(name, module, desc):
    return HealthKind(name=name, module=module, desc=desc)


_HEALTH_LIST = [
    # training resilience (docs/TELEMETRY.md "health — resilience events")
    _h("step_skipped", "hydragnn_tpu/telemetry/logger.py",
       "in-jit non-finite guard suppressed update(s)"),
    _h("preempt_save", "hydragnn_tpu/train/trainer.py",
       "preemption resume bundle written"),
    _h("walltime_save", "hydragnn_tpu/train/trainer.py",
       "SLURM-walltime resume bundle written"),
    _h("resume_from", "hydragnn_tpu/train/trainer.py",
       "run restored a resume bundle"),
    _h("ckpt_retry", "hydragnn_tpu/resilience/ckpt_io.py",
       "one failed checkpoint-write attempt"),
    _h("ckpt_giveup", "hydragnn_tpu/resilience/ckpt_io.py",
       "checkpoint retries exhausted, run degraded gracefully"),
    _h("nonfinite_abort", "hydragnn_tpu/resilience/guards.py",
       "guard monitor hit N consecutive bad steps and raised"),
    _h("graph_shard_fallback", "hydragnn_tpu/train/trainer.py",
       "graph sharding requested but the run fell back to plain DP"),
    _h("fused_fallback", "hydragnn_tpu/train/trainer.py",
       "an arch fell off its fused edge-block path (structural limit, "
       "missing sender_perm, or env override) and composed the XLA "
       "route instead — fields carry arch and reason"),
    _h("egcl_fallback", "hydragnn_tpu/train/trainer.py",
       "legacy alias of fused_fallback, still emitted when the arch is "
       "EGNN (kept one release for dashboards keyed on the old kind)"),
    _h("train_dtype_reject", "hydragnn_tpu/train/trainer.py",
       "bf16 train policy requested but rejected (golden-gate drift, "
       "graph sharding, or empty loader) — run fell back to f32"),
    # elastic training (docs/TELEMETRY.md + docs/RESILIENCE.md)
    _h("elastic_resize", "hydragnn_tpu/resilience/elastic.py",
       "a world resize was agreed at an epoch boundary, or a "
       "shape-changed resume was admitted"),
    _h("elastic_admit", "hydragnn_tpu/train/trainer.py",
       "this host resumed INTO a new world shape (carries the converted "
       "position and the saved shape)"),
    _h("elastic_retire", "hydragnn_tpu/resilience/elastic.py",
       "world shrinking: surplus hosts exit through the bundle path at "
       "the agreed boundary and never relaunch"),
    _h("elastic_refuse", "hydragnn_tpu/resilience/elastic.py",
       "strict policy refused a world-shape-mismatched resume"),
    # serving lifecycle (docs/TELEMETRY.md "Serving events")
    _h("request_enqueued", "hydragnn_tpu/serve/batcher.py",
       "request accepted into the bounded queue"),
    _h("batch_flushed", "hydragnn_tpu/serve/batcher.py",
       "micro-batcher ran one padded prediction"),
    _h("deadline_flush", "hydragnn_tpu/serve/batcher.py",
       "max_wait_ms fired before a bucket filled"),
    _h("cache_miss", "hydragnn_tpu/serve/engine.py",
       "a request batch compiled at serve time (warmup gap)"),
    _h("batch_error", "hydragnn_tpu/serve/batcher.py",
       "engine failure surfaced to a batch's requests"),
    _h("serve_start", "hydragnn_tpu/serve/server.py",
       "server (or fleet router) came up"),
    _h("serve_drain", "hydragnn_tpu/serve/server.py",
       "graceful drain completed"),
    # overload / robustness (docs/TELEMETRY.md "Overload/robustness kinds")
    _h("request_shed", "hydragnn_tpu/serve/batcher.py",
       "admission control rejected a request before queueing (429)"),
    _h("deadline_expired", "hydragnn_tpu/serve/batcher.py",
       "queued entries whose budget ran out, skipped pre-batch (429)"),
    _h("predict_timeout", "hydragnn_tpu/serve/batcher.py",
       "flush exceeded the predict watchdog (504)"),
    _h("breaker_open", "hydragnn_tpu/resilience/breaker.py",
       "circuit breaker tripped open"),
    _h("breaker_half_open", "hydragnn_tpu/resilience/breaker.py",
       "breaker cooldown elapsed, probe flush armed"),
    _h("breaker_close", "hydragnn_tpu/resilience/breaker.py",
       "probe succeeded, breaker closed"),
    _h("reload_ok", "hydragnn_tpu/serve/engine.py",
       "hot checkpoint reload validated and swapped"),
    _h("reload_rollback", "hydragnn_tpu/serve/engine.py",
       "reload rejected / rolled back (validation, breaker, api)"),
    # quantized inference (docs/TELEMETRY.md "Quantized-inference kinds")
    _h("quant_policy", "hydragnn_tpu/serve/engine.py",
       "non-f32 dtype policy passed the golden gate and serves"),
    _h("quant_reject", "hydragnn_tpu/serve/engine.py",
       "requested policy exceeded quant_tolerance, fell back to f32"),
    # replica fleet (docs/TELEMETRY.md "Fleet events")
    _h("fleet_start", "hydragnn_tpu/serve/fleet.py",
       "supervisor brought the replica pool up"),
    _h("replica_start", "hydragnn_tpu/serve/fleet.py",
       "one replica entered routing"),
    _h("replica_dead", "hydragnn_tpu/serve/fleet.py",
       "replica left routing involuntarily"),
    _h("replica_restart", "hydragnn_tpu/serve/fleet.py",
       "supervisor restarted a replica"),
    _h("replica_eject", "hydragnn_tpu/serve/fleet.py",
       "replica taken out of routing (breaker / restart storm)"),
    _h("replica_readmit", "hydragnn_tpu/serve/fleet.py",
       "ejected replica re-entered routing after cooldown"),
    _h("replica_drain", "hydragnn_tpu/serve/fleet.py",
       "drain-and-replace began"),
    _h("rolling_reload_start", "hydragnn_tpu/serve/fleet.py",
       "one-replica-at-a-time fleet reload began"),
    _h("rolling_reload_ok", "hydragnn_tpu/serve/fleet.py",
       "fleet reload completed on every replica"),
    _h("rolling_reload_rollback", "hydragnn_tpu/serve/fleet.py",
       "fleet reload aborted; swapped replicas rolled back"),
    _h("fleet_probe_error", "hydragnn_tpu/serve/fleet.py",
       "supervisor probe loop hit an unexpected error (loop survives)"),
    _h("fleet_retry", "hydragnn_tpu/serve/router.py",
       "router failed a request over to another replica"),
    _h("fleet_degraded", "hydragnn_tpu/serve/fleet.py",
       "live replicas dropped below quorum"),
    _h("fleet_empty", "hydragnn_tpu/serve/router.py",
       "a request found no live replica (503)"),
    # autoscaler + tenancy (docs/TELEMETRY.md "Autoscaler/tenancy kinds")
    _h("fleet_scale_up", "hydragnn_tpu/serve/fleet.py",
       "autoscaler added a replica (carries the drain-rate signal)"),
    _h("fleet_scale_down", "hydragnn_tpu/serve/fleet.py",
       "autoscaler retired a replica zero-drop after the quiet window"),
    _h("tenant_shed", "hydragnn_tpu/serve/router.py",
       "one tenant's request shed 429 (budget exceeded or chaos-hot)"),
    _h("tenant_evict", "hydragnn_tpu/serve/fleet.py",
       "LRU evicted a resident tenant engine from a replica"),
    _h("executable_evict", "hydragnn_tpu/serve/engine.py",
       "engine AOT-executable LRU evicted a compiled bucket"),
    # streaming data plane (docs/TELEMETRY.md "Streaming events")
    _h("stream_open", "hydragnn_tpu/train/trainer.py",
       "streaming data plane active (store, plan and window metadata)"),
    _h("stream_fallback", "hydragnn_tpu/train/trainer.py",
       "streaming requested but the run fell back to the in-memory path"),
    _h("stream_open_retry", "hydragnn_tpu/train/trainer.py",
       "one failed streaming store-open attempt that was retried with "
       "backoff before any fallback"),
    _h("stream_tail_grow", "hydragnn_tpu/train/trainer.py",
       "tail-mode store picked up newly sealed segments between epochs"),
    _h("stream_torn_segment", "hydragnn_tpu/data/stream/ingest.py",
       "ingest segment failed its manifest size check and was skipped"),
    # SLO monitoring (docs/TELEMETRY.md "Tracing")
    _h("slo_burn", "hydragnn_tpu/telemetry/slo.py",
       "burn-rate monitor: serving latency/shed budget burning faster "
       "than the configured multiple (edge-triggered per excursion)"),
]

HEALTH_KINDS: Dict[str, HealthKind] = {h.name: h for h in _HEALTH_LIST}


@dataclasses.dataclass(frozen=True)
class SpanName:
    name: str
    module: str  # recording module (repo-relative)
    desc: str


def _s(name, module, desc):
    return SpanName(name=name, module=module, desc=desc)


_SPAN_LIST = [
    # serving request path (docs/TELEMETRY.md "Tracing")
    _s("serve.request", "hydragnn_tpu/serve/server.py",
       "one HTTP request, admission to reply (router or single server)"),
    _s("serve.queue_wait", "hydragnn_tpu/serve/batcher.py",
       "enqueue -> flush pickup for one traced request"),
    _s("serve.flush", "hydragnn_tpu/serve/batcher.py",
       "one micro-batch flush; links the trace_ids it carried"),
    _s("serve.pad", "hydragnn_tpu/serve/engine.py",
       "bucket collation/padding inside a flush"),
    _s("serve.predict", "hydragnn_tpu/serve/engine.py",
       "device execution inside a flush (blocked-on-ready)"),
    # train-step phases (trace mode only)
    _s("train.data_wait", "hydragnn_tpu/train/trainer.py",
       "blocking loader next() before a train dispatch"),
    _s("train.h2d", "hydragnn_tpu/train/trainer.py",
       "jit arg ingest: synchronous host->device batch transfer"),
    _s("train.step", "hydragnn_tpu/train/trainer.py",
       "on-device step execution (compute + collectives; split via the "
       "comms probe)"),
    # collective regions (HLO metadata names under comm_probe=True)
    _s("comm.dp_psum", "hydragnn_tpu/parallel/mesh.py",
       "gradient/metric psum-pmean over the DP axes"),
    _s("comm.zero_all_gather", "hydragnn_tpu/parallel/mesh.py",
       "ZeRO stage-2 param all_gather before the forward"),
    _s("comm.halo_exchange", "hydragnn_tpu/parallel/mesh.py",
       "halo-row exchange assembling the extended graph shard"),
]

SPAN_NAMES: Dict[str, SpanName] = {s.name: s for s in _SPAN_LIST}


KNOB_DOC_HEADER = """\
# Env knobs — the generated registry

GENERATED by `python tools/graftlint.py --emit-docs` from
`hydragnn_tpu/analysis/registry.py` — do not edit by hand; the lint gate
(`tests/test_lint.py`, rule REG002) fails when this file drifts from the
registry.  Config spellings follow the env-wins overlay convention
(`hydragnn_tpu/utils/env.py` truthiness rules: unset/empty/`0`/`false`
disables a flag).

| knob | config spelling | default | owning module | effect |
|---|---|---|---|---|
"""


def emit_knob_docs() -> str:
    """Render docs/KNOBS.md from the registry."""
    rows = []
    for name in sorted(KNOBS):
        k = KNOBS[name]
        cfg = f"`{k.config}`" if k.config else "—"
        default = k.default if k.default != "" else "—"
        rows.append(f"| `{k.name}` | {cfg} | {default} "
                    f"| `{k.module}` | {k.desc} |")
    return KNOB_DOC_HEADER + "\n".join(rows) + "\n"
