"""Small shared AST helpers used by the rule modules."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as ``a.b.c`` (None for anything
    else, e.g. a call result attribute)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def base_name(name: Optional[str]) -> Optional[str]:
    """Last segment of a dotted name (``jax.jit`` -> ``jit``)."""
    return name.rsplit(".", 1)[-1] if name else None


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents) -> Iterator[ast.AST]:
    while node in parents:
        node = parents[node]
        yield node


def enclosing_function(node: ast.AST, parents) -> Optional[ast.AST]:
    for a in ancestors(node, parents):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return a
    return None


def walk_skip_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree WITHOUT descending into nested function/class
    definitions (their bodies execute in a different regime)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
