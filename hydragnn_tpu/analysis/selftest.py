"""Fixture-driven selftest: the analyzer itself is tested, not just its
current verdict on the tree.

Every rule ships at least one fixture that TRIGGERS it and one that
PASSES it (``fixtures/<ruleid>_bad.py`` / ``<ruleid>_ok.py``).  The
fixtures directory is excluded from normal scans (project.EXCLUDE_DIRS)
precisely because its files violate invariants on purpose.

``tools/graftlint.py --selftest`` and ``tests/test_lint.py`` both run
:func:`run_selftest`.
"""

from __future__ import annotations

import os
from typing import List, Tuple

from .core import all_rules, is_suppressed
from .project import Project, parse_file

# rules whose fixtures are ordinary per-file checks
PER_FILE_RULES = ("TRC001", "TRC002", "TRC003", "TRC004", "LCK001",
                  "REG001", "REG003", "REG006", "ROB001", "ROB002")
# project-scope rules exercised by special-case harnesses below
PROJECT_RULES = ("REG002", "REG004", "REG005")


def fixtures_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def _rule(rule_id: str):
    return next(r for r in all_rules() if r.id == rule_id)


def _file_findings(rule_id: str, path: str) -> List:
    ctx = parse_file(path, root=os.path.dirname(path))
    return [f for f in _rule(rule_id).check_file(ctx)
            if f.rule == rule_id]


def run_selftest() -> Tuple[bool, List[str]]:
    """Exercise every rule against its fixtures.  Returns (ok, report)."""
    fdir = fixtures_dir()
    report: List[str] = []
    ok = True

    def check(cond: bool, msg: str) -> None:
        nonlocal ok
        report.append(("PASS " if cond else "FAIL ") + msg)
        ok = ok and cond

    for rule_id in PER_FILE_RULES:
        low = rule_id.lower()
        bad = os.path.join(fdir, f"{low}_bad.py")
        good = os.path.join(fdir, f"{low}_ok.py")
        check(os.path.exists(bad) and os.path.exists(good),
              f"{rule_id}: fixture pair exists")
        if not (os.path.exists(bad) and os.path.exists(good)):
            continue
        check(len(_file_findings(rule_id, bad)) >= 1,
              f"{rule_id}: _bad fixture triggers the rule")
        check(len(_file_findings(rule_id, good)) == 0,
              f"{rule_id}: _ok fixture passes the rule")

    # REG005 (config-key drift) pairs a fixture file with itself via the
    # *_defaults/from_* fallback
    reg5 = _rule("REG005")
    files = [parse_file(os.path.join(fdir, n), root=fdir)
             for n in ("reg005_bad.py", "reg005_ok.py")
             if os.path.exists(os.path.join(fdir, n))]
    check(len(files) == 2, "REG005: fixture pair exists")
    if len(files) == 2:
        found = list(reg5.check_project(Project(root=fdir, files=files)))
        check(any(f.path == "reg005_bad.py" for f in found),
              "REG005: _bad fixture triggers the rule")
        check(not any(f.path == "reg005_ok.py" for f in found),
              "REG005: _ok fixture passes the rule")

    # REG002/REG004 (registry drift): a project holding ONLY the registry
    # mentions no knob and emits no kind — every declared entry must be
    # reported stale/unemitted.  The full-tree gate is the ok-direction.
    here = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(os.path.dirname(here))
    reg_ctx = parse_file(os.path.join(here, "registry.py"), root=repo_root)
    lonely = Project(root=fdir, files=[reg_ctx])  # no docs under fdir
    for rule_id in ("REG002", "REG004"):
        found = list(_rule(rule_id).check_project(lonely))
        check(len(found) >= 1,
              f"{rule_id}: registry-only project triggers the rule")

    # suppression mechanics: a violating line with an inline
    # `# graftlint: disable=...` must lint clean
    sup = os.path.join(fdir, "suppress_ok.py")
    check(os.path.exists(sup), "suppressions: fixture exists")
    if os.path.exists(sup):
        ctx = parse_file(sup, root=fdir)
        raw = [f for r in all_rules() for f in r.check_file(ctx)]
        check(len(raw) >= 1,
              "suppressions: fixture raises raw findings")
        unsup = [f for f in raw if not is_suppressed(
            f, ctx.suppressed_lines, ctx.suppressed_file)]
        check(len(unsup) == 0,
              "suppressions: inline disables silence them all")

    return ok, report
