"""Rule plugin API: severities, findings, registration, suppressions.

A rule is a subclass of :class:`Rule` registered with :func:`register`.
Per-file rules implement ``check_file(ctx)``; cross-artifact rules (the
registry family) implement ``check_project(project)`` and run once after
every file is parsed.  Findings carry a *fingerprint* — ``rule`` + path +
the whitespace-normalized source line — so the baseline survives pure
line drift (code moving down a file does not invalidate entries).
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import re
from typing import Dict, Iterable, List, Optional, Type


class Severity(enum.IntEnum):
    """Per-rule severity.  The gate fails on any unsuppressed finding
    regardless of severity; ``--min-severity`` filters reporting only."""

    NOTE = 10
    WARN = 20
    ERROR = 30

    @classmethod
    def parse(cls, name: str) -> "Severity":
        try:
            return cls[name.upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {name!r} (note|warn|error)") from None


def normalize_code(line: str) -> str:
    """Whitespace-normalized source line — the drift-stable part of a
    finding's identity."""
    return " ".join(line.split())


@dataclasses.dataclass
class Finding:
    rule: str
    severity: Severity
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    code: str = ""  # normalized source line at `line`

    @property
    def fingerprint(self) -> str:
        h = hashlib.sha1()
        h.update(f"{self.rule}|{self.path}|{self.code}".encode())
        return h.hexdigest()[:16]

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} [{self.severity.name.lower()}] {self.message}")

    def to_json(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "code": self.code,
            "fingerprint": self.fingerprint,
        }


class Rule:
    """Base class for one lint rule.

    Subclasses set ``id`` (stable, used in suppressions/baseline),
    ``name`` (kebab-case slug), ``severity``, and ``doc`` (one-line
    invariant statement; the full story lives in docs/ANALYSIS.md).
    """

    id: str = ""
    name: str = ""
    severity: Severity = Severity.WARN
    doc: str = ""

    def check_file(self, ctx) -> Iterable[Finding]:  # pragma: no cover
        return ()

    def check_project(self, project) -> Iterable[Finding]:  # pragma: no cover
        return ()

    def finding(self, ctx, node_or_line, message: str,
                col: Optional[int] = None) -> Finding:
        """Build a finding anchored at an AST node (or explicit line)."""
        if isinstance(node_or_line, int):
            line, c = node_or_line, col or 0
        else:
            line = getattr(node_or_line, "lineno", 1)
            c = col if col is not None else getattr(
                node_or_line, "col_offset", 0)
        code = ""
        if ctx is not None and 1 <= line <= len(ctx.lines):
            code = normalize_code(ctx.lines[line - 1])
        return Finding(rule=self.id, severity=self.severity,
                       path=ctx.rel if ctx is not None else "<project>",
                       line=line, col=c, message=message, code=code)


_RULES: Dict[str, Rule] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator: instantiate and register a rule by ``id``."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(rule_id: str) -> Rule:
    return _RULES[rule_id]


# -- suppressions ----------------------------------------------------------
#
# `# graftlint: disable=RULE1,RULE2 (reason)` — trailing on a line
# suppresses that line; on a line of its own it suppresses the NEXT line
# too (for statements too long to carry a trailing comment).
# `# graftlint: disable-file=RULE` anywhere in the first 10 lines
# suppresses the rule for the whole file.  `disable=all` matches every
# rule.  Suppressions are counted and reported so they stay auditable.

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


def parse_suppressions(lines: List[str]):
    """Return (per_line: dict[int, set[str]], file_wide: set[str])."""
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(raw)
        if m and i <= 10:
            file_wide.update(
                r.strip() for r in m.group(1).split(",") if r.strip())
            continue
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        per_line.setdefault(i, set()).update(rules)
        if raw.lstrip().startswith("#"):
            # standalone comment line: also covers the following line
            per_line.setdefault(i + 1, set()).update(rules)
    return per_line, file_wide


def is_suppressed(finding: Finding, per_line, file_wide) -> bool:
    if "all" in file_wide or finding.rule in file_wide:
        return True
    rules = per_line.get(finding.line, ())
    return "all" in rules or finding.rule in rules
