"""Fixture: health kind not declared in the registry (REG003)."""


class Emitter:
    def emit(self, telemetry):
        telemetry.health("definitely_not_a_kind", x=1)
