"""Fixture: unguarded write to a locked class's shared attr (LCK001)."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def reset(self):
        self.count = 0  # second thread may be inside bump() right now
