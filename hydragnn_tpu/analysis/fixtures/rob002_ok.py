"""Fixture: the tmp + os.replace idiom (ROB002 quiet)."""
import json
import os


def save(meta, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh)
    os.replace(tmp, path)
