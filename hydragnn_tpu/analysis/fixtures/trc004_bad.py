"""Fixture: donated argument read after the call (TRC004 fires)."""
import jax


def train(state, batch):
    step = jax.jit(lambda s, b: s + b, donate_argnums=0)
    new_state = step(state, batch)
    return state + new_state  # state's buffer was deleted by donation
