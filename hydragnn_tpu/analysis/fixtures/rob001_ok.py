"""Fixture: narrow type / logged / error used (ROB001 quiet)."""


def load(path, log):
    try:
        return open(path).read()
    except FileNotFoundError:
        return None
    except Exception as e:
        log.warning("load failed: %s", e)
        return None


def submit(fut, work):
    try:
        work()
    except Exception as e:
        fut.set_exception(e)
