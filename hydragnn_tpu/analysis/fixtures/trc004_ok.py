"""Fixture: donated argument rebound by the call (TRC004 quiet)."""
import jax


def train(state, batch):
    step = jax.jit(lambda s, b: s + b, donate_argnums=0)
    state = step(state, batch)
    return state + 1


def report(state):
    # same variable NAME as train()'s donated arg, different scope — the
    # rule must not cross-match function bodies (regression fixture)
    print(state)
    return state
