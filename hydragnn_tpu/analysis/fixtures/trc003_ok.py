"""Fixture: wrapper cached outside the loop (TRC003 quiet)."""
import jax

_gather = jax.jit(lambda x: x + 1)


def save_all(leaves):
    out = []
    for leaf in leaves:
        out.append(_gather(leaf))
    return out
