"""Fixture: every shared write under the lock (LCK001 quiet)."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def reset(self):
        with self._lock:
            self._reset_locked()

    def _reset_locked(self):
        self.count = 0
