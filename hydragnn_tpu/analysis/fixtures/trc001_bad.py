"""Fixture: host-sync calls inside a traced function (TRC001 fires)."""
import time

import jax
import numpy as np


@jax.jit
def step(state, batch):
    loss = (state - batch).sum()
    t = time.time()  # host clock read baked in at trace time
    host = np.asarray(loss)  # device->host sync under tracing
    return loss.item() + t + host
