"""Fixture: Python branch on a traced argument (TRC002 fires)."""
import jax


@jax.jit
def guard(loss, scale):
    if loss > 0:
        return loss * scale
    while scale:
        scale = scale - 1
    return loss
