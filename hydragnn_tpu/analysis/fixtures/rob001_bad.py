"""Fixture: broad except swallows the error silently (ROB001)."""


def load(path):
    try:
        return open(path).read()
    except Exception:
        return None
