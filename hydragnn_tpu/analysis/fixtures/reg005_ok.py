"""Fixture: writer and reader agree key-for-key (REG005 quiet)."""


def gadget_defaults():
    return {"alpha": 1, "beta": 2}


class GadgetConfig:
    @classmethod
    def from_gadget(cls, section):
        s = dict(section or {})
        return {"alpha": s.get("alpha", 1), "beta": s.get("beta", 2)}
