"""Fixture: jit wrapper constructed inside a loop (TRC003 fires)."""
import jax


def save_all(leaves):
    out = []
    for leaf in leaves:
        gather = jax.jit(lambda x: x + 1)  # fresh trace every iteration
        out.append(gather(leaf))
    return out
