"""Fixture: declared span names only; unrelated ``.span()`` spellings
(re.Match.span) stay out of the rule's reach (REG006 quiet)."""

import re


class Traced:
    def flush(self, tr, t0, t1):
        tr.record_interval("serve.flush", t0, t1, n=3)
        with tr.span("serve.predict"):
            pass

    def comm(self, comm_region, probe):
        with comm_region("comm.dp_psum", probe):
            pass

    def offsets(self, text):
        m = re.match(r"\d+", text)
        return m.span(0) if m else None
