"""Fixture: declared knob + prefix construction (REG001 quiet)."""
import os


def read_knob(name):
    on = os.environ.get("HYDRAGNN_TELEMETRY", "")
    dyn = os.environ.get("HYDRAGNN_SERVE_" + name, "")
    return on, dyn
