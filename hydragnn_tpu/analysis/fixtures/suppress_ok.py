"""Fixture: inline suppressions silence every raw finding here."""
import time

import jax


@jax.jit
def step(x):
    t = time.time()  # graftlint: disable=TRC001 (fixture: suppression mechanics)
    return x + t
