"""Fixture: env knob read but not declared in the registry (REG001)."""
import os


def read_knob():
    return os.environ.get("HYDRAGNN_NOT_A_REAL_KNOB", "0")
