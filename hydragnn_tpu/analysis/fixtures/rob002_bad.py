"""Fixture: torn-on-crash sidecar write (ROB002)."""
import json


def save(meta, path):
    with open(path, "w") as fh:
        json.dump(meta, fh)
