"""Fixture: span names the registry has never heard of (REG006)."""


class Traced:
    def flush(self, tr, t0, t1, which):
        tr.record_interval("serve.totally_undeclared", t0, t1)
        with tr.span("another.rogue_span"):
            pass
        # dynamic name: the registry rule cannot see it at all
        tr.record_interval(which, t0, t1)
