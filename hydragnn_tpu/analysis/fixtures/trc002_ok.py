"""Fixture: static/None/shape branches only (TRC002 quiet)."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("training",))
def guard(loss, training):
    if training:
        return loss * 2
    if loss is None:
        return loss
    if loss.shape[0] > 4:
        return loss[:4]
    return jax.lax.cond(loss.sum() > 0, lambda l: l * 2, lambda l: l, loss)
