"""Fixture: finalize-written key never validated on read (REG005)."""


def widget_defaults():
    return {"alpha": 1, "beta": 2}


class WidgetConfig:
    @classmethod
    def from_widget(cls, section):
        s = dict(section or {})
        return {"alpha": s.get("alpha", 1)}  # beta: written, never read
