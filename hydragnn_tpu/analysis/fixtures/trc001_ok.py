"""Fixture: the same calls OUTSIDE the traced region (TRC001 quiet)."""
import time

import jax
import numpy as np


@jax.jit
def step(state, batch):
    return (state - batch).sum()


def host_side(out):
    t0 = time.time()
    return float(np.asarray(out)), time.time() - t0
