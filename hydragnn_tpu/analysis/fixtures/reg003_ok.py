"""Fixture: declared kinds, incl. the two-literal IfExp (REG003 quiet)."""


class Emitter:
    def emit(self, telemetry, walltime):
        telemetry.health("serve_start", port=1)
        telemetry.health("walltime_save" if walltime else "preempt_save")
        snapshot = telemetry.health_counts() if walltime else None
        return snapshot
