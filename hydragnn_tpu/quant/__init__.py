"""Low-precision inference states (docs/SERVING.md "Quantized
inference"): f32 / bf16 / int8 weight-only dtype policies applied when
``load_inference_state`` builds an InferenceState, gated by the
engine's golden-batch replay against the f32 reference.

``POLICIES``/``check_policy`` live here, dependency-free, because
``config.finalize`` validates ``Serving.quant_policy`` in config-only
callers that must not drag flax/jax in; everything else resolves
lazily (PEP 562) from :mod:`hydragnn_tpu.quant.policy`.
"""

POLICIES = ("f32", "bf16", "int8")

# training-time dtype policies (Training.train_dtype_policy +
# HYDRAGNN_TRAIN_DTYPE): narrower than the inference set — int8 weights
# cannot carry an optimizer update, so training is f32, bf16-with-f32-
# accumulation, or the int8_edge pilot (docs/PERF.md PR-15): master
# params stay f32 and only the edge-MLP kernels are fake-quantized
# (int8 round-trip with a straight-through grad) in the forward —
# the same step-0 golden replay gates acceptance
TRAIN_POLICIES = ("f32", "bf16", "int8_edge")


def check_policy(policy: str) -> str:
    if policy not in POLICIES:
        raise ValueError(
            f"unknown quant policy {policy!r} (choose from {POLICIES})")
    return policy


def check_train_policy(policy: str) -> str:
    if policy not in TRAIN_POLICIES:
        raise ValueError(
            f"unknown train dtype policy {policy!r} "
            f"(choose from {TRAIN_POLICIES})")
    return policy


_EXPORTS = (
    "QTensor",
    "apply_policy",
    "cast_floats",
    "dequantize",
    "dequantize_tree",
    "fake_quant_edge_params",
    "policy_summary",
    "quantize_int8",
    "tree_nbytes",
    "wrap_eval_step",
)

__all__ = sorted(_EXPORTS + ("POLICIES", "TRAIN_POLICIES", "check_policy",
                             "check_train_policy"))


def __getattr__(name: str):
    if name not in _EXPORTS:
        raise AttributeError(
            f"module 'hydragnn_tpu.quant' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module("hydragnn_tpu.quant.policy"),
                   name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
