"""Inference dtype policies: f32 baseline, bf16, int8 weight-only.

The serving stack is padding- and memory-bound, not FLOP-bound
(BENCH_r05: the mainline SchNet run sits at ~1.2% of roofline), so the
cheapest per-chip rps lever is shrinking the resident parameter bytes —
more replicas (and more tenant checkpoints) fit per chip, and every
weight load moves half (bf16) or a quarter (int8) of the HBM traffic.

Three policies, selected by ``Serving.quant_policy``:

- ``f32``   — identity.  The engine's compiled program stays BYTE-equal
  to the training eval step, preserving the bit-parity contract with
  ``run_prediction``.
- ``bf16``  — every float leaf of params/batch_stats cast to bfloat16,
  and the eval step wrapped so batch floats are cast on entry and
  outputs are cast back to f32 on exit: weights AND compute in bf16
  (f32 accumulation inside the MXU), half the resident bytes.
- ``int8``  — weight-only quantization: 2-D+ kernels become
  :class:`QTensor` (int8 values + per-output-channel f32 scales,
  ~0.26x the f32 bytes), everything else falls to bf16.  At apply time
  the kernels are dequantized INTO bf16 (``q * scale -> bf16``) so the
  matmuls themselves run bf16 — XLA fuses the dequant into the
  consumer, and the resident state stays int8.

Quantization here is LOSSY by design and gated downstream: the engine
only activates a non-f32 policy when a golden-batch replay against the
f32 reference stays under ``Serving.quant_tolerance``
(serve/engine.py).  Nothing in this module decides acceptance.

Per-channel scales are along the LAST axis (flax Dense kernels are
``[in, out]``: one scale per output channel), ``absmax / 127``
symmetric — the weight distribution per output unit is what varies
across a trained layer, and symmetric scaling keeps the dequant a
single fused multiply.  Leaves with fewer than 2 rows are NOT
quantized: the f32 scale vector would cost as much as the int8 win.
"""

from __future__ import annotations

from typing import Any, Dict

from flax import struct

# canonical policy list + validator live in hydragnn_tpu/quant/__init__
# (dependency-free for config-only callers); re-exported here for the
# engine-side consumers that already pay the flax import
from hydragnn_tpu.quant import POLICIES, check_policy  # noqa: F401


@struct.dataclass
class QTensor:
    """int8 weight + per-output-channel scale (last-axis channels).

    A pytree node (flax struct), so quantized param trees flow through
    ``jax.device_put`` / ``jit`` / the engine's aval-specialized AOT
    executables like any other state."""

    q: Any      # int8, same shape as the source weight
    scale: Any  # f32, [shape[-1]]

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.scale.nbytes)


def quantize_int8(w) -> QTensor:
    """Symmetric per-channel int8 quantization along the last axis."""
    import jax.numpy as jnp

    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes)
    # all-zero channels get scale 1 so dequant is exactly zero (0 * 1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale.astype(jnp.float32))


def dequantize(qt: QTensor, dtype=None):
    """``q * scale`` in f32, cast into ``dtype`` (default bfloat16) —
    the bf16 operand the policy's matmuls consume."""
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    return (qt.q.astype(jnp.float32) * qt.scale).astype(dtype)


def _is_qtensor(x) -> bool:
    return isinstance(x, QTensor)


def dequantize_tree(tree, dtype=None):
    """Replace every QTensor leaf with its bf16 dequantization; other
    leaves pass through untouched."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if _is_qtensor(x) else x,
        tree, is_leaf=_is_qtensor)


def cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype``; ints, bools
    and QTensors are untouched."""
    import jax
    import jax.numpy as jnp

    def _cast(x):
        if _is_qtensor(x):
            return x
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(_cast, tree, is_leaf=_is_qtensor)


def _quantizable(x) -> bool:
    """Weight-only gate: float, >= 2 dims, >= 2 rows per channel (below
    that the f32 scale vector costs as much as the int8 saving)."""
    import numpy as np

    shape = np.shape(x)
    dt = getattr(x, "dtype", None)
    if dt is None or not np.issubdtype(np.dtype(dt), np.floating):
        return False
    if len(shape) < 2 or shape[-1] < 1:
        return False
    rows = 1
    for s in shape[:-1]:
        rows *= int(s)
    return rows >= 2


def apply_policy(state, policy: str):
    """Return ``state`` with its params/batch_stats transformed by the
    dtype policy.  ``state`` is any object with ``params``,
    ``batch_stats`` and a dataclass-style ``.replace`` (InferenceState).
    ``f32`` returns the state unchanged (identity object — the caller's
    bit-parity guarantee)."""
    import jax
    import jax.numpy as jnp

    check_policy(policy)
    if policy == "f32":
        return state
    if policy == "bf16":
        return state.replace(
            params=cast_floats(state.params, jnp.bfloat16),
            batch_stats=cast_floats(state.batch_stats, jnp.bfloat16))
    # int8 weight-only: kernels -> QTensor, the rest -> bf16
    params = jax.tree_util.tree_map(
        lambda x: quantize_int8(x) if _quantizable(x)
        else cast_floats(x, jnp.bfloat16),
        state.params)
    return state.replace(
        params=params,
        batch_stats=cast_floats(state.batch_stats, jnp.bfloat16))


def wrap_eval_step(eval_step, policy: str):
    """Wrap a ``(state, batch) -> metrics`` eval step for a low-precision
    policy: batch floats cast to bf16 on entry (params are already bf16 /
    int8, so the model's matmuls run bf16), QTensor kernels dequantized
    into bf16 inside the traced program (XLA fuses the multiply into the
    consumers; the RESIDENT buffers stay int8), and every float output
    cast back to f32 so host-side unpacking/denormalization sees the
    dtypes it always has."""
    import jax.numpy as jnp

    check_policy(policy)
    if policy == "f32":
        return eval_step

    def wrapped(state, batch):
        batch = cast_floats(batch, jnp.bfloat16)
        if policy == "int8":
            state = state.replace(params=dequantize_tree(state.params))
        m = eval_step(state, batch)
        return cast_floats(m, jnp.float32)

    return wrapped


# the edge-MLP module names the fused-block builder specs consume
# (ops/fused_block.py): SchNet's filter MLP, EGNN's edge MLP, CGCNN's
# gate pair, DimeNet's sbf embedding.  The int8_edge training pilot
# fake-quantizes exactly these kernels — the layers whose weights live
# as constant VMEM blocks in the fused kernels, i.e. where a future
# true-int8 MXU path would land first.
EDGE_MLP_MODULES = frozenset((
    "filter_0", "filter_1",
    "edge_mlp_0", "edge_mlp_1",
    "lin_f", "lin_s",
    "lin_sbf1", "lin_sbf2",
))


def fake_quant_edge_params(params):
    """``Training.train_dtype_policy="int8_edge"`` transform: every
    edge-MLP *kernel* (see :data:`EDGE_MLP_MODULES`) goes through an
    int8 round-trip (symmetric per-channel quantize -> dequantize back
    to its dtype) with a straight-through gradient, everything else
    passes through untouched.  Trace-time: the master params the
    optimizer updates stay f32 — this fakes the int8 numerics the
    fused edge kernels would see, so the step-0 golden replay can
    measure the drift before any kernel commits to int8 accumulate."""
    import jax

    def _fq(path, x):
        names = {getattr(p, "key", None) for p in path}
        if "kernel" not in names or not (names & EDGE_MLP_MODULES) \
                or not _quantizable(x):
            return x
        q = dequantize(quantize_int8(x), getattr(x, "dtype", None))
        # straight-through estimator: forward sees the rounded weights,
        # backward passes the cotangent to the master weights unchanged
        # (round() has zero gradient a.e., which would stall training)
        return x + jax.lax.stop_gradient(q - x)

    return jax.tree_util.tree_map_with_path(_fq, params)


def tree_nbytes(tree) -> int:
    """Resident bytes of every leaf in a pytree (QTensor counts q +
    scale) — the number behind the HBM-halving claim, reported by
    ``InferenceEngine.cache_stats`` and tools/servebench.py."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None:
            nb = np.asarray(leaf).nbytes
        total += int(nb)
    return total


def policy_summary(params, batch_stats=None) -> Dict[str, Any]:
    """Small introspection helper: leaf counts + resident bytes split by
    representation (int8 / bf16 / f32 / other)."""
    import jax
    import numpy as np

    by: Dict[str, int] = {}
    leaves = jax.tree_util.tree_leaves(
        (params, batch_stats if batch_stats is not None else {}),
        is_leaf=_is_qtensor)
    for leaf in leaves:
        if _is_qtensor(leaf):
            by["int8"] = by.get("int8", 0) + leaf.nbytes
            continue
        dt = str(np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype)))
        key = {"bfloat16": "bf16", "float32": "f32"}.get(dt, dt)
        by[key] = by.get(key, 0) + int(getattr(leaf, "nbytes", 0))
    return {"bytes_by_repr": by, "total_bytes": sum(by.values())}
