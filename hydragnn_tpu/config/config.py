"""Config system: accepts the reference's JSON schema, finalizes explicitly.

The reference mutates its config dict at runtime based on the loaded data
(``update_config``, reference hydragnn/utils/config_utils.py:23-106).  Here the
same inference is an explicit, pure step: :func:`finalize` takes the raw JSON
dict plus dataset statistics and returns the completed dict — output dims from
head specs, ``input_dim`` from selected features, PNA degree histogram,
edge-dim and equivariance validation — with identical key layout so existing
HydraGNN JSON configs work verbatim (e.g. reference tests/inputs/ci.json).
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.graph.batch import HeadSpec

# Architecture keys defaulted to None when absent, matching
# reference hydragnn/utils/config_utils.py:59-80.
_OPTIONAL_ARCH_KEYS = [
    "radius",
    "num_gaussians",
    "num_filters",
    "envelope_exponent",
    "num_after_skip",
    "num_before_skip",
    "basis_emb_size",
    "int_emb_size",
    "out_emb_size",
    "num_radial",
    "num_spherical",
]

def _telemetry_defaults() -> Dict[str, Any]:
    """Telemetry section defaults (docs/TELEMETRY.md), derived from the ONE
    source of truth — the TelemetryConfig dataclass — so the saved
    config.json can never document settings the run doesn't use.  Per-step
    structured metrics are opt-in (enable=0 keeps the hot path sync-free
    and file-free); the TensorBoard epoch scalars are unconditional."""
    from hydragnn_tpu.telemetry import TelemetryConfig

    d = TelemetryConfig()
    return {
        "enable": int(d.enable),
        "sinks": ",".join(d.sinks),
        # "" = the run-dir default (logs/<run>/telemetry); written back
        # so every key TelemetryConfig.from_section reads has a
        # documented default in the saved config.json (graftlint REG005)
        "dir": d.dir or "",
        "heartbeat": d.heartbeat,
        "ring": d.ring,
        "sync_steps": int(d.sync_steps),
        "mfu": int(d.mfu),
        "trace": int(d.trace),
        "trace_ring": d.trace_ring,
    }


EDGE_MODELS = ["PNA", "CGCNN", "SchNet", "EGNN"]
EQUIVARIANT_MODELS = ["EGNN", "SchNet"]
ALL_MODEL_TYPES = [
    "SAGE",
    "GIN",
    "GAT",
    "MFC",
    "PNA",
    "CGCNN",
    "SchNet",
    "DimeNet",
    "EGNN",
]


def load_config(path_or_dict) -> Dict[str, Any]:
    if isinstance(path_or_dict, dict):
        return copy.deepcopy(path_or_dict)
    with open(path_or_dict, "r") as f:
        return json.load(f)


def finalize(
    config: Dict[str, Any],
    dataset_stats: "DatasetStats",
) -> Dict[str, Any]:
    """Complete a raw config from dataset statistics (pure; returns a copy).

    Parity with reference update_config (hydragnn/utils/config_utils.py:23-106):
      - output_dim / output_type from Variables_of_interest + feature dims
      - input_dim = number of selected input node features
      - PNA degree histogram + max_neighbours
      - edge_dim validation (PNA/CGCNN/SchNet/EGNN only; CGCNN default 0)
      - equivariance validation (EGNN/SchNet only)
      - defaults: optimizer AdamW, loss mse, activation relu, SyncBatchNorm off
    """
    config = copy.deepcopy(config)
    nn = config["NeuralNetwork"]
    arch = nn["Architecture"]
    var = nn["Variables_of_interest"]
    training = nn["Training"]

    output_type: List[str] = var["type"]
    output_index: List[int] = var["output_index"]

    # Per-head output dims from the Dataset feature dims (reference
    # update_config_NN_outputs, config_utils.py:153-189).
    if "Dataset" in config and "node_features" in config["Dataset"]:
        gdims = config["Dataset"].get("graph_features", {}).get("dim", [])
        ndims = config["Dataset"]["node_features"]["dim"]
        dims_list = [
            gdims[output_index[i]] if t == "graph" else ndims[output_index[i]]
            for i, t in enumerate(output_type)
        ]
    else:
        dims_list = var["output_dim"]

    arch["output_dim"] = dims_list
    arch["output_type"] = output_type
    arch["num_nodes"] = int(dataset_stats.num_nodes_sample)

    if dataset_stats.graph_size_variable and (
        "node" in arch.get("output_heads", {})
        and arch["output_heads"]["node"].get("type") == "mlp_per_node"
        and "node" in output_type
    ):
        raise ValueError('"mlp_per_node" is not allowed for variable graph size')

    arch["input_dim"] = len(var["input_node_features"])

    if arch["model_type"] == "PNA":
        deg = dataset_stats.pna_deg
        assert deg is not None, "PNA requires a degree histogram in dataset stats"
        arch["pna_deg"] = [int(d) for d in deg]
        arch["max_neighbours"] = len(deg) - 1
    else:
        arch["pna_deg"] = None

    for key in _OPTIONAL_ARCH_KEYS:
        arch.setdefault(key, None)

    # edge_dim (reference update_config_edge_dim, config_utils.py:120-132)
    arch["edge_dim"] = None
    if arch.get("edge_features"):
        assert arch["model_type"] in EDGE_MODELS, (
            "Edge features can only be used with EGNN, SchNet, PNA and CGCNN."
        )
        arch["edge_dim"] = len(arch["edge_features"])
    elif arch["model_type"] == "CGCNN":
        arch["edge_dim"] = 0

    # equivariance (reference update_config_equivariance, config_utils.py:109-117)
    if arch.get("equivariance"):
        assert arch["model_type"] in EQUIVARIANT_MODELS, (
            "E(3) equivariance can only be ensured for EGNN and SchNet."
        )
    else:
        arch["equivariance"] = False

    arch.setdefault("freeze_conv_layers", False)
    arch.setdefault("initial_bias", None)
    training.setdefault("Optimizer", {"type": "AdamW", "learning_rate": 1e-3})
    training.setdefault("loss_function_type", "mse")
    arch.setdefault("activation_function", "relu")
    arch.setdefault("SyncBatchNorm", False)
    arch.setdefault("task_weights", [1.0] * len(output_type))
    var.setdefault("denormalize_output", False)
    # top-level Telemetry section (sibling of Profile): defaults written
    # back so the saved config.json documents the run's observability
    # settings; env knobs overlay at MetricsLogger construction
    # (telemetry/logger.py:TelemetryConfig.from_section)
    config.setdefault("Telemetry", {})
    for k, v in _telemetry_defaults().items():
        config["Telemetry"].setdefault(k, v)
    # top-level Serving section (docs/SERVING.md): same contract — the
    # saved config.json is what `python -m hydragnn_tpu.serve` later
    # loads, so write the knob defaults back AND the dataset-derived
    # per-graph worst case (the one piece of bucket sizing the serve-time
    # process cannot know without the training data); env knobs overlay
    # at ServingConfig.from_section.  Validation happens in the
    # ServingConfig dataclass on every construction path.
    from hydragnn_tpu.serve.config import serving_defaults

    config.setdefault("Serving", {})
    for k, v in serving_defaults().items():
        config["Serving"].setdefault(k, v)
    # unconditional, like edge_length_norm: the per-graph worst case is
    # THIS run's dataset provenance — a value inherited from a reused
    # config.json would size the serving buckets for the OLD dataset
    # and 413-reject valid graphs (serve-time overrides go through
    # HYDRAGNN_SERVE_MAX_NODES/_EDGES or editing the saved config)
    if dataset_stats.max_nodes:
        config["Serving"]["max_nodes_per_graph"] = int(
            dataset_stats.max_nodes)
    if dataset_stats.max_edges:
        config["Serving"]["max_edges_per_graph"] = int(
            dataset_stats.max_edges)
    # resilience knobs live in Training (they steer the trainer's step
    # builders and epoch driver); same defaults-written-back contract, env
    # knobs overlay at ResilienceConfig.from_training (docs/RESILIENCE.md)
    from hydragnn_tpu.resilience.config import resilience_training_defaults

    for k, v in resilience_training_defaults().items():
        training.setdefault(k, v)
    # elastic-resume policy (docs/RESILIENCE.md "Elastic training"):
    # default "strict" written back and VALIDATED on every construction
    # path — a typo'd policy must fail here, not silently refuse (or
    # silently admit) a resized resume.  The HYDRAGNN_ELASTIC_RESUME env
    # knob overlays at trainer build time (env wins).
    from hydragnn_tpu.resilience.elastic import check_elastic_policy

    training["elastic_resume"] = check_elastic_policy(
        training.get("elastic_resume", "strict"))
    # ZeRO sharding stage (docs/SCALING.md §4): default 0 (replicated DP)
    # written back like the other Training defaults, and VALIDATED on every
    # construction path — a typo'd stage must fail here, not silently train
    # replicated while the operator believes memory is sharded.  The
    # HYDRAGNN_ZERO env knob overlays at trainer build time (env wins).
    from hydragnn_tpu.parallel.zero import check_zero_stage

    training["zero_stage"] = check_zero_stage(training.get("zero_stage", 0))
    # training dtype policy (docs/PERF.md PR-15): default "f32" written
    # back like the other Training defaults, and VALIDATED on every
    # construction path — a typo'd policy must fail here, not silently
    # train f32 while the operator believes bf16 is on.  The
    # HYDRAGNN_TRAIN_DTYPE env knob overlays at trainer build time.
    from hydragnn_tpu.quant import check_train_policy

    training["train_dtype_policy"] = check_train_policy(
        training.get("train_dtype_policy", "f32"))
    # graph sharding backend/knobs (docs/SCALING.md §6): defaults written
    # back like the other Training defaults, and VALIDATED on every
    # construction path — a typo'd backend must fail here, not silently
    # train unsharded while the operator believes a giant graph fits.  The
    # HYDRAGNN_GRAPH_SHARD* env knobs overlay at trainer build time.
    from hydragnn_tpu.graph.partition import (
        check_graph_shard_backend,
        check_partition_method,
        graph_shard_training_defaults,
    )

    for k, v in graph_shard_training_defaults().items():
        training.setdefault(k, v)
    training["graph_shard"] = check_graph_shard_backend(
        training["graph_shard"])
    training["graph_shard_method"] = check_partition_method(
        training["graph_shard_method"])
    # streaming data-plane knobs (docs/DATA.md): Dataset-section defaults
    # written back like the other sections, and VALIDATED on every
    # construction path — a typo'd order mode must fail here, not silently
    # fall back to the in-memory loader.  The HYDRAGNN_STREAM* env knobs
    # overlay at data-loading time (env wins).
    from hydragnn_tpu.data.stream.config import (
        check_stream_flag,
        check_stream_order,
        stream_dataset_defaults,
    )

    config.setdefault("Dataset", {})
    dataset = config["Dataset"]
    for k, v in stream_dataset_defaults().items():
        dataset.setdefault(k, v)
    dataset["stream"] = check_stream_flag(dataset["stream"])
    dataset["stream_order"] = check_stream_order(dataset["stream_order"])
    return config


class DatasetStats:
    """Host-side dataset statistics needed to finalize a config."""

    def __init__(
        self,
        num_nodes_sample: int,
        graph_size_variable: bool,
        pna_deg: Optional[Sequence[int]] = None,
        max_nodes: Optional[int] = None,
        max_edges: Optional[int] = None,
        minmax_node_feature: Optional[np.ndarray] = None,
        minmax_graph_feature: Optional[np.ndarray] = None,
    ):
        self.num_nodes_sample = num_nodes_sample
        self.graph_size_variable = graph_size_variable
        self.pna_deg = pna_deg
        self.max_nodes = max_nodes or num_nodes_sample
        self.max_edges = max_edges
        self.minmax_node_feature = minmax_node_feature
        self.minmax_graph_feature = minmax_graph_feature

    @staticmethod
    def from_samples(samples, need_deg: bool = False) -> "DatasetStats":
        """Compute stats by scanning host-side GraphSamples (degree histogram
        parity with reference gather_deg, hydragnn/preprocess/utils.py:177-195)."""
        sizes = {s.num_nodes for s in samples}
        max_nodes = max(s.num_nodes for s in samples)
        max_edges = max(s.num_edges for s in samples)
        pna_deg = None
        if need_deg:
            max_deg = 0
            for s in samples:
                if s.num_edges:
                    d = np.bincount(s.edge_index[1], minlength=s.num_nodes)
                    max_deg = max(max_deg, int(d.max()))
            hist = np.zeros(max_deg + 1, dtype=np.int64)
            for s in samples:
                d = (
                    np.bincount(s.edge_index[1], minlength=s.num_nodes)
                    if s.num_edges
                    else np.zeros(s.num_nodes, dtype=np.int64)
                )
                hist += np.bincount(d, minlength=max_deg + 1)
            pna_deg = hist.tolist()
        return DatasetStats(
            num_nodes_sample=samples[0].num_nodes,
            graph_size_variable=len(sizes) > 1,
            pna_deg=pna_deg,
            max_nodes=max_nodes,
            max_edges=max_edges,
        )


def head_specs_from_config(config: Dict[str, Any]) -> List[HeadSpec]:
    """Static head layout from a finalized config."""
    nn = config["NeuralNetwork"]
    var = nn["Variables_of_interest"]
    arch = nn["Architecture"]
    names = var.get("output_names", [f"head{i}" for i in range(len(var["type"]))])
    return [
        HeadSpec(name=names[i], type=t, dim=int(arch["output_dim"][i]))
        for i, t in enumerate(var["type"])
    ]


def label_slices_from_config(config):
    """Per-head (start, end) column slices into the packed graph_y / node_y
    sample arrays, from Dataset feature dims + output_index (parity with
    reference update_predicted_values, hydragnn/preprocess/utils.py:237-279)."""
    nn = config["NeuralNetwork"]
    var = nn["Variables_of_interest"]
    ds = config.get("Dataset", {})
    gdims = ds.get("graph_features", {}).get("dim", [])
    ndims = ds.get("node_features", {}).get("dim", [])
    gslices, nslices = [], []
    for t, idx in zip(var["type"], var["output_index"]):
        if t == "graph":
            lo = int(sum(gdims[:idx]))
            gslices.append((lo, lo + int(gdims[idx])))
            nslices.append((0, 0))
        else:
            lo = int(sum(ndims[:idx]))
            nslices.append((lo, lo + int(ndims[idx])))
            gslices.append((0, 0))
    return gslices, nslices


def normalize_output_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Fill ``Variables_of_interest.y_minmax`` from the serialized dataset's
    min/max headers so predictions can be denormalized (parity: reference
    normalize_output_config/update_config_minmax,
    hydragnn/utils/config_utils.py:192-240)."""
    var = config["NeuralNetwork"]["Variables_of_interest"]
    if not var.get("denormalize_output"):
        return config
    import pickle

    ds = config["Dataset"]
    base = os.environ.get("SERIALIZED_DATA_PATH", os.getcwd())
    label = "" if "total" in ds["path"] else "_train"
    fname = os.path.join(base, "serialized_dataset",
                         f"{ds['name']}{label}.pkl")
    with open(fname, "rb") as f:
        minmax_node = pickle.load(f)
        minmax_graph = pickle.load(f)
    y_minmax = []
    for t, idx in zip(var["type"], var["output_index"]):
        mm = minmax_graph if t == "graph" else minmax_node
        y_minmax.append([float(mm[0, idx]), float(mm[1, idx])])
    var["y_minmax"] = y_minmax
    return config


def get_log_name_config(config: Dict[str, Any]) -> str:
    """Run-name string, same fields as reference get_log_name_config
    (hydragnn/utils/config_utils.py:243-276)."""
    nn = config["NeuralNetwork"]
    arch, training = nn["Architecture"], nn["Training"]
    name = config["Dataset"]["name"]
    trimmed = name[: name.rfind("_") if name.rfind("_") > 0 else None]
    return (
        f"{arch['model_type']}-r-{arch.get('radius')}-ncl-{arch['num_conv_layers']}"
        f"-hd-{arch['hidden_dim']}-ne-{training['num_epoch']}"
        f"-lr-{training['Optimizer']['learning_rate']}-bs-{training['batch_size']}"
        f"-data-{trimmed}"
        f"-node_ft-{''.join(str(x) for x in nn['Variables_of_interest']['input_node_features'])}"
        f"-task_weights-{''.join(str(w) + '-' for w in arch['task_weights'])}"
    )


def save_config(config: Dict[str, Any], log_name: str, path: str = "./logs/") -> None:
    from hydragnn_tpu.resilience.ckpt_io import atomic_write_json

    os.makedirs(os.path.join(path, log_name), exist_ok=True)
    # atomic: the saved config.json is what `python -m hydragnn_tpu.serve`
    # later loads — a crash mid-write must not tear it
    atomic_write_json(os.path.join(path, log_name, "config.json"), config)
