from hydragnn_tpu.config.config import (
    ALL_MODEL_TYPES,
    DatasetStats,
    finalize,
    get_log_name_config,
    head_specs_from_config,
    label_slices_from_config,
    load_config,
    save_config,
)
