"""JSON-config-driven prediction entry point.

Parity: reference hydragnn/run_prediction.py:28-83 — rebuild data + model,
load the checkpoint saved by run_training, evaluate the test split, optionally
denormalize, and return (error, per-task error, true values, predictions).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict

from hydragnn_tpu.config.config import get_log_name_config
from hydragnn_tpu.data.load_data import dataset_loading_and_splitting
from hydragnn_tpu.models.base import ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.serve.engine import load_inference_state
from hydragnn_tpu.train.trainer import make_eval_step, test


@functools.singledispatch
def run_prediction(config, **kwargs):
    raise TypeError("Input must be filename string or configuration dictionary.")


@run_prediction.register
def _(config_file: str, **kwargs):
    with open(config_file, "r") as f:
        config = json.load(f)
    return run_prediction(config, **kwargs)


@run_prediction.register
def _(config: dict, logs_dir: str = "./logs/", seed: int = 0):
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())

    # same launcher-env bootstrap as run_training (no-op when already
    # initialized or single-process)
    from hydragnn_tpu.parallel.mesh import setup_distributed

    setup_distributed()

    from hydragnn_tpu.parallel.comm import num_processes, process_index
    import jax

    world_size, rank = num_processes(), process_index()

    train_loader, val_loader, test_loader, config = dataset_loading_and_splitting(
        config, rank=rank, world_size=world_size, seed=seed)

    cfg = ModelConfig.from_config(config["NeuralNetwork"])
    model = create_model(cfg)
    # inference-only restore: params + batch_stats straight from the
    # checkpoint — no optimizer init, no throwaway full train state
    # (shared with the serving engine, hydragnn_tpu/serve/engine.py)
    state = load_inference_state(config, logs_dir)

    eval_step = jax.jit(make_eval_step(model, cfg))
    error, tasks_error, true_values, predicted_values = test(
        eval_step, state, test_loader, cfg.num_heads,
        world_size=world_size, output_types=cfg.output_type)

    if config["NeuralNetwork"]["Variables_of_interest"].get(
            "denormalize_output"):
        from hydragnn_tpu.postprocess.postprocess import output_denormalize

        true_values, predicted_values = output_denormalize(
            config["NeuralNetwork"]["Variables_of_interest"]["y_minmax"],
            true_values,
            predicted_values,
        )

    viz = config.get("Visualization", {})
    if viz.get("create_plots") and rank == 0:
        from hydragnn_tpu.postprocess.visualizer import Visualizer

        log_name = get_log_name_config(config)

        var = config["NeuralNetwork"]["Variables_of_interest"]
        names = var.get("output_names",
                        [f"head{i}" for i in range(cfg.num_heads)])
        v = Visualizer(log_name, num_heads=cfg.num_heads,
                       head_dims=cfg.output_dim, logs_dir=logs_dir)
        v.create_scatter_plots(true_values, predicted_values, names)
        v.create_plot_global(true_values, predicted_values, names)
        for ih in range(cfg.num_heads):
            if int(cfg.output_dim[ih]) > 1:
                v.create_parity_plot_vector(
                    names[ih], true_values[ih], predicted_values[ih],
                    int(cfg.output_dim[ih]))
            else:
                v.create_parity_plot_and_error_histogram_scalar(
                    names[ih], true_values[ih], predicted_values[ih])

    return error, tasks_error, true_values, predicted_values
