"""Pluggable region tracer (parity: reference hydragnn/utils/tracer.py:40-155).

Module-level ``start``/``stop`` fan out to registered tracers.  The built-in
tracers are :class:`TimerTracer` (cumulative wall-clock regions, the GPTL
analog) and :class:`JaxProfilerTracer` (wraps regions in
``jax.profiler.TraceAnnotation`` so they show in TensorBoard/Perfetto traces).
A ``@profile`` decorator and ``timer`` contextmanager mirror the reference API.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Dict, Optional

_tracers: Dict[str, "Tracer"] = {}
_enabled = True


class Tracer:
    def start(self, name: str):  # pragma: no cover - interface
        ...

    def stop(self, name: str):  # pragma: no cover - interface
        ...

    def reset(self):
        ...


class TimerTracer(Tracer):
    """Named cumulative wall-clock regions (GPTL-style)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._open: Dict[str, float] = {}

    def start(self, name: str):
        self._open[name] = time.perf_counter()

    def stop(self, name: str):
        t0 = self._open.pop(name, None)
        if t0 is None:
            return
        self.totals[name] = self.totals.get(name, 0.0) + time.perf_counter() - t0
        self.counts[name] = self.counts.get(name, 0) + 1

    def reset(self):
        self.totals.clear()
        self.counts.clear()
        self._open.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {"total_s": v, "count": self.counts.get(k, 0)}
            for k, v in sorted(self.totals.items())
        }


class JaxProfilerTracer(Tracer):
    """Region names become jax.profiler trace annotations."""

    def __init__(self):
        self._open: Dict[str, object] = {}

    def start(self, name: str):
        import jax.profiler

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        self._open[name] = ann

    def stop(self, name: str):
        ann = self._open.pop(name, None)
        if ann is not None:
            ann.__exit__(None, None, None)


def initialize(timer: bool = True, jax_annotations: bool = False) -> None:
    _tracers.clear()
    if timer:
        _tracers["timer"] = TimerTracer()
    if jax_annotations:
        _tracers["jax"] = JaxProfilerTracer()


def has(name: str) -> bool:
    return name in _tracers


def get(name: str) -> Optional[Tracer]:
    return _tracers.get(name)


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def start(name: str):
    if _enabled:
        for t in _tracers.values():
            t.start(name)


def stop(name: str):
    if _enabled:
        for t in _tracers.values():
            t.stop(name)


def reset():
    for t in _tracers.values():
        t.reset()


def profile(name: str):
    """Decorator: trace the wrapped call (reference tracer.py:132-144)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            start(name)
            try:
                return fn(*args, **kwargs)
            finally:
                stop(name)

        return wrapped

    return deco


@contextlib.contextmanager
def timer(name: str):
    start(name)
    try:
        yield
    finally:
        stop(name)


def print_timers(verbosity: int = 0):
    t = _tracers.get("timer")
    if t is None:
        return
    from hydragnn_tpu.utils.print_utils import print_distributed

    for name, s in t.summary().items():
        print_distributed(
            verbosity,
            f"Timer {name}: total {s['total_s']:.4f}s over {int(s['count'])} calls",
        )


# default: timers on
initialize()
