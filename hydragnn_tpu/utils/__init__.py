from hydragnn_tpu.utils.print_utils import (
    iterate_tqdm,
    log,
    log0,
    print_distributed,
    print_master,
    setup_log,
)
from hydragnn_tpu.utils import tracer
from hydragnn_tpu.utils.time_utils import Timer, get_timer, print_timers, reset_timers
from hydragnn_tpu.utils.profile import Profiler
from hydragnn_tpu.utils.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from hydragnn_tpu.utils.slurm import check_remaining, parse_slurm_nodelist
