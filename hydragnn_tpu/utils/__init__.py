from hydragnn_tpu.utils.print_utils import (
    iterate_tqdm,
    log,
    log0,
    print_distributed,
    print_master,
    setup_log,
)
from hydragnn_tpu.utils import tracer
