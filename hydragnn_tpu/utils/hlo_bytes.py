"""Fusion-boundary HBM byte accounting from optimized HLO text.

Round-2 VERDICT flagged the bench roofline as self-contradicting: XLA's
``cost_analysis()["bytes accessed"]`` is *fusion-blind* — it sums the
per-primitive traffic of every op as if each ran alone, so a compiled
program that keeps intermediates inside fusions gets billed for bytes it
never moves (the r02 artifact implied 1.9x the v5e's HBM spec).

The honest structural model for XLA:TPU is the **fusion boundary**: each
top-level instruction of the optimized entry computation (fusion,
custom-call, dot, copy, ...) streams its operands from HBM and writes its
outputs back — VMEM does not persist between kernels.  So

    bytes/step = sum over entry instructions of (operand bytes + output bytes)

computed on ``jit(f).lower(...).compile().as_text()`` — the exact program
being timed.  Re-reads are counted once per consumer (each kernel really
does re-read), free ops (parameter/constant/tuple plumbing/bitcast) are
skipped, and Pallas custom calls are counted by their operand/result
shapes, which is precisely the traffic the kernel performs (each operand
is streamed once).

This is a *diagnostic estimate*, not a hardware counter, with two known
biases on scheduled TPU HLO: (a) buffers placed in non-default memory
spaces (``S(1)`` VMEM / ``S(2)`` SMEM annotations in the layout) never
touch HBM — they are skipped; (b) async DMA bookkeeping pairs
(``*-start``/``*-done``/``*-update``) alias their operands and would be
double-billed — they are skipped too, which UNDERcounts the sliced
prefetch reads they perform.  An operand shared by several consumers is
billed once per consumer (each kernel really does re-read it), which can
OVERcount when the scheduler keeps it resident.  ``bench.py``'s headline
roofline therefore uses the buffer-assignment method
(``compiled.memory_analysis()``: args + outputs + 2*temps) and keeps this
module for per-instruction attribution when a program's traffic needs to
be understood op by op.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3": 1, "f8e5m2fnuz": 1,
    "token": 0, "opaque": 0,
}

# one array shape: dtype[d0,d1,...]{layout}  (layout optional, dims optional)
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](\{[^}]*\})?")

# an entry-computation instruction:  %name = SHAPE op-name(...)
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.+?)\s+([\w\-]+)\(")

# ops that move no HBM bytes of their own
_FREE_OPS = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "add-dependency", "partition-id", "replica-id", "iota",
}


def shape_bytes(shape_text: str) -> int:
    """Total HBM bytes of one (possibly tuple) shape string.  Components
    whose layout carries a non-default memory space (``S(1)`` VMEM,
    ``S(2)`` SMEM, ...) never touch HBM and count zero."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_text):
        dtype, dims, layout = m.groups()
        if dtype not in _DTYPE_BYTES:
            continue  # e.g. stray words that look shape-like
        if layout and "S(" in layout:
            continue  # VMEM/SMEM-resident buffer
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def entry_fusion_boundary_bytes(hlo_text: str) -> Tuple[int, Dict[str, int]]:
    """(total bytes, per-instruction bytes) across the ENTRY computation.

    Parses the optimized HLO module text; for every non-free instruction in
    the entry computation sums output bytes plus the bytes of each operand
    (looked up from the operand's definition in the same computation).
    """
    # isolate the ENTRY computation body
    m = re.search(r"^ENTRY [^\n]*\{\s*$", hlo_text, re.M)
    if m is None:
        raise ValueError("no ENTRY computation found in HLO text")
    body_lines = []
    for line in hlo_text[m.end():].splitlines():
        if line.strip() == "}":
            break
        body_lines.append(line)

    defs: Dict[str, Tuple[str, str]] = {}  # name -> (shape text, op)
    parsed = []
    for line in body_lines:
        im = _INSTR_RE.match(line)
        if not im:
            continue
        name, shape_text, op = im.groups()
        defs[name] = (shape_text, op)
        # operand names: only inside the BALANCED top-level call parens —
        # %names in trailing attributes (control-predecessors={%a}, ...)
        # must not be billed as operands (round-3 advisor)
        start = line.index("(", im.end(3) - 1)
        depth, end = 0, len(line)
        for i in range(start, len(line)):
            if line[i] == "(":
                depth += 1
            elif line[i] == ")":
                depth -= 1
                if depth == 0:
                    end = i + 1
                    break
        operands = re.findall(r"%([\w.\-]+)", line[start:end])
        parsed.append((name, shape_text, op, operands))

    per_instr: Dict[str, int] = {}
    total = 0
    for name, shape_text, op, operands in parsed:
        if op in _FREE_OPS:
            continue
        # async DMA bookkeeping aliases its operand; billing both halves
        # double-counts (see module docstring)
        if op.endswith(("-start", "-done", "-update")):
            continue
        b = shape_bytes(shape_text)
        for o in operands:
            d = defs.get(o)
            if d is not None:
                b += shape_bytes(d[0])
        per_instr[name] = b
        total += b
    return total, per_instr


def compiled_fusion_boundary_bytes(compiled) -> Tuple[int, Dict[str, int]]:
    """Convenience wrapper over a ``jax`` compiled object."""
    return entry_fusion_boundary_bytes(compiled.as_text())
