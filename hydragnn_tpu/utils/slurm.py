"""Scheduler integration: SLURM time-limit graceful stop.

Parity: reference hydragnn/utils/distributed.py:46-77 (nodelist parsing) and
:287-312 (``check_remaining``: rank 0 scrapes ``squeue -o %L``, compares the
remaining walltime to the last epoch's duration and broadcasts a stop flag).
"""

from __future__ import annotations

import os
import re
import subprocess
from typing import List, Optional


def parse_slurm_nodelist(nodelist: str) -> List[str]:
    """Expand 'frontier[00001-00003,00007]' style SLURM nodelists
    (reference distributed.py:46-77)."""
    out: List[str] = []
    for m in re.finditer(r"([a-zA-Z0-9._-]+?)(?:\[([^\]]+)\])?(?:,|$)", nodelist):
        prefix, ranges = m.group(1), m.group(2)
        if not prefix:
            continue
        if ranges is None:
            out.append(prefix)
            continue
        for part in ranges.split(","):
            if "-" in part:
                lo, hi = part.split("-")
                width = len(lo)
                for i in range(int(lo), int(hi) + 1):
                    out.append(f"{prefix}{str(i).zfill(width)}")
            else:
                out.append(f"{prefix}{part}")
    return out


def _remaining_seconds() -> Optional[float]:
    """Remaining walltime of this SLURM job in seconds, or None."""
    job = os.getenv("SLURM_JOB_ID")
    if not job:
        return None
    try:
        txt = subprocess.run(
            ["squeue", "-h", "-j", job, "-o", "%L"],
            capture_output=True, text=True, timeout=30,
        ).stdout.strip()
    except (OSError, subprocess.SubprocessError):
        return None
    if not txt:
        return None
    # formats: [DD-]HH:MM:SS | MM:SS | SS
    days = 0
    if "-" in txt:
        d, txt = txt.split("-", 1)
        days = int(d)
    parts = [int(p) for p in txt.split(":")]
    while len(parts) < 3:
        parts.insert(0, 0)
    h, m, s = parts
    return days * 86400 + h * 3600 + m * 60 + s


def check_remaining(epoch_seconds: float, safety_factor: float = 2.0) -> bool:
    """True if there is time for another epoch; rank-0 decision broadcast to
    every host (reference distributed.py:287-312)."""
    from hydragnn_tpu.parallel.comm import host_broadcast_scalar, process_index

    ok = 1.0
    if process_index() == 0:
        remaining = _remaining_seconds()
        if remaining is not None and remaining < epoch_seconds * safety_factor:
            ok = 0.0
    return bool(host_broadcast_scalar(ok) > 0.5)
