"""Periodic-table element embeddings.

Parity: reference hydragnn/utils/atomicdescriptors.py:12-243, which pulls
element properties from the ``mendeleev`` package (group, period, covalent
radius, electronegativity, ionization energy, electron affinity) with
optional one-hot binning and a JSON cache.  ``mendeleev`` is not available
here, so the property tables are an embedded snapshot (standard Pauling
electronegativities and covalent radii); group/period are derived from the
atomic number.  When ``mendeleev`` is importable it is preferred.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

# Noble-gas atomic numbers bound each period.
_PERIOD_EDGES = [0, 2, 10, 18, 36, 54, 86, 118]

# Embedded property snapshot, Z = 1..86.
# Pauling electronegativity (0.0 where undefined, e.g. noble gases).
_ELECTRONEGATIVITY = [
    2.20, 0.00, 0.98, 1.57, 2.04, 2.55, 3.04, 3.44, 3.98, 0.00,
    0.93, 1.31, 1.61, 1.90, 2.19, 2.58, 3.16, 0.00, 0.82, 1.00,
    1.36, 1.54, 1.63, 1.66, 1.55, 1.83, 1.88, 1.91, 1.90, 1.65,
    1.81, 2.01, 2.18, 2.55, 2.96, 3.00, 0.82, 0.95, 1.22, 1.33,
    1.60, 2.16, 1.90, 2.20, 2.28, 2.20, 1.93, 1.69, 1.78, 1.96,
    2.05, 2.10, 2.66, 2.60, 0.79, 0.89, 1.10, 1.12, 1.13, 1.14,
    1.13, 1.17, 1.20, 1.20, 1.10, 1.22, 1.23, 1.24, 1.25, 1.10,
    1.27, 1.30, 1.50, 2.36, 1.90, 2.20, 2.20, 2.28, 2.54, 2.00,
    1.62, 2.33, 2.02, 2.00, 2.20, 0.00,
]
# Covalent radius in pm (single-bond).
_COVALENT_RADIUS = [
    31, 28, 128, 96, 84, 76, 71, 66, 57, 58,
    166, 141, 121, 111, 107, 105, 102, 106, 203, 176,
    170, 160, 153, 139, 139, 132, 126, 124, 132, 122,
    122, 120, 119, 120, 120, 116, 220, 195, 190, 175,
    164, 154, 147, 146, 142, 139, 145, 144, 142, 139,
    139, 138, 139, 140, 244, 215, 207, 204, 203, 201,
    199, 198, 198, 196, 194, 192, 192, 189, 190, 187,
    187, 175, 170, 162, 151, 144, 141, 136, 136, 132,
    145, 146, 148, 140, 150, 150,
]
# First ionization energy in eV.
_IONIZATION_ENERGY = [
    13.60, 24.59, 5.39, 9.32, 8.30, 11.26, 14.53, 13.62, 17.42, 21.56,
    5.14, 7.65, 5.99, 8.15, 10.49, 10.36, 12.97, 15.76, 4.34, 6.11,
    6.56, 6.83, 6.75, 6.77, 7.43, 7.90, 7.88, 7.64, 7.73, 9.39,
    6.00, 7.90, 9.81, 9.75, 11.81, 14.00, 4.18, 5.69, 6.22, 6.63,
    6.76, 7.09, 7.28, 7.36, 7.46, 8.34, 7.58, 8.99, 5.79, 7.34,
    8.61, 9.01, 10.45, 12.13, 3.89, 5.21, 5.58, 5.54, 5.47, 5.53,
    5.58, 5.64, 5.67, 6.15, 5.86, 5.94, 6.02, 6.11, 6.18, 6.25,
    5.43, 6.83, 7.55, 7.86, 7.83, 8.44, 8.97, 8.96, 9.23, 10.44,
    6.11, 7.42, 7.29, 8.42, 9.32, 10.75,
]


def group_period(z: int):
    """(group, period) derived from the atomic number."""
    period = next(
        i for i in range(1, len(_PERIOD_EDGES))
        if z <= _PERIOD_EDGES[i])
    start = _PERIOD_EDGES[period - 1]
    offset = z - start  # 1-based position within the period
    width = _PERIOD_EDGES[period] - start
    if width == 2:
        group = 1 if offset == 1 else 18
    elif width == 8:
        group = offset if offset <= 2 else offset + 10
    elif width == 18:
        group = offset
    else:  # lanthanides/actinides fold into group 3
        group = offset if offset <= 2 else (3 if offset <= 16 else offset - 14)
    return group, period


class atomicdescriptors:
    """Element embedding table (drop-in analog of the reference class)."""

    def __init__(
        self,
        embeddingfilename: Optional[str] = None,
        overwritten: bool = True,
        element_types: Optional[Sequence[str]] = None,
        one_hot: bool = False,
        max_z: int = 86,
    ):
        from hydragnn_tpu.data.raw import ATOMIC_NUMBERS

        self.one_hot = one_hot
        if element_types is None:
            zs = list(range(1, max_z + 1))
        else:
            zs = sorted(ATOMIC_NUMBERS[e] for e in element_types)
        self.zs = zs
        table: Dict[str, List[float]] = {}
        for z in zs:
            g, p = group_period(z)
            feats = [
                float(z),
                float(g),
                float(p),
                _ELECTRONEGATIVITY[z - 1],
                float(_COVALENT_RADIUS[z - 1]),
                _IONIZATION_ENERGY[z - 1],
            ]
            table[str(z)] = feats
        self.table = table

        # normalize each column to [0, 1]
        arr = np.asarray([table[str(z)] for z in zs], dtype=np.float64)
        lo, hi = arr.min(0), arr.max(0)
        span = np.where(hi > lo, hi - lo, 1.0)
        self.normalized = (arr - lo) / span
        if one_hot:
            eye = np.eye(len(zs))
            self.normalized = np.concatenate([eye, self.normalized], axis=1)

        if embeddingfilename and (
                overwritten or not os.path.exists(embeddingfilename)):
            from hydragnn_tpu.resilience.ckpt_io import atomic_write_json

            atomic_write_json(embeddingfilename,
                              {str(z): self.normalized[i].tolist()
                               for i, z in enumerate(zs)})

    def get_atom_features(self, z: int) -> np.ndarray:
        return self.normalized[self.zs.index(int(z))]
