"""jax.profiler wrapper (parity: reference hydragnn/utils/profile.py:9-70).

The reference wraps ``torch.profiler.profile`` with a wait/warmup/active
schedule and a TensorBoard trace handler, enabled per-epoch from the config's
``Profile`` section.  Here the same schedule gates ``jax.profiler`` traces
(viewable in TensorBoard/Perfetto/XProf).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from hydragnn_tpu.utils.env import env_flag, env_int, env_str


class Profiler:
    """Step-scheduled profiler: wait -> warmup -> active -> done.

    Config keys (reference profile.py:32-43): ``enable`` (int), ``wait``,
    ``warmup``, ``active``, ``trace_dir``.  Env knobs override the config
    (env wins, matching every other overlay in the tree), so a device
    trace can be captured on a deployed config without editing it:
    ``HYDRAGNN_PROFILE`` (enable), ``HYDRAGNN_PROFILE_WAIT``,
    ``HYDRAGNN_PROFILE_WARMUP``, ``HYDRAGNN_PROFILE_ACTIVE`` (schedule
    steps), ``HYDRAGNN_PROFILE_DIR`` (trace output directory).
    """

    def __init__(self, config: Optional[Dict[str, Any]] = None,
                 log_name: str = "run", logs_dir: str = "./logs/"):
        config = config or {}
        self.enabled = bool(int(config.get("enable", 0)))
        self.wait = int(config.get("wait", 5))
        self.warmup = int(config.get("warmup", 3))
        self.active = int(config.get("active", 3))
        self.trace_dir = config.get(
            "trace_dir", os.path.join(logs_dir, log_name, "trace"))
        if "HYDRAGNN_PROFILE" in os.environ:
            self.enabled = env_flag("HYDRAGNN_PROFILE")
        if "HYDRAGNN_PROFILE_WAIT" in os.environ:
            self.wait = env_int("HYDRAGNN_PROFILE_WAIT", self.wait)
        if "HYDRAGNN_PROFILE_WARMUP" in os.environ:
            self.warmup = env_int("HYDRAGNN_PROFILE_WARMUP", self.warmup)
        if "HYDRAGNN_PROFILE_ACTIVE" in os.environ:
            self.active = env_int("HYDRAGNN_PROFILE_ACTIVE", self.active)
        if "HYDRAGNN_PROFILE_DIR" in os.environ:
            self.trace_dir = env_str("HYDRAGNN_PROFILE_DIR", self.trace_dir)
        self._step = 0
        self._tracing = False
        self._done = False

    def setup(self, config: Optional[Dict[str, Any]]):
        """Re-arm from a config section (reference Profiler.setup)."""
        if config:
            self.__init__(config, os.path.basename(
                os.path.dirname(self.trace_dir)) or "run",
                os.path.dirname(os.path.dirname(self.trace_dir)) or "./logs/")
        return self

    def step(self) -> None:
        """Advance the schedule; start/stop the device trace at boundaries."""
        if not self.enabled or self._done:
            return
        start_at = self.wait + self.warmup
        stop_at = start_at + self.active
        if self._step == start_at and not self._tracing:
            import jax.profiler

            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._tracing = True
        self._step += 1
        if self._step >= stop_at and self._tracing:
            import jax.profiler

            jax.profiler.stop_trace()
            self._tracing = False
            self._done = True

    def disable(self):
        if self._tracing:
            import jax.profiler

            jax.profiler.stop_trace()
            self._tracing = False
        self.enabled = False


def annotate(name: str):
    """Context manager adding a named region to device traces."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)
