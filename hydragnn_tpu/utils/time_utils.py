"""Named cumulative timers with cross-rank reduction.

Parity: reference hydragnn/utils/time_utils.py:70-138 — every ``stop`` folds
the interval into a named cumulative total; ``print_timers`` reports
min/max/avg across ranks.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

_timers: Dict[str, "Timer"] = {}


class Timer:
    def __init__(self, name: str):
        self.name = name
        self.total = 0.0
        self.count = 0
        self._start: Optional[float] = None
        _timers[name] = self

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        if self._start is None:
            return
        self.total += time.perf_counter() - self._start
        self.count += 1
        self._start = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def get_timer(name: str) -> Timer:
    return _timers.get(name) or Timer(name)


def reset_timers() -> None:
    _timers.clear()


def print_timers(verbosity: int = 0) -> None:
    """Per-timer min/max/avg across hosts (reference time_utils.py:95-138)."""
    from hydragnn_tpu.parallel.comm import host_allgather, num_processes
    from hydragnn_tpu.utils.print_utils import print_distributed

    if not _timers:
        return
    names = sorted(_timers)
    totals = np.asarray([_timers[n].total for n in names])
    if num_processes() > 1:
        stacked = host_allgather(totals)  # [n_hosts, n_timers]
        mins, maxs, avgs = stacked.min(0), stacked.max(0), stacked.mean(0)
    else:
        mins = maxs = avgs = totals
    for i, n in enumerate(names):
        print_distributed(
            verbosity,
            f"Timer {n}: min {mins[i]:.4f}s  max {maxs[i]:.4f}s  "
            f"avg {avgs[i]:.4f}s  ({_timers[n].count} calls)",
        )
