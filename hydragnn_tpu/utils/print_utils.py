"""Verbosity-gated printing + run logging.

Parity: reference hydragnn/utils/print_utils.py:29-111 (5 verbosity levels,
rank-0 and per-rank variants, tqdm gating, file+console logging under
./logs/<run>/run.log).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable

_MAX_VERBOSITY_LEVELS = 5


def _rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # graftlint: disable=ROB001 (bootstrap probe; rank 0 is the safe answer pre-init)
        return 0


def print_nothing(*args, **kwargs):
    pass


def print_master(*args, **kwargs):
    if _rank() == 0:
        print(*args, **kwargs)


def print_all_processes(*args, **kwargs):
    print(f"[rank {_rank()}]", *args, **kwargs)


def print_distributed(verbosity_level: int, *args, **kwargs):
    """Levels 0: silent; 1-2: rank 0 only; 3-4: every rank (parity:
    reference print_distributed dispatch, print_utils.py:29-53)."""
    assert 0 <= verbosity_level < _MAX_VERBOSITY_LEVELS, "unknown verbosity"
    if verbosity_level in (1, 2):
        print_master(*args, **kwargs)
    elif verbosity_level in (3, 4):
        print_all_processes(*args, **kwargs)


def iterate_tqdm(iterator: Iterable, verbosity_level: int, **kwargs):
    """tqdm wrapping at verbosity 2/4 (reference print_utils.py:56-60)."""
    if verbosity_level in (2, 4):
        from tqdm import tqdm

        return tqdm(iterator, **kwargs)
    return iterator


_logger_initialized = False


def setup_log(log_name: str, logs_dir: str = "./logs/") -> None:
    """File+console logging with rank prefix (reference print_utils.py:63-91)."""
    global _logger_initialized
    d = os.path.join(logs_dir, log_name)
    os.makedirs(d, exist_ok=True)
    fmt = logging.Formatter(
        f"%(levelname)s (rank {_rank()}): %(message)s")
    root = logging.getLogger("hydragnn_tpu")
    root.setLevel(logging.INFO)
    root.handlers.clear()
    fh = logging.FileHandler(os.path.join(d, "run.log"))
    fh.setFormatter(fmt)
    root.addHandler(fh)
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    root.addHandler(sh)
    _logger_initialized = True


def log(*args):
    logging.getLogger("hydragnn_tpu").info(" ".join(str(a) for a in args))


def log0(*args):
    if _rank() == 0:
        log(*args)
