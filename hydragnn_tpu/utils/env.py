"""Env-var knob parsing — one definition of the repo's truthiness rule."""

from __future__ import annotations

import os


def env_flag(name: str) -> bool:
    """True unless the var is unset/empty/"0"/"false"/"False" (the repo
    convention: HYDRAGNN_VALTEST=0 disables)."""
    return os.environ.get(name, "") not in ("", "0", "false", "False")


def env_int(name: str, default: int = 0) -> int:
    return int(os.environ.get(name, str(default)) or default)


def env_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default) or default
