"""SMILES -> graph sample conversion.

Parity: reference hydragnn/utils/smiles_utils.py:49-117 (RDKit molecule to
graph with one-hot atom types, aromatic/hybridization flags, and bond-type
one-hot edge features).  RDKit is preferred when importable; otherwise a
native minimal SMILES parser covers the organic subset (B C N O P S F Cl Br I,
aromatic lowercase forms, brackets, branches, ring closures, bond orders) —
enough for QM9 / OGB-style molecule strings.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.graph.batch import GraphSample

_ORGANIC = ["B", "C", "N", "O", "P", "S", "F", "Cl", "Br", "I"]
_BOND_ORDER = {"-": 1.0, "=": 2.0, "#": 3.0, ":": 1.5}

# hybridization one-hot slots (reference uses rdkit's SP/SP2/SP3)
_HYB = ["SP", "SP2", "SP3"]


def parse_smiles(smiles: str) -> Tuple[List[Dict], List[Tuple[int, int, float]]]:
    """Minimal SMILES parser: returns (atoms, bonds).

    atoms: dicts with ``symbol`` and ``aromatic``; bonds: (i, j, order).
    """
    atoms: List[Dict] = []
    bonds: List[Tuple[int, int, float]] = []
    stack: List[int] = []
    rings: Dict[str, Tuple[int, float]] = {}
    prev = -1
    pending_order: Optional[float] = None
    i = 0
    n = len(smiles)
    while i < n:
        ch = smiles[i]
        if ch in "-=#:":
            pending_order = _BOND_ORDER[ch]
            i += 1
        elif ch == "(":
            stack.append(prev)
            i += 1
        elif ch == ")":
            prev = stack.pop()
            i += 1
        elif ch in "/\\.":
            i += 1  # stereo marks / disconnection: ignored
        elif ch == "[":
            j = smiles.index("]", i)
            body = smiles[i + 1 : j]
            m = re.match(r"\d*([A-Za-z][a-z]?)", body)
            sym = m.group(1)
            aromatic = sym.islower()
            atoms.append({"symbol": sym.capitalize(), "aromatic": aromatic})
            idx = len(atoms) - 1
            if prev >= 0:
                order = pending_order or (1.5 if aromatic and atoms[prev]["aromatic"] else 1.0)
                bonds.append((prev, idx, order))
            prev = idx
            pending_order = None
            i = j + 1
        elif ch == "%":
            label = smiles[i + 1 : i + 3]
            _close_ring(rings, label, prev, pending_order, bonds, atoms)
            pending_order = None
            i += 3
        elif ch.isdigit():
            _close_ring(rings, ch, prev, pending_order, bonds, atoms)
            pending_order = None
            i += 1
        else:
            two = smiles[i : i + 2]
            if two in ("Cl", "Br"):
                sym, aromatic, i = two, False, i + 2
            elif ch.isupper():
                sym, aromatic, i = ch, False, i + 1
            elif ch.islower():
                sym, aromatic, i = ch.upper(), True, i + 1
            else:
                raise ValueError(f"Cannot parse SMILES at '{ch}' in {smiles}")
            atoms.append({"symbol": sym, "aromatic": aromatic})
            idx = len(atoms) - 1
            if prev >= 0:
                order = pending_order or (
                    1.5 if aromatic and atoms[prev]["aromatic"] else 1.0)
                bonds.append((prev, idx, order))
            prev = idx
            pending_order = None
    return atoms, bonds


def _close_ring(rings, label, prev, pending_order, bonds, atoms):
    if label in rings:
        j, order0 = rings.pop(label)
        order = pending_order or order0 or (
            1.5 if atoms[prev]["aromatic"] and atoms[j]["aromatic"] else 1.0)
        bonds.append((j, prev, order))
    else:
        rings[label] = (prev, pending_order)


def _approx_hybridization(symbol: str, aromatic: bool, orders: List[float]) -> str:
    """SP/SP2/SP3 estimate from bond orders (native fallback for rdkit)."""
    if aromatic or any(o == 2.0 for o in orders):
        return "SP2"
    if any(o == 3.0 for o in orders):
        return "SP"
    return "SP3"


def generate_graphdata_from_smilestr(
    smilestr: str,
    ytarget,
    types: Optional[Dict[str, int]] = None,
    var_config=None,
) -> GraphSample:
    """SMILES string -> GraphSample with one-hot types + aromatic +
    hybridization node features and bond-order one-hot edge features."""
    types = types or {s: i for i, s in enumerate(_ORGANIC)}
    try:
        return _from_rdkit(smilestr, ytarget, types)
    except ImportError:
        pass
    atoms, bonds = parse_smiles(smilestr)
    n = len(atoms)
    x = np.zeros((n, len(types) + 1 + len(_HYB)), np.float32)
    orders_per_atom: List[List[float]] = [[] for _ in range(n)]
    for i, j, o in bonds:
        orders_per_atom[i].append(o)
        orders_per_atom[j].append(o)
    for idx, a in enumerate(atoms):
        x[idx, types[a["symbol"]]] = 1.0
        x[idx, len(types)] = 1.0 if a["aromatic"] else 0.0
        hyb = _approx_hybridization(
            a["symbol"], a["aromatic"], orders_per_atom[idx])
        x[idx, len(types) + 1 + _HYB.index(hyb)] = 1.0

    src, dst, eattr = [], [], []
    for i, j, o in bonds:
        onehot = [float(o == 1.0), float(o == 1.5), float(o == 2.0),
                  float(o == 3.0)]
        src += [i, j]
        dst += [j, i]
        eattr += [onehot, onehot]
    edge_index = (np.asarray([src, dst], np.int32)
                  if src else np.zeros((2, 0), np.int32))
    edge_attr = (np.asarray(eattr, np.float32)
                 if eattr else np.zeros((0, 4), np.float32))
    y = np.atleast_1d(np.asarray(ytarget, np.float32))
    return GraphSample(
        x=x, pos=np.zeros((n, 3), np.float32), edge_index=edge_index,
        edge_attr=edge_attr, graph_y=y, node_y=x)


def _from_rdkit(smilestr: str, ytarget, types: Dict[str, int]) -> GraphSample:
    from rdkit import Chem  # noqa: F401 - gated import

    mol = Chem.MolFromSmiles(smilestr)
    if mol is None:
        raise ValueError(f"RDKit could not parse: {smilestr}")
    n = mol.GetNumAtoms()
    x = np.zeros((n, len(types) + 1 + len(_HYB)), np.float32)
    for atom in mol.GetAtoms():
        i = atom.GetIdx()
        x[i, types[atom.GetSymbol()]] = 1.0
        x[i, len(types)] = 1.0 if atom.GetIsAromatic() else 0.0
        h = str(atom.GetHybridization())
        if h in _HYB:
            x[i, len(types) + 1 + _HYB.index(h)] = 1.0
    src, dst, eattr = [], [], []
    for bond in mol.GetBonds():
        i, j = bond.GetBeginAtomIdx(), bond.GetEndAtomIdx()
        o = bond.GetBondTypeAsDouble()
        onehot = [float(o == 1.0), float(o == 1.5), float(o == 2.0),
                  float(o == 3.0)]
        src += [i, j]
        dst += [j, i]
        eattr += [onehot, onehot]
    edge_index = (np.asarray([src, dst], np.int32)
                  if src else np.zeros((2, 0), np.int32))
    edge_attr = (np.asarray(eattr, np.float32)
                 if eattr else np.zeros((0, 4), np.float32))
    y = np.atleast_1d(np.asarray(ytarget, np.float32))
    return GraphSample(
        x=x, pos=np.zeros((n, 3), np.float32), edge_index=edge_index,
        edge_attr=edge_attr, graph_y=y, node_y=x)
