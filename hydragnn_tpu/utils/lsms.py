"""LSMS total-energy -> formation Gibbs energy dataset conversion.

Parity: reference utils/lsms/convert_total_energy_to_formation_gibbs.py:30-183
(binary alloys only): find the two pure-element configurations, compute each
sample's linear-mixing energy from the pure energies, subtract to get the
formation enthalpy, subtract T*S (ideal mixing entropy in Rydberg units) and
rewrite the header energy into a ``<dir>_gibbs_energy`` copy of the dataset.
"""

from __future__ import annotations

import math
import os
import shutil
from typing import Dict, List, Sequence, Tuple

import numpy as np
from scipy import special

# LSMS units (Rydberg)
_KB_JOULE_PER_KELVIN = 1.380649e-23
_JOULE_TO_RYDBERG = 4.5874208973812e17
_KB_RYDBERG_PER_KELVIN = _KB_JOULE_PER_KELVIN * _JOULE_TO_RYDBERG


def _read_file(path: str) -> Tuple[str, List[str]]:
    with open(path, "r") as f:
        lines = f.readlines()
    return lines[0].split()[0], lines


def compute_formation_enthalpy(
    elements_list: Sequence[float],
    pure_elements_energy: Dict[float, float],
    total_energy: float,
    atoms: np.ndarray,
) -> Tuple[float, float, float, float]:
    """(composition, linear_mixing_energy, formation_enthalpy, entropy)."""
    elements, counts = np.unique(atoms[:, 0], return_counts=True)
    for e in elements:
        assert e in elements_list, (
            f"Sample contains element {e} not present in the binary considered.")
    for pos, elem in enumerate(elements_list):
        if elem not in elements:
            elements = np.insert(elements, pos, elem)
            counts = np.insert(counts, pos, 0)
    num_atoms = atoms.shape[0]
    composition = counts[0] / num_atoms
    linear_mixing_energy = (
        pure_elements_energy[elements[0]] * composition
        + pure_elements_energy[elements[1]] * (1 - composition)
    ) * num_atoms
    formation_enthalpy = total_energy - linear_mixing_energy
    entropy = _KB_RYDBERG_PER_KELVIN * math.log(
        special.comb(num_atoms, counts[0]))
    return composition, linear_mixing_energy, formation_enthalpy, entropy


def convert_raw_data_energy_to_gibbs(
    dir: str,
    elements_list: Sequence[float],
    temperature_kelvin: float = 0.0,
    overwrite_data: bool = False,
    create_plots: bool = True,
) -> None:
    """Rewrite every LSMS file's header energy with the formation Gibbs
    energy into ``<dir>_gibbs_energy/`` (binary alloys only)."""
    dir = dir.rstrip("/")
    new_dir = dir + "_gibbs_energy/"
    if os.path.exists(new_dir) and overwrite_data:
        shutil.rmtree(new_dir)
    os.makedirs(new_dir, exist_ok=True)

    elements_list = sorted(elements_list)
    pure_elements_energy: Dict[float, float] = {}
    all_files = sorted(os.listdir(dir))
    for fname in all_files:
        total_energy_txt, lines = _read_file(os.path.join(dir, fname))
        atoms = np.loadtxt(lines[1:])
        atoms = np.atleast_2d(atoms)
        pure = np.unique(atoms[:, 0])
        if len(pure) == 1:
            pure_elements_energy[pure[0]] = (
                float(total_energy_txt) / atoms.shape[0])
    assert len(pure_elements_energy) == 2, "Must have two single element files."

    comp_l, h_l, g_l, te_l, lme_l = [], [], [], [], []
    for fname in all_files:
        path = os.path.join(dir, fname)
        total_energy_txt, lines = _read_file(path)
        atoms = np.atleast_2d(np.loadtxt(lines[1:]))
        comp, lme, enthalpy, entropy = compute_formation_enthalpy(
            elements_list, pure_elements_energy, float(total_energy_txt), atoms)
        gibbs = enthalpy - temperature_kelvin * entropy
        comp_l.append(comp)
        h_l.append(enthalpy)
        g_l.append(gibbs)
        te_l.append(float(total_energy_txt))
        lme_l.append(lme)
        lines[0] = lines[0].replace(total_energy_txt, str(gibbs))
        with open(os.path.join(new_dir, fname), "w") as f:
            f.write("".join(lines))

    if create_plots:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        for fig, (xs, ys, xl, yl, out) in enumerate([
            (te_l, lme_l, "Total energy (Rydberg)",
             "Linear mixing energy (Rydberg)", "linear_mixing_energy.png"),
            (comp_l, h_l, "Concentration",
             "Formation enthalpy (Rydberg)", "formation_enthalpy.png"),
            (comp_l, g_l, "Concentration",
             "Formation Gibbs energy (Rydberg)", "formation_gibbs_energy.png"),
        ]):
            plt.figure(fig)
            plt.scatter(xs, ys, edgecolor="b", facecolor="none")
            plt.xlabel(xl)
            plt.ylabel(yl)
            plt.savefig(out)
            plt.close()
