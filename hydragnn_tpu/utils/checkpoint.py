"""Orbax-backed full-train-state checkpointing with step-level resume.

Beyond the reference's single-pickle best-model file (reference
hydragnn/utils/model.py:58-103, which saves only model+optimizer state and
restarts at epoch 0), this saves the FULL train state — step counter, params,
batch statistics, optimizer state — with orbax's async-capable, sharded-array
aware format, so multi-host runs restore each shard in place.

CheckpointManagers are cached per directory and reused across calls for the
life of the process: constructing one is not free (directory scan, option
validation, and on multi-host runs a barrier), and the old
construct-save-close-per-call pattern also leaked the manager on the
``restore_checkpoint`` not-found path.  ``close_manager``/``close_managers``
release them explicitly (tests, or before deleting a checkpoint directory).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

_MANAGERS: Dict[str, Any] = {}


class CheckpointDeclinedError(RuntimeError):
    """Orbax refused the save (step <= the directory's latest) — a
    PERMANENT condition, not an I/O flake: resilience/ckpt_io.with_retries
    fails fast on it instead of burning retry/backoff time (which on the
    preemption path runs inside the SIGTERM grace window)."""


def _manager(directory: str, max_to_keep: int = 3):
    """Cached per-directory CheckpointManager (created on first use)."""
    import orbax.checkpoint as ocp

    key = os.path.abspath(directory)
    mgr = _MANAGERS.get(key)
    if mgr is None:
        mgr = ocp.CheckpointManager(
            key,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )
        _MANAGERS[key] = mgr
    return mgr


def _reload(mgr) -> None:
    """Refresh the manager's cached step listing from disk — another
    process (a preempted run we are resuming after) may have written steps
    this manager has never seen."""
    reload_fn = getattr(mgr, "reload", None)
    if callable(reload_fn):
        try:
            reload_fn()
        except Exception:  # graftlint: disable=ROB001 (orbax reload is advisory; stale listing beats a crash)
            pass


def close_manager(directory: str) -> None:
    """Close and forget the cached manager for one directory (call before
    deleting the directory out from under it)."""
    mgr = _MANAGERS.pop(os.path.abspath(directory), None)
    if mgr is not None:
        try:
            mgr.close()
        except Exception:  # graftlint: disable=ROB001 (manager close is best-effort at teardown)
            pass


def close_managers() -> None:
    """Close every cached manager (test teardown / process shutdown)."""
    for key in list(_MANAGERS):
        close_manager(key)


def save_checkpoint(state, directory: str, step: Optional[int] = None,
                    max_to_keep: int = 3) -> None:
    """Save the full TrainState under ``directory/<step>``.

    A duplicate step raises (orbax's behavior).  Deliberately NOT
    delete-then-save: destroying the existing copy before the new one is
    finalized would turn a failed re-save into data loss — callers that
    can legitimately hit the same step twice (the resume bundle) skip the
    redundant save instead (resilience/resume.py).

    Orbax silently DECLINES (returns False, no exception) a save at a
    step <= the directory's latest — e.g. a stale checkpoint tree from an
    earlier run with a different steps-per-epoch numbering.  That is
    raised here as an error: callers' retry/degradation ladders
    (resilience/ckpt_io.with_retries) must see "nothing was saved", not
    report success and leave the old state as the latest checkpoint.
    """
    import orbax.checkpoint as ocp

    mgr = _manager(directory, max_to_keep)
    step = int(state.step) if step is None else int(step)
    _reload(mgr)
    saved = mgr.save(step, args=ocp.args.StandardSave(
        {"state": jax.device_get(state)}))
    mgr.wait_until_finished()
    if not saved:
        raise CheckpointDeclinedError(
            f"orbax declined to save step {step} in {directory} "
            f"(latest={mgr.latest_step()}) — stale higher-step checkpoints "
            "present?")


def restore_checkpoint(state, directory: str,
                       step: Optional[int] = None):
    """Restore into the given state skeleton; latest step when unspecified."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    if step is None:
        _reload(mgr)
        step = mgr.latest_step()
    else:
        step = int(step)
    if step is None:
        # the cached manager stays open for reuse — no per-call leak
        raise FileNotFoundError(f"No checkpoints under {directory}")
    restored = mgr.restore(
        step, args=ocp.args.StandardRestore({"state": state}))
    return restored["state"]


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    _reload(mgr)
    return mgr.latest_step()
