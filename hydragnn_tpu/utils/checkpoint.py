"""Orbax-backed full-train-state checkpointing with step-level resume.

Beyond the reference's single-pickle best-model file (reference
hydragnn/utils/model.py:58-103, which saves only model+optimizer state and
restarts at epoch 0), this saves the FULL train state — step counter, params,
batch statistics, optimizer state — with orbax's async-capable, sharded-array
aware format, so multi-host runs restore each shard in place.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _manager(directory: str, max_to_keep: int = 3):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True),
    )


def save_checkpoint(state, directory: str, step: Optional[int] = None,
                    max_to_keep: int = 3) -> None:
    """Save the full TrainState under ``directory/<step>``."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory, max_to_keep)
    step = int(state.step) if step is None else int(step)
    mgr.save(step, args=ocp.args.StandardSave(
        {"state": jax.device_get(state)}))
    mgr.wait_until_finished()
    mgr.close()


def restore_checkpoint(state, directory: str,
                       step: Optional[int] = None):
    """Restore into the given state skeleton; latest step when unspecified."""
    import orbax.checkpoint as ocp

    mgr = _manager(directory)
    step = mgr.latest_step() if step is None else int(step)
    if step is None:
        raise FileNotFoundError(f"No checkpoints under {directory}")
    restored = mgr.restore(
        step, args=ocp.args.StandardRestore({"state": state}))
    mgr.close()
    return restored["state"]


def latest_step(directory: str) -> Optional[int]:
    import orbax.checkpoint as ocp

    if not os.path.isdir(directory):
        return None
    mgr = _manager(directory)
    out = mgr.latest_step()
    mgr.close()
    return out
