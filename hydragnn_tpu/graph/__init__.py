from hydragnn_tpu.graph.batch import (
    GraphBatch,
    GraphSample,
    HeadSpec,
    PadSpec,
    collate,
    default_label_slices,
)
from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.neighborlist import (
    radius_graph,
    radius_graph_pbc,
    edge_lengths,
    normalize_rotation,
)
