from hydragnn_tpu.graph.batch import (
    GraphBatch,
    GraphSample,
    HeadSpec,
    PadSpec,
    collate,
    default_label_slices,
)
from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.partition import (
    GraphShardConfig,
    HaloBatch,
    ShardPlan,
    ShardedGraphLoader,
    apply_plan,
    build_shard_plan,
    shard_batch_halo,
)
from hydragnn_tpu.graph.neighborlist import (
    radius_graph,
    radius_graph_pbc,
    edge_lengths,
    normalize_rotation,
)
