"""Padded, static-shape graph batch — the core data structure of the framework.

TPU-first redesign of the reference's dynamic PyG ``Batch`` (HydraGNN collates
variable-size graphs with ``Batch.from_data_list``; see reference
hydragnn/preprocess/load_data.py:226-297).  XLA requires static shapes, so we
batch graphs jraph-style: concatenate nodes/edges of all graphs in the batch,
then pad nodes, edges and graphs up to a fixed ``PadSpec``.  Padding nodes are
assigned to a trailing *padding graph* (the last graph slot), padding edges
connect the last (padding) node to itself, and boolean masks record validity.

The multi-head label layout is *static*: instead of the reference's per-batch
``data.y``/``y_loc`` offset bookkeeping computed on CPU every step
(reference hydragnn/train/train_validate_test.py:287-350), the batcher emits
one label array per head — graph-level heads get ``[num_graphs, dim]``,
node-level heads get ``[num_nodes, dim]`` — so the loss is a masked mean with
no runtime index computation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from flax import struct


@dataclasses.dataclass(frozen=True)
class HeadSpec:
    """Static description of one prediction head (one task).

    Mirrors the information the reference spreads across
    ``Variables_of_interest.type``/``output_index``/``output_dim``
    (reference hydragnn/utils/config_utils.py:153-189).
    """

    name: str
    type: str  # "graph" | "node"
    dim: int   # feature dimension of this head's output (per graph or per node)


@dataclasses.dataclass(frozen=True)
class PadSpec:
    """Static padded sizes of a batch: everything XLA needs to know."""

    num_nodes: int
    num_edges: int
    num_graphs: int  # includes the trailing padding graph

    def __post_init__(self):
        assert self.num_nodes >= 1 and self.num_graphs >= 1

    @staticmethod
    def for_batch(
        batch_size: int,
        max_nodes_per_graph: int,
        max_edges_per_graph: int,
        round_to: int = 8,
    ) -> "PadSpec":
        """Pad spec for batches of up to ``batch_size`` graphs.

        One extra node/graph slot is reserved for padding; sizes are rounded
        up so the per-batch shapes hit TPU-friendly multiples.
        """

        def _round(x: int) -> int:
            return int(-(-x // round_to) * round_to)

        return PadSpec(
            num_nodes=_round(batch_size * max_nodes_per_graph + 1),
            num_edges=_round(batch_size * max_edges_per_graph + 1),
            num_graphs=batch_size + 1,
        )


@struct.dataclass
class GraphBatch:
    """A padded batch of graphs as a JAX pytree.

    Shapes (all static):
      x:          [N, F]   node input features
      pos:        [N, 3]   node positions
      senders:    [E]      edge source node index (message source)
      receivers:  [E]      edge destination node index (aggregation site)
      edge_attr:  [E, Fe]  or None
      node_gid:   [N]      graph id per node (padding nodes -> last graph)
      node_mask:  [N]      1.0 for real nodes
      edge_mask:  [E]      1.0 for real edges
      graph_mask: [G]      1.0 for real graphs
      labels:     tuple of per-head label arrays; graph heads [G, dim],
                  node heads [N, dim] (ordering matches the HeadSpec list)
      cell:       [G, 3, 3] periodic cell per graph, or None
      extras:     dict of auxiliary per-batch arrays (e.g. energy scaling)
    """

    x: jax.Array
    pos: jax.Array
    senders: jax.Array
    receivers: jax.Array
    edge_attr: Optional[jax.Array]
    node_gid: jax.Array
    node_mask: jax.Array
    edge_mask: jax.Array
    graph_mask: jax.Array
    labels: Tuple[jax.Array, ...]
    cell: Optional[jax.Array] = None
    extras: Dict[str, jax.Array] = struct.field(default_factory=dict)

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def num_edges(self) -> int:
        return self.senders.shape[0]

    @property
    def num_graphs(self) -> int:
        return self.graph_mask.shape[0]

    @property
    def n_real_graphs(self) -> jax.Array:
        return jnp.sum(self.graph_mask)


class GraphSample:
    """One host-side graph sample (numpy).

    The host-side analog of a PyG ``Data`` object: node features ``x``,
    positions ``pos``, optional precomputed edges, and packed label arrays
    (``graph_y``/``node_y``) that :func:`collate` slices into per-head
    labels via :func:`default_label_slices` or
    ``config.label_slices_from_config``.
    """

    __slots__ = (
        "x",
        "pos",
        "edge_index",
        "edge_attr",
        "graph_y",
        "node_y",
        "cell",
        "extras",
    )

    def __init__(
        self,
        x: np.ndarray,
        pos: np.ndarray,
        edge_index: Optional[np.ndarray] = None,
        edge_attr: Optional[np.ndarray] = None,
        graph_y: Optional[np.ndarray] = None,
        node_y: Optional[np.ndarray] = None,
        cell: Optional[np.ndarray] = None,
        extras: Optional[Dict[str, np.ndarray]] = None,
    ):
        self.x = np.asarray(x, dtype=np.float32)
        self.pos = np.asarray(pos, dtype=np.float32)
        self.edge_index = (
            None if edge_index is None else np.asarray(edge_index, dtype=np.int32)
        )
        self.edge_attr = (
            None if edge_attr is None else np.asarray(edge_attr, dtype=np.float32)
        )
        self.graph_y = (
            None if graph_y is None else np.asarray(graph_y, dtype=np.float32)
        )
        self.node_y = None if node_y is None else np.asarray(node_y, dtype=np.float32)
        self.cell = None if cell is None else np.asarray(cell, dtype=np.float32)
        self.extras = extras or {}

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def num_edges(self) -> int:
        return 0 if self.edge_index is None else self.edge_index.shape[1]


def collate(
    samples: Sequence[GraphSample],
    pad: PadSpec,
    head_specs: Sequence[HeadSpec],
    graph_feature_slices: Optional[Sequence[Tuple[int, int]]] = None,
    node_feature_slices: Optional[Sequence[Tuple[int, int]]] = None,
) -> GraphBatch:
    """Collate + pad host-side samples into a static-shape ``GraphBatch``.

    ``graph_feature_slices`` / ``node_feature_slices`` give, per head, the
    ``(start, end)`` column slice into ``sample.graph_y`` / ``sample.node_y``
    from which that head's labels are taken.  When omitted, heads consume
    consecutive slices by their declared dim.
    """

    n_samp = len(samples)
    if n_samp > pad.num_graphs - 1:
        raise ValueError(
            f"batch of {n_samp} graphs exceeds pad spec {pad.num_graphs - 1}"
        )
    tot_nodes = sum(s.num_nodes for s in samples)
    tot_edges = sum(s.num_edges for s in samples)
    if tot_nodes > pad.num_nodes - 1 or tot_edges > pad.num_edges:
        raise ValueError(
            f"batch ({tot_nodes} nodes, {tot_edges} edges) exceeds pad spec "
            f"({pad.num_nodes - 1}, {pad.num_edges})"
        )

    fdim = samples[0].x.shape[1] if samples[0].x.ndim > 1 else 1
    N, E, G = pad.num_nodes, pad.num_edges, pad.num_graphs

    # Vectorized packing: one np.concatenate per field instead of a 512-way
    # Python assignment loop (the loop was the input-pipeline bottleneck —
    # slower than the chip's step rate at flagship batch sizes).
    node_counts = np.fromiter(
        (s.num_nodes for s in samples), np.int64, count=n_samp)
    edge_counts = np.fromiter(
        (s.num_edges for s in samples), np.int64, count=n_samp)
    node_offs = np.zeros(n_samp, np.int64)
    np.cumsum(node_counts[:-1], out=node_offs[1:])

    x = np.zeros((N, fdim), np.float32)
    xs_list = [s.x if s.x.ndim > 1 else s.x[:, None] for s in samples]
    np.concatenate(xs_list, axis=0, out=x[:tot_nodes])
    pos = np.zeros((N, 3), np.float32)
    np.concatenate([s.pos for s in samples], axis=0, out=pos[:tot_nodes])

    senders = np.full((E,), N - 1, np.int32)
    receivers = np.full((E,), N - 1, np.int32)
    has_edge_attr = samples[0].edge_attr is not None
    edge_attr = None
    if has_edge_attr:
        ea_dim = samples[0].edge_attr.shape[1]
        edge_attr = np.zeros((E, ea_dim), np.float32)
    if tot_edges:
        ei = np.concatenate(
            [s.edge_index for s in samples if s.num_edges], axis=1)
        edge_shift = np.repeat(node_offs, edge_counts).astype(np.int32)
        senders[:tot_edges] = ei[0] + edge_shift
        receivers[:tot_edges] = ei[1] + edge_shift
        if has_edge_attr:
            np.concatenate(
                [s.edge_attr for s in samples if s.num_edges],
                axis=0, out=edge_attr[:tot_edges])

    node_gid = np.full((N,), G - 1, np.int32)
    node_gid[:tot_nodes] = np.repeat(
        np.arange(n_samp, dtype=np.int32), node_counts)
    node_mask = np.zeros((N,), np.float32)
    node_mask[:tot_nodes] = 1.0
    edge_mask = np.zeros((E,), np.float32)
    edge_mask[:tot_edges] = 1.0
    graph_mask = np.zeros((G,), np.float32)
    graph_mask[:n_samp] = 1.0

    has_cell = samples[0].cell is not None
    cell = None
    if has_cell:
        cell = np.zeros((G, 3, 3), np.float32)
        np.stack([s.cell for s in samples], axis=0, out=cell[:n_samp])

    # Per-head labels with a static layout.
    if graph_feature_slices is None and node_feature_slices is None:
        graph_feature_slices, node_feature_slices = default_label_slices(head_specs)
    elif graph_feature_slices is None or node_feature_slices is None:
        raise ValueError(
            "graph_feature_slices and node_feature_slices must be given together"
        )
    labels: List[np.ndarray] = []
    # One flat [n_samp, gy_dim] view of the packed graph labels, sliced per
    # head — avoids a per-sample loop per head.  Only pack a label type some
    # head consumes, and only when every sample carries it with a uniform
    # width; otherwise fall back to the per-sample loop (which tolerates
    # ragged/missing label arrays as long as each head's slice is valid).
    gy = ny = None
    if any(h.type == "graph" for h in head_specs):
        if all(s.graph_y is not None for s in samples):
            gys = [np.asarray(s.graph_y).reshape(-1) for s in samples]
            if all(a.shape == gys[0].shape for a in gys):
                gy = np.stack(gys)
    if any(h.type == "node" for h in head_specs):
        if all(s.node_y is not None for s in samples):
            nys = [s.node_y for s in samples]
            if all(a.ndim == 2 and a.shape[1] == nys[0].shape[1] for a in nys):
                ny = np.concatenate(nys, axis=0)
    for i, h in enumerate(head_specs):
        if h.type == "graph":
            lab = np.zeros((G, h.dim), np.float32)
            lo, hi = graph_feature_slices[i]
            if gy is not None:
                lab[:n_samp] = gy[:, lo:hi]
            else:
                for gid, s in enumerate(samples):
                    if s.graph_y is not None:
                        lab[gid] = np.asarray(s.graph_y).reshape(-1)[lo:hi]
        else:
            lab = np.zeros((N, h.dim), np.float32)
            lo, hi = node_feature_slices[i]
            if ny is not None:
                lab[:tot_nodes] = ny[:, lo:hi]
            else:
                node_off = 0
                for s in samples:
                    n = s.num_nodes
                    if s.node_y is not None:
                        lab[node_off : node_off + n] = s.node_y[:, lo:hi]
                    node_off += n
        labels.append(lab)

    extras: Dict[str, np.ndarray] = {}
    # HYDRAGNN_AGGR_BACKEND=fused: attach the sender-sorted edge permutation
    # the fused message-passing kernel's backward needs
    # (ops/fused_mp.py) — only when the kernel's block-locality invariant
    # holds (every graph fits one node block).  All other invariants
    # (nondecreasing receivers, contiguous graphs, intra-graph edges) hold
    # by construction of this function; the models fall back to the XLA
    # path whenever the permutation is absent.
    from hydragnn_tpu.ops.aggregate import aggr_backend

    if aggr_backend() == "fused":
        from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK

        max_nodes = int(max((s.num_nodes for s in samples), default=0))
        # receivers must ACTUALLY be nondecreasing — true for edges built by
        # graph/neighborlist, but stored edge lists (gpack/pickle written by
        # external pipelines) carry arbitrary order and would make the
        # kernel's steered ranges silently wrong
        recv_sorted = bool(np.all(np.diff(receivers[:tot_edges]) >= 0))
        if max_nodes <= _NODE_BLOCK and recv_sorted:
            extras["edge_perm_sender"] = np.argsort(
                senders, kind="stable").astype(np.int32)
    if samples[0].extras:
        for k in samples[0].extras:
            v0 = np.asarray(samples[0].extras[k])
            if v0.shape and v0.shape[0] == samples[0].num_nodes:
                # per-node extra: concatenate + pad like node features
                arr = np.zeros((N,) + v0.shape[1:], np.float32)
                np.concatenate(
                    [np.asarray(s.extras[k], np.float32)
                     for s in samples], axis=0, out=arr[:tot_nodes])
            else:
                # per-graph extra (scalar or fixed-shape array per graph)
                arr = np.zeros((G,) + v0.shape, np.float32)
                arr[:n_samp] = np.stack(
                    [np.asarray(s.extras[k], np.float32) for s in samples])
            extras[k] = arr

    return GraphBatch(
        x=x,
        pos=pos,
        senders=senders,
        receivers=receivers,
        edge_attr=edge_attr,
        node_gid=node_gid,
        node_mask=node_mask,
        edge_mask=edge_mask,
        graph_mask=graph_mask,
        labels=tuple(labels),
        cell=cell,
        extras=extras,
    )


def default_label_slices(
    head_specs: Sequence[HeadSpec],
) -> Tuple[List[Tuple[int, int]], List[Tuple[int, int]]]:
    """Consecutive column slices for heads into packed graph_y / node_y."""
    gslices: List[Tuple[int, int]] = []
    nslices: List[Tuple[int, int]] = []
    goff = noff = 0
    for h in head_specs:
        if h.type == "graph":
            gslices.append((goff, goff + h.dim))
            nslices.append((0, 0))
            goff += h.dim
        else:
            nslices.append((noff, noff + h.dim))
            gslices.append((0, 0))
            noff += h.dim
    return gslices, nslices
