"""Spatial graph partitioning + halo specs: ONE giant graph across the mesh.

The memory problem ZeRO (parallel/zero.py) does not touch: a single graph's
node/edge arrays must fit one device.  This module partitions a collated
:class:`GraphBatch`'s nodes into D contiguous shards with a locality-aware
reorder (BFS / space-filling curve on positions — few cut edges), reindexes
edges so each shard owns its receiver-local edges, and precomputes per-shard
**halo specs**: which remote node rows each peer shard must contribute so
the shard can run the UNCHANGED message-passing stack on ``local + halo``
rows.

The halo is **L-hop** (L = the model's conv depth by default): shard *d*'s
extended subgraph contains every node within L hops upstream of its local
nodes and every edge whose receiver is within L-1 hops, so after L
message-passing layers the LOCAL rows are exactly the values the
single-device run computes — one halo exchange per step, no per-layer
communication, no model rewrites.  Boundary work is duplicated (each shard
recomputes its halo rows' intermediate layers), which is the classic
halo-replication trade: per-device residency drops from N to
``N/D + halo``, at the price of recomputing an L-deep boundary layer.

At run time (parallel/mesh.py:make_halo_train_step) the halo rows are
gathered with one ``all_to_all`` into a bounded ``[D * halo_pair]`` buffer
(static, bucketed like PadSpec so topology jitter does not recompile), and
the collective's transpose reduce-scatters halo cotangents back to their
owner shards in the VJP — jax AD derives it from the forward exchange.

Graph-level reductions (mean pooling, masked BatchNorm statistics, the
masked-mean losses) are made shard-aware through the trace-time
:func:`halo_context` / :func:`halo_psum` hooks in graph/segment.py and
models/layers.py: partial per-shard sums and counts are ``psum``-ed across
the mesh axis, so SyncBatchNorm semantics and exact global losses hold with
graphs that span shards.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from flax import struct

from hydragnn_tpu.graph.batch import GraphBatch
from hydragnn_tpu.utils.env import env_int, env_str

GRAPH_SHARD_BACKENDS = ("off", "halo", "gspmd")
PARTITION_METHODS = ("sfc", "bfs", "block")
# conv stacks whose message passing is strictly 1-hop per layer: the L-hop
# halo argument holds.  DimeNet's triplet (edge-to-edge) interactions need
# edge-adjacency halos this module does not build — it falls back loudly.
HALO_SUPPORTED_MODELS = (
    "SAGE", "GIN", "GAT", "MFC", "PNA", "CGCNN", "SchNet", "EGNN")


# ---------------------------------------------------------------------------
# trace-time halo context: makes the global reductions shard-aware
# ---------------------------------------------------------------------------

_HALO_AXES: Any = None


@contextlib.contextmanager
def halo_context(axes):
    """Trace-time marker: while active, the framework's graph-global
    reductions (masked_mean_pool, MaskedBatchNorm statistics, the masked
    mean losses) psum their partial sums/counts over ``axes``.  Entered by
    the halo step builders around the model trace — Python-level state read
    at trace time, never inside compiled code."""
    global _HALO_AXES
    prev = _HALO_AXES
    _HALO_AXES = axes
    try:
        yield
    finally:
        _HALO_AXES = prev


def halo_axes():
    """The active halo mesh axis (or None outside a halo trace)."""
    return _HALO_AXES


def halo_psum(x):
    """psum over the halo axis when a halo trace is active, else identity.
    The one hook point the shard-aware reductions call."""
    if _HALO_AXES is None:
        return x
    return jax.lax.psum(x, _HALO_AXES)


# ---------------------------------------------------------------------------
# config knobs (Training section + HYDRAGNN_GRAPH_SHARD* env, env wins)
# ---------------------------------------------------------------------------


def check_graph_shard_backend(value: Any) -> str:
    """Normalize/validate a ``graph_shard`` knob value to a backend name.
    Accepts the repo's flag spellings: unset/empty/"0"/"off"/False -> off,
    "1"/True/"halo" -> halo, "gspmd" -> gspmd."""
    if value in (None, False, 0, "", "0", "off", "false", "False"):
        return "off"
    if value in (True, 1, "1", "halo", "true", "True"):
        return "halo"
    if value == "gspmd":
        return "gspmd"
    raise ValueError(
        f"graph_shard must be one of {GRAPH_SHARD_BACKENDS} (or 0/1), "
        f"got {value!r}")


def check_partition_method(value: Any) -> str:
    v = str(value or "sfc")
    if v not in PARTITION_METHODS:
        raise ValueError(
            f"graph_shard_method must be one of {PARTITION_METHODS}, "
            f"got {value!r}")
    return v


@dataclasses.dataclass
class GraphShardConfig:
    """Parsed graph-sharding knobs (``Training`` section + env, env wins).

    Env knobs: HYDRAGNN_GRAPH_SHARD, HYDRAGNN_GRAPH_SHARD_METHOD,
    HYDRAGNN_GRAPH_SHARD_HOPS, HYDRAGNN_GRAPH_SHARD_HALO_MAX.
    """

    backend: str = "off"    # off | halo | gspmd
    method: str = "sfc"     # sfc | bfs | block
    hops: int = 0           # halo depth; 0 = the model's num_conv_layers
    halo_max: int = 0       # per-peer halo row cap; 0 = auto (bucketed)

    @classmethod
    def from_training(cls, training: Optional[Dict[str, Any]]
                      ) -> "GraphShardConfig":
        s = dict(training or {})
        d = cls()
        cfg = cls(
            backend=check_graph_shard_backend(
                s.get("graph_shard", d.backend)),
            method=check_partition_method(
                s.get("graph_shard_method", d.method)),
            hops=int(s.get("graph_shard_hops", d.hops)),
            halo_max=int(s.get("graph_shard_halo_max", d.halo_max)),
        )
        # set-but-EMPTY env falls through to the config value (the repo's
        # env-knob convention, utils/env.py)
        if os.environ.get("HYDRAGNN_GRAPH_SHARD"):
            cfg.backend = check_graph_shard_backend(
                os.environ["HYDRAGNN_GRAPH_SHARD"])
        if os.environ.get("HYDRAGNN_GRAPH_SHARD_METHOD"):
            cfg.method = check_partition_method(
                env_str("HYDRAGNN_GRAPH_SHARD_METHOD", d.method))
        if os.environ.get("HYDRAGNN_GRAPH_SHARD_HOPS"):
            cfg.hops = env_int("HYDRAGNN_GRAPH_SHARD_HOPS", d.hops)
        if os.environ.get("HYDRAGNN_GRAPH_SHARD_HALO_MAX"):
            cfg.halo_max = env_int("HYDRAGNN_GRAPH_SHARD_HALO_MAX",
                                   d.halo_max)
        if cfg.hops < 0:
            raise ValueError(f"graph_shard_hops must be >= 0, got {cfg.hops}")
        if cfg.halo_max < 0:
            raise ValueError(
                f"graph_shard_halo_max must be >= 0, got {cfg.halo_max}")
        return cfg


def graph_shard_training_defaults() -> Dict[str, Any]:
    """``Training``-section defaults written back by config.finalize, so a
    saved config.json documents the run's graph-sharding settings."""
    d = GraphShardConfig()
    return {
        "graph_shard": d.backend,
        "graph_shard_method": d.method,
        "graph_shard_hops": d.hops,
        "graph_shard_halo_max": d.halo_max,
    }


# ---------------------------------------------------------------------------
# locality-aware node orders
# ---------------------------------------------------------------------------


def _order_block(n_real: int, *_args) -> np.ndarray:
    return np.arange(n_real, dtype=np.int64)


def _order_bfs(n_real: int, senders: np.ndarray,
               receivers: np.ndarray, _pos) -> np.ndarray:
    """BFS visit order over the undirected adjacency — contiguous chunks of
    the order are connected neighborhoods, so chunk boundaries cut few
    edges on mesh-like graphs.  Vectorized frontier expansion (no per-node
    Python loop over edges)."""
    order = np.empty(n_real, np.int64)
    visited = np.zeros(n_real, bool)
    # undirected adjacency in CSR form via sorted edge endpoints
    u = np.concatenate([senders, receivers])
    v = np.concatenate([receivers, senders])
    sort = np.argsort(u, kind="stable")
    u, v = u[sort], v[sort]
    starts = np.searchsorted(u, np.arange(n_real + 1))
    pos_out = 0
    for seed in range(n_real):
        if visited[seed]:
            continue
        frontier = np.asarray([seed], np.int64)
        visited[seed] = True
        while frontier.size:
            order[pos_out:pos_out + frontier.size] = frontier
            pos_out += frontier.size
            # all neighbors of the frontier, deduped, unvisited —
            # CSR range gather via repeat/cumsum, no per-node Python loop
            cnt = starts[frontier + 1] - starts[frontier]
            tot = int(cnt.sum())
            if not tot:
                break
            base = np.repeat(starts[frontier], cnt)
            within = np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt)
            nxt = np.unique(v[base + within])
            nxt = nxt[~visited[nxt]]
            visited[nxt] = True
            frontier = nxt
    assert pos_out == n_real
    return order


def _order_sfc(n_real: int, _senders, _receivers,
               pos: np.ndarray) -> np.ndarray:
    """Morton (Z-order) curve on quantized positions: spatially adjacent
    nodes land adjacent in the order, so contiguous chunks are compact
    spatial cells — the natural order for radius-graph inputs."""
    p = np.asarray(pos[:n_real], np.float64)
    lo = p.min(axis=0)
    span = np.maximum(p.max(axis=0) - lo, 1e-12)
    q = np.clip(((p - lo) / span * ((1 << 16) - 1)), 0,
                (1 << 16) - 1).astype(np.uint64)
    key = np.zeros(n_real, np.uint64)
    for bit in range(16):
        for axis in range(min(3, q.shape[1])):
            key |= ((q[:, axis] >> np.uint64(bit)) & np.uint64(1)) \
                << np.uint64(bit * 3 + axis)
    return np.argsort(key, kind="stable").astype(np.int64)


_ORDERS = {"block": _order_block, "bfs": _order_bfs, "sfc": _order_sfc}


# ---------------------------------------------------------------------------
# the shard plan (host-side, numpy): pure indexing, reusable across batches
# with the same topology
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShardPlan:
    """Index plan for one (topology, n_shards, method, hops) combination.
    All arrays are stacked per-shard along a leading [D] axis; applying the
    plan to a batch (:func:`apply_plan`) is plain numpy gathering."""

    n_shards: int
    n_local: int          # padded local node rows per shard
    e_local: int          # padded edge rows per shard
    halo_pair: int        # padded rows each ordered (owner, dest) pair ships
    ext_n: int            # n_local + n_shards * halo_pair + 1 (pad row last)
    hops: int
    method: str
    local_ids: np.ndarray     # [D, n_local] original node id (pad: -1)
    halo_ids: np.ndarray      # [D, D*halo_pair] original node id (pad: -1)
    send_idx: np.ndarray      # [D, D, halo_pair] LOCAL row idx to ship (pad 0)
    senders: np.ndarray       # [D, e_local] ext index (pad: ext_n-1)
    receivers: np.ndarray     # [D, e_local] ext index (pad: ext_n-1)
    edge_ids: np.ndarray      # [D, e_local] original edge id (pad: -1)
    edge_mask: np.ndarray     # [D, e_local] 1.0 = real (incl. halo-internal)
    edge_owned: np.ndarray    # [D, e_local] 1.0 = receiver is LOCAL real
    node_gid: np.ndarray      # [D, ext_n] graph id (pad rows -> G-1)
    node_mask: np.ndarray     # [D, ext_n] 1.0 = local real row
    stats: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _round_up(x: int, m: int) -> int:
    return int(-(-x // m) * m) if m > 1 else int(x)


def build_shard_plan(
    batch: GraphBatch,
    n_shards: int,
    method: str = "sfc",
    hops: int = 2,
    round_to: int = 8,
    halo_max: int = 0,
) -> ShardPlan:
    """Partition ``batch``'s real nodes into ``n_shards`` contiguous chunks
    of a locality-aware order and precompute the L-hop halo plan.

    ``halo_max`` caps the per-pair halo rows; 0 sizes the buffer from the
    measured need rounded up to a multiple of 32 (the PadSpec-style
    bucket, so small topology changes reuse the compiled step).  Raises
    when the measured need exceeds an explicit cap — a silently truncated
    halo would be a wrong answer, not a slow one.
    """
    if method not in _ORDERS:
        raise ValueError(f"unknown partition method {method!r}")
    if hops < 1:
        raise ValueError(f"halo hops must be >= 1, got {hops}")
    senders = np.asarray(batch.senders)
    receivers = np.asarray(batch.receivers)
    node_mask = np.asarray(batch.node_mask)
    edge_mask = np.asarray(batch.edge_mask)
    node_gid = np.asarray(batch.node_gid)
    n_real = int(node_mask.sum())
    e_real = int(edge_mask.sum())
    # collate packs real rows first — the plan indexes by that invariant
    assert node_mask[:n_real].all() and not node_mask[n_real:].any(), \
        "graph partitioning requires collate's real-rows-first layout"
    assert edge_mask[:e_real].all() and not edge_mask[e_real:].any()
    s_r = senders[:e_real].astype(np.int64)
    r_r = receivers[:e_real].astype(np.int64)

    order = _ORDERS[method](n_real, s_r, r_r, np.asarray(batch.pos))
    inv = np.empty(n_real, np.int64)
    inv[order] = np.arange(n_real)
    # n_real < n_shards (a degenerate tail val/test batch) leaves the
    # trailing shards empty — every reduction handles zero-node shards, so
    # a tiny batch must not kill a long run mid-validation
    chunk = max(-(-n_real // n_shards), 1)
    shard_of = inv // chunk           # [n_real] owner shard per node
    local_of = inv - shard_of * chunk  # [n_real] local row per node
    n_local = _round_up(chunk, round_to)

    D = n_shards
    # -- L-hop need sets + edge ownership per shard -------------------------
    need = np.zeros((D, n_real), bool)
    need[shard_of, np.arange(n_real)] = True
    need_lm1 = None
    for k in range(hops):
        if k == hops - 1:
            need_lm1 = need.copy()  # receivers at <= L-1 hops keep edges
        # expand: senders of edges whose receiver is in the need set
        hit = need[:, r_r]                      # [D, e_real]
        for d in range(D):
            need[d, s_r[hit[d]]] = True
    local = np.zeros((D, n_real), bool)
    local[shard_of, np.arange(n_real)] = True
    halo = need & ~local

    # -- halo slot assignment: per (dest, owner) pair, owner-local order ----
    halo_counts = np.zeros((D, D), np.int64)  # [dest, owner]
    halo_lists: List[List[np.ndarray]] = []
    for d in range(D):
        row = []
        ids = np.nonzero(halo[d])[0]
        owners = shard_of[ids]
        for p in range(D):
            sel = ids[owners == p]
            # deterministic order: the owner's local row order
            sel = sel[np.argsort(local_of[sel], kind="stable")]
            halo_counts[d, p] = sel.size
            row.append(sel)
        halo_lists.append(row)
    need_pair = int(halo_counts.max()) if D > 1 else 0
    if halo_max > 0:
        if need_pair > halo_max:
            raise ValueError(
                f"halo needs {need_pair} rows/pair but graph_shard_halo_max="
                f"{halo_max}; raise the cap or cut hops/improve the "
                "partition (a truncated halo is a wrong answer)")
        halo_pair = halo_max
    elif need_pair == 0:
        halo_pair = 1  # zero-cut partition: minimal (never zero-sized)
    else:
        # bucketed like PadSpec (multiple of 32): small topology drift
        # between batches reuses the compiled step instead of recompiling
        # per exact count, without power-of-two's up-to-2x buffer waste
        halo_pair = _round_up(need_pair, 32)
    ext_n = n_local + D * halo_pair + 1  # +1: dedicated pad row (last)
    pad_row = ext_n - 1

    # ext index per (shard, original node): local row, halo slot, or -1
    ext_index = np.full((D, n_real), -1, np.int64)
    for d in range(D):
        ids = np.nonzero(local[d])[0]
        ext_index[d, ids] = local_of[ids]
        for p in range(D):
            sel = halo_lists[d][p]
            ext_index[d, sel] = n_local + p * halo_pair + np.arange(sel.size)

    # -- per-shard edge lists (original order preserved per receiver) -------
    e_counts = []
    edge_sel: List[np.ndarray] = []
    for d in range(D):
        keep = need_lm1[d, r_r]  # receiver within L-1 hops of local
        eids = np.nonzero(keep)[0]
        edge_sel.append(eids)
        e_counts.append(eids.size)
    # power-of-two bucket like halo_pair: shuffled epochs yield slightly
    # different per-shard edge counts, and an exact-fit e_local would
    # recompile the step for every one of them
    e_need = max(e_counts) if e_counts else 0
    e_local = max(round_to, 8)
    while e_local < e_need:
        e_local *= 2

    G = int(np.asarray(batch.graph_mask).shape[0])
    plan_senders = np.full((D, e_local), pad_row, np.int32)
    plan_receivers = np.full((D, e_local), pad_row, np.int32)
    plan_edge_ids = np.full((D, e_local), -1, np.int64)
    plan_edge_mask = np.zeros((D, e_local), np.float32)
    plan_edge_owned = np.zeros((D, e_local), np.float32)
    plan_local_ids = np.full((D, n_local), -1, np.int64)
    plan_halo_ids = np.full((D, D * halo_pair), -1, np.int64)
    plan_send_idx = np.zeros((D, D, halo_pair), np.int32)
    plan_gid = np.full((D, ext_n), G - 1, np.int32)
    plan_nmask = np.zeros((D, ext_n), np.float32)
    for d in range(D):
        ids = order[d * chunk: min((d + 1) * chunk, n_real)]
        plan_local_ids[d, :ids.size] = ids
        plan_gid[d, :ids.size] = node_gid[ids]
        plan_nmask[d, :ids.size] = 1.0
        for p in range(D):
            sel = halo_lists[d][p]
            base = p * halo_pair
            plan_halo_ids[d, base:base + sel.size] = sel
            plan_gid[d, n_local + base:n_local + base + sel.size] = \
                node_gid[sel]
            # what shard p must SEND to d: p-local rows of those nodes
            plan_send_idx[p, d, :sel.size] = local_of[sel].astype(np.int32)
        eids = edge_sel[d]
        plan_edge_ids[d, :eids.size] = eids
        plan_senders[d, :eids.size] = ext_index[d, s_r[eids]].astype(np.int32)
        plan_receivers[d, :eids.size] = \
            ext_index[d, r_r[eids]].astype(np.int32)
        plan_edge_mask[d, :eids.size] = 1.0
        plan_edge_owned[d, :eids.size] = local[d, r_r[eids]].astype(
            np.float32)
        assert (plan_senders[d, :eids.size] >= 0).all()
        assert (plan_receivers[d, :eids.size] >= 0).all()

    cut = int((shard_of[s_r] != shard_of[r_r]).sum())
    real_per_shard = np.minimum(
        np.full(D, chunk, np.int64),
        np.maximum(n_real - np.arange(D) * chunk, 0))
    halo_rows = halo.sum(axis=1)
    owned_edges = np.asarray(
        [int(local[d, r_r].sum()) for d in range(D)], np.int64)
    halo_cap = D * (D * halo_pair)
    stats = {
        "n_shards": D,
        "method": method,
        "hops": int(hops),
        "n_nodes_real": n_real,
        "n_edges_real": e_real,
        "n_local": int(n_local),
        "e_local": int(e_local),
        "halo_pair": int(halo_pair),
        "ext_n": int(ext_n),
        "cut_edge_pct": round(100.0 * cut / max(e_real, 1), 2),
        "halo_rows_max": int(halo_rows.max()) if D > 1 else 0,
        "halo_rows_mean": round(float(halo_rows.mean()), 1),
        "node_imbalance": round(
            float(real_per_shard.max() / max(real_per_shard.mean(), 1e-9)),
            3),
        "edge_imbalance": round(
            float(owned_edges.max() / max(owned_edges.mean(), 1e-9)), 3),
        "halo_waste_pct": round(
            100.0 * (1.0 - float(halo_rows.sum()) / halo_cap), 1)
        if halo_cap else 0.0,
    }
    return ShardPlan(
        n_shards=D, n_local=n_local, e_local=e_local, halo_pair=halo_pair,
        ext_n=ext_n, hops=hops, method=method,
        local_ids=plan_local_ids, halo_ids=plan_halo_ids,
        send_idx=plan_send_idx, senders=plan_senders,
        receivers=plan_receivers, edge_ids=plan_edge_ids,
        edge_mask=plan_edge_mask, edge_owned=plan_edge_owned,
        node_gid=plan_gid, node_mask=plan_nmask, stats=stats)


# ---------------------------------------------------------------------------
# HaloBatch: the per-shard carrier the halo step consumes
# ---------------------------------------------------------------------------


@struct.dataclass
class HaloBatch:
    """Per-shard graph-shard input, stacked [D, ...] across the mesh axis.

    ``x``/``pos`` hold ONLY this shard's local rows ([n_local, .] — the
    N/D residency); ``senders``/``receivers`` index the EXTENDED row space
    [0, ext_n) = local rows ++ D*halo_pair halo slots ++ one pad row, which
    the step materializes by gathering ``x[send_idx]`` through one
    ``all_to_all``.  Graph-level arrays (graph_mask, graph labels, cell,
    per-graph extras) are replicated on every shard."""

    x: jax.Array                    # [n_local, F]
    pos: jax.Array                  # [n_local, 3]
    senders: jax.Array              # [e_local] ext index
    receivers: jax.Array            # [e_local] ext index
    edge_attr: Optional[jax.Array]  # [e_local, Fe] or None
    node_gid: jax.Array             # [ext_n]
    node_mask: jax.Array            # [ext_n] 1.0 = local real
    edge_mask: jax.Array            # [e_local]
    graph_mask: jax.Array           # [G] replicated
    labels: Tuple[jax.Array, ...]   # node heads [ext_n, d]; graph [G, d]
    send_idx: jax.Array             # [D, halo_pair] local rows per dest
    cell: Optional[jax.Array] = None
    extras: Dict[str, jax.Array] = struct.field(default_factory=dict)

    @property
    def n_real_graphs(self) -> jax.Array:
        return jnp.sum(self.graph_mask)


def _gather_rows(arr: np.ndarray, ids: np.ndarray,
                 fill: float = 0.0) -> np.ndarray:
    """arr[ids] with ids == -1 mapped to ``fill`` rows."""
    out = np.full((ids.shape[0],) + arr.shape[1:], fill, arr.dtype)
    ok = ids >= 0
    out[ok] = arr[ids[ok]]
    return out


def apply_plan(batch: GraphBatch, plan: ShardPlan,
               head_types: Optional[List[str]] = None) -> HaloBatch:
    """Gather ``batch``'s arrays through ``plan`` into a stacked [D, ...]
    :class:`HaloBatch` (pure numpy; cheap next to plan construction).

    ``head_types`` ("graph"|"node" per head) tells label routing; when
    omitted it is inferred from each label's leading dim (ambiguous only
    if padded node count equals padded graph count)."""
    x = np.asarray(batch.x)
    pos = np.asarray(batch.pos)
    D = plan.n_shards
    ext_label_n = plan.ext_n
    if head_types is None:
        head_types = ["node" if lab.shape[0] == x.shape[0] else "graph"
                      for lab in batch.labels]

    xs, ps, eattrs, labels_per_head, extras_out = [], [], [], [], []
    has_ea = batch.edge_attr is not None
    ea = np.asarray(batch.edge_attr) if has_ea else None
    for d in range(D):
        xs.append(_gather_rows(x, plan.local_ids[d]))
        ps.append(_gather_rows(pos, plan.local_ids[d]))
        if has_ea:
            eattrs.append(_gather_rows(ea, plan.edge_ids[d]))
    labels = []
    for ih, lab in enumerate(batch.labels):
        lab = np.asarray(lab)
        if head_types[ih] == "node":
            rows = []
            for d in range(D):
                full_ids = np.concatenate(
                    [plan.local_ids[d], plan.halo_ids[d],
                     np.asarray([-1], np.int64)])
                r = _gather_rows(lab, full_ids)
                # halo rows carry NO loss (mask excludes them) — zero them
                # so a stray unmasked reduction is loud, not subtly wrong
                r[plan.n_local:] = 0.0
                rows.append(r[:ext_label_n])
            labels.append(np.stack(rows))
        else:
            labels.append(np.broadcast_to(
                lab, (D,) + lab.shape).copy())
    extras: Dict[str, np.ndarray] = {}
    for k, v in (batch.extras or {}).items():
        if k == "edge_perm_sender":
            continue  # fused-kernel marker: invariants don't survive resharding
        v = np.asarray(v)
        if v.ndim >= 1 and v.shape[0] == x.shape[0]:
            rows = []
            for d in range(D):
                full_ids = np.concatenate(
                    [plan.local_ids[d], plan.halo_ids[d],
                     np.asarray([-1], np.int64)])
                rows.append(_gather_rows(v, full_ids))
            extras[k] = np.stack(rows)
        else:
            extras[k] = np.broadcast_to(v, (D,) + v.shape).copy()
    extras["edge_owned_mask"] = plan.edge_owned.astype(np.float32)

    cell = None
    if batch.cell is not None:
        c = np.asarray(batch.cell)
        cell = np.broadcast_to(c, (D,) + c.shape).copy()
    gm = np.asarray(batch.graph_mask)
    return HaloBatch(
        x=np.stack(xs),
        pos=np.stack(ps),
        senders=plan.senders,
        receivers=plan.receivers,
        edge_attr=np.stack(eattrs) if has_ea else None,
        node_gid=plan.node_gid,
        node_mask=plan.node_mask,
        edge_mask=plan.edge_mask,
        graph_mask=np.broadcast_to(gm, (D,) + gm.shape).copy(),
        labels=tuple(labels),
        send_idx=plan.send_idx,
        cell=cell,
        extras=extras,
    )


def halo_exchange(x_local: jax.Array, send_idx: jax.Array, axes):
    """Gather the rows each peer needs and swap them with ONE all_to_all;
    returns the [D*halo_pair, F] halo buffer.  Runs inside shard_map; its
    VJP (jax-derived) reduce-scatters halo cotangents back through the
    inverse all_to_all + a scatter-add onto the owner rows."""
    send = jnp.take(x_local, send_idx, axis=0)  # [D, halo_pair, F]
    recv = jax.lax.all_to_all(
        send, axes, split_axis=0, concat_axis=0, tiled=True)
    return recv.reshape((-1,) + recv.shape[2:])


def assemble_extended(hb: HaloBatch, axes) -> GraphBatch:
    """Materialize the extended per-shard :class:`GraphBatch` the unchanged
    model consumes: local rows ++ exchanged halo rows ++ one zero pad row.
    Runs inside shard_map (differentiable through the exchange)."""
    halo_x = halo_exchange(hb.x, hb.send_idx, axes)
    halo_p = halo_exchange(hb.pos, hb.send_idx, axes)
    pad_x = jnp.zeros((1,) + hb.x.shape[1:], hb.x.dtype)
    pad_p = jnp.zeros((1,) + hb.pos.shape[1:], hb.pos.dtype)
    x_ext = jnp.concatenate([hb.x, halo_x, pad_x], axis=0)
    pos_ext = jnp.concatenate([hb.pos, halo_p, pad_p], axis=0)
    return GraphBatch(
        x=x_ext,
        pos=pos_ext,
        senders=hb.senders,
        receivers=hb.receivers,
        edge_attr=hb.edge_attr,
        node_gid=hb.node_gid,
        node_mask=hb.node_mask,
        edge_mask=hb.edge_mask,
        graph_mask=hb.graph_mask,
        labels=hb.labels,
        cell=hb.cell,
        extras=hb.extras,
    )


# ---------------------------------------------------------------------------
# loader wrapper: partition each yielded batch, cache plans per topology
# ---------------------------------------------------------------------------


class ShardedGraphLoader:
    """Wrap a GraphDataLoader: every yielded batch is partitioned into a
    stacked :class:`HaloBatch` for the halo train/eval steps.

    Plans are cached per topology digest (edges + masks + graph-boundary
    assignment — the expensive BFS/SFC + hop expansion); repeated epochs
    over the same giant graph(s) pay numpy gathers only.  ``halo_pair``
    is bucketed to multiples of 32, so minor topology drift between
    cached plans reuses the compiled step."""

    def __init__(self, loader, n_shards: int, cfg: GraphShardConfig,
                 hops: int, head_types: Optional[List[str]] = None):
        self.loader = loader
        self.n_shards = n_shards
        self.cfg = cfg
        self.hops = hops if cfg.hops == 0 else cfg.hops
        self.head_types = head_types
        self._plans: Dict[bytes, ShardPlan] = {}
        self.stats: Dict[str, Any] = {}

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def _plan_for(self, batch: GraphBatch) -> ShardPlan:
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        h.update(np.asarray(batch.senders).tobytes())
        h.update(np.asarray(batch.receivers).tobytes())
        h.update(np.asarray(batch.node_mask).tobytes())
        # the plan bakes in graph-boundary assignment too: identical edge
        # topology collated as ONE graph vs two must not share a plan
        h.update(np.asarray(batch.node_gid).tobytes())
        h.update(np.asarray(batch.graph_mask).tobytes())
        key = h.digest()
        plan = self._plans.get(key)
        if plan is None:
            plan = build_shard_plan(
                batch, self.n_shards, method=self.cfg.method,
                hops=self.hops, halo_max=self.cfg.halo_max)
            if len(self._plans) >= 64:  # bound host memory on huge epochs
                self._plans.clear()
            self._plans[key] = plan
            self.stats = dict(plan.stats)
        return plan

    def peek_stats(self) -> Dict[str, Any]:
        """Partition stats of the first batch (builds + caches its plan) —
        what the trainer logs to telemetry before the epoch loop."""
        if not self.stats:
            try:
                first = next(iter(self.loader))
            except StopIteration:
                return {}
            self._plan_for(first)
        return self.stats

    def __iter__(self):
        for batch in self.loader:
            yield apply_plan(batch, self._plan_for(batch), self.head_types)


def shard_batch_halo(batch: GraphBatch, n_shards: int, method: str = "sfc",
                     hops: int = 2, halo_max: int = 0,
                     head_types: Optional[List[str]] = None,
                     ) -> Tuple[HaloBatch, ShardPlan]:
    """One-shot convenience: plan + apply for a single batch (tests,
    bench, tools)."""
    plan = build_shard_plan(batch, n_shards, method=method, hops=hops,
                            halo_max=halo_max)
    return apply_plan(batch, plan, head_types), plan


def synthetic_lattice_batch(k: int, features: int = 4, seed: int = 0
                            ) -> GraphBatch:
    """k^3 nodes on a 3D grid with edges to the 6 axis neighbors, collated
    as ONE giant graph — the shared synthetic input ``bench.py --giant``
    and ``tools/partview.py`` measure partitions on (one definition, so
    the partition-quality report describes the graphs the bench ladder
    actually times)."""
    from hydragnn_tpu.graph.batch import (
        GraphSample,
        HeadSpec,
        PadSpec,
        collate,
    )

    rng = np.random.RandomState(seed)
    n = k ** 3
    iz, iy, ix = np.meshgrid(*[np.arange(k)] * 3, indexing="ij")
    pos = np.stack([ix, iy, iz], axis=-1).reshape(n, 3).astype(np.float32)
    idx = np.arange(n).reshape(k, k, k)
    send, recv = [], []
    for axis in range(3):
        a = np.take(idx, np.arange(k - 1), axis=axis).ravel()
        b = np.take(idx, np.arange(1, k), axis=axis).ravel()
        send += [a, b]
        recv += [b, a]
    ei = np.stack([np.concatenate(send), np.concatenate(recv)]).astype(
        np.int32)
    x = rng.rand(n, features).astype(np.float32)
    s = GraphSample(x=x, pos=pos, edge_index=ei, node_y=x[:, :1] * 2.0)
    pad = PadSpec(num_nodes=n + 8, num_edges=ei.shape[1] + 8, num_graphs=2)
    return collate([s], pad, [HeadSpec("charge", "node", 1)])
