"""Host-side graph construction: radius graphs, periodic neighbor lists.

Graph construction never runs on the TPU — it happens once in the input
pipeline (as in the reference, where SerializedDataLoader recomputes radius
graphs at load time; hydragnn/preprocess/serialized_dataset_loader.py:127-141).

Replaces:
  - PyG ``RadiusGraph``           -> :func:`radius_graph` (scipy cKDTree)
  - ASE ``neighbor_list`` + PBC   -> :func:`radius_graph_pbc` (periodic image
    replication; reference hydragnn/preprocess/utils.py:134-174)
  - PyG ``NormalizeRotation``     -> :func:`normalize_rotation`
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
from scipy.spatial import cKDTree


def radius_graph(
    pos: np.ndarray,
    radius: float,
    max_neighbours: int = 32,
    loop: bool = False,
) -> np.ndarray:
    """Edges (2, E) between nodes within ``radius``.

    Matches PyG RadiusGraph semantics (reference
    hydragnn/preprocess/utils.py:102-107): for each target node, up to
    ``max_neighbours`` sources within the radius; ``edge_index[0]`` is the
    source, ``edge_index[1]`` the target.
    """
    pos = np.asarray(pos, dtype=np.float64)
    n = pos.shape[0]
    tree = cKDTree(pos)
    # Batched query: [n, k] distances/indices sorted by distance per target;
    # misses are inf/n.  Fully vectorized — no per-node Python loop (the
    # per-node version was far too slow for OC20/MPTrj-scale preprocessing).
    k = min(max_neighbours + 1, n)
    dists, idxs = tree.query(pos, k=k, distance_upper_bound=radius)
    dists = np.atleast_2d(np.asarray(dists).reshape(n, -1))
    idxs = np.atleast_2d(np.asarray(idxs).reshape(n, -1))
    dst = np.repeat(np.arange(n, dtype=np.int64), dists.shape[1])
    src = idxs.ravel()
    valid = np.isfinite(dists.ravel()) & (src < n)
    if not loop:
        valid &= src != dst
    src, dst = src[valid], dst[valid]
    if src.size == 0:
        return np.zeros((2, 0), np.int32)
    return np.stack([src.astype(np.int32), dst.astype(np.int32)], axis=0)


def _as_cell_matrix(cell) -> np.ndarray:
    cell = np.asarray(cell, dtype=np.float64)
    if cell.ndim == 1:
        return np.diag(cell)
    if cell.shape == (3, 3):
        return cell
    raise ValueError(f"cell must be a 3-vector or 3x3 matrix, got {cell.shape}")


def radius_graph_pbc(
    pos: np.ndarray,
    cell,
    radius: float,
    max_neighbours: int = 1000,
    loop: bool = False,
    check_duplicates: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Periodic radius graph: returns (edge_index (2,E), edge_length (E,)).

    Semantics of ASE ``neighbor_list("ijd", ...)`` as used by the reference's
    RadiusGraphPBC (hydragnn/preprocess/utils.py:139-171): neighbors across
    periodic images of the cell; a pair connected both directly and through an
    image would create duplicate (i, j) edges, which the reference rejects —
    we do the same when ``check_duplicates``.
    """
    pos = np.asarray(pos, dtype=np.float64)
    H = _as_cell_matrix(cell)  # rows are lattice vectors
    n = pos.shape[0]

    # How many images along each lattice direction can hold a point within
    # `radius`: use the perpendicular distance of each lattice plane.
    Hinv = np.linalg.inv(H)
    # perpendicular width along axis k = 1 / ||row_k of H^-T||
    widths = 1.0 / np.linalg.norm(Hinv, axis=0)
    n_img = np.maximum(np.ceil(radius / widths).astype(int), 0)

    shifts = []
    for ix in range(-n_img[0], n_img[0] + 1):
        for iy in range(-n_img[1], n_img[1] + 1):
            for iz in range(-n_img[2], n_img[2] + 1):
                shifts.append((ix, iy, iz))
    shifts = np.asarray(shifts, dtype=np.float64)  # [S, 3]
    disp = shifts @ H  # cartesian displacement per image [S, 3]

    # Replicated source points: image copies of every atom.
    S = shifts.shape[0]
    rep_pos = (pos[None, :, :] + disp[:, None, :]).reshape(S * n, 3)
    rep_idx = np.tile(np.arange(n), S)
    is_central = np.repeat((shifts == 0).all(axis=1), n)

    # Prune image atoms that cannot reach any target: every target lies in
    # the pos bounding box, so sources beyond `radius` outside it are dead.
    lo = pos.min(axis=0) - radius - 1e-9
    hi = pos.max(axis=0) + radius + 1e-9
    keep = np.all((rep_pos >= lo) & (rep_pos <= hi), axis=1)
    rep_pos, rep_idx, is_central = rep_pos[keep], rep_idx[keep], is_central[keep]

    # Batched KD-tree query over all image copies at once (the per-atom
    # query_ball_point loop was too slow for OC20/MPTrj-scale preprocessing):
    # [n, k] results sorted by distance; per-row rank among valid entries
    # caps neighbours without a Python loop.
    tree = cKDTree(rep_pos)
    total = rep_pos.shape[0]
    k = min(max_neighbours + 1, total)
    dists, idxs = tree.query(pos, k=k, distance_upper_bound=radius)
    dists = np.atleast_2d(np.asarray(dists).reshape(n, -1))
    idxs = np.atleast_2d(np.asarray(idxs).reshape(n, -1))
    rows = np.repeat(np.arange(n, dtype=np.int64), dists.shape[1]).reshape(
        n, -1)
    hit = np.isfinite(dists) & (idxs < total)
    idx_safe = np.where(hit, idxs, 0)
    if not loop:
        hit &= ~(is_central[idx_safe] & (rep_idx[idx_safe] == rows))
    # distance-sorted per row, so rank-among-valid <= max_neighbours keeps
    # the nearest max_neighbours sources per target
    rank = np.cumsum(hit, axis=1)
    hit &= rank <= max_neighbours
    src = rep_idx[idx_safe[hit]]
    dst = rows[hit]
    lengths = dists[hit]

    edge_index = (
        np.stack([src.astype(np.int32), dst.astype(np.int32)])
        if src.size
        else np.zeros((2, 0), np.int32)
    )
    lengths = np.asarray(lengths, np.float64)

    if check_duplicates and edge_index.shape[1]:
        pairs = edge_index[0].astype(np.int64) * n + edge_index[1]
        if np.unique(pairs).size != pairs.size:
            raise ValueError(
                "Adding periodic boundary conditions would result in duplicate "
                "edges. Cutoff radius must be reduced or system size increased."
            )
    return edge_index, lengths.astype(np.float32)


def edge_lengths(pos: np.ndarray, edge_index: np.ndarray) -> np.ndarray:
    """Euclidean length per edge, shape (E, 1)."""
    d = pos[edge_index[0]] - pos[edge_index[1]]
    return np.linalg.norm(d, axis=1, keepdims=True).astype(np.float32)


def normalize_rotation(pos: np.ndarray) -> np.ndarray:
    """Rotate positions onto their principal axes (PyG NormalizeRotation
    semantics, used by the reference's rotational-invariance path;
    hydragnn/preprocess/serialized_dataset_loader.py:123-125)."""
    pos = np.asarray(pos, dtype=np.float64)
    centered = pos - pos.mean(axis=0, keepdims=True)
    _, _, vt = np.linalg.svd(centered, full_matrices=False)
    # Fix sign convention for determinism.
    signs = np.sign(vt[np.arange(vt.shape[0]), np.argmax(np.abs(vt), axis=1)])
    vt = vt * signs[:, None]
    return (centered @ vt.T).astype(np.float32)
