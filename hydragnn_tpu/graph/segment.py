"""Segment (scatter/gather) ops — the message-passing primitives.

TPU-native replacement for torch-scatter / torch-sparse (reference depends on
them for all PyG conv internals; see SURVEY.md §2.3).  XLA lowers
``jax.ops.segment_sum`` to efficient one-hot matmuls / scatter kernels on TPU,
so message passing is expressed as gather (``x[senders]``) + segment reduce at
``receivers`` with *static* ``num_segments``.

All ops take an optional mask (1.0 = valid) so padded edges/nodes contribute
nothing — this is what makes padded static-shape batching exact.

``segment_sum`` (and everything built on it) honors HYDRAGNN_AGGR_BACKEND
(parity: reference train_validate_test.py:373-378): ``scatter`` (default XLA
scatter), ``onehot`` (MXU one-hot matmul), or ``pallas`` (blocked Pallas
kernel) — see hydragnn_tpu/ops/aggregate.py.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp

_BIG = 1e9


def _count(op: str, fused: bool) -> None:
    """Trace-time dispatch tally (fused fast path vs scatter fallback) —
    folded into the telemetry manifest and bench's per-arch records so a
    run that silently fell off the fast path is visible.  Runs once per
    trace (Python level), never inside compiled code."""
    from hydragnn_tpu.telemetry import pipeline

    pipeline.count_fused_choice(op, fused)


def segment_sum(data, segment_ids, num_segments, mask=None):
    if mask is not None:
        data = data * _bcast(mask, data)
    from hydragnn_tpu.ops.aggregate import (
        aggr_backend,
        segment_sum_onehot,
        segment_sum_pallas,
    )

    backend = aggr_backend()
    if backend == "onehot" and jnp.issubdtype(data.dtype, jnp.floating):
        return segment_sum_onehot(data, segment_ids, num_segments)
    if backend == "pallas" and jnp.issubdtype(data.dtype, jnp.floating):
        return segment_sum_pallas(data, segment_ids, num_segments)
    return jax.ops.segment_sum(data, segment_ids, num_segments)


def gather_mul_segment(x, w, g):
    """The message-passing core ``out[n] = sum_{e: recv[e]=n}
    x[send[e]] * w[e]`` — gather, edge-multiply, segment-sum.

    When HYDRAGNN_AGGR_BACKEND=fused and the batch carries the
    collate-provided ``edge_perm_sender`` (graph/batch.py attaches it when
    the block-locality invariant holds) this lowers to the single fused
    Pallas pass (ops/fused_mp.py) that never materializes the gathered
    messages in HBM; otherwise the standard gather + masked segment_sum.
    """
    perm = g.extras.get("edge_perm_sender") if g.extras else None
    _count("gather_mul", perm is not None)
    if perm is not None:
        from hydragnn_tpu.ops.fused_mp import gather_mul_segment_sum

        w = w * _bcast(g.edge_mask, w)
        # edge_valid: the kernel's schedule skips masked-edge blocks
        # outright (~half the slots at flagship padding ratios)
        return gather_mul_segment_sum(x, w, g.senders, g.receivers, perm,
                                      edge_valid=g.edge_mask)
    return segment_sum(
        x[g.senders] * w, g.receivers, x.shape[0], g.edge_mask)


def gather_segment(x, g):
    """Plain neighbor sum ``out[n] = sum_{e: recv[e]=n} x[send[e]]`` over
    real edges — fused-kernel path when available (same dispatch rules as
    :func:`gather_mul_segment`), else gather + masked segment_sum."""
    perm = g.extras.get("edge_perm_sender") if g.extras else None
    _count("gather_sum", perm is not None)
    if perm is not None:
        from hydragnn_tpu.ops.fused_mp import gather_segment_sum

        return gather_segment_sum(
            x, g.senders, g.receivers, perm, g.edge_mask)
    return segment_sum(
        x[g.senders], g.receivers, x.shape[0], g.edge_mask)


def gather_segment_mean(x, g):
    """Masked neighbor mean ``out[n] = mean_{e: recv[e]=n} x[send[e]]``
    (zero where a node has no real edges uses the max(count,1) convention
    of :func:`segment_mean`) — the sum lowers to the fused kernel when
    available."""
    total = gather_segment(x, g)
    deg = degree(g.receivers, x.shape[0], g.edge_mask)
    return total / jnp.maximum(deg, 1.0)[:, None]


def segment_count(segment_ids, num_segments, mask=None, dtype=jnp.float32):
    ones = jnp.ones((segment_ids.shape[0],), dtype)
    if mask is not None:
        ones = ones * mask.astype(dtype)
    return jax.ops.segment_sum(ones, segment_ids, num_segments)


def _mean_divide(total, count):
    """The one definition of the empty-segment convention: mean uses
    max(count, 1) so empty segments read zero, not NaN."""
    return total / _bcast(jnp.maximum(count, 1.0), total)


def segment_mean(data, segment_ids, num_segments, mask=None):
    total = segment_sum(data, segment_ids, num_segments, mask)
    count = segment_count(segment_ids, num_segments, mask)
    return _mean_divide(total, count)


def segment_max(data, segment_ids, num_segments, mask=None):
    """Max-reduce; empty/masked segments yield 0 (matching PyG conventions)."""
    if mask is not None:
        data = jnp.where(_bcast(mask, data) > 0, data, -_BIG)
    out = jax.ops.segment_max(data, segment_ids, num_segments)
    return jnp.where(out <= -_BIG * 0.5, 0.0, out)


def segment_min(data, segment_ids, num_segments, mask=None):
    if mask is not None:
        data = jnp.where(_bcast(mask, data) > 0, data, _BIG)
    out = jax.ops.segment_min(data, segment_ids, num_segments)
    return jnp.where(out >= _BIG * 0.5, 0.0, out)


def segment_std(data, segment_ids, num_segments, mask=None, eps=1e-5):
    """Per-segment standard deviation (PNA 'std' aggregator numerics)."""
    mean = segment_mean(data, segment_ids, num_segments, mask)
    sq_mean = segment_mean(data * data, segment_ids, num_segments, mask)
    var = jnp.maximum(sq_mean - mean * mean, 0.0)
    return jnp.sqrt(var + eps)


def segment_softmax(logits, segment_ids, num_segments, mask=None):
    """Numerically-stable softmax within segments (GATv2 attention).

    Padded entries (mask == 0) get zero weight.
    """
    if mask is not None:
        logits = jnp.where(_bcast(mask, logits) > 0, logits, -_BIG)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments)
    seg_max = jnp.where(seg_max <= -_BIG * 0.5, 0.0, seg_max)
    logits = logits - seg_max[segment_ids]
    unnorm = jnp.exp(logits)
    if mask is not None:
        unnorm = unnorm * _bcast(mask, unnorm)
    denom = jax.ops.segment_sum(unnorm, segment_ids, num_segments)
    return unnorm / jnp.maximum(denom, 1e-16)[segment_ids]


def degree(receivers, num_nodes, mask=None):
    """In-degree per node (reference computes degree on edge_index[1];
    hydragnn/preprocess/utils.py:188)."""
    return segment_count(receivers, num_nodes, mask)


def sorted_segment_sum(data, segment_ids, num_segments, mask=None,
                       sorted_hint=False):
    """Masked segment sum that rides the dense-schedule sorted scatter
    kernel when the caller vouches (``sorted_hint``) that ``segment_ids``
    are nondecreasing; else the standard masked segment_sum.  Masking
    happens BEFORE the dense scatter — padding rows park on real slots, so
    an unmasked dense scatter would corrupt them."""
    _count("sorted_sum", bool(sorted_hint))
    if sorted_hint:
        from hydragnn_tpu.ops.fused_mp import segment_sum_dense

        if mask is not None:
            data = data * _bcast(mask, data)
        # masked rows park out of range -> their blocks are schedule-
        # skipped (collate/add_dimenet_extras keep padding tail-sorted)
        return segment_sum_dense(data, segment_ids, num_segments,
                                 valid=mask)
    return segment_sum(data, segment_ids, num_segments, mask)


def scatter_segment(data, g):
    """Receiver-side MASKED segment sum of already-edge-valued ``data``
    (CGCNN's gated messages, PNA aggregates): lowers to the dense-schedule
    sorted scatter kernel when the batch carries collate's
    verified-invariants marker (``edge_perm_sender``), else the masked
    segment_sum.  Always edge-masked — padding edges park on a real node
    slot, so an unmasked dense scatter would corrupt it."""
    _count("scatter_sum", bool(g.extras and "edge_perm_sender" in g.extras))
    if g.extras and "edge_perm_sender" in g.extras:
        from hydragnn_tpu.ops.fused_mp import segment_sum_dense

        data = data * _bcast(g.edge_mask, data)
        # valid: schedule-skips padding-edge blocks (collate parks them
        # zero-valued and tail-sorted)
        return segment_sum_dense(data, g.receivers, g.num_nodes,
                                 valid=g.edge_mask)
    return segment_sum(data, g.receivers, g.num_nodes, g.edge_mask)


def masked_mean_pool(x, node_gid, num_graphs, node_mask, sorted_hint=False):
    """Per-graph mean over *real* nodes — parity with PyG global_mean_pool
    (reference hydragnn/models/Base.py:296) under padding.  ``sorted_hint``
    (set by Base.forward when the batch carries collate's
    verified-invariants marker) routes the sum through the dense-schedule
    sorted scatter kernel — collate's node_gid is nondecreasing by
    construction.

    Shard-aware: under an active halo-sharding trace (graph/partition.py:
    halo_context) a graph's nodes span shards, so the per-shard partial
    sums and counts are psum-ed across the mesh axis before the divide —
    every shard sees the exact global per-graph means."""
    from hydragnn_tpu.graph.partition import halo_axes, halo_psum

    _count("mean_pool", bool(sorted_hint))
    if sorted_hint and halo_axes() is None:
        from hydragnn_tpu.ops.fused_mp import segment_sum_dense

        total = segment_sum_dense(
            x * _bcast(node_mask, x), node_gid, num_graphs)
        count = segment_count(node_gid, num_graphs, node_mask)
        return _mean_divide(total, count)
    total = halo_psum(segment_sum(x, node_gid, num_graphs, node_mask))
    count = halo_psum(segment_count(node_gid, num_graphs, node_mask))
    return _mean_divide(total, count)


def masked_sum_pool(x, node_gid, num_graphs, node_mask):
    from hydragnn_tpu.graph.partition import halo_psum

    return halo_psum(segment_sum(x, node_gid, num_graphs, node_mask))


# ---------------------------------------------------------------------------
# multi-moment (poly) aggregation: sum/sq-derived mean+std, max, min, count
# in ONE fused pass (ops/poly_mp.py) when the batch carries the collate
# marker — the PNA-class multi-aggregator archs' hot path
# ---------------------------------------------------------------------------

def _poly_public_keys():
    """Public moment vocabulary, DERIVED from the kernel's MOMENT_ORDER
    (ops/poly_mp.py owns the contract): the combined ``mxmn`` kernel
    output splits into the ``mx``/``mn`` keys callers consume."""
    from hydragnn_tpu.ops.poly_mp import MOMENT_ORDER

    keys = []
    for m in MOMENT_ORDER:
        keys.extend(("mx", "mn") if m == "mxmn" else (m,))
    return tuple(keys)


def _poly_kernel_moments(moments):
    from hydragnn_tpu.ops.poly_mp import MOMENT_ORDER

    want = set(moments)
    unknown = want - set(_poly_public_keys())
    if unknown or not want:
        raise ValueError(f"moments must be a nonempty subset of "
                         f"{_poly_public_keys()}, got {moments!r}")
    return tuple(
        m for m in MOMENT_ORDER
        if m in want or (m == "mxmn" and ("mx" in want or "mn" in want)))


def _poly_unpack(kern_moments, outs, moments, f):
    """Kernel tuple -> {requested key: cleaned array}.  mx/mn get the
    segment_max/min empty-segment zero-clean (same convention as
    :func:`segment_max` / :func:`segment_min`)."""
    res: Dict[str, jax.Array] = {}
    by = dict(zip(kern_moments, outs))
    if "sum" in moments:
        res["sum"] = by["sum"]
    if "sq" in moments:
        res["sq"] = by["sq"]
    if "mx" in moments or "mn" in moments:
        # clean threshold derives from the KERNEL's empty-segment
        # sentinel (poly_mp._NEG), not segment.py's _BIG — retuning one
        # must not silently break the other
        from hydragnn_tpu.ops.poly_mp import _NEG

        mxmn = by["mxmn"]
        if "mx" in moments:
            mx = mxmn[:, :f]
            res["mx"] = jnp.where(mx <= _NEG * 0.5, 0.0, mx)
        if "mn" in moments:
            neg = mxmn[:, f:]
            res["mn"] = jnp.where(neg <= _NEG * 0.5, 0.0, -neg)
    if "cnt" in moments:
        res["cnt"] = by["cnt"]
    return res


def _poly_composed(moments, g, data_fn, sum_fn):
    """Composed fallback shared by both poly dispatchers: ``data_fn``
    lazily yields the edge-valued messages (only materialized when a
    beyond-sum moment needs them), ``sum_fn`` the masked segment sum of
    the raw inputs (which may itself still ride a fused sum kernel when
    only the poly WIDTH gate failed)."""
    res: Dict[str, jax.Array] = {}
    if "sum" in moments:
        res["sum"] = sum_fn()
    data = (data_fn() if ("sq" in moments or "mx" in moments
                          or "mn" in moments) else None)
    n = g.num_nodes
    if "sq" in moments:
        # scatter_segment re-dispatches like the sum: still the dense
        # kernel when only the poly width gate failed (data is
        # edge-valued here in BOTH modes)
        res["sq"] = scatter_segment(data * data, g)
    if "mx" in moments or "mn" in moments:
        f = data.shape[-1]
        mxmn = segment_max(jnp.concatenate([data, -data], axis=-1),
                           g.receivers, n, g.edge_mask)
        if "mx" in moments:
            res["mx"] = mxmn[:, :f]
        if "mn" in moments:
            res["mn"] = -mxmn[:, f:]
    if "cnt" in moments:
        res["cnt"] = degree(g.receivers, n, g.edge_mask)
    return res


def _poly_fused_ok(g, f: int, moments) -> bool:
    from hydragnn_tpu.ops.poly_mp import POLY_MAX_F, POLY_MAX_F_MXMN

    if not (g.extras and "edge_perm_sender" in g.extras):
        return False
    limit = (POLY_MAX_F_MXMN if ("mx" in moments or "mn" in moments)
             else POLY_MAX_F)
    return f <= limit


def poly_scatter_segment(data, g, moments: Sequence[str]):
    """Multi-moment masked segment reduce of already-edge-valued ``data``
    [E, F] at receivers: returns a dict with the requested subset of

      sum [N, F], sq [N, F] (sum of squares), mx/mn [N, F] (max/min over
      REAL edges, 0 on empty nodes — the segment_max/min convention),
      cnt [N] (real in-edges, == :func:`degree`).

    One fused Pallas pass (ops/poly_mp.py) when the batch carries
    collate's verified-invariants marker AND F fits the kernel's width
    gate (POLY_MAX_F_MXMN with mx/mn, POLY_MAX_F otherwise); composed
    segment ops otherwise.  mean/std are elementwise outside:
    ``sum / max(cnt, 1)`` and the :func:`segment_std` formula."""
    kern = _poly_kernel_moments(moments)
    if kern == ("sum",):
        # pure sum: scatter_segment's single-moment dense kernel already
        # does this exact job (and is compiled in the same program for
        # pooling) — don't trace a second near-identical Pallas kernel
        return {"sum": scatter_segment(data, g)}
    f = data.shape[-1]
    fused = _poly_fused_ok(g, f, moments)
    _count("poly_scatter", fused)
    if fused:
        from hydragnn_tpu.ops.poly_mp import segment_poly_dense

        outs = segment_poly_dense(data, g.receivers, g.num_nodes, kern,
                                  valid=g.edge_mask)
        return _poly_unpack(kern, outs, moments, f)
    # scatter_segment re-dispatches the sum: still the dense kernel when
    # only the poly width gate failed
    return _poly_composed(moments, g, lambda: data,
                          lambda: scatter_segment(data, g))


def poly_gather_segment(x, g, moments: Sequence[str]):
    """Multi-moment reduce of the gathered neighbor messages
    ``x[senders]`` over REAL edges — same result dict as
    :func:`poly_scatter_segment`, but the fused path forms the messages
    in-VMEM (one-hot window gather) so the [E, F] tensor never hits HBM.
    The SAGE/MFC neighbor aggregation (sum + cnt in one pass replaces the
    separate neighbor-sum and degree scatters)."""
    kern = _poly_kernel_moments(moments)
    if kern == ("sum",):
        # pure sum: gather_segment's existing fused kernel is this job
        return {"sum": gather_segment(x, g)}
    f = x.shape[-1]
    perm = g.extras.get("edge_perm_sender") if g.extras else None
    fused = perm is not None and _poly_fused_ok(g, f, moments)
    _count("poly_gather", fused)
    if fused:
        from hydragnn_tpu.ops.poly_mp import gather_poly_segment

        outs = gather_poly_segment(x, g.senders, g.receivers, perm, kern,
                                   mask=g.edge_mask)
        return _poly_unpack(kern, outs, moments, f)
    # gather_segment re-dispatches the sum: a marker-present batch that
    # only failed the poly WIDTH gate still rides the fused sum kernel
    return _poly_composed(moments, g, lambda: x[g.senders],
                          lambda: gather_segment(x, g))


def _bcast(mask, data):
    """Broadcast a [E]/[N] mask against [E, ...] data."""
    if mask.ndim == data.ndim:
        return mask.astype(data.dtype)
    return mask.reshape(mask.shape + (1,) * (data.ndim - mask.ndim)).astype(data.dtype)

def gather_receiver_sorted(x, g):
    """``x[receivers]`` whose BACKWARD is the dense-schedule sorted scatter
    (receivers are nondecreasing by collate invariant) instead of XLA's
    scatter-add — marker-gated, plain gather otherwise."""
    if g.extras and "edge_perm_sender" in g.extras:
        return _gather_dense_bwd(x, g.receivers, None)
    return x[g.receivers]


def gather_perm(x, idx, perm):
    """``x[idx]`` for an ARBITRARY index vector whose backward rides the
    dense sorted-scatter kernel through a host-precomputed stable argsort
    ``perm`` of ``idx`` (DimeNet's triplet-side ``idx_kj`` gathers).  Same
    zero-cotangent requirement as the other dense-backward gathers (see
    :func:`_gather_dense_bwd`)."""
    return _gather_dense_bwd(x, idx, perm)


def gather_sender(x, g):
    """``x[senders]`` whose BACKWARD rides the dense scatter through
    collate's sender-sorted permutation — marker-gated."""
    perm = g.extras.get("edge_perm_sender") if g.extras else None
    if perm is not None:
        return _gather_dense_bwd(x, g.senders, perm)
    return x[g.senders]


@jax.custom_vjp
def _gather_dense_bwd(x, idx, perm):
    """Gather with a dense-sorted-scatter backward.

    ZERO-COTANGENT REQUIREMENT: the backward scatters the incoming
    cotangent UNMASKED.  Padding rows of ``idx`` park on a REAL slot
    (node N-1 / edge E-1 by collate convention), so every caller must
    guarantee the cotangent is exactly zero on padding rows — i.e. the
    gathered value must be multiplied by the edge/triplet mask somewhere
    downstream before any loss.  All current call sites
    (gather_sender/gather_receiver_sorted/gather_perm) satisfy this; a
    new unmasked consumer would silently corrupt the parked slot's
    gradient."""
    return x[idx]


def _gdb_fwd(x, idx, perm):
    return x[idx], (idx, perm, x.shape)


def _gdb_bwd(res, grad):
    idx, perm, shape = res
    from hydragnn_tpu.ops.fused_mp import segment_sum_dense

    g2 = grad.reshape(grad.shape[0], -1)
    if perm is not None:
        out = segment_sum_dense(g2[perm], idx[perm], shape[0])
    else:
        out = segment_sum_dense(g2, idx, shape[0])
    return out.reshape(shape), None, None


_gather_dense_bwd.defvjp(_gdb_fwd, _gdb_bwd)
