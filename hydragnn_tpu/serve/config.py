"""Serving knobs: ``Serving`` config section keys + env overrides.

Same layering as telemetry (telemetry/logger.py:TelemetryConfig) and
resilience (resilience/config.py): the dataclass is the single default
source, config.finalize writes the defaults back into the saved
config.json, and a user-set ``HYDRAGNN_SERVE_*`` env knob wins over the
config so a deployed server can be retuned without a config edit.

The bucket ladder is the serving analog of the training loader's
``bucket_pad_specs``: a short sorted list of batch capacities, each
compiled once at startup (AOT warmup), so steady-state traffic never
recompiles — the same static-shape discipline that makes the train step
compile exactly once per bucket.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Tuple

from hydragnn_tpu.utils.env import env_int

# the implicit tenant every fleet serves: the checkpoint the engine was
# built from.  Requests without a "model" field route here, and it is
# never evicted from a replica's tenant pool.  (Lives in config.py so
# server.py/fleet.py/router.py can all import it without cycles.)
DEFAULT_TENANT = "default"


def _parse_buckets(v) -> Tuple[int, ...]:
    if isinstance(v, str):
        v = [x.strip() for x in v.split(",") if x.strip()]
    return tuple(int(x) for x in v)


@dataclasses.dataclass
class ServingConfig:
    """Parsed ``Serving`` config section + env knobs (env wins).

    Env knobs: HYDRAGNN_SERVE_BUCKETS (comma list of batch capacities),
    HYDRAGNN_SERVE_MAX_NODES / HYDRAGNN_SERVE_MAX_EDGES (per-graph
    worst case, sizes the bucket PadSpecs), HYDRAGNN_SERVE_EDGE_NORM,
    HYDRAGNN_SERVE_MAX_WAIT_MS, HYDRAGNN_SERVE_QUEUE,
    HYDRAGNN_SERVE_HOST, HYDRAGNN_SERVE_PORT, HYDRAGNN_SERVE_DRAIN_S,
    and the overload/robustness knobs HYDRAGNN_SERVE_DEADLINE_MS,
    HYDRAGNN_SERVE_PREDICT_TIMEOUT_S, HYDRAGNN_SERVE_BREAKER_THRESHOLD,
    HYDRAGNN_SERVE_BREAKER_COOLDOWN_S, HYDRAGNN_SERVE_RELOAD_WATCH,
    HYDRAGNN_SERVE_RELOAD_WATCH_S (docs/SERVING.md "Overload behavior"),
    the quantization knobs HYDRAGNN_SERVE_QUANT_POLICY /
    HYDRAGNN_SERVE_QUANT_TOL (docs/SERVING.md "Quantized inference"),
    and the replica-fleet knobs HYDRAGNN_SERVE_FLEET,
    HYDRAGNN_SERVE_FLEET_INPROCESS, HYDRAGNN_SERVE_FLEET_PROBE_S,
    HYDRAGNN_SERVE_FLEET_BACKOFF_S, HYDRAGNN_SERVE_FLEET_BACKOFF_MAX_S,
    HYDRAGNN_SERVE_FLEET_MAX_RESTARTS,
    HYDRAGNN_SERVE_FLEET_RESTART_WINDOW_S, HYDRAGNN_SERVE_FLEET_DRAIN_S,
    HYDRAGNN_SERVE_FLEET_STARTUP_S, HYDRAGNN_SERVE_FLEET_QUORUM
    (docs/SERVING.md "Replica fleet"), and the autoscaler/tenancy knobs
    HYDRAGNN_SERVE_FLEET_MIN, HYDRAGNN_SERVE_FLEET_MAX,
    HYDRAGNN_SERVE_AUTOSCALE_UP_FRAC, HYDRAGNN_SERVE_AUTOSCALE_UP_TICKS,
    HYDRAGNN_SERVE_AUTOSCALE_QUIET_S, HYDRAGNN_SERVE_AUTOSCALE_COOLDOWN_S,
    HYDRAGNN_SERVE_MAX_TENANTS, HYDRAGNN_SERVE_TENANT_BUDGET_FRAC,
    HYDRAGNN_SERVE_MAX_EXECUTABLES (docs/SERVING.md "Multi-tenant fleet
    & autoscaler").
    """

    # batch-capacity ladder (graphs per bucket), ascending; each entry
    # becomes one precompiled PadSpec bucket
    buckets: Tuple[int, ...] = (1, 4, 16)
    # per-graph worst case used to size the bucket PadSpecs; 0 = unset
    # (must come from config/env/dataset before an engine can be built)
    max_nodes_per_graph: int = 0
    max_edges_per_graph: int = 0
    # the neighbor cap the TRAINING transform built graphs with (raw
    # config value or its 100 default) — for PNA, finalize overwrites
    # Architecture.max_neighbours with the degree-histogram length, so
    # the server must not rebuild graphs from that.  0 = fall back to
    # the model config's value.  Written by the data pipeline.
    edge_build_max_neighbours: int = 0
    # the training dataset's max edge length — the normalization constant
    # of length edge features (edge_attr = lengths / norm in
    # data/transform.py).  0 = unset: requests to edge-feature models
    # must then carry a pre-normalized edge_attr.  Written into the
    # saved config.json by the data pipeline.
    edge_length_norm: float = 0.0
    # micro-batching: flush when a bucket fills or this deadline fires
    max_wait_ms: float = 20.0
    # bounded request queue; submits beyond this are rejected (503)
    max_queue: int = 1024
    host: str = "127.0.0.1"
    port: int = 8808
    # graceful-shutdown budget: how long close() waits for the queue to
    # drain before failing the leftovers
    drain_timeout_s: float = 10.0
    # default per-request deadline (queue wait + service); a client
    # `timeout_ms` body field / X-Timeout-Ms header overrides it.
    # Requests whose deadline expires in the queue are SHED (429 +
    # Retry-After) before batch formation.  0 = deadlines disabled.
    request_deadline_ms: float = 10_000.0
    # watchdog around each compiled predict call; a flush exceeding it
    # fails (504) and counts toward the breaker.  0 = no watchdog.
    predict_timeout_s: float = 30.0
    # circuit breaker: consecutive failed/timed-out flushes that trip
    # the open state (fail fast with 503, /healthz "degraded");
    # 0 disables the breaker
    breaker_threshold: int = 5
    # open -> half-open probe delay
    breaker_cooldown_s: float = 5.0
    # post-reload probation: a breaker trip within this many seconds of
    # a hot checkpoint swap rolls the engine back to the previous state
    reload_probation_s: float = 60.0
    # optional checkpoint file watch: a changed mtime hot-reloads the
    # file (with golden-batch validation + rollback); "" = off
    reload_watch_path: str = ""
    # watch poll interval; 0 = watch disabled even if a path is set
    reload_watch_s: float = 0.0
    # POST /reload trust boundary: pickle.load of a client-named path is
    # code execution, so non-loopback clients may only reload when this
    # allowlisted checkpoint directory is set AND the path resolves
    # inside it ("" = loopback clients only)
    reload_root: str = ""
    # inference dtype policy (hydragnn_tpu/quant): "f32" (bit-parity
    # baseline), "bf16" (params+compute, 0.5x resident bytes), "int8"
    # (weight-only, per-channel scales dequantized into bf16 matmuls,
    # ~0.26x).  Non-f32 policies only ACTIVATE when the engine's
    # golden-batch replay stays under quant_tolerance; otherwise the
    # server falls back to f32 and emits a quant_reject health event.
    quant_policy: str = "f32"
    # max abs golden-batch output drift vs the f32 reference a policy
    # may introduce and still be accepted (absolute, on the raw model
    # outputs); 0 = strictest (any drift rejects)
    quant_tolerance: float = 0.05
    # -- replica fleet (serve/fleet.py, serve/router.py;
    #    docs/SERVING.md "Replica fleet") --
    # number of supervised engine replicas behind the failover router;
    # 0 = single-server mode (the pre-fleet topology)
    fleet_replicas: int = 0
    # run replicas as threads in THIS process (shared compile cache via
    # engine.fork()) instead of subprocesses — the CPU/dev topology;
    # subprocess replicas are the production default
    fleet_inprocess: bool = False
    # supervisor health-probe interval (chaos ticks count these)
    fleet_probe_s: float = 1.0
    # exponential restart backoff: base, doubling per restart up to max,
    # forgiven after a quiet fleet_restart_window_s
    fleet_restart_backoff_s: float = 1.0
    fleet_restart_backoff_max_s: float = 30.0
    # restart-storm cap: more than this many restarts of one replica
    # within fleet_restart_window_s marks it "failed" (no more
    # restarts — operator attention required); 0 = never restart
    fleet_max_restarts: int = 5
    fleet_restart_window_s: float = 300.0
    # drain-and-replace budget: how long the supervisor waits for a
    # draining replica's in-flight requests before recycling it
    fleet_drain_timeout_s: float = 10.0
    # subprocess replicas: how long one may take to answer its first
    # /healthz after spawn (jax import + AOT warmup)
    fleet_startup_timeout_s: float = 300.0
    # live replicas below this -> fleet_degraded telemetry + teleview
    # WARNING; 0 = majority (N//2 + 1)
    fleet_quorum: int = 0
    # -- closed-loop autoscaler (serve/autoscale.py; docs/SERVING.md
    #    "Multi-tenant fleet & autoscaler") --
    # scale-down floor: the autoscaler never retires below this many
    # live replicas
    fleet_min_replicas: int = 1
    # scale-up ceiling; 0 = autoscaler disabled (the static-fleet
    # topology of PR 7 — fleet_replicas is the fixed size)
    fleet_max_replicas: int = 0
    # scale up when the drain-rate backlog estimate (queued work /
    # fleet drain rate, the same EWMA the admission shed uses) exceeds
    # this fraction of the request deadline
    autoscale_up_frac: float = 0.5
    # hysteresis: that many CONSECUTIVE hot probe ticks before a
    # scale-up fires (one slow flush can't add a replica)
    autoscale_up_ticks: int = 3
    # scale down only after the fleet has been completely idle (zero
    # queued work) for this long
    autoscale_quiet_s: float = 60.0
    # dead time after ANY scale event before the next may fire, so
    # scaling can't flap or interact with restart storms
    autoscale_cooldown_s: float = 30.0
    # -- multi-tenancy --
    # resident tenant engines per replica INCLUDING the default tenant;
    # beyond this the least-recently-used extra tenant is evicted
    # (re-admission is cheap: forks share the compiled cache)
    max_tenants: int = 4
    # per-tenant admission budget as a fraction of fleet capacity:
    # cap = max(1, ceil(frac * drain_rate_rps * deadline_s)) outstanding
    # requests per tenant; over budget -> 429 for THAT tenant only.
    # 0 = budgets disabled (fleet-wide shed only).
    tenant_budget_frac: float = 0.0
    # bounded LRU over AOT executables in the engine compile cache, for
    # structurally-distinct tenants; 0 = unbounded (single-tenant
    # default).  Sizing below one tenant's bucket ladder thrashes.
    max_resident_executables: int = 0

    def __post_init__(self):
        self.buckets = _parse_buckets(self.buckets)
        if not self.buckets or any(int(b) < 1 for b in self.buckets):
            raise ValueError(
                f"Serving.buckets must be positive batch capacities, "
                f"got {self.buckets!r}")
        if tuple(sorted(self.buckets)) != self.buckets:
            raise ValueError(
                f"Serving.buckets must be ascending, got {self.buckets!r}")
        if len(set(self.buckets)) != len(self.buckets):
            raise ValueError(
                f"Serving.buckets must be unique, got {self.buckets!r}")
        if self.max_wait_ms < 0:
            raise ValueError(
                f"Serving.max_wait_ms must be >= 0, got {self.max_wait_ms}")
        if self.max_queue < 1:
            raise ValueError(
                f"Serving.max_queue must be >= 1, got {self.max_queue}")
        if not (0 <= int(self.port) <= 65535):
            raise ValueError(f"Serving.port out of range: {self.port}")
        for name in ("request_deadline_ms", "predict_timeout_s",
                     "breaker_cooldown_s", "reload_probation_s",
                     "reload_watch_s", "fleet_restart_backoff_s",
                     "fleet_restart_backoff_max_s",
                     "fleet_restart_window_s", "fleet_drain_timeout_s",
                     "fleet_startup_timeout_s", "autoscale_up_frac",
                     "autoscale_quiet_s", "autoscale_cooldown_s",
                     "tenant_budget_frac"):
            if float(getattr(self, name)) < 0:
                raise ValueError(
                    f"Serving.{name} must be >= 0, "
                    f"got {getattr(self, name)}")
        for name in ("fleet_replicas", "fleet_max_restarts",
                     "fleet_quorum", "fleet_max_replicas",
                     "max_resident_executables"):
            if int(getattr(self, name)) < 0:
                raise ValueError(
                    f"Serving.{name} must be >= 0, "
                    f"got {getattr(self, name)}")
        for name in ("fleet_min_replicas", "autoscale_up_ticks",
                     "max_tenants"):
            if int(getattr(self, name)) < 1:
                raise ValueError(
                    f"Serving.{name} must be >= 1, "
                    f"got {getattr(self, name)}")
        if int(self.fleet_max_replicas) > 0 \
                and int(self.fleet_min_replicas) \
                > int(self.fleet_max_replicas):
            raise ValueError(
                f"Serving.fleet_min_replicas ({self.fleet_min_replicas}) "
                f"exceeds fleet_max_replicas ({self.fleet_max_replicas})")
        if float(self.fleet_probe_s) <= 0:
            raise ValueError(
                f"Serving.fleet_probe_s must be > 0, "
                f"got {self.fleet_probe_s}")
        # only meaningful when the replica count comes from config too
        # (directly-constructed fleets size themselves)
        if int(self.fleet_replicas) > 0 \
                and int(self.fleet_quorum) > int(self.fleet_replicas):
            raise ValueError(
                f"Serving.fleet_quorum ({self.fleet_quorum}) exceeds "
                f"fleet_replicas ({self.fleet_replicas})")
        if int(self.breaker_threshold) < 0:
            raise ValueError(
                f"Serving.breaker_threshold must be >= 0 (0 disables), "
                f"got {self.breaker_threshold}")
        from hydragnn_tpu.quant import check_policy

        check_policy(self.quant_policy)
        if float(self.quant_tolerance) < 0:
            raise ValueError(
                f"Serving.quant_tolerance must be >= 0, "
                f"got {self.quant_tolerance}")

    @classmethod
    def from_section(cls,
                     section: Optional[Dict[str, Any]]) -> "ServingConfig":
        s = dict(section or {})
        d = cls()
        cfg = cls(
            buckets=_parse_buckets(s.get("buckets", d.buckets)),
            max_nodes_per_graph=int(s.get("max_nodes_per_graph",
                                          d.max_nodes_per_graph)),
            max_edges_per_graph=int(s.get("max_edges_per_graph",
                                          d.max_edges_per_graph)),
            edge_build_max_neighbours=int(s.get(
                "edge_build_max_neighbours", d.edge_build_max_neighbours)),
            edge_length_norm=float(s.get("edge_length_norm",
                                         d.edge_length_norm)),
            max_wait_ms=float(s.get("max_wait_ms", d.max_wait_ms)),
            max_queue=int(s.get("max_queue", d.max_queue)),
            host=str(s.get("host", d.host)),
            port=int(s.get("port", d.port)),
            drain_timeout_s=float(s.get("drain_timeout_s",
                                        d.drain_timeout_s)),
            request_deadline_ms=float(s.get("request_deadline_ms",
                                            d.request_deadline_ms)),
            predict_timeout_s=float(s.get("predict_timeout_s",
                                          d.predict_timeout_s)),
            breaker_threshold=int(s.get("breaker_threshold",
                                        d.breaker_threshold)),
            breaker_cooldown_s=float(s.get("breaker_cooldown_s",
                                           d.breaker_cooldown_s)),
            reload_probation_s=float(s.get("reload_probation_s",
                                           d.reload_probation_s)),
            reload_watch_path=str(s.get("reload_watch_path",
                                        d.reload_watch_path)),
            reload_watch_s=float(s.get("reload_watch_s",
                                       d.reload_watch_s)),
            reload_root=str(s.get("reload_root", d.reload_root)),
            quant_policy=str(s.get("quant_policy", d.quant_policy)),
            quant_tolerance=float(s.get("quant_tolerance",
                                        d.quant_tolerance)),
            fleet_replicas=int(s.get("fleet_replicas", d.fleet_replicas)),
            fleet_inprocess=bool(s.get("fleet_inprocess",
                                       d.fleet_inprocess)),
            fleet_probe_s=float(s.get("fleet_probe_s", d.fleet_probe_s)),
            fleet_restart_backoff_s=float(s.get(
                "fleet_restart_backoff_s", d.fleet_restart_backoff_s)),
            fleet_restart_backoff_max_s=float(s.get(
                "fleet_restart_backoff_max_s",
                d.fleet_restart_backoff_max_s)),
            fleet_max_restarts=int(s.get("fleet_max_restarts",
                                         d.fleet_max_restarts)),
            fleet_restart_window_s=float(s.get(
                "fleet_restart_window_s", d.fleet_restart_window_s)),
            fleet_drain_timeout_s=float(s.get(
                "fleet_drain_timeout_s", d.fleet_drain_timeout_s)),
            fleet_startup_timeout_s=float(s.get(
                "fleet_startup_timeout_s", d.fleet_startup_timeout_s)),
            fleet_quorum=int(s.get("fleet_quorum", d.fleet_quorum)),
            fleet_min_replicas=int(s.get("fleet_min_replicas",
                                         d.fleet_min_replicas)),
            fleet_max_replicas=int(s.get("fleet_max_replicas",
                                         d.fleet_max_replicas)),
            autoscale_up_frac=float(s.get("autoscale_up_frac",
                                          d.autoscale_up_frac)),
            autoscale_up_ticks=int(s.get("autoscale_up_ticks",
                                         d.autoscale_up_ticks)),
            autoscale_quiet_s=float(s.get("autoscale_quiet_s",
                                          d.autoscale_quiet_s)),
            autoscale_cooldown_s=float(s.get("autoscale_cooldown_s",
                                             d.autoscale_cooldown_s)),
            max_tenants=int(s.get("max_tenants", d.max_tenants)),
            tenant_budget_frac=float(s.get("tenant_budget_frac",
                                           d.tenant_budget_frac)),
            max_resident_executables=int(s.get(
                "max_resident_executables", d.max_resident_executables)),
        )
        if "HYDRAGNN_SERVE_BUCKETS" in os.environ:
            cfg.buckets = _parse_buckets(os.environ["HYDRAGNN_SERVE_BUCKETS"])
        if "HYDRAGNN_SERVE_MAX_NODES" in os.environ:
            cfg.max_nodes_per_graph = env_int("HYDRAGNN_SERVE_MAX_NODES", 0)
        if "HYDRAGNN_SERVE_MAX_EDGES" in os.environ:
            cfg.max_edges_per_graph = env_int("HYDRAGNN_SERVE_MAX_EDGES", 0)
        if "HYDRAGNN_SERVE_EDGE_NORM" in os.environ:
            cfg.edge_length_norm = float(
                os.environ["HYDRAGNN_SERVE_EDGE_NORM"])
        if "HYDRAGNN_SERVE_MAX_WAIT_MS" in os.environ:
            cfg.max_wait_ms = float(os.environ["HYDRAGNN_SERVE_MAX_WAIT_MS"])
        if "HYDRAGNN_SERVE_QUEUE" in os.environ:
            cfg.max_queue = env_int("HYDRAGNN_SERVE_QUEUE", d.max_queue)
        if "HYDRAGNN_SERVE_HOST" in os.environ:
            cfg.host = os.environ["HYDRAGNN_SERVE_HOST"]
        if "HYDRAGNN_SERVE_PORT" in os.environ:
            cfg.port = env_int("HYDRAGNN_SERVE_PORT", d.port)
        if "HYDRAGNN_SERVE_DRAIN_S" in os.environ:
            cfg.drain_timeout_s = float(os.environ["HYDRAGNN_SERVE_DRAIN_S"])
        if "HYDRAGNN_SERVE_DEADLINE_MS" in os.environ:
            cfg.request_deadline_ms = float(
                os.environ["HYDRAGNN_SERVE_DEADLINE_MS"])
        if "HYDRAGNN_SERVE_PREDICT_TIMEOUT_S" in os.environ:
            cfg.predict_timeout_s = float(
                os.environ["HYDRAGNN_SERVE_PREDICT_TIMEOUT_S"])
        if "HYDRAGNN_SERVE_BREAKER_THRESHOLD" in os.environ:
            cfg.breaker_threshold = env_int(
                "HYDRAGNN_SERVE_BREAKER_THRESHOLD", d.breaker_threshold)
        if "HYDRAGNN_SERVE_BREAKER_COOLDOWN_S" in os.environ:
            cfg.breaker_cooldown_s = float(
                os.environ["HYDRAGNN_SERVE_BREAKER_COOLDOWN_S"])
        if "HYDRAGNN_SERVE_RELOAD_WATCH" in os.environ:
            cfg.reload_watch_path = os.environ["HYDRAGNN_SERVE_RELOAD_WATCH"]
        if "HYDRAGNN_SERVE_RELOAD_WATCH_S" in os.environ:
            cfg.reload_watch_s = float(
                os.environ["HYDRAGNN_SERVE_RELOAD_WATCH_S"])
        if "HYDRAGNN_SERVE_RELOAD_ROOT" in os.environ:
            cfg.reload_root = os.environ["HYDRAGNN_SERVE_RELOAD_ROOT"]
        if "HYDRAGNN_SERVE_QUANT_POLICY" in os.environ:
            cfg.quant_policy = os.environ["HYDRAGNN_SERVE_QUANT_POLICY"]
        if "HYDRAGNN_SERVE_QUANT_TOL" in os.environ:
            cfg.quant_tolerance = float(
                os.environ["HYDRAGNN_SERVE_QUANT_TOL"])
        if "HYDRAGNN_SERVE_FLEET" in os.environ:
            cfg.fleet_replicas = env_int("HYDRAGNN_SERVE_FLEET",
                                         d.fleet_replicas)
        if "HYDRAGNN_SERVE_FLEET_INPROCESS" in os.environ:
            cfg.fleet_inprocess = bool(env_int(
                "HYDRAGNN_SERVE_FLEET_INPROCESS", 0))
        if "HYDRAGNN_SERVE_FLEET_PROBE_S" in os.environ:
            cfg.fleet_probe_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_PROBE_S"])
        if "HYDRAGNN_SERVE_FLEET_BACKOFF_S" in os.environ:
            cfg.fleet_restart_backoff_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_BACKOFF_S"])
        if "HYDRAGNN_SERVE_FLEET_BACKOFF_MAX_S" in os.environ:
            cfg.fleet_restart_backoff_max_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_BACKOFF_MAX_S"])
        if "HYDRAGNN_SERVE_FLEET_MAX_RESTARTS" in os.environ:
            cfg.fleet_max_restarts = env_int(
                "HYDRAGNN_SERVE_FLEET_MAX_RESTARTS", d.fleet_max_restarts)
        if "HYDRAGNN_SERVE_FLEET_RESTART_WINDOW_S" in os.environ:
            cfg.fleet_restart_window_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_RESTART_WINDOW_S"])
        if "HYDRAGNN_SERVE_FLEET_DRAIN_S" in os.environ:
            cfg.fleet_drain_timeout_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_DRAIN_S"])
        if "HYDRAGNN_SERVE_FLEET_STARTUP_S" in os.environ:
            cfg.fleet_startup_timeout_s = float(
                os.environ["HYDRAGNN_SERVE_FLEET_STARTUP_S"])
        if "HYDRAGNN_SERVE_FLEET_QUORUM" in os.environ:
            cfg.fleet_quorum = env_int("HYDRAGNN_SERVE_FLEET_QUORUM",
                                       d.fleet_quorum)
        if "HYDRAGNN_SERVE_FLEET_MIN" in os.environ:
            cfg.fleet_min_replicas = env_int("HYDRAGNN_SERVE_FLEET_MIN",
                                             d.fleet_min_replicas)
        if "HYDRAGNN_SERVE_FLEET_MAX" in os.environ:
            cfg.fleet_max_replicas = env_int("HYDRAGNN_SERVE_FLEET_MAX",
                                             d.fleet_max_replicas)
        if "HYDRAGNN_SERVE_AUTOSCALE_UP_FRAC" in os.environ:
            cfg.autoscale_up_frac = float(
                os.environ["HYDRAGNN_SERVE_AUTOSCALE_UP_FRAC"])
        if "HYDRAGNN_SERVE_AUTOSCALE_UP_TICKS" in os.environ:
            cfg.autoscale_up_ticks = env_int(
                "HYDRAGNN_SERVE_AUTOSCALE_UP_TICKS", d.autoscale_up_ticks)
        if "HYDRAGNN_SERVE_AUTOSCALE_QUIET_S" in os.environ:
            cfg.autoscale_quiet_s = float(
                os.environ["HYDRAGNN_SERVE_AUTOSCALE_QUIET_S"])
        if "HYDRAGNN_SERVE_AUTOSCALE_COOLDOWN_S" in os.environ:
            cfg.autoscale_cooldown_s = float(
                os.environ["HYDRAGNN_SERVE_AUTOSCALE_COOLDOWN_S"])
        if "HYDRAGNN_SERVE_MAX_TENANTS" in os.environ:
            cfg.max_tenants = env_int("HYDRAGNN_SERVE_MAX_TENANTS",
                                      d.max_tenants)
        if "HYDRAGNN_SERVE_TENANT_BUDGET_FRAC" in os.environ:
            cfg.tenant_budget_frac = float(
                os.environ["HYDRAGNN_SERVE_TENANT_BUDGET_FRAC"])
        if "HYDRAGNN_SERVE_MAX_EXECUTABLES" in os.environ:
            cfg.max_resident_executables = env_int(
                "HYDRAGNN_SERVE_MAX_EXECUTABLES",
                d.max_resident_executables)
        # re-validate after the env overlay (the dataclass validated the
        # config values; env strings can be just as wrong)
        cfg.__post_init__()
        return cfg


def serving_defaults() -> Dict[str, Any]:
    """Top-level ``Serving`` section defaults written back by
    config.finalize, so a saved config.json documents the run's serving
    settings (docs/SERVING.md)."""
    d = ServingConfig()
    return {
        "buckets": ",".join(str(b) for b in d.buckets),
        "max_nodes_per_graph": d.max_nodes_per_graph,
        "max_edges_per_graph": d.max_edges_per_graph,
        "edge_build_max_neighbours": d.edge_build_max_neighbours,
        "edge_length_norm": d.edge_length_norm,
        "max_wait_ms": d.max_wait_ms,
        "max_queue": d.max_queue,
        "host": d.host,
        "port": d.port,
        "drain_timeout_s": d.drain_timeout_s,
        "request_deadline_ms": d.request_deadline_ms,
        "predict_timeout_s": d.predict_timeout_s,
        "breaker_threshold": d.breaker_threshold,
        "breaker_cooldown_s": d.breaker_cooldown_s,
        "reload_probation_s": d.reload_probation_s,
        "reload_watch_path": d.reload_watch_path,
        "reload_watch_s": d.reload_watch_s,
        "reload_root": d.reload_root,
        "quant_policy": d.quant_policy,
        "quant_tolerance": d.quant_tolerance,
        "fleet_replicas": d.fleet_replicas,
        "fleet_inprocess": d.fleet_inprocess,
        "fleet_probe_s": d.fleet_probe_s,
        "fleet_restart_backoff_s": d.fleet_restart_backoff_s,
        "fleet_restart_backoff_max_s": d.fleet_restart_backoff_max_s,
        "fleet_max_restarts": d.fleet_max_restarts,
        "fleet_restart_window_s": d.fleet_restart_window_s,
        "fleet_drain_timeout_s": d.fleet_drain_timeout_s,
        "fleet_startup_timeout_s": d.fleet_startup_timeout_s,
        "fleet_quorum": d.fleet_quorum,
        "fleet_min_replicas": d.fleet_min_replicas,
        "fleet_max_replicas": d.fleet_max_replicas,
        "autoscale_up_frac": d.autoscale_up_frac,
        "autoscale_up_ticks": d.autoscale_up_ticks,
        "autoscale_quiet_s": d.autoscale_quiet_s,
        "autoscale_cooldown_s": d.autoscale_cooldown_s,
        "max_tenants": d.max_tenants,
        "tenant_budget_frac": d.tenant_budget_frac,
        "max_resident_executables": d.max_resident_executables,
    }
