"""Dynamic micro-batcher: accumulate requests until a bucket fills or a
deadline fires.

Online traffic arrives one graph at a time; the accelerator wants padded
batches.  The batcher bridges the two with the classic
fill-or-deadline policy:

- requests land in a BOUNDED thread-safe queue (beyond ``max_queue`` the
  submit is rejected — backpressure instead of unbounded latency);
- a single worker thread groups consecutive requests until either the
  group would no longer fit the largest bucket (``full`` flush — zero
  added latency beyond the step time) or ``max_wait_ms`` has elapsed
  since the OLDEST request in the group was enqueued (``deadline``
  flush — the latency bound);
- each flush picks the smallest bucket that fits (minimum padding
  waste), runs one engine prediction, and resolves the per-request
  futures.

Why one worker: JAX dispatch is serialized per device anyway, and a
single consumer keeps request ordering and makes the shutdown drain
trivially correct.  Shutdown reuses the bounded-queue drain idiom shared
with the prefetch loaders (data/prefetch.py:drain_bounded_queue): a
sentinel closes the stream FIFO, so everything enqueued before close is
served, and the force path fails leftover futures instead of leaking
blocked clients.

Telemetry: request_enqueued / batch_flushed / deadline_flush health
events through the shared MetricsLogger (docs/TELEMETRY.md "Serving
events"); fill % and padding % ride the batch_flushed records.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from hydragnn_tpu.data.prefetch import drain_bounded_queue
from hydragnn_tpu.graph.batch import GraphSample

_SENTINEL = object()


class QueueFullError(RuntimeError):
    """The bounded request queue is at capacity (HTTP layer: 503)."""


class BatcherClosedError(RuntimeError):
    """Submit after close, or the request was dropped by a forced
    shutdown."""


class _Request:
    __slots__ = ("sample", "future", "t_enq")

    def __init__(self, sample: GraphSample):
        self.sample = sample
        self.future: Future = Future()
        self.t_enq = time.perf_counter()


class MicroBatcher:
    def __init__(self, engine, max_wait_ms: float = 20.0,
                 max_queue: int = 1024, telemetry=None):
        self.engine = engine
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(max_queue)))
        self.telemetry = telemetry if telemetry is not None \
            else engine.telemetry
        self._stop = threading.Event()    # force-exit signal (no drain)
        self._closed = threading.Event()  # no new submits
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._n = {"requests": 0, "rejected": 0, "batches": 0,
                   "full_flushes": 0, "deadline_flushes": 0,
                   "drain_flushes": 0, "errors": 0}
        self._fill_sum = 0.0
        self._pad_nodes_sum = 0.0
        self._predict_ms_sum = 0.0
        self._predict_ms_max = 0.0

    # -- producer side -------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="micro-batcher", daemon=True)
            self._thread.start()
        return self

    def submit(self, sample: GraphSample) -> Future:
        """Enqueue one request; the returned future resolves to the
        engine's per-sample result dict ``{head_name: array}``."""
        if self._closed.is_set():
            raise BatcherClosedError("batcher is shut down")
        # reject single requests that can never be batched
        if not self.engine.fits([sample]):
            from hydragnn_tpu.serve.engine import BucketOverflowError

            raise BucketOverflowError(
                f"graph with {sample.num_nodes} nodes / {sample.num_edges} "
                "edges exceeds the largest serving bucket")
        req = _Request(sample)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            with self._lock:
                self._n["rejected"] += 1
            raise QueueFullError(
                f"request queue at capacity ({self._q.maxsize})") from None
        if self._closed.is_set() and self._thread is None:
            # raced close(): the worker is already gone and its final
            # sweep may have run before our put — fail fast (the caller
            # sees the exception through the future) instead of letting
            # the client wait out its timeout
            self._sweep_leftovers()
            return req.future
        with self._lock:
            self._n["requests"] += 1
        self.telemetry.health("request_enqueued", depth=self._q.qsize())
        return req.future

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        pending: Optional[_Request] = None  # didn't fit the last group
        while not self._stop.is_set():
            if pending is not None:
                first, pending = pending, None
            else:
                try:
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    if self._closed.is_set():
                        break
                    continue
                if first is _SENTINEL:
                    break
            group = [first]
            # running totals for O(1) admission (re-summing the group
            # per arrival would be O(n^2) per flush on the hot path)
            g_nodes = first.sample.num_nodes
            g_edges = first.sample.num_edges
            top = self.engine.pad_specs[-1]
            deadline = first.t_enq + self.max_wait_s
            reason = "deadline"
            got_sentinel = False
            while True:
                if self._stop.is_set() or self._closed.is_set():
                    # draining: serve what we have NOW, don't wait out
                    # the deadline
                    reason = "drain"
                    break
                if len(group) >= self.engine.max_batch_graphs:
                    reason = "full"
                    break
                remaining = deadline - time.perf_counter()
                try:
                    if remaining > 0:
                        item = self._q.get(timeout=remaining)
                    else:
                        # deadline already passed (the queue backed up
                        # while we served earlier batches): keep
                        # gathering whatever is ALREADY queued without
                        # blocking, so a backlog still forms full
                        # buckets instead of degenerate size-1 flushes
                        item = self._q.get_nowait()
                except queue.Empty:
                    reason = "deadline"
                    break
                if item is _SENTINEL:
                    got_sentinel = True
                    reason = "drain"
                    break
                # largest-bucket bounds, same slot conventions as
                # engine.select_bucket (collate reserves one node slot
                # and the padding-graph slot)
                if (g_nodes + item.sample.num_nodes > top.num_nodes - 1
                        or g_edges + item.sample.num_edges > top.num_edges):
                    pending = item
                    reason = "full"
                    break
                group.append(item)
                g_nodes += item.sample.num_nodes
                g_edges += item.sample.num_edges
            self._flush(group, reason)
            if got_sentinel:
                break
        if pending is not None:
            self._fail(pending)

    def _flush(self, group: List[_Request], reason: str) -> None:
        samples = [r.sample for r in group]
        t0 = time.perf_counter()
        try:
            spec = self.engine.select_bucket(samples)
            results = self.engine.predict_samples(samples)
        except Exception as e:  # noqa: BLE001 — surfaced per request
            with self._lock:
                self._n["errors"] += 1
                self._n["batches"] += 1
            self.telemetry.health("batch_error", n=len(group),
                                  error=repr(e))
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        predict_ms = (time.perf_counter() - t0) * 1e3
        for r, res in zip(group, results):
            if not r.future.done():
                r.future.set_result(res)
        fill_pct = 100.0 * len(group) / max(spec.num_graphs - 1, 1)
        real_nodes = sum(s.num_nodes for s in samples)
        pad_nodes_pct = 100.0 * (1.0 - real_nodes / max(spec.num_nodes, 1))
        wait_ms = (t0 - group[0].t_enq) * 1e3
        with self._lock:
            self._n["batches"] += 1
            self._n[f"{reason}_flushes"] += 1
            self._fill_sum += fill_pct
            self._pad_nodes_sum += pad_nodes_pct
            self._predict_ms_sum += predict_ms
            self._predict_ms_max = max(self._predict_ms_max, predict_ms)
        self.telemetry.health(
            "batch_flushed", n=len(group), reason=reason,
            fill_pct=round(fill_pct, 2),
            pad_nodes_pct=round(pad_nodes_pct, 2),
            wait_ms=round(wait_ms, 3), predict_ms=round(predict_ms, 3))
        if reason == "deadline":
            self.telemetry.health("deadline_flush", n=len(group),
                                  wait_ms=round(wait_ms, 3))

    def _fail(self, item) -> None:
        if isinstance(item, _Request) and not item.future.done():
            item.future.set_exception(
                BatcherClosedError("batcher closed before the request was "
                                   "served"))

    def _sweep_leftovers(self) -> None:
        """Fail any request still queued after the worker exited (a
        submit racing close() can land one behind the drain sentinel)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not _SENTINEL:
                self._fail(item)

    # -- shutdown ------------------------------------------------------------

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop accepting requests and shut the worker down.

        ``drain=True``: a sentinel closes the queue FIFO — everything
        enqueued before the close is flushed (immediately, not waiting
        out deadlines) and answered; bounded by ``timeout``.  On timeout
        (or ``drain=False``) the shared drain helper unblocks any stuck
        producer and fails leftover futures so no client waits forever.
        """
        if self._closed.is_set() and self._thread is None:
            return
        self._closed.set()
        t = self._thread
        if t is None:
            # never started: fail whatever was queued
            drain_bounded_queue(self._q, _SENTINEL, self._stop,
                                on_item=self._fail)
            self._q.put(_SENTINEL)
            return
        if drain:
            try:
                self._q.put(_SENTINEL, timeout=1.0)
            except queue.Full:
                pass  # worker is behind; the force path below cleans up
            t.join(timeout=timeout)
        if not drain or t.is_alive():
            # force path: stop flag + sentinel wake a blocked worker; it
            # drain-flushes its current group and exits at the next check
            self._stop.set()
            try:
                self._q.put_nowait(_SENTINEL)
            except queue.Full:
                pass  # a full queue means the worker has items to wake on
            t.join(timeout=max(1.0, self.max_wait_s + 1.0))
            if t.is_alive():
                # worker is stuck inside a long predict: hand the queue
                # to the background drain helper (leak-safe shutdown —
                # same idiom as the prefetch loaders).  TWO sentinels:
                # the stuck worker, if it ever revives, may consume one
                # — the second still terminates the drain daemon (any
                # leftover sentinel is swallowed by the final sweep).
                drain_bounded_queue(self._q, _SENTINEL, self._stop,
                                    on_item=self._fail)
                self._q.put(_SENTINEL)
                self._q.put(_SENTINEL)
        self._thread = None
        # catch stragglers a racing submit slipped behind the sentinel
        # (also consumes stray sentinels left in the queue)
        self._sweep_leftovers()

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            nb = self._n["batches"]
            ok = max(nb - self._n["errors"], 0)
            return {
                **self._n,
                "queue_depth": self._q.qsize(),
                "max_wait_ms": self.max_wait_s * 1e3,
                "avg_fill_pct": (self._fill_sum / ok) if ok else 0.0,
                "avg_pad_nodes_pct": (self._pad_nodes_sum / ok) if ok
                                     else 0.0,
                "avg_predict_ms": (self._predict_ms_sum / ok) if ok else 0.0,
                "max_predict_ms": self._predict_ms_max,
            }
