"""Dynamic micro-batcher: accumulate requests until a bucket fills or a
deadline fires.

Online traffic arrives one graph at a time; the accelerator wants padded
batches.  The batcher bridges the two with the classic
fill-or-deadline policy:

- requests land in a BOUNDED thread-safe queue (beyond ``max_queue`` the
  submit is rejected — backpressure instead of unbounded latency);
- a single worker thread groups consecutive requests until either the
  group would no longer fit the largest bucket (``full`` flush — zero
  added latency beyond the step time) or ``max_wait_ms`` has elapsed
  since the OLDEST request in the group was enqueued (``deadline``
  flush — the latency bound);
- each flush picks the smallest bucket that fits (minimum padding
  waste), runs one engine prediction, and resolves the per-request
  futures.

Why one worker: JAX dispatch is serialized per device anyway, and a
single consumer keeps request ordering and makes the shutdown drain
trivially correct.  Shutdown reuses the bounded-queue drain idiom shared
with the prefetch loaders (data/prefetch.py:drain_bounded_queue): a
sentinel closes the stream FIFO, so everything enqueued before close is
served, and the force path fails leftover futures instead of leaking
blocked clients.

Overload safety (docs/SERVING.md "Overload behavior"): every request
may carry a DEADLINE (queue wait + service).  Requests that provably
cannot meet it are shed at submit time (``RequestShedError`` -> HTTP 429
with a Retry-After derived from the measured drain rate), and entries
whose deadline expired while queued are skipped before batch formation
(``DeadlineExpiredError``) so one slow burst cannot poison subsequent
batches.  Each predict flush runs under a WATCHDOG thread
(``predict_timeout_s``); timeouts and exceptions feed the circuit
breaker (resilience/breaker.py), which fails submits AND queued flushes
fast while open.

Telemetry: request_enqueued / batch_flushed / deadline_flush /
request_shed / deadline_expired / predict_timeout health events through
the shared MetricsLogger (docs/TELEMETRY.md "Serving events"); fill %
and padding % ride the batch_flushed records AND a full per-flush STEP
record in the trainer's JSONL schema (``source: "serve"`` — one format
for train and serve padding waste).  The batcher also tallies
request-size and per-flush demand histograms plus per-bucket
fill/waste aggregates into ``stats()`` (-> GET /metrics), the live
inputs of the bucket autotuner (serve/autotune.py,
tools/buckettune.py).
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from hydragnn_tpu.data.prefetch import drain_bounded_queue
from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.resilience.breaker import BreakerOpenError

_SENTINEL = object()


class QueueFullError(RuntimeError):
    """The bounded request queue is at capacity (HTTP layer: 503)."""


class BatcherClosedError(RuntimeError):
    """Submit after close, or the request was dropped by a forced
    shutdown."""


class RequestShedError(RuntimeError):
    """Load shed: the request cannot meet its deadline (HTTP 429).

    ``retry_after_s`` estimates when the queue will have drained —
    what the HTTP layer puts in the 429's ``Retry-After`` header.
    """

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeadlineExpiredError(RequestShedError):
    """The request's deadline expired while it waited in the queue."""


class PredictTimeoutError(RuntimeError):
    """A predict flush exceeded the watchdog timeout (HTTP 504)."""


class _WatchdogWorker:
    """One persistent daemon thread running predict jobs for the
    batcher's watchdog.  A job is a ``{"samples", "done", ...}`` box;
    the worker fills ``res``/``err`` and sets ``done``.  ``retire()``
    makes the thread exit after its current (possibly stuck) call —
    used when a timeout abandons it."""

    def __init__(self, fn):
        self._fn = fn
        self._inbox: "queue.Queue" = queue.Queue()
        self._retired = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="predict-watchdog", daemon=True)
        self._thread.start()

    def run(self, samples) -> Dict[str, Any]:
        box: Dict[str, Any] = {"samples": samples,
                               "done": threading.Event()}
        self._inbox.put(box)
        return box

    def retire(self) -> None:
        self._retired.set()
        self._inbox.put(None)  # wake it if it is idle

    def _loop(self) -> None:
        while not self._retired.is_set():
            box = self._inbox.get()
            if box is None or self._retired.is_set():
                return
            try:
                box["res"] = self._fn(box["samples"])
            except BaseException as e:  # noqa: BLE001 — re-raised by caller
                box["err"] = e
            finally:
                box["done"].set()


class _Request:
    __slots__ = ("sample", "future", "t_enq", "deadline", "trace")

    def __init__(self, sample: GraphSample,
                 deadline: Optional[float] = None, trace=None):
        self.sample = sample
        self.future: Future = Future()
        self.t_enq = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.trace = trace  # telemetry.trace.SpanContext, or None


class MicroBatcher:
    def __init__(self, engine, max_wait_ms: float = 20.0,
                 max_queue: int = 1024, telemetry=None,
                 default_deadline_ms: float = 0.0,
                 predict_timeout_s: float = 0.0,
                 breaker=None, chaos=None):
        self.engine = engine
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(max_queue)))
        self.telemetry = telemetry if telemetry is not None \
            else engine.telemetry
        # 0 = deadlines disabled unless the caller passes one per submit
        self.default_deadline_s = max(0.0, float(default_deadline_ms)) / 1e3
        # 0 = no watchdog (predict runs inline on the worker thread)
        self.predict_timeout_s = max(0.0, float(predict_timeout_s))
        self.breaker = breaker  # resilience.breaker.CircuitBreaker or None
        self.chaos = chaos      # resilience.chaos.ServeChaos or None
        self._stop = threading.Event()    # force-exit signal (no drain)
        self._closed = threading.Event()  # no new submits
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._n = {"requests": 0, "rejected": 0, "batches": 0,
                   "full_flushes": 0, "deadline_flushes": 0,
                   "drain_flushes": 0, "errors": 0,
                   "shed": 0, "expired": 0, "predict_timeouts": 0,
                   "breaker_fastfails": 0}
        self._fill_sum = 0.0
        self._pad_nodes_sum = 0.0
        self._pad_edges_sum = 0.0
        self._predict_ms_sum = 0.0
        self._predict_ms_max = 0.0
        # autotuner inputs (serve/autotune.py, GET /metrics): per-request
        # node/edge size histograms of ACCEPTED requests, the per-flush
        # required-capacity (demand) histogram, and per-bucket flush
        # aggregates.  Sizes are bounded by the top bucket, so the
        # distinct-key counts stay small.
        self._req_nodes_hist: Dict[int, int] = {}
        self._req_edges_hist: Dict[int, int] = {}
        self._flush_demands: Dict[int, int] = {}
        self._bucket_stats: Dict[str, Dict[str, float]] = {}
        # EWMA of served requests/second over flush cycles — the drain
        # rate behind admission-shed decisions and Retry-After hints —
        # and of per-flush predict seconds (a request's deadline covers
        # queue wait AND service, so admission must budget both)
        self._rate_ewma: Optional[float] = None
        self._predict_ewma_s: Optional[float] = None
        # lazily-started persistent watchdog helper (worker thread only)
        self._watchdog: Optional[_WatchdogWorker] = None

    # -- producer side -------------------------------------------------------

    def start(self) -> "MicroBatcher":
        # under the lock: two racing start() calls must not spawn two
        # workers draining one queue
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="micro-batcher", daemon=True)
                self._thread.start()
        return self

    def worker_alive(self) -> bool:
        """Is the consumer thread running?  The fleet supervisor's
        liveness probe for in-process replicas (serve/fleet.py) — False
        before start(), after close(), and if the worker ever died."""
        t = self._thread
        return t is not None and t.is_alive()

    # -- load shedding -------------------------------------------------------

    def _est_wait_s(self, depth: int) -> Optional[float]:
        """Estimated queue-drain time for ``depth`` requests at the
        measured service rate; None before any rate sample exists (cold
        start never sheds — there is nothing to base the estimate on)."""
        r = self._rate_ewma
        if r is None or r <= 0:
            return None
        return depth / r

    def retry_after_s(self) -> float:
        """How long a rejected client should back off: the estimated
        drain time of the current queue (>= 1 s, so 429/503 responses
        always carry a meaningful Retry-After)."""
        est = self._est_wait_s(max(1, self._q.qsize()))
        return max(1.0, est if est is not None else 1.0)

    def submit(self, sample: GraphSample,
               deadline_s: Optional[float] = None, trace=None) -> Future:
        """Enqueue one request; the returned future resolves to the
        engine's per-sample result dict ``{head_name: array}``.

        ``deadline_s`` is this request's total budget (queue wait +
        service) from now; None uses the configured default, and a
        default of 0 means no deadline.  A request whose deadline the
        current backlog provably exceeds is shed HERE — before it ever
        occupies a queue slot (``RequestShedError`` -> 429).

        ``trace`` carries the request's :class:`~hydragnn_tpu.telemetry
        .trace.SpanContext` so the flush that serves it can link its
        trace and attribute its queue wait (default None: untraced).
        """
        if self._closed.is_set():
            raise BatcherClosedError("batcher is shut down")
        if self.breaker is not None and not self.breaker.allow():
            raise BreakerOpenError(
                "predict path is circuit-broken — failing fast",
                retry_after_s=self.breaker.time_to_retry())
        # reject single requests that can never be batched
        if not self.engine.fits([sample]):
            from hydragnn_tpu.serve.engine import BucketOverflowError

            raise BucketOverflowError(
                f"graph with {sample.num_nodes} nodes / {sample.num_edges} "
                "edges exceeds the largest serving bucket")
        if deadline_s is None and self.default_deadline_s > 0:
            deadline_s = self.default_deadline_s
        deadline = None
        if deadline_s is not None:
            deadline = time.perf_counter() + max(0.0, float(deadline_s))
            # admission control: if draining the CURRENT backlog plus
            # this request's own service time already consumes its whole
            # budget, shed now (429 + Retry-After) instead of queueing a
            # guaranteed timeout
            est = self._est_wait_s(self._q.qsize() + 1)
            if est is not None:
                est += self._predict_ewma_s or 0.0
            if est is not None and est > max(0.0, float(deadline_s)):
                with self._lock:
                    self._n["shed"] += 1
                self.telemetry.health(
                    "request_shed", depth=self._q.qsize(),
                    est_wait_ms=round(est * 1e3, 1),
                    deadline_ms=round(float(deadline_s) * 1e3, 1))
                raise RequestShedError(
                    f"queue drain estimate {est * 1e3:.0f} ms exceeds the "
                    f"request deadline {float(deadline_s) * 1e3:.0f} ms",
                    retry_after_s=max(1.0, est))
        req = _Request(sample, deadline=deadline, trace=trace)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            with self._lock:
                self._n["rejected"] += 1
            raise QueueFullError(
                f"request queue at capacity ({self._q.maxsize})") from None
        if self._closed.is_set() and self._thread is None:
            # raced close(): the worker is already gone and its final
            # sweep may have run before our put — fail fast (the caller
            # sees the exception through the future) instead of letting
            # the client wait out its timeout
            self._sweep_leftovers()
            return req.future
        with self._lock:
            self._n["requests"] += 1
            n = int(sample.num_nodes)
            e = int(sample.num_edges)
            self._req_nodes_hist[n] = self._req_nodes_hist.get(n, 0) + 1
            self._req_edges_hist[e] = self._req_edges_hist.get(e, 0) + 1
        self.telemetry.health("request_enqueued", depth=self._q.qsize())
        return req.future

    # -- worker --------------------------------------------------------------

    def _expired(self, req: "_Request",
                 now: Optional[float] = None) -> bool:
        if req.deadline is None:
            return False
        # budget semantics: the deadline covers queue wait AND service.
        # An entry whose remaining budget cannot cover one predict would
        # only ever deliver a late, useless answer — shed it now so its
        # bucket slot goes to a request that can still make it.
        if now is None:
            now = time.perf_counter()
        return now + (self._predict_ewma_s or 0.0) > req.deadline

    def _shed_expired(self, reqs: List["_Request"]) -> None:
        """Fail requests whose deadline expired in the queue — skipped
        BEFORE batch formation so a stale burst can't poison the batch
        that follows it."""
        if not reqs:
            return
        now = time.perf_counter()
        retry = self.retry_after_s()
        for r in reqs:
            if not r.future.done():
                r.future.set_exception(DeadlineExpiredError(
                    f"deadline expired after {(now - r.t_enq) * 1e3:.0f} ms "
                    "in queue", retry_after_s=retry))
        with self._lock:
            self._n["expired"] += len(reqs)
        self.telemetry.health(
            "deadline_expired", count=len(reqs),
            waited_ms=round((now - reqs[0].t_enq) * 1e3, 1),
            depth=self._q.qsize())

    def _run(self) -> None:
        pending: Optional[_Request] = None  # didn't fit the last group
        while not self._stop.is_set():
            if pending is not None:
                first, pending = pending, None
            else:
                try:
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    if self._closed.is_set():
                        break
                    continue
                if first is _SENTINEL:
                    break
            if self._expired(first):
                # never anchor a group (and its max_wait) on a request
                # that is already dead
                self._shed_expired([first])
                continue
            group = [first]
            # running totals for O(1) admission (re-summing the group
            # per arrival would be O(n^2) per flush on the hot path)
            g_nodes = first.sample.num_nodes
            g_edges = first.sample.num_edges
            top = self.engine.pad_specs[-1]
            deadline = first.t_enq + self.max_wait_s
            reason = "deadline"
            got_sentinel = False
            while True:
                if self._stop.is_set() or self._closed.is_set():
                    # draining: serve what we have NOW, don't wait out
                    # the deadline
                    reason = "drain"
                    break
                if len(group) >= self.engine.max_batch_graphs:
                    reason = "full"
                    break
                remaining = deadline - time.perf_counter()
                try:
                    if remaining > 0:
                        item = self._q.get(timeout=remaining)
                    else:
                        # deadline already passed (the queue backed up
                        # while we served earlier batches): keep
                        # gathering whatever is ALREADY queued without
                        # blocking, so a backlog still forms full
                        # buckets instead of degenerate size-1 flushes
                        item = self._q.get_nowait()
                except queue.Empty:
                    reason = "deadline"
                    break
                if item is _SENTINEL:
                    got_sentinel = True
                    reason = "drain"
                    break
                # largest-bucket bounds, same slot conventions as
                # engine.select_bucket (collate reserves one node slot
                # and the padding-graph slot)
                if (g_nodes + item.sample.num_nodes > top.num_nodes - 1
                        or g_edges + item.sample.num_edges > top.num_edges):
                    pending = item
                    reason = "full"
                    break
                group.append(item)
                g_nodes += item.sample.num_nodes
                g_edges += item.sample.num_edges
            self._flush(group, reason)
            if got_sentinel:
                break
        if pending is not None:
            self._fail(pending)

    def _predict(self, samples: List[GraphSample]):
        """The guarded predict body (runs on the watchdog thread when a
        timeout is configured): chaos injection first, so injected
        latency/failures exercise the real timeout/breaker paths."""
        if self.chaos is not None:
            self.chaos.on_predict()
        return self.engine.predict_samples(samples)

    def _predict_watched(self, samples: List[GraphSample]):
        """Run the predict under the watchdog: a PERSISTENT helper
        thread computes while the worker waits at most
        ``predict_timeout_s`` — one long-lived thread, not a spawn per
        flush (the timeout is the rare exception; the hot path should
        not pay thread create/teardown every batch).  On timeout the
        helper is ABANDONED (Python threads can't be killed): it is
        retired so it exits after its stuck call eventually returns,
        a fresh helper takes over on the next flush, and any late
        result is discarded (futures already failed)."""
        if self.predict_timeout_s <= 0:
            return self._predict(samples)
        # the helper handle is shared with close() (which retires it from
        # another thread): swap it under the lock, run on a local ref
        with self._lock:
            if self._watchdog is None:
                self._watchdog = _WatchdogWorker(self._predict)
            wd = self._watchdog
        box = wd.run(samples)
        if not box["done"].wait(self.predict_timeout_s):
            with self._lock:
                if self._watchdog is wd:
                    self._watchdog = None
            wd.retire()
            raise PredictTimeoutError(
                f"predict exceeded the {self.predict_timeout_s:.3g} s "
                f"watchdog for a {len(samples)}-graph flush")
        if "err" in box:
            raise box["err"]
        return box["res"]

    def _flush(self, group: List[_Request], reason: str) -> None:
        # deadline skip at flush time: entries can expire while the
        # group waited out max_wait_ms — drop them here so the batch
        # only carries requests that can still use the answer
        now = time.perf_counter()
        dead, live = [], []
        for r in group:
            if r.future.done():
                # already answered elsewhere (a fleet router that timed
                # out and failed over CANCELS its abandoned submit) —
                # don't spend a bucket slot computing an answer nobody
                # will read
                continue
            (dead if self._expired(r, now) else live).append(r)
        if dead:
            self._shed_expired(dead)
        group = live
        if not group:
            return
        # circuit breaker fail-fast: while open, queued work is answered
        # immediately with 503s instead of feeding a known-broken predict
        # path (allow() also performs the open -> half-open transition,
        # making this flush the recovery probe)
        if self.breaker is not None and not self.breaker.allow():
            retry = self.breaker.time_to_retry()
            for r in group:
                if not r.future.done():
                    r.future.set_exception(BreakerOpenError(
                        "predict path is circuit-broken — failing fast",
                        retry_after_s=retry))
            with self._lock:
                self._n["breaker_fastfails"] += len(group)
            return
        samples = [r.sample for r in group]
        t0 = time.perf_counter()
        try:
            spec = self.engine.select_bucket(samples)
            results = self._predict_watched(samples)
        except Exception as e:  # noqa: BLE001 — surfaced per request
            with self._lock:
                self._n["errors"] += 1
                self._n["batches"] += 1
                if isinstance(e, PredictTimeoutError):
                    self._n["predict_timeouts"] += 1
            if isinstance(e, PredictTimeoutError):
                self.telemetry.health(
                    "predict_timeout", n=len(group),
                    timeout_s=self.predict_timeout_s)
            else:
                self.telemetry.health("batch_error", n=len(group),
                                      error=repr(e))
            if self.breaker is not None:
                self.breaker.record_failure()
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        if self.breaker is not None:
            self.breaker.record_success()
        predict_ms = (time.perf_counter() - t0) * 1e3
        # drain-rate EWMA from BUSY time only (requests served per
        # predict second): under overload — the only regime where the
        # estimate gates admission — the worker is predict-bound, so
        # this matches true throughput; under trickle traffic it
        # overestimates, which is SAFE (an idle-gap-based rate would
        # collapse toward zero after a quiet minute and admission would
        # then shed every default-deadline request forever, with no
        # flush ever running to recover the estimate)
        predict_s = max(predict_ms / 1e3, 1e-6)
        self._predict_ewma_s = predict_s if self._predict_ewma_s is None \
            else 0.7 * self._predict_ewma_s + 0.3 * predict_s
        inst = len(group) / predict_s
        self._rate_ewma = inst if self._rate_ewma is None \
            else 0.7 * self._rate_ewma + 0.3 * inst
        for r, res in zip(group, results):
            if not r.future.done():
                r.future.set_result(res)
        fill_pct = 100.0 * len(group) / max(spec.num_graphs - 1, 1)
        real_nodes = sum(s.num_nodes for s in samples)
        real_edges = sum(s.num_edges for s in samples)
        pad_nodes_pct = 100.0 * (1.0 - real_nodes / max(spec.num_nodes, 1))
        pad_edges_pct = 100.0 * (1.0 - real_edges / max(spec.num_edges, 1))
        wait_ms = (t0 - group[0].t_enq) * 1e3
        # ladder-independent demand of this flush (the autotuner's unit
        # of accounting) — computable only when the per-graph worst case
        # is configured (direct-built engines may not carry it)
        serving = getattr(self.engine, "serving", None)
        mn = int(getattr(serving, "max_nodes_per_graph", 0) or 0)
        me = int(getattr(serving, "max_edges_per_graph", 0) or 0)
        demand = 0
        if mn > 0 and me > 0:
            from hydragnn_tpu.serve.autotune import required_capacity

            demand = required_capacity(len(group), real_nodes, real_edges,
                                       mn, me)
        bucket_key = f"{spec.num_graphs - 1}g/{spec.num_nodes}n/" \
                     f"{spec.num_edges}e"
        with self._lock:
            self._n["batches"] += 1
            self._n[f"{reason}_flushes"] += 1
            self._fill_sum += fill_pct
            self._pad_nodes_sum += pad_nodes_pct
            self._pad_edges_sum += pad_edges_pct
            self._predict_ms_sum += predict_ms
            self._predict_ms_max = max(self._predict_ms_max, predict_ms)
            if demand:
                self._flush_demands[demand] = \
                    self._flush_demands.get(demand, 0) + 1
            b = self._bucket_stats.setdefault(bucket_key, {
                "flushes": 0, "graphs": 0, "fill_pct_sum": 0.0,
                "pad_nodes_pct_sum": 0.0, "pad_edges_pct_sum": 0.0,
                "request_nodes_hist": {}, "request_edges_hist": {}})
            b["flushes"] += 1
            b["graphs"] += len(group)
            b["fill_pct_sum"] += fill_pct
            b["pad_nodes_pct_sum"] += pad_nodes_pct
            b["pad_edges_pct_sum"] += pad_edges_pct
            # per-bucket request-size distribution: which sizes landed
            # in this bucket (attributed at flush — bucket membership
            # is a flush-time decision)
            for s in samples:
                hn, he = b["request_nodes_hist"], b["request_edges_hist"]
                hn[s.num_nodes] = hn.get(s.num_nodes, 0) + 1
                he[s.num_edges] = he.get(s.num_edges, 0) + 1
        self.telemetry.health(
            "batch_flushed", n=len(group), reason=reason,
            fill_pct=round(fill_pct, 2),
            pad_nodes_pct=round(pad_nodes_pct, 2),
            wait_ms=round(wait_ms, 3), predict_ms=round(predict_ms, 3))
        # the unified step-record twin of batch_flushed: same padding
        # schema as trainer steps, the format teleview's per-bucket
        # table and the bucket autotuner consume (docs/TELEMETRY.md)
        self.telemetry.serve_step(
            bucket={"graphs": spec.num_graphs - 1,
                    "nodes": spec.num_nodes, "edges": spec.num_edges},
            num_graphs=len(group), nodes_real=real_nodes,
            edges_real=real_edges, predict_ms=predict_ms,
            wait_ms=wait_ms, reason=reason, fill_pct=fill_pct,
            demand=demand, max_nodes_per_graph=mn,
            max_edges_per_graph=me,
            ladder=[p.num_graphs - 1 for p in self.engine.pad_specs])
        if reason == "deadline":
            self.telemetry.health("deadline_flush", n=len(group),
                                  wait_ms=round(wait_ms, 3))
        tr = getattr(self.telemetry, "spans", None)
        if tr is not None:
            # flight recorder (docs/TELEMETRY.md "Tracing"): one flush
            # span linking the N request traces it served, with
            # bucket-pad / compiled-predict children reconstructed from
            # the engine's phase clock and one queue-wait child per
            # traced request (parented to the flush, on the REQUEST's
            # trace so the client id resolves the whole story).  Gated on
            # the recorder existing — the default-off flush path above is
            # untouched.
            t1 = time.perf_counter()
            flush_sp = tr.record_interval(
                "serve.flush", t0, t1,
                links=[r.trace.trace_id for r in group
                       if r.trace is not None],
                n=len(group), reason=reason, bucket=bucket_key,
                fill_pct=round(fill_pct, 2))
            phases = getattr(self.engine, "last_phase_t", None)
            if phases is not None:
                pad0, pad1, exe0, exe1 = phases
                tr.record_interval("serve.pad", pad0, pad1,
                                   trace_id=flush_sp["trace_id"],
                                   parent_id=flush_sp["span_id"],
                                   bucket=bucket_key)
                tr.record_interval("serve.predict", exe0, exe1,
                                   trace_id=flush_sp["trace_id"],
                                   parent_id=flush_sp["span_id"],
                                   bucket=bucket_key, n=len(group))
            for r in group:
                if r.trace is not None:
                    tr.record_interval("serve.queue_wait", r.t_enq, t0,
                                       trace_id=r.trace.trace_id,
                                       parent_id=flush_sp["span_id"])

    def _fail(self, item) -> None:
        if isinstance(item, _Request) and not item.future.done():
            item.future.set_exception(
                BatcherClosedError("batcher closed before the request was "
                                   "served"))

    def _sweep_leftovers(self) -> None:
        """Fail any request still queued after the worker exited (a
        submit racing close() can land one behind the drain sentinel)."""
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not _SENTINEL:
                self._fail(item)

    # -- shutdown ------------------------------------------------------------

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop accepting requests and shut the worker down.

        ``drain=True``: a sentinel closes the queue FIFO — everything
        enqueued before the close is flushed (immediately, not waiting
        out deadlines) and answered; bounded by ``timeout``.  On timeout
        (or ``drain=False``) the shared drain helper unblocks any stuck
        producer and fails leftover futures so no client waits forever.
        """
        if self._closed.is_set() and self._thread is None:
            return
        self._closed.set()
        t = self._thread
        if t is None:
            # never started: fail whatever was queued
            drain_bounded_queue(self._q, _SENTINEL, self._stop,
                                on_item=self._fail)
            self._q.put(_SENTINEL)
            return
        if drain:
            try:
                self._q.put(_SENTINEL, timeout=1.0)
            except queue.Full:
                pass  # worker is behind; the force path below cleans up
            t.join(timeout=timeout)
        if not drain or t.is_alive():
            # force path: stop flag + sentinel wake a blocked worker; it
            # drain-flushes its current group and exits at the next check
            self._stop.set()
            try:
                self._q.put_nowait(_SENTINEL)
            except queue.Full:
                pass  # a full queue means the worker has items to wake on
            t.join(timeout=max(1.0, self.max_wait_s + 1.0))
            if t.is_alive():
                # worker is stuck inside a long predict: hand the queue
                # to the background drain helper (leak-safe shutdown —
                # same idiom as the prefetch loaders).  TWO sentinels:
                # the stuck worker, if it ever revives, may consume one
                # — the second still terminates the drain daemon (any
                # leftover sentinel is swallowed by the final sweep).
                drain_bounded_queue(self._q, _SENTINEL, self._stop,
                                    on_item=self._fail)
                self._q.put(_SENTINEL)
                self._q.put(_SENTINEL)
        with self._lock:
            self._thread = None
            wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.retire()
        # catch stragglers a racing submit slipped behind the sentinel
        # (also consumes stray sentinels left in the queue)
        self._sweep_leftovers()

    # -- stats ---------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            nb = self._n["batches"]
            ok = max(nb - self._n["errors"], 0)
            per_bucket = {
                key: {
                    "flushes": int(b["flushes"]),
                    "graphs": int(b["graphs"]),
                    "avg_fill_pct": round(
                        b["fill_pct_sum"] / b["flushes"], 2),
                    "avg_pad_nodes_pct": round(
                        b["pad_nodes_pct_sum"] / b["flushes"], 2),
                    "avg_pad_edges_pct": round(
                        b["pad_edges_pct_sum"] / b["flushes"], 2),
                    "request_nodes_hist": dict(b["request_nodes_hist"]),
                    "request_edges_hist": dict(b["request_edges_hist"]),
                }
                for key, b in self._bucket_stats.items()
            }
            return {
                **self._n,
                "queue_depth": self._q.qsize(),
                "max_wait_ms": self.max_wait_s * 1e3,
                "drain_rate_rps": round(self._rate_ewma, 2)
                                  if self._rate_ewma else 0.0,
                # the service-time half of the admission estimate, for
                # consumers of the drain signal (autoscaler, budgets)
                "predict_ewma_ms": round(self._predict_ewma_s * 1e3, 2)
                                   if self._predict_ewma_s else 0.0,
                "avg_fill_pct": (self._fill_sum / ok) if ok else 0.0,
                "avg_pad_nodes_pct": (self._pad_nodes_sum / ok) if ok
                                     else 0.0,
                "avg_pad_edges_pct": (self._pad_edges_sum / ok) if ok
                                     else 0.0,
                "avg_predict_ms": (self._predict_ms_sum / ok) if ok else 0.0,
                "max_predict_ms": self._predict_ms_max,
                # autotuner feed (tools/buckettune.py --url): accepted
                # request-size distribution + per-flush demand histogram
                # + per-bucket fill/padding aggregates
                "request_nodes_hist": dict(self._req_nodes_hist),
                "request_edges_hist": dict(self._req_edges_hist),
                "flush_demands": dict(self._flush_demands),
                "per_bucket": per_bucket,
            }
