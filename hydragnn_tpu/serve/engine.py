"""Online inference engine: checkpoint -> bucketed AOT-compiled eval.

Three properties distinguish this from the offline ``run_prediction``
evaluator:

- **Inference-only state.**  :func:`load_inference_state` reads the
  checkpoint pickle straight into params + batch_stats — no optimizer
  init, no training-dataset rebuild (the reference pattern rebuilt the
  ENTIRE train state just to run a forward pass;
  run_prediction now calls this same function).

- **Bucketed AOT executable cache.**  The engine precompiles the eval
  step for a ladder of PadSpec buckets at startup (``warmup``) and keeps
  the compiled executables keyed by bucket shape, with hit/miss
  counters.  Steady-state traffic therefore NEVER recompiles: every
  request batch is padded to one of the known buckets and dispatched
  straight to a cached executable — the same static-shape discipline
  that makes the train step compile once per bucket.

- **Bit-identical outputs.**  The compiled program is exactly the
  ``make_eval_step`` program ``run_prediction`` jits, fed batches built
  by the same ``collate`` — so for the same checkpoint, the same graphs
  and the same PadSpec, predictions match run_prediction bit for bit
  (tier-1 parity test in tests/test_serve.py).

Buffer donation: on accelerator backends the request batch's device
buffers are donated to the executable (they are fresh per request and
dead after the call); CPU has no donation support, so the flag is
dropped there to keep smoke runs warning-free.

**Quantized states.**  ``Serving.quant_policy`` (f32 / bf16 / int8
weight-only, hydragnn_tpu/quant) is applied at :meth:`warmup` behind a
golden-batch gate: the f32 reference outputs are captured first, the
quantized state replays the same batch, and the policy only activates
when its max output drift stays under ``Serving.quant_tolerance`` —
otherwise the engine keeps the f32 weights (fallback; /healthz and
/metrics report the active policy either way).  The policy rides the
executable-cache key, so every bucket compiles once per policy and
steady state stays recompile-free; reload candidates are re-quantized
with the active policy before validation so their avals always match.

**Hot reload.**  :meth:`InferenceEngine.reload_state` swaps a new
checkpoint in WITHOUT a restart and without re-paying AOT warmup: the
cached executables are specialized on the state's avals (shapes/dtypes),
not its values, so any structurally-identical checkpoint runs through
them unchanged.  A candidate is VALIDATED first — pytree structure +
leaf shape/dtype parity with the live state, then a replay of the golden
batch captured at startup whose outputs must be finite (and whose drift
vs the recorded outputs is reported) — and only then atomically swapped;
the previous state is retained for instant :meth:`rollback` when
validation fails or the circuit breaker trips right after the swap
(serve/server.py wires that).  In-flight flushes hold a snapshot of the
old state, so a reload drops zero requests.
"""

from __future__ import annotations

import collections
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from flax import struct

from hydragnn_tpu.config.config import (
    get_log_name_config,
    head_specs_from_config,
)
from hydragnn_tpu.graph.batch import (
    GraphBatch,
    GraphSample,
    HeadSpec,
    PadSpec,
    collate,
)
from hydragnn_tpu.models.base import ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.quant import (
    apply_policy,
    check_policy,
    tree_nbytes,
    wrap_eval_step,
)
from hydragnn_tpu.serve.config import ServingConfig
from hydragnn_tpu.train.trainer import make_eval_step


class BucketOverflowError(ValueError):
    """The request (or batch) exceeds the largest configured bucket."""


class ReloadValidationError(RuntimeError):
    """A hot-reload candidate failed validation (structure mismatch or
    non-finite golden-batch outputs); the live state was NOT swapped."""


def load_inference_state(config, logs_dir: str = "./logs/",
                         policy: str = "f32"):
    """Load a run's checkpoint into an inference-only state.

    Reads the single-file checkpoint ``run_training`` saves
    (``logs/<log_name>/<log_name>.pk``) and keeps only what a forward
    pass needs — params + batch_stats (+ the step counter for
    provenance).  No optimizer state is constructed and no dataset is
    loaded, unlike the old eval path that built a full train state
    (optimizer init included) just to overwrite it.

    ``config`` is a config dict (raw or finalized — the log name uses
    only raw fields) or a path to one.  Returns an :class:`InferenceState`
    whose ``params``/``batch_stats`` attributes satisfy every eval-side
    consumer of a TrainState (``make_eval_step``, ``test``).

    ``policy`` applies a low-precision dtype policy (hydragnn_tpu/quant:
    ``f32``/``bf16``/``int8``) to the loaded state.  NOTE the serving
    stack deliberately loads ``f32`` here and lets the ENGINE apply
    ``Serving.quant_policy`` during warmup — the golden-batch gate needs
    the f32 reference to measure drift against, and a rejected policy
    must fall back to the f32 weights.  Pass a policy here only for
    standalone consumers (tools, notebooks) that accept it ungated.
    """
    import jax.numpy as jnp

    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    log_name = get_log_name_config(config)
    fname = os.path.join(logs_dir, log_name, f"{log_name}.pk")
    with open(fname, "rb") as f:
        payload = pickle.load(f)
    state = InferenceState(
        step=jnp.asarray(payload["step"]),
        params=payload["params"],
        batch_stats=payload["batch_stats"],
    )
    return apply_policy(state, check_policy(policy))


# flax.struct so the state is a pytree (jit-traceable like TrainState)
@struct.dataclass
class InferenceState:
    """Eval-only slice of a TrainState: no optimizer state."""

    step: Any
    params: Any
    batch_stats: Any


class InferenceEngine:
    """Checkpointed model + bucketed compile cache + output unpacking.

    Thread-safe for concurrent ``predict_samples`` calls (the compile
    cache and counters are lock-guarded; JAX execution itself is
    thread-safe), though the intended topology is ONE MicroBatcher
    worker feeding it (serve/batcher.py).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        state: InferenceState,
        head_specs: Sequence[HeadSpec],
        pad_specs: Sequence[PadSpec],
        serving: Optional[ServingConfig] = None,
        telemetry=None,
        y_minmax: Optional[Sequence[Sequence[float]]] = None,
        post_collate=None,
        pbc: bool = False,
    ):
        import jax

        self.cfg = cfg
        self.model = create_model(cfg)
        # stage the weights on device ONCE: the pickled state is host
        # numpy, and passing it per call would re-upload the full param
        # tree H2D on every request batch (state is argument 0 — never
        # donated — so the staged buffers live for the engine lifetime).
        # _canon_state normalizes the step leaf so hot-reload candidates
        # always match the compiled executables' avals.
        self.state = self._canon_state(state)
        self.head_specs = list(head_specs)
        if not pad_specs:
            raise ValueError("InferenceEngine needs at least one PadSpec "
                             "bucket")
        self.pad_specs = sorted(pad_specs, key=lambda p: (p.num_nodes,
                                                          p.num_edges,
                                                          p.num_graphs))
        self.serving = serving or ServingConfig()
        if telemetry is None:
            from hydragnn_tpu.telemetry import MetricsLogger

            telemetry = MetricsLogger.disabled()
        self.telemetry = telemetry
        self.y_minmax = y_minmax
        self.post_collate = post_collate
        # periodic models need cell-aware neighbor lists the HTTP layer
        # cannot rebuild — the server rejects edge_index-less requests
        self.pbc = bool(pbc)
        # donate the per-request batch buffers (fresh every call, dead
        # after it); CPU has no donation — drop the flag so smoke tests
        # don't spray "donated buffers were not usable" warnings
        self._donate = () if jax.default_backend() == "cpu" else (1,)
        # one jitted eval per dtype policy, built lazily: the f32 entry
        # is EXACTLY the pre-quantization program (the run_prediction
        # bit-parity contract), non-f32 entries wrap it with the
        # quant-policy casts (hydragnn_tpu/quant.wrap_eval_step)
        self._evals: Dict[str, Any] = {}
        # dtype policy state: requested comes from Serving.quant_policy,
        # active flips only after the golden-batch gate in warmup()
        self._policy_requested = check_policy(self.serving.quant_policy)
        self._policy = "f32"
        self._quant: Dict[str, Any] = {
            "requested": self._policy_requested,
            "active": "f32",
            "tolerance": float(self.serving.quant_tolerance),
            "golden_max_delta": None,
            "fallback": False,
        }
        self._golden_f32: Optional[List[np.ndarray]] = None
        # LRU order (oldest first) so Serving.max_resident_executables
        # can bound residency for structurally-distinct tenants; with
        # the 0 (unbounded) default this is a plain dict in practice
        self._compiled: "collections.OrderedDict[tuple, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._warmup_compiles = 0
        self._evictions = 0
        # hot-reload machinery: previous state kept for instant rollback,
        # golden-batch reference outputs recorded at warmup
        self._reload_lock = threading.Lock()
        self._prev_state = None
        self._prev_golden: Optional[List[np.ndarray]] = None
        self._golden: Optional[List[np.ndarray]] = None
        self._reload_t: Optional[float] = None
        self._reloads = 0
        self._reload_failures = 0
        self._rollbacks = 0

    @staticmethod
    def _canon_state(state: "InferenceState"):
        """Device-staged state with a CANONICAL step leaf (strong int32):
        pickled checkpoints carry int / np.int64 / weak-typed steps, and
        an aval mismatch on any leaf would make the AOT-compiled
        executables reject an otherwise-valid hot-reload candidate."""
        import jax
        import jax.numpy as jnp

        return jax.device_put(InferenceState(
            step=jnp.int32(int(np.asarray(state.step))),
            params=state.params,
            batch_stats=state.batch_stats,
        ))

    # -- construction --------------------------------------------------------

    @classmethod
    def from_config(cls, config, logs_dir: str = "./logs/",
                    serving: Optional[ServingConfig] = None,
                    telemetry=None, state: Optional[InferenceState] = None,
                    post_collate=None) -> "InferenceEngine":
        """Build from a FINALIZED config (e.g. the config.json that
        run_training saved next to the checkpoint) + the checkpoint it
        points at."""
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        arch = config["NeuralNetwork"]["Architecture"]
        if "output_dim" not in arch or "input_dim" not in arch:
            raise ValueError(
                "InferenceEngine.from_config needs a FINALIZED config "
                "(output_dim/input_dim present) — use the config.json "
                "run_training saved in the log directory")
        cfg = ModelConfig.from_config(config["NeuralNetwork"])
        if cfg.model_type == "DimeNet" and post_collate is None:
            # DimeNet consumes a static padded triplet table attached at
            # collate time (data/load_data.py sizes it from the training
            # dataset); that sizing is not yet persisted into the saved
            # config, so config-only serving would crash in warmup with
            # a missing dn_idx_i extra — fail loud and early instead
            raise ValueError(
                "DimeNet serving needs the triplet-table post_collate "
                "hook: construct InferenceEngine directly with "
                "post_collate=add_dimenet_extras(...) (config-only "
                "DimeNet serving is open follow-on work)")
        if state is None:
            state = load_inference_state(config, logs_dir)
        serving = serving or ServingConfig.from_section(config.get("Serving"))
        if serving.max_nodes_per_graph < 1 or serving.max_edges_per_graph < 1:
            raise ValueError(
                "serving bucket sizing needs the per-graph worst case: set "
                "Serving.max_nodes_per_graph / max_edges_per_graph (or "
                "HYDRAGNN_SERVE_MAX_NODES / HYDRAGNN_SERVE_MAX_EDGES)")
        pad_specs = [
            PadSpec.for_batch(b, serving.max_nodes_per_graph,
                              serving.max_edges_per_graph)
            for b in serving.buckets
        ]
        var = config["NeuralNetwork"]["Variables_of_interest"]
        y_minmax = var.get("y_minmax") if var.get("denormalize_output") \
            else None
        return cls(cfg, state, head_specs_from_config(config), pad_specs,
                   serving=serving, telemetry=telemetry, y_minmax=y_minmax,
                   post_collate=post_collate,
                   pbc=bool(arch.get("periodic_boundary_conditions")))

    def fork(self) -> "InferenceEngine":
        """A new engine over the SAME model/buckets/weights that SHARES
        this engine's compiled-executable cache (and its lock) but owns
        its own serving state — reload/rollback machinery, quant gate,
        hit/miss counters.

        This is the in-process replica-fleet topology (serve/fleet.py):
        the executables are pure functions of the (state, batch) avals,
        so N structurally-identical replicas must not pay N AOT warmups
        or hold N copies of the compiled programs — a fork's
        :meth:`warmup` cache-hits every bucket and only replays the
        golden batch.  The forked state references the same device
        buffers until a hot reload swaps one replica's copy out (params
        are read-only on the predict path, so sharing is safe).
        """
        eng = InferenceEngine(
            self.cfg, self.state, self.head_specs, self.pad_specs,
            serving=self.serving, telemetry=self.telemetry,
            y_minmax=self.y_minmax, post_collate=self.post_collate,
            pbc=self.pbc)
        # share the compiled programs AND the lock that guards them —
        # two locks over one dict would not be mutual exclusion
        eng._compiled = self._compiled
        eng._evals = self._evals
        eng._lock = self._lock
        # the quant gate already ran on the parent: adopt its verdict
        # (a fork re-running _activate_policy would re-quantize and
        # re-replay for an identical answer)
        eng._policy = self._policy
        eng._quant = dict(self._quant)
        eng._golden_f32 = self._golden_f32
        eng._golden = self._golden
        return eng

    # -- bucket selection ----------------------------------------------------

    def _needs(self, samples: Sequence[GraphSample]):
        return (len(samples),
                sum(s.num_nodes for s in samples),
                sum(s.num_edges for s in samples))

    def select_bucket(self, samples: Sequence[GraphSample]) -> PadSpec:
        """Smallest bucket that fits (min padding waste; same rule as the
        training loader's ``_pick_spec`` plus the graph-count bound)."""
        ng, nn, ne = self._needs(samples)
        for spec in self.pad_specs:
            if (spec.num_graphs - 1 >= ng and spec.num_nodes - 1 >= nn
                    and spec.num_edges >= ne):
                return spec
        raise BucketOverflowError(
            f"batch of {ng} graphs / {nn} nodes / {ne} edges exceeds the "
            f"largest bucket (graphs {self.pad_specs[-1].num_graphs - 1}, "
            f"nodes {self.pad_specs[-1].num_nodes - 1}, "
            f"edges {self.pad_specs[-1].num_edges})")

    def fits(self, samples: Sequence[GraphSample]) -> bool:
        """Does this group fit SOME bucket (the batcher's accumulate-more
        check)?"""
        ng, nn, ne = self._needs(samples)
        top = self.pad_specs[-1]
        return (top.num_graphs - 1 >= ng and top.num_nodes - 1 >= nn
                and top.num_edges >= ne)

    @property
    def max_batch_graphs(self) -> int:
        return self.pad_specs[-1].num_graphs - 1

    # -- compile cache -------------------------------------------------------

    def _zero_sample(self) -> GraphSample:
        """One-node self-loop dummy whose collated batch has the same
        pytree structure as request batches (feature dims, edge_attr
        presence) — what warmup lowers against."""
        ea = (np.zeros((1, self.cfg.edge_dim), np.float32)
              if self.cfg.use_edge_attr else None)
        return GraphSample(
            x=np.zeros((1, self.cfg.input_dim), np.float32),
            pos=np.zeros((1, 3), np.float32),
            edge_index=np.zeros((2, 1), np.int32),
            edge_attr=ea,
        )

    def _collate(self, samples: Sequence[GraphSample],
                 spec: PadSpec) -> GraphBatch:
        batch = collate(samples, spec, self.head_specs)
        if self.post_collate is not None:
            batch = self.post_collate(batch)
        if "edge_perm_sender" in batch.extras:
            # volatile extra: the fused-backend marker attaches per batch
            # (sorted-receiver check) — request-dependent keys would break
            # the compiled executable's fixed input structure, so serving
            # always takes the XLA aggregation path
            extras = dict(batch.extras)
            extras.pop("edge_perm_sender")
            batch = batch.replace(extras=extras)
        return batch

    def _eval_fn(self, policy: Optional[str] = None):
        """Jitted eval step for a dtype policy (default: the active
        one).  The f32 program is byte-identical to the pre-quant
        engine's — bit-parity with run_prediction is a per-policy
        property of f32, not of the engine."""
        import jax

        policy = self._policy if policy is None else policy
        fn = self._evals.get(policy)
        if fn is None:
            base = make_eval_step(self.model, self.cfg)
            if policy != "f32":
                base = wrap_eval_step(base, policy)
            fn = jax.jit(base, donate_argnums=self._donate)
            self._evals[policy] = fn
        return fn

    def _executable(self, spec: PadSpec, batch: Optional[GraphBatch] = None,
                    warmup: bool = False, policy: Optional[str] = None,
                    state=None):
        """Compiled eval executable for one (policy, bucket); compiles
        AOT on first sighting (counted as warmup or cache_miss), cache
        hit thereafter.  The policy rides the cache key so a quant
        fallback (or the warmup-time f32 reference probe) never
        collides with the active policy's executables — and steady
        state stays at zero recompiles under every policy."""
        policy = self._policy if policy is None else policy
        key = (policy, spec.num_nodes, spec.num_edges, spec.num_graphs)
        with self._lock:
            exe = self._compiled.get(key)
            if exe is not None:
                self._compiled.move_to_end(key)  # LRU freshness
                if not warmup:
                    self._hits += 1
                return exe
            if warmup:
                self._warmup_compiles += 1
            else:
                self._misses += 1
        if not warmup:
            self.telemetry.health(
                "cache_miss", nodes=spec.num_nodes, edges=spec.num_edges,
                graphs=spec.num_graphs, policy=policy)
        # compile OUTSIDE the lock: a bucket compile takes seconds, and
        # cache_stats() (-> /healthz, /metrics) takes the same lock — a
        # liveness probe must not block behind XLA.  Concurrent callers
        # may race-compile the same bucket; first insert wins.
        if batch is None:
            batch = self._collate([self._zero_sample()], spec)
        # snapshot: a concurrent hot reload must not swap the state
        # between aval capture and compile
        if state is None:
            state = self.state
        exe = self._eval_fn(policy).lower(state, batch).compile()
        cap = int(self.serving.max_resident_executables)
        evicted: List[tuple] = []
        with self._lock:
            exe = self._compiled.setdefault(key, exe)
            self._compiled.move_to_end(key)
            # bounded residency for structurally-distinct tenants: drop
            # the least-recently-used executables beyond the cap (a cap
            # below one bucket ladder thrashes — docs/SERVING.md)
            while cap > 0 and len(self._compiled) > cap:
                old, _ = self._compiled.popitem(last=False)
                self._evictions += 1
                evicted.append(old)
        for old in evicted:
            self.telemetry.health(
                "executable_evict", policy=old[0], nodes=old[1],
                edges=old[2], graphs=old[3], cap=cap)
        return exe

    def warmup(self) -> int:
        """AOT-compile every configured bucket (server startup), then
        capture the golden batch + reference outputs that hot-reload
        validation replays; returns the number of executables compiled
        for the active policy.

        When ``Serving.quant_policy`` asks for a low-precision policy,
        warmup is also the GATE: the f32 reference golden outputs are
        captured first, the quantized state is staged and replayed, and
        the policy only becomes active when its ``golden_max_delta``
        against the f32 reference stays under
        ``Serving.quant_tolerance`` — otherwise the engine keeps the
        f32 weights (fallback, ``quant_reject`` health event)."""
        # f32 reference replay (smallest bucket): the baseline every
        # quant policy is gated against.  The reference capture and the
        # gate itself must see ONE state snapshot, so _activate_policy
        # captures the reference inside its own locked region; the
        # f32-policy path takes the same lock for the same reason
        if self._policy_requested != "f32":
            self._activate_policy(self._policy_requested)
        else:
            with self._reload_lock:
                self._golden_f32 = self._golden_outputs(self.state,
                                                        policy="f32")
        for spec in self.pad_specs:
            self._executable(spec, warmup=True)
        # under the reload lock END TO END: the golden reference must be
        # computed from the SAME state it is stored against — a
        # watch-triggered reload racing a late warmup could otherwise
        # swap state between the replay and the store, leaving a stale
        # golden that 409-rejects the next good candidate
        with self._reload_lock:
            self._golden = self._golden_outputs(self.state)
        with self._lock:
            return sum(1 for k in self._compiled if k[0] == self._policy)

    def _activate_policy(self, policy: str) -> bool:
        """Stage the quantized state, replay the golden batch, and swap
        the policy in only when drift vs the f32 reference is under
        tolerance.  On rejection the f32 state keeps serving (the
        fallback the HTTP layer reports via /healthz)."""
        tol = float(self.serving.quant_tolerance)
        # the WHOLE stage-replay-swap sequence rides the reload lock:
        # staging reads self.state, and a concurrent hot reload swapping
        # state mid-gate would let the final swap clobber the reloaded
        # weights with a quantized copy of the pre-reload ones
        with self._reload_lock:
            # reference and candidate derive from the SAME state under
            # one lock hold — a hot reload cannot land between them
            self._golden_f32 = self._golden_outputs(self.state,
                                                    policy="f32")
            staged = self._canon_state(apply_policy(self.state, policy))
            try:
                outs = self._golden_outputs(staged, policy=policy)
                finite = all(np.isfinite(o).all() for o in outs)
            except Exception as e:  # noqa: BLE001 — any failure rejects
                self._quant["fallback"] = True
                self.telemetry.health("quant_reject", policy=policy,
                                      error=repr(e)[:200])
                return False
            delta = max(
                (float(np.max(np.abs(o.astype(np.float64)
                                     - g.astype(np.float64))))
                 if o.size else 0.0)
                for o, g in zip(outs, self._golden_f32))
            self._quant["golden_max_delta"] = delta
            if not finite or delta > tol:
                self._quant["fallback"] = True
                self.telemetry.health(
                    "quant_reject", policy=policy,
                    golden_max_delta=round(delta, 9), tolerance=tol,
                    finite=finite)
                return False
            # accepted: the quantized state replaces the f32 one
            # (freeing the full-precision replica — the HBM saving IS
            # the point)
            self.state = staged
            self._policy = policy
        self._quant["active"] = policy
        self.telemetry.health(
            "quant_policy", policy=policy,
            golden_max_delta=round(delta, 9), tolerance=tol,
            param_bytes=tree_nbytes((staged.params, staged.batch_stats)))
        return True

    # -- hot reload ----------------------------------------------------------

    def _golden_outputs(self, state,
                        policy: Optional[str] = None) -> List[np.ndarray]:
        """Replay the golden batch (a freshly-collated dummy in the
        smallest bucket — re-collated per call because accelerator
        backends DONATE the batch buffers) through the already-compiled
        executable with ``state``.  ``policy`` selects which policy's
        executable runs it (default: active) — the quant gate replays
        both the f32 reference and the quantized candidate."""
        spec = self.pad_specs[0]
        batch = self._collate([self._zero_sample()], spec)
        exe = self._executable(spec, batch=batch, warmup=True,
                               policy=policy, state=state)
        m = exe(state, batch)
        return [np.asarray(o, dtype=np.float32) for o in m["outputs"]]

    def validate_state(self, state: "InferenceState") -> Dict[str, Any]:
        """Validate a DEVICE-STAGED hot-reload candidate against the
        live state: pytree structure + leaf shape/dtype parity, then a
        golden-batch replay whose outputs must be all-finite.  Returns
        the validation report (golden outputs + drift vs the recorded
        reference); raises :class:`ReloadValidationError` otherwise."""
        import jax

        cur = jax.tree_util.tree_leaves_with_path(
            (self.state.params, self.state.batch_stats))
        new = jax.tree_util.tree_leaves_with_path(
            (state.params, state.batch_stats))
        def _sig(leaf):
            # dtype without np.asarray: that would D2H-copy every leaf
            dt = getattr(leaf, "dtype", None)
            return np.shape(leaf), dt if dt is not None \
                else np.asarray(leaf).dtype
        if len(cur) != len(new) or any(
                pc != pn or _sig(lc) != _sig(ln)
                for (pc, lc), (pn, ln) in zip(cur, new)):
            raise ReloadValidationError(
                "candidate checkpoint's param/batch_stats tree does not "
                "match the served model (structure, shape or dtype) — "
                "reload needs a checkpoint from the same architecture")
        try:
            outs = self._golden_outputs(state)
        except Exception as e:  # noqa: BLE001 — any replay failure rejects
            raise ReloadValidationError(
                f"golden-batch replay failed: {e!r}") from e
        if not all(np.isfinite(o).all() for o in outs):
            raise ReloadValidationError(
                "candidate checkpoint produced non-finite golden-batch "
                "outputs (corrupt or incompatible weights)")
        delta = 0.0
        if self._golden is not None:
            delta = max(
                (float(np.max(np.abs(o - g))) if o.size else 0.0)
                for o, g in zip(outs, self._golden))
        return {"golden_max_delta": delta, "outputs": outs}

    def reload_state(self, state: "InferenceState",
                     source: str = "api") -> Dict[str, Any]:
        """Validate ``state`` and atomically swap it in; the previous
        state is retained for :meth:`rollback`.  In-flight predictions
        hold a snapshot of the old state, so no request is dropped.
        Raises :class:`ReloadValidationError` (live state untouched) on
        a bad candidate."""
        with self._reload_lock:
            # a live quant policy re-applies to every candidate: the
            # checkpoint arrives f32, the served tree is bf16/int8 —
            # quantizing FIRST keeps structure/aval parity with the
            # compiled executables (zero reload recompiles, quantized
            # or not)
            staged = self._canon_state(apply_policy(state, self._policy))
            try:
                report = self.validate_state(staged)
            except ReloadValidationError as e:
                self._reload_failures += 1
                self.telemetry.health(
                    "reload_rollback", reason="validation", source=source,
                    error=str(e)[:200])
                raise
            outs = report.pop("outputs")
            self._prev_state, self.state = self.state, staged
            self._prev_golden, self._golden = self._golden, outs
            self._reload_t = time.monotonic()
            self._reloads += 1
            self.telemetry.health(
                "reload_ok", source=source,
                step=int(np.asarray(staged.step)),
                golden_max_delta=round(report["golden_max_delta"], 9))
            return {"step": int(np.asarray(staged.step)), **report}

    def reload_from_checkpoint(self, path: str, chaos=None,
                               source: str = "api") -> Dict[str, Any]:
        """Load a checkpoint pickle (the ``run_training`` format:
        ``{step, params, batch_stats}``) and hot-swap it via
        :meth:`reload_state`.  ``chaos`` (a ServeChaos or None) lets the
        fault harness corrupt the candidate to exercise rollback."""
        with open(path, "rb") as f:
            payload = pickle.load(f)
        state = InferenceState(
            step=payload.get("step", 0),
            params=payload["params"],
            batch_stats=payload.get("batch_stats", {}),
        )
        if chaos is not None:
            state = chaos.on_reload_state(state)
        return self.reload_state(state, source=source)

    def rollback(self, reason: str = "breaker_trip") -> bool:
        """Instantly restore the pre-reload state (False when there is
        nothing to roll back to)."""
        with self._reload_lock:
            if self._prev_state is None:
                return False
            self.state, self._prev_state = self._prev_state, None
            self._golden, self._prev_golden = self._prev_golden, None
            self._reload_t = None
            self._rollbacks += 1
            self.telemetry.health("reload_rollback", reason=reason)
            return True

    def in_probation(self, probation_s: float) -> bool:
        """Is the engine inside the post-reload probation window (a
        breaker trip now should auto-rollback)?"""
        return (self._reload_t is not None
                and self._prev_state is not None
                and time.monotonic() - self._reload_t
                < max(0.0, float(probation_s)))

    def reload_stats(self) -> Dict[str, Any]:
        return {
            "reloads": self._reloads,
            "reload_failures": self._reload_failures,
            "rollbacks": self._rollbacks,
            "can_rollback": self._prev_state is not None,
        }

    def quant_stats(self) -> Dict[str, Any]:
        """Active dtype-policy report: requested vs active policy,
        golden drift vs the f32 reference, and the resident parameter
        bytes of the SERVED state (the HBM-per-replica number)."""
        return {
            **self._quant,
            "param_bytes": tree_nbytes(
                (self.state.params, self.state.batch_stats)),
        }

    def cache_stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "hits": self._hits,
                "misses": self._misses,
                "warmup_compiles": self._warmup_compiles,
                "evictions": self._evictions,
                "hit_rate": (self._hits / total) if total else 1.0,
                "compiled_buckets": len(self._compiled),
                "buckets": [
                    {"graphs": p.num_graphs - 1, "nodes": p.num_nodes,
                     "edges": p.num_edges}
                    for p in self.pad_specs
                ],
                "quant": self.quant_stats(),
            }

    # -- prediction ----------------------------------------------------------

    def predict_arrays(
        self, samples: Sequence[GraphSample]
    ) -> List[np.ndarray]:
        """One padded forward pass; per-head arrays with padding stripped
        and denormalization applied — graph heads ``[n_graphs, dim]``,
        node heads ``[total_real_nodes, dim]``.  Row order matches
        ``run_prediction``'s masked concatenation exactly (the parity
        contract)."""
        tracing = getattr(self.telemetry, "spans", None) is not None
        spec = self.select_bucket(samples)
        if tracing:
            # phase clock for the flight recorder: collate (bucket-pad)
            # vs compiled-predict boundaries, read back by the batcher as
            # serve.pad / serve.predict child spans.  The block inside
            # the exe window moves the device sync that np.asarray below
            # would pay anyway, so the phase covers real compute.
            # Default-off keeps this path free of even perf_counter calls.
            import jax

            t_pad0 = time.perf_counter()
            batch = self._collate(samples, spec)
            t_pad1 = time.perf_counter()
            exe = self._executable(spec, batch=batch)
            state = self.state
            t_exe0 = time.perf_counter()
            m = exe(state, batch)
            jax.block_until_ready(m["outputs"])
            self.last_phase_t = (t_pad0, t_pad1, t_exe0,
                                 time.perf_counter())
        else:
            batch = self._collate(samples, spec)
            exe = self._executable(spec, batch=batch)
            # snapshot: a hot reload swapping self.state mid-call must
            # not hand this flush two different param trees
            state = self.state
            m = exe(state, batch)
        outputs = m["outputs"]
        n_graphs = len(samples)
        n_nodes = sum(s.num_nodes for s in samples)
        arrays: List[np.ndarray] = []
        for ih, h in enumerate(self.head_specs):
            out = np.asarray(outputs[ih])
            n = n_graphs if h.type == "graph" else n_nodes
            # gaussian_nll heads emit [mean, log_sigma] at 2x the head
            # width — the prediction is the mean block (same slice as
            # trainer.test)
            arr = out[:n, : h.dim]
            if self.y_minmax is not None:
                ymin = float(self.y_minmax[ih][0])
                ymax = float(self.y_minmax[ih][1])
                # identical expression to postprocess.output_denormalize
                arr = np.asarray(arr) * (ymax - ymin) + ymin
            arrays.append(arr)
        return arrays

    def predict_samples(
        self, samples: Sequence[GraphSample]
    ) -> List[Dict[str, np.ndarray]]:
        """Per-request results: one ``{head_name: array}`` dict per input
        sample (graph heads ``[dim]``, node heads ``[n_nodes, dim]``) —
        what the micro-batcher hands back to each request future."""
        arrays = self.predict_arrays(samples)
        node_offs = np.cumsum([0] + [s.num_nodes for s in samples])
        results: List[Dict[str, np.ndarray]] = [dict() for _ in samples]
        for ih, h in enumerate(self.head_specs):
            arr = arrays[ih]
            for i in range(len(samples)):
                if h.type == "graph":
                    results[i][h.name] = arr[i]
                else:
                    results[i][h.name] = arr[node_offs[i]:node_offs[i + 1]]
        return results
