"""Closed-loop fleet autoscaler: the policy behind ``autoscale.value``.

PR 8 published the fleet's drain-rate EWMA sum in ``/metrics`` as an
autoscaling signal; this module is the consumer.  ``FleetAutoscaler`` is
a pure state machine evaluated once per supervisor probe tick
(fleet.py:FleetSupervisor.probe_once) on the same numbers the PR-5
admission shed uses — estimated backlog wait = queued work / fleet drain
rate — so the shed and the scaler can never disagree about whether the
fleet is overloaded:

* **up** when the backlog estimate exceeds ``autoscale_up_frac`` of the
  request deadline for ``autoscale_up_ticks`` CONSECUTIVE ticks
  (hysteresis: one slow flush can't add a replica),
* **down** after ``autoscale_quiet_s`` of sustained zero queued work
  (retirement goes through drain-and-replace machinery, so it drops
  nothing),
* never outside ``[fleet_min_replicas, fleet_max_replicas]``, and never
  within ``autoscale_cooldown_s`` of the previous scale event — the dead
  time that keeps scaling from flapping or interacting with restart
  storms.

Cold start never scales: with no drain-rate sample yet there is no
backlog estimate, exactly like the admission shed's cold-start
never-sheds rule.  The supervisor turns each returned decision into a
replica add/retire plus a ``fleet_scale_up`` / ``fleet_scale_down``
health event carrying the signal value that triggered it.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class ScaleDecision:
    """One autoscaler verdict: scale ``direction`` ("up"/"down"),
    triggered at ``signal`` seconds of estimated backlog wait with
    ``live`` routable replicas."""

    direction: str
    signal: float
    live: int


class FleetAutoscaler:
    """Hysteresis + cooldown + bounds around the drain-rate signal.

    Pure and clock-free: callers pass ``now`` (monotonic seconds) into
    :meth:`evaluate`, so tests drive the state machine with a fake
    clock.  Disabled (``evaluate`` always None) unless
    ``fleet_max_replicas > 0``.
    """

    def __init__(self, serving):
        self.serving = serving
        self.min_replicas = max(1, int(serving.fleet_min_replicas))
        self.max_replicas = int(serving.fleet_max_replicas)
        self.up_frac = float(serving.autoscale_up_frac)
        self.up_ticks = max(1, int(serving.autoscale_up_ticks))
        self.quiet_s = float(serving.autoscale_quiet_s)
        self.cooldown_s = float(serving.autoscale_cooldown_s)
        # with deadlines disabled the shed is off too; 1 s keeps the
        # up-threshold meaningful instead of dividing by zero
        self.deadline_ref_s = (
            float(serving.request_deadline_ms) / 1e3
            if float(serving.request_deadline_ms) > 0 else 1.0)
        self._hot_ticks = 0
        self._quiet_since: Optional[float] = None
        self._last_scale_at: Optional[float] = None
        self._last_est: Optional[float] = None

    def enabled(self) -> bool:
        return self.max_replicas > 0

    def _cooled(self, now: float) -> bool:
        return (self._last_scale_at is None
                or now - self._last_scale_at >= self.cooldown_s)

    def evaluate(self, queued: float, drain_rate_rps: float, live: int,
                 now: float) -> Optional[ScaleDecision]:
        """One probe tick: ``queued`` requests waiting fleet-wide,
        ``drain_rate_rps`` the fleet's summed drain-rate EWMA, ``live``
        routable replicas.  Returns a decision or None."""
        if not self.enabled():
            return None
        est = (float(queued) / drain_rate_rps) \
            if drain_rate_rps and drain_rate_rps > 0 else None
        self._last_est = est
        decision = None
        if est is not None and est > self.up_frac * self.deadline_ref_s:
            self._quiet_since = None
            self._hot_ticks += 1
            if (self._hot_ticks >= self.up_ticks
                    and live < self.max_replicas and self._cooled(now)):
                decision = ScaleDecision("up", est, live)
        else:
            self._hot_ticks = 0
            if float(queued) <= 0:
                if self._quiet_since is None:
                    self._quiet_since = now
                if (now - self._quiet_since >= self.quiet_s
                        and live > self.min_replicas
                        and self._cooled(now)):
                    decision = ScaleDecision(
                        "down", est if est is not None else 0.0, live)
            else:
                self._quiet_since = None
        if decision is not None:
            # cooldown starts at the DECISION, whether or not the scale
            # attempt succeeds — a failing scale-up must not retry every
            # tick into a storm
            self._last_scale_at = now
            self._hot_ticks = 0
            self._quiet_since = None
        return decision

    def state(self, now: Optional[float] = None) -> dict:
        """Introspection for /metrics: thresholds + live counters."""
        cooldown_left = 0.0
        if now is not None and self._last_scale_at is not None:
            cooldown_left = max(
                0.0, self.cooldown_s - (now - self._last_scale_at))
        return {
            "enabled": self.enabled(),
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_threshold_s": self.up_frac * self.deadline_ref_s,
            "hot_ticks": self._hot_ticks,
            "quiet_for_s": (0.0 if self._quiet_since is None or now is None
                            else max(0.0, now - self._quiet_since)),
            "cooldown_remaining_s": cooldown_left,
            "est_wait_s": self._last_est,
        }
