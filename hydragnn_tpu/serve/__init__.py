"""Online inference serving subsystem (docs/SERVING.md).

Checkpoint -> :func:`load_inference_state` (params + batch_stats, no
optimizer; optional f32/bf16/int8 dtype policy via hydragnn_tpu/quant)
-> :class:`InferenceEngine` (bucketed AOT compile cache, golden-gated
quantized states, hot reload with golden-batch validation + rollback)
-> :class:`MicroBatcher`
(fill-or-deadline dynamic micro-batching, deadline-based load shedding,
predict watchdog + circuit breaker) -> :class:`InferenceServer` (stdlib
HTTP: /predict, /reload, /healthz, /metrics, graceful SIGTERM drain).
``python -m hydragnn_tpu.serve`` runs a server from a trained run's
saved config.json.  Overload semantics: docs/SERVING.md "Overload
behavior & operational runbook".

Fault-tolerant FLEET topology (``--fleet N`` / ``Serving.fleet_*``,
docs/SERVING.md "Replica fleet"): N supervised replicas — each a full
engine+batcher, subprocess by default or in-process via
:meth:`InferenceEngine.fork` — behind :class:`FleetRouter`
(power-of-two-choices least-outstanding routing, failover retry under
the request deadline, breaker-driven ejection, 429 only when the whole
fleet is saturated, 503 only when it is empty) with
:class:`FleetSupervisor` restarting crashed replicas under exponential
backoff + a storm cap and fanning hot reloads out as a rolling
one-replica-at-a-time update.

Closed-loop autoscaling + multi-tenancy (docs/SERVING.md "Multi-tenant
fleet & autoscaler"): :class:`FleetAutoscaler` consumes the drain-rate
signal in the supervisor's probe loop (scale up under backlog, retire
replicas zero-drop after a quiet window, hysteresis/cooldown/bounds);
in-process replicas host extra tenants (``model`` field on /predict) as
:meth:`InferenceEngine.fork` engines behind a bounded LRU, with
per-tenant admission budgets shedding a hot tenant's 429s while the
other tenants keep their SLO.

Exports resolve lazily (PEP 562): ``config.finalize`` imports
``serve.config`` for the written-back Serving defaults, and that must
not drag the engine/server stack (flax, http.server, the trainer) into
every config-only caller.
"""

_EXPORTS = {
    "bucket_cost": "hydragnn_tpu.serve.autotune",
    "demands_from_flushes": "hydragnn_tpu.serve.autotune",
    "expected_cost": "hydragnn_tpu.serve.autotune",
    "replay_flushes": "hydragnn_tpu.serve.autotune",
    "required_capacity": "hydragnn_tpu.serve.autotune",
    "simulate_bursts": "hydragnn_tpu.serve.autotune",
    "tune_ladder": "hydragnn_tpu.serve.autotune",
    "BatcherClosedError": "hydragnn_tpu.serve.batcher",
    "DeadlineExpiredError": "hydragnn_tpu.serve.batcher",
    "MicroBatcher": "hydragnn_tpu.serve.batcher",
    "PredictTimeoutError": "hydragnn_tpu.serve.batcher",
    "QueueFullError": "hydragnn_tpu.serve.batcher",
    "RequestShedError": "hydragnn_tpu.serve.batcher",
    "DEFAULT_TENANT": "hydragnn_tpu.serve.config",
    "ServingConfig": "hydragnn_tpu.serve.config",
    "serving_defaults": "hydragnn_tpu.serve.config",
    "FleetAutoscaler": "hydragnn_tpu.serve.autoscale",
    "ScaleDecision": "hydragnn_tpu.serve.autoscale",
    "FleetSupervisor": "hydragnn_tpu.serve.fleet",
    "InProcessReplica": "hydragnn_tpu.serve.fleet",
    "PredictRequest": "hydragnn_tpu.serve.fleet",
    "ReplicaDeadError": "hydragnn_tpu.serve.fleet",
    "SubprocessReplica": "hydragnn_tpu.serve.fleet",
    "UnknownTenantError": "hydragnn_tpu.serve.fleet",
    "spawn_argv": "hydragnn_tpu.serve.fleet",
    "FleetEmptyError": "hydragnn_tpu.serve.router",
    "FleetRouter": "hydragnn_tpu.serve.router",
    "FleetSaturatedError": "hydragnn_tpu.serve.router",
    "BucketOverflowError": "hydragnn_tpu.serve.engine",
    "InferenceEngine": "hydragnn_tpu.serve.engine",
    "InferenceState": "hydragnn_tpu.serve.engine",
    "ReloadValidationError": "hydragnn_tpu.serve.engine",
    "load_inference_state": "hydragnn_tpu.serve.engine",
    "InferenceServer": "hydragnn_tpu.serve.server",
    "sample_from_json": "hydragnn_tpu.serve.server",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module 'hydragnn_tpu.serve' has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
