"""Padding-waste-driven bucket-ladder auto-tuning.

The serving bucket ladder (``Serving.buckets``) fixes which padded batch
shapes get AOT-compiled; every flush then pays the padded-slot cost of
the smallest bucket that fits it.  A ladder tuned for the wrong traffic
burns FLOPs and latency on padding — the per-flush padding % the
batcher records (telemetry serve step records, docs/TELEMETRY.md) is
the direct measurement of that waste.

This module turns those measurements back into a ladder:

- :func:`required_capacity` — the smallest batch capacity (graphs)
  whose PadSpec fits a flush of ``(ng, nn, ne)`` real graphs / nodes /
  edges: the ladder-independent "demand" of the flush.  The batcher
  tallies a live histogram of these (``flush_demands`` in its stats).
- :func:`tune_ladder` — given a demand histogram, solve for the ladder
  of at most ``max_ladder`` capacities minimizing expected padded
  slots (nodes + edges — the FLOP proxy every message-passing layer
  scales with).  Exact DP over distinct demand values: an optimal
  ladder only needs points AT observed demands (any other point could
  be lowered to the next demand below it without losing coverage), so
  the search space is the demand set itself — O(m^2 * K) for m
  distinct demands.
- :func:`replay_flushes` — validate a candidate ladder by replaying
  recorded flushes through the engine's own bucket-selection rule
  (smallest fitting bucket, the ``select_bucket`` slot conventions).
- :func:`simulate_bursts` — build a synthetic flush stream from a
  request-size distribution + burst (arrival) model, for tuning from
  ``/metrics`` request histograms when no per-flush log exists.

``tools/buckettune.py`` is the CLI wrapping these against a telemetry
JSONL or a live ``/metrics`` scrape.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Sequence, Tuple

from hydragnn_tpu.graph.batch import PadSpec

# hard stop for demand solving: a flush needing more than this many
# graph slots is a configuration error, not a tuning input
MAX_CAPACITY = 65536


def _fits(spec: PadSpec, ng: int, nn: int, ne: int) -> bool:
    """The engine's bucket-fit rule (serve/engine.py:select_bucket):
    collate reserves one node slot and the trailing padding graph."""
    return (spec.num_graphs - 1 >= ng and spec.num_nodes - 1 >= nn
            and spec.num_edges >= ne)


def bucket_cost(capacity: int, max_nodes_per_graph: int,
                max_edges_per_graph: int, round_to: int = 8) -> float:
    """Padded-slot cost of one flush in a bucket of ``capacity`` graphs:
    node slots + edge slots of its PadSpec — the quantity message-passing
    FLOPs (and step time, once memory-bound) scale with."""
    spec = PadSpec.for_batch(int(capacity), int(max_nodes_per_graph),
                             int(max_edges_per_graph), round_to)
    return float(spec.num_nodes + spec.num_edges)


def required_capacity(ng: int, nn: int, ne: int, max_nodes_per_graph: int,
                      max_edges_per_graph: int, round_to: int = 8) -> int:
    """Smallest batch capacity whose PadSpec fits ``ng`` graphs /
    ``nn`` nodes / ``ne`` edges — the flush's ladder-independent
    demand."""
    mn = int(max_nodes_per_graph)
    me = int(max_edges_per_graph)
    if mn < 1 or me < 1:
        raise ValueError(
            "required_capacity needs the per-graph worst case "
            f"(max_nodes_per_graph={mn}, max_edges_per_graph={me})")
    # lower bound from each constraint.  PadSpec rounds num_nodes/edges
    # UP by as much as round_to-1 slots, which spans SEVERAL capacity
    # steps when mn/me < round_to (2-3-atom graphs) — so the bound must
    # concede the whole rounding allowance, not one step: the padded
    # capacity of c is at most c*mn + round_to, hence the minimal c is
    # at least (nn - round_to) / mn.  Floor division keeps the start
    # at-or-under the true minimum; the walk-up finds it exactly.
    c = max(1, int(ng),
            max(0, int(nn) - round_to) // mn,
            max(0, int(ne) - round_to) // me)
    while c <= MAX_CAPACITY:
        if _fits(PadSpec.for_batch(c, mn, me, round_to), ng, nn, ne):
            return c
        c += 1
    raise ValueError(f"flush of {ng} graphs / {nn} nodes / {ne} edges "
                     f"needs a capacity beyond {MAX_CAPACITY}")


def expected_cost(demands: Dict[int, int], ladder: Sequence[int],
                  max_nodes_per_graph: int, max_edges_per_graph: int,
                  round_to: int = 8) -> Tuple[float, int]:
    """(total padded slots, overflowed flushes) of serving a demand
    histogram with ``ladder`` — each demand pays the cost of the
    smallest ladder point >= it; demands above the top overflow."""
    lad = sorted(set(int(c) for c in ladder))
    costs = {c: bucket_cost(c, max_nodes_per_graph, max_edges_per_graph,
                            round_to) for c in lad}
    total, overflow = 0.0, 0
    for d, w in demands.items():
        c = next((c for c in lad if c >= int(d)), None)
        if c is None:
            overflow += int(w)
            continue
        total += int(w) * costs[c]
    return total, overflow


def tune_ladder(demands: Dict[int, int], max_ladder: int,
                max_nodes_per_graph: int, max_edges_per_graph: int,
                force_top: int = 0, round_to: int = 8) -> Dict[str, Any]:
    """Exact minimum-expected-padded-slots ladder of size <= max_ladder.

    ``demands`` maps required capacity -> flush count (the batcher's
    ``flush_demands`` histogram, or :func:`demands_from_flushes`).
    ``force_top`` (the CURRENT top capacity) is always covered so the
    tuned ladder never shrinks serviceability: a request the old ladder
    admitted must not start bouncing with 413s.

    Returns ``{"ladder", "cost", "buckets_used", "per_demand"}``.
    """
    if max_ladder < 1:
        raise ValueError(f"max_ladder must be >= 1, got {max_ladder}")
    if not demands:
        raise ValueError("empty demand histogram — nothing to tune from")
    ds = sorted(int(d) for d in demands if int(demands[d]) > 0)
    if not ds:
        raise ValueError("demand histogram has no positive counts")
    w = {int(d): int(demands[d]) for d in ds}
    if force_top and int(force_top) > ds[-1]:
        # zero-weight sentinel demand: the DP must still place (or
        # cover with) a point >= it
        ds.append(int(force_top))
        w[int(force_top)] = 0
    m = len(ds)
    k_max = min(int(max_ladder), m)
    costs = [bucket_cost(d, max_nodes_per_graph, max_edges_per_graph,
                         round_to) for d in ds]
    # prefix weights: W[j] = sum of counts of ds[0..j-1]
    pref = [0] * (m + 1)
    for j, d in enumerate(ds):
        pref[j + 1] = pref[j] + w[d]
    inf = float("inf")
    # f[j][k]: min cost covering ds[0..j] with k ladder points, the
    # largest of which is ds[j]; every demand in (ds[i], ds[j]] pays
    # cost(ds[j])
    f = [[inf] * (k_max + 1) for _ in range(m)]
    parent = [[-1] * (k_max + 1) for _ in range(m)]
    for j in range(m):
        f[j][1] = pref[j + 1] * costs[j]
        for k in range(2, k_max + 1):
            for i in range(j):
                cand = f[i][k - 1] + (pref[j + 1] - pref[i + 1]) * costs[j]
                if cand < f[j][k]:
                    f[j][k] = cand
                    parent[j][k] = i
    best_k = min(range(1, k_max + 1), key=lambda k: f[m - 1][k])
    ladder: List[int] = []
    j, k = m - 1, best_k
    while j >= 0 and k >= 1:
        ladder.append(ds[j])
        j, k = parent[j][k], k - 1
    ladder.reverse()
    cost, overflow = expected_cost(
        {d: w[d] for d in ds}, ladder, max_nodes_per_graph,
        max_edges_per_graph, round_to)
    assert overflow == 0, "tuned ladder must cover every demand"
    per_demand = {}
    lad = sorted(ladder)
    for d in ds:
        if w[d]:
            per_demand[int(d)] = next(c for c in lad if c >= d)
    return {"ladder": tuple(ladder), "cost": cost,
            "buckets_used": len(ladder), "per_demand": per_demand}


def demands_from_flushes(flushes: Iterable[Tuple[int, int, int]],
                         max_nodes_per_graph: int,
                         max_edges_per_graph: int,
                         round_to: int = 8) -> Dict[int, int]:
    """Histogram of :func:`required_capacity` over recorded flushes
    ``(real_graphs, real_nodes, real_edges)``."""
    out: Dict[int, int] = {}
    for ng, nn, ne in flushes:
        c = required_capacity(ng, nn, ne, max_nodes_per_graph,
                              max_edges_per_graph, round_to)
        out[c] = out.get(c, 0) + 1
    return out


def replay_flushes(flushes: Iterable[Tuple[int, int, int]],
                   ladder: Sequence[int], max_nodes_per_graph: int,
                   max_edges_per_graph: int,
                   round_to: int = 8) -> Dict[str, Any]:
    """Replay recorded flushes through a ladder with the engine's own
    smallest-fitting-bucket selection; returns padded/real slot totals,
    waste percentages, per-bucket flush counts, and overflows (flushes
    no bucket fits — must be 0 for a deployable ladder)."""
    specs = [PadSpec.for_batch(int(c), int(max_nodes_per_graph),
                               int(max_edges_per_graph), round_to)
             for c in sorted(set(int(c) for c in ladder))]
    caps = sorted(set(int(c) for c in ladder))
    padded_n = padded_e = real_n = real_e = 0
    per_bucket: Dict[int, int] = {}
    overflow = 0
    for ng, nn, ne in flushes:
        chosen = None
        for cap, spec in zip(caps, specs):
            if _fits(spec, ng, nn, ne):
                chosen = (cap, spec)
                break
        if chosen is None:
            overflow += 1
            continue
        cap, spec = chosen
        per_bucket[cap] = per_bucket.get(cap, 0) + 1
        padded_n += spec.num_nodes
        padded_e += spec.num_edges
        real_n += int(nn)
        real_e += int(ne)
    def _waste(real, padded):
        return (1.0 - real / padded) * 100.0 if padded else 0.0
    return {
        "flushes": sum(per_bucket.values()),
        "overflow": overflow,
        "padded_nodes": padded_n,
        "padded_edges": padded_e,
        "real_nodes": real_n,
        "real_edges": real_e,
        "padded_slots": padded_n + padded_e,
        "nodes_waste_pct": _waste(real_n, padded_n),
        "edges_waste_pct": _waste(real_e, padded_e),
        "slots_waste_pct": _waste(real_n + real_e, padded_n + padded_e),
        "per_bucket": per_bucket,
    }


def simulate_bursts(request_sizes: Sequence[Tuple[int, int]],
                    burst_sizes: Sequence[int], top_capacity: int,
                    max_nodes_per_graph: int, max_edges_per_graph: int,
                    round_to: int = 8) -> List[Tuple[int, int, int]]:
    """Turn a request-size stream into flushes under the batcher's
    accumulation rule: each burst (requests arriving inside one
    ``max_wait_ms`` window) flushes together, split early whenever the
    TOP bucket would overflow — the ``full``-flush bound of
    serve/batcher.py.  Returns ``(ng, nn, ne)`` flushes for
    :func:`replay_flushes`/:func:`demands_from_flushes`.

    ``request_sizes`` is ``[(num_nodes, num_edges), ...]`` (e.g. drawn
    from the /metrics per-request histograms); ``burst_sizes`` is the
    arrival model — how many requests land in each batching window.
    """
    top = PadSpec.for_batch(int(top_capacity), int(max_nodes_per_graph),
                            int(max_edges_per_graph), round_to)
    flushes: List[Tuple[int, int, int]] = []
    it = iter(request_sizes)
    exhausted = False
    for burst in burst_sizes:
        if exhausted:
            break
        ng = nn = ne = 0
        for _ in range(int(burst)):
            try:
                rn, re_ = next(it)
            except StopIteration:
                exhausted = True
                break
            if ng and not _fits(top, ng + 1, nn + int(rn), ne + int(re_)):
                flushes.append((ng, nn, ne))  # full flush: top overflow
                ng = nn = ne = 0
            ng += 1
            nn += int(rn)
            ne += int(re_)
        if ng:
            flushes.append((ng, nn, ne))      # deadline flush: burst end
    return flushes
