"""Serve a trained run over HTTP.

    python -m hydragnn_tpu.serve --config logs/<run>/config.json \
        [--logs-dir ./logs/] [--host H] [--port P] \
        [--fleet N [--fleet-inprocess]]

``--config`` is the FINALIZED config run_training saved next to the
checkpoint (it carries output dims, head layout and the written-back
``Serving`` section).  Per-graph bucket sizing must be present —
``Serving.max_nodes_per_graph``/``max_edges_per_graph`` in the config or
the ``HYDRAGNN_SERVE_MAX_NODES``/``HYDRAGNN_SERVE_MAX_EDGES`` env knobs.
Telemetry env knobs (HYDRAGNN_TELEMETRY=1 etc.) give the server a JSONL
event log viewable with tools/teleview.py.

``--fleet N`` (or ``Serving.fleet_replicas``) runs N supervised engine
replicas behind the failover router instead of one server: each replica
is a child ``python -m hydragnn_tpu.serve`` process on an ephemeral
loopback port (``--fleet-inprocess`` keeps them as threads sharing one
compile cache — the CPU/dev topology), crashed replicas restart with
exponential backoff, and ``POST /reload`` becomes a rolling
one-replica-at-a-time fleet update (docs/SERVING.md "Replica fleet").
``--reload-watch`` applies to single-server mode only.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True,
                    help="finalized config.json from a trained run's log dir")
    ap.add_argument("--logs-dir", default="./logs/",
                    help="logs root holding the checkpoint (default ./logs/)")
    ap.add_argument("--host", default=None, help="bind host override")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port override")
    ap.add_argument("--reload-watch", default=None, metavar="CKPT",
                    help="hot-reload this checkpoint file whenever its "
                         "mtime changes (validated + rollback-protected; "
                         "see docs/SERVING.md)")
    ap.add_argument("--reload-watch-s", type=float, default=None,
                    help="file-watch poll interval in seconds "
                         "(default 5 when --reload-watch is set)")
    ap.add_argument("--fleet", type=int, default=None, metavar="N",
                    help="run N supervised replicas behind the failover "
                         "router (overrides Serving.fleet_replicas; "
                         "0 = single server)")
    ap.add_argument("--fleet-inprocess", action="store_true",
                    help="fleet replicas as in-process threads sharing "
                         "one compile cache (CPU/dev) instead of "
                         "subprocesses")
    args = ap.parse_args(argv)

    with open(args.config) as f:
        config = json.load(f)

    from hydragnn_tpu.serve import InferenceEngine, InferenceServer, \
        ServingConfig
    from hydragnn_tpu.telemetry import MetricsLogger

    serving = ServingConfig.from_section(config.get("Serving"))
    if args.host is not None:
        serving.host = args.host
    if args.port is not None:
        serving.port = args.port
    if args.reload_watch is not None:
        serving.reload_watch_path = args.reload_watch
        # CLI interval > configured (config/env) interval > 5 s default
        serving.reload_watch_s = args.reload_watch_s \
            if args.reload_watch_s is not None \
            else (serving.reload_watch_s or 5.0)
    elif args.reload_watch_s is not None:
        serving.reload_watch_s = args.reload_watch_s
    if args.fleet is not None:
        serving.fleet_replicas = max(0, int(args.fleet))
    if args.fleet_inprocess:
        serving.fleet_inprocess = True
    telemetry = MetricsLogger.from_env(run_name="serve")

    if serving.fleet_replicas > 0:
        from hydragnn_tpu.resilience import FleetChaos
        from hydragnn_tpu.serve import (
            FleetRouter, FleetSupervisor, InProcessReplica,
            SubprocessReplica, spawn_argv)

        n = serving.fleet_replicas
        if serving.fleet_inprocess:
            base = InferenceEngine.from_config(
                config, logs_dir=args.logs_dir, serving=serving,
                telemetry=telemetry)
            base.warmup()  # forks share this one compiled cache
            replicas = [
                InProcessReplica(i, base.fork, serving, telemetry)
                for i in range(n)
            ]
            cfg, pbc = base.cfg, base.pbc

            def replica_factory(i):
                return InProcessReplica(i, base.fork, serving, telemetry)
        else:
            builder = spawn_argv(args.config, logs_dir=args.logs_dir)
            replicas = [
                SubprocessReplica(i, builder, serving, telemetry)
                for i in range(n)
            ]
            cfg, pbc = None, False

            def replica_factory(i):
                return SubprocessReplica(i, builder, serving, telemetry)
        # fleet_max_replicas > 0 arms the closed-loop autoscaler: the
        # supervisor builds the FleetAutoscaler policy itself and grows
        # or shrinks the fleet via this factory (serve/autoscale.py)
        fleet = FleetSupervisor(replicas, serving, telemetry=telemetry,
                                chaos=FleetChaos.from_env(
                                    config.get("Serving", {}).get(
                                        "FleetChaos")),
                                replica_factory=replica_factory)
        router = FleetRouter(fleet, serving=serving, cfg=cfg, pbc=pbc,
                             telemetry=telemetry)
        mode = "in-process" if serving.fleet_inprocess else "subprocess"
        print(f"fleet of {n} {mode} replicas — router on "
              f"http://{serving.host}:{router.port} — SIGTERM drains "
              "gracefully", flush=True)
        try:
            router.run()
        finally:
            telemetry.finalize()
        return 0

    engine = InferenceEngine.from_config(
        config, logs_dir=args.logs_dir, serving=serving, telemetry=telemetry)
    server = InferenceServer(engine, serving=serving)
    print(f"serving on http://{serving.host}:{server.port}  "
          f"(buckets: {[p.num_graphs - 1 for p in engine.pad_specs]}, "
          f"max_wait {serving.max_wait_ms} ms) — SIGTERM drains gracefully",
          flush=True)
    try:
        server.run()
    finally:
        telemetry.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
