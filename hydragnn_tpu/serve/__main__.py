"""Serve a trained run over HTTP.

    python -m hydragnn_tpu.serve --config logs/<run>/config.json \
        [--logs-dir ./logs/] [--host H] [--port P]

``--config`` is the FINALIZED config run_training saved next to the
checkpoint (it carries output dims, head layout and the written-back
``Serving`` section).  Per-graph bucket sizing must be present —
``Serving.max_nodes_per_graph``/``max_edges_per_graph`` in the config or
the ``HYDRAGNN_SERVE_MAX_NODES``/``HYDRAGNN_SERVE_MAX_EDGES`` env knobs.
Telemetry env knobs (HYDRAGNN_TELEMETRY=1 etc.) give the server a JSONL
event log viewable with tools/teleview.py.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True,
                    help="finalized config.json from a trained run's log dir")
    ap.add_argument("--logs-dir", default="./logs/",
                    help="logs root holding the checkpoint (default ./logs/)")
    ap.add_argument("--host", default=None, help="bind host override")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port override")
    ap.add_argument("--reload-watch", default=None, metavar="CKPT",
                    help="hot-reload this checkpoint file whenever its "
                         "mtime changes (validated + rollback-protected; "
                         "see docs/SERVING.md)")
    ap.add_argument("--reload-watch-s", type=float, default=None,
                    help="file-watch poll interval in seconds "
                         "(default 5 when --reload-watch is set)")
    args = ap.parse_args(argv)

    with open(args.config) as f:
        config = json.load(f)

    from hydragnn_tpu.serve import InferenceEngine, InferenceServer, \
        ServingConfig
    from hydragnn_tpu.telemetry import MetricsLogger

    serving = ServingConfig.from_section(config.get("Serving"))
    if args.host is not None:
        serving.host = args.host
    if args.port is not None:
        serving.port = args.port
    if args.reload_watch is not None:
        serving.reload_watch_path = args.reload_watch
        # CLI interval > configured (config/env) interval > 5 s default
        serving.reload_watch_s = args.reload_watch_s \
            if args.reload_watch_s is not None \
            else (serving.reload_watch_s or 5.0)
    elif args.reload_watch_s is not None:
        serving.reload_watch_s = args.reload_watch_s
    telemetry = MetricsLogger.from_env(run_name="serve")
    engine = InferenceEngine.from_config(
        config, logs_dir=args.logs_dir, serving=serving, telemetry=telemetry)
    server = InferenceServer(engine, serving=serving)
    print(f"serving on http://{serving.host}:{server.port}  "
          f"(buckets: {[p.num_graphs - 1 for p in engine.pad_specs]}, "
          f"max_wait {serving.max_wait_ms} ms) — SIGTERM drains gracefully",
          flush=True)
    try:
        server.run()
    finally:
        telemetry.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
