"""Failover router: one HTTP front end spreading load over the fleet.

The client-facing half of the replica fleet (serve/fleet.py): requests
are parsed/validated ONCE, dispatched to a replica chosen by
least-outstanding-requests with power-of-two-choices (pick two live
replicas at random, route to the one with fewer requests in flight —
O(1) per request, provably near-optimal balance without a global
queue), and, on replica failure, retried against a DIFFERENT replica
under the request's existing deadline budget — /predict is idempotent,
so failover is free of duplicate-effect hazards.

Degradation ladder (the fleet contract, docs/SERVING.md):

- **Any replica can serve it** -> 200.  A killed/hung/crashed replica
  mid-request surfaces as a retryable error; the router fails over and
  the client never sees it (zero 5xx under single-replica loss).
- **Replica circuit-broken** -> ejected from routing (the supervisor
  readmits it after the cooldown, making the next routed flush the
  half-open probe); the request retries elsewhere.
- **Whole fleet saturated** (every live replica shed or queue-full) ->
  429 whose ``Retry-After`` is the MINIMUM surviving-replica drain
  estimate — the soonest ANY replica will have capacity, not whichever
  replica happened to be asked first.
- **Fleet empty** (no live replicas at all) -> 503 + Retry-After.

Aggregated observability: ``GET /healthz`` reports per-replica states
and quorum; ``GET /metrics`` adds per-replica detail (breaker
snapshots, restart counts, queue depths) plus fleet totals and the
drain-rate EWMA SUM — the autoscaling signal (ROADMAP item 1).
``POST /reload`` performs the rolling one-replica-at-a-time fleet
reload with first-replica rollback (FleetSupervisor.rolling_reload).
"""

from __future__ import annotations

import json
import math
import random
import threading
import time
# py3.10: concurrent.futures.TimeoutError is not yet the builtin one
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Dict, List, Optional

from hydragnn_tpu.resilience.breaker import BreakerOpenError
from hydragnn_tpu.serve.batcher import (
    BatcherClosedError,
    DeadlineExpiredError,
    PredictTimeoutError,
    QueueFullError,
    RequestShedError,
)
from hydragnn_tpu.serve.config import DEFAULT_TENANT, ServingConfig
from hydragnn_tpu.serve.fleet import (
    FleetSupervisor,
    PredictRequest,
    ReplicaDeadError,
    UnknownTenantError,
)
from hydragnn_tpu.serve.server import (
    JsonRequestHandler,
    _BodyTooLarge,
    extract_deadline_s,
    reload_request_denied,
    sample_from_json,
)
from hydragnn_tpu.telemetry.trace import extract_trace_context


class FleetSaturatedError(RequestShedError):
    """Every live replica shed the request (HTTP 429).  ``retry_after_s``
    is the MINIMUM drain estimate across the surviving replicas — the
    soonest any of them expects capacity."""


class FleetEmptyError(RuntimeError):
    """No live replicas at all (HTTP 503 — the only fleet 5xx)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(1.0, float(retry_after_s))


class FleetRouter:
    """HTTP front end + failover dispatch over a FleetSupervisor.

    ``cfg``/``pbc`` enable local request validation and in-process
    dispatch (required for InProcessReplica fleets; for subprocess
    fleets they are optional — without them the router forwards raw
    bodies and lets replicas validate).
    """

    def __init__(self, fleet: FleetSupervisor,
                 serving: Optional[ServingConfig] = None,
                 cfg=None, pbc: bool = False, telemetry=None,
                 request_timeout_s: float = 30.0):
        self.fleet = fleet
        self.serving = serving or fleet.serving
        self.telemetry = telemetry if telemetry is not None \
            else fleet.telemetry
        self.cfg = cfg
        self.pbc = bool(pbc)
        inproc = fleet.replicas[0].kind == "inprocess"
        if inproc and cfg is None:
            raise ValueError(
                "an in-process fleet needs the model config for request "
                "parsing: pass cfg=engine.cfg")
        self._parse = cfg is not None
        self.request_timeout_s = float(request_timeout_s)
        self._rng = random.Random(0x5EED)
        self._lock = threading.Lock()
        self._n: Dict[str, int] = {
            "requests": 0, "responses_200": 0, "failovers": 0,
            "shed_attempts": 0, "saturated_429": 0, "empty_503": 0,
            "tenant_shed_429": 0, "errors": 0}
        self._per_replica: Dict[int, int] = {}
        # per-tenant admission state: outstanding counts gate the
        # budget, the counters feed /metrics "tenancy"
        self._tenant_out: Dict[str, int] = {}
        self._per_tenant: Dict[str, Dict[str, int]] = {}
        self._was_empty = False
        self._t0 = time.time()
        # bind in the constructor (same contract as InferenceServer):
        # the ephemeral port is known before start(), and a request
        # racing fleet startup just sees an empty fleet (503)
        self.httpd = self._build_httpd()
        self.port: int = int(self.httpd.server_address[1])
        self._serve_thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- replica selection ---------------------------------------------------

    def _pick(self, cands: List[Any]):
        """Power-of-two-choices over outstanding counts; ``sample``
        randomizes the pair order, so ties break randomly too."""
        if len(cands) == 1:
            return cands[0]
        a, b = self._rng.sample(cands, 2)
        return a if a.outstanding <= b.outstanding else b

    def _empty_retry_after(self) -> float:
        # a dead fleet usually comes back within one restart backoff +
        # startup; there is no measured drain rate to do better with
        return max(1.0, self.fleet.serving.fleet_restart_backoff_s)

    # -- failover dispatch ---------------------------------------------------

    def route_predict(self, req: PredictRequest,
                      deadline_s: Optional[float]) -> Dict[str, Any]:
        """Dispatch with failover: try replicas (po2, least-outstanding)
        until one answers, a terminal client error surfaces, the
        request's deadline budget runs out, every live replica shed it
        (:class:`FleetSaturatedError` -> 429 with the MIN surviving
        drain estimate), or none remain (:class:`FleetEmptyError` ->
        503).  The request first clears its tenant's admission gate
        (:meth:`_admit_tenant` -> 429 for THAT tenant only).  Returns
        ``{"heads": ..., "replica": idx}``."""
        with self._lock:
            self._n["requests"] += 1
            tn = self._per_tenant.setdefault(
                req.tenant,
                {"requests": 0, "responses_200": 0, "shed_429": 0})
            tn["requests"] += 1
        self._admit_tenant(req.tenant, deadline_s)
        try:
            out = self._dispatch(req, deadline_s)
        finally:
            with self._lock:
                self._tenant_out[req.tenant] = max(
                    0, self._tenant_out.get(req.tenant, 1) - 1)
        with self._lock:
            self._per_tenant[req.tenant]["responses_200"] += 1
        return out

    def _tenant_cap(self, deadline_s: Optional[float]) -> Optional[int]:
        """Per-tenant outstanding-work cap: the share of the fleet's
        measured drain rate (last probe tick's EWMA sum) one tenant may
        hold for a deadline's worth of time —
        ``ceil(tenant_budget_frac * drain_rate_rps * deadline_s)``.
        None (no cap) when budgets are off or before the first drain
        sample: cold start never sheds, same rule as the admission
        shed."""
        frac = float(self.serving.tenant_budget_frac)
        if frac <= 0:
            return None
        rate = float(getattr(self.fleet, "last_drain_rate", 0.0) or 0.0)
        if rate <= 0:
            return None
        ref = deadline_s if deadline_s and deadline_s > 0 \
            else ((self.serving.request_deadline_ms / 1e3) or 1.0)
        return max(1, math.ceil(frac * rate * ref))

    def _admit_tenant(self, tenant: str,
                      deadline_s: Optional[float]) -> None:
        """Tenant admission gate; on admit the tenant's outstanding
        count is already incremented (route_predict releases it).
        Sheds (429) when the tenant is over its budget cap or marked
        hot by chaos — the OTHER tenants' traffic is untouched, which
        is the whole point."""
        hot = tenant in getattr(self.fleet, "hot_tenants", set())
        cap = None if hot else self._tenant_cap(deadline_s)
        with self._lock:
            out = self._tenant_out.get(tenant, 0)
            shed = hot or (cap is not None and out >= cap)
            if not shed:
                self._tenant_out[tenant] = out + 1
            else:
                self._n["tenant_shed_429"] += 1
                self._per_tenant.setdefault(
                    tenant,
                    {"requests": 0, "responses_200": 0, "shed_429": 0}
                )["shed_429"] += 1
        if not shed:
            return
        if hot:
            self.telemetry.health("tenant_shed", tenant=tenant,
                                  reason="chaos_hot")
            raise RequestShedError(
                f"tenant {tenant!r} marked hot (chaos)",
                retry_after_s=1.0)
        rate = float(getattr(self.fleet, "last_drain_rate", 0.0) or 0.0)
        retry = max(1.0, out / rate) if rate > 0 else 1.0
        self.telemetry.health("tenant_shed", tenant=tenant,
                              reason="budget", outstanding=out, cap=cap)
        raise RequestShedError(
            f"tenant {tenant!r} over its admission budget "
            f"({out}/{cap} outstanding)", retry_after_s=retry)

    def _dispatch(self, req: PredictRequest,
                  deadline_s: Optional[float]) -> Dict[str, Any]:
        deadline_abs = None if deadline_s is None \
            else time.perf_counter() + deadline_s
        tried: set = set()
        shed_estimates: List[float] = []
        last_exc: Optional[Exception] = None
        while True:
            live = self.fleet.routable()
            if not live:
                with self._lock:
                    self._n["empty_503"] += 1
                    first = not self._was_empty
                    self._was_empty = True
                if first:
                    self.telemetry.health("fleet_empty",
                                          total=len(self.fleet.replicas))
                raise FleetEmptyError(
                    "no live replicas — the fleet is restarting or gone",
                    retry_after_s=self._empty_retry_after())
            self._was_empty = False
            cands = [r for r in live if r.idx not in tried]
            if not cands:
                # every live replica was tried: saturation (429) when
                # they shed, otherwise surface the last real failure
                if shed_estimates:
                    with self._lock:
                        self._n["saturated_429"] += 1
                    raise FleetSaturatedError(
                        f"all {len(live)} live replicas shed the request",
                        retry_after_s=min(shed_estimates))
                with self._lock:
                    self._n["errors"] += 1
                raise last_exc if last_exc is not None else RuntimeError(
                    "no replica could serve the request")
            remaining: Optional[float] = None
            if deadline_abs is not None:
                remaining = deadline_abs - time.perf_counter()
                if remaining <= 0:
                    with self._lock:
                        self._n["saturated_429"] += 1
                    raise FleetSaturatedError(
                        "deadline budget exhausted during failover",
                        retry_after_s=min(shed_estimates)
                        if shed_estimates else 1.0)
            r = self._pick(cands)
            tried.add(r.idx)
            r.inc_outstanding()
            try:
                heads = r.predict(req, remaining)
                with self._lock:
                    self._n["responses_200"] += 1
                    self._per_replica[r.idx] = \
                        self._per_replica.get(r.idx, 0) + 1
                return {"heads": heads, "replica": r.idx}
            except DeadlineExpiredError as e:
                # the request's own budget died in r's queue: there is
                # nothing left to retry WITH — 429 now, min estimate
                shed_estimates.append(e.retry_after_s)
                with self._lock:
                    self._n["saturated_429"] += 1
                raise FleetSaturatedError(
                    str(e), retry_after_s=min(shed_estimates)) from None
            except RequestShedError as e:
                # admission shed: THIS replica's backlog can't make the
                # deadline — another replica's might
                shed_estimates.append(e.retry_after_s)
                with self._lock:
                    self._n["shed_attempts"] += 1
                continue
            except QueueFullError:
                shed_estimates.append(r.retry_after_s())
                with self._lock:
                    self._n["shed_attempts"] += 1
                continue
            except BreakerOpenError:
                # circuit-broken replica: eject it (the supervisor
                # readmits after cooldown) and fail over
                self.fleet.eject(r, reason="breaker_open")
                self._note_failover(r, "breaker_open")
                continue
            except (ReplicaDeadError, BatcherClosedError) as e:
                # the replica died under us: stop routing to it,
                # schedule its restart, retry elsewhere
                self.fleet.mark_dead(r, reason="predict_failure")
                self._note_failover(r, repr(e))
                last_exc = e
                continue
            except PredictTimeoutError as e:
                # its watchdog tripped (breaker already recorded the
                # failure); the retry may still make the deadline
                self._note_failover(r, "predict_timeout")
                last_exc = e
                continue
            except (_FutureTimeout, TimeoutError):
                # the replica never answered within budget + grace:
                # failover; exhausted -> 504, not 500
                self._note_failover(r, "result_timeout")
                last_exc = PredictTimeoutError(
                    "replica did not answer within the request budget")
                continue
            except UnknownTenantError:
                # terminal: every replica hosts the SAME tenant set, so
                # failing over would only repeat the 404
                raise
            except (ValueError, FileNotFoundError):
                # client error (subprocess replicas validate bodies
                # themselves): not retryable, not a replica fault
                raise
            except Exception as e:  # noqa: BLE001 — engine failure
                self._note_failover(r, repr(e))
                last_exc = e
                continue
            finally:
                r.dec_outstanding()

    def _note_failover(self, r, why: str) -> None:
        with self._lock:
            self._n["failovers"] += 1
        self.telemetry.health("fleet_retry", replica=r.idx,
                              error=str(why)[:200])

    # -- HTTP ----------------------------------------------------------------

    def _build_httpd(self):
        from http.server import ThreadingHTTPServer

        class _RouterHTTPServer(ThreadingHTTPServer):
            # the router fronts the WHOLE fleet's capacity, so bursts
            # arrive N times harder than at a single server — the
            # stdlib's listen backlog of 5 drops (RSTs) connections the
            # fleet could happily serve
            request_queue_size = 128

        router = self

        class Handler(JsonRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    self._reply(200, router.health())
                elif self.path == "/metrics":
                    self._reply(200, router.metrics())
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                if self.path == "/reload":
                    self._do_reload()
                    return
                if self.path != "/predict":
                    self._reply(404, {"error": f"unknown path {self.path}"})
                    return
                t0 = time.perf_counter()
                # mint/adopt the trace identity at the FLEET EDGE — the
                # SAME SpanContext rides the PredictRequest across every
                # failover retry, so one trace_id tells the whole story
                # even when the answer came from the third replica tried
                ctx = extract_trace_context(self.headers)
                code, payload, hdrs = self._predict_answer(t0, ctx)
                payload["trace_id"] = ctx.trace_id
                hdrs = dict(hdrs or {})
                hdrs["X-Request-Id"] = ctx.trace_id
                tr = getattr(router.telemetry, "spans", None)
                if tr is not None:
                    tr.record_interval(
                        "serve.request", t0, time.perf_counter(),
                        trace_id=ctx.trace_id, parent_id=ctx.parent_id,
                        status=code)
                self._reply(code, payload, headers=hdrs)

            def _predict_answer(self, t0, ctx):
                """The /predict dispatch as (code, payload, headers) —
                one exit point so EVERY answer (200 and every
                shed/saturated/timeout error) carries the trace id."""
                try:
                    obj = self._read_json()
                    deadline_s = extract_deadline_s(self.headers, obj)
                    req = router.build_request(obj)
                    req.trace = ctx
                except _BodyTooLarge as e:
                    return 413, {"error": str(e)}, None
                except (ValueError, TypeError, IndexError, KeyError,
                        json.JSONDecodeError) as e:
                    return 400, {"error": str(e)}, None
                if deadline_s is None \
                        and router.serving.request_deadline_ms > 0:
                    # apply the server default AT THE ROUTER: failover
                    # needs the budget to ration retries against
                    deadline_s = router.serving.request_deadline_ms / 1e3
                try:
                    out = router.route_predict(req, deadline_s)
                except UnknownTenantError as e:
                    return 404, {"error": str(e)}, None
                except FleetEmptyError as e:
                    return 503, {"error": str(e), "fleet": "empty"}, \
                        self._retry_after(e.retry_after_s)
                except FleetSaturatedError as e:
                    return 429, {"error": str(e)}, \
                        self._retry_after(e.retry_after_s)
                except RequestShedError as e:
                    return 429, {"error": str(e)}, \
                        self._retry_after(e.retry_after_s)
                except BreakerOpenError as e:
                    return 503, {"error": str(e), "breaker": "open"}, \
                        self._retry_after(e.retry_after_s)
                except PredictTimeoutError as e:
                    return 504, {"error": str(e)}, None
                except Exception as e:  # noqa: BLE001
                    from hydragnn_tpu.serve.engine import \
                        BucketOverflowError

                    if isinstance(e, BucketOverflowError):
                        return 413, {"error": str(e)}, None
                    if isinstance(e, (ValueError, FileNotFoundError)):
                        return 400, {"error": str(e)}, None
                    if isinstance(e, TimeoutError):
                        return 504, {"error": "request timed out"}, None
                    return 500, {"error": repr(e)}, None
                return 200, {
                    **out,
                    "num_nodes": int(req.num_nodes),
                    "latency_ms": round(
                        (time.perf_counter() - t0) * 1e3, 3),
                }, None

            def _do_reload(self) -> None:
                try:
                    obj = self._read_json()
                    path = obj.get("checkpoint") \
                        if isinstance(obj, dict) else None
                    if not path or not isinstance(path, str):
                        self._reply(400, {
                            "error": "reload body needs "
                                     "{\"checkpoint\": \"path\"}"})
                        return
                except _BodyTooLarge:
                    self._reply(413, {"error": "reload body too large"})
                    return
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                # the single server's trust boundary, one implementation
                denied = reload_request_denied(path, router.serving,
                                               self.client_address[0])
                if denied:
                    self._reply(403, {"error": denied})
                    return
                from hydragnn_tpu.serve.engine import ReloadValidationError

                try:
                    report = router.fleet.rolling_reload(path)
                except FileNotFoundError:
                    self._reply(404, {"error": f"no checkpoint at {path}"})
                    return
                except ReloadValidationError as e:
                    self._reply(409, {"status": "rolled_back",
                                      "error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001
                    self._reply(500, {"error": repr(e)})
                    return
                self._reply(200, {"status": "ok", **report})

        return _RouterHTTPServer(
            (self.serving.host, int(self.serving.port)), Handler)

    def build_request(self, obj: Dict[str, Any]) -> PredictRequest:
        """Parse/validate once at the router (in-process fleets), or
        package the raw body for proxying (subprocess fleets).  The
        optional ``model`` field selects the tenant; whether the fleet
        hosts it is decided at dispatch (UnknownTenantError -> 404)."""
        tenant = DEFAULT_TENANT
        if isinstance(obj, dict) and "model" in obj:
            tenant = obj["model"]
            if not isinstance(tenant, str) or not tenant:
                raise ValueError('"model" must be a non-empty string')
        if self._parse:
            sample = sample_from_json(
                obj, self.cfg,
                edge_length_norm=self.serving.edge_length_norm,
                pbc=self.pbc,
                build_max_neighbours=self.serving.edge_build_max_neighbours)
            body = None
            if self.fleet.replicas[0].kind == "subprocess":
                body = json.dumps(obj).encode()
            return PredictRequest(sample=sample, body=body,
                                  num_nodes=int(sample.num_nodes),
                                  tenant=tenant)
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        n = len(obj.get("x") or ())
        return PredictRequest(body=json.dumps(obj).encode(), num_nodes=n,
                              tenant=tenant)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        """Start the replicas (supervised), then accept traffic."""
        self.fleet.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="fleet-router",
            daemon=True)
        self._serve_thread.start()
        self.telemetry.health("serve_start", port=self.port,
                              replicas=len(self.fleet.replicas))
        return self

    def shutdown(self, drain: bool = True) -> None:
        if self._stopped:
            return
        self._stopped = True
        if self._serve_thread is not None:
            # shutdown() handshakes with serve_forever — calling it
            # with no serve loop running would block forever
            self.httpd.shutdown()
            self._serve_thread.join(timeout=5.0)
        self.httpd.server_close()
        self.fleet.stop(drain=drain)
        self.telemetry.health("serve_drain", drained=bool(drain))

    def run(self, poll_s: float = 0.05) -> None:
        """Blocking serve loop with the shared SIGTERM/SIGINT graceful
        drain (resilience/preempt.py) — same contract as the single
        server's run()."""
        from hydragnn_tpu.resilience import PreemptionHandler

        handler = PreemptionHandler(cross_rank=False).install()
        try:
            # start() inside the try: a replica failing to come up must
            # still tear the rest down (FleetSupervisor.start cleans its
            # own partial state; shutdown() handles the never-started
            # serve thread)
            self.start()
            while not handler.poll():
                time.sleep(poll_s)
        finally:
            handler.uninstall()
            self.shutdown(drain=True)

    # -- introspection -------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        snap = self.fleet.snapshot()
        live, total = snap["live"], snap["total"]
        status = "ok" if live == total else (
            "empty" if live == 0 else "degraded")
        return {
            "status": status,
            "uptime_s": round(time.time() - self._t0, 3),
            "live": live,
            "total": total,
            "quorum": snap["quorum"],
            "below_quorum": snap["below_quorum"],
            "replicas": [{"replica": s["replica"], "state": s["state"],
                          "restarts": s["restarts"]}
                         for s in snap["replicas"]],
        }

    def metrics(self) -> Dict[str, Any]:
        snap = self.fleet.snapshot()
        with self._lock:
            router = dict(self._n)
            per_replica = {str(k): v
                           for k, v in sorted(self._per_replica.items())}
            per_tenant = {k: dict(v)
                          for k, v in sorted(self._per_tenant.items())}
            tenant_out = {k: v for k, v in
                          sorted(self._tenant_out.items()) if v}
        cache = dict(snap["cache"])
        total = cache["hits"] + cache["misses"]
        cache["hit_rate"] = (cache["hits"] / total) if total else 1.0
        autoscale = {"signal": "drain_rate_rps_sum",
                     "value": snap["drain_rate_rps_sum"],
                     "queued": snap.get("queue_depth_sum", 0.0),
                     "live": snap["live"]}
        if "autoscaler" in snap:
            # the closed loop's policy state: thresholds, hysteresis
            # counters, cooldown — ROADMAP item 3's consumer
            autoscale["policy"] = snap["autoscaler"]
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "fleet": snap,
            # fleet-aggregated cache totals under the same key the
            # single server uses, so tools/servebench.py --url reads
            # one shape from either front end
            "engine": cache,
            "router": {**router, "per_replica_200": per_replica},
            # the autoscaling signal (ROADMAP item 1): fleet service
            # capacity as the sum of per-replica drain-rate EWMAs
            "autoscale": autoscale,
            "tenancy": {
                "per_tenant": per_tenant,
                "outstanding": tenant_out,
                "budget_frac": float(self.serving.tenant_budget_frac),
                "hot": sorted(getattr(self.fleet, "hot_tenants", ())),
            },
            "health_events": self.telemetry.health_counts,
            # span-latency breakdown at the fleet edge (request-level
            # percentiles when the flight recorder is on; {} otherwise —
            # same always-present contract as the single server)
            "spans": (self.telemetry.spans.percentiles()
                      if getattr(self.telemetry, "spans", None)
                      is not None else {}),
        }
