"""Supervised replica fleet: N engine+batcher workers behind one router.

The single-server stack (serve/server.py) fails as a unit: one wedged
predict or one SIGKILL takes the whole service down, and the circuit
breaker (PR 5) can only fail *fast*, not fail *over*.  This module adds
the process-supervision layer the ROADMAP's "millions of users" north
star needs:

- **Replicas.**  Each replica is a full engine + micro-batcher +
  circuit breaker.  :class:`SubprocessReplica` runs one as a child
  process (``python -m hydragnn_tpu.serve --port P``) — the production
  topology, where a crash is a real SIGKILL and isolation is the OS's.
  :class:`InProcessReplica` runs one as threads in this process — the
  CPU/test topology, where N replicas share ONE compiled-executable
  cache via :meth:`InferenceEngine.fork` (structurally identical
  replicas must not pay N AOT warmups) and a "kill" is the SIGKILL
  analog: in-flight work fails (the router retries it elsewhere) and
  the worker goes away without drain.

- **Supervision.**  :class:`FleetSupervisor` owns the replicas and runs
  a probe loop (``Serving.fleet_probe_s``): dead replicas (process
  exit, worker-thread exit, chaos kill) are restarted with exponential
  backoff (``fleet_restart_backoff_s`` doubling up to
  ``fleet_restart_backoff_max_s``, reset after a quiet
  ``fleet_restart_window_s``) under a restart-storm cap
  (``fleet_max_restarts`` restarts within the window marks the replica
  ``failed`` — a crash-looping replica must not burn the fleet's
  attention forever); replicas whose breaker is OPEN are ejected from
  routing and re-admitted once the cooldown elapses, so the next routed
  request is the breaker's half-open probe — the PR 5 state machine,
  reused per replica rather than reinvented.

- **Drain-and-replace.**  :meth:`FleetSupervisor.drain_and_replace`
  recycles a live replica with zero dropped requests: stop routing
  (state ``draining``), wait for the router's outstanding count to hit
  zero, graceful-stop (the batcher answers everything queued), start a
  fresh incarnation.

- **Rolling reload.**  :meth:`FleetSupervisor.rolling_reload` fans the
  PR 5 hot-reload machinery fleet-wide, ONE replica at a time (>= N-1
  replicas keep serving throughout): each replica validates the
  candidate against its own golden batch and swaps atomically; a
  validation failure on the first replica aborts before any other
  replica is touched, and a failure later rolls the already-swapped
  replicas back.  The per-replica breaker probation (a trip shortly
  after a swap auto-rolls that replica back) stays armed as usual.

Fault injection: :class:`~hydragnn_tpu.resilience.chaos.FleetChaos`
(``HYDRAGNN_CHAOS_REPLICA_KILL`` / ``_HANG`` / ``_FLAP``) is consulted
once per probe tick, so every failover path above is exercised by
tests (tests/test_serve_fleet.py) and by the chaos-kill bench
(``tools/servebench.py --fleet``), not just by argument.

Telemetry: ``fleet_start`` / ``replica_start`` / ``replica_dead`` /
``replica_restart`` / ``replica_eject`` / ``replica_readmit`` /
``replica_drain`` / ``rolling_reload_start`` / ``rolling_reload_ok`` /
``rolling_reload_rollback`` / ``fleet_degraded`` health events through
the shared MetricsLogger (docs/TELEMETRY.md "Fleet events").
"""

from __future__ import annotations

import collections
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import http.client
import urllib.error
import urllib.request
# py3.10: concurrent.futures.TimeoutError is not yet the builtin one
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from hydragnn_tpu.resilience.breaker import BreakerOpenError, CircuitBreaker
from hydragnn_tpu.serve.batcher import (
    MicroBatcher,
    PredictTimeoutError,
    QueueFullError,
    RequestShedError,
)
from hydragnn_tpu.serve.config import DEFAULT_TENANT, ServingConfig


class ReplicaDeadError(RuntimeError):
    """The replica died under this request (SIGKILL, worker exit,
    connection reset) — the router retries on a DIFFERENT replica."""


class UnknownTenantError(Exception):
    """The request names a model this fleet does not host (HTTP 404).
    Never failed over: every replica hosts the same tenant set, so a
    second replica would only repeat the answer."""


@dataclass
class PredictRequest:
    """One parsed-and-validated /predict request as the router hands it
    to a replica: ``sample`` drives in-process dispatch, ``body`` (the
    JSON-encoded graph) drives the subprocess HTTP proxy — the deadline
    always travels separately as the REMAINING budget, so a retried
    request never re-spends time a previous replica already burned.
    ``tenant`` is the request's ``model`` field (default tenant when
    absent): in-process replicas dispatch to that tenant's batcher,
    subprocess replicas forward the body and let the child resolve it."""

    sample: Any = None          # GraphSample (in-process replicas)
    body: Optional[bytes] = None  # raw JSON body (subprocess replicas)
    num_nodes: int = 0
    tenant: str = DEFAULT_TENANT
    # trace identity (telemetry.trace.SpanContext): set once at the
    # router edge and carried across failover retries, so the SAME
    # trace_id reaches whichever replica finally answers
    trace: Any = None


def free_port() -> int:
    """An ephemeral port for a subprocess replica to bind."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ReplicaChaos:
    """Per-replica chaos slot threaded into the batcher at construction:
    delegates to an optional inner :class:`ServeChaos` and lets the
    fleet layer wedge (hang) or kill the predict path of ONE incarnation
    at runtime.  Runs inside the batcher's watchdog thread, so a hang is
    detected by the predict watchdog -> breaker -> ejection chain, not
    by magic."""

    def __init__(self, inner=None):
        self.inner = inner
        self._dead = False
        self._hang: Optional[threading.Event] = None

    def kill(self) -> None:
        self._dead = True
        self.release()

    def hang(self) -> None:
        if self._hang is None:
            self._hang = threading.Event()

    def release(self) -> None:
        """Unwedge a hung predict (replica recycle): the blocked thread
        wakes and fails its stale flush instead of sleeping forever."""
        h, self._hang = self._hang, None
        if h is not None:
            h.set()

    def on_predict(self) -> None:
        h = self._hang
        if h is not None:
            # wedged until the supervisor recycles this incarnation (the
            # bounded wait is a leak guard, not a behavior knob)
            h.wait(timeout=600.0)
            raise ReplicaDeadError("replica predict path was wedged "
                                   "(chaos hang) and the replica recycled")
        if self._dead:
            raise ReplicaDeadError("replica is dead (chaos kill)")
        if self.inner is not None:
            self.inner.on_predict()

    def on_reload_state(self, state):
        if self.inner is not None:
            return self.inner.on_reload_state(state)
        return state


class InProcessReplica:
    """One engine + batcher + breaker as threads in this process — the
    CPU and test topology (docs/SERVING.md "Replica fleet").

    ``engine_factory`` builds (or forks) the replica's engine per
    incarnation; a factory returning :meth:`InferenceEngine.fork` of a
    warmed base engine gives N replicas one shared compile cache and
    near-free restarts.  ``chaos_factory`` (optional) supplies a fresh
    inner ServeChaos per incarnation — per-replica fault injection for
    the breaker/ejection tests.

    ``tenant_factories`` (optional) maps extra model names to engine
    factories: a request whose ``model`` field names one dispatches to
    that tenant's OWN engine + micro-batcher, built lazily on first use
    and kept in a bounded LRU (``Serving.max_tenants`` resident per
    replica, default tenant included and never evicted).  Tenant
    factories are usually :meth:`InferenceEngine.fork` closures too —
    structurally identical tenants share the compiled cache, so
    admission and re-admission after eviction cost zero compiles; a
    factory may ``reload_state`` different weights or carry its own
    autotuned bucket ladder.  Tenant batchers share the replica's
    breaker and chaos slot: replica-level failure semantics stay whole.
    """

    kind = "inprocess"

    def __init__(self, idx: int, engine_factory: Callable[[], Any],
                 serving: ServingConfig, telemetry,
                 chaos_factory: Optional[Callable[[], Any]] = None,
                 tenant_factories: Optional[
                     Dict[str, Callable[[], Any]]] = None):
        self.idx = int(idx)
        self._engine_factory = engine_factory
        self._chaos_factory = chaos_factory
        self.serving = serving
        self.telemetry = telemetry
        self.state = "stopped"
        self.restarts = 0
        self.port: Optional[int] = None
        self.engine = None
        self.batcher: Optional[MicroBatcher] = None
        self.breaker: Optional[CircuitBreaker] = None
        self.chaos: Optional[_ReplicaChaos] = None
        self.outstanding = 0
        self._out_lock = threading.Lock()
        self._tenant_factories = dict(tenant_factories or {})
        # resident non-default tenants, LRU order (oldest first)
        self._tenants: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._tenant_lock = threading.Lock()
        self.tenant_evictions = 0

    # -- lifecycle -----------------------------------------------------------

    def _set_state(self, state: str) -> None:
        # lifecycle transitions ride the outstanding-counter lock: the
        # supervisor probe loop, the router's mark_dead and the rolling
        # reload all write replica state from different threads
        with self._out_lock:
            self.state = state

    def start(self) -> None:
        self._set_state("starting")
        self.chaos = _ReplicaChaos(
            self._chaos_factory() if self._chaos_factory else None)
        self.engine = self._engine_factory()
        # forks arrive warmed (shared compile cache + copied golden);
        # a fresh engine pays the one AOT warmup here
        if self.engine._golden is None:
            self.engine.warmup()
        s = self.serving
        self.breaker = CircuitBreaker(
            threshold=s.breaker_threshold, cooldown_s=s.breaker_cooldown_s,
            what=f"replica{self.idx}", telemetry=self.telemetry,
            on_open=self._on_breaker_open)
        self.batcher = MicroBatcher(
            self.engine, max_wait_ms=s.max_wait_ms, max_queue=s.max_queue,
            telemetry=self.telemetry,
            default_deadline_ms=s.request_deadline_ms,
            predict_timeout_s=s.predict_timeout_s, breaker=self.breaker,
            chaos=self.chaos).start()
        with self._tenant_lock:
            self._tenants = collections.OrderedDict()
        self._set_state("live")

    def _on_breaker_open(self) -> None:
        # same probation rule as the single server: a breaker trip right
        # after a hot reload rolls THIS replica's checkpoint back
        if self.engine is not None and self.engine.in_probation(
                self.serving.reload_probation_s):
            if self.engine.rollback(reason="breaker_trip"):
                self.breaker.reset(to="half_open")

    def stop(self, drain: bool = True) -> None:
        if self.chaos is not None:
            self.chaos.release()
        for _, batcher in self._drop_tenants():
            batcher.close(drain=drain,
                          timeout=self.serving.drain_timeout_s)
        if self.batcher is not None:
            self.batcher.close(drain=drain,
                               timeout=self.serving.drain_timeout_s)
        self._set_state("stopped")

    def restart(self) -> None:
        """Recycle: tear the old incarnation down hard, start fresh."""
        self.stop(drain=False)
        self.restarts += 1
        self.start()

    def kill(self) -> None:
        """The SIGKILL analog: every in-flight and queued request FAILS
        (the router retries them on other replicas) and the worker goes
        away without drain.  The STATE transition stays with the
        supervisor (mark_dead schedules the backoff restart) — exactly
        like a real SIGKILL, which the victim never observes."""
        if self.chaos is not None:
            self.chaos.kill()
        for _, batcher in self._drop_tenants():
            batcher.close(drain=False)
        if self.batcher is not None:
            self.batcher.close(drain=False)

    def hang(self) -> None:
        """Wedge the predict path: the watchdog (predict_timeout_s) must
        time the flushes out and the breaker must eject the replica."""
        if self.chaos is not None:
            self.chaos.hang()

    # -- probes --------------------------------------------------------------

    def alive(self) -> bool:
        b = self.batcher
        if b is None or not b.worker_alive():
            return False
        return not (self.chaos is not None and self.chaos._dead)

    def probe(self) -> str:
        """Liveness + breaker verdict: ``ok`` / ``open`` / ``dead``.
        Half-open is NOT reported as open — the breaker's recovery probe
        needs traffic, so a half-open replica stays routable."""
        if not self.alive():
            return "dead"
        if self.breaker is not None and self.breaker.state == "open":
            return "open"
        return "ok"

    def ready_to_readmit(self) -> bool:
        """An ejected replica re-enters routing once its breaker
        cooldown has elapsed — the next routed flush is the half-open
        probe that decides recovery."""
        return self.breaker is not None \
            and self.breaker.time_to_retry() == 0.0

    # -- routing hooks -------------------------------------------------------

    def inc_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding += 1

    def dec_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding = max(0, self.outstanding - 1)

    def retry_after_s(self) -> float:
        b = self.batcher
        return b.retry_after_s() if b is not None else 1.0

    # -- tenancy -------------------------------------------------------------

    def tenants(self) -> List[str]:
        """Every model name this replica can serve (resident or not)."""
        return [DEFAULT_TENANT] + sorted(self._tenant_factories)

    def _drop_tenants(self) -> List[Any]:
        """Detach the whole tenant pool (stop/kill paths); the caller
        closes the returned (name, batcher) pairs outside the lock."""
        with self._tenant_lock:
            tenants, self._tenants = self._tenants, \
                collections.OrderedDict()
        return [(name, batcher) for name, (_, batcher)
                in tenants.items()]

    def _tenant_batcher(self, name: str) -> MicroBatcher:
        """The batcher serving tenant ``name``, building it on first
        use and evicting the least-recently-used extra tenant beyond
        ``max_tenants`` (eviction is cheap to undo — forks share the
        compiled cache, so re-admission recompiles nothing)."""
        if name == DEFAULT_TENANT:
            return self.batcher
        factory = self._tenant_factories.get(name)
        if factory is None:
            raise UnknownTenantError(
                f"unknown model {name!r} (hosted: {self.tenants()})")
        s = self.serving
        evicted: List[Any] = []
        with self._tenant_lock:
            ent = self._tenants.get(name)
            if ent is not None:
                self._tenants.move_to_end(name)
                return ent[1]
            engine = factory()
            if engine._golden is None:
                engine.warmup()
            batcher = MicroBatcher(
                engine, max_wait_ms=s.max_wait_ms,
                max_queue=s.max_queue, telemetry=self.telemetry,
                default_deadline_ms=s.request_deadline_ms,
                predict_timeout_s=s.predict_timeout_s,
                breaker=self.breaker, chaos=self.chaos).start()
            self._tenants[name] = (engine, batcher)
            # default tenant occupies one resident slot but lives
            # outside the pool; at least one extra stays admittable
            cap = max(1, int(s.max_tenants) - 1)
            while len(self._tenants) > cap:
                old, (_, ob) = self._tenants.popitem(last=False)
                evicted.append((old, ob))
        for old, ob in evicted:
            # short drain: the LRU tenant is idle by construction, and
            # anything still queued fails over to a replica that will
            # rebuild it
            ob.close(drain=True, timeout=1.0)
            self.tenant_evictions += 1
            self.telemetry.health("tenant_evict", replica=self.idx,
                                  tenant=old,
                                  resident=len(self._tenants) + 1)
        return batcher

    def predict(self, req: PredictRequest,
                deadline_s: Optional[float]) -> Dict[str, Any]:
        """One attempt on THIS replica; shed/breaker/timeout/dead errors
        propagate for the router to map or fail over."""
        fut = self._tenant_batcher(req.tenant).submit(
            req.sample, deadline_s=deadline_s, trace=req.trace)
        if deadline_s is None:
            wait = 30.0
        else:
            # the request's own budget plus the worst predict it could
            # sit behind (same rule as InferenceServer._wait_s)
            wait = deadline_s + max(1.0, self.serving.predict_timeout_s)
        try:
            res = fut.result(timeout=wait)
        except (_FutureTimeout, TimeoutError):
            # abandoning the wait to fail over: cancel the queued entry
            # so this replica doesn't burn a bucket slot computing an
            # answer nobody reads (the batcher skips done futures)
            fut.cancel()
            raise
        return {name: np.asarray(arr).tolist() for name, arr in res.items()}

    # -- control -------------------------------------------------------------

    def reload(self, path: str) -> Dict[str, Any]:
        return self.engine.reload_from_checkpoint(
            path, chaos=self.chaos, source="rolling")

    def rollback(self) -> bool:
        return self.engine.rollback(reason="rolling_reload")

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replica": self.idx,
            "kind": self.kind,
            "state": self.state,
            "restarts": self.restarts,
            "outstanding": self.outstanding,
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.snapshot()
        if self.batcher is not None:
            st = self.batcher.stats()
            out["queue_depth"] = st["queue_depth"]
            out["drain_rate_rps"] = st["drain_rate_rps"]
            out["requests"] = st["requests"]
            out["batches"] = st["batches"]
            # resident tenant batchers contribute to the replica's load
            # signal — the autoscaler and the admission budgets must see
            # EVERY queue, not just the default tenant's
            with self._tenant_lock:
                extras = list(self._tenants.items())
            for _, (_, batcher) in extras:
                ts = batcher.stats()
                out["queue_depth"] += ts["queue_depth"]
                out["drain_rate_rps"] += ts["drain_rate_rps"]
                out["requests"] += ts["requests"]
                out["batches"] += ts["batches"]
            out["tenants_resident"] = \
                [DEFAULT_TENANT] + [name for name, _ in extras]
            out["tenant_evictions"] = self.tenant_evictions
        if self.engine is not None:
            out["reload"] = self.engine.reload_stats()
            cache = self.engine.cache_stats()
            out["cache"] = {k: cache[k] for k in
                            ("hits", "misses", "warmup_compiles")}
        return out


class SubprocessReplica:
    """One replica as a child ``python -m hydragnn_tpu.serve`` process —
    the production topology: a crash is a real SIGKILL, a hang is a real
    SIGSTOP, and memory/device isolation is the operating system's.

    ``argv_builder(port)`` returns the child's command line; the
    supervisor assigns an ephemeral port per incarnation and waits for
    ``/healthz`` before admitting the replica to routing.  The child
    env gets ``HYDRAGNN_SERVE_FLEET=0`` so a fleet-configured config
    can never recurse into fleets of fleets.
    """

    kind = "subprocess"

    def __init__(self, idx: int, argv_builder: Callable[[int], List[str]],
                 serving: ServingConfig, telemetry,
                 env: Optional[Dict[str, str]] = None):
        self.idx = int(idx)
        self._argv_builder = argv_builder
        self.serving = serving
        self.telemetry = telemetry
        self._env = dict(env if env is not None else os.environ)
        self._env["HYDRAGNN_SERVE_FLEET"] = "0"
        self.state = "stopped"
        self.restarts = 0
        self.port: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None
        self.outstanding = 0
        self._out_lock = threading.Lock()
        self._last_health: Dict[str, Any] = {}

    # -- lifecycle -----------------------------------------------------------

    def _set_state(self, state: str) -> None:
        # same contract as InProcessReplica._set_state: lifecycle
        # transitions are written from supervisor, router and reload
        # threads — they ride the outstanding-counter lock
        with self._out_lock:
            self.state = state

    def start(self) -> None:
        self._set_state("starting")
        self.port = free_port()
        self._proc = subprocess.Popen(self._argv_builder(self.port),
                                      env=self._env)
        deadline = time.monotonic() + self.serving.fleet_startup_timeout_s
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                self._set_state("dead")
                raise ReplicaDeadError(
                    f"replica {self.idx} exited with rc "
                    f"{self._proc.returncode} during startup")
            try:
                if self._get("/healthz", timeout=2.0) is not None:
                    self._set_state("live")
                    return
            except (OSError, ValueError,
                    http.client.HTTPException):  # not up / partial body
                pass
            time.sleep(0.2)
        self._set_state("dead")
        raise ReplicaDeadError(
            f"replica {self.idx} did not become healthy within "
            f"{self.serving.fleet_startup_timeout_s:.0f} s")

    def stop(self, drain: bool = True) -> None:
        p = self._proc
        if p is not None and p.poll() is None:
            try:
                # SIGCONT first: a SIGSTOPped (chaos-hung) child cannot
                # handle the SIGTERM drain
                p.send_signal(signal.SIGCONT)
                p.send_signal(signal.SIGTERM if drain else signal.SIGKILL)
                p.wait(timeout=self.serving.drain_timeout_s + 5.0
                       if drain else 5.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5.0)
        self._set_state("stopped")

    def restart(self) -> None:
        self.stop(drain=False)
        self.restarts += 1
        self.start()

    def kill(self) -> None:
        # the state transition stays with the supervisor (mark_dead)
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()  # SIGKILL — the real thing

    def hang(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.send_signal(signal.SIGSTOP)

    # -- probes --------------------------------------------------------------

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def probe(self) -> str:
        if not self.alive():
            return "dead"  # process exit: definitive, no tolerance
        try:
            h = self._get("/healthz", timeout=2.0)
        except (OSError, ValueError,
                http.client.HTTPException):  # slow or wedged (SIGSTOP)
            # NOT "dead": one missed 2 s probe on a busy-but-healthy
            # child must not SIGKILL its whole queue — the supervisor
            # requires consecutive misses before declaring death
            return "unresponsive"
        self._last_health = h or {}
        br = (h or {}).get("breaker") or {}
        return "open" if br.get("state") == "open" else "ok"

    def ready_to_readmit(self) -> bool:
        try:
            h = self._get("/healthz", timeout=2.0)
        except (OSError, ValueError,
                http.client.HTTPException):  # child gone / partial body
            return False
        br = (h or {}).get("breaker") or {}
        return br.get("state") != "open" \
            or float(br.get("time_to_retry_s", 1.0)) == 0.0

    # -- routing hooks -------------------------------------------------------

    def inc_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding += 1

    def dec_outstanding(self) -> None:
        with self._out_lock:
            self.outstanding = max(0, self.outstanding - 1)

    def retry_after_s(self) -> float:
        return 1.0

    def _url(self, path: str) -> str:
        return f"http://127.0.0.1:{self.port}{path}"

    def _get(self, path: str, timeout: float = 10.0):
        with urllib.request.urlopen(self._url(path), timeout=timeout) as r:
            return json.loads(r.read())

    def predict(self, req: PredictRequest,
                deadline_s: Optional[float]) -> Dict[str, Any]:
        """Proxy one attempt to the child's /predict.  The REMAINING
        budget rides the ``X-Timeout-Ms`` header, which wins over any
        (stale) ``timeout_ms`` field in the forwarded body."""
        headers = {"Content-Type": "application/json"}
        if req.trace is not None:
            # the trace identity crosses the process boundary as the
            # X-Request-Id header — the child adopts it, so its JSONL
            # spans carry the router's trace_id (one id, whole story)
            headers["X-Request-Id"] = req.trace.trace_id
        wait = 30.0
        if deadline_s is not None:
            headers["X-Timeout-Ms"] = str(max(0.0, deadline_s * 1e3))
            wait = deadline_s + max(1.0, self.serving.predict_timeout_s)
        request = urllib.request.Request(self._url("/predict"),
                                         data=req.body, headers=headers)
        try:
            with urllib.request.urlopen(request, timeout=wait) as r:
                return json.loads(r.read())["heads"]
        except urllib.error.HTTPError as e:
            raise _error_from_status(e) from None
        except urllib.error.URLError as e:
            raise ReplicaDeadError(
                f"replica {self.idx} unreachable: {e.reason!r}") from None
        except (ConnectionError, socket.timeout, TimeoutError) as e:
            raise ReplicaDeadError(
                f"replica {self.idx} connection failed: {e!r}") from None

    # -- control -------------------------------------------------------------

    def reload(self, path: str) -> Dict[str, Any]:
        from hydragnn_tpu.serve.engine import ReloadValidationError

        body = json.dumps({"checkpoint": path}).encode()
        request = urllib.request.Request(
            self._url("/reload"), data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(request, timeout=120.0) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read())
            except (OSError, ValueError,
                    http.client.HTTPException):  # unreadable / not JSON
                pass
            if e.code == 409:
                raise ReloadValidationError(
                    payload.get("error", "candidate rejected")) from None
            if e.code == 404:
                raise FileNotFoundError(
                    payload.get("error", path)) from None
            raise RuntimeError(
                f"replica {self.idx} reload failed: "
                f"{e.code} {payload.get('error')}") from None

    def rollback(self) -> bool:
        """POST /rollback on the child: restore its retained pre-reload
        state (the rolling-reload abort path — a later replica rejected
        the candidate this one already swapped in)."""
        request = urllib.request.Request(
            self._url("/rollback"), data=b"{}",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(request, timeout=30.0) as r:
                return json.loads(r.read()).get("status") == "rolled_back"
        except (OSError, ValueError,
                http.client.HTTPException):  # nothing retained / gone
            return False

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "replica": self.idx,
            "kind": self.kind,
            "state": self.state,
            "restarts": self.restarts,
            "outstanding": self.outstanding,
            "port": self.port,
            "pid": self._proc.pid if self._proc is not None else None,
        }
        try:
            m = self._get("/metrics", timeout=2.0)
            out["breaker"] = m.get("breaker")
            bat = m.get("batcher") or {}
            out["queue_depth"] = bat.get("queue_depth")
            out["drain_rate_rps"] = bat.get("drain_rate_rps", 0.0)
            out["requests"] = bat.get("requests")
            out["batches"] = bat.get("batches")
            out["reload"] = m.get("reload")
            eng = m.get("engine") or {}
            out["cache"] = {k: int(eng.get(k, 0)) for k in
                            ("hits", "misses", "warmup_compiles")}
        except Exception:  # graftlint: disable=ROB001 (dead/hung child: snapshot degrades to states only)
            pass
        return out


def _error_from_status(e: "urllib.error.HTTPError") -> Exception:
    """Map a child replica's HTTP error onto the SAME exception types the
    in-process dispatch raises, so the router's failover logic has one
    vocabulary."""
    try:
        payload = json.loads(e.read())
    except (OSError, ValueError,
            http.client.HTTPException):  # body unreadable / not JSON
        payload = {}
    msg = str(payload.get("error", f"replica returned {e.code}"))
    retry = float(e.headers.get("Retry-After", 1.0) or 1.0)
    if e.code == 429:
        return RequestShedError(msg, retry_after_s=retry)
    if e.code == 503:
        if payload.get("breaker") == "open":
            return BreakerOpenError(msg, retry_after_s=retry)
        return QueueFullError(msg)
    if e.code == 504:
        return PredictTimeoutError(msg)
    if e.code == 413:
        from hydragnn_tpu.serve.engine import BucketOverflowError

        return BucketOverflowError(msg)
    if e.code == 404:
        # the child is a single-model server: an unknown "model" field
        # 404s there, and no sibling replica would answer differently
        return UnknownTenantError(msg)
    if e.code == 400:
        return ValueError(msg)
    return RuntimeError(f"replica error {e.code}: {msg}")


class FleetSupervisor:
    """Owns the replica pool: health probing, backoff restarts under a
    storm cap, breaker-driven ejection/readmission, drain-and-replace,
    and rolling fleet reload (module docstring for the full story)."""

    # consecutive unresponsive /healthz probes before a live replica is
    # declared dead (process exit is always immediate)
    UNRESPONSIVE_PROBES = 3

    def __init__(self, replicas: List[Any], serving: ServingConfig,
                 telemetry=None, chaos=None, replica_factory=None,
                 autoscaler=None):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        self.replicas = list(replicas)
        self.serving = serving
        if telemetry is None:
            from hydragnn_tpu.telemetry import MetricsLogger

            telemetry = MetricsLogger.disabled()
        self.telemetry = telemetry
        self.chaos = chaos  # resilience.chaos.FleetChaos or None
        # closed-loop autoscaling (serve/autoscale.py): with a factory
        # for fresh replicas and fleet_max_replicas > 0, the probe loop
        # evaluates the drain-rate policy once per tick
        self._replica_factory = replica_factory
        if autoscaler is None and replica_factory is not None \
                and int(serving.fleet_max_replicas) > 0:
            from hydragnn_tpu.serve.autoscale import FleetAutoscaler

            autoscaler = FleetAutoscaler(serving)
        self.autoscaler = autoscaler
        # tenants the chaos layer marked hot THIS tick: the router sheds
        # their traffic (429) as if their budget were exhausted
        self.hot_tenants: set = set()
        self._scale_fail_next = False
        # last probe tick's load signal, cached for the router's
        # per-tenant budget math (zero until the first armed tick)
        self.last_queue_depth = 0.0
        self.last_drain_rate = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        base = max(0.05, serving.fleet_restart_backoff_s)
        self._base_backoff = base
        self._backoff: Dict[int, float] = {}
        self._restart_at: Dict[int, float] = {}
        self._last_restart: Dict[int, float] = {}
        self._restart_times: Dict[int, collections.deque] = {}
        self._rr = 0  # chaos target round-robin cursor
        self._was_degraded = False
        self._rolling_lock = threading.Lock()
        # consecutive "unresponsive" probe verdicts per replica (a slow
        # /healthz is not death; this many in a row is)
        self._unresponsive: Dict[int, int] = {}
        # the fleet's desired checkpoint: set by a successful rolling
        # reload so replicas that restart (from the ORIGINAL weights)
        # or rejoin later are brought onto the same version instead of
        # silently serving stale predictions
        self._desired_ckpt: Optional[str] = None
        self._reload_gen = 0
        self._replica_gen: Dict[int, int] = {}

    # -- lifecycle -----------------------------------------------------------

    @property
    def quorum(self) -> int:
        q = int(self.serving.fleet_quorum)
        return q if q > 0 else len(self.replicas) // 2 + 1

    def start(self) -> "FleetSupervisor":
        started: List[Any] = []
        try:
            for r in self.replicas:
                r.start()
                started.append(r)
                self.telemetry.health("replica_start", replica=r.idx,
                                      port=r.port or 0,
                                      restarts=r.restarts)
        except Exception:
            # partial startup must not leak live replicas (subprocess
            # mode: orphaned jax children holding memory and ports)
            for r in started:
                try:
                    r.stop(drain=False)
                except Exception:  # graftlint: disable=ROB001 (best-effort teardown of a failed partial startup)
                    pass
            raise
        self.telemetry.health("fleet_start", replicas=len(self.replicas),
                              mode=self.replicas[0].kind,
                              quorum=self.quorum)
        t = threading.Thread(
            target=self._probe_loop, name="fleet-supervisor", daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self._stop.set()
        # swap the handle out under the lock, join OUTSIDE it (the probe
        # loop takes self._lock; joining while holding it would deadlock)
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        for r in self.replicas:
            try:
                r.stop(drain=drain)
            except Exception:  # graftlint: disable=ROB001 (best-effort teardown at fleet shutdown)
                pass

    # -- routing view --------------------------------------------------------

    def routable(self) -> List[Any]:
        return [r for r in self.replicas if r.state == "live"]

    def live_count(self) -> int:
        return len(self.routable())

    def mark_dead(self, r, reason: str) -> None:
        """Router- or probe-reported death: stop routing, schedule the
        backoff restart."""
        with self._lock:
            if r.state in ("dead", "failed", "restarting", "stopped"):
                return
            r.state = "dead"
            backoff = self._backoff.get(r.idx, self._base_backoff)
            self._restart_at[r.idx] = time.monotonic() + backoff
        self.telemetry.health("replica_dead", replica=r.idx, reason=reason)

    def eject(self, r, reason: str) -> None:
        """Breaker-driven ejection: the replica is alive but its predict
        path is circuit-broken — take it out of routing until the
        cooldown elapses (readmission makes the next routed flush the
        half-open probe)."""
        with self._lock:
            if r.state != "live":
                return
            r.state = "ejected"
        self.telemetry.health("replica_eject", replica=r.idx, reason=reason)

    # -- probe loop ----------------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.serving.fleet_probe_s):
            try:
                self.probe_once()
            except Exception as e:  # noqa: BLE001 — must survive a bad tick
                self.telemetry.health("fleet_probe_error",
                                      error=repr(e)[:200])

    def probe_once(self) -> None:
        """One supervision tick (public so tests and the bench can drive
        deterministic ticks): apply armed chaos, check every replica,
        update the quorum latch, evaluate the autoscaler."""
        if self.chaos is not None:
            hot: set = set()
            for action, idx in self.chaos.on_probe():
                if action == "tenant_hot":
                    # the target is a tenant NAME for this action
                    hot.add(idx if idx is not None else DEFAULT_TENANT)
                elif action == "scale_fail":
                    with self._lock:
                        self._scale_fail_next = True
                else:
                    self._apply_chaos(action, idx)
            self.hot_tenants = hot
        now = time.monotonic()
        for r in list(self.replicas):  # scale events mutate the list
            self._check(r, now)
        self._check_quorum()
        self._autoscale(now)

    def _apply_chaos(self, action: str, idx: Optional[int]) -> None:
        if idx is not None:
            target = self.replicas[idx] if 0 <= idx < len(self.replicas) \
                else None
        else:
            live = self.routable()
            if not live:
                return
            target = live[self._rr % len(live)]
            self._rr += 1
        if target is None:
            return
        if action in ("kill", "flap"):
            target.kill()
            self.mark_dead(target, reason=f"chaos_{action}")
        elif action == "hang":
            target.hang()

    def _check(self, r, now: float) -> None:
        st = r.state
        if st == "live":
            verdict = r.probe()
            if verdict == "unresponsive":
                # a busy-but-healthy replica can miss one 2 s probe —
                # only consecutive misses are death
                n = self._unresponsive.get(r.idx, 0) + 1
                self._unresponsive[r.idx] = n
                if n >= self.UNRESPONSIVE_PROBES:
                    self._unresponsive[r.idx] = 0
                    self.mark_dead(r, reason="unresponsive")
                return
            self._unresponsive[r.idx] = 0
            if verdict == "dead":
                self.mark_dead(r, reason="probe_dead")
            elif verdict == "open":
                self.eject(r, reason="breaker_open")
            elif not self._sync_checkpoint(r):
                # serving STALE weights (restarted/rejoined across a
                # rolling reload) and the re-reload failed: out of
                # routing until a sync succeeds
                with self._lock:
                    if r.state == "live":
                        r.state = "ejected"
            elif self._backoff.get(r.idx, 0.0) > self._base_backoff \
                    and now - self._last_restart.get(r.idx, now) \
                    > self.serving.fleet_restart_window_s:
                # survived a full window since its last restart: the
                # crash is over, forgive the accumulated backoff
                self._backoff[r.idx] = self._base_backoff
        elif st == "ejected":
            if not r.alive():
                self.mark_dead(r, reason="probe_dead")
            elif r.ready_to_readmit() and self._sync_checkpoint(r):
                with self._lock:
                    if r.state == "ejected":
                        r.state = "live"
                self.telemetry.health("replica_readmit", replica=r.idx)
        elif st == "dead":
            if now >= self._restart_at.get(r.idx, 0.0):
                self._try_restart(r, now)

    def _sync_checkpoint(self, r) -> bool:
        """Is ``r`` on the fleet's desired checkpoint (re-reloading it
        when a restart/rejoin left it behind a rolling reload)?  False
        means the caller must keep it out of routing — a mixed-version
        fleet answering from stale weights is a silent correctness bug,
        not a degraded mode."""
        if self._desired_ckpt is None \
                or self._replica_gen.get(r.idx, 0) == self._reload_gen:
            return True
        if not self._rolling_lock.acquire(blocking=False):
            # a rolling reload is in flight; it (or the next tick)
            # covers this replica
            return True
        try:
            gen = self._reload_gen
            try:
                r.reload(self._desired_ckpt)
            except Exception as e:  # noqa: BLE001 — keep it out of routing
                self.telemetry.health(
                    "replica_eject", replica=r.idx,
                    reason="stale_checkpoint", error=str(e)[:200])
                return False
            self._replica_gen[r.idx] = gen
            return True
        finally:
            self._rolling_lock.release()

    def _try_restart(self, r, now: float) -> None:
        if self._stop.is_set():
            # shutting down: a restart here would spawn a replica the
            # teardown sweep already missed (an orphaned jax child)
            return
        window = self.serving.fleet_restart_window_s
        times = self._restart_times.setdefault(
            r.idx, collections.deque())
        while times and now - times[0] > window:
            times.popleft()
        if len(times) >= self.serving.fleet_max_restarts:
            # restart storm: this replica is crash-looping — stop
            # burning supervision on it (operator attention required)
            with self._lock:
                r.state = "failed"
            self.telemetry.health(
                "replica_eject", replica=r.idx, reason="restart_storm",
                restarts_in_window=len(times))
            return
        with self._lock:
            r.state = "restarting"
        backoff = self._backoff.get(r.idx, self._base_backoff)
        try:
            r.restart()
        except Exception as e:  # noqa: BLE001 — keep backing off
            nxt = min(backoff * 2.0,
                      self.serving.fleet_restart_backoff_max_s)
            with self._lock:
                r.state = "dead"
                self._backoff[r.idx] = nxt
                self._restart_at[r.idx] = time.monotonic() + nxt
            self.telemetry.health("replica_dead", replica=r.idx,
                                  reason="restart_failed",
                                  error=repr(e)[:200])
            return
        if self._stop.is_set():
            # stop() raced the restart (its teardown sweep may have run
            # before this incarnation existed): don't leak it
            r.stop(drain=False)
            return
        times.append(now)
        with self._lock:
            self._last_restart[r.idx] = now
            self._backoff[r.idx] = min(
                backoff * 2.0, self.serving.fleet_restart_backoff_max_s)
        self.telemetry.health("replica_restart", replica=r.idx,
                              restarts=r.restarts,
                              backoff_s=round(backoff, 3))
        # a restart rebuilds from the ORIGINAL weights: the fresh
        # incarnation is NOT on any rolled-out generation (clear the
        # old incarnation's mark), so sync re-reloads the fleet's
        # desired checkpoint before it takes traffic
        self._replica_gen.pop(r.idx, None)
        if not self._sync_checkpoint(r):
            with self._lock:
                if r.state == "live":
                    r.state = "ejected"

    def _check_quorum(self) -> None:
        live = self.live_count()
        degraded = live < self.quorum
        if degraded and not self._was_degraded:
            self.telemetry.health("fleet_degraded", live=live,
                                  total=len(self.replicas),
                                  quorum=self.quorum)
        self._was_degraded = degraded

    # -- closed-loop autoscaling (serve/autoscale.py) ------------------------

    def _load_signal(self) -> "tuple":
        """(queued, drain_rate_rps) summed over routable replicas: the
        SAME numbers the admission shed divides, so the scaler and the
        shed agree about overload by construction."""
        queued = 0.0
        rate = 0.0
        for r in self.routable():
            s = r.snapshot()
            queued += float(s.get("queue_depth") or 0.0)
            rate += float(s.get("drain_rate_rps") or 0.0)
        return queued, rate

    def _autoscale(self, now: float) -> None:
        a = self.autoscaler
        want_scale = a is not None and a.enabled()
        # per-tenant budgets read the cached signal too — sampling it
        # here (once per tick) keeps the request path free of
        # per-request snapshot() calls
        want_budget = float(self.serving.tenant_budget_frac) > 0
        if not (want_scale or want_budget):
            return
        queued, rate = self._load_signal()
        self.last_queue_depth = queued
        self.last_drain_rate = rate
        if not want_scale:
            return
        decision = a.evaluate(queued, rate, self.live_count(), now)
        if decision is None:
            return
        if decision.direction == "up":
            self.scale_up(signal=decision.signal)
        else:
            self.scale_down(signal=decision.signal)

    def scale_up(self, signal: float = 0.0) -> bool:
        """Add one replica (autoscaler "up", public for tests/tools):
        build via the replica factory at the next free index, start it,
        admit it to routing.  A failed start enters the normal dead ->
        backoff-restart machinery instead of being retried inline — a
        scale-up must never turn into a spawn storm."""
        factory = self._replica_factory
        if factory is None:
            return False
        with self._lock:
            cap = int(self.serving.fleet_max_replicas)
            if cap > 0 and len(self.replicas) >= cap:
                return False
            idx = max(r.idx for r in self.replicas) + 1
            chaos_fail = self._scale_fail_next
            self._scale_fail_next = False
        r = factory(idx)
        try:
            r.start()
        except Exception as e:  # noqa: BLE001 — hand off to backoff restart
            with self._lock:
                r.state = "dead"
                self.replicas.append(r)
                self._restart_at[r.idx] = \
                    time.monotonic() + self._base_backoff
            self.telemetry.health("fleet_scale_up", replica=r.idx,
                                  signal=round(float(signal), 3),
                                  live=self.live_count(), ok=False,
                                  error=repr(e)[:200])
            return False
        with self._lock:
            self.replicas.append(r)
        if chaos_fail:
            # chaos: the fresh replica dies the moment it joins — the
            # backoff restart machinery must absorb it, and the cooldown
            # must keep the scaler from stacking more spawns on top
            r.kill()
            self.mark_dead(r, reason="chaos_scale_fail")
        self.telemetry.health("fleet_scale_up", replica=r.idx,
                              signal=round(float(signal), 3),
                              live=self.live_count(),
                              replicas=len(self.replicas))
        return True

    def scale_down(self, signal: float = 0.0) -> bool:
        """Retire one replica (autoscaler "down", public for tests and
        tools) with ZERO dropped requests: highest-index live replica
        leaves routing (state ``draining``), in-flight work completes,
        drain-stop answers everything queued, then it is removed from
        the pool entirely — the drain_and_replace discipline, minus the
        replacement."""
        with self._lock:
            live = [x for x in self.replicas if x.state == "live"]
            if len(live) <= max(1, int(self.serving.fleet_min_replicas)):
                return False
            r = max(live, key=lambda x: x.idx)
            r.state = "draining"
        self.telemetry.health("replica_drain", replica=r.idx)
        deadline = time.monotonic() + self.serving.fleet_drain_timeout_s
        while r.outstanding > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        r.stop(drain=True)
        with self._lock:
            self.replicas = [x for x in self.replicas if x is not r]
            for d in (self._backoff, self._restart_at,
                      self._last_restart, self._restart_times,
                      self._unresponsive, self._replica_gen):
                d.pop(r.idx, None)
        self.telemetry.health("fleet_scale_down", replica=r.idx,
                              signal=round(float(signal), 3),
                              live=self.live_count(),
                              replicas=len(self.replicas))
        return True

    # -- drain-and-replace ---------------------------------------------------

    def drain_and_replace(self, idx: int) -> bool:
        """Gracefully recycle replica ``idx``: stop routing to it, wait
        for in-flight work to finish, drain-stop, start fresh.  Zero
        dropped requests by construction; returns False when the
        replica was not live."""
        r = self.replicas[idx]
        with self._lock:
            if r.state != "live":
                return False
            r.state = "draining"
        self.telemetry.health("replica_drain", replica=r.idx)
        deadline = time.monotonic() + self.serving.fleet_drain_timeout_s
        while r.outstanding > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        r.stop(drain=True)
        r.restarts += 1
        r.start()
        self.telemetry.health("replica_restart", replica=r.idx,
                              restarts=r.restarts, backoff_s=0.0,
                              reason="drain_replace")
        # the fresh incarnation rebuilt from the original weights: put
        # it on the fleet's desired checkpoint before it takes traffic
        self._replica_gen.pop(r.idx, None)
        if not self._sync_checkpoint(r):
            with self._lock:
                if r.state == "live":
                    r.state = "ejected"
        return True

    # -- rolling reload ------------------------------------------------------

    def rolling_reload(self, path: str) -> Dict[str, Any]:
        """Fan a hot checkpoint reload fleet-wide, one replica at a
        time: each replica leaves rotation only for its own validate +
        swap (>= N-1 serving throughout).  A validation failure on the
        FIRST replica aborts before any other replica is touched; a
        failure later rolls the already-swapped replicas back.  Raises
        the failing replica's error (ReloadValidationError -> HTTP
        409)."""
        with self._rolling_lock:
            targets = [r for r in self.replicas if r.state == "live"]
            if not targets:
                raise ReplicaDeadError("no live replicas to reload")
            self.telemetry.health("rolling_reload_start",
                                  replicas=len(targets))
            done: List[Any] = []
            report: Dict[str, Any] = {}
            for r in targets:
                with self._lock:
                    if r.state != "live":
                        continue
                    r.state = "reloading"
                try:
                    report = r.reload(path)
                except Exception as e:  # noqa: BLE001 — abort + roll back
                    rolled = 0
                    for d in reversed(done):
                        if d.rollback():
                            rolled += 1
                    self.telemetry.health(
                        "rolling_reload_rollback", replica=r.idx,
                        swapped=len(done), rolled_back=rolled,
                        error=str(e)[:200])
                    raise
                finally:
                    with self._lock:
                        if r.state == "reloading":
                            r.state = "live"
                done.append(r)
            # the fleet's desired version from here on: replicas that
            # restart (from the original weights) or rejoin later are
            # re-reloaded onto it by _sync_checkpoint before they take
            # traffic — no silent mixed-version fleet
            self._reload_gen += 1
            self._desired_ckpt = path
            for d in done:
                self._replica_gen[d.idx] = self._reload_gen
            self.telemetry.health("rolling_reload_ok",
                                  replicas=len(done),
                                  step=report.get("step"))
            return {"replicas": len(done), **report}

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        reps = [r.snapshot() for r in self.replicas]
        by_state: Dict[str, int] = {}
        for s in reps:
            by_state[s["state"]] = by_state.get(s["state"], 0) + 1
        live = by_state.get("live", 0)
        # the autoscaling signal ROADMAP item 1 names: the sum of the
        # per-replica drain-rate EWMAs is the fleet's measured service
        # capacity in requests/second — scale out when offered load
        # approaches it, in when it dwarfs the offered load
        drain_sum = sum(float(s.get("drain_rate_rps") or 0.0)
                        for s in reps)
        queue_sum = sum(float(s.get("queue_depth") or 0.0) for s in reps)
        cache = {k: sum(int((s.get("cache") or {}).get(k, 0))
                        for s in reps)
                 for k in ("hits", "misses", "warmup_compiles")}
        out = {
            "replicas": reps,
            "total": len(self.replicas),
            "live": live,
            "by_state": by_state,
            "quorum": self.quorum,
            "below_quorum": live < self.quorum,
            "restarts_total": sum(int(s.get("restarts", 0)) for s in reps),
            "drain_rate_rps_sum": round(drain_sum, 2),
            # the dividend of the backlog estimate the autoscaler and
            # the per-tenant budgets both derive from drain_rate_rps_sum
            "queue_depth_sum": round(queue_sum, 2),
            # fleet-wide compile-cache totals: steady state must stay at
            # zero misses across EVERY replica, restarts included
            "cache": cache,
        }
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.state(
                now=time.monotonic())
        if self.hot_tenants:
            out["hot_tenants"] = sorted(self.hot_tenants)
        evs = sum(int(s.get("tenant_evictions") or 0) for s in reps)
        if evs:
            out["tenant_evictions"] = evs
        return out


def spawn_argv(config_path: str, logs_dir: str = "./logs/") -> Any:
    """argv builder for subprocess replicas: each child is a plain
    single-engine ``python -m hydragnn_tpu.serve`` bound to the port the
    supervisor assigns."""
    def build(port: int) -> List[str]:
        return [sys.executable, "-m", "hydragnn_tpu.serve",
                "--config", config_path, "--logs-dir", logs_dir,
                "--host", "127.0.0.1", "--port", str(port)]

    return build
