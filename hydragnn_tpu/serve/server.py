"""Stdlib HTTP front-end for the inference engine.

``ThreadingHTTPServer`` + ``BaseHTTPRequestHandler`` — no web framework
(the container bakes in no server deps, and the hot path is the engine,
not the transport).  One handler thread per connection; every handler
funnels into the single MicroBatcher worker, so concurrency is bounded
and ordering is sane.

Endpoints:

- ``POST /predict`` — JSON graph in, per-head predictions out::

      {"x": [[...feat...], ...], "pos": [[x,y,z], ...],
       "edge_index": [[senders...], [receivers...]],   # optional
       "edge_attr": [[...], ...]}                      # models with edge features

  ``edge_index`` may be omitted when the model config carries a radius —
  the server builds the neighbor list exactly like the training
  transform (graph/neighborlist.py:radius_graph).  Response::

      {"heads": {head_name: [...]}, "num_nodes": N, "latency_ms": ...}

  Errors: 400 malformed/invalid graph, 413 graph exceeds the largest
  bucket, 503 request queue full (backpressure), 504 timed out in queue.

- ``GET /healthz`` — liveness + warmup state.
- ``GET /metrics`` — engine compile-cache stats, batcher stats,
  telemetry health-event tally (the JSON the load generator
  tools/servebench.py scrapes).

Graceful shutdown: ``run()`` installs the SIGTERM/SIGINT machinery from
resilience/preempt.py (the same signal->flag->poll pattern the trainer
uses, second Ctrl-C escape hatch included), stops accepting, then drains
the request queue so every accepted request is answered before exit.
"""

from __future__ import annotations

import json
import threading
import time
# py3.10: concurrent.futures.TimeoutError is not yet the builtin one
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.serve.batcher import (
    BatcherClosedError,
    MicroBatcher,
    QueueFullError,
)
from hydragnn_tpu.serve.config import ServingConfig
from hydragnn_tpu.serve.engine import BucketOverflowError, InferenceEngine


# hard ceiling on request bodies, checked BEFORE reading the stream: a
# graph that fits any plausible bucket is far below this, and an
# unbounded read would let one oversized POST balloon the process
MAX_REQUEST_BYTES = 16 << 20


def sample_from_json(obj: Dict[str, Any], cfg,
                     edge_length_norm: float = 0.0,
                     pbc: bool = False,
                     build_max_neighbours: int = 0) -> GraphSample:
    """Validate + convert one request body into a host-side GraphSample
    (the same numpy dtypes collate expects).

    Server-side graph building mirrors ``transform_raw_samples``
    EXACTLY: float64 positions into ``radius_graph``, the transform's
    defaults for radius (5.0) and max_neighbours (100), and — for models
    with length edge features — ``edge_lengths / edge_length_norm`` where
    the norm is the TRAINING dataset's max edge length (persisted into
    the saved config's ``Serving.edge_length_norm`` by the data
    pipeline; a client-supplied ``edge_attr`` must already be normalized
    the same way).  Rotational-invariance datasets are the exception:
    the training transform rotates positions onto principal axes, which
    the server does not replay — pre-normalize such requests.
    """
    if not isinstance(obj, dict):
        raise ValueError("request body must be a JSON object")
    if "x" not in obj or "pos" not in obj:
        raise ValueError("request needs 'x' (node features) and 'pos' "
                         "(node positions)")
    x = np.asarray(obj["x"], dtype=np.float32)
    if x.ndim == 1:
        x = x[:, None]
    if x.ndim != 2:
        raise ValueError(
            f"'x' must be [n_nodes, features], got shape {list(x.shape)}")
    # float64 for graph building (the transform's precision); cast to
    # f32 only for the stored sample, exactly like transform_raw_samples
    pos64 = np.asarray(obj["pos"], dtype=np.float64)
    if pos64.ndim != 2 or pos64.shape[1] != 3:
        raise ValueError(
            f"'pos' must be [n_nodes, 3], got {list(pos64.shape)}")
    if x.shape[0] != pos64.shape[0]:
        raise ValueError(f"'x' has {x.shape[0]} nodes but 'pos' has "
                         f"{pos64.shape[0]}")
    if x.shape[0] < 1:
        raise ValueError("empty graph")
    if x.shape[1] != cfg.input_dim:
        raise ValueError(f"'x' feature dim {x.shape[1]} != model input_dim "
                         f"{cfg.input_dim}")
    if obj.get("edge_index") is not None:
        ei = np.asarray(obj["edge_index"], dtype=np.int32)
        if ei.ndim != 2 or ei.shape[0] != 2:
            raise ValueError("'edge_index' must be [2, n_edges]")
        if ei.size and (ei.min() < 0 or ei.max() >= x.shape[0]):
            raise ValueError("'edge_index' references nodes out of range")
    elif pbc:
        # periodic models build edges with radius_graph_pbc over a cell
        # the request doesn't carry — an open-boundary build here would
        # silently drop every cross-boundary edge
        raise ValueError(
            "this model was trained with periodic boundary conditions: "
            "the server cannot rebuild the periodic neighbor list — send "
            "'edge_index' computed client-side (graph/neighborlist.py:"
            "radius_graph_pbc)")
    else:
        # the training transform's graph build, defaults included
        # (transform_raw_samples: radius `or 5.0`, max_neighbours
        # `or 100`, float64 positions).  ``build_max_neighbours`` is the
        # cap the transform ACTUALLY used (persisted by the data
        # pipeline) — cfg.max_neighbours is finalize-overwritten for
        # PNA (degree-histogram length) and would truncate differently
        from hydragnn_tpu.graph.neighborlist import radius_graph

        cap = int(build_max_neighbours or cfg.max_neighbours or 100)
        ei = radius_graph(pos64, float(cfg.radius or 5.0),
                          max_neighbours=cap)
    ea = None
    if obj.get("edge_attr") is not None:
        if obj.get("edge_index") is None:
            # a client cannot know the server-side radius_graph's edge
            # ORDER — a count-matching edge_attr would silently assign
            # each edge another edge's feature
            raise ValueError("'edge_attr' requires the matching "
                             "'edge_index' in the same request")
        if not cfg.use_edge_attr:
            # an unexpected edge_attr would collate a batch whose pytree
            # differs from the warmed executables' and fail the whole
            # flushed group — reject THIS request instead
            raise ValueError("this model does not consume edge features: "
                             "drop 'edge_attr' from the request")
        ea = np.asarray(obj["edge_attr"], dtype=np.float32)
        if ea.ndim == 1:
            ea = ea[:, None]
        if ea.ndim != 2 or ea.shape[0] != ei.shape[1]:
            raise ValueError(f"'edge_attr' must be [{ei.shape[1]}, "
                             f"{cfg.edge_dim}], got {list(ea.shape)}")
        if ea.shape[1] != int(cfg.edge_dim or 0):
            raise ValueError(f"'edge_attr' has {ea.shape[1]} features but "
                             f"the model expects {cfg.edge_dim}")
    if cfg.use_edge_attr and ea is None:
        if pbc:
            # training lengths are minimum-image distances from
            # radius_graph_pbc; the open-boundary Euclidean distance is
            # wrong for every cross-boundary edge — require the client's
            raise ValueError(
                "this periodic model consumes edge features: send "
                "'edge_attr' computed client-side (minimum-image "
                "lengths / edge_length_norm)")
        if edge_length_norm and edge_length_norm > 0:
            # length edge features, normalized with the training run's
            # constant — identical arithmetic to transform_raw_samples
            from hydragnn_tpu.graph.neighborlist import edge_lengths

            ea = (edge_lengths(pos64, ei)
                  / edge_length_norm).astype(np.float32)
        else:
            raise ValueError(
                "this model consumes edge features: send 'edge_attr' "
                "normalized like training, or serve with "
                "Serving.edge_length_norm (written into config.json by "
                "training runs) so the server can compute it")
    return GraphSample(x=x, pos=pos64.astype(np.float32), edge_index=ei,
                       edge_attr=ea)


def _result_to_json(res: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return {name: np.asarray(arr).tolist() for name, arr in res.items()}


class InferenceServer:
    """Engine + batcher + ThreadingHTTPServer, wired for graceful drain."""

    def __init__(self, engine: InferenceEngine,
                 serving: Optional[ServingConfig] = None,
                 batcher: Optional[MicroBatcher] = None,
                 request_timeout_s: float = 30.0):
        self.engine = engine
        self.serving = serving or engine.serving
        self.batcher = batcher or MicroBatcher(
            engine, max_wait_ms=self.serving.max_wait_ms,
            max_queue=self.serving.max_queue, telemetry=engine.telemetry)
        self.request_timeout_s = float(request_timeout_s)
        self._t0 = time.time()
        server = self

        class Handler(BaseHTTPRequestHandler):
            # socket timeout: a client declaring Content-Length N but
            # sending fewer bytes must not pin its handler thread (and
            # fd) forever — the stdlib catches socket.timeout and reaps
            # the connection
            timeout = 30.0

            # quiet: no per-request stderr lines (telemetry carries the
            # signal); override to keep test output clean
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _reply(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    self._reply(200, server.health())
                elif self.path == "/metrics":
                    self._reply(200, server.metrics())
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                if self.path != "/predict":
                    self._reply(404, {"error": f"unknown path {self.path}"})
                    return
                t0 = time.perf_counter()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    if n < 0:
                        # rfile.read(-1) would read until EOF — the
                        # unbounded buffering the cap exists to prevent
                        self._reply(400, {"error": "invalid Content-Length"})
                        return
                    if n > MAX_REQUEST_BYTES:
                        self._reply(413, {
                            "error": f"request body {n} bytes exceeds the "
                                     f"{MAX_REQUEST_BYTES}-byte limit"})
                        return
                    obj = json.loads(self.rfile.read(n) or b"{}")
                    sample = sample_from_json(
                        obj, server.engine.cfg,
                        edge_length_norm=server.serving.edge_length_norm,
                        pbc=server.engine.pbc,
                        build_max_neighbours=(
                            server.serving.edge_build_max_neighbours))
                except (ValueError, TypeError, IndexError, KeyError,
                        json.JSONDecodeError) as e:
                    # malformed payloads must answer 400, never escape
                    # into the stdlib handler (dropped connection)
                    self._reply(400, {"error": str(e)})
                    return
                try:
                    fut = server.batcher.submit(sample)
                    res = fut.result(timeout=server.request_timeout_s)
                except BucketOverflowError as e:
                    self._reply(413, {"error": str(e)})
                    return
                except QueueFullError as e:
                    self._reply(503, {"error": str(e)})
                    return
                except BatcherClosedError as e:
                    self._reply(503, {"error": str(e)})
                    return
                except (_FutureTimeout, TimeoutError):
                    self._reply(504, {"error": "request timed out"})
                    return
                except Exception as e:  # noqa: BLE001 — engine failure
                    self._reply(500, {"error": repr(e)})
                    return
                self._reply(200, {
                    "heads": _result_to_json(res),
                    "num_nodes": int(sample.num_nodes),
                    "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
                })

        self.httpd = ThreadingHTTPServer(
            (self.serving.host, int(self.serving.port)), Handler)
        # ephemeral-port support (port 0): the bound port is the real one
        self.port = int(self.httpd.server_address[1])
        self._serve_thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InferenceServer":
        """AOT warmup (compile every bucket BEFORE accepting traffic, so
        no request ever pays a compile), then serve in the background."""
        n = self.engine.warmup()
        self.batcher.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-serve", daemon=True)
        self._serve_thread.start()
        self.engine.telemetry.health(
            "serve_start", port=self.port, buckets=n,
            max_wait_ms=self.serving.max_wait_ms)
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Stop accepting, then drain (or fail) the pending queue."""
        if self._stopped:
            return
        self._stopped = True
        self.httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self.batcher.close(drain=drain,
                           timeout=self.serving.drain_timeout_s)
        self.httpd.server_close()
        self.engine.telemetry.health(
            "serve_drain", drained=bool(drain),
            served=self.batcher.stats()["batches"])

    def run(self, poll_s: float = 0.05) -> None:
        """Blocking serve loop with graceful SIGTERM/SIGINT handling —
        the resilience/preempt.py signal->flag->poll machinery (second
        Ctrl-C raises KeyboardInterrupt, the operator's escape hatch)."""
        from hydragnn_tpu.resilience import PreemptionHandler

        handler = PreemptionHandler(cross_rank=False).install()
        self.start()
        try:
            while not handler.poll():
                time.sleep(poll_s)
        finally:
            handler.uninstall()
            self.shutdown(drain=True)

    # -- introspection -------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        cache = self.engine.cache_stats()
        return {
            "status": "ok",
            "uptime_s": round(time.time() - self._t0, 3),
            "compiled_buckets": cache["compiled_buckets"],
            "queue_depth": self.batcher.stats()["queue_depth"],
        }

    def metrics(self) -> Dict[str, Any]:
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "engine": self.engine.cache_stats(),
            "batcher": self.batcher.stats(),
            "health_events": self.engine.telemetry.health_counts,
        }
