"""Stdlib HTTP front-end for the inference engine.

``ThreadingHTTPServer`` + ``BaseHTTPRequestHandler`` — no web framework
(the container bakes in no server deps, and the hot path is the engine,
not the transport).  One handler thread per connection; every handler
funnels into the single MicroBatcher worker, so concurrency is bounded
and ordering is sane.

Endpoints:

- ``POST /predict`` — JSON graph in, per-head predictions out::

      {"x": [[...feat...], ...], "pos": [[x,y,z], ...],
       "edge_index": [[senders...], [receivers...]],   # optional
       "edge_attr": [[...], ...]}                      # models with edge features

  ``edge_index`` may be omitted when the model config carries a radius —
  the server builds the neighbor list exactly like the training
  transform (graph/neighborlist.py:radius_graph).  Response::

      {"heads": {head_name: [...]}, "num_nodes": N, "latency_ms": ...}

  Requests may carry a deadline (``timeout_ms`` body field or
  ``X-Timeout-Ms`` header; server default ``Serving.request_deadline_ms``).

  Errors: 400 malformed/invalid graph, 413 graph exceeds the largest
  bucket, 429 shed under overload (deadline unmeetable or expired in
  queue; ``Retry-After`` derived from the measured drain rate), 503
  request queue full or circuit breaker open (``Retry-After`` set),
  504 timed out (client wait or predict watchdog).

- ``POST /reload`` — hot checkpoint reload: ``{"checkpoint": path}``
  loads the pickle into a fresh state, validates it against the golden
  batch, and atomically swaps it in (409 + automatic rollback to the
  previous state when validation fails) — zero dropped requests.  A
  file watch (``Serving.reload_watch_path``/``reload_watch_s``) can
  trigger the same path on checkpoint mtime changes.  Trust boundary:
  unpickling a client-named path is code execution, so non-loopback
  clients are refused (403) unless ``Serving.reload_root`` allowlists a
  checkpoint directory the path must resolve into.
- ``POST /rollback`` — restore the retained pre-reload state (the
  manual spelling of the probation rollback; the fleet supervisor uses
  it to roll already-swapped subprocess replicas back when a later
  replica rejects a rolling-reload candidate).  409 when nothing is
  retained; same trust boundary as ``/reload``.
- ``GET /healthz`` — liveness + warmup state; ``status`` degrades to
  ``"degraded"`` while the circuit breaker is open/half-open.
- ``GET /metrics`` — engine compile-cache stats, batcher stats
  (incl. shed/expired/timeout counters), breaker + reload state,
  telemetry health-event tally (the JSON the load generator
  tools/servebench.py scrapes).

Graceful shutdown: ``run()`` installs the SIGTERM/SIGINT machinery from
resilience/preempt.py (the same signal->flag->poll pattern the trainer
uses, second Ctrl-C escape hatch included), stops accepting, then drains
the request queue so every accepted request is answered before exit.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
# py3.10: concurrent.futures.TimeoutError is not yet the builtin one
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.resilience.breaker import BreakerOpenError, CircuitBreaker
from hydragnn_tpu.resilience.chaos import ServeChaos
from hydragnn_tpu.serve.batcher import (
    BatcherClosedError,
    MicroBatcher,
    PredictTimeoutError,
    QueueFullError,
    RequestShedError,
)
from hydragnn_tpu.serve.config import DEFAULT_TENANT, ServingConfig
from hydragnn_tpu.serve.engine import (
    BucketOverflowError,
    InferenceEngine,
    ReloadValidationError,
)
from hydragnn_tpu.telemetry.trace import extract_trace_context


# hard ceiling on request bodies, checked BEFORE reading the stream: a
# graph that fits any plausible bucket is far below this, and an
# unbounded read would let one oversized POST balloon the process
MAX_REQUEST_BYTES = 16 << 20


class _BodyTooLarge(ValueError):
    def __init__(self, n: int):
        super().__init__(f"body of {n} bytes over the cap")
        self.n = n


def reload_request_denied(path: str, serving,
                          client_ip: str) -> Optional[str]:
    """The /reload trust boundary, shared by the single server and the
    fleet router (serve/router.py): ``pickle.load`` of a client-named
    path is code execution, so non-loopback clients may only name paths
    resolving inside the allowlisted ``Serving.reload_root`` (without
    one, reload is loopback-only).  Returns the 403 error string, or
    None when the request is allowed — ONE implementation, so a future
    hardening reaches every front end."""
    root = serving.reload_root
    if root:
        real = os.path.realpath(path)
        if not real.startswith(os.path.realpath(root) + os.sep):
            return (f"checkpoint path outside the allowlisted "
                    f"reload_root {root}")
        return None
    if client_ip not in ("127.0.0.1", "::1"):
        return ("reload is loopback-only unless Serving.reload_root "
                "allowlists a checkpoint directory")
    return None


def extract_deadline_s(headers, obj) -> Optional[float]:
    """Per-request deadline from the transport: the ``X-Timeout-Ms``
    header wins over the ``timeout_ms`` body field; absent -> None (the
    batcher's configured default applies).  NOTE client semantics differ
    from the server knob: a client that wants NO deadline omits the
    field (timeout_ms=0 means zero tolerance -> immediate shed), while
    ``Serving.request_deadline_ms=0`` disables the server default.
    Raises ValueError on a negative value (HTTP layer: 400, not a
    silent clamp).  Shared by the single server's handler and the fleet
    router (serve/router.py) so both spellings behave identically at
    every layer."""
    tmo = headers.get("X-Timeout-Ms")
    if tmo is None and isinstance(obj, dict):
        tmo = obj.get("timeout_ms")
    if tmo is None:
        return None
    deadline_s = float(tmo) / 1e3
    if deadline_s < 0:
        raise ValueError("timeout_ms must be >= 0 (omit it for the "
                         "server default deadline)")
    return deadline_s


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Shared JSON-over-HTTP handler plumbing (bounded body reads,
    JSON replies, Retry-After headers, quiet logging) — the base of the
    single server's handler below AND the fleet router's
    (serve/router.py)."""

    # socket timeout: a client declaring Content-Length N but sending
    # fewer bytes must not pin its handler thread (and fd) forever —
    # the stdlib catches socket.timeout and reaps the connection
    timeout = 30.0

    # quiet: no per-request stderr lines (telemetry carries the
    # signal); override to keep test output clean
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _reply(self, code: int, payload: Dict[str, Any],
               headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _retry_after(self, seconds: float) -> Dict[str, str]:
        return {"Retry-After": str(max(1, math.ceil(seconds)))}

    def _read_json(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0))
        if n < 0:
            # rfile.read(-1) would read until EOF — the unbounded
            # buffering the cap exists to prevent
            raise ValueError("invalid Content-Length")
        if n > MAX_REQUEST_BYTES:
            raise _BodyTooLarge(n)
        return json.loads(self.rfile.read(n) or b"{}")


def sample_from_json(obj: Dict[str, Any], cfg,
                     edge_length_norm: float = 0.0,
                     pbc: bool = False,
                     build_max_neighbours: int = 0) -> GraphSample:
    """Validate + convert one request body into a host-side GraphSample
    (the same numpy dtypes collate expects).

    Server-side graph building mirrors ``transform_raw_samples``
    EXACTLY: float64 positions into ``radius_graph``, the transform's
    defaults for radius (5.0) and max_neighbours (100), and — for models
    with length edge features — ``edge_lengths / edge_length_norm`` where
    the norm is the TRAINING dataset's max edge length (persisted into
    the saved config's ``Serving.edge_length_norm`` by the data
    pipeline; a client-supplied ``edge_attr`` must already be normalized
    the same way).  Rotational-invariance datasets are the exception:
    the training transform rotates positions onto principal axes, which
    the server does not replay — pre-normalize such requests.
    """
    if not isinstance(obj, dict):
        raise ValueError("request body must be a JSON object")
    if "x" not in obj or "pos" not in obj:
        raise ValueError("request needs 'x' (node features) and 'pos' "
                         "(node positions)")
    x = np.asarray(obj["x"], dtype=np.float32)
    if x.ndim == 1:
        x = x[:, None]
    if x.ndim != 2:
        raise ValueError(
            f"'x' must be [n_nodes, features], got shape {list(x.shape)}")
    # float64 for graph building (the transform's precision); cast to
    # f32 only for the stored sample, exactly like transform_raw_samples
    pos64 = np.asarray(obj["pos"], dtype=np.float64)
    if pos64.ndim != 2 or pos64.shape[1] != 3:
        raise ValueError(
            f"'pos' must be [n_nodes, 3], got {list(pos64.shape)}")
    if x.shape[0] != pos64.shape[0]:
        raise ValueError(f"'x' has {x.shape[0]} nodes but 'pos' has "
                         f"{pos64.shape[0]}")
    if x.shape[0] < 1:
        raise ValueError("empty graph")
    if x.shape[1] != cfg.input_dim:
        raise ValueError(f"'x' feature dim {x.shape[1]} != model input_dim "
                         f"{cfg.input_dim}")
    if obj.get("edge_index") is not None:
        ei = np.asarray(obj["edge_index"], dtype=np.int32)
        if ei.ndim != 2 or ei.shape[0] != 2:
            raise ValueError("'edge_index' must be [2, n_edges]")
        if ei.size and (ei.min() < 0 or ei.max() >= x.shape[0]):
            raise ValueError("'edge_index' references nodes out of range")
    elif pbc:
        # periodic models build edges with radius_graph_pbc over a cell
        # the request doesn't carry — an open-boundary build here would
        # silently drop every cross-boundary edge
        raise ValueError(
            "this model was trained with periodic boundary conditions: "
            "the server cannot rebuild the periodic neighbor list — send "
            "'edge_index' computed client-side (graph/neighborlist.py:"
            "radius_graph_pbc)")
    else:
        # the training transform's graph build, defaults included
        # (transform_raw_samples: radius `or 5.0`, max_neighbours
        # `or 100`, float64 positions).  ``build_max_neighbours`` is the
        # cap the transform ACTUALLY used (persisted by the data
        # pipeline) — cfg.max_neighbours is finalize-overwritten for
        # PNA (degree-histogram length) and would truncate differently
        from hydragnn_tpu.graph.neighborlist import radius_graph

        cap = int(build_max_neighbours or cfg.max_neighbours or 100)
        ei = radius_graph(pos64, float(cfg.radius or 5.0),
                          max_neighbours=cap)
    ea = None
    if obj.get("edge_attr") is not None:
        if obj.get("edge_index") is None:
            # a client cannot know the server-side radius_graph's edge
            # ORDER — a count-matching edge_attr would silently assign
            # each edge another edge's feature
            raise ValueError("'edge_attr' requires the matching "
                             "'edge_index' in the same request")
        if not cfg.use_edge_attr:
            # an unexpected edge_attr would collate a batch whose pytree
            # differs from the warmed executables' and fail the whole
            # flushed group — reject THIS request instead
            raise ValueError("this model does not consume edge features: "
                             "drop 'edge_attr' from the request")
        ea = np.asarray(obj["edge_attr"], dtype=np.float32)
        if ea.ndim == 1:
            ea = ea[:, None]
        if ea.ndim != 2 or ea.shape[0] != ei.shape[1]:
            raise ValueError(f"'edge_attr' must be [{ei.shape[1]}, "
                             f"{cfg.edge_dim}], got {list(ea.shape)}")
        if ea.shape[1] != int(cfg.edge_dim or 0):
            raise ValueError(f"'edge_attr' has {ea.shape[1]} features but "
                             f"the model expects {cfg.edge_dim}")
    if cfg.use_edge_attr and ea is None:
        if pbc:
            # training lengths are minimum-image distances from
            # radius_graph_pbc; the open-boundary Euclidean distance is
            # wrong for every cross-boundary edge — require the client's
            raise ValueError(
                "this periodic model consumes edge features: send "
                "'edge_attr' computed client-side (minimum-image "
                "lengths / edge_length_norm)")
        if edge_length_norm and edge_length_norm > 0:
            # length edge features, normalized with the training run's
            # constant — identical arithmetic to transform_raw_samples
            from hydragnn_tpu.graph.neighborlist import edge_lengths

            ea = (edge_lengths(pos64, ei)
                  / edge_length_norm).astype(np.float32)
        else:
            raise ValueError(
                "this model consumes edge features: send 'edge_attr' "
                "normalized like training, or serve with "
                "Serving.edge_length_norm (written into config.json by "
                "training runs) so the server can compute it")
    return GraphSample(x=x, pos=pos64.astype(np.float32), edge_index=ei,
                       edge_attr=ea)


def _result_to_json(res: Dict[str, np.ndarray]) -> Dict[str, Any]:
    return {name: np.asarray(arr).tolist() for name, arr in res.items()}


class InferenceServer:
    """Engine + batcher + ThreadingHTTPServer, wired for graceful drain."""

    def __init__(self, engine: InferenceEngine,
                 serving: Optional[ServingConfig] = None,
                 batcher: Optional[MicroBatcher] = None,
                 request_timeout_s: float = 30.0,
                 chaos: Optional[ServeChaos] = None):
        self.engine = engine
        self.serving = serving or engine.serving
        # serving-side fault injection (HYDRAGNN_CHAOS_SERVE_*): threads
        # through the batcher's predict path and the reload loader
        self.chaos = chaos if chaos is not None else ServeChaos.from_env()
        # consecutive predict failures/timeouts trip the breaker: fail
        # fast with 503 + degraded /healthz instead of queueing behind a
        # broken predict path; a trip right after a hot reload rolls the
        # checkpoint back (reload probation)
        self.breaker = CircuitBreaker(
            threshold=self.serving.breaker_threshold,
            cooldown_s=self.serving.breaker_cooldown_s,
            what="predict", telemetry=engine.telemetry,
            on_open=self._on_breaker_open)
        self.batcher = batcher or MicroBatcher(
            engine, max_wait_ms=self.serving.max_wait_ms,
            max_queue=self.serving.max_queue, telemetry=engine.telemetry,
            default_deadline_ms=self.serving.request_deadline_ms,
            predict_timeout_s=self.serving.predict_timeout_s,
            breaker=self.breaker, chaos=self.chaos)
        self.request_timeout_s = float(request_timeout_s)
        self._t0 = time.time()
        server = self

        class Handler(JsonRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    self._reply(200, server.health())
                elif self.path == "/metrics":
                    self._reply(200, server.metrics())
                else:
                    self._reply(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                if self.path == "/reload":
                    self._do_reload()
                    return
                if self.path == "/rollback":
                    self._do_rollback()
                    return
                if self.path != "/predict":
                    self._reply(404, {"error": f"unknown path {self.path}"})
                    return
                t0 = time.perf_counter()
                # trace identity is adopted/minted from the HEADERS before
                # the body is even read, so a 400/413 answer still quotes
                # the id the client sent (docs/TELEMETRY.md "Tracing")
                ctx = extract_trace_context(self.headers)
                code, payload, hdrs = self._predict_answer(t0, ctx)
                payload["trace_id"] = ctx.trace_id
                hdrs = dict(hdrs or {})
                hdrs["X-Request-Id"] = ctx.trace_id
                tr = getattr(server.engine.telemetry, "spans", None)
                if tr is not None:
                    # the request span covers the request's whole server
                    # residency: parse + queue wait + flush + reply
                    # formation; its trace links to the flush span that
                    # served it via the flush's ``links`` list
                    tr.record_interval(
                        "serve.request", t0, time.perf_counter(),
                        trace_id=ctx.trace_id, parent_id=ctx.parent_id,
                        status=code)
                self._reply(code, payload, headers=hdrs)

            def _predict_answer(self, t0, ctx):
                """The /predict state machine as (code, payload, headers)
                — one exit point so the trace id and request span reach
                EVERY answer, shed/timeout/breaker errors included."""
                try:
                    obj = self._read_json()
                    if ctx.minted and isinstance(obj, dict) \
                            and obj.get("trace_id"):
                        # body-field spelling (no header): adopt in place
                        body_ctx = extract_trace_context(
                            self.headers, obj)
                        ctx.trace_id = body_ctx.trace_id
                        ctx.minted = body_ctx.minted
                    model = obj.get("model") if isinstance(obj, dict) \
                        else None
                    if model is not None and model != DEFAULT_TENANT:
                        # single-model server (also the subprocess fleet
                        # replica): tenancy lives in the in-process
                        # fleet; an unknown model is a 404, not a 400 —
                        # the router maps it to UnknownTenantError
                        return 404, {
                            "error": f"unknown model {model!r}: this "
                                     "server hosts a single model "
                                     f"({DEFAULT_TENANT!r})"}, None
                    deadline_s = extract_deadline_s(self.headers, obj)
                    sample = sample_from_json(
                        obj, server.engine.cfg,
                        edge_length_norm=server.serving.edge_length_norm,
                        pbc=server.engine.pbc,
                        build_max_neighbours=(
                            server.serving.edge_build_max_neighbours))
                except _BodyTooLarge as e:
                    return 413, {
                        "error": f"request body {e.n} bytes exceeds the "
                                 f"{MAX_REQUEST_BYTES}-byte limit"}, None
                except (ValueError, TypeError, IndexError, KeyError,
                        json.JSONDecodeError) as e:
                    # malformed payloads must answer 400, never escape
                    # into the stdlib handler (dropped connection)
                    return 400, {"error": str(e)}, None
                try:
                    fut = server.batcher.submit(sample,
                                                deadline_s=deadline_s,
                                                trace=ctx)
                    res = fut.result(timeout=server._wait_s(deadline_s))
                except BucketOverflowError as e:
                    return 413, {"error": str(e)}, None
                except BreakerOpenError as e:
                    # breaker open: fail fast, tell the client when the
                    # half-open probe will be admitted
                    return 503, {"error": str(e), "breaker": "open"}, \
                        self._retry_after(e.retry_after_s)
                except RequestShedError as e:
                    # shed (admission control or expired-in-queue):
                    # 429 + Retry-After from the measured drain rate
                    return 429, {"error": str(e)}, \
                        self._retry_after(e.retry_after_s)
                except QueueFullError as e:
                    return 503, {"error": str(e)}, self._retry_after(
                        server.batcher.retry_after_s())
                except BatcherClosedError as e:
                    return 503, {"error": str(e)}, None
                except PredictTimeoutError as e:
                    return 504, {"error": str(e)}, None
                except (_FutureTimeout, TimeoutError):
                    return 504, {"error": "request timed out"}, None
                except Exception as e:  # noqa: BLE001 — engine failure
                    return 500, {"error": repr(e)}, None
                return 200, {
                    "heads": _result_to_json(res),
                    "num_nodes": int(sample.num_nodes),
                    "latency_ms": round((time.perf_counter() - t0) * 1e3,
                                        3),
                }, None

            def _do_rollback(self) -> None:
                """Restore the retained pre-reload state (the manual
                spelling of the breaker-probation rollback).  Control
                surface like /reload: loopback-only unless a reload_root
                is configured (a remote caller allowed to reload may
                also un-reload).  409 when there is nothing retained.
                The fleet supervisor uses this to roll already-swapped
                SUBPROCESS replicas back when a later replica rejects a
                rolling-reload candidate (serve/fleet.py)."""
                if not server.serving.reload_root \
                        and self.client_address[0] not in ("127.0.0.1",
                                                           "::1"):
                    self._reply(403, {
                        "error": "rollback is loopback-only unless "
                                 "Serving.reload_root is configured"})
                    return
                if server.engine.rollback(reason="api"):
                    self._reply(200, {"status": "rolled_back"})
                else:
                    self._reply(409, {
                        "error": "nothing to roll back: no previous "
                                 "state is retained"})

            def _do_reload(self) -> None:
                try:
                    obj = self._read_json()
                    path = obj.get("checkpoint") if isinstance(obj, dict) \
                        else None
                    if not path or not isinstance(path, str):
                        self._reply(400, {
                            "error": "reload body needs "
                                     "{\"checkpoint\": \"path/to/ckpt.pk\"}"})
                        return
                except _BodyTooLarge:
                    self._reply(413, {"error": "reload body too large"})
                    return
                except (ValueError, json.JSONDecodeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                denied = reload_request_denied(path, server.serving,
                                               self.client_address[0])
                if denied:
                    self._reply(403, {"error": denied})
                    return
                try:
                    report = server.reload(path)
                except FileNotFoundError:
                    self._reply(404, {"error": f"no checkpoint at {path}"})
                    return
                except ReloadValidationError as e:
                    # validation rejected the candidate: the previous
                    # state keeps serving — a rollback, not an outage
                    self._reply(409, {"status": "rolled_back",
                                      "error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 — loader failure
                    self._reply(500, {"error": repr(e)})
                    return
                self._reply(200, {"status": "ok", **report})

        self.httpd = ThreadingHTTPServer(
            (self.serving.host, int(self.serving.port)), Handler)
        # ephemeral-port support (port 0): the bound port is the real one
        self.port = int(self.httpd.server_address[1])
        self._serve_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        self._stopped = False

    # -- overload / reload plumbing ------------------------------------------

    def _wait_s(self, deadline_s: Optional[float]) -> float:
        """How long a handler thread waits on its future: the request's
        own deadline plus the worst predict it could sit behind, capped
        by the global request timeout."""
        if deadline_s is None:
            return self.request_timeout_s
        grace = max(1.0, self.serving.predict_timeout_s)
        return min(self.request_timeout_s, deadline_s + grace)

    def _on_breaker_open(self) -> None:
        """Breaker trip hook: inside the post-reload probation window
        the freshly-swapped checkpoint is the prime suspect — roll back
        to the retained previous state instantly and half-open the
        breaker so the next flush probes the restored state."""
        if self.engine.in_probation(self.serving.reload_probation_s):
            if self.engine.rollback(reason="breaker_trip"):
                self.breaker.reset(to="half_open")

    def reload(self, path: str) -> Dict[str, Any]:
        """Hot-swap the checkpoint at ``path`` (validation + atomic swap
        + retained rollback state); raises ReloadValidationError when
        the candidate is rejected."""
        return self.engine.reload_from_checkpoint(
            path, chaos=self.chaos, source="http")

    def _watch_loop(self, poll_s: float) -> None:
        """Checkpoint file watch: a changed mtime (or the file's first
        appearance) triggers the same validated reload as POST /reload;
        failures keep the old state serving (telemetry records them)."""
        path = self.serving.reload_watch_path
        try:
            last: Optional[float] = os.path.getmtime(path)
        except OSError:
            last = None
        while not self._stopped:
            time.sleep(poll_s)
            try:
                m = os.path.getmtime(path)
            except OSError:
                continue
            if last is not None and m == last:
                continue
            last = m
            try:
                self.engine.reload_from_checkpoint(
                    path, chaos=self.chaos, source="watch")
            except Exception:  # graftlint: disable=ROB001 (reload path already emitted reload_rollback with the error)
                pass

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InferenceServer":
        """AOT warmup (compile every bucket BEFORE accepting traffic, so
        no request ever pays a compile), then serve in the background."""
        n = self.engine.warmup()
        self.batcher.start()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-serve", daemon=True)
        self._serve_thread.start()
        if self.serving.reload_watch_path and self.serving.reload_watch_s > 0:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="reload-watch", daemon=True,
                args=(self.serving.reload_watch_s,))
            self._watch_thread.start()
        self.engine.telemetry.health(
            "serve_start", port=self.port, buckets=n,
            max_wait_ms=self.serving.max_wait_ms)
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Stop accepting, then drain (or fail) the pending queue."""
        if self._stopped:
            return
        self._stopped = True
        self.httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self.batcher.close(drain=drain,
                           timeout=self.serving.drain_timeout_s)
        self.httpd.server_close()
        self.engine.telemetry.health(
            "serve_drain", drained=bool(drain),
            served=self.batcher.stats()["batches"])

    def run(self, poll_s: float = 0.05) -> None:
        """Blocking serve loop with graceful SIGTERM/SIGINT handling —
        the resilience/preempt.py signal->flag->poll machinery (second
        Ctrl-C raises KeyboardInterrupt, the operator's escape hatch)."""
        from hydragnn_tpu.resilience import PreemptionHandler

        handler = PreemptionHandler(cross_rank=False).install()
        self.start()
        try:
            while not handler.poll():
                time.sleep(poll_s)
        finally:
            handler.uninstall()
            self.shutdown(drain=True)

    # -- introspection -------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        cache = self.engine.cache_stats()
        breaker = self.breaker.snapshot()
        # the breaker only degrades /healthz when it actually gates
        # traffic (threshold 0 = disabled)
        degraded = self.breaker.degraded
        quant = self.engine.quant_stats()
        return {
            "status": "degraded" if degraded else "ok",
            "uptime_s": round(time.time() - self._t0, 3),
            "compiled_buckets": cache["compiled_buckets"],
            "queue_depth": self.batcher.stats()["queue_depth"],
            "breaker": breaker,
            "reload": self.engine.reload_stats(),
            # active dtype policy (+ whether the requested one was
            # rejected by the golden-batch gate and fell back to f32)
            "quant_policy": quant["active"],
            "quant_fallback": bool(quant["fallback"]),
        }

    def metrics(self) -> Dict[str, Any]:
        cache = self.engine.cache_stats()  # carries quant_stats already
        return {
            "uptime_s": round(time.time() - self._t0, 3),
            "engine": cache,
            "batcher": self.batcher.stats(),
            "breaker": self.breaker.snapshot(),
            "reload": self.engine.reload_stats(),
            # the serving shape parameters a bucket autotuner needs to
            # interpret the batcher histograms (tools/buckettune.py
            # --url scrapes this instead of log files)
            "serving": {
                "buckets": [int(b) for b in self.serving.buckets],
                "max_nodes_per_graph": int(
                    self.serving.max_nodes_per_graph),
                "max_edges_per_graph": int(
                    self.serving.max_edges_per_graph),
                "quant_policy": cache["quant"]["active"],
            },
            "health_events": self.engine.telemetry.health_counts,
            # span-latency breakdown (queue-wait vs pad vs predict
            # percentiles) when the flight recorder is on — {} otherwise,
            # so scrapers can treat the key as always-present
            "spans": (self.engine.telemetry.spans.percentiles()
                      if getattr(self.engine.telemetry, "spans", None)
                      is not None else {}),
        }
