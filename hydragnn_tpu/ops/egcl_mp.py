"""Fused EGCL interaction block as a thin spec on the fused-block builder
(:mod:`hydragnn_tpu.ops.fused_block`): gather -> 2-layer edge MLP -> tanh
coordinate gate -> BOTH scatters (message segment-sum AND coordinate
translation sum) in ONE Pallas pass, forward and backward — no [E, hidden]
HBM streams.

EGNN aggregates BOTH outputs at the edge *source* (reference
EGCLStack.py:194,210), so the spec's primary side is the SENDER: the
host-precomputed ``edge_perm_sender`` ordering makes the two scatters
block-local one-hot matmuls while the single receiver gather rides the
±1-block window.

  t0   = x[send] @ W0s + x[recv] @ W0r + geo @ W0g     (split concat; b0
                                                        on geo's bias lane)
  m    = relu(relu(t0) @ W1 + b1)                      -> agg[send]
  c    = tanh(relu(m @ Wc0 + bc0) @ Wc1)               (equivariant only)
  clip(diff * c, ±100)                                 -> psum[send]

``geo`` is ``concat([diff_normed (3), radial (1), edge_attr (A)])`` — ONE
canonical geometry definition shared with the composed path
(models/layers.edge_geometry).  The concat matmul is split into three
partial matmuls summed in f32 — same math, different f32 rounding order
(tests bound the drift with the scf tolerance contract).  The ±100 clamp
never binds (``|diff_normed| < 1``, ``|tanh| <= 1``) so its vjp mask is
identically 1 on reachable inputs.

Width limits: F <= EGCL_F_LIMIT and H <= EGCL_H_LIMIT (one 128-lane tile
each keeps every weight/accumulator block single-tile) and geo payload
(3 diff + 1 radial + edge_dim) <= 127.  Callers gate on all three and
fall back to the composed path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import (
    _GP, EdgeBlockSpec, _dot, build_fused_edge_op)

_EDGE_BLOCK = 256  # F/H capped at one tile => every temporary is [256, 128]
EGCL_F_LIMIT = 128
EGCL_H_LIMIT = 128
EGCL_GEO_LIMIT = _GP - 1  # payload lanes; lane 127 carries the folded b0


def _make_chain(equivariant: bool):
    def chain(w_vals, geo, xp, xo, dt):
        if equivariant:
            w0s, w0r, w0g, w1, b1, wc0, bc0, wc1 = w_vals
        else:
            w0s, w0r, w0g, w1, b1 = w_vals
        t0 = (_dot(xp, w0s, ((1,), (0,)), dt)
              + _dot(xo, w0r, ((1,), (0,)), dt)
              + _dot(geo, w0g, ((1,), (0,)), dt))
        f1 = jax.nn.relu(t0)
        m = jax.nn.relu(_dot(f1, w1, ((1,), (0,)), dt) + b1[0:1, :])
        if not equivariant:
            return (m,)
        u0 = _dot(m, wc0, ((1,), (0,)), dt) + bc0[0:1, :]
        v = jax.nn.relu(u0)
        cp = _dot(v, wc1, ((1,), (0,)), dt)  # [BE, GP]; col 0 real
        c = jnp.tanh(cp[:, 0:1])             # [BE, 1]
        lane = jax.lax.broadcasted_iota(jnp.int32, geo.shape, 1)
        diffm = jnp.where(lane < 3, geo, 0.0)
        return (m, jnp.clip(diffm * c, -100.0, 100.0))
    return chain


@functools.lru_cache(maxsize=None)
def _egcl_op(equivariant: bool):
    return build_fused_edge_op(EdgeBlockSpec(
        name="egcl", primary="sender", gather_primary=True,
        gather_other=True, num_outputs=2 if equivariant else 1,
        chain=_make_chain(equivariant), edge_block=_EDGE_BLOCK))


def _pack_weights(equivariant, w0, b0, w1, b1, wc0, bc0, wc1,
                  f, f_pad, h_pad, bf16):
    """Split the composed path's concat kernel w0 [2F+1+A, H] into the
    three partial kernels the chain consumes (sender rows, receiver
    rows, geometry rows on the geo lane layout) with b0 folded onto the
    geo bias lane; b1/bc0 as [8, H] row-broadcast blocks; wc1 [H, 1] on
    column 0 of a full tile."""
    h = w1.shape[0]
    gd = w0.shape[0] - 2 * f  # 1 radial + edge_attr lanes
    w0s = jnp.zeros((f_pad, h_pad), jnp.float32).at[:f, :h].set(
        w0[:f].astype(jnp.float32))
    w0r = jnp.zeros((f_pad, h_pad), jnp.float32).at[:f, :h].set(
        w0[f:2 * f].astype(jnp.float32))
    w0g = jnp.zeros((_GP, h_pad), jnp.float32)
    w0g = w0g.at[3:3 + gd, :h].set(w0[2 * f:].astype(jnp.float32))
    w0g = w0g.at[_GP - 1, :h].set(b0.astype(jnp.float32))
    w1_p = jnp.zeros((h_pad, h_pad), jnp.float32).at[:h, :h].set(
        w1.astype(jnp.float32))
    b1_p = jnp.zeros((8, h_pad), jnp.float32).at[:, :h].set(
        jnp.broadcast_to(b1.astype(jnp.float32), (8, h)))
    packs = [w0s, w0r, w0g, w1_p, b1_p]
    if equivariant:
        wc0_p = jnp.zeros((h_pad, h_pad), jnp.float32).at[:h, :h].set(
            wc0.astype(jnp.float32))
        bc0_p = jnp.zeros((8, h_pad), jnp.float32).at[:, :h].set(
            jnp.broadcast_to(bc0.astype(jnp.float32), (8, h)))
        wc1_p = jnp.zeros((h_pad, _GP), jnp.float32).at[:h, 0].set(
            wc1[:, 0].astype(jnp.float32))
        packs += [wc0_p, bc0_p, wc1_p]
    if bf16:
        # halves the constant blocks' VMEM; bias blocks stay f32 (added
        # after the f32-accumulating dots)
        packs = [p if p.shape[0] == 8 else p.astype(jnp.bfloat16)
                 for p in packs]
    return tuple(packs)


def egcl_block(equivariant, x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
               senders, receivers, sender_perm):
    """Fused EGCL interaction block.

    ``m_e = relu(relu([x[send_e], x[recv_e], geo_e] @ w0 + b0) @ w1 + b1)``
    then ``agg[n] = sum_{e: send[e]=n} m_e`` and (equivariant only)
    ``psum[n] = sum_{e: send[e]=n} clip(diff_e * tanh(relu(m_e @ wc0 +
    bc0) @ wc1), ±100)`` — returns ``(agg [N, H], psum [N, _GP] or None)``.
    ``psum``'s first 3 lanes are the translation sums; the caller divides
    by the sender degree for the segment-mean and adds to positions.

    Differentiable wrt x, geo and all weights (geo's cotangent chains
    into position grads outside).  Requires the builder's collate
    invariants plus the EGCL_* width limits (callers gate).  ``em`` is
    the int32 edge-validity mask: em == 0 edges are schedule-skipped
    entirely and get EXACTLY ZERO for every output and grad (masked
    edges must tail-sort in both orderings — collate guarantees this)."""
    n, f = x.shape
    h = w1.shape[0]
    f_pad = _round_up(max(f, 1), 128)
    h_pad = _round_up(max(h, 1), 128)
    packs = _pack_weights(equivariant, w0, b0, w1, b1, wc0, bc0, wc1,
                          f, f_pad, h_pad, x.dtype == jnp.bfloat16)
    outs = _egcl_op(bool(equivariant))(
        x, geo, em, packs, senders, receivers, sender_perm)
    agg = outs[0][:n, :h].astype(x.dtype)
    if equivariant:
        return agg, outs[1][:n]
    return agg, None
