"""Fused EGCL interaction block: gather -> 2-layer edge MLP -> tanh
coordinate gate -> BOTH scatters (message segment-sum AND coordinate
translation sum) in ONE Pallas pass, forward and backward — no [E, hidden]
HBM streams.

Motivation (ROADMAP item 2): EGNN is the second-highest-traffic mainline
arch in the BENCH_r05 sweep (94.6k g/s) and its composed step materializes
every per-edge tensor — the [E, 2F+geo] concat, two [E, H] MLP
activations, the [E, H] coord-MLP activation, the [E, 1] gate and the
[E, 3] translations — then pays gather/scatter passes over each.  At
EGNN's narrow hidden width (64) the step is stream-bound, not FLOP-bound,
so the scf_mp recompute-over-store trade applies even though the matmuls
are small: keep the entire per-edge pipeline in VMEM and let the extra
backward re-evaluations ride the idle MXU.

Schedule: fused_mp's dense block schedule, but SENDER-sorted as primary —
EGNN aggregates BOTH outputs at the edge *source* (reference
EGCLStack.py:194,210), so the host-precomputed ``edge_perm_sender``
ordering makes the two scatters block-local one-hot matmuls while the
single receiver gather rides the ±1-block window (collate invariant:
graphs never straddle a node block).

  forward (sender-sorted):
    t0   = x[send] @ W0s + x[recv] @ W0r + geo @ W0g     (split concat; b0
                                                          on geo's bias lane)
    m    = relu(relu(t0) @ W1 + b1)
    agg[send]  += m                                      (one-hot scatter)
    c    = tanh(relu(m @ Wc0 + bc0) @ Wc1)               (equivariant only)
    psum[send] += clip(diff * c, ±100)                   (same one-hot)

  backward pass R (sender-sorted): recomputes the chain per block,
    accumulates ALL weight grads IN-KERNEL (constant-mapped output blocks),
    emits the per-edge dgeo stream [E, geo] (diff lanes carry the
    coordinate-gate grad, radial/edge_attr lanes the MLP input grad — XLA
    chains them into position grads outside) and scatters the sender-side
    dx — the scatter target IS the sorted side here, so pass R covers it.
  backward pass S (natural receiver order): recomputes the chain and
    scatters the receiver-side dx; sender-side tensors ride the window.

Clip note: ``|diff_normed| < 1`` (norm_diff divides by sqrt(r)+1) and
``|tanh| <= 1``, so the ±100 clamp NEVER binds and its grad mask is
identically 1 — the backward drops it (the composed path's VJP is 1
everywhere reachable too).

Invariants: exactly fused_mp's (nondecreasing receivers, intra-graph
edges, graphs within one node block, host-precomputed stable sender
argsort).  Width limits: F <= EGCL_F_LIMIT and H <= EGCL_H_LIMIT (one
128-lane tile each keeps every weight/accumulator block single-tile) and
geo payload (3 diff + 1 radial + edge_dim) <= 127 (one pad lane carries
the folded bias).  Callers gate on all three and fall back to the
composed path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK, _dense_schedule
from hydragnn_tpu.ops.scf_mp import _GP, _dot, _gather_window, _window_maps

_EDGE_BLOCK = 256  # F/H capped at one tile => every temporary is [256, 128]
EGCL_F_LIMIT = 128
EGCL_H_LIMIT = 128
EGCL_GEO_LIMIT = _GP - 1  # payload lanes; lane 127 carries the folded b0


def _gather_local(idx_ref, blk_ref, i, bn, dt):
    """Block-local one-hot gather: rows of ``blk_ref`` (node block ``i``)
    at global ids ``idx``.  Out-of-block ids produce an all-zero one-hot
    row — gathered value 0, and the same one-hot transposed gates the
    scatter, so such edges contribute nothing this visit (they are
    in-block for exactly one visiting node block)."""
    be = idx_ref.shape[0]
    loc = idx_ref[:] - i * bn
    onehot = (loc == jax.lax.broadcasted_iota(
        jnp.int32, (be, bn), 1)).astype(dt)
    return _dot(onehot, blk_ref[:], ((1,), (0,)), dt), onehot


def _edge_chain(xs, xr, geo_ref, w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref,
                dt):
    """Edge-MLP recompute: returns every intermediate the backward needs.
    The concat matmul of the composed path is split into three partial
    matmuls summed in f32 — same math, different f32 rounding order
    (tests bound the drift with the scf tolerance contract)."""
    t0 = (_dot(xs, w0s_ref[:], ((1,), (0,)), dt)
          + _dot(xr, w0r_ref[:], ((1,), (0,)), dt)
          + _dot(geo_ref[:], w0g_ref[:], ((1,), (0,)), dt))
    f1 = jnp.maximum(t0, 0.0)
    t1 = _dot(f1, w1_ref[:], ((1,), (0,)), dt) + b1_ref[0:1, :]
    m = jnp.maximum(t1, 0.0)
    return t0, f1, t1, m


def _coord_chain(m, geo_ref, wc0_ref, bc0_ref, wc1_ref, dt):
    """Coordinate gate recompute: c = tanh(relu(m@Wc0+bc0) @ Wc1) and the
    diff lanes of the geo stream (lanes 0..2) isolated for the
    translation product."""
    u0 = _dot(m, wc0_ref[:], ((1,), (0,)), dt) + bc0_ref[0:1, :]
    v = jnp.maximum(u0, 0.0)
    cp = _dot(v, wc1_ref[:], ((1,), (0,)), dt)  # [BE, 128]; col 0 real
    c = jnp.tanh(cp[:, 0:1])                    # [BE, 1]
    lane = jax.lax.broadcasted_iota(jnp.int32, geo_ref.shape, 1)
    diffm = jnp.where(lane < 3, geo_ref[:].astype(jnp.float32), 0.0)
    return u0, v, c, diffm


def _pack_edges(geo, em, senders, receivers, e_pad, n_pad):
    """Pad edge arrays; bias lane (_GP - 1) of geo is constant 1.0.

    MASKED edges (em == 0) are parked on the out-of-range sentinel node
    ``n_pad`` in both id columns, so the dense schedule assigns their
    edge blocks to NO node block and never visits them (scf_mp's
    schedule-skip — requires masked edges to tail-sort in both edge
    orderings, which collate guarantees by parking them on node N-1).
    Their outputs and grads are therefore exactly zero by construction."""
    e, gd = geo.shape
    geo_p = jnp.zeros((e_pad, _GP), jnp.float32)
    geo_p = geo_p.at[:e, :gd].set(geo.astype(jnp.float32))
    geo_p = geo_p.at[:, _GP - 1].set(1.0)
    valid = em != 0
    send_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, senders, n_pad).astype(jnp.int32))
    recv_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, receivers, n_pad).astype(jnp.int32))
    return geo_p, send_p, recv_p


def _pack_weights(equivariant, w0, b0, w1, b1, wc0, bc0, wc1,
                  f, f_pad, h_pad, bf16):
    """Split the composed path's concat kernel w0 [2F+1+A, H] into the
    three partial kernels the kernel consumes (sender rows, receiver
    rows, geometry rows on the geo lane layout) with b0 folded onto the
    geo bias lane; b1/bc0 as [8, H] row-broadcast blocks; wc1 [H, 1] on
    column 0 of a full tile."""
    h = w1.shape[0]
    gd = w0.shape[0] - 2 * f  # 1 radial + edge_attr lanes
    w0s = jnp.zeros((f_pad, h_pad), jnp.float32).at[:f, :h].set(
        w0[:f].astype(jnp.float32))
    w0r = jnp.zeros((f_pad, h_pad), jnp.float32).at[:f, :h].set(
        w0[f:2 * f].astype(jnp.float32))
    w0g = jnp.zeros((_GP, h_pad), jnp.float32)
    w0g = w0g.at[3:3 + gd, :h].set(w0[2 * f:].astype(jnp.float32))
    w0g = w0g.at[_GP - 1, :h].set(b0.astype(jnp.float32))
    w1_p = jnp.zeros((h_pad, h_pad), jnp.float32).at[:h, :h].set(
        w1.astype(jnp.float32))
    b1_p = jnp.zeros((8, h_pad), jnp.float32).at[:, :h].set(
        jnp.broadcast_to(b1.astype(jnp.float32), (8, h)))
    packs = [w0s, w0r, w0g, w1_p, b1_p]
    if equivariant:
        wc0_p = jnp.zeros((h_pad, h_pad), jnp.float32).at[:h, :h].set(
            wc0.astype(jnp.float32))
        bc0_p = jnp.zeros((8, h_pad), jnp.float32).at[:, :h].set(
            jnp.broadcast_to(bc0.astype(jnp.float32), (8, h)))
        wc1_p = jnp.zeros((h_pad, _GP), jnp.float32).at[:h, 0].set(
            wc1[:, 0].astype(jnp.float32))
        packs += [wc0_p, bc0_p, wc1_p]
    if bf16:
        # halves the constant blocks' VMEM; bias blocks stay f32 (added
        # after the f32-accumulating dots)
        packs = [p if p.shape[0] == 8 else p.astype(jnp.bfloat16)
                 for p in packs]
    return packs


# ---------------------------------------------------------------------------
# forward (sender-sorted)
# ---------------------------------------------------------------------------


def _fwd_kernel(equivariant, si_ref, se_ref, av_ref, fi_ref,
                send_ref, recv_ref, geo_ref,
                w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, *rest):
    from jax.experimental import pallas as pl

    if equivariant:
        (wc0_ref, bc0_ref, wc1_ref, xm1_ref, x0_ref, xp1_ref,
         agg_ref, psum_ref) = rest
    else:
        xm1_ref, x0_ref, xp1_ref, agg_ref = rest
        psum_ref = None

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        agg_ref[:] = jnp.zeros_like(agg_ref)
        if equivariant:
            psum_ref[:] = jnp.zeros_like(psum_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = agg_ref.shape[0]
        dt = w1_ref.dtype
        xs, onehot_s = _gather_local(send_ref, x0_ref, i, bn, dt)
        xr, _ = _gather_window(
            recv_ref, (xm1_ref, x0_ref, xp1_ref), i - 1, bn)
        _t0, _f1, _t1, m = _edge_chain(
            xs, xr, geo_ref, w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, dt)
        agg_ref[:] += _dot(onehot_s, m, ((0,), (0,)), dt)
        if equivariant:
            _u0, _v, c, diffm = _coord_chain(
                m, geo_ref, wc0_ref, bc0_ref, wc1_ref, dt)
            trans = jnp.clip(diffm * c, -100.0, 100.0)
            psum_ref[:] += _dot(onehot_s, trans, ((0,), (0,)), dt)


def _fwd_impl(equivariant, x, geo, em, senders, receivers, sender_perm,
              interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = x.shape
    e = geo.shape[0]
    f_pad = _round_up(max(f, 1), 128)
    bn, be = _NODE_BLOCK, _EDGE_BLOCK
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    geo_p, send_p, recv_p = _pack_edges(
        geo[sender_perm], em[sender_perm], senders[sender_perm],
        receivers[sender_perm], e_pad, n_pad)

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        send_p[:, 0], n_blocks, bn, be, n_eblocks)
    eix, xoff, const, outx = _window_maps(n_blocks)

    def run(packs, h_pad):
        n_w = len(packs)
        w_specs = [pl.BlockSpec(p.shape, const) for p in packs]
        out_specs = [pl.BlockSpec((bn, h_pad), outx)]
        out_shape = [jax.ShapeDtypeStruct((n_pad, h_pad), jnp.float32)]
        if equivariant:
            out_specs.append(pl.BlockSpec((bn, _GP), outx))
            out_shape.append(
                jax.ShapeDtypeStruct((n_pad, _GP), jnp.float32))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(s_max,),
            in_specs=[
                pl.BlockSpec((be, 1), eix),
                pl.BlockSpec((be, 1), eix),
                pl.BlockSpec((be, _GP), eix),
                *w_specs[:n_w],
                pl.BlockSpec((bn, f_pad), xoff(-1)),
                pl.BlockSpec((bn, f_pad), xoff(0)),
                pl.BlockSpec((bn, f_pad), xoff(1)),
            ],
            out_specs=out_specs if equivariant else out_specs[0],
        )
        return pl.pallas_call(
            functools.partial(_fwd_kernel, equivariant),
            out_shape=out_shape if equivariant else out_shape[0],
            grid_spec=grid_spec,
            interpret=interpret,
        )(step_i, step_eb, acc_valid, is_first,
          send_p, recv_p, geo_p, *packs, x_p, x_p, x_p)

    return run, (f_pad, n_pad, n, f)


# ---------------------------------------------------------------------------
# backward pass R: weight grads + dgeo + sender-side dx (sender-sorted)
# ---------------------------------------------------------------------------


def _bwd_r_kernel(equivariant, si_ref, se_ref, av_ref, fi_ref, feb_ref,
                  send_ref, recv_ref, geo_ref,
                  w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, *rest):
    from jax.experimental import pallas as pl

    if equivariant:
        (wc0_ref, bc0_ref, wc1_ref,
         xm1_ref, x0_ref, xp1_ref, ga0_ref, gp0_ref,
         dw0s_ref, dw0r_ref, dw0g_ref, dw1_ref, db1_ref,
         dwc0_ref, dbc0_ref, dwc1_ref, dgeo_ref, dx_ref) = rest
    else:
        (xm1_ref, x0_ref, xp1_ref, ga0_ref,
         dw0s_ref, dw0r_ref, dw0g_ref, dw1_ref, db1_ref,
         dgeo_ref, dx_ref) = rest

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(s == 0)
    def _init_w():
        dw0s_ref[:] = jnp.zeros_like(dw0s_ref)
        dw0r_ref[:] = jnp.zeros_like(dw0r_ref)
        dw0g_ref[:] = jnp.zeros_like(dw0g_ref)
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        db1_ref[:] = jnp.zeros_like(db1_ref)
        if equivariant:
            dwc0_ref[:] = jnp.zeros_like(dwc0_ref)
            dbc0_ref[:] = jnp.zeros_like(dbc0_ref)
            dwc1_ref[:] = jnp.zeros_like(dwc1_ref)

    @pl.when(fi_ref[s] == 1)
    def _init_x():
        dx_ref[:] = jnp.zeros_like(dx_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = dx_ref.shape[0]
        dt = w1_ref.dtype
        xs, onehot_s = _gather_local(send_ref, x0_ref, i, bn, dt)
        xr, _ = _gather_window(
            recv_ref, (xm1_ref, x0_ref, xp1_ref), i - 1, bn)
        t0, f1, t1, m = _edge_chain(
            xs, xr, geo_ref, w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, dt)
        # cotangent gathers at the SORTED side gate everything: an edge
        # whose sender is out of this block gets dm = dps = 0, zeroing its
        # whole grad chain this visit (its in-block visit supplies it)
        dm = _dot(onehot_s, ga0_ref[:], ((1,), (0,)), dt)
        if equivariant:
            u0, v, c, diffm = _coord_chain(
                m, geo_ref, wc0_ref, bc0_ref, wc1_ref, dt)
            dps = _dot(onehot_s, gp0_ref[:], ((1,), (0,)), dt)  # [BE, GP]
            ddiff = dps * c           # lanes >= 3 zero (cotangent padding)
            dc = jnp.sum(dps * diffm, axis=1, keepdims=True)    # [BE, 1]
            col = jax.lax.broadcasted_iota(jnp.int32, dps.shape, 1)
            dcp = jnp.where(col == 0, dc * (1.0 - c * c), 0.0)
            dwc1_ref[:] += _dot(v, dcp, ((0,), (0,)), dt)
            dv = _dot(dcp, wc1_ref[:], ((1,), (1,)), dt)
            du0 = dv * (u0 > 0)
            dwc0_ref[:] += _dot(m, du0, ((0,), (0,)), dt)
            dbc0_ref[:] += jnp.broadcast_to(
                jnp.sum(du0, axis=0, keepdims=True) / dbc0_ref.shape[0],
                dbc0_ref.shape)
            dm = dm + _dot(du0, wc0_ref[:], ((1,), (1,)), dt)
        dt1 = dm * (t1 > 0)
        dw1_ref[:] += _dot(f1, dt1, ((0,), (0,)), dt)
        db1_ref[:] += jnp.broadcast_to(
            jnp.sum(dt1, axis=0, keepdims=True) / db1_ref.shape[0],
            db1_ref.shape)
        df1 = _dot(dt1, w1_ref[:], ((1,), (1,)), dt)
        dt0 = df1 * (t0 > 0)
        dw0s_ref[:] += _dot(xs, dt0, ((0,), (0,)), dt)
        dw0r_ref[:] += _dot(xr, dt0, ((0,), (0,)), dt)
        dw0g_ref[:] += _dot(geo_ref[:], dt0, ((0,), (0,)), dt)
        # per-edge geometry grad stream: radial/edge_attr lanes from the
        # MLP input grad (w0g's diff rows are zero), diff lanes from the
        # translation product; the bias lane carries a per-edge db0 term
        # the caller discards (db0 is read off dw0g's bias row instead)
        dgeo_v = _dot(dt0, w0g_ref[:], ((1,), (1,)), dt)
        if equivariant:
            dgeo_v = dgeo_v + ddiff
        dgeo_ref[:] = jnp.where(feb_ref[s] == 1, dgeo_v,
                                dgeo_ref[:] + dgeo_v)
        dxs = _dot(dt0, w0s_ref[:], ((1,), (1,)), dt)
        dx_ref[:] += _dot(onehot_s, dxs, ((0,), (0,)), dt)

    # a freshly-entered edge block that is NOT accumulated this step (the
    # forced step of an empty node block) must still be initialized, or a
    # boundary block's second visit would accumulate onto garbage
    @pl.when((av_ref[s] == 0) & (feb_ref[s] == 1))
    def _init_e():
        dgeo_ref[:] = jnp.zeros_like(dgeo_ref)


# ---------------------------------------------------------------------------
# backward pass S: receiver-side dx (natural receiver-sorted order)
# ---------------------------------------------------------------------------


def _bwd_s_kernel(equivariant, si_ref, se_ref, av_ref, fi_ref,
                  send_ref, recv_ref, geo_ref,
                  w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, *rest):
    from jax.experimental import pallas as pl

    if equivariant:
        (wc0_ref, bc0_ref, wc1_ref,
         xm1_ref, x0_ref, xp1_ref,
         gam1_ref, ga0_ref, gap1_ref,
         gpm1_ref, gp0_ref, gpp1_ref, dx_ref) = rest
    else:
        (xm1_ref, x0_ref, xp1_ref,
         gam1_ref, ga0_ref, gap1_ref, dx_ref) = rest

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        dx_ref[:] = jnp.zeros_like(dx_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = dx_ref.shape[0]
        dt = w1_ref.dtype
        # roles swapped: receivers are the sorted/output side, senders ride
        # the window (cotangents included — both live at the sender)
        xr, onehot_r = _gather_local(recv_ref, x0_ref, i, bn, dt)
        xs, _ = _gather_window(
            send_ref, (xm1_ref, x0_ref, xp1_ref), i - 1, bn)
        t0, f1, t1, m = _edge_chain(
            xs, xr, geo_ref, w0s_ref, w0r_ref, w0g_ref, w1_ref, b1_ref, dt)
        dm, _ = _gather_window(
            send_ref, (gam1_ref, ga0_ref, gap1_ref), i - 1, bn)
        if equivariant:
            u0, v, c, diffm = _coord_chain(
                m, geo_ref, wc0_ref, bc0_ref, wc1_ref, dt)
            dps, _ = _gather_window(
                send_ref, (gpm1_ref, gp0_ref, gpp1_ref), i - 1, bn)
            dc = jnp.sum(dps * diffm, axis=1, keepdims=True)
            col = jax.lax.broadcasted_iota(jnp.int32, dps.shape, 1)
            dcp = jnp.where(col == 0, dc * (1.0 - c * c), 0.0)
            dv = _dot(dcp, wc1_ref[:], ((1,), (1,)), dt)
            du0 = dv * (u0 > 0)
            dm = dm + _dot(du0, wc0_ref[:], ((1,), (1,)), dt)
        dt1 = dm * (t1 > 0)
        df1 = _dot(dt1, w1_ref[:], ((1,), (1,)), dt)
        dt0 = df1 * (t0 > 0)
        dxr = _dot(dt0, w0r_ref[:], ((1,), (1,)), dt)
        dx_ref[:] += _dot(onehot_r, dxr, ((0,), (0,)), dt)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def egcl_block(equivariant, x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
               senders, receivers, sender_perm):
    """Fused EGCL interaction block.

    ``m_e = relu(relu([x[send_e], x[recv_e], geo_e] @ w0 + b0) @ w1 + b1)``
    then ``agg[n] = sum_{e: send[e]=n} m_e`` and (equivariant only)
    ``psum[n] = sum_{e: send[e]=n} clip(diff_e * tanh(relu(m_e @ wc0 +
    bc0) @ wc1), ±100)`` — returns ``(agg [N, H], psum [N, _GP] or None)``.
    ``psum``'s first 3 lanes are the translation sums; the caller divides
    by the sender degree for the segment-mean and adds to positions.

    ``geo`` is ``concat([diff_normed (3), radial (1), edge_attr (A)])``
    per edge — ONE canonical geometry definition shared with the composed
    path (models/egnn.py ``_edge_geometry``); its cotangent chains into
    position grads outside.  Differentiable wrt x, geo and all weights.

    Requires fused_mp's collate invariants plus the EGCL_* width limits
    (callers gate).  ``em`` is the int32 edge-validity mask: em == 0
    edges are schedule-skipped entirely and get EXACTLY ZERO for every
    output and grad (masked edges must tail-sort in both orderings —
    collate guarantees this)."""
    out, _ = _egcl_fwd_res(equivariant, x, geo, em, w0, b0, w1, b1,
                           wc0, bc0, wc1, senders, receivers, sender_perm)
    return out


def _egcl_fwd_res(equivariant, x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
                  senders, receivers, sender_perm):
    interpret = jax.default_backend() != "tpu"
    n, f = x.shape
    h = w1.shape[0]
    h_pad = _round_up(max(h, 1), 128)
    f_pad = _round_up(max(f, 1), 128)
    bf16 = x.dtype == jnp.bfloat16
    run, _dims = _fwd_impl(equivariant, x, geo, em, senders, receivers,
                           sender_perm, interpret)
    packs = _pack_weights(equivariant, w0, b0, w1, b1, wc0, bc0, wc1,
                          f, f_pad, h_pad, bf16)
    out = run(packs, h_pad)
    if equivariant:
        agg_p, psum_p = out
        agg = agg_p[:n, :h].astype(x.dtype)
        return (agg, psum_p[:n]), h_pad
    agg = out[:n, :h].astype(x.dtype)
    return (agg, None), h_pad


def _egcl_vjp_fwd(equivariant, x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
                  senders, receivers, sender_perm):
    out, _ = _egcl_fwd_res(equivariant, x, geo, em, w0, b0, w1, b1,
                           wc0, bc0, wc1, senders, receivers, sender_perm)
    return out, (x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
                 senders, receivers, sender_perm)


def _egcl_vjp_bwd(equivariant, res, ct):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    (x, geo, em, w0, b0, w1, b1, wc0, bc0, wc1,
     senders, receivers, sender_perm) = res
    ga, gp = ct
    interpret = jax.default_backend() != "tpu"
    n, f = x.shape
    e, gd = geo.shape
    h = w1.shape[0]
    bf16 = x.dtype == jnp.bfloat16
    f_pad = _round_up(max(f, 1), 128)
    h_pad = _round_up(max(h, 1), 128)
    bn, be = _NODE_BLOCK, _EDGE_BLOCK
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    ga_p = jnp.zeros((n_pad, h_pad), x.dtype).at[:n, :h].set(
        ga.astype(x.dtype))
    gp_p = None
    if equivariant:
        gp_p = jnp.zeros((n_pad, _GP), x.dtype).at[:n].set(
            gp.astype(x.dtype))
    packs = _pack_weights(equivariant, w0, b0, w1, b1, wc0, bc0, wc1,
                          f, f_pad, h_pad, bf16)
    eix, xoff, const, outx = _window_maps(n_blocks)

    # ---- pass R: sender-sorted — weight grads, dgeo, sender-side dx ----
    geo_s, send_s, recv_s = _pack_edges(
        geo[sender_perm], em[sender_perm], senders[sender_perm],
        receivers[sender_perm], e_pad, n_pad)
    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        send_s[:, 0], n_blocks, bn, be, n_eblocks)
    prev_eb = jnp.concatenate(
        [jnp.full(1, -1, jnp.int32), step_eb[:-1]])
    first_eb = (step_eb != prev_eb).astype(jnp.int32)

    w_specs = [pl.BlockSpec(p.shape, const) for p in packs]
    in_specs_r = [
        pl.BlockSpec((be, 1), eix),
        pl.BlockSpec((be, 1), eix),
        pl.BlockSpec((be, _GP), eix),
        *w_specs,
        pl.BlockSpec((bn, f_pad), xoff(-1)),
        pl.BlockSpec((bn, f_pad), xoff(0)),
        pl.BlockSpec((bn, f_pad), xoff(1)),
        pl.BlockSpec((bn, h_pad), xoff(0)),
    ]
    out_specs_r = [
        pl.BlockSpec((f_pad, h_pad), const),
        pl.BlockSpec((f_pad, h_pad), const),
        pl.BlockSpec((_GP, h_pad), const),
        pl.BlockSpec((h_pad, h_pad), const),
        pl.BlockSpec((8, h_pad), const),
    ]
    out_shape_r = [
        jax.ShapeDtypeStruct((f_pad, h_pad), jnp.float32),
        jax.ShapeDtypeStruct((f_pad, h_pad), jnp.float32),
        jax.ShapeDtypeStruct((_GP, h_pad), jnp.float32),
        jax.ShapeDtypeStruct((h_pad, h_pad), jnp.float32),
        jax.ShapeDtypeStruct((8, h_pad), jnp.float32),
    ]
    ins_r = [send_s, recv_s, geo_s, *packs, x_p, x_p, x_p, ga_p]
    if equivariant:
        in_specs_r.append(pl.BlockSpec((bn, _GP), xoff(0)))
        ins_r.append(gp_p)
        out_specs_r += [
            pl.BlockSpec((h_pad, h_pad), const),
            pl.BlockSpec((8, h_pad), const),
            pl.BlockSpec((h_pad, _GP), const),
        ]
        out_shape_r += [
            jax.ShapeDtypeStruct((h_pad, h_pad), jnp.float32),
            jax.ShapeDtypeStruct((8, h_pad), jnp.float32),
            jax.ShapeDtypeStruct((h_pad, _GP), jnp.float32),
        ]
    out_specs_r += [
        pl.BlockSpec((be, _GP), eix),
        pl.BlockSpec((bn, f_pad), outx),
    ]
    out_shape_r += [
        jax.ShapeDtypeStruct((e_pad, _GP), jnp.float32),
        jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
    ]
    grid_r = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s_max,),
        in_specs=in_specs_r,
        out_specs=out_specs_r,
    )
    outs_r = pl.pallas_call(
        functools.partial(_bwd_r_kernel, equivariant),
        out_shape=out_shape_r,
        grid_spec=grid_r,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, first_eb, *ins_r)
    if equivariant:
        (dw0s_p, dw0r_p, dw0g_p, dw1_p, db1_p,
         dwc0_p, dbc0_p, dwc1_p, dgeo_p, dxs_p) = outs_r
    else:
        dw0s_p, dw0r_p, dw0g_p, dw1_p, db1_p, dgeo_p, dxs_p = outs_r

    # ---- pass S: natural receiver order — receiver-side dx ----
    geo_n, send_n, recv_n = _pack_edges(
        geo, em, senders, receivers, e_pad, n_pad)
    step_i2, step_eb2, acc_valid2, is_first2, s_max2 = _dense_schedule(
        recv_n[:, 0], n_blocks, bn, be, n_eblocks)
    in_specs_s = [
        pl.BlockSpec((be, 1), eix),
        pl.BlockSpec((be, 1), eix),
        pl.BlockSpec((be, _GP), eix),
        *w_specs,
        pl.BlockSpec((bn, f_pad), xoff(-1)),
        pl.BlockSpec((bn, f_pad), xoff(0)),
        pl.BlockSpec((bn, f_pad), xoff(1)),
        pl.BlockSpec((bn, h_pad), xoff(-1)),
        pl.BlockSpec((bn, h_pad), xoff(0)),
        pl.BlockSpec((bn, h_pad), xoff(1)),
    ]
    ins_s = [send_n, recv_n, geo_n, *packs, x_p, x_p, x_p,
             ga_p, ga_p, ga_p]
    if equivariant:
        in_specs_s += [pl.BlockSpec((bn, _GP), xoff(-1)),
                       pl.BlockSpec((bn, _GP), xoff(0)),
                       pl.BlockSpec((bn, _GP), xoff(1))]
        ins_s += [gp_p, gp_p, gp_p]
    grid_s = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max2,),
        in_specs=in_specs_s,
        out_specs=pl.BlockSpec((bn, f_pad), outx),
    )
    dxr_p = pl.pallas_call(
        functools.partial(_bwd_s_kernel, equivariant),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        grid_spec=grid_s,
        interpret=interpret,
    )(step_i2, step_eb2, acc_valid2, is_first2, *ins_s)

    dx = (dxs_p[:n, :f] + dxr_p[:n, :f]).astype(x.dtype)
    # pass R ran in sender order: un-permute the per-edge stream, then
    # `where`-select masked rows to zero — their blocks are never visited
    # so the memory is uninitialized (a multiply would propagate NaN bits)
    dgeo_nat = jnp.zeros((e, _GP), jnp.float32).at[sender_perm].set(
        dgeo_p[:e])
    valid = (em != 0)[:, None]
    dgeo = jnp.where(valid, dgeo_nat[:, :gd], 0.0).astype(geo.dtype)
    # reassemble the composed concat kernel's grad: sender rows, receiver
    # rows, then the geometry rows (geo lanes 3..3+gd map to w0[2F:])
    dw0 = jnp.concatenate([
        dw0s_p[:f, :h], dw0r_p[:f, :h],
        dw0g_p[3:3 + (w0.shape[0] - 2 * f), :h],
    ], axis=0).astype(w0.dtype)
    db0 = dw0g_p[_GP - 1, :h].astype(b0.dtype)
    dw1 = dw1_p[:h, :h].astype(w1.dtype)
    db1 = jnp.sum(db1_p[:, :h], axis=0).astype(b1.dtype)
    if equivariant:
        dwc0 = dwc0_p[:h, :h].astype(wc0.dtype)
        dbc0 = jnp.sum(dbc0_p[:, :h], axis=0).astype(bc0.dtype)
        dwc1 = dwc1_p[:h, 0:1].astype(wc1.dtype)
    else:
        dwc0 = dbc0 = dwc1 = None
    return (dx, dgeo, None, dw0, db0, dw1, db1, dwc0, dbc0, dwc1,
            None, None, None)


egcl_block.defvjp(_egcl_vjp_fwd, _egcl_vjp_bwd)
