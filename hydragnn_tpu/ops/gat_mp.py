"""Fused GATv2 edge attention: gather -> logits -> online softmax ->
weighted aggregation in ONE Pallas pass (round-4 VERDICT item 3).

The composed implementation (models/gat.py round 3) spends ~10.7 ms/layer
fwd+bwd at the v5e sweep shapes across five separate segment ops (two
logits gathers, segment max, denominator scatter, weighted aggregation),
each materializing [E, H*F] or [E, H] intermediates in HBM.  This kernel
computes the whole edge-side attention in one dense-schedule pass over the
receiver-sorted edge blocks (the same CSR-style scalar-prefetched schedule
as ops/fused_mp.py), flash-attention style:

  for each node block i (rows of out), iterating its edge blocks:
      xs = one-hot window gather of xl at senders     (3-block locality)
      xt = one-hot gather of xr at receivers          (block-local)
      e  = leaky_relu(xs + xt) @ att_mat              [BE, H]   (MXU)
      online-rescale (m, d, acc) with p = exp(e - m); the numerator uses
      the caller's dropout bits
  returns acc[n] = sum_e p_e b_e xl[src_e],  m[n] = max_e e_e,
          d[n]   = sum_e p_e          (softmax-then-dropout convention:
                                       the denominator ignores dropout)

The SELF-LOOP term and the final normalization are merged OUTSIDE in plain
jnp (models/gat.py): softmax shift-invariance makes ``stop_gradient(m)``
exact there, so the merge is ordinary autodiff'd elementwise code.

Backward (custom VJP, no [E, H*F] HBM intermediates): with m frozen,
  dL/de_k      = p_k (b_k <ga[r], xl[s]>_h + gd[r, h])
  dxl[s]      += p_k b_k ga[r] + dz_k        (pass S, sender-sorted)
  dxr[r]      += dz_k                        (pass R, receiver-sorted)
  datt_mat    += z^T de                      (pass R, accumulated)
  dz_k         = (de_k @ att_mat^T) * leaky_relu'(xs + xt)
Both passes recompute z/e/p from the saved inputs (flash-attention's
recompute-over-store trade), so only [N, .] arrays ever hit HBM.

Invariants REQUIRED (same as fused_mp): receivers nondecreasing; graphs
contiguous and within one node block, so a triple-block window covers
every edge's other endpoint; ``sender_perm`` = stable argsort of senders
(collate's ``edge_perm_sender``).  Reference: GATStack.py:87-113 + PyG
GATv2Conv.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import (
    _NODE_BLOCK, _dense_schedule)
from hydragnn_tpu.ops.fused_block import _window_maps as _shared_window_maps

_EDGE_BLOCK = 512

# Widest flat head-feature width (h*f) ONE fused kernel call compiles for:
# the per-iteration [BE, HF] temporaries and the double-buffered [BN, HF]
# window blocks scale with HF against the v5e's 16 MB scoped-VMEM budget.
# Measured on the v5e: hf=768 (34.6 ms/step) and hf=1020 (49.3 ms/step)
# compile and run at BE=256; hf=1536 (h256 x 6 heads) OOMs at BE=512 AND
# at BE=128 (the backward's seven double-buffered [BN, HF] node windows
# alone approach the budget).  Wider configs stay fused by TILING over the
# flat head-feature axis (:func:`gat_edge_attention_tiled`): attention is
# independent per head, so the heads split into balanced groups of
# group_hf <= this limit, one kernel call each.  Only a SINGLE head wider
# than the limit (f > FUSED_HF_LIMIT) still forces the composed path.
FUSED_HF_LIMIT = 1024


def _edge_block(hf: int) -> int:
    """Edge-block size that keeps the kernels' [BE, HF]-scale temporaries
    (4-5 live per iteration, f32) inside scoped VMEM alongside the
    double-buffered [BN, HF] node windows (hf=768 -> BE=256 measured
    34.6 ms/step at the h128 sweep config, vs 36.1 at BE=512)."""
    return _EDGE_BLOCK if hf <= 512 else 256


# sentinels deliberately 1e9, NOT 1e30: they ride one-hot MATMULS (m_e =
# onehot @ m), and reduced-precision matmul backends (CPU oneDNN tf32-ish
# rounding; MXU bf16 passes) round huge magnitudes with absolute errors
# that can flip exp(e - m_e) into overflow -> inf * 0 = NaN.  At 1e9 the
# worst rounding error (~5e-4 relative = 5e5) still leaves exp(-1e9 +
# 5e5) == 0 exactly.
_NEG = -1e9
_POS = 1e9
_HP = 128  # head-axis lane padding (H <= 128)


def _window_maps(n_blocks):
    """GAT-shaped view of the builder's shared index maps: the ±1 window
    unrolled to named slots (the attention kernels address window blocks
    individually rather than as a spec-generated list)."""
    eix, xoff, const, _outx = _shared_window_maps(n_blocks)
    return eix, xoff(-1), xoff(0), xoff(1), const


def _head_expander(hf: int, f: int):
    """[Hp, HF] 0/1 matrix: lane l of the output belongs to head l // f."""
    head = jax.lax.broadcasted_iota(jnp.int32, (_HP, hf), 1) // f
    row = jax.lax.broadcasted_iota(jnp.int32, (_HP, hf), 0)
    return (head == row).astype(jnp.float32)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _make_fwd_kernel(slope: float, f: int, h: int):
    from jax.experimental import pallas as pl

    def kernel(si_ref, se_ref, av_ref, fi_ref,
               send_ref, recv_ref, mask_ref, b_ref, am_ref,
               xlm1_ref, xl0_ref, xlp1_ref, xr0_ref,
               acc_ref, m_ref, d_ref):
        s = pl.program_id(0)
        i = si_ref[s]

        @pl.when(fi_ref[s] == 1)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            # garbage head lanes (>= h) pin to 0 so their p stays exp(0)=1
            # (finite) — they are sliced away on the host side
            lane = jax.lax.broadcasted_iota(jnp.int32, m_ref.shape, 1)
            m_ref[:] = jnp.where(lane < h, _NEG, 0.0)
            d_ref[:] = jnp.zeros_like(d_ref)

        @pl.when(av_ref[s] == 1)
        def _acc():
            bn = acc_ref.shape[0]
            be = send_ref.shape[0]
            hf = acc_ref.shape[1]
            base = (i - 1) * bn
            sloc = send_ref[:] - base
            onehot_s = (sloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, 3 * bn), 1)).astype(jnp.float32)
            xcat = jnp.concatenate(
                [xlm1_ref[:], xl0_ref[:], xlp1_ref[:]],
                axis=0).astype(jnp.float32)
            xs = jax.lax.dot_general(
                onehot_s, xcat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [BE, HF]
            rloc = recv_ref[:] - i * bn
            onehot_r = (rloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, bn), 1)).astype(jnp.float32)
            xt = jax.lax.dot_general(
                onehot_r, xr0_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            zpre = xs + xt
            z = jnp.where(zpre > 0, zpre, slope * zpre)
            e = jax.lax.dot_general(
                z, am_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [BE, Hp]
            valid = (jnp.sum(onehot_r, axis=1, keepdims=True)
                     * mask_ref[:].astype(jnp.float32))
            e = jnp.where(valid > 0, e, _NEG)
            # per-head block max (static H loop keeps intermediates 2D —
            # a [BE, BN, Hp] masked-max blob would blow VMEM)
            m_blk = m_ref[:]
            lane_n = jax.lax.broadcasted_iota(
                jnp.int32, (bn, m_blk.shape[1]), 1)
            bm = jnp.zeros_like(m_blk)
            for hh in range(h):
                masked = jnp.where(
                    onehot_r > 0, e[:, hh][:, None], _NEG)  # [BE, BN]
                bm_h = jnp.max(masked, axis=0)              # [BN]
                bm = jnp.where(lane_n == hh, bm_h[:, None], bm)
            m_new = jnp.maximum(m_blk, bm)
            r = jnp.exp(m_blk - m_new)                      # [BN, Hp]
            m_e = jax.lax.dot_general(
                onehot_r, m_new, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = jnp.exp(e - m_e) * valid                    # [BE, Hp]
            d_ref[:] = d_ref[:] * r + jax.lax.dot_general(
                onehot_r, p, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ex = _head_expander(hf, f)                      # [Hp, HF]
            pb_x = jax.lax.dot_general(
                p * b_ref[:].astype(jnp.float32), ex,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [BE, HF]
            r_x = jax.lax.dot_general(
                r, ex, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [BN, HF]
            acc_ref[:] = acc_ref[:] * r_x + jax.lax.dot_general(
                onehot_r, xs * pb_x, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[:] = m_new

    return kernel


def _pad_nodes(x, n_pad):
    n = x.shape[0]
    return jnp.zeros((n_pad,) + x.shape[1:], jnp.float32).at[:n].set(
        x.astype(jnp.float32))


def _pad_edges(senders, receivers, edge_mask, b_edge, n_pad, e_pad):
    e = senders.shape[0]
    send_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        senders.astype(jnp.int32))
    recv_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        receivers.astype(jnp.int32))
    # collate parks padding edges on REAL node N-1 — they must not enter
    # any node's max/denominator, so the mask is an explicit kernel input
    # (a zero dropout bit is NOT equivalent: dropped real edges still
    # count in the denominator)
    mask_p = jnp.zeros((e_pad, 1), jnp.float32).at[:e, 0].set(
        edge_mask.astype(jnp.float32))
    b_p = jnp.zeros((e_pad, _HP), jnp.float32).at[:e, :b_edge.shape[1]].set(
        b_edge.astype(jnp.float32))
    return send_p, recv_p, mask_p, b_p


def _fwd_impl(xl, xr, att_mat, senders, receivers, edge_mask, b_edge,
              slope, f, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, hf = xl.shape
    h = att_mat.shape[1]
    bn, be = _NODE_BLOCK, _edge_block(hf)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(senders.shape[0], 1), be)
    xl_p = _pad_nodes(xl, n_pad)
    xr_p = _pad_nodes(xr, n_pad)
    send_p, recv_p, mask_p, b_p = _pad_edges(
        senders, receivers, edge_mask, b_edge, n_pad, e_pad)
    am_p = jnp.zeros((hf, _HP), jnp.float32).at[:, :h].set(
        att_mat.astype(jnp.float32))
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)
    eix, xm1, x0, xp1, const = _window_maps(n_blocks)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, _HP), eix),
            pl.BlockSpec((hf, _HP), const),
            pl.BlockSpec((bn, hf), xm1),
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, hf), xp1),
            pl.BlockSpec((bn, hf), x0),
        ],
        out_specs=[
            pl.BlockSpec((bn, hf), lambda s, si, se, av, fi: (si[s], 0)),
            pl.BlockSpec((bn, _HP), lambda s, si, se, av, fi: (si[s], 0)),
            pl.BlockSpec((bn, _HP), lambda s, si, se, av, fi: (si[s], 0)),
        ],
    )
    acc, m, d = pl.pallas_call(
        _make_fwd_kernel(slope, f, h),
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, hf), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, _HP), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, _HP), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first,
      send_p, recv_p, mask_p, b_p, am_p, xl_p, xl_p, xl_p, xr_p)
    return acc[:n], m[:n, :h], d[:n, :h]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _make_bwd_r_kernel(slope: float, f: int):
    """Receiver-sorted pass: dxr (block rows) + datt_mat (accumulated)."""
    from jax.experimental import pallas as pl

    def kernel(si_ref, se_ref, av_ref, fi_ref,
               send_ref, recv_ref, mask_ref, b_ref, am_ref, qm_ref,
               xlm1_ref, xl0_ref, xlp1_ref, xr0_ref, ga0_ref, mg0_ref,
               dxr_ref, datt_ref):
        s = pl.program_id(0)
        i = si_ref[s]

        @pl.when(fi_ref[s] == 1)
        def _init():
            dxr_ref[:] = jnp.zeros_like(dxr_ref)

        @pl.when(s == 0)
        def _init_att():
            datt_ref[:] = jnp.zeros_like(datt_ref)

        @pl.when(av_ref[s] == 1)
        def _acc():
            bn = dxr_ref.shape[0]
            be = send_ref.shape[0]
            hf = dxr_ref.shape[1]
            base = (i - 1) * bn
            sloc = send_ref[:] - base
            onehot_s = (sloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, 3 * bn), 1)).astype(jnp.float32)
            xcat = jnp.concatenate(
                [xlm1_ref[:], xl0_ref[:], xlp1_ref[:]],
                axis=0).astype(jnp.float32)
            xs = jax.lax.dot_general(
                onehot_s, xcat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            rloc = recv_ref[:] - i * bn
            onehot_r = (rloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, bn), 1)).astype(jnp.float32)
            xt = jax.lax.dot_general(
                onehot_r, xr0_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            zpre = xs + xt
            z = jnp.where(zpre > 0, zpre, slope * zpre)
            e = jax.lax.dot_general(
                z, am_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            valid = (jnp.sum(onehot_r, axis=1, keepdims=True)
                     * mask_ref[:].astype(jnp.float32))
            ga_e = jax.lax.dot_general(
                onehot_r, ga0_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            mg = mg0_ref[:].astype(jnp.float32)            # [BN, 2*Hp]
            m_e = jax.lax.dot_general(
                onehot_r, mg[:, :_HP], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            # rows with no one-hot (other-block/padding edges) get m_e = 0
            # while e = -1e30 -> p = 0; real rows read the true m
            gd_e = jax.lax.dot_general(
                onehot_r, mg[:, _HP:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            e = jnp.where(valid > 0, e, _NEG)
            p = jnp.exp(e - m_e) * valid
            q = jax.lax.dot_general(
                xs * ga_e, qm_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [BE, Hp]
            de = p * (b_ref[:].astype(jnp.float32) * q + gd_e)
            dz = jax.lax.dot_general(
                de, am_ref[:].astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)        # [BE, HF]
            dz = dz * jnp.where(zpre > 0, 1.0, slope)
            dxr_ref[:] += jax.lax.dot_general(
                onehot_r, dz, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            datt_ref[:] += jax.lax.dot_general(
                z, de, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [HF, Hp]

    return kernel


def _make_bwd_s_kernel(slope: float, f: int):
    """Sender-sorted pass: dxl rows = sum_e (p b ga[r] + dz)."""
    from jax.experimental import pallas as pl

    def kernel(si_ref, se_ref, av_ref, fi_ref,
               send_ref, recv_ref, mask_ref, b_ref, am_ref, qm_ref,
               xl0_ref, xrm1_ref, xr0_ref, xrp1_ref,
               gam1_ref, ga0_ref, gap1_ref, mgm1_ref, mg0_ref, mgp1_ref,
               dxl_ref):
        s = pl.program_id(0)
        i = si_ref[s]

        @pl.when(fi_ref[s] == 1)
        def _init():
            dxl_ref[:] = jnp.zeros_like(dxl_ref)

        @pl.when(av_ref[s] == 1)
        def _acc():
            bn = dxl_ref.shape[0]
            be = send_ref.shape[0]
            hf = dxl_ref.shape[1]
            # sorted side: SENDERS in block i
            sloc = send_ref[:] - i * bn
            onehot_s = (sloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, bn), 1)).astype(jnp.float32)
            base = (i - 1) * bn
            rloc = recv_ref[:] - base
            onehot_r = (rloc == jax.lax.broadcasted_iota(
                jnp.int32, (be, 3 * bn), 1)).astype(jnp.float32)
            xs = jax.lax.dot_general(
                onehot_s, xl0_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            xrcat = jnp.concatenate(
                [xrm1_ref[:], xr0_ref[:], xrp1_ref[:]],
                axis=0).astype(jnp.float32)
            xt = jax.lax.dot_general(
                onehot_r, xrcat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            gacat = jnp.concatenate(
                [gam1_ref[:], ga0_ref[:], gap1_ref[:]],
                axis=0).astype(jnp.float32)
            ga_e = jax.lax.dot_general(
                onehot_r, gacat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            mgcat = jnp.concatenate(
                [mgm1_ref[:], mg0_ref[:], mgp1_ref[:]],
                axis=0).astype(jnp.float32)
            mg_e = jax.lax.dot_general(
                onehot_r, mgcat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)        # [BE, 2Hp]
            m_e = mg_e[:, :_HP]
            gd_e = mg_e[:, _HP:]
            zpre = xs + xt
            z = jnp.where(zpre > 0, zpre, slope * zpre)
            e = jax.lax.dot_general(
                z, am_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            valid = (jnp.sum(onehot_s, axis=1, keepdims=True)
                     * mask_ref[:].astype(jnp.float32))
            e = jnp.where(valid > 0, e, _NEG)
            p = jnp.exp(e - m_e) * valid
            q = jax.lax.dot_general(
                xs * ga_e, qm_ref[:].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            b = b_ref[:].astype(jnp.float32)
            de = p * (b * q + gd_e)
            dz = jax.lax.dot_general(
                de, am_ref[:].astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dz = dz * jnp.where(zpre > 0, 1.0, slope)
            ex = _head_expander(hf, f)
            pb_x = jax.lax.dot_general(
                p * b, ex, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            contrib = pb_x * ga_e + dz
            dxl_ref[:] += jax.lax.dot_general(
                onehot_s, contrib, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    return kernel


# ---------------------------------------------------------------------------
# public custom-vjp op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(8,))
def gat_edge_attention(xl, xr, att_mat, senders, receivers, sender_perm,
                       edge_mask, b_edge, slope_f):
    """Edge-side GATv2 attention partials.

    Returns (acc [N, HF], m [N, H], d [N, H]) where, over each node's REAL
    incident edges: m = max logit, d = sum exp(e - m),
    acc = sum exp(e - m) * b * xl[src].  The caller merges the self-loop
    and normalizes — and MUST ``stop_gradient`` the m it uses (softmax
    shift-invariance makes that exact; this op's backward treats m as a
    constant and returns a zero cotangent through it).

    ``att_mat`` [HF, H]: block-diagonal logit matrix (att[h, f] at row
    h*F+f, column h) — build it with jnp ops from the [H, F] parameter so
    autodiff carries datt_mat back to it.
    ``b_edge`` [E, H]: edge_mask times dropout-bits/keep (ones for eval).
    ``slope_f``: static (negative_slope, per-head F) pair.
    Differentiable wrt xl, xr, att_mat.
    """
    slope, f = slope_f
    interpret = jax.default_backend() != "tpu"
    return _fwd_impl(xl, xr, att_mat, senders, receivers, edge_mask, b_edge,
                     slope, f, interpret)


def _gea_fwd(xl, xr, att_mat, senders, receivers, sender_perm, edge_mask,
             b_edge, slope_f):
    out = gat_edge_attention(xl, xr, att_mat, senders, receivers,
                             sender_perm, edge_mask, b_edge, slope_f)
    _, m, _ = out
    return out, (xl, xr, att_mat, senders, receivers, sender_perm,
                 edge_mask, b_edge, m)


def _gea_bwd(slope_f, res, cot):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slope, f = slope_f
    xl, xr, att_mat, senders, receivers, sender_perm, edge_mask, b_edge, m \
        = res
    ga, _gm, gd = cot  # gm is zero by contract (caller stop_gradients m)
    interpret = jax.default_backend() != "tpu"

    n, hf = xl.shape
    h = att_mat.shape[1]
    bn, be = _NODE_BLOCK, _edge_block(hf)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(senders.shape[0], 1), be)
    xl_p = _pad_nodes(xl, n_pad)
    xr_p = _pad_nodes(xr, n_pad)
    send_p, recv_p, mask_p, b_p = _pad_edges(
        senders, receivers, edge_mask, b_edge, n_pad, e_pad)
    am_p = jnp.zeros((hf, _HP), jnp.float32).at[:, :h].set(
        att_mat.astype(jnp.float32))
    rows = jnp.arange(hf)
    qm_p = jnp.zeros((hf, _HP), jnp.float32).at[rows, rows // f].set(1.0)
    ga_p = _pad_nodes(ga, n_pad)
    # m and gd ride one concatenated [N, 2*Hp] array; the m half fills
    # padding rows/lanes with +BIG so their p = exp(e - BIG) underflows to
    # zero instead of overflowing to inf*0 = NaN
    mg = jnp.full((n_pad, 2 * _HP), _POS, jnp.float32)
    mg = mg.at[:n, :h].set(m.astype(jnp.float32))
    mg = mg.at[:, _HP:].set(0.0)
    mg = mg.at[:n, _HP:_HP + h].set(gd.astype(jnp.float32))
    n_blocks, n_eblocks = n_pad // bn, e_pad // be
    eix, xm1, x0, xp1, const = _window_maps(n_blocks)

    # ---- pass R: receiver-sorted (the natural edge order) ----
    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)
    grid_r = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, _HP), eix),
            pl.BlockSpec((hf, _HP), const),
            pl.BlockSpec((hf, _HP), const),
            pl.BlockSpec((bn, hf), xm1),
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, hf), xp1),
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, 2 * _HP), x0),
        ],
        out_specs=[
            pl.BlockSpec((bn, hf), lambda s, si, se, av, fi: (si[s], 0)),
            pl.BlockSpec((hf, _HP), const),
        ],
    )
    dxr, datt = pl.pallas_call(
        _make_bwd_r_kernel(slope, f),
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, hf), jnp.float32),
            jax.ShapeDtypeStruct((hf, _HP), jnp.float32),
        ],
        grid_spec=grid_r,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first,
      send_p, recv_p, mask_p, b_p, am_p, qm_p,
      xl_p, xl_p, xl_p, xr_p, ga_p, mg)

    # ---- pass S: sender-sorted (via the host-precomputed permutation) ----
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)
    perm = sender_perm.astype(jnp.int32)
    e_n = senders.shape[0]
    send_s = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e_n, 0].set(
        senders[perm].astype(jnp.int32))
    recv_s = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e_n, 0].set(
        receivers[perm].astype(jnp.int32))
    b_s = jnp.zeros((e_pad, _HP), jnp.float32).at[:e_n, :b_edge.shape[1]].set(
        b_edge[perm].astype(jnp.float32))
    mask_s = jnp.zeros((e_pad, 1), jnp.float32).at[:e_n, 0].set(
        edge_mask[perm].astype(jnp.float32))
    step_i2, step_eb2, acc_valid2, is_first2, s_max2 = _dense_schedule(
        send_s[:, 0], n_blocks, bn, be, n_eblocks)
    grid_s = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max2,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, _HP), eix),
            pl.BlockSpec((hf, _HP), const),
            pl.BlockSpec((hf, _HP), const),
            pl.BlockSpec((bn, hf), x0),       # xl block (sender side)
            pl.BlockSpec((bn, hf), xm1),      # xr windows
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, hf), xp1),
            pl.BlockSpec((bn, hf), xm1),      # ga windows
            pl.BlockSpec((bn, hf), x0),
            pl.BlockSpec((bn, hf), xp1),
            pl.BlockSpec((bn, 2 * _HP), xm1),  # mg windows
            pl.BlockSpec((bn, 2 * _HP), x0),
            pl.BlockSpec((bn, 2 * _HP), xp1),
        ],
        out_specs=pl.BlockSpec(
            (bn, hf), lambda s, si, se, av, fi: (si[s], 0)),
    )
    dxl = pl.pallas_call(
        _make_bwd_s_kernel(slope, f),
        out_shape=jax.ShapeDtypeStruct((n_pad, hf), jnp.float32),
        grid_spec=grid_s,
        interpret=interpret,
    )(step_i2, step_eb2, acc_valid2, is_first2,
      send_s, recv_s, mask_s, b_s, am_p, qm_p,
      xl_p, xr_p, xr_p, xr_p, ga_p, ga_p, ga_p, mg, mg, mg)

    return (dxl[:n].astype(xl.dtype), dxr[:n].astype(xr.dtype),
            datt[:, :h].astype(att_mat.dtype), None, None, None,
            jnp.zeros_like(edge_mask), jnp.zeros_like(b_edge))


gat_edge_attention.defvjp(_gea_fwd, _gea_bwd)


def fused_head_width_ok(f: int) -> bool:
    """The per-head width gate, reading THIS module's live limit — the
    dispatcher (models/gat.py) queries it instead of caching an
    import-time copy, so adjusting FUSED_HF_LIMIT at runtime (tests,
    smaller-VMEM parts) moves the gate and the tiling together."""
    return f <= FUSED_HF_LIMIT


def _head_groups(h: int, f: int):
    """Balanced head-group sizes with group_hf = size * f <= FUSED_HF_LIMIT.

    Groups are as equal as possible (6 heads at cap 4 -> [3, 3], not
    [4, 2]) so same-shaped calls share one compiled kernel."""
    assert f <= FUSED_HF_LIMIT, "single head exceeds the kernel width cap"
    gmax = max(1, FUSED_HF_LIMIT // f)
    n_groups = -(-h // gmax)
    base, rem = divmod(h, n_groups)
    return [base + 1] * rem + [base] * (n_groups - rem)


def gat_edge_attention_tiled(xl, xr, att_mat, senders, receivers,
                             sender_perm, edge_mask, b_edge, slope_f):
    """:func:`gat_edge_attention`, tiled over the flat head-feature axis
    so hf = h*f > FUSED_HF_LIMIT configs (h256 x 6 heads = 1536, the
    round-4 VMEM OOM) STAY on the fused path instead of silently
    reverting to the composed segment ops.  Attention is independent per
    head, so the heads split into balanced groups of group_hf <= the
    limit — one kernel call per group over column slices of
    xl / xr / att_mat / b_edge, outputs concatenated back.  Gradients
    flow through the slicing (each group's custom VJP applies); the
    caller's stop_gradient(m) contract is unchanged.  Within the limit
    this is exactly one untiled call."""
    slope, f = slope_f
    h = att_mat.shape[1]
    if h * f <= FUSED_HF_LIMIT:
        return gat_edge_attention(xl, xr, att_mat, senders, receivers,
                                  sender_perm, edge_mask, b_edge, slope_f)
    accs, ms, ds = [], [], []
    h0 = 0
    for size in _head_groups(h, f):
        h1 = h0 + size
        cols = slice(h0 * f, h1 * f)
        acc, m, d = gat_edge_attention(
            xl[:, cols], xr[:, cols], att_mat[cols, h0:h1], senders,
            receivers, sender_perm, edge_mask, b_edge[:, h0:h1], slope_f)
        accs.append(acc)
        ms.append(m)
        ds.append(d)
        h0 = h1
    return (jnp.concatenate(accs, axis=1), jnp.concatenate(ms, axis=1),
            jnp.concatenate(ds, axis=1))
