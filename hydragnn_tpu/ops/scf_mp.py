"""SchNet CFConv as a thin spec on the fused-block builder
(:mod:`hydragnn_tpu.ops.fused_block`): filter-MLP -> gather -> multiply ->
segment sum in ONE Pallas pass, forward AND backward — no [E, F] HBM
streams.

  filt_e = (ssp(rbf_e @ W0 + b0) @ W1 + b1) * cm_e
  out[n] = sum_{e: recv[e]=n} h[send_e] * filt_e

The geometry stream carries the rbf lanes, the cutoff*mask ``cm`` on lane
G, and the builder's constant bias lane last (b0 folded onto W0's
matching row) — so dcm falls out of the geometry cotangent with no
special-casing.  Motivation, measured numbers and the recompute-over-
store trade are in docs/PERF.md; schedule/VJP mechanics live in the
builder.

Width limits: G (num_gaussians) <= 127 and F <= SCF_F_LIMIT (VMEM: W1
and its grad accumulator are [F, F] f32 blocks).  Callers gate on both
and fall back to the composed path.
"""

from __future__ import annotations

import functools
import os

import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import (
    _GP, EdgeBlockSpec, _dot, _ssp, build_fused_edge_op)

_EDGE_BLOCK = 128  # [BE, F] temporaries x ~8 live + [F, F] weights in VMEM
SCF_F_LIMIT = 1024


def _edge_block_fwd(f_pad: int, bf16: bool) -> int:
    """Forward / pass-S edge block: 256 halves the schedule's per-step
    overhead and doubles the one-hot matmul's MXU utilization; it fits
    scoped VMEM except at wide-F f32 (W1 4 MB + f32 windows + [BE, F]
    temporaries).  Pass P keeps 128 — its dW1 accumulator block doubles
    the resident [F, F] footprint."""
    return 256 if (f_pad <= 512 or bf16) else _EDGE_BLOCK


def _edge_block_r(f_pad: int, bf16: bool) -> int:
    """Pass P edge block: 128 everywhere (the resident dW1 [F, F] f32
    accumulator plus ~8 [BE, F] f32 temporaries cap the block well below
    fwd/pass-S's).  HYDRAGNN_SCF_BE_R overrides for sweeps; the sweep
    result (if a larger block wins at some width) gets baked here with
    the measurement.  f_pad/bf16 are the future conditioning inputs."""
    v = os.environ.get("HYDRAGNN_SCF_BE_R")
    if v:
        return int(v)
    del f_pad, bf16
    return _EDGE_BLOCK


def _make_chain(g: int):
    def chain(w_vals, geo, xp, xo, dt):
        w0, w1, b1 = w_vals
        t0 = _dot(geo, w0, ((1,), (0,)), dt)
        f2 = _dot(_ssp(t0), w1, ((1,), (0,)), dt) + b1[0:1, :]
        filt = f2 * geo[:, g:g + 1]        # cm rides geometry lane G
        return (xo * filt,)
    return chain


@functools.lru_cache(maxsize=None)
def _scf_op(g: int):
    return build_fused_edge_op(EdgeBlockSpec(
        name="scf", primary="receiver", gather_primary=False,
        gather_other=True, num_outputs=1, chain=_make_chain(g),
        edge_block=_edge_block_fwd, edge_block_p=_edge_block_r))


def scf_edge_pipeline(h, rbf, cm, em, w0, b0, w1, b1, senders, receivers,
                      sender_perm):
    """``out[n] = sum_{e: recv[e]=n} h[send[e]] * filt_e`` with
    ``filt_e = (ssp(rbf_e @ w0 + b0) @ w1 + b1) * cm_e`` computed in-VMEM.

    Differentiable wrt h, rbf, cm, w0, b0, w1, b1.  Requires the builder's
    collate invariants plus G <= 127 and F <= SCF_F_LIMIT (callers gate).
    ``cm`` must be zero on padding edges (it carries the edge mask).
    ``em`` is the int32 edge-validity mask (1 = real): em == 0 edges are
    skipped by the block schedule entirely, halving the scheduled MXU
    work at flagship padding ratios.  Contract: em == 0 edges carry
    cm == 0, sort after all real edges in both edge orderings (collate
    guarantees this), and get EXACTLY ZERO for every grad — including
    dcm, whose true value at cm == 0 need not be zero; callers must not
    consume dcm on masked edges (SchNet's hard-zeroed cutoff `where`
    satisfies this)."""
    n, f = h.shape
    e, g = rbf.shape
    f_pad = _round_up(max(f, 1), 128)
    gpw = _round_up(g + 2, _GP)  # rbf lanes + cm lane + builder bias lane
    geo = jnp.concatenate(
        [rbf, cm[:, None].astype(rbf.dtype)], axis=1)
    w0_p = jnp.zeros((gpw, f_pad), jnp.float32)
    w0_p = w0_p.at[:g, :f].set(w0.astype(jnp.float32))
    w0_p = w0_p.at[gpw - 1, :f].set(b0.astype(jnp.float32))
    w1_p = jnp.zeros((f_pad, f_pad), jnp.float32).at[:f, :f].set(
        w1.astype(jnp.float32))
    b1_p = jnp.zeros((8, f_pad), jnp.float32).at[:, :f].set(
        jnp.broadcast_to(b1.astype(jnp.float32), (8, f)))
    if h.dtype == jnp.bfloat16:
        # halves the constant weight blocks' VMEM; bias stays f32 (added
        # after the f32-accumulating dots)
        w0_p = w0_p.astype(jnp.bfloat16)
        w1_p = w1_p.astype(jnp.bfloat16)
    (out,) = _scf_op(int(g))(
        h, geo, em, (w0_p, w1_p, b1_p), senders, receivers, sender_perm)
    return out[:n, :f].astype(h.dtype)
