"""Fused CFConv edge pipeline: filter-MLP -> gather -> multiply -> segment
sum in ONE Pallas pass, forward AND backward — no [E, F] HBM streams.

Motivation (round-4 MFU attribution, docs/PERF.md): at dense-SchNet width
(hidden 1024, batch 2048) the step is 221 ms of which only 55.7 ms is the
matmul-flops bound; the rest is [E, 1024]-scale edge streams — dominated
by the continuous-filter chain ``filt = (W1 @ ssp(W0 @ rbf + b0) + b1) *
cut`` materialized per edge, its gather/scatter traffic, and the backward
re-reads.  This kernel keeps the whole per-edge pipeline in VMEM:

  forward (receiver-sorted dense schedule, fused_mp invariants):
    t0   = rbf_e @ W0аug             (bias folded into a constant lane)
    filt = (ssp(t0) @ W1 + b1) * cm_e          cm = cutoff-envelope * mask
    out[n] += h[send_e] * filt                 (one-hot window gather +
                                                one-hot scatter on the MXU)

  backward pass R (receiver-sorted): recomputes the chain per block and
    accumulates dW0/db0/dW1/db1 IN-KERNEL (constant-mapped output blocks,
    sequential TPU grid), emits per-edge drbf [E, G] and dcm [E] (compact
    streams that XLA chains into distance/position grads outside), using
    the flash-attention recompute-over-store trade.
  backward pass S (sender-sorted, host-precomputed permutation): recomputes
    filt and accumulates dh — the same fused kernel with edge roles
    swapped (fused_mp _vjp_bwd's trick, plus the in-VMEM filter).

FLOP cost: the filter matmul E*F^2 is evaluated 3x (fwd, R, S) plus the
two weight-grad matmuls — vs 3x E*F^2 for the composed XLA path — i.e.
~5/3 the MXU work in exchange for eliminating every [E, F] HBM stream;
at width the step is bandwidth-bound so the trade wins (measured numbers
in docs/PERF.md).

Invariants: exactly fused_mp's (nondecreasing receivers, intra-graph
edges, graphs within one node block, pre-sorted sender permutation).
Width limits: G (num_gaussians) <= 127 (one pad lane carries the folded
bias) and F <= SCF_F_LIMIT (VMEM: W1 and the dW1 accumulator are [F, F]
f32 blocks).  Callers gate on both and fall back to the composed path.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK, _dense_schedule

_EDGE_BLOCK = 128  # [BE, F] temporaries x ~8 live + [F, F] weights in VMEM
SCF_F_LIMIT = 1024
_GP = 128  # padded gaussian lane count (G + bias lane <= 128)


def _edge_block_fwd(f_pad: int, bf16: bool) -> int:
    """Forward / pass-S edge block: 256 halves the schedule's per-step
    overhead and doubles the one-hot matmul's MXU utilization; it fits
    scoped VMEM except at wide-F f32 (W1 4 MB + f32 windows + [BE, F]
    temporaries).  Pass R keeps 128 — its dW1 accumulator block doubles
    the resident [F, F] footprint."""
    return 256 if (f_pad <= 512 or bf16) else _EDGE_BLOCK


def _edge_block_r(f_pad: int, bf16: bool) -> int:
    """Pass R edge block: 128 everywhere (the resident dW1 [F, F] f32
    accumulator plus ~8 [BE, F] f32 temporaries cap the block well below
    fwd/pass-S's).  HYDRAGNN_SCF_BE_R overrides for sweeps; the sweep
    result (if a larger block wins at some width) gets baked here with
    the measurement.  f_pad/bf16 are the future conditioning inputs."""
    v = os.environ.get("HYDRAGNN_SCF_BE_R")
    if v:
        return int(v)
    del f_pad, bf16
    return _EDGE_BLOCK


def _ssp(x):
    """shifted softplus, f32, matching models/layers.shifted_softplus."""
    return jax.nn.softplus(x) - 0.6931471805599453


def _window_maps(n_blocks):
    # variadic: pass R prefetches five scalar tables, fwd/pass S four
    def eix(s, si, se, *rest):
        return (se[s], 0)

    def xoff(off):
        def f(s, si, se, *rest):
            return (jnp.clip(si[s] + off, 0, n_blocks - 1), 0)
        return f

    def const(s, *rest):
        return (0, 0)

    def outx(s, si, se, *rest):
        return (si[s], 0)

    return eix, xoff, const, outx


def _pack_edges(rbf, cm, em, senders, receivers, e_pad, n_pad):
    """Pad edge arrays; bias lane (_GP - 1) of rbf is constant 1.0.

    MASKED edges (em == 0) are parked on the out-of-range sentinel node
    ``n_pad`` alongside the shape-padding slots, so the dense schedule
    assigns their edge blocks to NO node block and never visits them —
    at flagship collate shapes HALF the edge slots are batch padding, so
    this halves the kernel's scheduled MXU work.  Exactness: an em == 0
    edge must carry cm == 0 (callers derive em from the same mask that
    zeroes cm), so it contributes nothing forward (filt = f2 * cm) and
    all its grads except dcm are proportional to cm; the caller-facing
    contract is that dcm is ZERO for masked edges (scf_edge_pipeline
    docstring).  Requires masked edges to sort AFTER all real edges in
    both edge orderings (collate parks them on node N-1, the maximum
    id — the invariant holds for the receiver sort and the stable
    sender argsort)."""
    e, g = rbf.shape
    rbf_p = jnp.zeros((e_pad, _GP), jnp.float32)
    rbf_p = rbf_p.at[:e, :g].set(rbf.astype(jnp.float32))
    rbf_p = rbf_p.at[:, _GP - 1].set(1.0)
    cm_p = jnp.zeros((e_pad, 1), jnp.float32).at[:e, 0].set(
        cm.astype(jnp.float32))
    valid = em != 0
    send_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, senders, n_pad).astype(jnp.int32))
    recv_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, receivers, n_pad).astype(jnp.int32))
    return rbf_p, cm_p, send_p, recv_p


def _pack_weights(w0, b0, w1, b1, f_pad):
    """W0 padded to [_GP, F] with b0 on the bias lane's row; b1 as an
    [8, F] constant block (row-broadcast in kernel)."""
    g, f = w0.shape
    w0_p = jnp.zeros((_GP, f_pad), jnp.float32)
    w0_p = w0_p.at[:g, :f].set(w0.astype(jnp.float32))
    w0_p = w0_p.at[_GP - 1, :f].set(b0.astype(jnp.float32))
    w1_p = jnp.zeros((f_pad, f_pad), jnp.float32).at[:f, :f].set(
        w1.astype(jnp.float32))
    b1_p = jnp.zeros((8, f_pad), jnp.float32).at[:, :f].set(
        jnp.broadcast_to(b1.astype(jnp.float32), (8, f)))
    return w0_p, w1_p, b1_p


def _dot(a, b, dims, dt):
    """MXU dot with operands in the compute dtype and f32 accumulation.

    Measured NEUTRAL on the v5e (173.9 -> 173.2 ms at dense h1024):
    JAX's default matmul precision already runs f32 dots through the MXU
    as bf16 passes, so explicit bf16 operands buy no rate — kept because
    it makes the operand dtype explicit and lets the constant weight
    blocks and one-hots live in bf16 VMEM (per-step-produced f32
    operands still pay one downcast; accumulation and every
    elementwise stays f32)."""
    return jax.lax.dot_general(
        a.astype(dt), b.astype(dt), (dims, ((), ())),
        preferred_element_type=jnp.float32)


def _filt_block(rbf_ref, cm_ref, w0_ref, w1_ref, b1_ref):
    """One edge block's filter chain: returns (t0, s0, f2, filt) so the
    backward reuses every intermediate instead of re-running the E*F^2
    matmul (each extra evaluation is a full matmul unit per layer)."""
    dt = w1_ref.dtype  # bf16 when the model computes in bf16
    t0 = _dot(rbf_ref[:], w0_ref[:], ((1,), (0,)), dt)
    s0 = _ssp(t0)
    f2 = _dot(s0, w1_ref[:], ((1,), (0,)), dt) + b1_ref[0:1, :]
    return t0, s0, f2, f2 * cm_ref[:].astype(jnp.float32)


def _gather_window(idx_ref, win_refs, base_block, bn):
    """One-hot window gather: rows of concat(win_refs) at idx (global node
    ids), returning ([BE, F] gathered, [BE, W*BN] onehot)."""
    be = idx_ref.shape[0]
    w = len(win_refs)
    base = base_block * bn
    loc = idx_ref[:] - base
    dt = win_refs[0].dtype  # 0/1 one-hot is exact in any dtype
    onehot = (loc == jax.lax.broadcasted_iota(
        jnp.int32, (be, w * bn), 1)).astype(dt)
    cat = jnp.concatenate([r[:] for r in win_refs], axis=0)
    out = _dot(onehot, cat, ((1,), (0,)), dt)
    return out, onehot


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(si_ref, se_ref, av_ref, fi_ref,
                send_ref, recv_ref, rbf_ref, cm_ref,
                w0_ref, w1_ref, b1_ref,
                hm1_ref, h0_ref, hp1_ref,
                out_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_ref.shape[0]
        be = send_ref.shape[0]
        _t0, _s0, _f2, filt = _filt_block(
            rbf_ref, cm_ref, w0_ref, w1_ref, b1_ref)
        hs, _ = _gather_window(
            send_ref, (hm1_ref, h0_ref, hp1_ref), i - 1, bn)
        msg = hs * filt
        rloc = recv_ref[:] - i * bn
        onehot_r = (rloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(w1_ref.dtype)
        out_ref[:] += _dot(onehot_r, msg, ((0,), (0,)), w1_ref.dtype)


def _fwd_impl(h, rbf, cm, em, senders, receivers, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = h.shape
    e = rbf.shape[0]
    bf16 = h.dtype == jnp.bfloat16
    f_pad = _round_up(max(f, 1), 128)
    bn, be = _NODE_BLOCK, _edge_block_fwd(f_pad, bf16)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    # node windows ride HBM<->VMEM in the COMPUTE dtype (the kernels
    # upcast per block); under bf16 this halves the dominant window traffic
    h_p = jnp.zeros((n_pad, f_pad), h.dtype).at[:n, :f].set(h)
    rbf_p, cm_p, send_p, recv_p = _pack_edges(
        rbf, cm, em, senders, receivers, e_pad, n_pad)

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)
    eix, xoff, const, outx = _window_maps(n_blocks)

    def run(w0_p, w1_p, b1_p):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(s_max,),
            in_specs=[
                pl.BlockSpec((be, 1), eix),
                pl.BlockSpec((be, 1), eix),
                pl.BlockSpec((be, _GP), eix),
                pl.BlockSpec((be, 1), eix),
                pl.BlockSpec((_GP, f_pad), const),
                pl.BlockSpec((f_pad, f_pad), const),
                pl.BlockSpec((8, f_pad), const),
                pl.BlockSpec((bn, f_pad), xoff(-1)),
                pl.BlockSpec((bn, f_pad), xoff(0)),
                pl.BlockSpec((bn, f_pad), xoff(1)),
            ],
            out_specs=pl.BlockSpec((bn, f_pad), outx),
        )
        return pl.pallas_call(
            _fwd_kernel,
            out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
            grid_spec=grid_spec,
            interpret=interpret,
        )(step_i, step_eb, acc_valid, is_first,
          send_p, recv_p, rbf_p, cm_p, w0_p, w1_p, b1_p,
          h_p, h_p, h_p)

    return run, (f_pad, n, f)


# ---------------------------------------------------------------------------
# backward pass R: weight grads + per-edge basis grads (receiver-sorted)
# ---------------------------------------------------------------------------


def _bwd_r_kernel(si_ref, se_ref, av_ref, fi_ref, feb_ref,
                  send_ref, recv_ref, rbf_ref, cm_ref,
                  w0_ref, w1_ref, b1_ref,
                  hm1_ref, h0_ref, hp1_ref, ga0_ref,
                  dw0_ref, dw1_ref, db1_ref, drbf_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(s == 0)
    def _init_w():
        dw0_ref[:] = jnp.zeros_like(dw0_ref)
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        db1_ref[:] = jnp.zeros_like(db1_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = ga0_ref.shape[0]
        be = send_ref.shape[0]
        t0, s0, f2, filt = _filt_block(
            rbf_ref, cm_ref, w0_ref, w1_ref, b1_ref)
        hs, _ = _gather_window(
            send_ref, (hm1_ref, h0_ref, hp1_ref), i - 1, bn)
        dt = w1_ref.dtype
        rloc = recv_ref[:] - i * bn
        onehot_r = (rloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(dt)
        ge = _dot(onehot_r, ga0_ref[:], ((1,), (0,)), dt)
        dfilt = ge * hs                       # [BE, F]
        cm = cm_ref[:].astype(jnp.float32)
        df2 = dfilt * cm
        dcm_v = jnp.sum(dfilt * f2, axis=1, keepdims=True)  # [BE, 1]
        dw1_ref[:] += _dot(s0, df2, ((0,), (0,)), dt)       # [F, F]
        db1_ref[:] += jnp.broadcast_to(
            jnp.sum(df2, axis=0, keepdims=True) / db1_ref.shape[0],
            db1_ref.shape)
        dt0 = _dot(df2, w1_ref[:], ((1,), (1,)), dt) * jax.nn.sigmoid(t0)
        dw0_ref[:] += _dot(rbf_ref[:], dt0, ((0,), (0,)), dt)  # [GP, F]
        drbf_v = _dot(dt0, w0_ref[:], ((1,), (1,)), dt)        # [BE, GP]
        # the bias lane's drbf slot (wrt the constant 1.0) is unused by the
        # caller — carry dcm there instead of a second per-edge output
        lane = jax.lax.broadcasted_iota(jnp.int32, drbf_v.shape, 1)
        drbf_v = jnp.where(lane == drbf_v.shape[1] - 1, dcm_v, drbf_v)
        first_eb = feb_ref[s] == 1
        drbf_ref[:] = jnp.where(first_eb, drbf_v, drbf_ref[:] + drbf_v)

    # a freshly-entered edge block that is NOT accumulated this step (the
    # forced step of an empty node block) must still be initialized, or a
    # boundary block's second visit would accumulate onto garbage
    @pl.when((av_ref[s] == 0) & (feb_ref[s] == 1))
    def _init_e():
        drbf_ref[:] = jnp.zeros_like(drbf_ref)


# ---------------------------------------------------------------------------
# backward pass S: dh (sender-sorted roles-swapped fused kernel)
# ---------------------------------------------------------------------------


def _bwd_s_kernel(si_ref, se_ref, av_ref, fi_ref,
                  send_ref, recv_ref, rbf_ref, cm_ref,
                  w0_ref, w1_ref, b1_ref,
                  gm1_ref, g0_ref, gp1_ref,
                  dh_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        dh_ref[:] = jnp.zeros_like(dh_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = dh_ref.shape[0]
        be = send_ref.shape[0]
        _t0, _s0, _f2, filt = _filt_block(
            rbf_ref, cm_ref, w0_ref, w1_ref, b1_ref)
        # roles swapped: send_ref carries the SORTED senders (output rows),
        # recv_ref the corresponding receivers (gather side)
        gr, _ = _gather_window(
            recv_ref, (gm1_ref, g0_ref, gp1_ref), i - 1, bn)
        msg = gr * filt
        sloc = send_ref[:] - i * bn
        onehot_s = (sloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(w1_ref.dtype)
        dh_ref[:] += _dot(onehot_s, msg, ((0,), (0,)), w1_ref.dtype)


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@jax.custom_vjp
def scf_edge_pipeline(h, rbf, cm, em, w0, b0, w1, b1, senders, receivers,
                      sender_perm):
    """``out[n] = sum_{e: recv[e]=n} h[send[e]] * filt_e`` with
    ``filt_e = (ssp(rbf_e @ w0 + b0) @ w1 + b1) * cm_e`` computed in-VMEM.

    Differentiable wrt h, rbf, cm, w0, b0, w1, b1.  Requires fused_mp's
    collate invariants plus G <= 127 and F <= SCF_F_LIMIT (callers gate).
    ``cm`` must be zero on padding edges (it carries the edge mask).
    ``em`` is the int32 edge-validity mask (1 = real): em == 0 edges are
    skipped by the block schedule entirely, halving the scheduled MXU
    work at flagship padding ratios.  Contract: em == 0 edges carry
    cm == 0, sort after all real edges in both edge orderings (collate
    guarantees this), and get EXACTLY ZERO for every grad — including
    dcm, whose true value at cm == 0 need not be zero; callers must not
    consume dcm on masked edges (SchNet's hard-zeroed cutoff `where`
    satisfies this)."""
    out, _ = _scf_fwd_res(h, rbf, cm, em, w0, b0, w1, b1, senders,
                          receivers)
    return out


def _scf_fwd_res(h, rbf, cm, em, w0, b0, w1, b1, senders, receivers):
    interpret = jax.default_backend() != "tpu"
    run, (f_pad, n, f) = _fwd_impl(h, rbf, cm, em, senders, receivers,
                                   interpret)
    w0_p, w1_p, b1_p = _pack_weights(w0, b0, w1, b1, f_pad)
    if h.dtype == jnp.bfloat16:
        # halves the constant weight blocks' VMEM and skips the per-step
        # in-kernel downcast
        w0_p = w0_p.astype(jnp.bfloat16)
        w1_p = w1_p.astype(jnp.bfloat16)
    out = run(w0_p, w1_p, b1_p)
    return out[:n, :f].astype(h.dtype), f_pad


def _scf_vjp_fwd(h, rbf, cm, em, w0, b0, w1, b1, senders, receivers,
                 sender_perm):
    out, _ = _scf_fwd_res(h, rbf, cm, em, w0, b0, w1, b1, senders,
                          receivers)
    return out, (h, rbf, cm, em, w0, b0, w1, b1, senders, receivers,
                 sender_perm)


def _scf_vjp_bwd(res, ga):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    h, rbf, cm, em, w0, b0, w1, b1, senders, receivers, sender_perm = res
    interpret = jax.default_backend() != "tpu"
    n, f = h.shape
    e, g = rbf.shape
    bf16 = h.dtype == jnp.bfloat16
    f_pad = _round_up(max(f, 1), 128)
    # pass R keeps a narrow edge block (its dW1 accumulator doubles the
    # resident [F, F] VMEM footprint); pass S uses the forward's
    bn, be = _NODE_BLOCK, _edge_block_r(f_pad, bf16)
    be_s = _edge_block_fwd(f_pad, bf16)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    h_p = jnp.zeros((n_pad, f_pad), h.dtype).at[:n, :f].set(h)
    ga_p = jnp.zeros((n_pad, f_pad), h.dtype).at[:n, :f].set(
        ga.astype(h.dtype))
    w0_p, w1_p, b1_p = _pack_weights(w0, b0, w1, b1, f_pad)
    if bf16:
        w0_p = w0_p.astype(jnp.bfloat16)
        w1_p = w1_p.astype(jnp.bfloat16)
    rbf_p, cm_p, send_p, recv_p = _pack_edges(
        rbf, cm, em, senders, receivers, e_pad, n_pad)

    eix, xoff, const, outx = _window_maps(n_blocks)

    # ---- pass R: receiver-sorted (natural order) ----
    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)
    prev_eb = jnp.concatenate(
        [jnp.full(1, -1, jnp.int32), step_eb[:-1]])
    first_eb = (step_eb != prev_eb).astype(jnp.int32)

    grid_r = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, _GP), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((_GP, f_pad), const),
            pl.BlockSpec((f_pad, f_pad), const),
            pl.BlockSpec((8, f_pad), const),
            pl.BlockSpec((bn, f_pad), xoff(-1)),
            pl.BlockSpec((bn, f_pad), xoff(0)),
            pl.BlockSpec((bn, f_pad), xoff(1)),
            pl.BlockSpec((bn, f_pad), xoff(0)),
        ],
        out_specs=[
            pl.BlockSpec((_GP, f_pad), const),
            pl.BlockSpec((f_pad, f_pad), const),
            pl.BlockSpec((8, f_pad), const),
            pl.BlockSpec((be, _GP), eix),
        ],
    )
    dw0_p, dw1_p, db1_p, drbf_p = pl.pallas_call(
        _bwd_r_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((_GP, f_pad), jnp.float32),
            jax.ShapeDtypeStruct((f_pad, f_pad), jnp.float32),
            jax.ShapeDtypeStruct((8, f_pad), jnp.float32),
            jax.ShapeDtypeStruct((e_pad, _GP), jnp.float32),
        ],
        grid_spec=grid_r,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, first_eb,
      send_p, recv_p, rbf_p, cm_p, w0_p, w1_p, b1_p,
      h_p, h_p, h_p, ga_p)

    # ---- pass S: sender-sorted (dh) ----
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)
    e_pad_s = _round_up(max(e, 1), be_s)
    n_eblocks_s = e_pad_s // be_s
    rbf_s, cm_s, send_s, recv_s = _pack_edges(
        rbf[sender_perm], cm[sender_perm], em[sender_perm],
        senders[sender_perm], receivers[sender_perm], e_pad_s, n_pad)
    step_i2, step_eb2, acc_valid2, is_first2, s_max2 = _dense_schedule(
        send_s[:, 0], n_blocks, bn, be_s, n_eblocks_s)
    grid_s = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max2,),
        in_specs=[
            pl.BlockSpec((be_s, 1), eix),
            pl.BlockSpec((be_s, 1), eix),
            pl.BlockSpec((be_s, _GP), eix),
            pl.BlockSpec((be_s, 1), eix),
            pl.BlockSpec((_GP, f_pad), const),
            pl.BlockSpec((f_pad, f_pad), const),
            pl.BlockSpec((8, f_pad), const),
            pl.BlockSpec((bn, f_pad), xoff(-1)),
            pl.BlockSpec((bn, f_pad), xoff(0)),
            pl.BlockSpec((bn, f_pad), xoff(1)),
        ],
        out_specs=pl.BlockSpec((bn, f_pad), outx),
    )
    dh_p = pl.pallas_call(
        _bwd_s_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        grid_spec=grid_s,
        interpret=interpret,
    )(step_i2, step_eb2, acc_valid2, is_first2,
      send_s, recv_s, rbf_s, cm_s, w0_p, w1_p, b1_p,
      ga_p, ga_p, ga_p)

    dh = dh_p[:n, :f].astype(h.dtype)
    # masked-edge blocks are never visited (schedule skip — _pack_edges),
    # so their drbf output rows are uninitialized memory: select them to
    # zero with `where` — a multiply would propagate NaN/Inf garbage bits
    # (0 * NaN = NaN).  Their true grads are 0 except dcm, which the
    # contract defines as 0 too.
    valid = (em != 0)[:, None]
    drbf = jnp.where(valid, drbf_p[:e, :g], 0.0).astype(rbf.dtype)
    dcm = jnp.where(valid[:, 0], drbf_p[:e, _GP - 1], 0.0).astype(cm.dtype)
    # weight grads: slice the pads; b0 rides W0's bias lane; db1's rows
    # were pre-divided by the row count so their sum is the true grad
    dw0 = dw0_p[:g, :f].astype(w0.dtype)
    db0 = dw0_p[_GP - 1, :f].astype(b0.dtype)
    dw1 = dw1_p[:f, :f].astype(w1.dtype)
    db1 = jnp.sum(db1_p[:, :f], axis=0).astype(b1.dtype)
    return (dh, drbf, dcm, None, dw0, db0, dw1, db1, None, None, None)


scf_edge_pipeline.defvjp(_scf_vjp_fwd, _scf_vjp_bwd)
