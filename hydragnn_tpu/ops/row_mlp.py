"""Fused row-local residual-MLP chain: DimeNet's post-interaction block
(lin_up -> +x_ji -> before-skip residual layers -> lin+skip -> after-skip
residual layers) in ONE Pallas pass per direction.

Motivation (round-5 DimeNet attribution, docs/PERF.md): after the
triplet kernel and tight padding, the step's top HBM consumers are the
interaction block's ~19 NARROW [E, 64] Dense ops — each one a
bandwidth-bound [E,64]@[64,64] matmul (32 flops/byte at f32 against the
v5e's ~240 flops/byte ridge) whose input/output stream through HBM at
every fusion boundary.  Rows are independent, weights are tiny
([64,64] x ~8 fits VMEM many times over), so the whole chain runs per
row-block in VMEM: 3 input streams + 1 output stream replace ~16
boundary streams forward (backward recomputes activations from the same
inputs and accumulates dW in constant-mapped blocks).

Chain (reference InteractionPPBlock tail, DIMEStack.py / PyG
DimeNet++):

    u  = silu(W_up @ tri)                       # no bias
    h  = x_ji + u
    for i in range(n_before):  h = h + silu(W2_i silu(W1_i h + b1_i) + b2_i)
    h  = silu(W h + b) + x_edge
    for i in range(n_after):   h = h + silu(W2_i silu(W1_i h + b1_i) + b2_i)

n_before / n_after are STATIC (config); the kernel body unrolls them.
Requires hidden <= 128 and int_emb <= 128 (one lane block each).
Weights ride one stacked [L, 128, 128] constant (L = 1 + 2*(n_before +
n_after) + 1) with biases folded into a [L, 8, 128] block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up

_RB = 512   # rows per grid step
_HP = 128   # padded feature lanes


def _silu(z):
    return z * jax.nn.sigmoid(z)


def _dsilu(z):
    s = jax.nn.sigmoid(z)
    return s * (1.0 + z * (1.0 - s))


def _dot(a, b, dims, dt):
    return jax.lax.dot_general(
        a.astype(dt), b.astype(dt), (dims, ((), ())),
        preferred_element_type=jnp.float32)


def _chain_fwd(tri, x_ji, x_edge, w_ref, b_ref, n_before, n_after, dt):
    """Run the chain, returning (h, pre-activation list, input list) —
    pres[k]/ins[k] are the k-th dense's pre-activation and input."""
    pres, ins = [], []

    def dense(k, v):
        ins.append(v)
        z = _dot(v, w_ref[k], ((1,), (0,)), dt) + b_ref[k][0:1, :]
        pres.append(z)
        return z

    k = 0
    h = x_ji + _silu(dense(k, tri)); k += 1
    for _ in range(n_before):
        t = _silu(dense(k, h)); k += 1
        h = h + _silu(dense(k, t)); k += 1
    h = _silu(dense(k, h)) + x_edge; k += 1
    for _ in range(n_after):
        t = _silu(dense(k, h)); k += 1
        h = h + _silu(dense(k, t)); k += 1
    return h, pres, ins


def _fwd_kernel(n_before, n_after, tri_ref, xji_ref, xe_ref, w_ref, b_ref,
                out_ref):
    dt = w_ref.dtype
    h, _p, _i = _chain_fwd(
        tri_ref[:].astype(jnp.float32), xji_ref[:].astype(jnp.float32),
        xe_ref[:].astype(jnp.float32), w_ref, b_ref, n_before, n_after, dt)
    out_ref[:] = h


def _bwd_kernel(n_before, n_after, tri_ref, xji_ref, xe_ref, w_ref, b_ref,
                g_ref, dtri_ref, dxji_ref, dxe_ref, dw_ref, db_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    dt = w_ref.dtype

    @pl.when(s == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    _h, pres, ins = _chain_fwd(
        tri_ref[:].astype(jnp.float32), xji_ref[:].astype(jnp.float32),
        xe_ref[:].astype(jnp.float32), w_ref, b_ref, n_before, n_after, dt)
    g = g_ref[:].astype(jnp.float32)

    def back(k, dz_post):
        """Backward through dense k given d(silu(z_k)); returns d(input)."""
        dz = dz_post * _dsilu(pres[k])
        dw_ref[k] += _dot(ins[k], dz, ((0,), (0,)), dt)
        db_ref[k] += jnp.broadcast_to(
            jnp.sum(dz, axis=0, keepdims=True) / db_ref.shape[1],
            (db_ref.shape[1], db_ref.shape[2]))
        return _dot(dz, w_ref[k], ((1,), (1,)), dt)

    k = 1 + 2 * (n_before + n_after)  # last dense index
    dh = g
    for _ in range(n_after):
        # h = h_prev + silu(D2(silu(D1(h_prev))))
        dt2 = back(k, dh); k -= 1
        dh = dh + back(k, dt2); k -= 1
    # h = silu(D(h_prev)) + x_edge
    dxe_ref[:] = dh
    dh = back(k, dh); k -= 1
    for _ in range(n_before):
        dt2 = back(k, dh); k -= 1
        dh = dh + back(k, dt2); k -= 1
    # h0 = x_ji + silu(D_up(tri))
    dxji_ref[:] = dh
    dtri_ref[:] = back(k, dh)


def _pack_rows(a, e_pad, dt):
    e, d = a.shape
    out = jnp.zeros((e_pad, _HP), dt)
    return out.at[:e, :d].set(a.astype(dt))


def _pack_wb(ws, bs, dt):
    L = len(ws)
    w_p = jnp.zeros((L, _HP, _HP), jnp.float32)
    b_p = jnp.zeros((L, 8, _HP), jnp.float32)
    for k, (w, b) in enumerate(zip(ws, bs)):
        di, do = w.shape
        w_p = w_p.at[k, :di, :do].set(w.astype(jnp.float32))
        if b is not None:
            b_p = b_p.at[k, :, :do].set(
                jnp.broadcast_to(b.astype(jnp.float32), (8, do)))
    return w_p.astype(dt), b_p.astype(dt)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def dimenet_post_mlp(tri, x_ji, x_edge, n_before, n_after, *wb):
    """The InteractionPPBlock tail as one fused row-local pass.

    ``wb`` is the flat (w_0, b_0, w_1, b_1, ...) parameter list in chain
    order: lin_up (bias None), then n_before x (lin1, lin2) residual
    pairs, then lin, then n_after x (lin1, lin2) pairs.  Differentiable
    wrt tri/x_ji/x_edge and every w/b.  hidden and int_emb must be
    <= 128."""
    return _post_fwd(tri, x_ji, x_edge, n_before, n_after, wb)


def _n_dense(n_before, n_after):
    return 2 + 2 * (n_before + n_after)


def _post_fwd(tri, x_ji, x_edge, n_before, n_after, wb):
    from jax.experimental import pallas as pl

    interpret = jax.default_backend() != "tpu"
    e, h = x_edge.shape
    bf16 = x_edge.dtype == jnp.bfloat16
    dt = jnp.bfloat16 if bf16 else jnp.float32
    e_pad = _round_up(max(e, 1), _RB)
    ws, bs = list(wb[0::2]), list(wb[1::2])
    w_p, b_p = _pack_wb(ws, bs, dt)
    tri_p = _pack_rows(tri, e_pad, dt)
    xji_p = _pack_rows(x_ji, e_pad, dt)
    xe_p = _pack_rows(x_edge, e_pad, dt)
    grid = e_pad // _RB

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, n_before, n_after),
        out_shape=jax.ShapeDtypeStruct((e_pad, _HP), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_RB, _HP), lambda s: (s, 0)),
            pl.BlockSpec((_RB, _HP), lambda s: (s, 0)),
            pl.BlockSpec((_RB, _HP), lambda s: (s, 0)),
            pl.BlockSpec(w_p.shape, lambda s: (0, 0, 0)),
            pl.BlockSpec(b_p.shape, lambda s: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((_RB, _HP), lambda s: (s, 0)),
        interpret=interpret,
    )(tri_p, xji_p, xe_p, w_p, b_p)
    return out[:e, :h].astype(x_edge.dtype)


def _post_vjp_fwd(tri, x_ji, x_edge, n_before, n_after, *wb):
    out = _post_fwd(tri, x_ji, x_edge, n_before, n_after, wb)
    return out, (tri, x_ji, x_edge, wb)


def _post_vjp_bwd(n_before, n_after, res, g):
    from jax.experimental import pallas as pl

    tri, x_ji, x_edge, wb = res
    interpret = jax.default_backend() != "tpu"
    e, h = x_edge.shape
    d = tri.shape[1]
    bf16 = x_edge.dtype == jnp.bfloat16
    dt = jnp.bfloat16 if bf16 else jnp.float32
    e_pad = _round_up(max(e, 1), _RB)
    ws, bs = list(wb[0::2]), list(wb[1::2])
    L = len(ws)
    w_p, b_p = _pack_wb(ws, bs, dt)
    tri_p = _pack_rows(tri, e_pad, dt)
    xji_p = _pack_rows(x_ji, e_pad, dt)
    xe_p = _pack_rows(x_edge, e_pad, dt)
    g_p = _pack_rows(g, e_pad, dt)
    grid = e_pad // _RB

    row = pl.BlockSpec((_RB, _HP), lambda s: (s, 0))
    const_w = pl.BlockSpec(w_p.shape, lambda s: (0, 0, 0))
    const_b = pl.BlockSpec(b_p.shape, lambda s: (0, 0, 0))
    dtri_p, dxji_p, dxe_p, dw_p, db_p = pl.pallas_call(
        functools.partial(_bwd_kernel, n_before, n_after),
        out_shape=[
            jax.ShapeDtypeStruct((e_pad, _HP), jnp.float32),
            jax.ShapeDtypeStruct((e_pad, _HP), jnp.float32),
            jax.ShapeDtypeStruct((e_pad, _HP), jnp.float32),
            jax.ShapeDtypeStruct((L, _HP, _HP), jnp.float32),
            jax.ShapeDtypeStruct((L, 8, _HP), jnp.float32),
        ],
        grid=(grid,),
        in_specs=[row, row, row, const_w, const_b, row],
        out_specs=[row, row, row,
                   pl.BlockSpec((L, _HP, _HP), lambda s: (0, 0, 0)),
                   pl.BlockSpec((L, 8, _HP), lambda s: (0, 0, 0))],
        interpret=interpret,
    )(tri_p, xji_p, xe_p, w_p, b_p, g_p)

    grads = [dtri_p[:e, :d].astype(tri.dtype),
             dxji_p[:e, :h].astype(x_ji.dtype),
             dxe_p[:e, :h].astype(x_edge.dtype)]
    out_wb = []
    for k, (w, b) in enumerate(zip(ws, bs)):
        di, do = w.shape
        out_wb.append(dw_p[k, :di, :do].astype(w.dtype))
        out_wb.append(None if b is None
                      else jnp.sum(db_p[k, :, :do], axis=0).astype(b.dtype))
    return tuple(grads) + tuple(out_wb)


dimenet_post_mlp.defvjp(_post_vjp_fwd, _post_vjp_bwd)
