"""Aggregation backends for the message-passing scatter-add.

The reference exposes HYDRAGNN_AGGR_BACKEND to switch PyG's aggregation
between torch-scatter and its native fallback (reference
hydragnn/train/train_validate_test.py:373-378).  Here the same knob selects
how ``graph/segment.py:segment_sum`` lowers on the device:

- ``scatter`` (default): ``jax.ops.segment_sum`` — XLA's sort/scatter path.
- ``onehot``: one-hot × messages matmul in plain jnp.  O(E·N·F) FLOPs, but
  they run on the MXU systolic array at full rate, which on TPU often beats
  the scatter path for the padded static shapes this framework batches to.
- ``pallas``: hand-written Pallas kernel of the same one-hot contraction,
  blocked over edges so the one-hot tile is built on the fly in VMEM and
  never materialized in HBM (the jnp version materializes an [E, N] array).

All backends are exact (no atomics — deterministic accumulation order) and
differentiable; ``segment_sum``'s gradient is a gather, which the custom VJP
implements directly instead of differentiating through the kernel.

Measured on the real chip (v-era TPU, f32): isolated segment_sum at
E=32768/N=2560/F=64 runs 0.9-1.5ms for onehot vs 1.2ms scatter vs 1.2ms
pallas; end-to-end on the flagship QM9-SchNet bench the XLA scatter path
wins (60.1k graphs/s vs 58.2k onehot, 38.4k pallas — the standalone kernel
can't fuse into neighboring elementwise ops the way XLA's scatter does), so
``scatter`` stays the default and the others are shape-dependent tuning
knobs, not a blanket win.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_EDGE_BLOCK = 256  # edges per grid step; onehot tile = _EDGE_BLOCK x N_pad


def aggr_backend() -> str:
    """Current backend name.  The env knob is read at TRACE time: a jitted
    caller (every real train/eval step) pins whichever backend was active
    when it was first traced, so set the knob before building the step —
    flipping it mid-process does not retrace cached executables."""
    return os.environ.get("HYDRAGNN_AGGR_BACKEND", "scatter").lower()


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# onehot backend: plain jnp, XLA fuses the one-hot build into the matmul
# ---------------------------------------------------------------------------

def segment_sum_onehot(data, segment_ids, num_segments):
    """sum_e onehot[e, n] * data[e, f] on the MXU.  data: [E, ...]."""
    shape = data.shape
    flat = data.reshape(shape[0], -1)
    onehot = jax.nn.one_hot(segment_ids, num_segments, dtype=flat.dtype)
    # HIGHEST matches scatter bit-accuracy (default bf16 passes round the
    # messages to 8 mantissa bits) and measured the same speed on-chip —
    # this contraction is HBM-bandwidth-bound, not MXU-bound
    out = jax.lax.dot_general(
        onehot, flat, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).astype(flat.dtype)
    return out.reshape((num_segments,) + shape[1:])


# ---------------------------------------------------------------------------
# pallas backend: blocked one-hot contraction, accumulated across grid steps
# ---------------------------------------------------------------------------

def _segment_kernel(seg_ref, data_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    seg = seg_ref[:]                                   # [BE, 1] int32
    n_pad = out_ref.shape[0]
    # compute in f32 regardless of input dtype: bf16->f32 upcast is exact and
    # Mosaic rejects bf16 operands under an fp32 contract precision
    onehot = (seg == jax.lax.broadcasted_iota(
        jnp.int32, (seg.shape[0], n_pad), 1)).astype(jnp.float32)
    out_ref[:] += jax.lax.dot_general(
        onehot, data_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)


def _pallas_segment_sum_impl(data2d, segment_ids, n_pad: int,
                             interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, f = data2d.shape
    e_pad = _round_up(max(e, 1), _EDGE_BLOCK)
    f_pad = _round_up(max(f, 1), 128)
    # padded edges carry zero data -> contribute zeros wherever they scatter
    data_p = jnp.zeros((e_pad, f_pad), data2d.dtype).at[:e, :f].set(data2d)
    seg_p = jnp.zeros((e_pad, 1), jnp.int32).at[:e, 0].set(
        segment_ids.astype(jnp.int32))

    # accumulator is ALWAYS f32 (bf16 inputs accumulate in f32 on the MXU;
    # a bf16 out_ref would both reject the f32 store and lose the guarantee)
    return pl.pallas_call(
        _segment_kernel,
        grid=(e_pad // _EDGE_BLOCK,),
        in_specs=[
            pl.BlockSpec((_EDGE_BLOCK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_EDGE_BLOCK, f_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_pad, f_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        interpret=interpret,
    )(seg_p, data_p)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pallas_segment_sum(data2d, segment_ids, num_segments):
    interpret = jax.default_backend() != "tpu"
    n_pad = _round_up(num_segments, 128)
    out = _pallas_segment_sum_impl(data2d, segment_ids, n_pad, interpret)
    return out[:num_segments, :data2d.shape[1]].astype(data2d.dtype)


def _fwd(data2d, segment_ids, num_segments):
    return _pallas_segment_sum(data2d, segment_ids, num_segments), segment_ids


def _bwd(num_segments, segment_ids, g):
    # d/d(data)[e] = g[segment_ids[e]] — a row gather, no kernel needed.
    # Out-of-range ids (padded edges) were DROPPED in the forward, so their
    # gradient is zero; a bare gather would clamp them onto the last row.
    valid = (segment_ids >= 0) & (segment_ids < num_segments)
    safe = jnp.clip(segment_ids, 0, num_segments - 1)
    return jnp.where(valid[:, None], g[safe], 0.0), None


_pallas_segment_sum.defvjp(_fwd, _bwd)


def segment_sum_pallas(data, segment_ids, num_segments):
    shape = data.shape
    out = _pallas_segment_sum(
        data.reshape(shape[0], -1), segment_ids, num_segments)
    return out.reshape((num_segments,) + shape[1:])
