"""Aggregation backends for the message-passing scatter-add.

The reference exposes HYDRAGNN_AGGR_BACKEND to switch PyG's aggregation
between torch-scatter and its native fallback (reference
hydragnn/train/train_validate_test.py:373-378).  Here the same knob selects
how ``graph/segment.py:segment_sum`` lowers on the device:

- ``scatter`` (default): ``jax.ops.segment_sum`` — XLA's sort/scatter path.
- ``onehot``: one-hot × messages matmul in plain jnp.  O(E·N·F) FLOPs, but
  they run on the MXU systolic array at full rate, which on TPU often beats
  the scatter path for the padded static shapes this framework batches to.
- ``pallas``: hand-written Pallas kernel of the same one-hot contraction,
  blocked over edges so the one-hot tile is built on the fly in VMEM and
  never materialized in HBM (the jnp version materializes an [E, N] array).
- ``fused``: the full gather->multiply->segment-sum message-passing core in
  one sorted-receiver dense-schedule Pallas pass (ops/fused_mp.py,
  dispatched via graph/segment.py:gather_mul_segment) — +26% end-to-end on
  the flagship bench (docs/PERF.md); plain ``segment_sum`` calls under
  this backend use the scatter path.

All backends are exact (no atomics — deterministic accumulation order) and
differentiable; ``segment_sum``'s gradient is a gather, which the custom VJP
implements directly instead of differentiating through the kernel.

Measured on the real chip (v5e, f32): isolated segment_sum at
E=32768/N=2560/F=64 runs 0.9-1.5ms for onehot vs 1.2ms scatter vs 1.2ms
pallas; end-to-end on the flagship QM9-SchNet bench the XLA scatter path
wins (60.1k graphs/s vs 58.2k onehot, 38.4k pallas — the standalone kernel
can't fuse into neighboring elementwise ops the way XLA's scatter does), so
``scatter`` stays the default and the others are shape-dependent tuning
knobs, not a blanket win.

``segment_sum_sorted`` additionally exploits the collate invariant that
receivers are NONDECREASING with bounded in-degree: each output node-block
owns a contiguous scalar-prefetch-steered edge range, so there is no sort
and no full-N onehot tile.  Measured at flagship shapes
(E=82k/N=10.2k/F=64, degree<=20): 2.57ms vs scatter's 2.67ms — parity, not
a win, because the blocked onehot contraction spends ~BN redundant MACs
per edge that offset the sort savings.  Kept as the building block for
fused conv kernels, where skipping the sort AND the message
materialization could pay.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_EDGE_BLOCK = 256  # edges per grid step; onehot tile = _EDGE_BLOCK x N_pad


# THE backend vocabulary (config validation in run_training.py imports it
# — one definition, no drift between the two validation points)
KNOWN_BACKENDS = ("scatter", "onehot", "pallas", "fused")
_warned_unknown = set()


def aggr_backend() -> str:
    """Current backend name.  The env knob is read at TRACE time: a jitted
    caller (every real train/eval step) pins whichever backend was active
    when it was first traced, so set the knob before building the step —
    flipping it mid-process does not retrace cached executables.

    An unrecognized env value warns ONCE and behaves as ``scatter``
    (every backend check misses): a typo like ``fusd`` would otherwise
    silently lose the whole fused path AND evade the fallback telemetry,
    which only compares against the exact string ``fused``."""
    v = os.environ.get("HYDRAGNN_AGGR_BACKEND", "scatter").lower()
    if v not in KNOWN_BACKENDS and v not in _warned_unknown:
        _warned_unknown.add(v)
        import warnings

        warnings.warn(
            f"HYDRAGNN_AGGR_BACKEND={v!r} is not one of {KNOWN_BACKENDS};"
            " every aggregation will take the scatter path", stacklevel=2)
    return v


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def block_ranges(segment_ids, n_blocks: int, bn: int, be: int,
                 n_eblocks: int):
    """Per-node-block [start, end) EDGE-BLOCK ranges for nondecreasing
    ``segment_ids`` (shared by the sorted backend and ops/fused_mp.py):
    block i's segments span rows [i*bn, (i+1)*bn), located by searchsorted,
    then converted to edge-block indices (floor start, ceil end)."""
    bounds = jnp.arange(n_blocks + 1, dtype=jnp.int32) * bn
    v = jnp.searchsorted(segment_ids, bounds, side="left")
    lo, hi = v[:-1], v[1:]
    start = (lo // be).astype(jnp.int32)
    end = jnp.minimum((-(-hi // be)).astype(jnp.int32), n_eblocks)
    return start, end


# ---------------------------------------------------------------------------
# onehot backend: plain jnp, XLA fuses the one-hot build into the matmul
# ---------------------------------------------------------------------------

def segment_sum_onehot(data, segment_ids, num_segments):
    """sum_e onehot[e, n] * data[e, f] on the MXU.  data: [E, ...]."""
    shape = data.shape
    flat = data.reshape(shape[0], -1)
    onehot = jax.nn.one_hot(segment_ids, num_segments, dtype=flat.dtype)
    # HIGHEST matches scatter bit-accuracy (default bf16 passes round the
    # messages to 8 mantissa bits) and measured the same speed on-chip —
    # this contraction is HBM-bandwidth-bound, not MXU-bound
    out = jax.lax.dot_general(
        onehot, flat, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST).astype(flat.dtype)
    return out.reshape((num_segments,) + shape[1:])


# ---------------------------------------------------------------------------
# pallas backend: blocked one-hot contraction, accumulated across grid steps
# ---------------------------------------------------------------------------

def _segment_kernel(seg_ref, data_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    seg = seg_ref[:]                                   # [BE, 1] int32
    n_pad = out_ref.shape[0]
    # compute in f32 regardless of input dtype: bf16->f32 upcast is exact and
    # Mosaic rejects bf16 operands under an fp32 contract precision
    onehot = (seg == jax.lax.broadcasted_iota(
        jnp.int32, (seg.shape[0], n_pad), 1)).astype(jnp.float32)
    out_ref[:] += jax.lax.dot_general(
        onehot, data_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)


def _pallas_segment_sum_impl(data2d, segment_ids, n_pad: int,
                             interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, f = data2d.shape
    e_pad = _round_up(max(e, 1), _EDGE_BLOCK)
    f_pad = _round_up(max(f, 1), 128)
    # padded edges carry zero data -> contribute zeros wherever they scatter
    data_p = jnp.zeros((e_pad, f_pad), data2d.dtype).at[:e, :f].set(data2d)
    seg_p = jnp.zeros((e_pad, 1), jnp.int32).at[:e, 0].set(
        segment_ids.astype(jnp.int32))

    # accumulator is ALWAYS f32 (bf16 inputs accumulate in f32 on the MXU;
    # a bf16 out_ref would both reject the f32 store and lose the guarantee)
    return pl.pallas_call(
        _segment_kernel,
        grid=(e_pad // _EDGE_BLOCK,),
        in_specs=[
            pl.BlockSpec((_EDGE_BLOCK, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_EDGE_BLOCK, f_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((n_pad, f_pad), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        interpret=interpret,
    )(seg_p, data_p)


# ---------------------------------------------------------------------------
# sorted backend: receivers are nondecreasing after collate (graph/batch.py
# concatenates per-sample KD-tree neighbor lists with node offsets), so each
# output node-block owns a CONTIGUOUS edge range — no sort, no full-N onehot.
# Grid = (node_blocks, K) where K edge-blocks per node block is statically
# bounded by the caller's max-in-degree contract; scalar-prefetched
# searchsorted offsets steer each step's edge-block DMA.
# ---------------------------------------------------------------------------

_SORT_NODE_BLOCK = 1024
_SORT_EDGE_BLOCK = 2048


def _sorted_kernel(start_ref, end_ref, seg_ref, data_ref, out_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # steps beyond this node block's edge range are pure no-ops (their DMA'd
    # block is a clamped re-read; accumulating it would double count)
    @pl.when(start_ref[i] + k < end_ref[i])
    def _acc():
        bn = out_ref.shape[0]
        local = seg_ref[:] - i * bn                      # [BE, 1] int32
        onehot = (local == jax.lax.broadcasted_iota(
            jnp.int32, (seg_ref.shape[0], bn), 1)).astype(jnp.float32)
        out_ref[:] += jax.lax.dot_general(
            onehot, data_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)


def _sorted_impl(data2d, segment_ids, num_segments: int,
                 max_per_segment: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, f = data2d.shape
    be, bn = _SORT_EDGE_BLOCK, _SORT_NODE_BLOCK
    e_pad = _round_up(max(e, 1), be)
    f_pad = _round_up(max(f, 1), 128)
    n_pad = _round_up(num_segments, bn)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    data_p = jnp.zeros((e_pad, f_pad), data2d.dtype).at[:e, :f].set(data2d)
    # padding edges get the out-of-every-window sentinel n_pad
    seg_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        segment_ids.astype(jnp.int32))

    start, end = block_ranges(segment_ids, n_blocks, bn, be, n_eblocks)
    # static bound on edge-blocks per node block: bn segments x
    # max_per_segment edges, +1 for a range not aligned to a block boundary
    k_max = min(n_eblocks, -(-bn * max_per_segment // be) + 1)

    def edge_index_map(i, k, start_ref, end_ref):
        return (jnp.minimum(start_ref[i] + k, n_eblocks - 1), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_blocks, k_max),
        in_specs=[
            pl.BlockSpec((be, 1), edge_index_map),
            pl.BlockSpec((be, f_pad), edge_index_map),
        ],
        out_specs=pl.BlockSpec((bn, f_pad), lambda i, k, s, e2: (i, 0)),
    )
    return pl.pallas_call(
        _sorted_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(start, end, seg_p, data_p)


def _gather_bwd(num_segments, segment_ids, g):
    """Shared VJP of any exact segment sum: d/d(data)[e] = g[ids[e]], with
    zeros where the forward DROPPED the row (out-of-range ids; a bare gather
    would clamp them onto the last segment)."""
    valid = (segment_ids >= 0) & (segment_ids < num_segments)
    safe = jnp.clip(segment_ids, 0, num_segments - 1)
    return jnp.where(valid[:, None], g[safe], 0.0), None


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _sorted_segment_sum(data2d, segment_ids, num_segments, max_per_segment):
    interpret = jax.default_backend() != "tpu"
    out = _sorted_impl(data2d, segment_ids, num_segments,
                       max_per_segment, interpret)
    return out[:num_segments, :data2d.shape[1]].astype(data2d.dtype)


def _sorted_fwd(data2d, segment_ids, num_segments, max_per_segment):
    return (_sorted_segment_sum(data2d, segment_ids, num_segments,
                                max_per_segment), segment_ids)


_sorted_segment_sum.defvjp(
    _sorted_fwd,
    lambda num_segments, _mps, ids, g: _gather_bwd(num_segments, ids, g))


def segment_sum_sorted(data, segment_ids, num_segments: int,
                       max_per_segment: int):
    """Exact segment sum REQUIRING nondecreasing ``segment_ids`` and at most
    ``max_per_segment`` REAL entries per segment (collate's receivers are
    sorted with in-degree capped by max_neighbours).  Collate's PADDING
    edges all target node N-1 — far exceeding the cap — so edge data MUST
    be pre-masked (zeros at padded rows, as ``segment.segment_sum``'s mask
    argument does): overflow contributions beyond the cap are silently
    dropped, which is only harmless when they are zeros."""
    shape = data.shape
    out = _sorted_segment_sum(
        data.reshape(shape[0], -1), segment_ids, num_segments,
        int(max_per_segment))
    return out.reshape((num_segments,) + shape[1:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _pallas_segment_sum(data2d, segment_ids, num_segments):
    interpret = jax.default_backend() != "tpu"
    n_pad = _round_up(num_segments, 128)
    out = _pallas_segment_sum_impl(data2d, segment_ids, n_pad, interpret)
    return out[:num_segments, :data2d.shape[1]].astype(data2d.dtype)


def _fwd(data2d, segment_ids, num_segments):
    return _pallas_segment_sum(data2d, segment_ids, num_segments), segment_ids


_pallas_segment_sum.defvjp(_fwd, _gather_bwd)


def segment_sum_pallas(data, segment_ids, num_segments):
    shape = data.shape
    out = _pallas_segment_sum(
        data.reshape(shape[0], -1), segment_ids, num_segments)
    return out.reshape((num_segments,) + shape[1:])
