"""CGCNN gated sum as a thin spec on the fused-block builder
(:mod:`hydragnn_tpu.ops.fused_block`): both gathers -> gate MLP pair ->
sigmoid*softplus -> segment sum in ONE Pallas pass, forward AND
backward — no [E, 2F+A] concat stream, no [E, F] gate/core streams.

  z_e    = [x[recv_e], x[send_e], edge_attr_e]
  out[n] = sum_{e: recv[e]=n} sigmoid(z_e @ Wf + bf) * softplus(z_e @ Ws + bs)

CGConv aggregates at the edge *receiver*, so the spec's primary side is
the RECEIVER: collate's nondecreasing receiver order makes the scatter
(and the x[recv] gather) block-local while the x[send] gather rides the
±1-block window.  Each concat matmul is split into three partial
matmuls summed in f32 — same math, different f32 rounding order (the
parity tests bound the drift with the scf tolerance contract).  The
biases fold onto the geometry stream's constant bias lane.

Width limits: F <= CGCNN_F_LIMIT (the six [F, F] weight blocks and
their pass-P grad accumulators are the VMEM ceiling) and
edge_dim <= 127 (one geometry tile incl. the bias lane).  Callers gate
on both and fall back to the composed path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import (
    _GP, EdgeBlockSpec, _dot, build_fused_edge_op)

_EDGE_BLOCK = 256
CGCNN_F_LIMIT = 256
CGCNN_GEO_LIMIT = _GP - 1  # edge_attr lanes; lane 127 carries the biases


def _chain(w_vals, geo, xp, xo, dt):
    wfp, wfo, wfg, wsp, wso, wsg = w_vals
    tf = (_dot(xp, wfp, ((1,), (0,)), dt)
          + _dot(xo, wfo, ((1,), (0,)), dt)
          + _dot(geo, wfg, ((1,), (0,)), dt))
    ts = (_dot(xp, wsp, ((1,), (0,)), dt)
          + _dot(xo, wso, ((1,), (0,)), dt)
          + _dot(geo, wsg, ((1,), (0,)), dt))
    return (jax.nn.sigmoid(tf) * jax.nn.softplus(ts),)


@functools.lru_cache(maxsize=None)
def _cgcnn_op():
    return build_fused_edge_op(EdgeBlockSpec(
        name="cgcnn", primary="receiver", gather_primary=True,
        gather_other=True, num_outputs=1, chain=_chain,
        edge_block=_EDGE_BLOCK))


def _split(k, b, f, a, d, f_pad, d_pad, gpw):
    """Split a composed-path concat kernel k [2F+A, D] into the three
    partial kernels the chain consumes (receiver rows, sender rows,
    edge_attr rows) with b folded onto the geo bias lane."""
    kp = jnp.zeros((f_pad, d_pad), jnp.float32).at[:f, :d].set(
        k[:f].astype(jnp.float32))
    ko = jnp.zeros((f_pad, d_pad), jnp.float32).at[:f, :d].set(
        k[f:2 * f].astype(jnp.float32))
    kg = jnp.zeros((gpw, d_pad), jnp.float32)
    if a:
        kg = kg.at[:a, :d].set(k[2 * f:].astype(jnp.float32))
    kg = kg.at[gpw - 1, :d].set(b.astype(jnp.float32))
    return kp, ko, kg


def cgcnn_gated_block(x, edge_attr, em, kf, bf, ks, bs, senders, receivers,
                      sender_perm):
    """``out[n] = sum_{e: recv[e]=n} sigmoid(z_e @ kf + bf) *
    softplus(z_e @ ks + bs)`` with ``z_e = [x[recv_e], x[send_e],
    edge_attr_e]`` computed in-VMEM.

    Differentiable wrt x, edge_attr and both kernel/bias pairs.
    Requires the builder's collate invariants plus F <= CGCNN_F_LIMIT
    and edge_dim <= CGCNN_GEO_LIMIT (callers gate).  ``em`` is the
    int32 edge-validity mask (1 = real): em == 0 edges are skipped by
    the block schedule entirely and get EXACTLY ZERO for every output
    and grad (masked edges tail-sort in both orderings — collate
    guarantees this)."""
    n, f = x.shape
    e = senders.shape[0]
    d = kf.shape[-1]  # output width (nn.Dense features; may differ from f)
    a = 0 if edge_attr is None else edge_attr.shape[-1]
    f_pad = _round_up(max(f, 1), 128)
    d_pad = _round_up(max(d, 1), 128)
    gpw = _round_up(a + 1, _GP)
    geo = (edge_attr if edge_attr is not None
           else jnp.zeros((e, 0), x.dtype))
    packs = _split(kf, bf, f, a, d, f_pad, d_pad, gpw) \
        + _split(ks, bs, f, a, d, f_pad, d_pad, gpw)
    if x.dtype == jnp.bfloat16:
        # halves the constant weight blocks' VMEM (the chain's dots
        # recast operands to the compute dtype either way)
        packs = tuple(p.astype(jnp.bfloat16) for p in packs)
    (out,) = _cgcnn_op()(
        x, geo, em, tuple(packs), senders, receivers, sender_perm)
    return out[:n, :d].astype(x.dtype)
