"""Fused message-passing kernel: gather -> edge-multiply -> segment-sum in
one Pallas pass.

The CFConv-style core ``out[n] = sum_{e: recv[e]=n} x[send[e]] * w[e]`` is
the hot op of every conv stack.  XLA executes it as gather + multiply +
scatter; measured on the v5e the gather/scatter machinery dominates the
step's HBM traffic (cost model: 7.3 GB/step for the flagship SchNet, and
bf16-casting the features removes only ~3% of it), putting the step at the
bandwidth roofline.

This kernel exploits two invariants the collate layer guarantees
(graph/batch.py):

1. ``receivers`` are NONDECREASING (per-sample edge lists concatenated with
   node offsets), so each output node-block owns a contiguous edge range —
   scalar-prefetched searchsorted offsets steer the edge-block DMAs and no
   sort/scatter ever happens.
2. Edges are INTRA-GRAPH and graphs are stored contiguously, so the senders
   of a node block's edges lie within the adjacent node blocks — a 3-block
   x window (gathered as a block-local one-hot contraction on the MXU)
   replaces the global row gather, provided every graph fits in one node
   block (``max_nodes_per_graph <= _NODE_BLOCK``; callers must fall back to
   the XLA path otherwise).

Padding edges (parked on node N-1 by collate with edge_mask 0) contribute
nothing: the caller's pre-masked ``w`` zeroes them, and out-of-window
one-hot rows are all-zero anyway.

The grid is a DENSE CSR-style schedule: scalar-prefetched step tables map
each grid step to one populated (node-block, edge-block) pair, so no step
is a wasted DMA and — unlike a rectangular (block, k_max) grid bounded by a
declared max degree — ANY degree distribution is processed exactly (total
steps are unconditionally <= edge blocks + 2 * node blocks).

Backward: dL/dw = x[senders] * g[receivers] (two XLA gathers — the
receivers gather is sorted and cheap); dL/dx reuses THIS kernel on the
sender-sorted edge ordering (host-precomputed permutation: sorting edges by
sender turns the sender-scatter into another sorted-receiver segment sum).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import (  # noqa: F401 — canonical home;
    _NODE_BLOCK, _dense_schedule)           # re-exported for back-compat


_EDGE_BLOCK = 512   # edges per inner step


def _fwd_kernel(has_w, window, si_ref, se_ref, av_ref, fi_ref, send_ref,
                recv_ref, *rest):
    from jax.experimental import pallas as pl

    if has_w:
        w_ref = rest[0]
    else:
        # w omitted: messages are the gathered features themselves, scaled
        # by the scalar edge mask (GIN/MFC-style sum aggregation)
        mask_ref = rest[0]
    xwin_refs = rest[1:1 + window]
    out_ref = rest[1 + window]

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_ref.shape[0]
        be = send_ref.shape[0]
        # window rows are blocks [i-hw .. i+hw]; at the boundaries the
        # clamped duplicate slots are unreachable because the base stays
        # (i-hw)*bn (negative at the low edge is fine — senders then map
        # into the later window rows, never the duplicated ones)
        hw = window // 2
        base = (i - hw) * bn
        sloc = send_ref[:] - base                       # [BE, 1]
        onehot_s = (sloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, window * bn), 1)).astype(jnp.float32)
        xcat = jnp.concatenate(
            [r[:] for r in xwin_refs], axis=0).astype(jnp.float32)
        msgs = jax.lax.dot_general(
            onehot_s, xcat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BE, F]
        if has_w:
            msgs = msgs * w_ref[:].astype(jnp.float32)
        else:
            msgs = msgs * mask_ref[:].astype(jnp.float32)
        rloc = recv_ref[:] - i * bn
        onehot_r = (rloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(jnp.float32)
        out_ref[:] += jax.lax.dot_general(
            onehot_r, msgs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BN, F]


def _fused_impl(x, w, senders, receivers, interpret, mask=None, window=3,
                edge_valid=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    has_w = w is not None
    n, f = x.shape
    e = w.shape[0] if has_w else senders.shape[0]
    bn, be = _NODE_BLOCK, _EDGE_BLOCK
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    f_pad = _round_up(max(f, 1), 128)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    if has_w:
        w_p = jnp.zeros((e_pad, f_pad), w.dtype).at[:e, :f].set(w)
    else:
        m = (jnp.ones((e,), jnp.float32) if mask is None
             else mask.astype(jnp.float32))
        w_p = jnp.zeros((e_pad, 1), jnp.float32).at[:e, 0].set(m)
    # shape-padding edges: park outside every block/window so they can't
    # contribute even with nonzero data (their w rows are zero anyway).
    # MASK-padding edges (edge_valid == 0 — the batch's own padding, ~half
    # the edge slots at flagship collate shapes) are parked the same way,
    # so the dense schedule assigns their edge blocks to NO node block and
    # never spends a step on them.  Contract (callers): masked edges carry
    # zero w/mask AND sort after all real edges in the current ordering
    # (collate parks them on node N-1, the maximum id, so both the
    # receiver sort and the stable sender argsort keep them last).
    if edge_valid is not None:
        ev = edge_valid != 0
        senders = jnp.where(ev, senders, n_pad)
        receivers = jnp.where(ev, receivers, n_pad)
    send_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        senders.astype(jnp.int32))
    recv_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        receivers.astype(jnp.int32))

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)

    def eix(s, si, se, av, fi):
        return (se[s], 0)

    def xoff(off):
        def f(s, si, se, av, fi):
            return (jnp.clip(si[s] + off, 0, n_blocks - 1), 0)
        return f

    assert window % 2 == 1, "window must be odd"
    hw = window // 2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, f_pad if has_w else 1), eix),
        ] + [pl.BlockSpec((bn, f_pad), xoff(o))
             for o in range(-hw, hw + 1)],
        out_specs=pl.BlockSpec(
            (bn, f_pad), lambda s, si, se, av, fi: (si[s], 0)),
    )
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, has_w, window),
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, send_p, recv_p, w_p,
      *([x_p] * window))
    return out[:n, :f].astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def gather_mul_segment_sum(x, w, senders, receivers, sender_perm,
                           window=3, edge_valid=None):
    """``out[n, f] = sum_{e: recv[e]=n} x[send[e], f] * w[e, f]``.

    REQUIRES (collate invariants — see module docstring): nondecreasing
    ``receivers``; intra-graph edges, graphs contiguous, every graph within
    ``_NODE_BLOCK`` nodes; ``w`` pre-masked (zero rows on padding edges).
    No degree bound: the dense schedule processes every populated
    (node-block, edge-block) pair exactly once.  ``sender_perm`` is the
    host-precomputed stable argsort of ``senders`` (collate emits it once
    per batch) used by the backward; pass None for a forward-only call.
    Exact (f32 accumulation, deterministic order); differentiable wrt x
    and w.

    ``window`` (odd, static) widens the sender one-hot window: segment i
    gathers from blocks i-w//2..i+w//2 — 3 suffices for node-space message
    passing (graphs within one node block); DimeNet's triplet interaction
    runs in EDGE space where graphs span up to ~2 blocks and needs 5.

    ``edge_valid`` (optional int mask, 1 = real) lets the schedule SKIP
    masked-edge blocks outright (halves scheduled work at flagship
    padding ratios).  Contract: edge_valid == 0 edges carry zero ``w``
    rows and sort after all real edges in BOTH edge orderings (collate
    guarantees this).  Their dw cotangent is computed densely and is
    GARBAGE: a skipped edge contributes nothing forward, so its true
    gradient is zero, but the dense ``x[send] * g[recv]`` formula reads
    the padding node's rows instead — callers must not consume dw on
    masked edges; the caller's w-premask multiply must kill it (same
    contract as :func:`~hydragnn_tpu.ops.scf_mp.scf_edge_pipeline`'s
    masked-edge grads).
    """
    interpret = jax.default_backend() != "tpu"
    return _fused_impl(x, w, senders, receivers, interpret, window=window,
                       edge_valid=edge_valid)


def _vjp_fwd(x, w, senders, receivers, sender_perm, window=3,
             edge_valid=None):
    out = gather_mul_segment_sum(x, w, senders, receivers, sender_perm,
                                 window, edge_valid)
    return out, (x, w, senders, receivers, sender_perm, edge_valid)


def _vjp_bwd(window, res, g):
    x, w, senders, receivers, sender_perm, edge_valid = res
    # dL/dw[e] = x[send[e]] * g[recv[e]] — plain gathers (recv gather is
    # over sorted indices)
    dw = (x[senders] * g[receivers]).astype(w.dtype)
    # dL/dx[n] = sum_{e: send[e]=n} w[e] * g[recv[e]]: on the sender-sorted
    # ordering this is the SAME fused sorted-receiver kernel with the edge
    # roles swapped
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)
    dx = _fused_impl(
        g.astype(jnp.float32), w[sender_perm].astype(jnp.float32),
        receivers[sender_perm], senders[sender_perm],
        jax.default_backend() != "tpu", window=window,
        edge_valid=None if edge_valid is None else edge_valid[sender_perm])
    return dx.astype(x.dtype), dw, None, None, None, None


gather_mul_segment_sum.defvjp(_vjp_fwd, _vjp_bwd)


@jax.custom_vjp
def gather_segment_sum(x, senders, receivers, sender_perm, mask=None):
    """``out[n] = sum_{e: recv[e]=n} mask[e] * x[send[e]]`` — the w-less
    variant (GIN/MFC-style neighbor sum) with the same invariants as
    :func:`gather_mul_segment_sum`; ``mask`` is the [E] edge mask (padding
    edges contribute nothing — and their blocks are schedule-skipped, so
    mask == 0 edges must sort after all real edges, which collate
    guarantees).  Differentiable wrt ``x`` only."""
    interpret = jax.default_backend() != "tpu"
    return _fused_impl(x, None, senders, receivers, interpret, mask=mask,
                       edge_valid=mask)


def _gss_fwd(x, senders, receivers, sender_perm, mask=None):
    out = gather_segment_sum(x, senders, receivers, sender_perm, mask)
    return out, (senders, receivers, sender_perm, mask)


def _gss_bwd(res, g):
    senders, receivers, sender_perm, mask = res
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)
    interpret = jax.default_backend() != "tpu"
    mp = None if mask is None else mask[sender_perm]
    dx = _fused_impl(
        g.astype(jnp.float32), None, receivers[sender_perm],
        senders[sender_perm], interpret, mask=mp, edge_valid=mp)
    return dx.astype(g.dtype), None, None, None, None


gather_segment_sum.defvjp(_gss_fwd, _gss_bwd)


# ---------------------------------------------------------------------------
# scatter-only variant: sorted segment sum on the dense schedule (no gather)
# — replaces XLA's sort-based scatter for already-edge-valued data (CGCNN's
# gated messages, PNA aggregates, masked pooling over node_gid)
# ---------------------------------------------------------------------------

def _scatter_kernel(si_ref, se_ref, av_ref, fi_ref, ids_ref, data_ref,
                    out_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_ref.shape[0]
        be = ids_ref.shape[0]
        loc = ids_ref[:] - i * bn
        onehot = (loc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(jnp.float32)
        out_ref[:] += jax.lax.dot_general(
            onehot, data_ref[:].astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


def _scatter_impl(data2d, sorted_ids, num_segments, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, f = data2d.shape
    bn, be = _NODE_BLOCK, _EDGE_BLOCK
    n_pad = _round_up(num_segments, bn)
    e_pad = _round_up(max(e, 1), be)
    f_pad = _round_up(max(f, 1), 128)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    data_p = jnp.zeros((e_pad, f_pad), data2d.dtype).at[:e, :f].set(data2d)
    ids_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        sorted_ids.astype(jnp.int32))

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        ids_p[:, 0], n_blocks, bn, be, n_eblocks)

    def eix(s, si, se, av, fi):
        return (se[s], 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, f_pad), eix),
        ],
        out_specs=pl.BlockSpec(
            (bn, f_pad), lambda s, si, se, av, fi: (si[s], 0)),
    )
    out = pl.pallas_call(
        _scatter_kernel,
        out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, ids_p, data_p)
    return out[:num_segments, :f].astype(data2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def segment_sum_dense(data, sorted_ids, num_segments, valid=None):
    """Exact segment sum REQUIRING nondecreasing ``sorted_ids`` (collate's
    receivers / node_gid invariant) — one dense-schedule Pallas pass
    instead of XLA's sort-based scatter.  Any id distribution is processed
    exactly (no degree bound); out-of-range ids contribute nothing.
    ``valid`` (optional int mask, 1 = real) parks masked rows out of
    range so the schedule skips their blocks; masked rows must carry zero
    ``data`` and sort last (collate guarantees both for padding edges).
    Differentiable wrt ``data``."""
    shape = data.shape
    interpret = jax.default_backend() != "tpu"
    if valid is not None:
        sorted_ids = jnp.where(valid != 0, sorted_ids, num_segments)
    out = _scatter_impl(
        data.reshape(shape[0], -1), sorted_ids, num_segments, interpret)
    return out.reshape((num_segments,) + shape[1:])


def _ssd_fwd(data, sorted_ids, num_segments, valid=None):
    if valid is not None:
        sorted_ids = jnp.where(valid != 0, sorted_ids, num_segments)
    return segment_sum_dense(data, sorted_ids, num_segments), (
        sorted_ids, data.shape)


def _ssd_bwd(num_segments, res, g):
    sorted_ids, shape = res
    g2 = g.reshape(num_segments, -1)
    ok = (sorted_ids >= 0) & (sorted_ids < num_segments)
    safe = jnp.clip(sorted_ids, 0, num_segments - 1)
    d = jnp.where(ok[:, None], g2[safe], 0.0)
    return d.reshape(shape), None, None


segment_sum_dense.defvjp(_ssd_fwd, _ssd_bwd)
