"""Universal fused message-passing builder: ONE dense-schedule Pallas
engine for every gather -> edge-chain -> gate -> segment-reduce block.

PR 2 (poly_mp), the SchNet cfconv pipeline (scf_mp), the fused EGCL block
(egcl_mp) and the DimeNet row-MLP tail were four hand-written instances of
the same shape, each reimplementing the sorted one-hot placement, the
3-block gather window, the masked-edge schedule skip and the two-pass
no-[E,H]-in-HBM VJP.  This module owns that machinery once and emits both
the forward kernel and the custom VJP from a declarative
:class:`EdgeBlockSpec`:

  * ``chain(w_vals, geo, xp, xo, dt) -> tuple of [BE, Wk]`` — the per-edge
    math, written once as plain JAX.  The backward is derived with
    ``jax.vjp`` INSIDE the kernel (ref reads are tracers, so the pullback
    traces into the same Pallas body — flash-attention-style recompute
    with no [E, H] HBM stream, and no hand-derived transposes to keep in
    sync with the forward).
  * ``primary`` names the scatter side ("sender" or "receiver"); the edge
    stream is processed sorted by it, making both scatters block-local
    one-hot matmuls, while the other side rides a ±hw-block window
    (collate invariant: graphs never straddle a node block; DimeNet's
    edge-space triplets span up to 2, hence ``window``).

Backward splits into the two passes every retired kernel used:

  pass P (primary-sorted): recompute the chain, gather the cotangent
    through the primary one-hot (zero rows gate the whole pullback — an
    out-of-block edge contributes nothing this visit), then
    ``jax.vjp`` wrt (weights, geo, x_primary): weight grads accumulate
    in-kernel into constant-mapped f32 blocks, dgeo streams per edge
    (first-visit init, forced-empty-block re-init), dx_primary scatters
    through the same one-hot.  Weight values are upcast to f32 BEFORE the
    vjp so their cotangents accumulate without per-step rounding, while
    the refs stay bf16 under a bf16 policy (``_dot`` recasts operands to
    the compute dtype for the MXU).
  pass S (other-sorted): recompute, cotangent gathered through the
    window, ``jax.vjp`` wrt x_other ONLY — the pullback jaxpr contains no
    wasted weight/geo transposes.

Masked edges (em == 0) are parked on the out-of-range sentinel node in
BOTH id columns, so the dense schedule never visits their blocks: outputs
and every grad are exactly zero by construction (uninitialized per-edge
stream rows are ``where``-selected to zero — never multiplied, since
0 * NaN = NaN).  Contract: masked edges tail-sort in both edge orderings
(collate parks them on node N-1, the maximum id).

Geometry lanes: the builder pads ``geo`` to a whole number of 128-lane
tiles with a constant-1.0 bias lane LAST — specs fold biases onto the
matching weight row, and bias grads fall out of the weight-block
cotangent for free.

The per-moment aggregation kernels (poly_mp) and the trivial-chain
gather/scatter ops (fused_mp) keep their specialized bodies — their
chains are identity/multiply and already share this module's schedule.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up, block_ranges

_NODE_BLOCK = 128   # rows of out per grid step (gather window = W x this)
_GP = 128           # one geometry lane tile; widths are multiples of this


# ---------------------------------------------------------------------------
# dense schedule (canonical home; fused_mp/poly_mp/gat_mp import from here)
# ---------------------------------------------------------------------------


def _dense_schedule(sorted_ids, n_blocks, bn, be, n_eblocks):
    """DENSE grid schedule: one step per (node-block, populated edge-block)
    pair, flattened CSR-style into scalar-prefetched step tables — instead
    of a rectangular (n_blocks, k_max) grid whose bound-degree worst case
    makes most steps no-op DMAs.  Empty blocks get exactly one step (their
    out must still be zeroed).  Total steps are UNCONDITIONALLY bounded:
    ranges tile the edge blocks with at most one shared boundary block per
    adjacent pair, so sum(max(range_i, 1)) <= n_eblocks + 2*n_blocks
    regardless of degree distribution — no degree contract, no dropped
    edges, no overflow case at all.

    Returns (step_i, step_eb, acc_valid, is_first, s_max)."""
    start, end = block_ranges(sorted_ids, n_blocks, bn, be, n_eblocks)
    counts = end - start
    steps = jnp.maximum(counts, 1)
    offsets = jnp.cumsum(steps)
    total = offsets[-1]
    s_max = n_eblocks + 2 * n_blocks
    s_idx = jnp.arange(s_max, dtype=jnp.int32)
    step_i = jnp.minimum(
        jnp.searchsorted(offsets, s_idx, side="right"),
        n_blocks - 1).astype(jnp.int32)
    block_off = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), offsets[:-1].astype(jnp.int32)])
    k = s_idx - block_off[step_i]
    step_eb = jnp.clip(start[step_i] + k, 0, n_eblocks - 1).astype(jnp.int32)
    # accumulate only on real (block, edge-block) pairs; the forced step of
    # an empty block and the trailing padding steps (which clamp onto the
    # last block and re-read its final edge block — a cached DMA) are no-ops
    acc_valid = ((k < counts[step_i]) & (s_idx < total)).astype(jnp.int32)
    prev_i = jnp.concatenate([jnp.full(1, -1, jnp.int32), step_i[:-1]])
    is_first = (step_i != prev_i).astype(jnp.int32)
    return step_i, step_eb, acc_valid, is_first, s_max


def _first_eb(step_eb):
    """First visit of each edge block (per-edge output streams re-init on
    it; a boundary block's second visit accumulates)."""
    prev_eb = jnp.concatenate([jnp.full(1, -1, jnp.int32), step_eb[:-1]])
    return (step_eb != prev_eb).astype(jnp.int32)


def _window_maps(n_blocks):
    # variadic: pass P prefetches five scalar tables, fwd/pass S four
    def eix(s, si, se, *rest):
        return (se[s], 0)

    def xoff(off):
        def f(s, si, se, *rest):
            return (jnp.clip(si[s] + off, 0, n_blocks - 1), 0)
        return f

    def const(s, *rest):
        return (0, 0)

    def outx(s, si, se, *rest):
        return (si[s], 0)

    return eix, xoff, const, outx


# ---------------------------------------------------------------------------
# shared in-kernel primitives
# ---------------------------------------------------------------------------


def _ssp(x):
    """shifted softplus, f32, matching models/layers.shifted_softplus."""
    return jax.nn.softplus(x) - 0.6931471805599453


def _dot(a, b, dims, dt):
    """MXU dot with operands in the compute dtype and f32 accumulation.

    Measured NEUTRAL on the v5e (173.9 -> 173.2 ms at dense h1024):
    JAX's default matmul precision already runs f32 dots through the MXU
    as bf16 passes, so explicit bf16 operands buy no rate — kept because
    it makes the operand dtype explicit and lets the constant weight
    blocks and one-hots live in bf16 VMEM (per-step-produced f32
    operands still pay one downcast; accumulation and every elementwise
    stays f32)."""
    return jax.lax.dot_general(
        a.astype(dt), b.astype(dt), (dims, ((), ())),
        preferred_element_type=jnp.float32)


def _onehot_local(idx_ref, i, bn, dt):
    """Block-local one-hot [BE, BN] of global ids against node block ``i``.
    Out-of-block ids produce an all-zero row — such edges contribute
    nothing this visit (they are in-block for exactly one visiting node
    block)."""
    be = idx_ref.shape[0]
    loc = idx_ref[:] - i * bn
    return (loc == jax.lax.broadcasted_iota(
        jnp.int32, (be, bn), 1)).astype(dt)


def _gather_local(idx_ref, blk_ref, i, bn, dt):
    """Block-local one-hot gather: rows of ``blk_ref`` (node block ``i``)
    at global ids ``idx``; returns ([BE, F] f32 gathered, [BE, BN]
    one-hot — the transposed one-hot gates the matching scatter)."""
    onehot = _onehot_local(idx_ref, i, bn, dt)
    return _dot(onehot, blk_ref[:], ((1,), (0,)), dt), onehot


def _gather_window(idx_ref, win_refs, base_block, bn):
    """One-hot window gather: rows of concat(win_refs) at idx (global node
    ids), returning ([BE, F] gathered, [BE, W*BN] onehot)."""
    be = idx_ref.shape[0]
    w = len(win_refs)
    base = base_block * bn
    loc = idx_ref[:] - base
    dt = win_refs[0].dtype  # 0/1 one-hot is exact in any dtype
    onehot = (loc == jax.lax.broadcasted_iota(
        jnp.int32, (be, w * bn), 1)).astype(dt)
    cat = jnp.concatenate([r[:] for r in win_refs], axis=0)
    out = _dot(onehot, cat, ((1,), (0,)), dt)
    return out, onehot


def _pack_geo(geo, em, p_ids, o_ids, e_pad, n_pad, gpw):
    """Pad the geometry stream to ``gpw`` lanes with the constant-1.0 bias
    lane LAST, and park masked edges (em == 0) on the out-of-range
    sentinel node ``n_pad`` in both id columns so the dense schedule
    assigns their blocks to NO node block and never visits them — at
    flagship collate shapes HALF the edge slots are batch padding, so the
    skip halves the scheduled MXU work.  Their outputs and grads are
    exactly zero by construction."""
    e, gd = geo.shape
    geo_p = jnp.zeros((e_pad, gpw), jnp.float32)
    if gd:
        geo_p = geo_p.at[:e, :gd].set(geo.astype(jnp.float32))
    geo_p = geo_p.at[:, gpw - 1].set(1.0)
    valid = em != 0
    p_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, p_ids, n_pad).astype(jnp.int32))
    o_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        jnp.where(valid, o_ids, n_pad).astype(jnp.int32))
    return geo_p, p_p, o_p


# ---------------------------------------------------------------------------
# the declarative spec
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EdgeBlockSpec:
    """Declarative fused edge block.

    ``chain(w_vals, geo, xp, xo, dt) -> tuple of [BE, Wk] f32`` is the
    per-edge math: ``w_vals`` the packed weight-block VALUES (biases
    folded onto the geometry bias lane's weight row or carried as [8, H]
    row-broadcast blocks used via ``b[0:1, :]``), ``geo`` the padded
    [BE, GPW] f32 geometry tile(s) (bias lane ``GPW - 1`` constant 1.0),
    ``xp``/``xo`` the gathered primary/other node features ([BE, F] f32,
    or None when the matching gather flag is off), ``dt`` the compute
    dtype for ``_dot``.  Every output is scattered (segment-summed) onto
    the PRIMARY node side.  The chain must be pure JAX — the builder
    derives the whole backward from it with ``jax.vjp``.

    ``edge_block`` / ``edge_block_p`` (pass P may need a smaller block:
    its weight-grad accumulators double the resident VMEM) are ints or
    ``f(f_pad, bf16) -> int`` callables."""
    name: str
    primary: str                      # "sender" | "receiver"
    gather_primary: bool
    gather_other: bool
    num_outputs: int
    chain: Callable[..., Tuple[Any, ...]]
    window: int = 3
    edge_block: Union[int, Callable[[int, bool], int]] = 256
    edge_block_p: Optional[Union[int, Callable[[int, bool], int]]] = None

    def __post_init__(self):
        assert self.primary in ("sender", "receiver"), self.primary
        assert self.window % 2 == 1, "window must be odd"
        assert self.gather_primary or self.gather_other, self.name


def _resolve_be(eb, f_pad, bf16):
    return eb(f_pad, bf16) if callable(eb) else eb


def _primary_order(spec, geo, em, senders, receivers, sender_perm):
    """(geo, em, p_ids, o_ids) in the primary-sorted edge ordering."""
    if spec.primary == "sender":
        if sender_perm is None:
            sender_perm = jnp.argsort(senders, stable=True)
        return (geo[sender_perm], em[sender_perm], senders[sender_perm],
                receivers[sender_perm], sender_perm)
    return geo, em, receivers, senders, sender_perm


def _other_order(spec, geo, em, senders, receivers, sender_perm):
    """(geo, em, sorted_ids, window_ids) in the OTHER-side ordering for
    pass S: the sorted side is the other/gathered side, the primary side
    (where cotangents live) rides the window."""
    if spec.primary == "sender":
        return geo, em, receivers, senders     # natural receiver order
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)
    return (geo[sender_perm], em[sender_perm], senders[sender_perm],
            receivers[sender_perm])


# ---------------------------------------------------------------------------
# generic kernels
# ---------------------------------------------------------------------------


def _fwd_kernel(spec, nw, si_ref, se_ref, av_ref, fi_ref,
                p_ref, o_ref, geo_ref, *rest):
    from jax.experimental import pallas as pl

    w_refs = rest[:nw]
    win_refs = rest[nw:nw + spec.window]
    out_refs = rest[nw + spec.window:]

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        for r in out_refs:
            r[:] = jnp.zeros_like(r)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_refs[0].shape[0]
        dt = win_refs[0].dtype
        hw = spec.window // 2
        if spec.gather_primary:
            xp, onehot_p = _gather_local(p_ref, win_refs[hw], i, bn, dt)
        else:
            xp, onehot_p = None, _onehot_local(p_ref, i, bn, dt)
        xo = (_gather_window(o_ref, win_refs, i - hw, bn)[0]
              if spec.gather_other else None)
        w_vals = tuple(r[:] for r in w_refs)
        outs = spec.chain(w_vals, geo_ref[:], xp, xo, dt)
        for r, o in zip(out_refs, outs):
            r[:] += _dot(onehot_p, o, ((0,), (0,)), dt)


def _bwd_p_kernel(spec, nw, si_ref, se_ref, av_ref, fi_ref, feb_ref,
                  p_ref, o_ref, geo_ref, *rest):
    from jax.experimental import pallas as pl

    k = spec.num_outputs
    w_refs = rest[:nw]
    win_refs = rest[nw:nw + spec.window]
    ct_refs = rest[nw + spec.window:nw + spec.window + k]
    outs = rest[nw + spec.window + k:]
    dw_refs = outs[:nw]
    dgeo_ref = outs[nw]
    dx_ref = outs[nw + 1] if spec.gather_primary else None

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(s == 0)
    def _init_w():
        for r in dw_refs:
            r[:] = jnp.zeros_like(r)

    if spec.gather_primary:
        @pl.when(fi_ref[s] == 1)
        def _init_x():
            dx_ref[:] = jnp.zeros_like(dx_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = win_refs[0].shape[0]
        dt = win_refs[0].dtype
        hw = spec.window // 2
        if spec.gather_primary:
            xp, onehot_p = _gather_local(p_ref, win_refs[hw], i, bn, dt)
        else:
            xp, onehot_p = None, _onehot_local(p_ref, i, bn, dt)
        xo = (_gather_window(o_ref, win_refs, i - hw, bn)[0]
              if spec.gather_other else None)
        # weight VALUES upcast to f32 so their cotangents come back f32
        # (accumulate without per-step rounding); the chain's _dot recasts
        # operands to the compute dtype for the MXU
        w_vals = tuple(r[:].astype(jnp.float32) for r in w_refs)
        geo_val = geo_ref[:]
        # cotangents gathered at the SORTED side gate everything: an edge
        # whose primary node is out of this block gets an all-zero ct row,
        # and the pullback is linear in it — zero grads this visit (its
        # in-block visit supplies them)
        cts = tuple(_dot(onehot_p, c[:], ((1,), (0,)), dt)
                    for c in ct_refs)
        if spec.gather_primary:
            def fn(wv, g, xpv):
                return spec.chain(wv, g, xpv, xo, dt)
            _, pull = jax.vjp(fn, w_vals, geo_val, xp)
            dws, dgeo_v, dxp = pull(cts)
        else:
            def fn(wv, g):
                return spec.chain(wv, g, None, xo, dt)
            _, pull = jax.vjp(fn, w_vals, geo_val)
            dws, dgeo_v = pull(cts)
        for r, d in zip(dw_refs, dws):
            r[:] += d
        dgeo_ref[:] = jnp.where(feb_ref[s] == 1, dgeo_v,
                                dgeo_ref[:] + dgeo_v)
        if spec.gather_primary:
            dx_ref[:] += _dot(onehot_p, dxp, ((0,), (0,)), dt)

    # a freshly-entered edge block that is NOT accumulated this step (the
    # forced step of an empty node block) must still be initialized, or a
    # boundary block's second visit would accumulate onto garbage
    @pl.when((av_ref[s] == 0) & (feb_ref[s] == 1))
    def _init_e():
        dgeo_ref[:] = jnp.zeros_like(dgeo_ref)


def _bwd_s_kernel(spec, nw, si_ref, se_ref, av_ref, fi_ref,
                  sord_ref, wside_ref, geo_ref, *rest):
    from jax.experimental import pallas as pl

    k = spec.num_outputs
    w = spec.window
    w_refs = rest[:nw]
    win_refs = rest[nw:nw + w]
    ct_wins = [rest[nw + w + j * w:nw + w + (j + 1) * w] for j in range(k)]
    dx_ref = rest[nw + w + k * w]

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        dx_ref[:] = jnp.zeros_like(dx_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = dx_ref.shape[0]
        dt = win_refs[0].dtype
        hw = w // 2
        # roles swapped: the other/gathered side is sorted (output rows),
        # the primary side — cotangents included — rides the window
        xo, onehot_o = _gather_local(sord_ref, win_refs[hw], i, bn, dt)
        xp = (_gather_window(wside_ref, win_refs, i - hw, bn)[0]
              if spec.gather_primary else None)
        w_vals = tuple(r[:] for r in w_refs)
        geo_val = geo_ref[:]
        cts = tuple(_gather_window(wside_ref, cw, i - hw, bn)[0]
                    for cw in ct_wins)

        def fn(xov):
            return spec.chain(w_vals, geo_val, xp, xov, dt)

        _, pull = jax.vjp(fn, xo)
        (dxo,) = pull(cts)
        dx_ref[:] += _dot(onehot_o, dxo, ((0,), (0,)), dt)


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------


def _out_widths(spec, weights, gpw, f_pad, be, dt):
    """Static chain output widths via abstract evaluation — specs never
    declare shapes the chain already implies."""
    w_avals = tuple(jax.ShapeDtypeStruct(w.shape, jnp.float32)
                    for w in weights)
    geo_aval = jax.ShapeDtypeStruct((be, gpw), jnp.float32)
    x_aval = jax.ShapeDtypeStruct((be, f_pad), jnp.float32)
    outs = jax.eval_shape(
        lambda wv, g, xp, xo: spec.chain(wv, g, xp, xo, dt),
        w_avals, geo_aval,
        x_aval if spec.gather_primary else None,
        x_aval if spec.gather_other else None)
    assert len(outs) == spec.num_outputs, (spec.name, len(outs))
    return tuple(o.shape[1] for o in outs)


def _fused_fwd(spec, x, geo, em, weights, senders, receivers, sender_perm,
               interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = x.shape
    e, gd = geo.shape
    bf16 = x.dtype == jnp.bfloat16
    f_pad = _round_up(max(f, 1), 128)
    gpw = _round_up(gd + 1, _GP)
    bn = _NODE_BLOCK
    be = _resolve_be(spec.edge_block, f_pad, bf16)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    geo_o, em_o, p_ids, o_ids, _ = _primary_order(
        spec, geo, em, senders, receivers, sender_perm)
    geo_p, p_p, o_p = _pack_geo(geo_o, em_o, p_ids, o_ids, e_pad, n_pad, gpw)

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        p_p[:, 0], n_blocks, bn, be, n_eblocks)
    eix, xoff, const, outx = _window_maps(n_blocks)
    hw = spec.window // 2

    dt = jnp.bfloat16 if bf16 else jnp.float32
    widths = _out_widths(spec, weights, gpw, f_pad, be, dt)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, gpw), eix),
        ] + [pl.BlockSpec(w.shape, const) for w in weights]
        + [pl.BlockSpec((bn, f_pad), xoff(o)) for o in range(-hw, hw + 1)],
        out_specs=[pl.BlockSpec((bn, wk), outx) for wk in widths],
    )
    outs = pl.pallas_call(
        functools.partial(_fwd_kernel, spec, len(weights)),
        out_shape=[jax.ShapeDtypeStruct((n_pad, wk), jnp.float32)
                   for wk in widths],
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, p_p, o_p, geo_p,
      *weights, *([x_p] * spec.window))
    return tuple(outs)


def _fused_bwd(spec, res, cts):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x, geo, em, weights, senders, receivers, sender_perm = res
    interpret = jax.default_backend() != "tpu"
    n, f = x.shape
    e, gd = geo.shape
    bf16 = x.dtype == jnp.bfloat16
    f_pad = _round_up(max(f, 1), 128)
    gpw = _round_up(gd + 1, _GP)
    bn = _NODE_BLOCK
    be_p = _resolve_be(spec.edge_block_p or spec.edge_block, f_pad, bf16)
    be_s = _resolve_be(spec.edge_block, f_pad, bf16)
    n_pad = _round_up(n, bn)
    hw = spec.window // 2
    k = spec.num_outputs
    nw = len(weights)

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    # cotangents ride HBM<->VMEM in the compute dtype like the windows
    ct_ps = tuple(c.astype(x.dtype) for c in cts)
    eix, xoff, const, outx = _window_maps(n_pad // bn)

    # ---- pass P: primary-sorted — weight grads, dgeo, primary-side dx ----
    e_pad = _round_up(max(e, 1), be_p)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be_p
    geo_o, em_o, p_ids, o_ids, perm = _primary_order(
        spec, geo, em, senders, receivers, sender_perm)
    geo_p, p_p, o_p = _pack_geo(geo_o, em_o, p_ids, o_ids, e_pad, n_pad, gpw)
    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        p_p[:, 0], n_blocks, bn, be_p, n_eblocks)
    feb = _first_eb(step_eb)

    in_specs_p = [
        pl.BlockSpec((be_p, 1), eix),
        pl.BlockSpec((be_p, 1), eix),
        pl.BlockSpec((be_p, gpw), eix),
    ] + [pl.BlockSpec(w.shape, const) for w in weights] \
      + [pl.BlockSpec((bn, f_pad), xoff(o)) for o in range(-hw, hw + 1)] \
      + [pl.BlockSpec((bn, c.shape[1]), xoff(0)) for c in ct_ps]
    out_specs_p = [pl.BlockSpec(w.shape, const) for w in weights] \
        + [pl.BlockSpec((be_p, gpw), eix)]
    out_shape_p = [jax.ShapeDtypeStruct(w.shape, jnp.float32)
                   for w in weights] \
        + [jax.ShapeDtypeStruct((e_pad, gpw), jnp.float32)]
    if spec.gather_primary:
        out_specs_p.append(pl.BlockSpec((bn, f_pad), outx))
        out_shape_p.append(jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32))
    grid_p = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s_max,),
        in_specs=in_specs_p,
        out_specs=out_specs_p,
    )
    outs_p = pl.pallas_call(
        functools.partial(_bwd_p_kernel, spec, nw),
        out_shape=out_shape_p,
        grid_spec=grid_p,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, feb,
      p_p, o_p, geo_p, *weights, *([x_p] * spec.window), *ct_ps)
    dws_p = outs_p[:nw]
    dgeo_p = outs_p[nw]
    dxp_p = outs_p[nw + 1] if spec.gather_primary else None

    # ---- pass S: other-sorted — other-side dx ----
    dxo_p = None
    if spec.gather_other:
        e_pad_s = _round_up(max(e, 1), be_s)
        n_eblocks_s = e_pad_s // be_s
        geo_s, em_s, sord, wside = _other_order(
            spec, geo, em, senders, receivers, sender_perm)
        geo_sp, sord_p, wside_p = _pack_geo(
            geo_s, em_s, sord, wside, e_pad_s, n_pad, gpw)
        step_i2, step_eb2, acc_valid2, is_first2, s_max2 = _dense_schedule(
            sord_p[:, 0], n_blocks, bn, be_s, n_eblocks_s)
        in_specs_s = [
            pl.BlockSpec((be_s, 1), eix),
            pl.BlockSpec((be_s, 1), eix),
            pl.BlockSpec((be_s, gpw), eix),
        ] + [pl.BlockSpec(w.shape, const) for w in weights] \
          + [pl.BlockSpec((bn, f_pad), xoff(o))
             for o in range(-hw, hw + 1)] \
          + [pl.BlockSpec((bn, c.shape[1]), xoff(o))
             for c in ct_ps for o in range(-hw, hw + 1)]
        grid_s = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(s_max2,),
            in_specs=in_specs_s,
            out_specs=pl.BlockSpec((bn, f_pad), outx),
        )
        ct_wins = [c for c in ct_ps for _ in range(spec.window)]
        dxo_p = pl.pallas_call(
            functools.partial(_bwd_s_kernel, spec, nw),
            out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), jnp.float32),
            grid_spec=grid_s,
            interpret=interpret,
        )(step_i2, step_eb2, acc_valid2, is_first2,
          sord_p, wside_p, geo_sp, *weights, *([x_p] * spec.window),
          *ct_wins)

    if dxp_p is not None and dxo_p is not None:
        dx = (dxp_p[:n, :f] + dxo_p[:n, :f]).astype(x.dtype)
    else:
        dx = (dxp_p if dxp_p is not None else dxo_p)[:n, :f].astype(x.dtype)

    # pass P ran in primary order: un-permute the per-edge stream if the
    # primary side was the sorted-sender one, then `where`-select masked
    # rows to zero — their blocks are never visited so the memory is
    # uninitialized (a multiply would propagate NaN bits)
    if spec.primary == "sender":
        dgeo_nat = jnp.zeros((e, gpw), jnp.float32).at[perm].set(dgeo_p[:e])
    else:
        dgeo_nat = dgeo_p[:e]
    valid = (em != 0)[:, None]
    dgeo = jnp.where(valid, dgeo_nat[:, :gd], 0.0).astype(geo.dtype)
    dweights = tuple(d.astype(w.dtype) for d, w in zip(dws_p, weights))
    return dx, dgeo, None, dweights, None, None, None


def build_fused_edge_op(spec: EdgeBlockSpec):
    """Emit the fused op for ``spec``: forward Pallas pass + two-pass
    custom VJP.

    ``op(x, geo, em, weights, senders, receivers, sender_perm)`` returns
    a tuple of [N_pad, Wk] f32 segment sums on the primary side (callers
    slice ``[:n, :w]`` and cast — the slice's AD zero-pads cotangents).
    ``weights`` is the tuple of PACKED weight blocks (callers pack with
    plain jnp ops so raw-parameter grads fall out of the padded-block
    cotangent by AD).  Differentiable wrt x, geo and weights.

    Requires the collate invariants (nondecreasing receivers, intra-graph
    edges, graphs within one node block — ``spec.window`` blocks for
    edge-space specs — and the host-precomputed stable sender argsort);
    ``em`` is the int edge-validity mask: em == 0 edges are
    schedule-skipped entirely and get EXACTLY ZERO for every output and
    grad."""

    @jax.custom_vjp
    def op(x, geo, em, weights, senders, receivers, sender_perm):
        interpret = jax.default_backend() != "tpu"
        return _fused_fwd(spec, x, geo, em, tuple(weights), senders,
                          receivers, sender_perm, interpret)

    def fwd(x, geo, em, weights, senders, receivers, sender_perm):
        out = op(x, geo, em, weights, senders, receivers, sender_perm)
        return out, (x, geo, em, tuple(weights), senders, receivers,
                     sender_perm)

    def bwd(res, cts):
        return _fused_bwd(spec, res, cts)

    op.defvjp(fwd, bwd)
    op.spec = spec
    return op


# ---------------------------------------------------------------------------
# unified dispatch-layer fallback telemetry
# ---------------------------------------------------------------------------


def note_fallback(arch: str, reason: str, **fields) -> None:
    """Record a one-shot fused-path fallback for the unified
    ``fused_fallback`` health event ({arch, reason} + spec fields) —
    every arch's dispatch gate funnels through here instead of minting
    per-arch kinds (``egcl_fallback`` is kept as an alias for one
    release; the trainer emits both)."""
    from hydragnn_tpu.telemetry import pipeline

    pipeline.record_fallback("fused", arch=arch, reason=reason, **fields)
