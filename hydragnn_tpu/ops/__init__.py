"""TPU kernels and backend-selectable aggregation primitives."""

from hydragnn_tpu.ops.aggregate import (  # noqa: F401
    aggr_backend,
    segment_sum_onehot,
    segment_sum_pallas,
    segment_sum_sorted,
)
