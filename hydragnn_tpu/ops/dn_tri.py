"""Fused DimeNet++ triplet interaction: spherical-basis product,
sbf-embedding MLP, edge gather and ji-scatter in ONE Pallas pass per
direction — no [T, hidden] HBM streams.

Motivation (round-4 PERF attribution, docs/PERF.md): the DimeNet step
moves ~9.4 GB at gather/scatter-pattern bandwidth (137 GB/s achieved vs
585 ceiling), dominated by [T, *] triplet streams: the gathered
``x_kj[idx_kj]``, the sbf chain ``(sbf @ W1) @ W2`` materialized per
triplet, and their backward re-reads (T ~ 2.3 x E).  The round-4 fused
attempt (gather_mul_segment_sum over precomputed [T, D] sbf embeddings)
still STREAMED the [T, D] operand and lost to schedule overhead; this
kernel instead exploits the basis factorization

    sbf[t, (l, r)] = radial[kj(t), (l, r)] * cbf[t, l]

(radial_sbf is EDGE-space, angular_cbf is triplet-space — see
models/dimenet.py:277-331, reference DIMEStack.py:118-182) so the only
[T, *] HBM traffic is the COMPACT angular stream ``cbf`` ([T, S], S <= 8
lanes; lane-expanded to (l, r) slots in-kernel by a 0/1 matmul) plus two
index streams; radial and the down-projected edge features ride ONE
dtype-packed 128-lane window array (radial in lanes 0:64, x2 in 64:128)
exactly like fused_mp's node windows — the v1 of this kernel streamed a
256-lane f32 window pair plus [T, 128] basis/cotangent streams and
measured NEUTRAL (63.7 vs 64.9 ms): the glue gave back everything the
fusion saved, so v2's whole design point is stream slimming.

  forward (triplets sorted by idx_ji — the builder's order):
    g        = onehot-window gather of xcat[idx_kj]
    sbf      = g[:, :64] * (cbf @ EXPAND)
    emb      = (sbf @ W1) @ W2                        (skinny MXU matmuls)
    out[e]  += onehot(idx_ji) ^T (g[:, 64:] * emb)

  backward (ONE pass, triplets sorted by the host argsort of idx_kj):
    recompute sbf/emb from the same windows; accumulate dW1/dW2 in
    constant-mapped blocks; accumulate d_xcat = (d_radial | d_x2) into
    the kj-sorted output blocks; emit the compact per-triplet stream
    d_cbf [T, S] (kj-sorted; caller unpermutes) — everything else
    (d_angle via the Legendre chain, d_dist via the Bessel chain,
    dW_down etc.) chains outside in edge-/scalar-space XLA.

Masked triplets are parked on the out-of-range sentinel (schedule skip,
as in scf_mp/fused_mp): zero contribution and exactly-zero grads.
Requires: idx_ji nondecreasing (builder invariant), masked triplets
tail-sorted (add_dimenet_extras pads the tail), every graph's edge-id
span <= 2 edge blocks (window 5; the caller checks the marker),
num_spherical <= 8, num_radial such that S*R <= 64, int_emb <= 64.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops import fused_block as _fb
from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import _dense_schedule
from hydragnn_tpu.ops.fused_block import _window_maps as _win_maps

_EB = 128      # edge block (output rows / window unit)
_TB = 512      # triplets per grid step
_SP = 8        # padded angular lane count (num_spherical <= 8)
_GH = 64       # radial/x2 half-lane width (S*R <= 64, int_emb <= 64)
_W = 5         # edge-block gather window (graphs span <= 2 blocks)


def _expand_matrix(s, r, dt):
    """[SP, GH] 0/1 matrix: lane l*r_width+r of the output is angular
    slot l — ``cbf @ EXPAND`` broadcasts each Legendre column over its
    radial slots on the MXU (no lane shuffles)."""
    m = jnp.zeros((_SP, _GH), jnp.float32)
    rows = jnp.repeat(jnp.arange(s), r)
    cols = jnp.arange(s * r)
    return m.at[rows, cols].set(1.0).astype(dt)


def _gather_w(idx_ref, win_refs, base_block, bn, dt):
    be = idx_ref.shape[0]
    w = len(win_refs)
    loc = idx_ref[:] - base_block * bn
    onehot = (loc == jax.lax.broadcasted_iota(
        jnp.int32, (be, w * bn), 1)).astype(dt)
    cat = jnp.concatenate([r[:] for r in win_refs], axis=0)
    return jax.lax.dot_general(
        onehot, cat.astype(dt), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32), onehot


def _dot(a, b, dims, dt):
    return jax.lax.dot_general(
        a.astype(dt), b.astype(dt), (dims, ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel(si_ref, se_ref, av_ref, fi_ref,
                kj_ref, ji_ref, cbf_ref,
                w1_ref, w2_ref, exp_ref,
                xm2_ref, xm1_ref, x0_ref, xp1_ref, xp2_ref,
                out_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_ref.shape[0]
        bt = kj_ref.shape[0]
        dt = w1_ref.dtype
        wins = (xm2_ref, xm1_ref, x0_ref, xp1_ref, xp2_ref)
        g, _ = _gather_w(kj_ref, wins, i - _W // 2, bn, dt)
        cbf_e = _dot(cbf_ref[:], exp_ref[:], ((1,), (0,)), dt)
        sbf = g[:, :_GH] * cbf_e
        emb1 = _dot(sbf, w1_ref[:], ((1,), (0,)), dt)
        emb2 = _dot(emb1, w2_ref[:], ((1,), (0,)), dt)
        msg = g[:, _GH:] * emb2
        jloc = ji_ref[:] - i * bn
        onehot_j = (jloc == jax.lax.broadcasted_iota(
            jnp.int32, (bt, bn), 1)).astype(dt)
        out_ref[:] += _dot(onehot_j, msg, ((0,), (0,)), dt)


def _bwd_kernel(si_ref, se_ref, av_ref, fi_ref, ftb_ref,
                kj_ref, ji_ref, cbf_ref,
                w1_ref, w2_ref, exp_ref,
                xm2_ref, xm1_ref, x0_ref, xp1_ref, xp2_ref,
                gm2_ref, gm1_ref, g0_ref, gp1_ref, gp2_ref,
                dx_ref, dw1_ref, dw2_ref, dcbf_ref):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(s == 0)
    def _init_w():
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        dw2_ref[:] = jnp.zeros_like(dw2_ref)

    @pl.when(fi_ref[s] == 1)
    def _init_o():
        dx_ref[:] = jnp.zeros_like(dx_ref)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = dx_ref.shape[0]
        bt = kj_ref.shape[0]
        dt = w1_ref.dtype
        xw = (xm2_ref, xm1_ref, x0_ref, xp1_ref, xp2_ref)
        gw = (gm2_ref, gm1_ref, g0_ref, gp1_ref, gp2_ref)
        base = i - _W // 2
        g, onehot_k = _gather_w(kj_ref, xw, base, bn, dt)
        cbf_e = _dot(cbf_ref[:], exp_ref[:], ((1,), (0,)), dt)
        radial_g = g[:, :_GH]
        x2 = g[:, _GH:]
        sbf = radial_g * cbf_e
        emb1 = _dot(sbf, w1_ref[:], ((1,), (0,)), dt)
        emb2 = _dot(emb1, w2_ref[:], ((1,), (0,)), dt)
        dout, _ = _gather_w(ji_ref, gw, base, bn, dt)      # [BT, GH pad]
        # OWNERSHIP mask: a boundary triplet block is revisited by every
        # out-block whose kj rows it holds; each visit must count only
        # the rows OWNED by out-block i (kj in block i), or dW1/dW2/
        # d_radial/d_cbf double-count.  Everything downstream is
        # proportional to dout, so one mask suffices (the dx scatter is
        # already own-masked by its center-slice one-hot).
        kloc = kj_ref[:, 0] - i * bn
        own = ((kloc >= 0) & (kloc < bn)).astype(jnp.float32)[:, None]
        dout = dout * own
        d_emb2 = dout * x2
        d_x2 = dout * emb2
        d_emb1 = _dot(d_emb2, w2_ref[:], ((1,), (1,)), dt)
        d_sbf = _dot(d_emb1, w1_ref[:], ((1,), (1,)), dt)
        dw2_ref[:] += _dot(emb1, d_emb2, ((0,), (0,)), dt)
        dw1_ref[:] += _dot(sbf, d_emb1, ((0,), (0,)), dt)
        d_radial = d_sbf * cbf_e                            # [BT, GH]
        # compact angular cotangent: compress (l, r) slots back to l
        dcbf_v = _dot(d_sbf * radial_g, exp_ref[:], ((1,), (1,)), dt)
        dxcat = jnp.concatenate([d_radial, d_x2], axis=1)
        dx_ref[:] += _dot(
            onehot_k[:, (_W // 2) * bn:(_W // 2 + 1) * bn],
            dxcat, ((0,), (0,)), dt)
        first_tb = ftb_ref[s] == 1
        dcbf_ref[:] = jnp.where(first_tb, dcbf_v, dcbf_ref[:] + dcbf_v)

    @pl.when((av_ref[s] == 0) & (ftb_ref[s] == 1))
    def _init_t():
        dcbf_ref[:] = jnp.zeros_like(dcbf_ref)


def _pack_x(radial, x2, e_pad, dt):
    e, g1 = radial.shape
    d = x2.shape[1]
    xcat = jnp.zeros((e_pad, 2 * _GH), dt)
    xcat = xcat.at[:e, :g1].set(radial.astype(dt))
    xcat = xcat.at[:e, _GH:_GH + d].set(x2.astype(dt))
    return xcat


def _pack_tri(cbf, idx_kj, idx_ji, tmask, t_pad, e_pad):
    t, s = cbf.shape
    cbf_p = jnp.zeros((t_pad, _SP), jnp.float32)
    cbf_p = cbf_p.at[:t, :s].set(cbf.astype(jnp.float32))
    valid = tmask != 0
    kj_p = jnp.full((t_pad, 1), e_pad, jnp.int32).at[:t, 0].set(
        jnp.where(valid, idx_kj, e_pad).astype(jnp.int32))
    ji_p = jnp.full((t_pad, 1), e_pad, jnp.int32).at[:t, 0].set(
        jnp.where(valid, idx_ji, e_pad).astype(jnp.int32))
    return cbf_p, kj_p, ji_p


def _pack_w(w1, w2, dt):
    g1, b = w1.shape
    b2, d = w2.shape
    w1_p = jnp.zeros((_GH, _GH), jnp.float32).at[:g1, :b].set(
        w1.astype(jnp.float32))
    w2_p = jnp.zeros((_GH, _GH), jnp.float32).at[:b2, :d].set(
        w2.astype(jnp.float32))
    return w1_p.astype(dt), w2_p.astype(dt)


import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(9,))
def dimenet_triplet_mp(radial, x2, cbf, w1, w2, idx_kj, idx_ji,
                       tmask, perm_kj, num_radial):
    """``out[e] = sum_{t: idx_ji[t]=e} x2[idx_kj[t]] * emb(radial[idx_kj[t]]
    * expand(cbf[t]))`` with ``emb(s) = (s @ w1) @ w2`` computed in-VMEM;
    ``expand`` repeats the [T, S] angular columns over their radial slots
    (an 0/1 matmul in-kernel — the [T, S*R] stream never exists).

    radial: [E, S*R] edge-space radial basis; x2: [E, D] down-projected
    edge features; cbf: [T, S] angular basis; w1: [S*R, B], w2: [B, D];
    tmask: int, 1 = real triplet; perm_kj: host-precomputed stable
    argsort of idx_kj; num_radial: static R.  Differentiable wrt radial,
    x2, cbf, w1, w2.  Requires nondecreasing idx_ji with masked triplets
    tail-sorted and graphs spanning <= 2 edge blocks (window 5);
    S <= 8, S*R <= 64, B <= 64, D <= 64; masked triplets get
    exactly-zero grads."""
    out, _ = _tri_fwd(radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask,
                      num_radial)
    return out


def _tri_fwd(radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask, num_radial):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = jax.default_backend() != "tpu"
    e, d = x2.shape
    t, s = cbf.shape
    bf16 = x2.dtype == jnp.bfloat16
    dt = jnp.bfloat16 if bf16 else jnp.float32
    e_pad = _round_up(max(e, 1), _EB)
    t_pad = _round_up(max(t, 1), _TB)
    n_blocks, n_tblocks = e_pad // _EB, t_pad // _TB

    xcat = _pack_x(radial, x2, e_pad, dt)
    cbf_p, kj_p, ji_p = _pack_tri(cbf, idx_kj, idx_ji, tmask, t_pad, e_pad)
    w1_p, w2_p = _pack_w(w1, w2, dt)
    exp_m = _expand_matrix(s, num_radial, dt)

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        ji_p[:, 0], n_blocks, _EB, _TB, n_tblocks)
    tix, xoff, const, outx = _win_maps(n_blocks)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((_TB, 1), tix),
            pl.BlockSpec((_TB, 1), tix),
            pl.BlockSpec((_TB, _SP), tix),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_SP, _GH), const),
        ] + [pl.BlockSpec((_EB, 2 * _GH), xoff(o))
             for o in range(-(_W // 2), _W // 2 + 1)],
        out_specs=pl.BlockSpec((_EB, _GH), outx),
    )
    out = pl.pallas_call(
        _fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((e_pad, _GH), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first,
      kj_p, ji_p, cbf_p, w1_p, w2_p, exp_m,
      xcat, xcat, xcat, xcat, xcat)
    return out[:e, :d].astype(x2.dtype), (e_pad, t_pad, dt)


def _tri_vjp_fwd(radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask,
                 perm_kj, num_radial):
    out, _ = _tri_fwd(radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask,
                      num_radial)
    return out, (radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask,
                 perm_kj)


def _tri_vjp_bwd(num_radial, res, dout):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask, perm_kj = res
    interpret = jax.default_backend() != "tpu"
    e, d = x2.shape
    t, s = cbf.shape
    g1 = radial.shape[1]
    bf16 = x2.dtype == jnp.bfloat16
    dt = jnp.bfloat16 if bf16 else jnp.float32
    e_pad = _round_up(max(e, 1), _EB)
    t_pad = _round_up(max(t, 1), _TB)
    n_blocks, n_tblocks = e_pad // _EB, t_pad // _TB

    if perm_kj is None:
        perm_kj = jnp.argsort(idx_kj, stable=True)

    xcat = _pack_x(radial, x2, e_pad, dt)
    gout = jnp.zeros((e_pad, _GH), dt).at[:e, :d].set(dout.astype(dt))
    cbf_s, kj_s, ji_s = _pack_tri(
        cbf[perm_kj], idx_kj[perm_kj], idx_ji[perm_kj],
        tmask[perm_kj], t_pad, e_pad)
    w1_p, w2_p = _pack_w(w1, w2, dt)
    exp_m = _expand_matrix(s, num_radial, dt)

    # schedule sorted by idx_kj (output axis = kj's edge blocks)
    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        kj_s[:, 0], n_blocks, _EB, _TB, n_tblocks)
    prev_tb = jnp.concatenate([jnp.full(1, -1, jnp.int32), step_eb[:-1]])
    first_tb = (step_eb != prev_tb).astype(jnp.int32)
    tix, xoff, const, outx = _win_maps(n_blocks)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((_TB, 1), tix),
            pl.BlockSpec((_TB, 1), tix),
            pl.BlockSpec((_TB, _SP), tix),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_SP, _GH), const),
        ] + [pl.BlockSpec((_EB, 2 * _GH), xoff(o))
             for o in range(-(_W // 2), _W // 2 + 1)]
          + [pl.BlockSpec((_EB, _GH), xoff(o))
             for o in range(-(_W // 2), _W // 2 + 1)],
        out_specs=[
            pl.BlockSpec((_EB, 2 * _GH), outx),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_GH, _GH), const),
            pl.BlockSpec((_TB, _SP), tix),
        ],
    )
    dx_p, dw1_p, dw2_p, dcbf_s = pl.pallas_call(
        _bwd_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((e_pad, 2 * _GH), jnp.float32),
            jax.ShapeDtypeStruct((_GH, _GH), jnp.float32),
            jax.ShapeDtypeStruct((_GH, _GH), jnp.float32),
            jax.ShapeDtypeStruct((t_pad, _SP), jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, first_tb,
      kj_s, ji_s, cbf_s, w1_p, w2_p, exp_m,
      xcat, xcat, xcat, xcat, xcat,
      gout, gout, gout, gout, gout)

    d_radial = dx_p[:e, :g1].astype(radial.dtype)
    d_x2 = dx_p[:e, _GH:_GH + d].astype(x2.dtype)
    dw1 = dw1_p[:g1, :w1.shape[1]].astype(w1.dtype)
    dw2 = dw2_p[:w2.shape[0], :d].astype(w2.dtype)
    # unpermute the kj-sorted d_cbf stream; zero masked rows (their
    # blocks are never visited -> uninitialized memory; `where`, not
    # multiply, so NaN/Inf garbage cannot propagate)
    inv = jnp.argsort(perm_kj)
    dcbf = dcbf_s[:t][inv]
    valid = (tmask != 0)[:, None]
    dcbf = jnp.where(valid, dcbf[:, :s], 0.0).astype(cbf.dtype)
    return (d_radial, d_x2, dcbf, dw1, dw2, None, None, None, None)


dimenet_triplet_mp.defvjp(_tri_vjp_fwd, _tri_vjp_bwd)


# ---------------------------------------------------------------------------
# builder-backed triplet contraction (wide dims)
# ---------------------------------------------------------------------------
#
# The factored-basis kernel above is gated to S <= 8 / S*R <= 64 /
# int_emb <= 64.  Beyond those (but within one 128-lane tile) the
# contraction still IS message passing in edge space, so it rides the
# generic fused-block builder: geo carries the per-triplet sbf stream,
# the chain fuses lin_sbf1/lin_sbf2, and the gather/scatter pair uses
# the same 5-block window invariant the collate marker vouches for.
# Trades the factored kernel's compact [T, S<=8] angular stream for the
# full [T, S*R] sbf stream — still one pass, no [T, D] embedding
# materialization.

TRI_SBF_LIMIT = _fb._GP - 1  # S*R lanes (one geo tile incl. bias lane)
TRI_EMB_LIMIT = 128          # basis_emb / int_emb single tile


def _tri_chain(w_vals, geo, xp, xo, dt):
    k1, k2 = w_vals
    emb = _fb._dot(_fb._dot(geo, k1, ((1,), (0,)), dt),
                   k2, ((1,), (0,)), dt)
    return (xo * emb,)


@functools.lru_cache(maxsize=None)
def _tri_builder_op():
    return _fb.build_fused_edge_op(_fb.EdgeBlockSpec(
        name="dn_tri_builder", primary="receiver", gather_primary=False,
        gather_other=True, num_outputs=1, chain=_tri_chain,
        window=_W, edge_block=256))


def dimenet_tri_builder(x_kj, sbf, tmask, k1, k2, idx_kj, idx_ji, perm_kj):
    """``out[e'] = sum_{t: ji(t)=e'} x_kj[kj(t)] * ((sbf_t @ k1) @ k2)``
    in ONE pass, forward and backward (builder two-pass VJP).

    Differentiable wrt x_kj, sbf, k1, k2 (the sbf cotangent chains into
    angle/distance grads outside).  Requires idx_ji nondecreasing,
    masked triplets tail-sorted in both orderings (add_dimenet_extras
    pads the tail), every graph's edge-id span <= 2 edge blocks (the
    collate marker vouches), S*R <= TRI_SBF_LIMIT and basis/int
    embedding sizes <= TRI_EMB_LIMIT (callers gate).  ``tmask`` is the
    int32 triplet-validity mask: masked triplets are schedule-skipped
    and get exactly zero for every output and grad."""
    e, d = x_kj.shape
    s = sbf.shape[-1]
    b = k1.shape[-1]
    d_pad = _round_up(max(d, 1), 128)
    b_pad = _round_up(max(b, 1), 128)
    gpw = _round_up(s + 1, _fb._GP)
    k1_p = jnp.zeros((gpw, b_pad), jnp.float32).at[:s, :b].set(
        k1.astype(jnp.float32))
    k2_p = jnp.zeros((b_pad, d_pad), jnp.float32).at[:b, :d].set(
        k2.astype(jnp.float32))
    if x_kj.dtype == jnp.bfloat16:
        k1_p = k1_p.astype(jnp.bfloat16)
        k2_p = k2_p.astype(jnp.bfloat16)
    (out,) = _tri_builder_op()(
        x_kj, sbf, tmask, (k1_p, k2_p), idx_kj, idx_ji, perm_kj)
    return out[:e, :d].astype(x_kj.dtype)
