"""Fused multi-aggregator message passing: sum / sum-of-squares / max / min
/ count in ONE Pallas pass over the sorted-receiver edge blocks.

PNA — the reference framework's flagship conv — needs [mean, min, max, std]
per node.  Composed, that costs two scatter-sums (mean/std share a
sum/sum-of-squares pair), a double-width ``segment_max`` that XLA lowers to
a long sort pipeline, and a separate degree scatter: four passes over the
[E, F] message tensor, each streaming it through HBM.  This kernel rides
the same CSR-style dense schedule as ops/fused_mp.py (scalar-prefetched
step tables over (node-block, edge-block) pairs; see ``_dense_schedule``)
and emits every requested aggregation moment from a single read of each
edge block:

  sum    += onehot_r^T @ msgs                  (MXU)
  sq     += onehot_r^T @ msgs^2                (MXU)
  mxmn    = running max of [msgs, -msgs]       (segmented scan, see below)
  cnt    += column sums of onehot_r            (VPU)

mean and std are ordinary elementwise math OUTSIDE the kernel
(``sum / max(cnt, 1)``; ``sqrt(max(sq/cnt - mean^2, 0) + eps)`` — the
``segment_mean``/``segment_std`` numerics), min is ``-max(-msg)``.

In-kernel segment max WITHOUT a sort and WITHOUT the serial per-row loop
that was measured-and-rejected for the GAT logits max (docs/PERF.md
"measured and rejected", 6.5k g/s): receivers are NONDECREASING, so within
an edge block each node's edges form a contiguous run.  A Hillis-Steele
segmented max-scan (log2(BE) shifted maxima, gated on shifted-id equality
— valid precisely because equal ids are contiguous) leaves each run's LAST
row holding the run max; a 0/1 ``last-of-run`` selector turns the
placement into one onehot matmul (at most one selected row per node per
block, so SUM is exact placement), and a running ``jnp.maximum`` across
grid steps merges runs that span edge-block boundaries.

Modes:
  scatter  — ``data`` is already edge-valued (PNA's pre_nn messages,
             CGCNN's gated messages): moments of ``data`` at receivers.
  gather   — messages are ``x[senders] * mask`` formed in-VMEM via the
             3-block one-hot window (SAGE/MFC neighbor aggregation): the
             [E, F] message tensor never exists in HBM.

Masked/padding edges are parked on the out-of-range sentinel (same
contract as fused_mp: zero-data rows that sort after all real edges), so
the schedule never visits their blocks and they enter no node's max.

Backward (custom VJP, no kernel differentiation):
  d sum / d data[e]  = g_sum[ids[e]]                    (sorted gather)
  d sq  / d data[e]  = 2 data[e] g_sq[ids[e]]
  d mxmn / d data[e] = +- tie(e) g[ids[e]] / n_ties     (even tie split —
                       bit-parity with jax.ops.segment_max's VJP; the tie
                       counts ride ONE segment_sum_dense pass)
  cnt carries no data gradient.
Gather mode chains these through ``msgs = x[send] * mask`` and scatters at
senders via the sender-sorted permutation (collate's ``edge_perm_sender``),
exactly like fused_mp's backward; the sum-only case rides the fused
gather->scatter kernel directly with no [E, F] intermediate.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.aggregate import _round_up
from hydragnn_tpu.ops.fused_block import _dense_schedule
from hydragnn_tpu.ops.fused_mp import segment_sum_dense

_NODE_BLOCK = 128
_EDGE_BLOCK = 512

# sentinel magnitude: rides matmuls (placement onehot) and exp-free maxima;
# 1e9 keeps reduced-precision contractions from rounding it into inf (the
# gat_mp sentinel rationale)
_NEG = -1e9

# canonical kernel-moment order; public dispatchers map mx/mn onto "mxmn"
MOMENT_ORDER = ("sum", "sq", "mxmn", "cnt")

# widest feature width (pre-padding) the kernel compiles for: the mxmn scan
# holds two [BE, 2*F_pad] f32 temporaries (y + its shift) next to the data
# block and the double-buffered outputs, so the concatenated width is the
# binding one.  Above these the dispatchers fall back to the composed path.
POLY_MAX_F_MXMN = 512
POLY_MAX_F = 1024


def _norm_moments(moments):
    ms = tuple(m for m in MOMENT_ORDER if m in moments)
    unknown = set(moments) - set(MOMENT_ORDER)
    if unknown or not ms:
        raise ValueError(f"moments must be a nonempty subset of "
                         f"{MOMENT_ORDER}, got {moments!r}")
    return ms


def _edge_block(f_pad: int, moments) -> int:
    """Edge-block size keeping the widest per-row temporary (2*f_pad when
    the mxmn scan runs) inside scoped VMEM next to the moment outputs."""
    widest = 2 * f_pad if "mxmn" in moments else f_pad
    return _EDGE_BLOCK if widest <= 512 else 256


def _shift_down(a, d, fill):
    """Rows shifted down by ``d`` (row e reads e-d), top filled."""
    pad = jnp.full((d,) + a.shape[1:], fill, a.dtype)
    return jnp.concatenate([pad, a[: a.shape[0] - d]], axis=0)


def _accum_moments(moments, msgs, onehot_r, rloc, out_refs):
    """Accumulate the requested moments of ``msgs`` [BE, F] into the node
    block's output refs.  ``onehot_r`` [BE, BN] is the receiver one-hot
    (all-zero rows for parked edges), ``rloc`` [BE, 1] the block-local
    receiver ids (>= BN for parked edges — never colliding with real
    locals, so scan runs of parked rows stay separate from real runs)."""
    o = 0
    if "sum" in moments:
        out_refs[o][:] += jax.lax.dot_general(
            onehot_r, msgs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o += 1
    if "sq" in moments:
        out_refs[o][:] += jax.lax.dot_general(
            onehot_r, msgs * msgs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o += 1
    if "mxmn" in moments:
        be = msgs.shape[0]
        y = jnp.concatenate([msgs, -msgs], axis=1)       # [BE, 2F]
        in_block = jnp.sum(onehot_r, axis=1, keepdims=True)  # [BE, 1]
        y = jnp.where(in_block > 0, y, _NEG)
        ids = rloc
        # Hillis-Steele segmented inclusive max-scan: equal ids are
        # CONTIGUOUS (sorted receivers), so gating each shifted max on
        # id equality is exact — after offset d, row e holds the max over
        # the last 2d rows of its run
        d = 1
        while d < be:
            ids_sh = _shift_down(ids, d, -1)
            y_sh = _shift_down(y, d, _NEG)
            y = jnp.where(ids_sh == ids, jnp.maximum(y, y_sh), y)
            d *= 2
        # last row of each id run now holds the run max; one selected row
        # per node per block makes the onehot SUM an exact placement
        ids_nx = jnp.concatenate(
            [ids[1:], jnp.full((1, 1), -2, jnp.int32)], axis=0)
        sel = (ids != ids_nx).astype(jnp.float32)        # [BE, 1]
        pick = onehot_r * sel                            # [BE, BN]
        contrib = jax.lax.dot_general(
            pick, y, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BN, 2F]
        has = jnp.sum(pick, axis=0)[:, None]             # [BN, 1]
        contrib = jnp.where(has > 0, contrib, _NEG)
        out_refs[o][:] = jnp.maximum(out_refs[o][:], contrib)
        o += 1
    if "cnt" in moments:
        out_refs[o][:] += jnp.broadcast_to(
            jnp.sum(onehot_r, axis=0)[:, None], out_refs[o].shape)


def _init_outs(moments, out_refs):
    for m, ref in zip(moments, out_refs):
        ref[:] = (jnp.full_like(ref, _NEG) if m == "mxmn"
                  else jnp.zeros_like(ref))


def _poly_scatter_kernel(moments, si_ref, se_ref, av_ref, fi_ref,
                         ids_ref, data_ref, *out_refs):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        _init_outs(moments, out_refs)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_refs[0].shape[0]
        be = ids_ref.shape[0]
        rloc = ids_ref[:] - i * bn                       # [BE, 1]
        onehot_r = (rloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(jnp.float32)
        _accum_moments(moments, data_ref[:].astype(jnp.float32),
                       onehot_r, rloc, out_refs)


def _poly_gather_kernel(moments, window, si_ref, se_ref, av_ref, fi_ref,
                        send_ref, recv_ref, mask_ref, *rest):
    from jax.experimental import pallas as pl

    xwin_refs = rest[:window]
    out_refs = rest[window:]

    s = pl.program_id(0)
    i = si_ref[s]

    @pl.when(fi_ref[s] == 1)
    def _init():
        _init_outs(moments, out_refs)

    @pl.when(av_ref[s] == 1)
    def _acc():
        bn = out_refs[0].shape[0]
        be = send_ref.shape[0]
        hw = window // 2
        base = (i - hw) * bn
        sloc = send_ref[:] - base
        onehot_s = (sloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, window * bn), 1)).astype(jnp.float32)
        xcat = jnp.concatenate(
            [r[:] for r in xwin_refs], axis=0).astype(jnp.float32)
        msgs = jax.lax.dot_general(
            onehot_s, xcat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [BE, F]
        msgs = msgs * mask_ref[:].astype(jnp.float32)
        rloc = recv_ref[:] - i * bn
        onehot_r = (rloc == jax.lax.broadcasted_iota(
            jnp.int32, (be, bn), 1)).astype(jnp.float32)
        _accum_moments(moments, msgs, onehot_r, rloc, out_refs)


def _out_layout(moments, f_pad):
    """(width per moment output, in kernel-moment order)."""
    return tuple(2 * f_pad if m == "mxmn" else (128 if m == "cnt" else f_pad)
                 for m in moments)


def _slice_outs(moments, outs, num_segments, f, f_pad, dtype):
    res = []
    for m, o in zip(moments, outs):
        if m == "mxmn":
            res.append(jnp.concatenate(
                [o[:num_segments, :f], o[:num_segments, f_pad:f_pad + f]],
                axis=1).astype(dtype))
        elif m == "cnt":
            res.append(o[:num_segments, 0])
        else:
            res.append(o[:num_segments, :f].astype(dtype))
    return tuple(res)


def _poly_scatter_impl(data2d, sorted_ids, num_segments, moments, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e, f = data2d.shape
    f_pad = _round_up(max(f, 1), 128)
    bn, be = _NODE_BLOCK, _edge_block(f_pad, moments)
    n_pad = _round_up(num_segments, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    data_p = jnp.zeros((e_pad, f_pad), data2d.dtype).at[:e, :f].set(data2d)
    ids_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        sorted_ids.astype(jnp.int32))

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        ids_p[:, 0], n_blocks, bn, be, n_eblocks)

    def eix(s, si, se, av, fi):
        return (se[s], 0)

    def oix(s, si, se, av, fi):
        return (si[s], 0)

    widths = _out_layout(moments, f_pad)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, f_pad), eix),
        ],
        out_specs=[pl.BlockSpec((bn, w), oix) for w in widths],
    )
    outs = pl.pallas_call(
        functools.partial(_poly_scatter_kernel, moments),
        out_shape=[jax.ShapeDtypeStruct((n_pad, w), jnp.float32)
                   for w in widths],
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, ids_p, data_p)
    return _slice_outs(moments, outs, num_segments, f, f_pad, data2d.dtype)


def _poly_gather_impl(x, senders, receivers, moments, mask, interpret,
                      window=3):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = x.shape
    e = senders.shape[0]
    f_pad = _round_up(max(f, 1), 128)
    bn, be = _NODE_BLOCK, _edge_block(f_pad, moments)
    n_pad = _round_up(n, bn)
    e_pad = _round_up(max(e, 1), be)
    n_blocks, n_eblocks = n_pad // bn, e_pad // be

    x_p = jnp.zeros((n_pad, f_pad), x.dtype).at[:n, :f].set(x)
    m = (jnp.ones((e,), jnp.float32) if mask is None
         else mask.astype(jnp.float32))
    # masked edges park out of every block/window (fused_mp contract: they
    # sort after all real edges, so the schedule skips their blocks)
    ev = m != 0
    senders = jnp.where(ev, senders, n_pad)
    receivers = jnp.where(ev, receivers, n_pad)
    send_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        senders.astype(jnp.int32))
    recv_p = jnp.full((e_pad, 1), n_pad, jnp.int32).at[:e, 0].set(
        receivers.astype(jnp.int32))
    mask_p = jnp.zeros((e_pad, 1), jnp.float32).at[:e, 0].set(m)

    step_i, step_eb, acc_valid, is_first, s_max = _dense_schedule(
        recv_p[:, 0], n_blocks, bn, be, n_eblocks)

    def eix(s, si, se, av, fi):
        return (se[s], 0)

    def oix(s, si, se, av, fi):
        return (si[s], 0)

    def xoff(off):
        def fmap(s, si, se, av, fi):
            return (jnp.clip(si[s] + off, 0, n_blocks - 1), 0)
        return fmap

    assert window % 2 == 1, "window must be odd"
    hw = window // 2
    widths = _out_layout(moments, f_pad)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(s_max,),
        in_specs=[
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
            pl.BlockSpec((be, 1), eix),
        ] + [pl.BlockSpec((bn, f_pad), xoff(o)) for o in range(-hw, hw + 1)],
        out_specs=[pl.BlockSpec((bn, w), oix) for w in widths],
    )
    outs = pl.pallas_call(
        functools.partial(_poly_gather_kernel, moments, window),
        out_shape=[jax.ShapeDtypeStruct((n_pad, w), jnp.float32)
                   for w in widths],
        grid_spec=grid_spec,
        interpret=interpret,
    )(step_i, step_eb, acc_valid, is_first, send_p, recv_p, mask_p,
      *([x_p] * window))
    return _slice_outs(moments, outs, n, f, f_pad, x.dtype)


# ---------------------------------------------------------------------------
# scatter-mode public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_poly_dense(data, sorted_ids, num_segments, moments, valid=None):
    """Multi-moment segment reduce of edge-valued ``data`` [E, F] at
    NONDECREASING ``sorted_ids`` — one dense-schedule Pallas pass returning
    a tuple in kernel-moment order (subset of ``MOMENT_ORDER``):

      sum [N, F], sq [N, F] (sum of squares), mxmn [N, 2F] (max of
      [data, -data]; -1e9 on empty segments — callers apply the
      segment_max zero-clean), cnt [N] (rows per segment).

    ``valid`` (optional, 1 = real) parks masked rows out of range so the
    schedule skips their blocks; masked rows must sort after all real rows
    (collate's padding-edge guarantee).  Masked/out-of-range rows get ZERO
    gradients.  Differentiable wrt ``data``; the max/min gradient splits
    evenly among ties, matching ``jax.ops.segment_max``'s VJP.
    """
    moments = _norm_moments(moments)
    interpret = jax.default_backend() != "tpu"
    if valid is not None:
        sorted_ids = jnp.where(valid != 0, sorted_ids, num_segments)
    return _poly_scatter_impl(data, sorted_ids, num_segments, moments,
                              interpret)


def _spd_fwd(data, sorted_ids, num_segments, moments, valid=None):
    moments = _norm_moments(moments)
    if valid is not None:
        sorted_ids = jnp.where(valid != 0, sorted_ids, num_segments)
    out = segment_poly_dense(data, sorted_ids, num_segments, moments)
    mxmn = out[moments.index("mxmn")] if "mxmn" in moments else None
    return out, (data, sorted_ids, mxmn)


def _spd_bwd(num_segments, moments, res, g):
    moments = _norm_moments(moments)
    data, ids, mxmn = res
    f = data.shape[1]
    ok = (ids >= 0) & (ids < num_segments)
    safe = jnp.clip(ids, 0, num_segments - 1)
    d = jnp.zeros(data.shape, jnp.float32)
    for m, gm in zip(moments, g):
        if m == "sum":
            d += jnp.where(ok[:, None], gm[safe].astype(jnp.float32), 0.0)
        elif m == "sq":
            d += 2.0 * data.astype(jnp.float32) * jnp.where(
                ok[:, None], gm[safe].astype(jnp.float32), 0.0)
        elif m == "mxmn":
            both = jnp.concatenate([data, -data], axis=1)
            tie = (both == mxmn[safe]) & ok[:, None]        # [E, 2F]
            # even tie split (jax.ops.segment_max VJP parity): tie counts
            # for max and min ride ONE sorted dense pass
            n_tie = segment_sum_dense(
                tie.astype(jnp.float32), ids, num_segments)
            gmx = jnp.where(ok[:, None], gm[safe].astype(jnp.float32), 0.0)
            term = jnp.where(
                tie, gmx / jnp.maximum(n_tie[safe], 1.0), 0.0)
            d += term[:, :f] - term[:, f:]
        # cnt: no data gradient
    return d.astype(data.dtype), None, None


segment_poly_dense.defvjp(_spd_fwd, _spd_bwd)


# ---------------------------------------------------------------------------
# gather-mode public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def gather_poly_segment(x, senders, receivers, sender_perm, moments,
                        mask=None):
    """Multi-moment reduce of the gathered messages ``x[senders] * mask``
    at NONDECREASING ``receivers``, without materializing the [E, F]
    message tensor (same collate invariants as
    :func:`~hydragnn_tpu.ops.fused_mp.gather_mul_segment_sum`: graphs
    contiguous and within one node block, masked edges zero-masked and
    tail-sorted).  Returns the same tuple layout as
    :func:`segment_poly_dense`.  ``sender_perm`` is collate's stable
    sender argsort (backward scatters dx at senders through it; pass None
    for a forward-only call).  Differentiable wrt ``x``.
    """
    moments = _norm_moments(moments)
    interpret = jax.default_backend() != "tpu"
    return _poly_gather_impl(x, senders, receivers, moments, mask,
                             interpret)


def _gps_fwd(x, senders, receivers, sender_perm, moments, mask=None):
    moments = _norm_moments(moments)
    out = gather_poly_segment(x, senders, receivers, sender_perm, moments,
                              mask)
    mxmn = out[moments.index("mxmn")] if "mxmn" in moments else None
    return out, (x, senders, receivers, sender_perm, mask, mxmn)


def _gps_bwd(moments, res, g):
    from hydragnn_tpu.ops.fused_mp import _fused_impl

    moments = _norm_moments(moments)
    x, senders, receivers, sender_perm, mask, mxmn = res
    n, f = x.shape
    interpret = jax.default_backend() != "tpu"
    m = (jnp.ones((senders.shape[0],), jnp.float32) if mask is None
         else mask.astype(jnp.float32))
    if sender_perm is None:
        sender_perm = jnp.argsort(senders, stable=True)

    moms = dict(zip(moments, g))
    need_msgs = ("sq" in moments) or ("mxmn" in moments)
    if not need_msgs and "sum" not in moms:
        return jnp.zeros_like(x), None, None, None, None  # cnt-only
    if not need_msgs:
        # sum-only (cnt has no x-grad): dx[n] = sum_{e: send=n} m_e
        # g_sum[recv_e] — the fused gather->scatter kernel on the
        # sender-sorted ordering, no [E, F] intermediate (fused_mp's
        # _gss_bwd structure)
        g_sum = moms["sum"].astype(jnp.float32)
        mp = m[sender_perm]
        dx = _fused_impl(
            g_sum, None, receivers[sender_perm], senders[sender_perm],
            interpret, mask=mp, edge_valid=mp)
        return dx.astype(x.dtype), None, None, None, None

    # sq/mxmn need the messages: recompute the gather (receivers gather of
    # g is sorted and cheap; senders gather of x is the one re-read)
    msgs = x[senders].astype(jnp.float32) * m[:, None]
    c = jnp.zeros(msgs.shape, jnp.float32)               # dL/dmsgs
    if "sum" in moments:
        c += moms["sum"][receivers].astype(jnp.float32)
    if "sq" in moments:
        c += 2.0 * msgs * moms["sq"][receivers].astype(jnp.float32)
    if "mxmn" in moments:
        both = jnp.concatenate([msgs, -msgs], axis=1)
        ids = jnp.where(m != 0, receivers, n)
        ok = m != 0
        safe = jnp.clip(ids, 0, n - 1)
        tie = (both == mxmn[safe]) & ok[:, None]
        n_tie = segment_sum_dense(tie.astype(jnp.float32), ids, n)
        gmx = jnp.where(ok[:, None],
                        moms["mxmn"][safe].astype(jnp.float32), 0.0)
        term = jnp.where(tie, gmx / jnp.maximum(n_tie[safe], 1.0), 0.0)
        c += term[:, :f] - term[:, f:]
    # dmsgs/dx[send] = m; scatter at senders over the sorted permutation
    c = c * m[:, None]
    perm = sender_perm
    dx = segment_sum_dense(c[perm], senders[perm], n,
                           valid=m[perm])
    return dx.astype(x.dtype), None, None, None, None


gather_poly_segment.defvjp(_gps_fwd, _gps_bwd)
