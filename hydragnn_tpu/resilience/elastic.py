"""Elastic training: resume a run saved at world-shape N in world-shape M.

Production pods ride preemptible capacity that shrinks and grows under a
run; the reference HydraGNN assumes (rank, world_size) is fixed for the
run's life.  Every primitive for elasticity already exists in this repo
and this module composes them:

- resume bundles are CONSOLIDATED stage-agnostically (parallel/zero.py:
  consolidate_state runs before every save), so
  :func:`~hydragnn_tpu.parallel.zero.reshard_state` can place the same
  bundle under any launched mesh and ZeRO stage — leading dims re-pad to
  multiples of the new axis size, moments re-slice;
- the streaming StreamPlan is a pure function of
  ``(n_total, seed, epoch, rank, world_size)`` (data/stream/plan.py), so
  the per-host order at the new world size is a re-partition of the SAME
  seeded global permutation — every dataset index is visited exactly once
  per epoch at any world size (``StreamPlan.elastic_handoff``);
- preemption agreement (resilience/preempt.py) supplies the allreduce
  machinery the epoch-boundary :class:`ElasticCoordinator` reuses to
  admit/retire hosts without a new collective protocol.

The contract is EPOCH-GRANULAR: a resize takes effect at an epoch
boundary, where the world's data position is a single integer (epoch).
Mid-epoch positions (``items_consumed`` dispatch units) are world-shape
DEPENDENT — a dispatch unit at world N covers ``G_N`` global samples —
so a mid-epoch elastic resume either converts the position EXACTLY (the
consumed sample count is a whole number of new-shape units, which holds
whenever the global batch is preserved across the resize) or rounds UP
to the next epoch boundary, loudly.

``Training.elastic_resume`` policies:

- ``strict`` (default) — refuse any world-shape mismatch with a
  diagnostic naming both shapes and this knob.  This replaces the old
  SILENT hazard: a bundle saved at N and resumed at M used to replay a
  wrong-world shuffle and mis-count ``items_consumed`` without a word.
- ``epoch``  — admit the resize.  Epoch-boundary bundles resume
  directly; mid-epoch bundles convert exactly when possible, else round
  up to the next epoch boundary.

Health events: ``elastic_resize`` (a shape-changed resume was admitted,
or the coordinator agreed on a resize), ``elastic_admit`` (this host
entered the new world shape), ``elastic_retire`` (this host is leaving
at an epoch boundary, bundle saved), ``elastic_refuse`` (strict policy
refused a mismatched resume).  See docs/RESILIENCE.md "Elastic
training".
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

ELASTIC_POLICIES = ("strict", "epoch")


def check_elastic_policy(value: Any) -> str:
    """Validate a ``Training.elastic_resume`` knob value."""
    v = str(value or "strict").strip().lower()
    if v not in ELASTIC_POLICIES:
        raise ValueError(
            f"Training.elastic_resume must be one of {ELASTIC_POLICIES}, "
            f"got {value!r}")
    return v


def elastic_policy_from_training(training: Optional[Dict[str, Any]],
                                 *, env: bool = True) -> str:
    """Resolve the elastic-resume policy: ``Training.elastic_resume``
    overlaid by the HYDRAGNN_ELASTIC_RESUME env knob (env wins; a
    set-but-empty env falls through to the config value — the repo's
    env-knob convention, utils/env.py)."""
    s = dict(training or {})
    policy = check_elastic_policy(s.get("elastic_resume", "strict"))
    if env and os.environ.get("HYDRAGNN_ELASTIC_RESUME"):
        policy = check_elastic_policy(os.environ["HYDRAGNN_ELASTIC_RESUME"])
    return policy


# -- the resume-meta `world` block -----------------------------------------


def world_block(*, world_size: int, n_local_devices: int, dp_extent: int,
                zero_stage: int, epoch_units: Optional[int] = None,
                plan_fingerprint: Optional[str] = None) -> Dict[str, Any]:
    """The ``world`` block written into ``resume_meta.json``: everything a
    resume at a DIFFERENT shape needs to validate and convert the saved
    position.

    ``dp_extent`` is the total data-parallel extent (the number of
    batch shards per step — mesh device count on the mesh path, 1 on the
    local-jit path); it is the shape the stream split and the state
    padding actually depend on, not ``world_size`` alone.
    ``epoch_units`` is the saved run's dispatch units per train epoch
    (``len`` of the final wrapped train loader) — the denominator for
    converting a mid-epoch ``items_consumed`` across shapes.
    ``plan_fingerprint`` identifies the streaming plan's GLOBAL order
    (shape-independent, data/stream/plan.py) when the run streams."""
    return {
        "world_size": int(world_size),
        "n_local_devices": int(n_local_devices),
        "dp_extent": int(dp_extent),
        "zero_stage": int(zero_stage),
        "epoch_units": (int(epoch_units)
                        if epoch_units is not None else None),
        "plan_fingerprint": plan_fingerprint,
    }


def saved_world_from_meta(meta: Dict[str, Any]) -> Dict[str, Any]:
    """The saved run's world block, with a legacy fallback: pre-elastic
    bundles carry only top-level ``world_size`` and
    ``pipeline.n_local_devices`` — synthesize a partial block (no
    ``epoch_units``) so the shape comparison still works."""
    w = meta.get("world")
    if isinstance(w, dict) and "dp_extent" in w:
        return dict(w)
    pipeline = meta.get("pipeline") or {}
    ws = int(meta.get("world_size", 1) or 1)
    nl = int(pipeline.get("n_local_devices", 1) or 1)
    mesh_dp = bool(pipeline.get("use_mesh_dp", nl > 1 or ws > 1))
    return world_block(
        world_size=ws, n_local_devices=nl,
        dp_extent=(ws * nl if mesh_dp else 1),
        zero_stage=int(pipeline.get("zero_stage", 0) or 0),
        epoch_units=None, plan_fingerprint=None)


class ElasticWorldMismatchError(ValueError):
    """A resume bundle's world shape differs from the launched shape and
    the policy refuses the resize (``strict``, the default)."""


@dataclasses.dataclass(frozen=True)
class ElasticDecision:
    """Resolved resume position for the launched world shape.

    ``elastic`` is False on the same-shape path — the caller must then
    behave EXACTLY as before this module existed (the acceptance
    criterion: a same-shape resume stays bit-identical, the elastic path
    provably dormant)."""

    elastic: bool
    start_epoch: int
    skip_first: int
    rounded: bool  # a mid-epoch position was rounded to the next boundary
    reason: str
    saved: Dict[str, Any]
    launched: Dict[str, Any]


def _shapes_match(saved: Dict[str, Any], launched: Dict[str, Any]) -> bool:
    return (int(saved.get("world_size", 1)) ==
            int(launched.get("world_size", 1))
            and int(saved.get("dp_extent", 1)) ==
            int(launched.get("dp_extent", 1)))


def _shape_str(w: Dict[str, Any]) -> str:
    return (f"world_size={w.get('world_size')} "
            f"dp_extent={w.get('dp_extent')} "
            f"zero_stage={w.get('zero_stage')}")


def resolve_resume(meta: Dict[str, Any], *, policy: str,
                   launched: Dict[str, Any],
                   telemetry=None) -> ElasticDecision:
    """Decide where the launched run resumes, given the saved bundle meta
    and the launched world block.

    Same shape -> dormant pass-through of the saved position.  Shape
    mismatch under ``strict`` -> :class:`ElasticWorldMismatchError`
    naming both shapes and the knob.  Shape mismatch under ``epoch`` ->
    admit: epoch-boundary bundles (``items_consumed == 0``) resume
    directly; mid-epoch bundles convert ``items_consumed`` exactly when
    the consumed sample count is a whole number of launched-shape
    dispatch units (``items * units_new % units_saved == 0`` — both
    epochs cover the same sample total, so units scale inversely with
    the global batch), else round UP to the next epoch boundary: the
    already-applied updates are never replayed (no double-count), and
    the abandoned remainder of the epoch is surfaced loudly.
    """
    policy = check_elastic_policy(policy)
    saved = saved_world_from_meta(meta)
    epoch = int(meta.get("epoch", 0))
    items = int(meta.get("items_consumed", 0))

    if _shapes_match(saved, launched):
        # the plan fingerprint must agree even at the same shape: a
        # changed fingerprint means a DIFFERENT dataset/seed/order under
        # the same world — items_consumed would replay the wrong samples
        _check_fingerprint(saved, launched)
        return ElasticDecision(
            elastic=False, start_epoch=epoch, skip_first=items,
            rounded=False, reason="same_shape", saved=saved,
            launched=launched)

    if policy == "strict":
        msg = (
            "resume bundle world shape mismatch: saved "
            f"[{_shape_str(saved)}] but this run launched "
            f"[{_shape_str(launched)}].  A bundle resumed at a different "
            "world shape needs its state re-sharded and its stream "
            "re-planned; set Training.elastic_resume: epoch (env "
            "HYDRAGNN_ELASTIC_RESUME=epoch) to admit the resize at the "
            "epoch boundary, or relaunch at the saved shape.")
        if telemetry is not None:
            telemetry.health("elastic_refuse", policy=policy,
                             saved=_shape_str(saved),
                             launched=_shape_str(launched))
        raise ElasticWorldMismatchError(msg)

    _check_fingerprint(saved, launched)
    if items == 0:
        return ElasticDecision(
            elastic=True, start_epoch=epoch, skip_first=0, rounded=False,
            reason="epoch_boundary", saved=saved, launched=launched)

    units_saved = saved.get("epoch_units")
    units_new = launched.get("epoch_units")
    if units_saved and units_new:
        units_saved, units_new = int(units_saved), int(units_new)
        if items >= units_saved:
            # the whole epoch's units were consumed before the save —
            # positionally an epoch boundary
            return ElasticDecision(
                elastic=True, start_epoch=epoch + 1, skip_first=0,
                rounded=False, reason="completed_epoch", saved=saved,
                launched=launched)
        if (items * units_new) % units_saved == 0:
            return ElasticDecision(
                elastic=True, start_epoch=epoch,
                skip_first=(items * units_new) // units_saved,
                rounded=False, reason="mid_epoch_exact", saved=saved,
                launched=launched)
    return ElasticDecision(
        elastic=True, start_epoch=epoch + 1, skip_first=0, rounded=True,
        reason="mid_epoch_rounded", saved=saved, launched=launched)


def _check_fingerprint(saved: Dict[str, Any],
                       launched: Dict[str, Any]) -> None:
    fs, fl = saved.get("plan_fingerprint"), launched.get("plan_fingerprint")
    if fs and fl and fs != fl:
        raise ElasticWorldMismatchError(
            f"resume bundle stream-plan fingerprint {fs} does not match "
            f"this run's {fl}: the saved run streamed a different global "
            "order (dataset size, seed, or order mode changed) — "
            "items_consumed cannot be mapped onto this stream.  Relaunch "
            "against the saved store/seed, or clear the resume bundle.")


# -- epoch-boundary coordinator --------------------------------------------


class ElasticCoordinator:
    """Epoch-boundary admit/retire agreement for elastic resizes.

    The coordinator answers one question at each epoch boundary: *does
    the world resize now?*  A resize decision is armed locally — by the
    chaos harness (``HYDRAGNN_CHAOS_ELASTIC``, resilience/chaos.py) or
    programmatically via :meth:`request_resize` (a scheduler draining a
    host) — and agreed across ranks with the same allreduce-max
    machinery preemption agreement uses (resilience/preempt.py): any
    rank arming makes EVERY rank see the decision at the same boundary,
    so the bundle save below is a symmetric collective.

    On an agreed resize every rank saves the epoch-boundary resume
    bundle and exits (the trainer drives this through the existing
    SIGTERM bundle path) — a retiring host simply never relaunches, a
    joining host relaunches with ``continue`` at the new shape and
    :func:`resolve_resume` admits it.  The JAX runtime cannot resize a
    live mesh, so "resize" is deliberately checkpoint-and-relaunch; what
    this module buys is that the relaunch may be a DIFFERENT size with
    no bit lost.
    """

    def __init__(self, *, chaos=None, telemetry=None, world_size: int = 1,
                 cross_rank: bool = False):
        self.chaos = chaos
        self.telemetry = telemetry
        self.world_size = int(world_size)
        self.cross_rank = bool(cross_rank)
        self._requested_delta = 0
        self._fired = False

    @classmethod
    def from_env(cls, *, chaos=None, telemetry=None, world_size: int = 1,
                 cross_rank: bool = False) -> Optional["ElasticCoordinator"]:
        """Build only when something can arm a resize (the chaos knob);
        None otherwise — the trainer then threads no coordinator at all,
        zero overhead on the common path."""
        if chaos is None or not getattr(chaos, "elastic_armed", False):
            return None
        return cls(chaos=chaos, telemetry=telemetry, world_size=world_size,
                   cross_rank=cross_rank)

    def request_resize(self, delta: int) -> None:
        """Arm a resize of ``delta`` hosts for the next epoch boundary
        (a drain request from the capacity scheduler)."""
        self._requested_delta = int(delta)

    def poll(self, epoch: int) -> Optional[Dict[str, Any]]:
        """One epoch-boundary check (after epoch ``epoch`` completed);
        every rank must call it — the agreement is a collective.
        Returns the agreed resize decision or None."""
        if self._fired:
            return None
        delta = self._requested_delta
        if self.chaos is not None and delta == 0:
            delta = self.chaos.elastic_now(epoch)
        if self.cross_rank:
            from hydragnn_tpu.resilience.preempt import host_agree_max

            # agree on the largest-magnitude armed delta (allreduce-max
            # of magnitude, sign carried separately) — the same
            # primitive preemption agreement rides: every rank enters
            agreed = host_agree_max(
                [abs(float(delta)), 1.0 if delta >= 0 else 0.0])
            delta = int(agreed[0]) * (1 if agreed[1] > 0.5 else -1)
        if delta == 0:
            return None
        self._fired = True
        decision = {
            "epoch": int(epoch) + 1,
            "delta": int(delta),
            "world_size": self.world_size,
            "target_world_size": max(1, self.world_size + int(delta)),
        }
        if self.telemetry is not None:
            self.telemetry.health("elastic_resize", **decision)
            if delta < 0:
                # shrinking: the surplus hosts retire through the bundle
                # path and never relaunch; `elastic_admit` is emitted by
                # the trainer when a host resumes INTO the new shape
                self.telemetry.health(
                    "elastic_retire", epoch=decision["epoch"],
                    delta=int(delta),
                    target_world_size=decision["target_world_size"])
        return decision
